"""Megatron-format checkpoint loading with model-parallel re-sharding.

Rebuild of deepspeed/runtime/state_dict_factory.py (``SDLoaderFactory``
:17, ``SDLoaderBase`` :35, ``MegatronSDLoader`` :195): given a list of
per-mp-rank checkpoint files and a target mp world size, loads this rank's
state dict, MERGING multiple files (num_ckpt > mp_world_size) or SPLITTING
one file (num_ckpt < mp_world_size) along the megatron partition axes:

* axis 0 (column-parallel): ``mlp.dense_h_to_4h.{weight,bias}``,
  ``word_embeddings.weight``;
* axis 1 (row-parallel): ``attention.dense.weight``,
  ``mlp.dense_4h_to_h.weight``;
* QKV: version-dependent head-interleaved layouts (reference
  ``merge_query_key_value`` :195, ``split_query_key_value`` :235 — the
  three formats of checkpoint_version 0 / 1.0 / 2.0);
* everything else replicated.

TPU-native: tensors become numpy on load (torch .pt checkpoints are read
via the baked-in cpu torch when available, plain pickles otherwise);
:func:`megatron_to_gpt2_params` then maps the Megatron naming onto this
package's flax GPT-2 for the InferenceEngine.
"""

import collections
import copy
import json
import os
import pickle
from abc import ABC, abstractmethod
from typing import Any, Dict, List

import numpy as np

from deepspeed_tpu.utils.logging import logger

AUTO_MODULE_KEY = "auto"


def _to_numpy(obj):
    """torch.Tensor -> np.ndarray passthrough tree conversion."""
    try:
        import torch
        if isinstance(obj, torch.Tensor):
            t = obj.detach().cpu()
            if t.dtype == torch.bfloat16:  # numpy has no bf16; widen
                t = t.float()
            return t.numpy()
    except ImportError:
        pass
    if isinstance(obj, dict):
        return {k: _to_numpy(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_numpy(v) for v in obj)
    return obj


def load_checkpoint_file(path):
    """torch.load or pickle.load; tensors normalised to numpy."""
    try:
        import torch
    except ImportError:
        torch = None
    if torch is not None:
        try:
            return _to_numpy(torch.load(path, map_location="cpu",
                                        weights_only=False))
        except Exception as torch_err:
            try:  # plain-pickle checkpoints are legal; corrupt .pt is not
                with open(path, "rb") as f:
                    return _to_numpy(pickle.load(f))
            except Exception:
                raise torch_err from None
    with open(path, "rb") as f:
        return _to_numpy(pickle.load(f))


class SDLoaderFactory:
    @staticmethod
    def get_sd_loader_json(json_file):
        with open(json_file) as f:
            data = json.load(f)
        return SDLoaderFactory.get_sd_loader(data["checkpoints"],
                                             data["type"],
                                             data.get("version"))

    @staticmethod
    def get_sd_loader(ckpt_list, sd_type="Megatron", version=None):
        if sd_type == "Megatron":
            return MegatronSDLoader(ckpt_list, version)
        raise ValueError(f"{sd_type} checkpoint type is not supported")


class SDLoaderBase(ABC):
    def __init__(self, ckpt_list: List[str], version=None):
        self.module_key = None
        self.ckpt_list = ckpt_list
        self.version = version
        self._first_sd_cache = None  # shard 0, loaded once (multi-GB files)
        self.check_ckpt_list()

    def _load_first(self):
        if self._first_sd_cache is None:
            self._first_sd_cache = load_checkpoint_file(self.ckpt_list[0])
        return self._first_sd_cache

    def load(self, mp_world_size, mp_rank, module_key=AUTO_MODULE_KEY,
             is_pipe_parallel=False, quantize=False, quantize_bits=8,
             quantize_groups=64, mlp_extra_grouping=True):
        """Returns (load_path, sd, (all_scales, merge_count)) — the
        reference surface (state_dict_factory.py:41)."""
        self.module_key = module_key
        num_ckpt = len(self.ckpt_list)
        idx = mp_rank * num_ckpt // mp_world_size

        if is_pipe_parallel and module_key is not None and \
                mp_world_size != num_ckpt:
            mp_world_size = num_ckpt
            idx = 0

        load_path = self.ckpt_list[idx]
        merge_count = 1
        all_scales = None
        if num_ckpt == mp_world_size:
            sd = load_checkpoint_file(load_path)
            if quantize:
                from deepspeed_tpu.runtime.weight_quantizer import \
                    WeightQuantization
                q = WeightQuantization(mlp_extra_grouping=mlp_extra_grouping,
                                       mp_size=mp_world_size)
                module, all_scales = q.sd_quantize_megatron(
                    self.get_module(sd), quantize_bits, quantize_groups)
                sd = self.set_module(sd, module)
        elif num_ckpt > mp_world_size:
            sd, all_scales, merge_count = self.merge_state_dict(
                mp_world_size, mp_rank, quantize, quantize_bits,
                quantize_groups, mlp_extra_grouping)
        else:
            sd, all_scales = self.split_state_dict(
                mp_world_size, mp_rank, quantize, quantize_bits,
                quantize_groups, mlp_extra_grouping)
        return load_path, sd, (all_scales, merge_count)

    def get_merge_state_dicts(self, mp_world_size, mp_rank):
        num_ckpt = len(self.ckpt_list)
        assert num_ckpt % mp_world_size == 0, \
            "Invalid checkpoints and world size for sd merge"
        num_to_merge = num_ckpt // mp_world_size
        ckpts = self.ckpt_list[num_to_merge * mp_rank:
                               num_to_merge * (mp_rank + 1)]
        logger.info(f"mp_rank: {mp_rank}, ckpt_list: {ckpts}")
        return [self._load_first() if c == self.ckpt_list[0]
                else load_checkpoint_file(c) for c in ckpts]

    def get_split_state_dict(self, mp_world_size, mp_rank):
        num_ckpt = len(self.ckpt_list)
        assert mp_world_size % num_ckpt == 0, \
            "Invalid checkpoints and world size for sd split"
        num_to_split = mp_world_size // num_ckpt
        ckpt_index = mp_rank // num_to_split
        ckpt_offset = mp_rank % num_to_split
        sd = self._load_first() if ckpt_index == 0 \
            else load_checkpoint_file(self.ckpt_list[ckpt_index])
        return sd, num_to_split, ckpt_offset

    def _choose_module_key(self, sd):
        assert not ("module" in sd and "model" in sd), \
            "checkpoint has both 'model' and 'module' keys"
        assert "module" in sd or "model" in sd, \
            "checkpoint contains neither 'model' nor 'module' keys"
        return "module" if "module" in sd else "model"

    def get_module(self, sd):
        if self.module_key is None:
            return sd
        if self.module_key == AUTO_MODULE_KEY:
            return sd[self._choose_module_key(sd)]
        return sd[self.module_key]

    def set_module(self, sd, module):
        if self.module_key is None:
            sd = module
        elif self.module_key == AUTO_MODULE_KEY:
            sd[self._choose_module_key(sd)] = module
        else:
            sd[self.module_key] = module
        return sd

    def check_ckpt_list(self):
        assert len(self.ckpt_list) > 0
        sd = self._load_first()
        if isinstance(sd, dict) and "mp_world_size" in sd:
            assert len(self.ckpt_list) == sd["mp_world_size"], (
                f"checkpoint count {len(self.ckpt_list)} != saved "
                f"mp_world_size {sd['mp_world_size']}")

    @abstractmethod
    def merge_state_dict(self, mp_world_size, mp_rank, quantize,
                         quantize_bits, groups, mlp_extra_grouping):
        ...

    @abstractmethod
    def split_state_dict(self, mp_world_size, mp_rank, quantize,
                         quantize_bits, groups, mlp_extra_grouping):
        ...


class MegatronSDLoader(SDLoaderBase):
    """Megatron-LM GPT checkpoint loader (reference :195)."""

    def merge_query_key_value(self, param_list, ckpt_ver):
        """The three QKV layouts (reference docstring :196-211):
        v0: [(3 * np * hn), h] — q/k/v thirds per rank, regrouped;
        v1.0/v2.0: head-interleaved — plain concat."""
        if ckpt_ver == 0:
            assert param_list[0].shape[0] % 3 == 0
            size_qkv = param_list[0].shape[0] // 3
            split_tensors = [np.split(p, [size_qkv, 2 * size_qkv], axis=0)
                             for p in param_list]
            return np.concatenate(
                [np.concatenate([t[i] for t in split_tensors], axis=0)
                 for i in range(3)], axis=0)
        if ckpt_ver in (1.0, 2.0):
            return np.concatenate(param_list, axis=0)
        raise ValueError(f"checkpoint version: {ckpt_ver} is not supported")

    def split_query_key_value(self, param, num_to_split, offset, ckpt_ver):
        if ckpt_ver == 0:
            assert param.shape[0] % 3 == 0
            size_qkv = param.shape[0] // 3
            q, k, v = np.split(param, [size_qkv, 2 * size_qkv], axis=0)
            assert size_qkv % num_to_split == 0
            return np.concatenate(
                [np.split(t, num_to_split, axis=0)[offset]
                 for t in (q, k, v)], axis=0)
        if ckpt_ver in (1.0, 2.0):
            assert param.shape[0] % num_to_split == 0
            return np.split(param, num_to_split, axis=0)[offset]
        raise ValueError(f"checkpoint version: {ckpt_ver} is not supported")

    def merge_state_dict(self, mp_world_size, mp_rank, quantize=False,
                         quantize_bits=8, groups=64,
                         mlp_extra_grouping=True):
        self.sanity_check(self.ckpt_list[0])
        sd_list = self.get_merge_state_dicts(mp_world_size, mp_rank)
        ds_sd = copy.deepcopy(sd_list[0])
        new_client_sd = collections.OrderedDict()
        client_sd_list = [self.get_module(sd) for sd in sd_list]
        ckpt_ver = self.get_checkpoint_version(ds_sd)
        quantizer = None
        if quantize:
            from deepspeed_tpu.runtime.weight_quantizer import \
                WeightQuantization
            quantizer = WeightQuantization(
                mlp_extra_grouping=mlp_extra_grouping, mp_size=mp_world_size)

        for key in client_sd_list[0].keys():
            value_list = [sd[key] for sd in client_sd_list]
            if "attention.dense.weight" in key or \
                    "mlp.dense_4h_to_h.weight" in key:
                if quantize:
                    value_list = quantizer.Quantize(
                        value_list, quantize_bits, groups, key=key,
                        merge_dim=1)
                new_client_sd[key] = np.concatenate(value_list, axis=1)
            elif "attention.query_key_value" in key:
                if quantize and "attention.query_key_value.weight" in key:
                    value_list = quantizer.Quantize(value_list,
                                                    quantize_bits, groups,
                                                    key=key)
                    # reference behavior (state_dict_factory.py:338-344):
                    # quantized QKV merges by plain axis-0 concat (NOT
                    # merge_query_key_value) so the int8 rows stay aligned
                    # with their per-rank group scales — the inference
                    # kernels consume the rank-blocked layout
                    new_client_sd[key] = np.concatenate(value_list, axis=0)
                else:
                    new_client_sd[key] = self.merge_query_key_value(
                        value_list, ckpt_ver)
            elif "mlp.dense_h_to_4h.weight" in key or \
                    "word_embeddings.weight" in key or \
                    "mlp.dense_h_to_4h.bias" in key:
                if quantize and "mlp.dense_h_to_4h.weight" in key:
                    value_list = quantizer.Quantize(value_list,
                                                    quantize_bits, groups,
                                                    key=key)
                new_client_sd[key] = np.concatenate(value_list, axis=0)
            else:
                new_client_sd[key] = value_list[0]

        all_scales = quantizer.merge_scales() if quantize else None
        ds_sd = self.set_module(ds_sd, new_client_sd)
        return ds_sd, all_scales, len(client_sd_list)

    def split_state_dict(self, mp_world_size, mp_rank, quantize=False,
                         quantize_bits=8, groups=64,
                         mlp_extra_grouping=True):
        self.sanity_check(self.ckpt_list[0])
        sd, num_to_split, ckpt_offset = self.get_split_state_dict(
            mp_world_size, mp_rank)
        ds_sd = copy.deepcopy(sd)
        new_client_sd = collections.OrderedDict()
        client_sd = self.get_module(sd)
        ckpt_ver = self.get_checkpoint_version(ds_sd)
        quantizer = None
        if quantize:
            from deepspeed_tpu.runtime.weight_quantizer import \
                WeightQuantization
            quantizer = WeightQuantization(
                mlp_extra_grouping=mlp_extra_grouping, mp_size=mp_world_size)

        for key, value in client_sd.items():
            if "attention.dense.weight" in key or \
                    "mlp.dense_4h_to_h.weight" in key:
                assert value.shape[1] % num_to_split == 0
                if quantize:
                    value = quantizer.Quantize([value], quantize_bits,
                                               groups, key)[0]
                new_client_sd[key] = np.split(value, num_to_split,
                                              axis=1)[ckpt_offset]
            elif "attention.query_key_value" in key:
                if quantize and "attention.query_key_value.weight" in key:
                    value = quantizer.Quantize([value], quantize_bits,
                                               groups, key)[0]
                new_client_sd[key] = self.split_query_key_value(
                    value, num_to_split, ckpt_offset, ckpt_ver)
            elif "mlp.dense_h_to_4h.weight" in key or \
                    "word_embeddings.weight" in key or \
                    "mlp.dense_h_to_4h.bias" in key:
                assert value.shape[0] % num_to_split == 0
                if quantize and "mlp.dense_h_to_4h.weight" in key:
                    value = quantizer.Quantize([value], quantize_bits,
                                               groups, key)[0]
                new_client_sd[key] = np.split(value, num_to_split,
                                              axis=0)[ckpt_offset]
            else:
                new_client_sd[key] = value

        all_scales = quantizer.merge_scales_split(num_to_split) \
            if quantize else None
        ds_sd = self.set_module(ds_sd, new_client_sd)
        return ds_sd, all_scales

    def sanity_check(self, ckpt_file_name):
        keys_to_check = ["attention.dense.weight",
                         "mlp.dense_4h_to_h.weight",
                         "attention.query_key_value",
                         "mlp.dense_h_to_4h.weight",
                         "mlp.dense_h_to_4h.bias"]
        sd = self._load_first() if ckpt_file_name == self.ckpt_list[0] \
            else load_checkpoint_file(ckpt_file_name)
        module = self.get_module(sd)
        for key in keys_to_check:
            assert any(key in k for k in module.keys()), (
                f"key: {key} is not found in the checkpoint "
                f"{ckpt_file_name}")

    def get_checkpoint_version(self, state_dict):
        if self.version is not None:
            return self.version
        if isinstance(state_dict, dict):
            return state_dict.get("checkpoint_version", 0)
        return 0


# --------------------------------------------------------- flax conversion
def reorder_qkv_to_contiguous(qkv, version, n_head):
    """Re-order a merged (mp=1) Megatron QKV tensor from its version
    layout to the contiguous [q|k|v] rows this package's Dense expects.
    v0 is already contiguous; v2.0 is [n, 3, hn]; v1.0 is [n, hn, 3]
    (reference layout docstring, state_dict_factory.py:196-211)."""
    if version == 0:
        return qkv
    three_e = qkv.shape[0]
    hn = three_e // (3 * n_head)
    rest = qkv.shape[1:]
    if version == 2.0:
        x = qkv.reshape(n_head, 3, hn, *rest)
        return np.ascontiguousarray(
            np.moveaxis(x, 1, 0)).reshape(three_e, *rest)
    if version == 1.0:
        x = qkv.reshape(n_head, hn, 3, *rest)
        return np.ascontiguousarray(
            np.moveaxis(x, 2, 0)).reshape(three_e, *rest)
    raise ValueError(f"checkpoint version: {version} is not supported")


def megatron_to_gpt2_params(client_sd: Dict[str, Any], config,
                            checkpoint_version=0) -> Dict:
    """Map a (merged, mp=1) Megatron GPT state dict onto this package's
    flax GPT2LMHeadModel params. Megatron linears are [out, in]; flax
    kernels are [in, out] (transpose). Head-interleaved QKV layouts
    (checkpoint_version 1.0/2.0) are re-ordered to contiguous [q|k|v].

    Keys are matched by suffix, so Megatron-LM's module prefixes
    ('language_model.embedding.word_embeddings.weight', ...) resolve the
    same way the loader's substring matching does."""
    E = config.n_embd
    p: Dict[str, Any] = {}

    def lookup(name):
        if name in client_sd:
            return client_sd[name]
        hits = [k for k in client_sd if k.endswith("." + name)]
        assert len(hits) == 1, (
            f"expected exactly one key ending with {name!r}, got {hits}")
        return client_sd[hits[0]]

    def ln(dst, src):
        p[dst] = {"scale": np.asarray(lookup(f"{src}.weight")),
                  "bias": np.asarray(lookup(f"{src}.bias"))}

    wte = np.asarray(lookup("word_embeddings.weight"), np.float32)
    assert wte.shape[0] <= config.padded_vocab, (
        f"checkpoint vocab {wte.shape[0]} exceeds the model's padded "
        f"vocab {config.padded_vocab} (vocab_size {config.vocab_size}); "
        f"the checkpoint was trained with a larger vocabulary")
    if wte.shape[0] < config.padded_vocab:
        wte = np.pad(wte, [(0, config.padded_vocab - wte.shape[0]), (0, 0)])
    p["wte"] = wte
    p["wpe"] = np.asarray(lookup("position_embeddings.weight"),
                          np.float32)
    ln("ln_f", "transformer.final_layernorm")
    for i in range(config.n_layer):
        pre = f"transformer.layers.{i}"
        blk: Dict[str, Any] = {}
        blk["ln_1"] = {
            "scale": np.asarray(lookup(f"{pre}.input_layernorm.weight")),
            "bias": np.asarray(lookup(f"{pre}.input_layernorm.bias"))}
        blk["ln_2"] = {
            "scale": np.asarray(
                lookup(f"{pre}.post_attention_layernorm.weight")),
            "bias": np.asarray(
                lookup(f"{pre}.post_attention_layernorm.bias"))}
        qkv_w = reorder_qkv_to_contiguous(
            np.asarray(lookup(f"{pre}.attention.query_key_value.weight")),
            checkpoint_version, config.n_head)
        qkv_b = reorder_qkv_to_contiguous(
            np.asarray(lookup(f"{pre}.attention.query_key_value.bias")),
            checkpoint_version, config.n_head)
        assert qkv_w.shape == (3 * E, E), qkv_w.shape
        blk["attn"] = {
            "qkv": {"kernel": qkv_w.T, "bias": qkv_b},
            "proj": {
                "kernel": np.asarray(
                    lookup(f"{pre}.attention.dense.weight")).T,
                "bias": np.asarray(
                    lookup(f"{pre}.attention.dense.bias"))}}
        blk["mlp"] = {
            "fc": {"kernel": np.asarray(
                lookup(f"{pre}.mlp.dense_h_to_4h.weight")).T,
                "bias": np.asarray(
                    lookup(f"{pre}.mlp.dense_h_to_4h.bias"))},
            "proj": {"kernel": np.asarray(
                lookup(f"{pre}.mlp.dense_4h_to_h.weight")).T,
                "bias": np.asarray(
                    lookup(f"{pre}.mlp.dense_4h_to_h.bias"))}}
        p[f"h_{i}"] = blk
    return p


def is_hf_gpt2_state_dict(sd: Dict[str, Any]) -> bool:
    """Heuristic: HuggingFace GPT-2 naming (transformer.h.N.attn.c_attn)."""
    return any("attn.c_attn.weight" in k for k in sd)


def _hf_get(state_dict, name):
    """Fetch a tensor accepting either bare or 'transformer.'-prefixed HF
    keys (shared by the hf_*_to_params converters)."""
    for k in (name, f"transformer.{name}"):
        if k in state_dict:
            return np.asarray(state_dict[k], np.float32)
    raise KeyError(name)


def _hf_layer_count(state_dict) -> int:
    """Number of transformer layers recorded in an HF state dict (keys
    'h.N.*' / 'transformer.h.N.*')."""
    return 1 + max(
        (int(k.split("h.")[1].split(".")[0]) for k in state_dict
         if ".h." in k or k.startswith("h.")), default=-1)


def hf_gpt2_to_params(state_dict: Dict[str, Any], config) -> Dict:
    """Map a HuggingFace GPT-2 state dict (torch ``GPT2LMHeadModel``
    naming) onto this package's flax params — the HF half of the
    reference's checkpoint interop (state_dict_factory + module_inject
    HFGPT2LayerPolicy). HF's Conv1D stores weights [in, out], which is
    already the flax kernel layout (no transpose, unlike Megatron)."""
    E = config.n_embd

    def get(name):
        return _hf_get(state_dict, name)

    # fail fast on config/checkpoint mismatch (a silent drop of extra
    # layers or a short wpe would serve wrong-but-plausible logits)
    ckpt_layers = _hf_layer_count(state_dict)
    assert ckpt_layers == config.n_layer, (
        f"checkpoint has {ckpt_layers} transformer layers but the model "
        f"config says n_layer={config.n_layer}")

    p: Dict[str, Any] = {}
    wte = get("wte.weight")
    if wte.shape[0] < config.padded_vocab:
        wte = np.pad(wte, [(0, config.padded_vocab - wte.shape[0]), (0, 0)])
    p["wte"] = wte
    p["wpe"] = get("wpe.weight")
    assert p["wpe"].shape[0] >= config.n_positions, (
        f"checkpoint wpe covers {p['wpe'].shape[0]} positions but the "
        f"model config says n_positions={config.n_positions}")
    p["ln_f"] = {"scale": get("ln_f.weight"), "bias": get("ln_f.bias")}
    for i in range(config.n_layer):
        pre = f"h.{i}"
        blk = {
            "ln_1": {"scale": get(f"{pre}.ln_1.weight"),
                     "bias": get(f"{pre}.ln_1.bias")},
            "ln_2": {"scale": get(f"{pre}.ln_2.weight"),
                     "bias": get(f"{pre}.ln_2.bias")},
            "attn": {
                "qkv": {"kernel": get(f"{pre}.attn.c_attn.weight"),
                        "bias": get(f"{pre}.attn.c_attn.bias")},
                "proj": {"kernel": get(f"{pre}.attn.c_proj.weight"),
                         "bias": get(f"{pre}.attn.c_proj.bias")}},
            "mlp": {
                "fc": {"kernel": get(f"{pre}.mlp.c_fc.weight"),
                       "bias": get(f"{pre}.mlp.c_fc.bias")},
                "proj": {"kernel": get(f"{pre}.mlp.c_proj.weight"),
                         "bias": get(f"{pre}.mlp.c_proj.bias")}},
        }
        assert blk["attn"]["qkv"]["kernel"].shape == (E, 3 * E), \
            blk["attn"]["qkv"]["kernel"].shape
        p[f"h_{i}"] = blk
    return p


def gpt2_params_to_megatron(params: Dict, config) -> Dict[str, Any]:
    """Inverse of :func:`megatron_to_gpt2_params` (checkpoint tooling +
    round-trip tests)."""
    sd: Dict[str, Any] = collections.OrderedDict()
    sd["word_embeddings.weight"] = np.asarray(
        params["wte"])[:config.vocab_size]
    if "wpe" in params:  # rope models have no learned position table
        sd["position_embeddings.weight"] = np.asarray(params["wpe"])
    sd["transformer.final_layernorm.weight"] = np.asarray(
        params["ln_f"]["scale"])
    sd["transformer.final_layernorm.bias"] = np.asarray(
        params["ln_f"]["bias"])
    for i in range(config.n_layer):
        blk = params[f"h_{i}"]
        pre = f"transformer.layers.{i}"
        sd[f"{pre}.input_layernorm.weight"] = np.asarray(blk["ln_1"]["scale"])
        sd[f"{pre}.input_layernorm.bias"] = np.asarray(blk["ln_1"]["bias"])
        sd[f"{pre}.post_attention_layernorm.weight"] = np.asarray(
            blk["ln_2"]["scale"])
        sd[f"{pre}.post_attention_layernorm.bias"] = np.asarray(
            blk["ln_2"]["bias"])
        sd[f"{pre}.attention.query_key_value.weight"] = np.asarray(
            blk["attn"]["qkv"]["kernel"]).T
        sd[f"{pre}.attention.query_key_value.bias"] = np.asarray(
            blk["attn"]["qkv"]["bias"])
        sd[f"{pre}.attention.dense.weight"] = np.asarray(
            blk["attn"]["proj"]["kernel"]).T
        sd[f"{pre}.attention.dense.bias"] = np.asarray(
            blk["attn"]["proj"]["bias"])
        sd[f"{pre}.mlp.dense_h_to_4h.weight"] = np.asarray(
            blk["mlp"]["fc"]["kernel"]).T
        sd[f"{pre}.mlp.dense_h_to_4h.bias"] = np.asarray(
            blk["mlp"]["fc"]["bias"])
        sd[f"{pre}.mlp.dense_4h_to_h.weight"] = np.asarray(
            blk["mlp"]["proj"]["kernel"]).T
        sd[f"{pre}.mlp.dense_4h_to_h.bias"] = np.asarray(
            blk["mlp"]["proj"]["bias"])
    return sd


def is_hf_gptneo_state_dict(sd: Dict[str, Any]) -> bool:
    """HF GPT-Neo naming: transformer.h.N.attn.attention.q_proj."""
    return any(".attn.attention.q_proj.weight" in k for k in sd)


def hf_gptneo_to_params(state_dict: Dict[str, Any], config) -> Dict:
    """Map an HF ``GPTNeoForCausalLM`` state dict onto this package's flax
    ``GPT2LMHeadModel`` params (the GPTNEOLayerPolicy analogue,
    reference module_inject/replace_policy.py:103).

    Differences from GPT-2 handled here:
    * torch ``nn.Linear`` weights are [out, in] (transpose — HF GPT-2 uses
      Conv1D which is already [in, out]);
    * separate un-biased q/k/v projections -> fused qkv kernel with a zero
      bias;
    * GPT-Neo does NOT scale attention scores; our attention always
      multiplies by 1/sqrt(head_dim), so sqrt(head_dim) is folded into the
      q columns (the scale_attention=False of the reference policy).

    NOTE GPT-Neo alternates global/local(window-256) attention layers; the
    converted model computes full causal attention everywhere, which is
    only equivalent while sequences stay within the local window.
    """
    E = config.n_embd
    D = E // config.n_head

    if config.n_positions > 256:
        logger.warning(
            "GPT-Neo checkpoints may contain local-attention (window-256) "
            "layers that this conversion approximates with full causal "
            f"attention; with n_positions={config.n_positions} > 256, "
            "sequences beyond the window will diverge from the HF model.")

    def get(name):
        return _hf_get(state_dict, name)

    ckpt_layers = _hf_layer_count(state_dict)
    assert ckpt_layers == config.n_layer, (
        f"checkpoint has {ckpt_layers} transformer layers but the model "
        f"config says n_layer={config.n_layer}")

    p: Dict[str, Any] = {}
    wte = get("wte.weight")
    assert wte.shape[0] <= config.padded_vocab, (
        f"checkpoint vocab {wte.shape[0]} exceeds padded_vocab "
        f"{config.padded_vocab}")
    if wte.shape[0] < config.padded_vocab:
        wte = np.pad(wte, [(0, config.padded_vocab - wte.shape[0]), (0, 0)])
    p["wte"] = wte
    p["wpe"] = get("wpe.weight")
    assert p["wpe"].shape[0] >= config.n_positions
    p["ln_f"] = {"scale": get("ln_f.weight"), "bias": get("ln_f.bias")}
    for i in range(config.n_layer):
        pre = f"h.{i}"
        att = f"{pre}.attn.attention"
        q = get(f"{att}.q_proj.weight").T * np.sqrt(D).astype(np.float32)
        k = get(f"{att}.k_proj.weight").T
        v = get(f"{att}.v_proj.weight").T
        p[f"h_{i}"] = {
            "ln_1": {"scale": get(f"{pre}.ln_1.weight"),
                     "bias": get(f"{pre}.ln_1.bias")},
            "ln_2": {"scale": get(f"{pre}.ln_2.weight"),
                     "bias": get(f"{pre}.ln_2.bias")},
            "attn": {
                "qkv": {"kernel": np.concatenate([q, k, v], axis=1),
                        "bias": np.zeros((3 * E,), np.float32)},
                "proj": {"kernel": get(f"{att}.out_proj.weight").T,
                         "bias": get(f"{att}.out_proj.bias")}},
            "mlp": {
                "fc": {"kernel": get(f"{pre}.mlp.c_fc.weight").T,
                       "bias": get(f"{pre}.mlp.c_fc.bias")},
                "proj": {"kernel": get(f"{pre}.mlp.c_proj.weight").T,
                         "bias": get(f"{pre}.mlp.c_proj.bias")}},
        }
    return p
