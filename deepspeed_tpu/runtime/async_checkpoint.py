"""Background checkpoint persistence (snapshot-then-persist, CheckFreq).

The engine's async save path splits a checkpoint into a SNAPSHOT phase
(device→host copy at the step boundary — the only part the train loop
waits for, and the only part the goodput ledger books as
``checkpoint_save``) and a PERSIST phase (pickle + fsync + rename +
manifest), which this writer runs on a background thread while training
continues.

Contract (mirrors the prefetch pipeline's shutdown discipline,
``runtime/prefetch.py``):

* at most ONE persist is in flight — ``submit`` drains the previous one
  first, so two saves can never interleave files within a tag or race the
  ``latest`` pointer;
* a background failure is never silent: it re-raises (wrapped in
  :class:`AsyncCheckpointError`) at the next ``submit``/``drain``/
  ``close`` — exactly the "next save/close" surface the caller already
  has in hand;
* the thread runs under the goodput ledger's ``suppress_attribution`` —
  its overlapped wall time books NOTHING; the honest ``checkpoint_save``
  seconds are the snapshot plus whatever the consumer actually waits in
  ``drain()``;
* shutdown is leak-free: the (daemon) thread holds only the shared
  :class:`_WriterState`, never the engine, so an abandoned engine is
  reclaimed by GC via ``weakref.finalize`` — which also fires at
  interpreter exit and joins the in-flight write (bounded), so a normal
  process exit does not truncate a checkpoint.
"""

import threading
import weakref

from deepspeed_tpu.telemetry.ledger import suppress_attribution
from deepspeed_tpu.utils.logging import logger

# at interpreter exit the finalizer joins the in-flight persist; bounded
# so a wedged filesystem degrades to a warning, not a hung exit
_EXIT_JOIN_TIMEOUT_S = 120.0


class AsyncCheckpointError(RuntimeError):
    """A background checkpoint persist failed; raised at the next
    save/drain/close so the failure cannot vanish."""


class _WriterState:
    """What the background thread (and the GC finalizer) share. Holding
    only this — never the writer or the engine — keeps an abandoned
    engine collectable."""
    __slots__ = ("thread", "error", "tag")

    def __init__(self):
        self.thread = None
        self.error = None
        self.tag = None


def _finalize_state(state):
    t = state.thread
    if t is not None and t.is_alive():
        t.join(timeout=_EXIT_JOIN_TIMEOUT_S)
        if t.is_alive():
            logger.warning(
                f"async checkpoint: background write of tag "
                f"{state.tag!r} did not finish within "
                f"{_EXIT_JOIN_TIMEOUT_S:.0f}s at shutdown; the tag will "
                f"be left without a manifest (detectably incomplete)")


class AsyncCheckpointWriter:
    """One in-flight background persist at a time. Built lazily by the
    engine when ``checkpoint.async_save`` is on."""

    def __init__(self, name="ckpt-writer"):
        self._name = name
        self._state = _WriterState()
        self._closed = False
        self._finalizer = weakref.finalize(self, _finalize_state,
                                           self._state)

    @property
    def in_flight(self):
        t = self._state.thread
        return t is not None and t.is_alive()

    def submit(self, persist_fn, tag=""):
        """Drain any previous persist (re-raising its failure), then run
        ``persist_fn()`` on a fresh background thread."""
        self.drain()
        if self._closed:
            raise AsyncCheckpointError(
                "async checkpoint writer is closed (engine.close() ran)")
        state = self._state
        state.tag = str(tag)

        def _run():
            try:
                # overlapped persist seconds must not book into the
                # ledger's shared totals (they run CONCURRENT with the
                # train loop's attributed time)
                with suppress_attribution():
                    persist_fn()
            except BaseException as e:      # surfaced at the next drain
                state.error = e

        t = threading.Thread(target=_run, name=f"ds-{self._name}",
                             daemon=True)
        state.thread = t
        t.start()

    def drain(self):
        """Wait for the in-flight persist (if any); re-raise its
        failure. Idempotent."""
        state = self._state
        t = state.thread
        if t is not None:
            t.join()
            state.thread = None
        err = state.error
        if err is not None:
            state.error = None
            raise AsyncCheckpointError(
                f"background checkpoint write of tag {state.tag!r} "
                f"failed: {err}") from err

    def close(self):
        """Drain and refuse further submits. Re-raises a pending
        background failure (the last chance for it to surface)."""
        self._closed = True
        try:
            self.drain()
        finally:
            if self._state.thread is None:
                self._finalizer.detach()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
