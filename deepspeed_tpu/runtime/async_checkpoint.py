"""Background checkpoint persistence (snapshot-then-persist, CheckFreq).

The engine's async save path splits a checkpoint into a SNAPSHOT phase
(device→host copy at the step boundary — the only part the train loop
waits for, and the only part the goodput ledger books as
``checkpoint_save``) and a PERSIST phase (pickle + fsync + rename +
manifest), which this writer runs on a background thread while training
continues.

Contract (mirrors the prefetch pipeline's shutdown discipline,
``runtime/prefetch.py``):

* at most ONE persist is in flight — ``submit`` drains the previous one
  first, so two saves can never interleave files within a tag or race the
  ``latest`` pointer;
* a background failure is never silent: it re-raises (wrapped in
  :class:`AsyncCheckpointError`) at the next ``submit``/``drain``/
  ``close`` — exactly the "next save/close" surface the caller already
  has in hand;
* the thread runs under the goodput ledger's ``suppress_attribution`` —
  its overlapped wall time books NOTHING; the honest ``checkpoint_save``
  seconds are the snapshot plus whatever the consumer actually waits in
  ``drain()``;
* shutdown is leak-free: the (daemon) thread holds only the shared
  :class:`_WriterState`, never the engine, so an abandoned engine is
  reclaimed by GC via ``weakref.finalize`` — which also fires at
  interpreter exit and joins the in-flight write (bounded), so a normal
  process exit does not truncate a checkpoint.
"""

import random
import threading
import time
import weakref

from deepspeed_tpu.telemetry.ledger import suppress_attribution
from deepspeed_tpu.telemetry.metrics import get_registry
from deepspeed_tpu.utils.logging import logger

# at interpreter exit the finalizer joins the in-flight persist; bounded
# so a wedged filesystem degrades to a warning, not a hung exit
_EXIT_JOIN_TIMEOUT_S = 120.0


class AsyncCheckpointError(RuntimeError):
    """A background checkpoint persist failed; raised at the next
    save/drain/close so the failure cannot vanish."""


class _WriterState:
    """What the background thread (and the GC finalizer) share. Holding
    only this — never the writer or the engine — keeps an abandoned
    engine collectable."""
    __slots__ = ("thread", "error", "tag")

    def __init__(self):
        self.thread = None
        self.error = None
        self.tag = None


def _finalize_state(state):
    t = state.thread
    if t is not None and t.is_alive():
        t.join(timeout=_EXIT_JOIN_TIMEOUT_S)
        if t.is_alive():
            logger.warning(
                f"async checkpoint: background write of tag "
                f"{state.tag!r} did not finish within "
                f"{_EXIT_JOIN_TIMEOUT_S:.0f}s at shutdown; the tag will "
                f"be left without a manifest (detectably incomplete)")


class AsyncCheckpointWriter:
    """One in-flight background persist at a time. Built lazily by the
    engine when ``checkpoint.async_save`` is on."""

    def __init__(self, name="ckpt-writer", retries=0, backoff_s=0.05):
        self._name = name
        # transient-failure budget for the persist stage: a failed
        # persist_fn is re-run up to `retries` more times with jittered
        # exponential backoff; only the LAST failure surfaces (at the
        # next drain). 0 = seed behavior, fail on first error.
        self.retries = max(0, int(retries))
        self.backoff_s = max(0.0, float(backoff_s))
        self._state = _WriterState()
        self._closed = False
        self._finalizer = weakref.finalize(self, _finalize_state,
                                           self._state)

    @property
    def in_flight(self):
        t = self._state.thread
        return t is not None and t.is_alive()

    def submit(self, persist_fn, tag=""):
        """Drain any previous persist (re-raising its failure), then run
        ``persist_fn()`` on a fresh background thread."""
        self.drain()
        if self._closed:
            raise AsyncCheckpointError(
                "async checkpoint writer is closed (engine.close() ran)")
        state = self._state
        state.tag = str(tag)

        retries, backoff_s = self.retries, self.backoff_s

        def _run():
            try:
                # overlapped persist seconds must not book into the
                # ledger's shared totals (they run CONCURRENT with the
                # train loop's attributed time)
                with suppress_attribution():
                    for attempt in range(retries + 1):
                        try:
                            persist_fn()
                            break
                        except Exception as e:
                            # a transient filesystem hiccup must not be
                            # terminal when budget remains: back off
                            # (exponential, jittered so a fleet of ranks
                            # doesn't retry in lockstep) and re-run the
                            # whole persist — every file write is
                            # idempotent (atomic tmp+rename)
                            if attempt >= retries:
                                raise
                            get_registry().counter(
                                "checkpoint_retries_total",
                                "checkpoint persist attempts retried "
                                "after a transient failure").inc()
                            delay = (backoff_s * (2 ** attempt)
                                     * (0.5 + random.random()))
                            logger.warning(
                                f"async checkpoint: persist of tag "
                                f"{state.tag!r} failed (attempt "
                                f"{attempt + 1}/{retries + 1}: {e}); "
                                f"retrying in {delay:.3f}s")
                            if delay > 0:
                                time.sleep(delay)
            except BaseException as e:      # surfaced at the next drain
                state.error = e

        t = threading.Thread(target=_run, name=f"ds-{self._name}",
                             daemon=True)
        state.thread = t
        t.start()

    def drain(self):
        """Wait for the in-flight persist (if any); re-raise its
        failure. Idempotent."""
        state = self._state
        t = state.thread
        if t is not None:
            t.join()
            state.thread = None
        err = state.error
        if err is not None:
            state.error = None
            raise AsyncCheckpointError(
                f"background checkpoint write of tag {state.tag!r} "
                f"failed: {err}") from err

    def close(self):
        """Drain and refuse further submits. Re-raises a pending
        background failure (the last chance for it to surface)."""
        self._closed = True
        try:
            self.drain()
        finally:
            if self._state.thread is None:
                self._finalizer.detach()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
