"""Optimizer update rules.

The compute core behind ``deepspeed_tpu.ops.adam.FusedAdam`` /
``ops.lamb.FusedLamb`` (reference: csrc/adam/multi_tensor_adam.cu,
csrc/lamb/fused_lamb_cuda_kernel.cu and their Python wrappers
ops/adam/fused_adam.py:16, ops/lamb/fused_lamb.py:12).

Design: each optimizer is an ``Optimizer(init, update)`` pair of pure
functions; ``update(grads, state, params, lr)`` takes the learning rate as
a traced argument so LR schedules run inside the jitted train step. The
reference fuses the elementwise chain into one CUDA kernel over 512-element
chunks (multi_tensor_apply.cuh); under XLA the same fusion falls out of the
compiler, and the Pallas fused variants (ops/adam/) exist for the cases XLA
schedules poorly. ZeRO stages shard ``state`` leaves over the DP axes (see
runtime/zero/partition.py) which turns these updates into shard-local work
— the partitioned optimizer step of stage_1_and_2.py:1628.

Bias correction follows the reference ordering exactly (step incremented
before correction; denominators computed in fp32) so loss curves are
bit-comparable.
"""

from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp


class Optimizer(NamedTuple):
    init: Callable[[Any], Any]
    update: Callable[..., Any]  # (grads, state, params, lr) -> (updates, state)
    # fuses_clip: the optimizer applies the global-norm clip INSIDE its
    # own sweep — update() accepts clip_coef= and the engine skips the
    # separate clip pass over the grad tree (one fewer full HBM read+
    # write). Only the whole-state sweep variants set this.
    fuses_clip: bool = False


class AdamState(NamedTuple):
    step: jnp.ndarray
    mu: Any
    nu: Any


def _tree_zeros_like(params, dtype=None):
    return jax.tree.map(lambda p: jnp.zeros_like(p, dtype=dtype or p.dtype), params)


def adam(b1=0.9, b2=0.999, eps=1e-8, weight_decay=0.0, adam_w_mode=True,
         bias_correction=True):
    """Adam/AdamW (reference FusedAdam defaults: adam_w_mode=True).

    adam_w_mode=True → decoupled weight decay (AdamW); False → L2-style
    decay folded into the gradient, matching the reference's two modes
    (multi_tensor_adam.cu ADAM_MODE 0/1).
    """

    def init(params):
        return AdamState(step=jnp.zeros([], jnp.int32),
                         mu=_tree_zeros_like(params),
                         nu=_tree_zeros_like(params))

    def update(grads, state, params, lr):
        step = state.step + 1
        if bias_correction:
            bc1 = 1.0 - b1 ** step.astype(jnp.float32)
            bc2 = 1.0 - b2 ** step.astype(jnp.float32)
        else:
            bc1 = bc2 = jnp.float32(1.0)

        if not adam_w_mode and weight_decay > 0.0:
            grads = jax.tree.map(lambda g, p: g + weight_decay * p, grads, params)

        mu = jax.tree.map(lambda m, g: b1 * m + (1.0 - b1) * g, state.mu, grads)
        nu = jax.tree.map(lambda v, g: b2 * v + (1.0 - b2) * (g * g),
                          state.nu, grads)

        def upd(m, v, p):
            m_hat = m / bc1
            v_hat = v / bc2
            u = -lr * m_hat / (jnp.sqrt(v_hat) + eps)
            if adam_w_mode and weight_decay > 0.0:
                u = u - lr * weight_decay * p
            return u

        updates = jax.tree.map(upd, mu, nu, params)
        return updates, AdamState(step=step, mu=mu, nu=nu)

    return Optimizer(init, update)


class LambState(NamedTuple):
    step: jnp.ndarray
    mu: Any
    nu: Any


def lamb(b1=0.9, b2=0.999, eps=1e-6, weight_decay=0.0, min_coeff=0.01,
         max_coeff=10.0, bias_correction=True):
    """LAMB with per-tensor trust ratio (reference FusedLamb,
    fused_lamb_cuda_kernel.cu: two-pass — update norm + weight norm
    reductions, then scaled apply; min/max_coeff clamp the ratio)."""

    def init(params):
        return LambState(step=jnp.zeros([], jnp.int32),
                         mu=_tree_zeros_like(params),
                         nu=_tree_zeros_like(params))

    def update(grads, state, params, lr):
        step = state.step + 1
        if bias_correction:
            bc1 = 1.0 - b1 ** step.astype(jnp.float32)
            bc2 = 1.0 - b2 ** step.astype(jnp.float32)
        else:
            bc1 = bc2 = jnp.float32(1.0)

        mu = jax.tree.map(lambda m, g: b1 * m + (1.0 - b1) * g, state.mu, grads)
        nu = jax.tree.map(lambda v, g: b2 * v + (1.0 - b2) * (g * g),
                          state.nu, grads)

        def upd(m, v, p):
            m_hat = m / bc1
            v_hat = v / bc2
            u = m_hat / (jnp.sqrt(v_hat) + eps)
            if weight_decay > 0.0:
                u = u + weight_decay * p
            w_norm = jnp.linalg.norm(p.astype(jnp.float32).reshape(-1))
            u_norm = jnp.linalg.norm(u.astype(jnp.float32).reshape(-1))
            ratio = jnp.where(
                (w_norm > 0) & (u_norm > 0),
                jnp.clip(w_norm / u_norm, min_coeff, max_coeff),
                jnp.float32(1.0))
            return -lr * ratio * u

        updates = jax.tree.map(upd, mu, nu, params)
        return updates, LambState(step=step, mu=mu, nu=nu)

    return Optimizer(init, update)


class SGDState(NamedTuple):
    momentum: Any


def sgd(momentum=0.0, weight_decay=0.0, nesterov=False):
    def init(params):
        if momentum == 0.0:
            return SGDState(momentum=())
        return SGDState(momentum=_tree_zeros_like(params))

    def update(grads, state, params, lr):
        if weight_decay > 0.0:
            grads = jax.tree.map(lambda g, p: g + weight_decay * p, grads, params)
        if momentum == 0.0:
            return jax.tree.map(lambda g: -lr * g, grads), state
        buf = jax.tree.map(lambda b, g: momentum * b + g, state.momentum, grads)
        if nesterov:
            upd = jax.tree.map(lambda b, g: -lr * (g + momentum * b), buf, grads)
        else:
            upd = jax.tree.map(lambda b: -lr * b, buf)
        return upd, SGDState(momentum=buf)

    return Optimizer(init, update)


class AdagradState(NamedTuple):
    accum: Any


def adagrad(eps=1e-8, weight_decay=0.0, initial_accumulator_value=0.0):
    """Adagrad (reference DeepSpeedCPUAdagrad semantics, cpu_adagrad.cpp)."""

    def init(params):
        return AdagradState(accum=jax.tree.map(
            lambda p: jnp.full_like(p, initial_accumulator_value), params))

    def update(grads, state, params, lr):
        if weight_decay > 0.0:
            grads = jax.tree.map(lambda g, p: g + weight_decay * p, grads, params)
        accum = jax.tree.map(lambda a, g: a + g * g, state.accum, grads)
        updates = jax.tree.map(lambda g, a: -lr * g / (jnp.sqrt(a) + eps),
                               grads, accum)
        return updates, AdagradState(accum=accum)

    return Optimizer(init, update)


class FlatTreeSpec(NamedTuple):
    """Static recipe to rebuild a pytree from one flat vector: treedef +
    per-leaf shapes/dtypes (python data — baked into the trace, never a
    traced value). ``n`` is the unpadded element count; ``n_pad`` the
    padded vector length the spec was built with."""
    treedef: Any
    shapes: tuple
    dtypes: tuple
    n: int
    n_pad: int


def flatten_leaves(leaves, n_pad=None, dtype=jnp.float32):
    """One contiguous ``dtype`` vector holding ``leaves`` back to back
    (tail zero-padded to ``n_pad`` when given), assembled with
    ``dynamic_update_slice`` writes into a preallocated buffer — NOT
    ``concatenate``-of-ravels, which XLA CPU lowers to a pathological
    element loop (measured 225 ms vs 18 ms for the same 37 MB on the
    bench host). Shared by :func:`flatten_tree` and the comm-overlap
    bucket assembly (runtime/comm_overlap.bucketed_pmean)."""
    n = sum(x.size for x in leaves)
    n_pad = n if n_pad is None else n_pad
    vec = jnp.zeros((n_pad,), dtype)
    off = 0
    for x in leaves:
        vec = jax.lax.dynamic_update_slice(
            vec, jnp.ravel(x).astype(dtype), (off,))
        off += x.size
    return vec


def flatten_tree(tree, pad_to=1, dtype=jnp.float32):
    """Flatten a pytree into ONE contiguous ``dtype`` vector (padded to a
    multiple of ``pad_to``) + the :class:`FlatTreeSpec` to undo it.

    The shim behind the whole-state sweep optimizers (ops/adam
    ``fused_adam_sweep``): the per-leaf fused Adam lost to XLA as a
    per-bucket dispatch — one kernel launch per tensor — and a single
    flattened sweep turns the whole optimizer step into ONE pass over
    contiguous state."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    assert leaves, "flatten_tree: empty pytree"
    n = sum(x.size for x in leaves)
    pad_to = max(1, int(pad_to))
    n_pad = -(-n // pad_to) * pad_to
    vec = flatten_leaves(leaves, n_pad=n_pad, dtype=dtype)
    spec = FlatTreeSpec(
        treedef=treedef,
        shapes=tuple(tuple(x.shape) for x in leaves),
        dtypes=tuple(x.dtype for x in leaves),
        n=n, n_pad=n_pad)
    return vec, spec


def unflatten_tree(vec, spec: FlatTreeSpec):
    """Rebuild the pytree from a (padded) flat vector produced against
    the same tree structure; each leaf is cast back to its own dtype."""
    assert vec.shape == (spec.n_pad,), (
        f"unflatten_tree: vector shape {vec.shape} != spec ({spec.n_pad},)")
    out, off = [], 0
    import numpy as _np
    for shape, dt in zip(spec.shapes, spec.dtypes):
        size = int(_np.prod(shape)) if shape else 1
        out.append(vec[off:off + size].reshape(shape).astype(dt))
        off += size
    return jax.tree_util.tree_unflatten(spec.treedef, out)


def global_norm(tree):
    """Global L2 norm over a pytree (reference runtime/utils.py
    get_global_norm / clip_grad_norm_). Under pjit the per-shard partial
    sums are combined by XLA automatically."""
    leaves = [jnp.sum(jnp.square(x.astype(jnp.float32)))
              for x in jax.tree.leaves(tree)]
    return jnp.sqrt(jnp.sum(jnp.stack(leaves)))


def clip_by_global_norm(grads, max_norm):
    """Scale grads so that global norm <= max_norm (torch semantics:
    clip_coef = max_norm / (norm + 1e-6), applied only when norm > max)."""
    norm = global_norm(grads)
    clip_coef = jnp.minimum(max_norm / (norm + 1e-6), 1.0)
    return jax.tree.map(lambda g: g * clip_coef, grads), norm


def clipped_update(opt, grads, state, params, lr, max_norm=1.0):
    """Global-norm clip + optimizer update composed the way the engine's
    grad_epilogue composes them: the torch-semantics clip coefficient is
    handed to a clip-fusing optimizer via ``update(clip_coef=)``, else
    applied as a grad-tree pre-scale. Shared by the optimizer
    microbenches (bench.py, tests/perf/overlap_bench.py) so they measure
    exactly the composition the engine runs and cannot drift from it."""
    norm = global_norm(grads)
    clip_coef = jnp.minimum(max_norm / (norm + 1e-6), 1.0)
    if getattr(opt, "fuses_clip", False):
        return opt.update(grads, state, params, lr, clip_coef=clip_coef)
    grads = jax.tree.map(lambda g: g * clip_coef, grads)
    return opt.update(grads, state, params, lr)
