"""Tensor ↔ NVMe swapping.

Rebuild of deepspeed/runtime/swap_tensor/ (``AsyncTensorSwapper``
async_swapper.py, ``AsyncPartitionedParameterSwapper``
partitioned_param_swapper.py:36, optimizer swappers optimizer_utils.py:118)
over the native aio engine (csrc/aio.cpp). Pytree leaves map to files in a
swap folder; swap-out submits async writes and releases the host buffer,
swap-in reads back with overlapped requests (the reference's
double-buffered PipelinedOptimizerSwapper pattern).
"""

import os
from typing import Any, Dict, List, Optional

import jax
import numpy as np

from deepspeed_tpu.ops.aio.aio_handle import AsyncIOHandle


class AsyncTensorSwapper:
    """Swap individual numpy buffers (reference async_swapper.py)."""

    def __init__(self, swap_folder, aio_handle: Optional[AsyncIOHandle] = None):
        self.swap_folder = swap_folder
        os.makedirs(swap_folder, exist_ok=True)
        self.aio = aio_handle or AsyncIOHandle()
        self._pending: List[int] = []
        self._meta: Dict[str, dict] = {}

    def _path(self, key: str) -> str:
        safe = key.replace("/", "_").replace("[", "_").replace("]", "_")
        return os.path.join(self.swap_folder, f"{safe}.swp")

    def swap_out(self, key: str, array: np.ndarray, block=False):
        arr = np.ascontiguousarray(array)
        self._meta[key] = {"shape": arr.shape, "dtype": arr.dtype,
                           "buf": arr}  # keep alive until waited
        req = self.aio.async_pwrite(arr, self._path(key))
        self._pending.append(req)
        if block:
            self.synchronize()

    def swap_in(self, key: str, block=True) -> np.ndarray:
        meta = self._meta[key]
        out = np.empty(meta["shape"], meta["dtype"])
        req = self.aio.async_pread(out, self._path(key))
        if block:
            assert self.aio.wait(req) == out.nbytes
        else:
            self._pending.append(req)
        return out

    def swap_in_async(self, key: str):
        """Submit a read and return (buffer, request) — the per-request
        half of the reference's PipelinedOptimizerSwapper: callers overlap
        the read with compute and wait(req, nbytes) just before use."""
        meta = self._meta[key]
        out = np.empty(meta["shape"], meta["dtype"])
        req = self.aio.async_pread(out, self._path(key))
        return out, req

    def wait(self, req, expect_nbytes=None) -> int:
        """Block on one request; a failed or short transfer raises (the
        buffer would otherwise hold uninitialised garbage)."""
        n = self.aio.wait(req)
        assert n >= 0, f"aio request failed (errno {-n})"
        if expect_nbytes is not None:
            assert n == expect_nbytes, (
                f"short aio transfer: {n} of {expect_nbytes} bytes")
        return n

    def synchronize(self):
        """Wait for all in-flight requests (reference swap_out_tensors
        epilogue); releases the keep-alive buffers."""
        for req in self._pending:
            self.aio.wait(req)
        self._pending.clear()
        for meta in self._meta.values():
            meta.pop("buf", None)


class OptimizerSwapper:
    """Swap a whole optimizer-state pytree (reference
    PartitionedOptimizerSwapper): swap_out frees host RAM between steps;
    swap_in_then(fn) reads states back, runs the update, swaps out."""

    def __init__(self, swap_folder, aio_handle=None):
        # several worker threads by default: one request per leaf, and a
        # single-thread pool would serialize the pipelined reads
        if aio_handle is None:
            aio_handle = AsyncIOHandle(thread_count=4)
        self.swapper = AsyncTensorSwapper(swap_folder, aio_handle)
        self._paths: List[str] = []

    def swap_out_tree(self, tree: Any, block=True):
        flat = jax.tree_util.tree_flatten_with_path(tree)[0]
        self._paths = []
        for path, leaf in flat:
            key = jax.tree_util.keystr(path)
            self._paths.append(key)
            self.swapper.swap_out(key, np.asarray(leaf))
        if block:
            self.swapper.synchronize()

    def swap_in_tree(self, template: Any) -> Any:
        """Pipelined (round-5; was one blocking read per leaf): ALL leaf
        reads are submitted up front and waited in order — the aio
        worker pool overlaps them, the reference's
        PipelinedOptimizerSwapper discipline at tree granularity. Peak
        host memory equals the materialised tree either way."""
        flat, treedef = jax.tree_util.tree_flatten_with_path(template)[0], \
            jax.tree_util.tree_structure(template)
        reqs = []
        for path, _ in flat:
            key = jax.tree_util.keystr(path)
            buf, req = self.swapper.swap_in_async(key)
            reqs.append((buf, req))
        leaves = []
        for buf, req in reqs:
            self.swapper.wait(req, buf.nbytes)
            leaves.append(buf)
        return jax.tree_util.tree_unflatten(treedef, leaves)

    def swap_in_then(self, template: Any, update_fn) -> Any:
        """Per-leaf pipelined update (reference
        PipelinedOptimizerSwapper.swap_in_optimizer_state: overlap leaf
        N+1's read with leaf N's update): submit read N+1, wait read N,
        run ``update_fn(leaf) -> new_leaf``, swap the result back out.
        Returns the updated tree; writes are synchronized before
        returning."""
        flat, treedef = jax.tree_util.tree_flatten_with_path(template)[0], \
            jax.tree_util.tree_structure(template)
        keys = [jax.tree_util.keystr(path) for path, _ in flat]
        leaves = []
        pending = self.swapper.swap_in_async(keys[0]) if keys else None
        for i, key in enumerate(keys):
            buf, req = pending
            if i + 1 < len(keys):                  # prefetch the next leaf
                pending = self.swapper.swap_in_async(keys[i + 1])
            self.swapper.wait(req, buf.nbytes)
            new = update_fn(buf)
            self.swapper.swap_out(key, np.asarray(new))
            leaves.append(new)
        self.swapper.synchronize()
        return jax.tree_util.tree_unflatten(treedef, leaves)
