"""Curriculum learning scheduler.

Faithful port of deepspeed/runtime/data_pipeline/curriculum_scheduler.py
(``CurriculumScheduler`` :8) — pure step→difficulty math, identical
schedule types: ``fixed_linear`` (:60), ``fixed_root`` (:36),
``fixed_discrete`` (:89). The engine injects ``curriculum_seqlen`` into
the model kwargs at each step exactly like the reference
(engine.py:1577-1583); under jit the seqlen becomes a static slice bound,
so each distinct difficulty compiles once (the schedule plateaus make
this a handful of compilations).
"""

import math

CURRICULUM_LEARNING_MIN_DIFFICULTY = "min_difficulty"
CURRICULUM_LEARNING_MAX_DIFFICULTY = "max_difficulty"
CURRICULUM_LEARNING_SCHEDULE_TYPE = "schedule_type"
CURRICULUM_LEARNING_SCHEDULE_CONFIG = "schedule_config"
FIXED_LINEAR = "fixed_linear"
FIXED_ROOT = "fixed_root"
FIXED_DISCRETE = "fixed_discrete"
CUSTOM = "custom"


class CurriculumScheduler:
    def __init__(self, config):
        self.state = {}
        assert CURRICULUM_LEARNING_MIN_DIFFICULTY in config
        assert CURRICULUM_LEARNING_MAX_DIFFICULTY in config
        assert CURRICULUM_LEARNING_SCHEDULE_TYPE in config
        self.state[CURRICULUM_LEARNING_MIN_DIFFICULTY] = \
            config[CURRICULUM_LEARNING_MIN_DIFFICULTY]
        self.state[CURRICULUM_LEARNING_MAX_DIFFICULTY] = \
            config[CURRICULUM_LEARNING_MAX_DIFFICULTY]
        self.state[CURRICULUM_LEARNING_SCHEDULE_TYPE] = \
            config[CURRICULUM_LEARNING_SCHEDULE_TYPE]
        self.state[CURRICULUM_LEARNING_SCHEDULE_CONFIG] = \
            config.get(CURRICULUM_LEARNING_SCHEDULE_CONFIG, {})
        self.state["current_difficulty"] = \
            config[CURRICULUM_LEARNING_MIN_DIFFICULTY]
        self.first_step = True
        self.custom_get_difficulty = None

        sched = self.state[CURRICULUM_LEARNING_SCHEDULE_TYPE]
        cfg = self.state[CURRICULUM_LEARNING_SCHEDULE_CONFIG]
        if sched in (FIXED_LINEAR, FIXED_ROOT):
            assert "total_curriculum_step" in cfg
            assert "difficulty_step" in cfg
            if cfg["difficulty_step"] % 8 != 0:
                import warnings
                warnings.warn(
                    "difficulty_step not multiple of 8 can hurt TPU "
                    "throughput (reference warns for fp16 tensor cores)")
            if sched == FIXED_ROOT:
                assert "root_degree" in cfg
        elif sched == FIXED_DISCRETE:
            assert "difficulty" in cfg and "max_step" in cfg
            assert len(cfg["max_step"]) > 0
            assert len(cfg["difficulty"]) == len(cfg["max_step"]) + 1
        elif sched == CUSTOM:
            pass
        else:
            raise RuntimeError(f"unsupported schedule type {sched}")

    def get_current_difficulty(self):
        return self.state["current_difficulty"]

    def set_current_difficulty(self, difficulty):
        self.state["current_difficulty"] = difficulty

    def set_custom_get_difficulty(self, fn):
        self.custom_get_difficulty = fn
        self.state[CURRICULUM_LEARNING_SCHEDULE_TYPE] = CUSTOM

    def get_state(self):
        return self.state

    def set_state(self, state):
        self.state = state

    def __fixed_root_get_difficulty(self, global_steps, root_degree=None):
        cfg = self.state[CURRICULUM_LEARNING_SCHEDULE_CONFIG]
        if root_degree is None:
            root_degree = cfg["root_degree"]
        next_difficulty = (min(1.0, global_steps /
                               cfg["total_curriculum_step"])) ** (1.0 / root_degree)
        next_difficulty = math.floor(
            next_difficulty *
            (self.state[CURRICULUM_LEARNING_MAX_DIFFICULTY] -
             self.state[CURRICULUM_LEARNING_MIN_DIFFICULTY]) +
            self.state[CURRICULUM_LEARNING_MIN_DIFFICULTY])
        next_difficulty -= next_difficulty % cfg["difficulty_step"]
        return min(next_difficulty,
                   self.state[CURRICULUM_LEARNING_MAX_DIFFICULTY])

    def __fixed_discrete_get_difficulty(self, global_steps):
        cfg = self.state[CURRICULUM_LEARNING_SCHEDULE_CONFIG]
        for i, step in enumerate(cfg["max_step"]):
            if global_steps <= step:
                return cfg["difficulty"][i]
        return cfg["difficulty"][-1]

    def get_difficulty(self, global_steps):
        sched = self.state[CURRICULUM_LEARNING_SCHEDULE_TYPE]
        if sched == FIXED_ROOT:
            return self.__fixed_root_get_difficulty(global_steps)
        if sched == FIXED_LINEAR:
            return self.__fixed_root_get_difficulty(global_steps, 1)
        if sched == FIXED_DISCRETE:
            return self.__fixed_discrete_get_difficulty(global_steps)
        if sched == CUSTOM:
            return self.custom_get_difficulty(global_steps)
        raise RuntimeError(f"unsupported schedule type {sched}")

    def update_difficulty(self, global_steps):
        if self.state["current_difficulty"] < \
                self.state[CURRICULUM_LEARNING_MAX_DIFFICULTY]:
            self.state["current_difficulty"] = self.get_difficulty(global_steps)
        return self.state["current_difficulty"]


def apply_seqlen_truncation(scheduler, global_steps, batch):
    """Truncate every >=2-D batch leaf's axis 1 to the scheduled
    difficulty (the reference injects curriculum_seqlen into forward,
    engine.py:1577 / pipe engine.py:307; here the batch is sliced so each
    difficulty plateau compiles once). Shared by the fused DP engine and
    the host-loop pipe engine — one truncation rule, two executors."""
    import jax
    seqlen = scheduler.update_difficulty(global_steps + 1)

    def trunc(x):
        if hasattr(x, "ndim") and x.ndim >= 2 and x.shape[1] > seqlen:
            return x[:, :seqlen]
        return x
    return jax.tree.map(trunc, batch)
