"""Quantize-aware training (MoQ).

Rebuild of deepspeed/runtime/quantize.py (``Quantizer`` :12): progressive
bit-reduction during training, optionally guided by the per-block
eigenvalue estimate; engine hooks it at the gradient boundary
(_take_model_step, engine.py:1816-1827). The quantization kernel is
ops/quantizer/quantizer.py; this class owns the SCHEDULE (per-block
period, start bits, target bits, mixed fp16/quantized groups) — pure
host logic.

Schedule semantics follow the reference's ``compute_quantization``
(quantize.py:129-157): a block whose step counter reaches its period
drops one bit and DOUBLES its period (so precision falls fast early,
slowly near the target); with eigenvalue guidance the new period is
additionally multiplied by ``1 + floor(curvature_ratio * 4)`` — flat
blocks (low curvature ratio) re-quantize sooner than sharp ones
(quantize.py:75-80). ``qsteps`` counts engine steps (the reference
counts TWO_D_PARAMS * layer_num per step; periods here are in steps).
"""

import math

import jax

from deepspeed_tpu.ops.quantizer.quantizer import quantize as quantize_kernel
from deepspeed_tpu.runtime.eigenvalue import path_str
from deepspeed_tpu.utils.logging import log_dist


class Quantizer:
    def __init__(self, q_groups=1, q_mixed_fp16=False, q_change_ratio=0.001,
                 q_type=0, q_rounding=0, q_verbose=False, q_eigenvalue=False,
                 use_quantizer_kernel=True, layer_num=0,
                 q_start_bits=16, q_target_bits=8, q_period=1000):
        n = layer_num if layer_num != 0 else 1
        self.q_groups = q_groups
        self.q_mixed_fp16 = q_mixed_fp16
        self.q_change_ratio = q_change_ratio
        self.q_type = q_type            # 0 symmetric, 1 asymmetric
        self.q_rounding = q_rounding    # 0 nearest, 1 stochastic
        self.q_verbose = q_verbose
        self.use_eigenvalue = q_eigenvalue
        self.use_quantizer_kernel = use_quantizer_kernel
        self.layer_num = layer_num
        self.q_start_bits = [q_start_bits] * n
        self.q_target_bits = q_target_bits
        self.q_period = [q_period] * n
        self.qsteps = 0
        self.quantize_real_ratio = 1.0
        self._seen_blocks = set()   # block ids that own at least one matrix

    def any_precision_switch(self):
        """True when the NEXT step will drop a bit for some block
        (reference quantize.py:46-56) — the engine's cue to spend a
        (costly) eigenvalue computation. Only blocks that actually own a
        quantized matrix count once known (a layer_num larger than the
        real layer count would otherwise keep this True forever and the
        engine would power-iterate the Hessian every step)."""
        ids = range(len(self.q_start_bits))
        if self.qsteps > 0:  # after the first pass the real blocks are known
            ids = self._seen_blocks
        return any(
            self.q_start_bits[i] != self.q_target_bits
            and self.qsteps + 1 >= self.q_period[i]
            for i in ids)

    def current_bits(self, index=0):
        return self.q_start_bits[index]

    def _maybe_switch(self, index, factor):
        """Per-block bit drop + period doubling at the period boundary
        (reference compute_quantization:141-155)."""
        if (self.q_start_bits[index] != self.q_target_bits
                and self.qsteps >= self.q_period[index]):
            self.quantize_real_ratio = 1.0
            if self.use_eigenvalue:
                self.q_period[index] = (self.q_period[index] << 1) * factor
                self.q_start_bits[index] -= 1
            else:
                for i in range(len(self.q_start_bits)):
                    self.q_start_bits[i] -= 1
                    self.q_period[i] <<= 1
            if self.q_verbose:
                log_dist(
                    f"MoQ: block {index} -> {self.q_start_bits[index]} "
                    f"bits, next period {self.q_period[index]} "
                    f"(step {self.qsteps})", ranks=[0])

    def quantize(self, parameter_group, overflow=False,
                 eigenvalue_enabled=False, block_eigenvalue=None):
        """Fake-quantize a pytree of params in place of the reference's
        in-place tensor mutation; returns the new pytree.

        ``block_eigenvalue``: ``{leaf_path: (curvature_ratio, layer_id)}``
        from ``Eigenvalue.compute_block_eigenvalues`` (paths joined by
        ``eigenvalue.path_str``). Empty/None falls back to the uniform
        schedule with every 2D+ param in block 0."""
        if overflow and not eigenvalue_enabled:
            return parameter_group
        self.qsteps += 1
        block_eigenvalue = block_eigenvalue or {}
        # reference calls update_fp16_ratio() BEFORE its param loop
        # (quantize.py step ordering), so the decremented ratio is the one
        # the blend below uses
        if self.q_mixed_fp16:
            self.quantize_real_ratio = max(
                0.0, self.quantize_real_ratio - self.q_change_ratio)

        def q(path, x):
            # reference quantizes only matrices (len(p.size()) > 1)
            if x.ndim < 2 or x.size % self.q_groups:
                return x
            ev, layer_id = block_eigenvalue.get(
                path_str(path), (None, 0))
            if layer_id >= len(self.q_start_bits):
                raise ValueError(
                    f"MoQ: eigenvalue block id {layer_id} for param "
                    f"'{path_str(path)}' exceeds the quantizer's "
                    f"layer_num={self.layer_num}; set eigenvalue."
                    "layer_num to the model's repeated-layer count")
            self._seen_blocks.add(layer_id)
            factor = 1 + math.floor(ev * 4) if ev is not None else 1
            self._maybe_switch(layer_id, factor)
            bits = self.q_start_bits[layer_id]
            if bits >= 16:
                return x
            ratio = self.quantize_real_ratio
            qx = quantize_kernel(
                x, num_bits=bits, groups=self.q_groups,
                symmetric=(self.q_type == 0),
                stochastic=(self.q_rounding == 1))
            if self.q_mixed_fp16 and ratio < 1.0:
                return ratio * x + (1.0 - ratio) * qx
            return qx

        return jax.tree_util.tree_map_with_path(q, parameter_group)
