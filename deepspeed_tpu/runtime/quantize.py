"""Quantize-aware training (MoQ).

Rebuild of deepspeed/runtime/quantize.py (``Quantizer`` :12): progressive
bit-reduction during training, optionally guided by the eigenvalue
estimate; engine hooks it at the gradient boundary (_take_model_step,
engine.py:1816-1827). The quantization kernel is
ops/quantizer/quantizer.py; this class owns the SCHEDULE (period, start
bits, target bits, mixed fp16/quantized groups) — pure host logic."""

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.quantizer.quantizer import quantize as quantize_kernel


class Quantizer:
    def __init__(self, q_groups=1, q_mixed_fp16=False, q_change_ratio=0.001,
                 q_type=0, q_rounding=0, q_verbose=False, q_eigenvalue=False,
                 use_quantizer_kernel=True, layer_num=0,
                 q_start_bits=16, q_target_bits=8, q_period=1000):
        self.q_groups = q_groups
        self.q_mixed_fp16 = q_mixed_fp16
        self.q_change_ratio = q_change_ratio
        self.q_type = q_type            # 0 symmetric, 1 asymmetric
        self.q_rounding = q_rounding    # 0 nearest, 1 stochastic
        self.q_verbose = q_verbose
        self.use_eigenvalue = q_eigenvalue
        self.use_quantizer_kernel = use_quantizer_kernel
        self.layer_num = layer_num
        self.q_start_bits = q_start_bits
        self.q_target_bits = q_target_bits
        self.q_period = q_period
        self.qsteps = 0
        self.quantize_real_ratio = 1.0

    def any_precision_switch(self):
        if self.q_start_bits == self.q_target_bits:
            return False
        return (self.qsteps % self.q_period) == 0

    def current_bits(self):
        """Progressive schedule: one bit per period toward the target
        (reference runtime/quantize.py decrements q_start_bits each
        period)."""
        reductions = self.qsteps // self.q_period
        return max(self.q_target_bits, self.q_start_bits - reductions)

    def quantize(self, parameter_group, overflow=False, eigenvalue_enabled=False,
                 block_eigenvalue=None):
        """Fake-quantize a pytree of params in place of the reference's
        in-place tensor mutation; returns the new pytree."""
        if overflow and not eigenvalue_enabled:
            return parameter_group
        self.qsteps += 1
        bits = self.current_bits()
        if bits >= 16:
            return parameter_group

        def q(x):
            if x.ndim < 1 or x.size % self.q_groups:
                return x
            ratio = self.quantize_real_ratio
            qx = quantize_kernel(
                x, num_bits=bits, groups=self.q_groups,
                symmetric=(self.q_type == 0),
                stochastic=(self.q_rounding == 1))
            if self.q_mixed_fp16 and ratio < 1.0:
                return ratio * x + (1.0 - ratio) * qx
            return qx

        if self.q_mixed_fp16:
            self.quantize_real_ratio = max(
                0.0, self.quantize_real_ratio - self.q_change_ratio)
        return jax.tree.map(q, parameter_group)
