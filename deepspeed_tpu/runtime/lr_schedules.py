"""Learning-rate schedules.

Parity with ``deepspeed/runtime/lr_schedules.py``: the same four schedules
selectable from config by name — ``LRRangeTest`` (:310), ``OneCycle``
(:417), ``WarmupLR`` (:706), ``WarmupDecayLR`` (:802) — with the same
parameter names and step semantics. Each is usable two ways: as a stateful
object with ``step()/get_lr()/state_dict()/load_state_dict()`` (the
reference surface) and as a pure ``schedule_fn(step) -> lr`` suitable for
closing over inside a jitted train step (the TPU-native path: the schedule
is traced into the update so there is no host round-trip per step).
"""

import math

import jax.numpy as jnp

VALID_LR_SCHEDULES = ["LRRangeTest", "OneCycle", "WarmupLR", "WarmupDecayLR"]

LR_RANGE_TEST_MIN_LR = "lr_range_test_min_lr"
LR_RANGE_TEST_STEP_RATE = "lr_range_test_step_rate"
LR_RANGE_TEST_STEP_SIZE = "lr_range_test_step_size"
LR_RANGE_TEST_STAIRCASE = "lr_range_test_staircase"

WARMUP_MIN_LR = "warmup_min_lr"
WARMUP_MAX_LR = "warmup_max_lr"
WARMUP_NUM_STEPS = "warmup_num_steps"
TOTAL_NUM_STEPS = "total_num_steps"


def _as_list(x, n=1):
    return list(x) if isinstance(x, (list, tuple)) else [x] * n


class _LRSchedule:
    """Base: stateful stepping over a pure per-step lr function."""

    def __init__(self, optimizer=None, last_batch_iteration=-1):
        # `optimizer` kept for API parity; on TPU the engine reads get_lr()
        # and feeds it into the jitted update instead of mutating param
        # groups.
        self.optimizer = optimizer
        self.last_batch_iteration = last_batch_iteration

    def lr_at(self, step):
        """Pure step→lr, written in jnp so it traces inside jit AND
        evaluates eagerly for the host-side class API."""
        raise NotImplementedError

    def get_lr(self):
        return [float(self.lr_at(max(0, self.last_batch_iteration)))]

    def get_last_lr(self):
        assert getattr(self, "_last_lr", None) is not None, "need to call step() first"
        return self._last_lr

    def step(self, last_batch_iteration=None):
        if last_batch_iteration is None:
            last_batch_iteration = self.last_batch_iteration + 1
        self.last_batch_iteration = last_batch_iteration
        self._last_lr = self.get_lr()
        if self.optimizer is not None and hasattr(self.optimizer, "set_lr"):
            self.optimizer.set_lr(self._last_lr[0])

    def state_dict(self):
        return {"last_batch_iteration": self.last_batch_iteration}

    def load_state_dict(self, sd):
        self.last_batch_iteration = sd["last_batch_iteration"]

    def as_schedule_fn(self):
        """Return a pure ``step -> lr`` callable for use inside jit."""
        return self.lr_at


class LRRangeTest(_LRSchedule):
    """LR range-test sweep (reference lr_schedules.py:310).

    lr(t) = min_lr * (1 + t/step_size * step_rate) — continuous, or with
    t floored to step_size multiples when staircase.
    """

    def __init__(self, optimizer=None, lr_range_test_min_lr=1e-3,
                 lr_range_test_step_size=2000, lr_range_test_step_rate=1.0,
                 lr_range_test_staircase=False, last_batch_iteration=-1):
        self.min_lr = lr_range_test_min_lr
        self.step_size = lr_range_test_step_size
        self.step_rate = lr_range_test_step_rate
        self.staircase = lr_range_test_staircase
        super().__init__(optimizer, last_batch_iteration)

    def lr_at(self, step):
        step = jnp.asarray(step, jnp.float32)
        if self.staircase:
            interval = jnp.floor(step / self.step_size)
        else:
            interval = step / float(self.step_size)
        return self.min_lr * (1.0 + interval * self.step_rate)


class OneCycle(_LRSchedule):
    """1-cycle policy (reference lr_schedules.py:417): linear ramp
    min→max over ``cycle_first_step_size`` steps, back down over
    ``cycle_second_step_size``, then linear decay by ``decay_lr_rate``
    per post-cycle step. Momentum cycles inversely when enabled."""

    def __init__(self, optimizer=None, cycle_min_lr=1e-3, cycle_max_lr=1e-2,
                 decay_lr_rate=0.0, cycle_first_step_size=2000,
                 cycle_second_step_size=None, cycle_first_stair_count=0,
                 cycle_second_stair_count=None, decay_step_size=0,
                 cycle_momentum=True, cycle_min_mom=0.85, cycle_max_mom=0.99,
                 decay_mom_rate=0.0, last_batch_iteration=-1):
        self.cycle_min_lr = cycle_min_lr
        self.cycle_max_lr = cycle_max_lr
        self.decay_lr_rate = decay_lr_rate
        self.first_size = int(cycle_first_step_size)
        self.second_size = int(cycle_second_step_size
                               if cycle_second_step_size is not None
                               else cycle_first_step_size)
        self.decay_step_size = int(decay_step_size)
        self.cycle_momentum = cycle_momentum
        self.cycle_min_mom = cycle_min_mom
        self.cycle_max_mom = cycle_max_mom
        self.decay_mom_rate = decay_mom_rate
        self.total_size = self.first_size + self.second_size
        super().__init__(optimizer, last_batch_iteration)

    def _cycle_pct(self, step):
        up = step / float(self.first_size)
        down = 1.0 - (step - self.first_size) / float(self.second_size)
        return jnp.where(step <= self.first_size, up, down)

    def _decay_steps(self, step):
        post = jnp.maximum(step - self.total_size, 0.0)
        if self.decay_step_size > 0:
            return jnp.floor(post / self.decay_step_size)
        return post

    def lr_at(self, step):
        step = jnp.asarray(step, jnp.float32)
        pct = jnp.clip(self._cycle_pct(step), 0.0, 1.0)
        in_cycle = self.cycle_min_lr + (self.cycle_max_lr - self.cycle_min_lr) * pct
        if self.decay_lr_rate > 0:
            decayed = self.cycle_min_lr / (1.0 + self._decay_steps(step) * self.decay_lr_rate)
        else:
            decayed = jnp.float32(self.cycle_min_lr)
        return jnp.where(step <= self.total_size, in_cycle, decayed)

    def mom_at(self, step):
        if not self.cycle_momentum:
            return jnp.float32(self.cycle_max_mom)
        step = jnp.asarray(step, jnp.float32)
        pct = jnp.clip(self._cycle_pct(step), 0.0, 1.0)
        in_cycle = self.cycle_max_mom - (self.cycle_max_mom - self.cycle_min_mom) * pct
        if self.decay_mom_rate > 0:
            decayed = self.cycle_max_mom * (1.0 + self._decay_steps(step) * self.decay_mom_rate)
        else:
            decayed = jnp.float32(self.cycle_max_mom)
        return jnp.where(step <= self.total_size, in_cycle, decayed)

    def get_mom(self):
        return [float(self.mom_at(max(0, self.last_batch_iteration)))]


class WarmupLR(_LRSchedule):
    """Linear warmup min→max over warmup_num_steps, then constant max
    (reference lr_schedules.py:706; log-warmup variant included)."""

    def __init__(self, optimizer=None, warmup_min_lr=0.0, warmup_max_lr=0.001,
                 warmup_num_steps=1000, warmup_type="log",
                 last_batch_iteration=-1):
        self.min_lr = warmup_min_lr
        self.max_lr = warmup_max_lr
        self.warmup_num_steps = max(2, warmup_num_steps)
        self.warmup_type = warmup_type
        self.inverse_log_warm_up = 1.0 / math.log(self.warmup_num_steps)
        super().__init__(optimizer, last_batch_iteration)

    def _gamma_at(self, step):
        step = jnp.asarray(step, jnp.float32)
        if self.warmup_type == "log":
            warm = self.inverse_log_warm_up * jnp.log(step + 1.0)
        else:
            warm = step / self.warmup_num_steps
        return jnp.where(step < self.warmup_num_steps, warm, 1.0)

    def lr_at(self, step):
        return self.min_lr + (self.max_lr - self.min_lr) * self._gamma_at(step)


class WarmupDecayLR(WarmupLR):
    """Warmup then linear decay to zero at total_num_steps
    (reference lr_schedules.py:802)."""

    def __init__(self, optimizer=None, total_num_steps=10000, warmup_min_lr=0.0,
                 warmup_max_lr=0.001, warmup_num_steps=1000, warmup_type="log",
                 last_batch_iteration=-1):
        self.total_num_steps = total_num_steps
        super().__init__(optimizer, warmup_min_lr, warmup_max_lr,
                         warmup_num_steps, warmup_type, last_batch_iteration)
        if self.total_num_steps < self.warmup_num_steps:
            from deepspeed_tpu.utils.logging import logger
            logger.warning("total_num_steps %s is less than warmup_num_steps %s",
                           total_num_steps, warmup_num_steps)

    def _gamma_at(self, step):
        step_f = jnp.asarray(step, jnp.float32)
        decay = jnp.maximum(
            0.0,
            (self.total_num_steps - step_f) /
            max(1.0, float(self.total_num_steps - self.warmup_num_steps)))
        return jnp.where(step_f < self.warmup_num_steps,
                         super()._gamma_at(step), decay)


SCHEDULE_CLASSES = {
    "LRRangeTest": LRRangeTest,
    "OneCycle": OneCycle,
    "WarmupLR": WarmupLR,
    "WarmupDecayLR": WarmupDecayLR,
}


def get_lr_schedule(name, params, optimizer=None):
    """Instantiate a schedule from config (engine._configure_lr_scheduler)."""
    assert name in SCHEDULE_CLASSES, \
        f"unknown lr schedule {name}; valid: {VALID_LR_SCHEDULES}"
    return SCHEDULE_CLASSES[name](optimizer=optimizer, **(params or {}))


def add_tuning_arguments(parser):
    """CLI tuning args (reference lr_schedules.py:57)."""
    group = parser.add_argument_group("Convergence Tuning", "Convergence tuning configurations")
    group.add_argument("--lr_schedule", type=str, default=None,
                       help="LR schedule for training.")
    group.add_argument("--lr_range_test_min_lr", type=float, default=0.001)
    group.add_argument("--lr_range_test_step_rate", type=float, default=1.0)
    group.add_argument("--lr_range_test_step_size", type=int, default=1000)
    group.add_argument("--lr_range_test_staircase", type=bool, default=False)
    group.add_argument("--cycle_first_step_size", type=int, default=1000)
    group.add_argument("--cycle_first_stair_count", type=int, default=-1)
    group.add_argument("--cycle_second_step_size", type=int, default=-1)
    group.add_argument("--cycle_second_stair_count", type=int, default=-1)
    group.add_argument("--decay_step_size", type=int, default=1000)
    group.add_argument("--cycle_min_lr", type=float, default=0.01)
    group.add_argument("--cycle_max_lr", type=float, default=0.1)
    group.add_argument("--decay_lr_rate", type=float, default=0.0)
    group.add_argument("--cycle_momentum", type=bool, default=False)
    group.add_argument("--cycle_min_mom", type=float, default=0.8)
    group.add_argument("--cycle_max_mom", type=float, default=0.9)
    group.add_argument("--decay_mom_rate", type=float, default=0.0)
    group.add_argument("--warmup_min_lr", type=float, default=0)
    group.add_argument("--warmup_max_lr", type=float, default=0.001)
    group.add_argument("--warmup_num_steps", type=int, default=1000)
    group.add_argument("--warmup_type", type=str, default="log")
    return parser
