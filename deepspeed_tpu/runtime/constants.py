"""Config JSON keys and defaults.

Schema parity with ``deepspeed/runtime/constants.py`` (454 LoC in the
reference): the user-facing JSON keys are identical so a DeepSpeed config
file drops in unchanged. Defaults follow the reference except where noted
(TPU prefers bf16; fp16 remains available for loss-curve parity runs).
"""

ROUTE_TRAIN = "train"
ROUTE_EVAL = "eval"
ROUTE_PREDICT = "predict"
ROUTE_ENCODE = "encode"

# Batch size triangulation: train_batch = micro_batch * gas * dp_world
TRAIN_BATCH_SIZE = "train_batch_size"
TRAIN_BATCH_SIZE_DEFAULT = None
TRAIN_MICRO_BATCH_SIZE_PER_GPU = "train_micro_batch_size_per_gpu"
TRAIN_MICRO_BATCH_SIZE_PER_GPU_DEFAULT = None
GRADIENT_ACCUMULATION_STEPS = "gradient_accumulation_steps"
GRADIENT_ACCUMULATION_STEPS_DEFAULT = None

STEPS_PER_PRINT = "steps_per_print"
STEPS_PER_PRINT_DEFAULT = 10

OPTIMIZER = "optimizer"
OPTIMIZER_TYPE_DEFAULT = None
OPTIMIZER_PARAMS = "params"
TYPE = "type"
LEGACY_FUSION = "legacy_fusion"
LEGACY_FUSION_DEFAULT = False
MAX_GRAD_NORM = "max_grad_norm"

SCHEDULER = "scheduler"
SCHEDULER_TYPE_DEFAULT = None
SCHEDULER_PARAMS = "params"

ZERO_ALLOW_UNTESTED_OPTIMIZER = "zero_allow_untested_optimizer"
ZERO_ALLOW_UNTESTED_OPTIMIZER_DEFAULT = False

# Precision
FP16 = "fp16"
FP16_ENABLED = "enabled"
FP16_ENABLED_DEFAULT = False
FP16_LOSS_SCALE = "loss_scale"
FP16_LOSS_SCALE_DEFAULT = 0
FP16_INITIAL_SCALE_POWER = "initial_scale_power"
FP16_INITIAL_SCALE_POWER_DEFAULT = 32
FP16_LOSS_SCALE_WINDOW = "loss_scale_window"
FP16_LOSS_SCALE_WINDOW_DEFAULT = 1000
FP16_HYSTERESIS = "hysteresis"
FP16_HYSTERESIS_DEFAULT = 2
FP16_MIN_LOSS_SCALE = "min_loss_scale"
FP16_MIN_LOSS_SCALE_DEFAULT = 1
FP16_MASTER_WEIGHTS_AND_GRADS = "fp16_master_weights_and_grads"
FP16_MASTER_WEIGHTS_AND_GRADS_DEFAULT = False

BFLOAT16 = "bf16"
BFLOAT16_OLD = "bfloat16"
BFLOAT16_ENABLED = "enabled"
BFLOAT16_ENABLED_DEFAULT = False

AMP = "amp"
AMP_ENABLED = "enabled"
AMP_ENABLED_DEFAULT = False

# Gradient handling
GRADIENT_CLIPPING = "gradient_clipping"
GRADIENT_CLIPPING_DEFAULT = 0.0
PRESCALE_GRADIENTS = "prescale_gradients"
PRESCALE_GRADIENTS_DEFAULT = False
GRADIENT_PREDIVIDE_FACTOR = "gradient_predivide_factor"
GRADIENT_PREDIVIDE_FACTOR_DEFAULT = 1.0
SPARSE_GRADIENTS = "sparse_gradients"
SPARSE_GRADIENTS_DEFAULT = False
COMMUNICATION_DATA_TYPE = "communication_data_type"
COMMUNICATION_DATA_TYPE_DEFAULT = None
DISABLE_ALLGATHER = "disable_allgather"
DISABLE_ALLGATHER_DEFAULT = False

# Observability
DUMP_STATE = "dump_state"
DUMP_STATE_DEFAULT = False
WALL_CLOCK_BREAKDOWN = "wall_clock_breakdown"
WALL_CLOCK_BREAKDOWN_DEFAULT = False
MEMORY_BREAKDOWN = "memory_breakdown"
MEMORY_BREAKDOWN_DEFAULT = False
TENSORBOARD = "tensorboard"
TENSORBOARD_ENABLED = "enabled"
TENSORBOARD_ENABLED_DEFAULT = False
TENSORBOARD_OUTPUT_PATH = "output_path"
TENSORBOARD_OUTPUT_PATH_DEFAULT = ""
TENSORBOARD_JOB_NAME = "job_name"
TENSORBOARD_JOB_NAME_DEFAULT = "DeepSpeedJobName"

# Telemetry (TPU-native block: trace spans, compile watch, metrics sinks)
TELEMETRY = "telemetry"
TELEMETRY_ENABLED = "enabled"
TELEMETRY_ENABLED_DEFAULT = False
TELEMETRY_OUTPUT_PATH = "output_path"
TELEMETRY_OUTPUT_PATH_DEFAULT = ""          # "" -> "telemetry/"
TELEMETRY_JOB_NAME = "job_name"
TELEMETRY_JOB_NAME_DEFAULT = "DeepSpeedJobName"
TELEMETRY_TRACE = "trace"
TELEMETRY_TRACE_DEFAULT = True
TELEMETRY_JAX_ANNOTATIONS = "jax_annotations"
TELEMETRY_JAX_ANNOTATIONS_DEFAULT = False
TELEMETRY_COMPILE_WATCH = "compile_watch"
TELEMETRY_COMPILE_WATCH_DEFAULT = True
TELEMETRY_JSONL = "jsonl"
TELEMETRY_JSONL_DEFAULT = True
TELEMETRY_PROMETHEUS = "prometheus"
TELEMETRY_PROMETHEUS_DEFAULT = True
TELEMETRY_MEMORY_METRICS = "memory_metrics"
TELEMETRY_MEMORY_METRICS_DEFAULT = True
TELEMETRY_MAX_TRACE_EVENTS = "max_trace_events"
TELEMETRY_MAX_TRACE_EVENTS_DEFAULT = 100000

# telemetry.cost_explorer: compiled-program cost census + roofline/MFU
# attribution + HBM watermark pre-flight (telemetry/cost_explorer.py).
# When enabled the engine compiles its step program through the AOT path
# at first dispatch (same single compile, but the artifact is KEPT) so
# the census and explain_step() never trigger a duplicate compile.
COST_EXPLORER = "cost_explorer"
COST_EXPLORER_ENABLED = "enabled"
COST_EXPLORER_ENABLED_DEFAULT = False
COST_EXPLORER_PEAK_TFLOPS = "peak_tflops"          # 0 -> chip table
COST_EXPLORER_PEAK_TFLOPS_DEFAULT = 0
COST_EXPLORER_PEAK_HBM_GBPS = "peak_hbm_gbps"      # 0 -> chip table
COST_EXPLORER_PEAK_HBM_GBPS_DEFAULT = 0
COST_EXPLORER_ICI_GBPS = "ici_gbps"                # 0 -> chip table
COST_EXPLORER_ICI_GBPS_DEFAULT = 0
COST_EXPLORER_HBM_GB = "hbm_gb"                    # 0 -> device/chip table
COST_EXPLORER_HBM_GB_DEFAULT = 0
COST_EXPLORER_PREFLIGHT = "preflight"
COST_EXPLORER_PREFLIGHT_DEFAULT = True
COST_EXPLORER_PREFLIGHT_THRESHOLD = "preflight_threshold"
COST_EXPLORER_PREFLIGHT_THRESHOLD_DEFAULT = 0.95

# telemetry.health: training-health observatory (telemetry/health.py).
# When enabled the compiled step additionally emits a small static-shaped
# numerics-stats pytree (grad/param/update norms, per-module grad-norm
# buckets, loss-scale scalars, non-finite provenance bitmask); the host
# fetches it only at `cadence` and runs EWMA/z-score anomaly rules that
# escalate warn -> HEALTH.json snapshot -> forced trace export.
TELEMETRY_HEALTH = "health"
HEALTH_ENABLED = "enabled"
HEALTH_ENABLED_DEFAULT = False
HEALTH_BUCKET_DEPTH = "bucket_depth"       # max module buckets (<= 32)
HEALTH_BUCKET_DEPTH_DEFAULT = 8
HEALTH_CADENCE = "cadence"                 # 0 -> steps_per_print
HEALTH_CADENCE_DEFAULT = 0
HEALTH_EWMA_ALPHA = "ewma_alpha"
HEALTH_EWMA_ALPHA_DEFAULT = 0.1
HEALTH_LOSS_SPIKE_ZSCORE = "loss_spike_zscore"
HEALTH_LOSS_SPIKE_ZSCORE_DEFAULT = 6.0
HEALTH_GRAD_SPIKE_ZSCORE = "grad_spike_zscore"
HEALTH_GRAD_SPIKE_ZSCORE_DEFAULT = 6.0
HEALTH_WARMUP_SAMPLES = "warmup_samples"   # samples before z-rules arm
HEALTH_WARMUP_SAMPLES_DEFAULT = 8
HEALTH_OVERFLOW_STREAK = "overflow_streak"  # consecutive skips -> critical
HEALTH_OVERFLOW_STREAK_DEFAULT = 4
HEALTH_STALL_WINDOW = "stall_window"       # health samples; <2 disables
HEALTH_STALL_WINDOW_DEFAULT = 50
HEALTH_STALL_REL_DELTA = "stall_rel_delta"
HEALTH_STALL_REL_DELTA_DEFAULT = 1e-3
HEALTH_RING_SIZE = "ring_size"             # forensics ring buffer samples
HEALTH_RING_SIZE_DEFAULT = 256
HEALTH_SNAPSHOT_FILE = "snapshot_file"     # "" -> <output_path>/HEALTH.json
HEALTH_SNAPSHOT_FILE_DEFAULT = ""
HEALTH_TRACE_ON_ANOMALY = "trace_on_anomaly"
HEALTH_TRACE_ON_ANOMALY_DEFAULT = True

# telemetry.goodput: wall-clock goodput/badput ledger (telemetry/ledger.py).
# When enabled the host decomposes every second of the run into named
# categories (device_compute, compile, input_wait, host_dispatch,
# checkpoint_save/load, eval, overflow_skipped, unattributed residual)
# that sum to elapsed wall time; window rules escalate warn -> GOODPUT.json
# snapshot -> optional bounded programmatic jax.profiler capture. Pure
# host-side arithmetic: zero added host<->device syncs.
TELEMETRY_GOODPUT = "goodput"
GOODPUT_ENABLED = "enabled"
GOODPUT_ENABLED_DEFAULT = False
GOODPUT_CADENCE = "cadence"                 # window ticks; 0 -> steps_per_print
GOODPUT_CADENCE_DEFAULT = 0
GOODPUT_INPUT_WAIT_FRAC = "input_wait_frac"  # window fraction -> input_stall
GOODPUT_INPUT_WAIT_FRAC_DEFAULT = 0.25
GOODPUT_UNATTRIBUTED_FRAC = "unattributed_frac"
GOODPUT_UNATTRIBUTED_FRAC_DEFAULT = 0.5
GOODPUT_WARMUP_WINDOWS = "warmup_windows"   # windows before rules arm
GOODPUT_WARMUP_WINDOWS_DEFAULT = 1
GOODPUT_WINDOW_RING = "window_ring"         # per-window ring buffer size
GOODPUT_WINDOW_RING_DEFAULT = 128
GOODPUT_SNAPSHOT_FILE = "snapshot_file"     # "" -> <output_path>/GOODPUT.json
GOODPUT_SNAPSHOT_FILE_DEFAULT = ""
GOODPUT_PROFILER_CAPTURE = "profiler_capture"
GOODPUT_PROFILER_CAPTURE_DEFAULT = True
GOODPUT_PROFILER_CAPTURE_STEPS = "profiler_capture_steps"
GOODPUT_PROFILER_CAPTURE_STEPS_DEFAULT = 5
GOODPUT_PROFILER_MAX_CAPTURES = "profiler_max_captures"  # per run
GOODPUT_PROFILER_MAX_CAPTURES_DEFAULT = 1
GOODPUT_PROFILER_DIR = "profiler_dir"       # "" -> <output_path>/goodput_profile
GOODPUT_PROFILER_DIR_DEFAULT = ""

# telemetry.anatomy: step-anatomy profiler (telemetry/step_anatomy.py).
# When enabled, engine.profile_step(n) / ServingEngine.profile_window(n)
# run a bounded jax.profiler capture, post-process the XSpace trace with
# the dependency-free xplane parser, and write a schema-pinned
# STEP_ANATOMY.json (measured per-category device seconds joined to the
# HLO census + CostExplorer rooflines). Inert unless profile_step is
# called: no imports, no overhead on the train path.
TELEMETRY_ANATOMY = "anatomy"
ANATOMY_ENABLED = "enabled"
ANATOMY_ENABLED_DEFAULT = True
ANATOMY_CAPTURE_STEPS = "capture_steps"     # default steps per profile_step
ANATOMY_CAPTURE_STEPS_DEFAULT = 3
ANATOMY_KEEP_RAW_TRACES = "keep_raw_traces"  # newest N raw trace dirs kept
ANATOMY_KEEP_RAW_TRACES_DEFAULT = 2
ANATOMY_REPORT_FILE = "report_file"  # "" -> <output_path>/STEP_ANATOMY.json
ANATOMY_REPORT_FILE_DEFAULT = ""

# telemetry.fleet: cross-rank flight recorder (telemetry/fleet.py). Every
# rank ships window records (atomic files) into a shared run directory;
# fleet rank 0 merges them and runs the cross-rank sentinels —
# step_time_skew (straggler attribution), input_wait_skew,
# checkpoint_persist_skew, and the desync sentinel (per-bucket parameter
# checksums across data-parallel replicas) — escalating warn-once ->
# throttled FLEET_HEALTH.json -> trace flush.
# DS_TELEMETRY_FLEET=1/0 force-toggles `enabled`; DS_TELEMETRY_FLEET_RUN_DIR
# overrides `run_dir`; DS_TELEMETRY_FLEET_RANK overrides `rank` (the
# subprocess multi-rank simulations use it).
TELEMETRY_FLEET = "fleet"
FLEET_ENABLED = "enabled"
FLEET_ENABLED_DEFAULT = False
FLEET_RUN_DIR = "run_dir"                   # "" -> <output_path>/fleet_run
FLEET_RUN_DIR_DEFAULT = ""
FLEET_RANK = "rank"                         # -1 -> dist.get_rank()
FLEET_RANK_DEFAULT = -1
FLEET_CADENCE = "cadence"                   # ship every N steps; 0 -> steps_per_print
FLEET_CADENCE_DEFAULT = 0
FLEET_DESYNC = "desync"                     # arm the desync sentinel
FLEET_DESYNC_DEFAULT = True
FLEET_DESYNC_CADENCE = "desync_cadence"     # checksum every N fleet ticks; 0 -> 1
FLEET_DESYNC_CADENCE_DEFAULT = 0
FLEET_STEP_TIME_SKEW_FRAC = "step_time_skew_frac"   # (slow-fast)/slow
FLEET_STEP_TIME_SKEW_FRAC_DEFAULT = 0.25
FLEET_INPUT_WAIT_SKEW_FRAC = "input_wait_skew_frac"  # max-min window frac
FLEET_INPUT_WAIT_SKEW_FRAC_DEFAULT = 0.25
FLEET_CHECKPOINT_SKEW_FRAC = "checkpoint_skew_frac"  # (max-min)/max
FLEET_CHECKPOINT_SKEW_FRAC_DEFAULT = 0.5
FLEET_CHECKPOINT_SKEW_FLOOR_MS = "checkpoint_skew_floor_ms"
FLEET_CHECKPOINT_SKEW_FLOOR_MS_DEFAULT = 50.0
FLEET_WARMUP_WINDOWS = "warmup_windows"     # windows before the skew rules arm
FLEET_WARMUP_WINDOWS_DEFAULT = 1
FLEET_WINDOW_RING = "window_ring"           # merged-window ring buffer size
FLEET_WINDOW_RING_DEFAULT = 128
FLEET_SNAPSHOT_FILE = "snapshot_file"       # "" -> <output_path>/FLEET_HEALTH.json
FLEET_SNAPSHOT_FILE_DEFAULT = ""
FLEET_BACKGROUND_SHIP = "background_ship"   # write records off-thread
FLEET_BACKGROUND_SHIP_DEFAULT = True

# telemetry.memory: HBM residency observatory (telemetry/memory_observatory
# .py). At cadence the engine/serving tick fetches one
# jax.profiler.device_memory_profile(), decodes it with the dependency-free
# pprof parser, attributes every live buffer to
# {params, optimizer_state, kv_pool, activations_workspace, other} (exact-sum
# by construction; params/opt-state bucketed through build_bucket_spec), and
# runs the residency sentinels — hbm_leak, watermark_drift (measured peak vs
# the cost-explorer pre-flight, both directions), kv_fragmentation, and
# oom_risk (critical; the budget is a real HBM limit only — host-RSS
# fallbacks are refused). Escalation: warn-once -> throttled
# MEMORY_HEALTH.json -> on_anomaly hook. engine.memory_report(write=True)
# writes MEMORY_ANATOMY.json. DS_TELEMETRY_MEMORY=1/0 force-toggles
# `enabled`.
TELEMETRY_MEMORY = "memory"
MEMORY_ENABLED = "enabled"
MEMORY_ENABLED_DEFAULT = False
MEMORY_CADENCE = "cadence"                  # windows every N steps; 0 -> steps_per_print
MEMORY_CADENCE_DEFAULT = 0
MEMORY_SNAPSHOT_FILE = "snapshot_file"      # "" -> <output_path>/MEMORY_HEALTH.json
MEMORY_SNAPSHOT_FILE_DEFAULT = ""
MEMORY_REPORT_FILE = "report_file"          # "" -> <output_path>/MEMORY_ANATOMY.json
MEMORY_REPORT_FILE_DEFAULT = ""
MEMORY_LEAK_WINDOWS = "leak_windows"        # monotone-growth windows before hbm_leak fires
MEMORY_LEAK_WINDOWS_DEFAULT = 4
MEMORY_WARMUP_WINDOWS = "warmup_windows"    # windows before the rules arm
MEMORY_WARMUP_WINDOWS_DEFAULT = 2
MEMORY_DRIFT_THRESHOLD = "drift_threshold"  # |measured/predicted - 1| that flags
MEMORY_DRIFT_THRESHOLD_DEFAULT = 0.25
MEMORY_FRAG_THRESHOLD = "frag_threshold"    # KV pool fragmentation that flags
MEMORY_FRAG_THRESHOLD_DEFAULT = 0.5
MEMORY_HEADROOM = "headroom"                # oom_risk fires above headroom x budget
MEMORY_HEADROOM_DEFAULT = 0.92
MEMORY_BUDGET_BYTES = "budget_bytes"        # 0 -> detect (device memory_stats only)
MEMORY_BUDGET_BYTES_DEFAULT = 0
MEMORY_RING_SIZE = "ring_size"              # live-bytes window ring buffer size
MEMORY_RING_SIZE_DEFAULT = 64

# telemetry.chronicle: the run chronicle (telemetry/chronicle.py) — one
# append-only, integer-µs, causally-ordered event timeline every
# subsystem emits into (monitor rule firings, guardian actions, engine
# lifecycle, compile-watch retraces, serving admission/preemption/
# livelock, chaos injections, goodput windows). Streams land as one
# atomic JSONL per rank under `run_dir`; engine.chronicle_report /
# ServingEngine.chronicle_report summarise to CHRONICLE.json and run the
# incident correlator (telemetry/incidents.py) to INCIDENTS.json.
# DS_TELEMETRY_CHRONICLE=1/0 force-toggles `enabled`.
TELEMETRY_CHRONICLE = "chronicle"
CHRONICLE_ENABLED = "enabled"
CHRONICLE_ENABLED_DEFAULT = False
CHRONICLE_RUN_DIR = "run_dir"               # "" -> <output_path>/chronicle
CHRONICLE_RUN_DIR_DEFAULT = ""
CHRONICLE_MAX_EVENTS = "max_events"         # in-memory cap; past it NEW events drop (counted)
CHRONICLE_MAX_EVENTS_DEFAULT = 16384
CHRONICLE_SUMMARY_FILE = "summary_file"     # "" -> <output_path>/CHRONICLE.json
CHRONICLE_SUMMARY_FILE_DEFAULT = ""
CHRONICLE_INCIDENTS_FILE = "incidents_file"  # "" -> <output_path>/INCIDENTS.json
CHRONICLE_INCIDENTS_FILE_DEFAULT = ""
CHRONICLE_STEP_WINDOW = "step_window"       # correlator step-join radius
CHRONICLE_STEP_WINDOW_DEFAULT = 8
CHRONICLE_TIME_WINDOW_S = "time_window_s"   # correlator time-join radius
CHRONICLE_TIME_WINDOW_S_DEFAULT = 30.0
CHRONICLE_BACKGROUND = "background"         # stream writes off-thread
CHRONICLE_BACKGROUND_DEFAULT = True

# telemetry.server: the live observability plane (telemetry/
# obs_server.py) — a zero-dependency stdlib HTTP endpoint on rank 0
# serving GET /metrics (render_prometheus over the live registry — a
# real scrape target; the .prom file sink stays the node_exporter
# textfile-collector path), /healthz + /readyz (armed-monitor
# inventory), /api/report/{goodput,health,serving,memory,fleet,
# guardian,chronicle,incidents,slo} (each monitor's HOST-SIDE report —
# a scrape never forces a device fetch, sync, or compile) and
# /api/events (bounded chronicle tail, ?since_seq= resumable).
# DS_TELEMETRY_SERVER=1/0 force-toggles `enabled`.
TELEMETRY_SERVER = "server"
SERVER_ENABLED = "enabled"
SERVER_ENABLED_DEFAULT = False
SERVER_HOST = "host"                        # bind address (loopback default)
SERVER_HOST_DEFAULT = "127.0.0.1"
SERVER_PORT = "port"                        # 0 -> auto-pick a free port
SERVER_PORT_DEFAULT = 0
SERVER_TOKEN = "token"                      # "" -> no auth; else Bearer <token>
SERVER_TOKEN_DEFAULT = ""
SERVER_EVENTS_TAIL = "events_tail"          # /api/events max tail length
SERVER_EVENTS_TAIL_DEFAULT = 256

# telemetry.slo: the SLO burn-rate monitor (telemetry/slo.py) — SRE
# multi-window error-budget alerting over declarative objectives
# (latency objectives from registry histograms, training goodput from
# the ledger). Fast+slow windows both burning -> page-tier
# `slo_burn_page` anomaly (critical; a guardian admission-pause rule),
# fast-only -> `slo_burn_fast` (warning); escalation rides the shared
# protocol into SLO_REPORT.json, the chronicle and the guardian.
# DS_TELEMETRY_SLO=1/0 force-toggles `enabled`.
TELEMETRY_SLO = "slo"
SLO_ENABLED = "enabled"
SLO_ENABLED_DEFAULT = False
SLO_FAST_WINDOW_S = "fast_window_s"         # onset window (~5 min)
SLO_FAST_WINDOW_S_DEFAULT = 300.0
SLO_SLOW_WINDOW_S = "slow_window_s"         # sustain window (~1 h)
SLO_SLOW_WINDOW_S_DEFAULT = 3600.0
SLO_BURN_THRESHOLD = "burn_threshold"       # burn (x budget) that counts as burning
SLO_BURN_THRESHOLD_DEFAULT = 1.0
SLO_EVAL_INTERVAL_S = "eval_interval_s"     # tick self-throttle
SLO_EVAL_INTERVAL_S_DEFAULT = 10.0
SLO_OBJECTIVES = "objectives"               # [] -> goodput default (+ serving adds ttft/e2e)
SLO_OBJECTIVES_DEFAULT = ()
SLO_GOODPUT_TARGET = "goodput_target"       # default training_goodput objective target
SLO_GOODPUT_TARGET_DEFAULT = 0.90
SLO_TTFT_TARGET = "ttft_target"             # serving_ttft objective target
SLO_TTFT_TARGET_DEFAULT = 0.99
SLO_TTFT_THRESHOLD_MS = "ttft_threshold_ms"
SLO_TTFT_THRESHOLD_MS_DEFAULT = 500.0
SLO_E2E_TARGET = "e2e_target"               # serving_e2e objective target
SLO_E2E_TARGET_DEFAULT = 0.99
SLO_E2E_THRESHOLD_MS = "e2e_threshold_ms"
SLO_E2E_THRESHOLD_MS_DEFAULT = 5000.0
SLO_SNAPSHOT_FILE = "snapshot_file"         # "" -> <output_path>/SLO_REPORT.json
SLO_SNAPSHOT_FILE_DEFAULT = ""

# telemetry.federation: cross-process mission control (telemetry/
# federation.py) — a FleetAggregator on the aggregator rank discovers
# peers (static `peers` URL list + the run-dir registry every rank's
# ObsServer announces into), scrapes each peer's /metrics, reports and
# resumable /api/events over keep-alive HTTP with per-peer timeouts
# (a hanging peer degrades to `stale`, never blocks the loop), and
# serves merged views from its own ObsServer: /federation/metrics
# (every family rank-labelled), /federation/status, /api/fleet/events
# (one (t_us, seq, rank)-ordered timeline), /api/fleet/report/<name>.
# Fleet-scope SLO burn + cross-rank incident correlation ride the
# merged stream into FLEET_CONTROL.json. DS_TELEMETRY_FEDERATION=1/0
# force-toggles `enabled`; DS_TELEMETRY_FEDERATION_RUN_DIR,
# DS_TELEMETRY_FEDERATION_PEERS (comma list) and
# DS_TELEMETRY_FEDERATION_AGGREGATOR override their keys.
TELEMETRY_FEDERATION = "federation"
FEDERATION_ENABLED = "enabled"
FEDERATION_ENABLED_DEFAULT = False
FEDERATION_PEERS = "peers"                  # static peer base-url list
FEDERATION_PEERS_DEFAULT = ()
FEDERATION_RUN_DIR = "run_dir"              # peer-registry dir ("" -> chronicle run_dir)
FEDERATION_RUN_DIR_DEFAULT = ""
FEDERATION_AGGREGATOR = "aggregator"        # auto (rank 0) / always / never
FEDERATION_AGGREGATOR_DEFAULT = "auto"
FEDERATION_SCRAPE_INTERVAL_S = "scrape_interval_s"
FEDERATION_SCRAPE_INTERVAL_S_DEFAULT = 2.0
FEDERATION_TIMEOUT_S = "timeout_s"          # per-request peer timeout
FEDERATION_TIMEOUT_S_DEFAULT = 2.0
FEDERATION_STALE_AFTER_S = "stale_after_s"  # last-seen age that marks a peer stale
FEDERATION_STALE_AFTER_S_DEFAULT = 10.0
FEDERATION_EVENTS_RING = "events_ring"      # merged per-peer event buffer
FEDERATION_EVENTS_RING_DEFAULT = 4096
FEDERATION_SNAPSHOT_FILE = "snapshot_file"  # "" -> <output_path>/FLEET_CONTROL.json
FEDERATION_SNAPSHOT_FILE_DEFAULT = ""
FEDERATION_GOODPUT_TARGET = "goodput_target"   # fleet_goodput objective target
FEDERATION_GOODPUT_TARGET_DEFAULT = 0.90
FEDERATION_TTFT_TARGET = "ttft_target"         # fleet_ttft objective target
FEDERATION_TTFT_TARGET_DEFAULT = 0.99

# Checkpoint
CHECKPOINT = "checkpoint"
CHECKPOINT_TAG_VALIDATION = "tag_validation"
CHECKPOINT_TAG_VALIDATION_DEFAULT = "Warn"
CHECKPOINT_TAG_VALIDATION_MODES = ["Warn", "Ignore", "Fail"]
LOAD_UNIVERSAL_CHECKPOINT = "load_universal"
LOAD_UNIVERSAL_CHECKPOINT_DEFAULT = False
# async_save: snapshot-then-persist saves (runtime/async_checkpoint.py) —
# save_checkpoint returns after the device->host snapshot and a background
# thread does the file I/O while training continues. DS_CHECKPOINT_ASYNC_SAVE
# =1/0 force-toggles it.
CHECKPOINT_ASYNC_SAVE = "async_save"
CHECKPOINT_ASYNC_SAVE_DEFAULT = False
# fallback_to_intact: when the `latest` pointer names a tag that fails
# manifest verification, recover to the newest intact tag instead of
# raising. Explicit tag= loads never fall back. DS_CHECKPOINT_FALLBACK=1/0.
CHECKPOINT_FALLBACK = "fallback_to_intact"
CHECKPOINT_FALLBACK_DEFAULT = True
# writable_wait_timeout_s: how long rank 0 waits for the other ranks'
# shard files before writing the manifest (shared-filesystem gate).
CHECKPOINT_WAIT_TIMEOUT = "rank_wait_timeout_s"
CHECKPOINT_WAIT_TIMEOUT_DEFAULT = 300.0
# persist_retries: transient I/O failures in the (async) persist stage are
# retried this many times with jittered exponential backoff before the
# failure surfaces as AsyncCheckpointError at the next drain. Retries are
# counted into checkpoint_retries_total. DS_CHECKPOINT_PERSIST_RETRIES.
CHECKPOINT_PERSIST_RETRIES = "persist_retries"
CHECKPOINT_PERSIST_RETRIES_DEFAULT = 2
CHECKPOINT_PERSIST_BACKOFF_S = "persist_retry_backoff_s"
CHECKPOINT_PERSIST_BACKOFF_S_DEFAULT = 0.05

# Guardian (runtime/guardian.py): the anomaly->action policy engine.
# Config-gated and OFF by default — arming it means the run may take
# emergency checkpoints, roll itself back to the newest intact tag on
# confirmed divergence, reset a collapsed fp16 loss scale, and pause
# serving admission under overload. Every action is rate-limited,
# bounded, and journaled to GUARDIAN.json. DS_GUARDIAN=1/0 force-toggles.
GUARDIAN = "guardian"
GUARDIAN_ENABLED = "enabled"
GUARDIAN_ENABLED_DEFAULT = False
GUARDIAN_JOURNAL_FILE = "journal_file"      # "" -> <output_path>/GUARDIAN.json
GUARDIAN_JOURNAL_FILE_DEFAULT = ""
GUARDIAN_ACTION_COOLDOWN = "action_cooldown_steps"
GUARDIAN_ACTION_COOLDOWN_DEFAULT = 25
GUARDIAN_EMERGENCY_CHECKPOINT = "emergency_checkpoint"
GUARDIAN_EMERGENCY_CHECKPOINT_DEFAULT = True
GUARDIAN_EMERGENCY_RULES = "emergency_rules"  # [] -> built-in warning tier
GUARDIAN_MAX_EMERGENCY_CHECKPOINTS = "max_emergency_checkpoints"
GUARDIAN_MAX_EMERGENCY_CHECKPOINTS_DEFAULT = 4
GUARDIAN_ROLLBACK = "rollback"
GUARDIAN_ROLLBACK_DEFAULT = True
GUARDIAN_DIVERGENCE_WINDOW = "divergence_window"    # steps of evidence
GUARDIAN_DIVERGENCE_WINDOW_DEFAULT = 50
GUARDIAN_DIVERGENCE_STREAK = "divergence_streak"    # nonfinite firings
GUARDIAN_DIVERGENCE_STREAK_DEFAULT = 2
GUARDIAN_ROLLBACK_COOLDOWN = "rollback_cooldown_steps"
GUARDIAN_ROLLBACK_COOLDOWN_DEFAULT = 200
GUARDIAN_MAX_ROLLBACKS = "max_rollbacks"
GUARDIAN_MAX_ROLLBACKS_DEFAULT = 2
GUARDIAN_FP16_RESCUE = "fp16_rescue"
GUARDIAN_FP16_RESCUE_DEFAULT = True
GUARDIAN_MAX_FP16_RESCUES = "max_fp16_rescues"
GUARDIAN_MAX_FP16_RESCUES_DEFAULT = 2
GUARDIAN_SERVING_DEGRADE = "serving_degrade"
GUARDIAN_SERVING_DEGRADE_DEFAULT = True
GUARDIAN_PAUSE_RULES = "pause_rules"        # [] -> built-in overload rules
GUARDIAN_RESUME_CLEAR_STEPS = "resume_clear_steps"
GUARDIAN_RESUME_CLEAR_STEPS_DEFAULT = 64

# Eigenvalue (MoQ curvature)
EIGENVALUE = "eigenvalue"
EIGENVALUE_ENABLED = "enabled"
EIGENVALUE_ENABLED_DEFAULT = False
EIGENVALUE_VERBOSE = "verbose"
EIGENVALUE_VERBOSE_DEFAULT = False
EIGENVALUE_MAX_ITER = "max_iter"
EIGENVALUE_MAX_ITER_DEFAULT = 100
EIGENVALUE_TOL = "tol"
EIGENVALUE_TOL_DEFAULT = 1e-2
EIGENVALUE_STABILITY = "stability"
EIGENVALUE_STABILITY_DEFAULT = 1e-6
EIGENVALUE_GAS_BOUNDARY_RESOLUTION = "gas_boundary_resolution"
EIGENVALUE_GAS_BOUNDARY_RESOLUTION_DEFAULT = 1
EIGENVALUE_LAYER_NAME = "layer_name"
EIGENVALUE_LAYER_NAME_DEFAULT = "bert.encoder.layer"
EIGENVALUE_LAYER_NUM = "layer_num"
EIGENVALUE_LAYER_NUM_DEFAULT = 0

# Progressive layer drop
PROGRESSIVE_LAYER_DROP = "progressive_layer_drop"
PLD_ENABLED = "enabled"
PLD_ENABLED_DEFAULT = False
PLD_THETA = "theta"
PLD_THETA_DEFAULT = 1.0
PLD_GAMMA = "gamma"
PLD_GAMMA_DEFAULT = 0.001

# Curriculum learning
CURRICULUM_LEARNING = "curriculum_learning"
CURRICULUM_ENABLED = "enabled"
CURRICULUM_ENABLED_DEFAULT = False

# Quantize-aware training (MoQ)
QUANTIZE_TRAINING = "quantize_training"
QUANTIZE_TRAINING_ENABLED = "enabled"
QUANTIZE_TRAINING_ENABLED_DEFAULT = False
QUANTIZE_BITS = "quantize_bits"
START_BITS = "start_bits"
START_BITS_DEFAULT = 16
TARGET_BITS = "target_bits"
TARGET_BITS_DEFAULT = 8
QUANTIZER_KERNEL = "quantizer_kernel"
QUANTIZER_KERNEL_DEFAULT = False
QUANTIZE_SCHEDULE = "quantize_schedule"
QUANTIZE_PERIOD = "quantize_period"
QUANTIZE_PERIOD_DEFAULT = 1000
SCHEDULE_OFFSET = "schedule_offset"
SCHEDULE_OFFSET_DEFAULT = 1000
QUANTIZE_GROUPS = "quantize_groups"
QUANTIZE_GROUPS_DEFAULT = 1
QUANTIZE_CHANGE_RATIO = "quantize_change_ratio"
QUANTIZE_CHANGE_RATIO_DEFAULT = 0.001
QUANTIZE_TYPE = "quantize_type"
QUANTIZE_SYMMETRIC = "symmetric"
QUANTIZE_ASYMMETRIC = "asymmetric"
STOCHASTIC_ROUNDING = "stochastic_rounding"
STOCHASTIC_ROUNDING_DEFAULT = False
QUANTIZE_VERBOSE = "quantize_verbose"
QUANTIZE_VERBOSE_DEFAULT = False
QUANTIZE_ALGO = "quantize_algo"
QUANTIZE_ROUNDING = "rounding"
FP16_MIXED_QUANTIZE = "fp16_mixed_quantize"
QUANTIZE_OFFSET = "quantize_offset"
QUANTIZE_OFFSET_DEFAULT = 1000

# Sparse attention
SPARSE_ATTENTION = "sparse_attention"
SPARSE_DENSE_MODE = "dense"
SPARSE_FIXED_MODE = "fixed"
SPARSE_VARIABLE_MODE = "variable"
SPARSE_BIGBIRD_MODE = "bigbird"
SPARSE_BSLONGFORMER_MODE = "bslongformer"
SPARSE_MODE = "mode"
SPARSE_MODE_DEFAULT = SPARSE_FIXED_MODE
SPARSE_BLOCK = "block"
SPARSE_BLOCK_DEFAULT = 16
SPARSE_DIFFERENT_LAYOUT_PER_HEAD = "different_layout_per_head"
SPARSE_DIFFERENT_LAYOUT_PER_HEAD_DEFAULT = False
SPARSE_NUM_LOCAL_BLOCKS = "num_local_blocks"
SPARSE_NUM_LOCAL_BLOCKS_DEFAULT = 4
SPARSE_NUM_GLOBAL_BLOCKS = "num_global_blocks"
SPARSE_NUM_GLOBAL_BLOCKS_DEFAULT = 1
SPARSE_ATTENTION_TYPE = "attention"
SPARSE_ATTENTION_TYPE_DEFAULT = "bidirectional"
SPARSE_HORIZONTAL_GLOBAL_ATTENTION = "horizontal_global_attention"
SPARSE_HORIZONTAL_GLOBAL_ATTENTION_DEFAULT = False
SPARSE_NUM_DIFFERENT_GLOBAL_PATTERNS = "num_different_global_patterns"
SPARSE_NUM_DIFFERENT_GLOBAL_PATTERNS_DEFAULT = 1
SPARSE_NUM_RANDOM_BLOCKS = "num_random_blocks"
SPARSE_NUM_RANDOM_BLOCKS_DEFAULT = 0
SPARSE_LOCAL_WINDOW_BLOCKS = "local_window_blocks"
SPARSE_LOCAL_WINDOW_BLOCKS_DEFAULT = [4]
SPARSE_GLOBAL_BLOCK_INDICES = "global_block_indices"
SPARSE_GLOBAL_BLOCK_INDICES_DEFAULT = [0]
SPARSE_GLOBAL_BLOCK_END_INDICES = "global_block_end_indices"
SPARSE_GLOBAL_BLOCK_END_INDICES_DEFAULT = None
SPARSE_NUM_SLIDING_WINDOW_BLOCKS = "num_sliding_window_blocks"
SPARSE_NUM_SLIDING_WINDOW_BLOCKS_DEFAULT = 3

# Flops profiler
FLOPS_PROFILER = "flops_profiler"
FLOPS_PROFILER_ENABLED = "enabled"
FLOPS_PROFILER_ENABLED_DEFAULT = False
FLOPS_PROFILER_PROFILE_STEP = "profile_step"
FLOPS_PROFILER_PROFILE_STEP_DEFAULT = 1
FLOPS_PROFILER_MODULE_DEPTH = "module_depth"
FLOPS_PROFILER_MODULE_DEPTH_DEFAULT = -1
FLOPS_PROFILER_TOP_MODULES = "top_modules"
FLOPS_PROFILER_TOP_MODULES_DEFAULT = 1
FLOPS_PROFILER_DETAILED = "detailed"
FLOPS_PROFILER_DETAILED_DEFAULT = True
FLOPS_PROFILER_OUTPUT_FILE = "output_file"
FLOPS_PROFILER_OUTPUT_FILE_DEFAULT = None

# Activation checkpointing (remat)
ACTIVATION_CHECKPOINTING = "activation_checkpointing"
ACT_CHKPT_PARTITION_ACTIVATIONS = "partition_activations"
ACT_CHKPT_PARTITION_ACTIVATIONS_DEFAULT = False
ACT_CHKPT_NUMBER_CHECKPOINTS = "number_checkpoints"
ACT_CHKPT_NUMBER_CHECKPOINTS_DEFAULT = None
ACT_CHKPT_CONTIGUOUS_MEMORY_OPTIMIZATION = "contiguous_memory_optimization"
ACT_CHKPT_CONTIGUOUS_MEMORY_OPTIMIZATION_DEFAULT = False
ACT_CHKPT_SYNCHRONIZE_CHECKPOINT_BOUNDARY = "synchronize_checkpoint_boundary"
ACT_CHKPT_SYNCHRONIZE_CHECKPOINT_BOUNDARY_DEFAULT = False
ACT_CHKPT_PROFILE = "profile"
ACT_CHKPT_PROFILE_DEFAULT = False
ACT_CHKPT_CPU_CHECKPOINTING = "cpu_checkpointing"
ACT_CHKPT_CPU_CHECKPOINTING_DEFAULT = False

# Async I/O (NVMe swap)
AIO = "aio"
AIO_BLOCK_SIZE = "block_size"
AIO_BLOCK_SIZE_DEFAULT = 1048576
AIO_QUEUE_DEPTH = "queue_depth"
AIO_QUEUE_DEPTH_DEFAULT = 8
AIO_THREAD_COUNT = "thread_count"
AIO_THREAD_COUNT_DEFAULT = 1
AIO_SINGLE_SUBMIT = "single_submit"
AIO_SINGLE_SUBMIT_DEFAULT = False
AIO_OVERLAP_EVENTS = "overlap_events"
AIO_OVERLAP_EVENTS_DEFAULT = True

# Dataloader
DATALOADER_DROP_LAST = "dataloader_drop_last"
DATALOADER_DROP_LAST_DEFAULT = False

# data_prefetch: asynchronous input pipeline (runtime/prefetch.py).
# When enabled, deepspeed_io-built loaders (and iterators handed to
# train_batch) are wrapped in a bounded background pipeline: host worker
# thread(s) pull + collate the next `depth` batches, and a device stage
# issues _globalize_batch/device_put for batch N+1 while step N computes,
# so the H2D copy overlaps device execution. The device stage runs on
# multi-process meshes too: background placement is collective-free
# (verify=False) and the cross-process verification collectives run on
# the main thread at consumption.
# `num_local_io_workers` (deepspeed_io argument) sets the host-stage
# worker count. DS_DATA_PREFETCH=1/0 force-toggles `enabled`.
DATA_PREFETCH = "data_prefetch"
DATA_PREFETCH_ENABLED = "enabled"
DATA_PREFETCH_ENABLED_DEFAULT = False
DATA_PREFETCH_DEPTH = "depth"               # max batches in the pipeline
DATA_PREFETCH_DEPTH_DEFAULT = 2
DATA_PREFETCH_TO_DEVICE = "to_device"       # arm the device stage
DATA_PREFETCH_TO_DEVICE_DEFAULT = True

# comm_overlap: bucketed gradient-collective overlap
# (runtime/comm_overlap.py). When enabled (and the config is in the
# supported envelope: dp > 1, zero stage <= 1, mp/ep/pp == 1, dense
# grads), the train step computes gradients under shard_map and reduces
# them with ONE psum per size-targeted bucket — issued per-bucket as the
# backward produces each bucket's grads — instead of GSPMD's one
# all-reduce per grad leaf parked on the step tail. `bucket_mb` sets the
# flattened bucket target; `scheduler_flags` logs the XLA latency-hiding
# scheduler flag line when it is missing on a TPU backend (XLA_FLAGS is
# read once at process start, so the engine cannot arm it itself).
# DS_COMM_OVERLAP=1/0 force-toggles `enabled`.
COMM_OVERLAP = "comm_overlap"
COMM_OVERLAP_ENABLED = "enabled"
COMM_OVERLAP_ENABLED_DEFAULT = False
COMM_OVERLAP_BUCKET_MB = "bucket_mb"        # flattened bucket target, MiB
COMM_OVERLAP_BUCKET_MB_DEFAULT = 4.0
COMM_OVERLAP_SCHEDULER_FLAGS = "scheduler_flags"
COMM_OVERLAP_SCHEDULER_FLAGS_DEFAULT = True

# serving: continuous-batching inference server (serving/). Paged KV
# cache of `block_size`-token blocks (`num_blocks` 0 -> sized so
# `max_batch` full-length sequences fit, i.e. preemption-free), a
# `max_batch`-slot static decode batch, `prefill_chunk`-token chunked
# prefill, and `max_model_len` (0 -> the model's n_positions) as the
# per-request position cap. On TPU pick block_size * blocks-per-seq in
# multiples of the decode kernel's 512-token KV tile so the per-step
# gather stays copy-free.
SERVING = "serving"
SERVING_BLOCK_SIZE = "block_size"
SERVING_BLOCK_SIZE_DEFAULT = 16
SERVING_NUM_BLOCKS = "num_blocks"
SERVING_NUM_BLOCKS_DEFAULT = 0
SERVING_MAX_BATCH = "max_batch"
SERVING_MAX_BATCH_DEFAULT = 8
SERVING_PREFILL_CHUNK = "prefill_chunk"
SERVING_PREFILL_CHUNK_DEFAULT = 32
SERVING_MAX_MODEL_LEN = "max_model_len"
SERVING_MAX_MODEL_LEN_DEFAULT = 0
# "paged" streams attention over LIVE KV blocks (dynamic trip count, the
# PagedAttention shape — per-step traffic scales with tokens that exist);
# "gather" materialises the block table into the contiguous view the
# Pallas decode kernel consumes (fixed window, tuned TPU GEMMs)
SERVING_ATTENTION_IMPL = "attention_impl"
SERVING_ATTENTION_IMPL_DEFAULT = "paged"
# tokens decoded per dispatch (vLLM num_scheduler_steps-style multi-step
# scheduling): >1 amortises host dispatch + the device sync over K
# tokens at the cost of K-token admission/finish granularity (tokens a
# request samples past its eos inside a dispatch are discarded)
SERVING_DECODE_STEPS = "decode_steps"
SERVING_DECODE_STEPS_DEFAULT = 1

# serving.speculative: draft/verify speculative decoding
# (serving/speculative.py). A cheap draft proposes `k` greedy tokens,
# then ONE target forward verifies all k+1 positions and keeps the
# longest accepted prefix — decode is weight-bandwidth-bound at small
# batch, so the verify runs at near-single-token cost. `draft_layers`
# 0 -> auto (n_layer // 4, floor 1) selects the truncated-layer
# self-draft (the target's own first layers — zero extra weights);
# `draft_model` null -> self-draft (an explicit small model is passed
# programmatically as `draft_params`). `acceptance` "exact" keeps
# greedy AND sampled outputs bit-exact vs the non-speculative engine;
# "typical" relaxes sampled slots to `typical_threshold` x the modal
# probability for higher acceptance. `acceptance_floor` arms the
# observatory's speculation_waste rule (windowed acceptance below the
# floor -> warn; the guardian can disable speculation as an action).
# Replaces the decode program with exactly {1 draft, 1 verify}
# programs; rejected tokens are booked into the slot-step ledger's
# drafted_rejected category. DS_SERVING_SPEC=1/0 force-toggles
# `enabled`.
SERVING_SPECULATIVE = "speculative"
SERVING_SPEC_ENABLED = "enabled"
SERVING_SPEC_ENABLED_DEFAULT = False
SERVING_SPEC_K = "k"                        # drafted tokens per dispatch
SERVING_SPEC_K_DEFAULT = 4
SERVING_SPEC_DRAFT_LAYERS = "draft_layers"  # 0 -> n_layer // 4 (min 1)
SERVING_SPEC_DRAFT_LAYERS_DEFAULT = 0
SERVING_SPEC_DRAFT_MODEL = "draft_model"    # null -> self-draft
SERVING_SPEC_DRAFT_MODEL_DEFAULT = None
SERVING_SPEC_ACCEPTANCE = "acceptance"      # "exact" | "typical"
SERVING_SPEC_ACCEPTANCE_DEFAULT = "exact"
SERVING_SPEC_TYPICAL_THRESHOLD = "typical_threshold"
SERVING_SPEC_TYPICAL_THRESHOLD_DEFAULT = 0.3
SERVING_SPEC_ACCEPTANCE_FLOOR = "acceptance_floor"
SERVING_SPEC_ACCEPTANCE_FLOOR_DEFAULT = 0.35

# serving.prefix_cache: block-level shared-prefix KV reuse
# (serving/kv_cache.py PrefixCache). FULL prompt blocks are
# content-addressed by a chain hash of (parent digest, token ids,
# position base) salted with attention_impl|kv_dtype into a bounded LRU
# index; admission maps hits read-only into the slot's block table
# (prefill starts at the first uncached token), the first divergent
# write copy-on-write-forks the block, and refcount-1 (cache-only)
# blocks are reclaimed before any preemption fires. capacity_blocks 0
# -> uncapped (bounded by the pool itself). DS_SERVING_PREFIX_CACHE=1/0
# force-toggles `enabled`.
SERVING_PREFIX_CACHE = "prefix_cache"
SERVING_PREFIX_ENABLED = "enabled"
SERVING_PREFIX_ENABLED_DEFAULT = False
SERVING_PREFIX_CAPACITY_BLOCKS = "capacity_blocks"
SERVING_PREFIX_CAPACITY_BLOCKS_DEFAULT = 0

# serving.router: SLO-aware multi-replica admission (serving/router.py).
# Each request is scored per replica as
#   affinity_weight * matched-prefix-blocks
#   - queue_weight * queue_depth - occupancy_weight * kv_occupancy
#   - breach_penalty * (recent ttft_slo_breach or queue_growth)
# and lands on the argmax; `breach_penalty` is sized so a breaching
# replica only wins when every replica is breaching (failover, not
# blacklist). replicas is the engine count a ServingRouter.build spins
# up when the caller does not hand it engines.
SERVING_ROUTER = "router"
SERVING_ROUTER_REPLICAS = "replicas"
SERVING_ROUTER_REPLICAS_DEFAULT = 1
SERVING_ROUTER_AFFINITY_WEIGHT = "affinity_weight"
SERVING_ROUTER_AFFINITY_WEIGHT_DEFAULT = 4.0
SERVING_ROUTER_QUEUE_WEIGHT = "queue_weight"
SERVING_ROUTER_QUEUE_WEIGHT_DEFAULT = 1.0
SERVING_ROUTER_OCCUPANCY_WEIGHT = "occupancy_weight"
SERVING_ROUTER_OCCUPANCY_WEIGHT_DEFAULT = 2.0
SERVING_ROUTER_BREACH_PENALTY = "breach_penalty"
SERVING_ROUTER_BREACH_PENALTY_DEFAULT = 100.0

# serving.observability: the serving observatory
# (telemetry/serving_observatory.py). Per-request lifecycle timelines
# (exported as per-slot Chrome-trace lanes when the tracer is live), a
# slot-step ledger decomposing every scheduler step's
# max_batch x decode_steps slot micro-units into decode_useful /
# cached_prefill / prefill
# / recompute / frozen / idle (sums to steps x max_batch x K by
# construction), and windowed SLO rules (ttft_slo_breach, queue_growth,
# preemption_thrash, decode_stall, no_progress) escalating warn-once ->
# throttled SERVING_HEALTH.json -> trace flush. Pure host bookkeeping:
# adds zero device syncs and zero compiled-program changes.
# DS_SERVING_OBS=1/0 force-toggles `enabled`.
SERVING_OBSERVABILITY = "observability"
SERVING_OBS_ENABLED = "enabled"
SERVING_OBS_ENABLED_DEFAULT = False
SERVING_OBS_WINDOW = "window"               # scheduler steps per window
SERVING_OBS_WINDOW_DEFAULT = 32
SERVING_OBS_WARMUP = "warmup_windows"       # windows before rules arm
SERVING_OBS_WARMUP_DEFAULT = 1
SERVING_OBS_TTFT_SLO_MS = "ttft_slo_ms"
SERVING_OBS_TTFT_SLO_MS_DEFAULT = 1000.0
SERVING_OBS_TTFT_BREACH_FRAC = "ttft_breach_frac"
SERVING_OBS_TTFT_BREACH_FRAC_DEFAULT = 0.5
SERVING_OBS_QUEUE_GROWTH_WINDOWS = "queue_growth_windows"
SERVING_OBS_QUEUE_GROWTH_WINDOWS_DEFAULT = 3
SERVING_OBS_PREEMPTION_THRASH = "preemption_thrash"  # per window
SERVING_OBS_PREEMPTION_THRASH_DEFAULT = 8
SERVING_OBS_NO_PROGRESS_STEPS = "no_progress_steps"
SERVING_OBS_NO_PROGRESS_STEPS_DEFAULT = 200
SERVING_OBS_TIMELINE_RING = "timeline_ring"  # finished timelines kept
SERVING_OBS_TIMELINE_RING_DEFAULT = 64
SERVING_OBS_WINDOW_RING = "window_ring"
SERVING_OBS_WINDOW_RING_DEFAULT = 128
SERVING_OBS_TRACE_LANES = "trace_lanes"     # per-slot Chrome lanes
SERVING_OBS_TRACE_LANES_DEFAULT = True
SERVING_OBS_SNAPSHOT_FILE = "snapshot_file"
SERVING_OBS_SNAPSHOT_FILE_DEFAULT = "SERVING_HEALTH.json"

# autotuning: goodput-driven two-stage config search (autotuning/tune.py).
# Stage 1 AOT-compiles every candidate ONCE (abstract engines — zero
# device execution), rejects candidates whose HBM watermark exceeds
# `memory_headroom` x the device budget (`hbm_budget_gb` 0 -> the same
# memory_stats/host-RSS detection chain the telemetry registry uses) and
# ranks survivors by roofline cost; stage 2 probes the top `top_k`
# survivors for `probe_steps` measured steps each (after
# `probe_warmup_steps`), scored by the goodput ledger's goodput fraction
# (metric "goodput") or raw wall time (metric "step_time"). The run
# emits `report_file` (TUNE_REPORT.json). DS_AUTOTUNING=1/0 force-
# toggles `enabled`; DS_AUTOTUNING_TOP_K / DS_AUTOTUNING_REPORT override
# top_k / report_file.
AUTOTUNING = "autotuning"
AUTOTUNING_ENABLED = "enabled"
AUTOTUNING_ENABLED_DEFAULT = False
AUTOTUNING_METRIC = "metric"
AUTOTUNING_METRIC_DEFAULT = "goodput"
AUTOTUNING_TOP_K = "top_k"
AUTOTUNING_TOP_K_DEFAULT = 3
AUTOTUNING_PROBE_STEPS = "probe_steps"
AUTOTUNING_PROBE_STEPS_DEFAULT = 8
AUTOTUNING_PROBE_WARMUP = "probe_warmup_steps"
AUTOTUNING_PROBE_WARMUP_DEFAULT = 2
AUTOTUNING_MEMORY_HEADROOM = "memory_headroom"
AUTOTUNING_MEMORY_HEADROOM_DEFAULT = 0.95
AUTOTUNING_HBM_BUDGET_GB = "hbm_budget_gb"
AUTOTUNING_HBM_BUDGET_GB_DEFAULT = 0
AUTOTUNING_REPORT_FILE = "report_file"
AUTOTUNING_REPORT_FILE_DEFAULT = "TUNE_REPORT.json"
AUTOTUNING_RESULTS_DIR = "results_dir"
AUTOTUNING_RESULTS_DIR_DEFAULT = "autotuning_results"
AUTOTUNING_SEED = "seed"
AUTOTUNING_SEED_DEFAULT = 0
# declared search space: {dim: [values]} — special dims micro_batch /
# gas / zero_stage / prefetch_depth, "model.<kwarg>" dims forwarded to
# the model factory (remat, attention impl, ...), anything else a
# dotted config path set into each candidate's config dict
AUTOTUNING_SPACE = "space"
AUTOTUNING_SPACE_DEFAULT = None

# Pipeline
PIPE_REPLICATED = "ds_pipe_replicated"
PIPELINE = "pipeline"
PIPELINE_STAGES = "stages"
PIPELINE_STAGES_DEFAULT = "auto"
PIPELINE_PARTITION = "partition"
PIPELINE_PARTITION_DEFAULT = "best"
PIPELINE_SEED_LAYERS = "seed_layers"
PIPELINE_SEED_LAYERS_DEFAULT = False
PIPELINE_ACTIVATION_CHECKPOINT_INTERVAL = "activation_checkpoint_interval"
PIPELINE_ACTIVATION_CHECKPOINT_INTERVAL_DEFAULT = 0

# Misc
VOCABULARY_SIZE = "vocabulary_size"
VOCABULARY_SIZE_DEFAULT = None
GRADIENT_ACCUMULATION_FORMAT = "gradient_accumulation_dtype"
