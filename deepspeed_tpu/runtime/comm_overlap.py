"""Bucketed gradient-collective overlap (the ``comm_overlap`` block).

PERF.md's round-5 ablation left multi-chip gradient reductions firing
only at the step boundary: under plain GSPMD jit the partitioner emits
one all-reduce per grad leaf (the NORTHSTAR gpt2-xl program carries 586
of them) and the default scheduler parks them on the critical tail of
the backward. This module is the reference's ZeRO bucketed
``allreduce_bucket`` discipline (PAPER.md §2; Megatron-style grad
bucketing in the scheduling literature) rebuilt for the shard_map world:

* :func:`build_grad_bucket_spec` groups the grad leaves into
  size-targeted buckets **in reverse tree order** — the vjp produces the
  LAST layer's grads first, so the bucket holding layer N's grads is
  complete while layer N-1's backward is still running;
* :func:`bucketed_pmean` issues ONE ``lax.pmean`` per bucket (leaves
  flattened into a contiguous vector) instead of one per leaf. Each
  bucket's psum depends only on its own leaves, so the scheduler is
  free to issue it as soon as the bucket's grads exist — with the
  latency-hiding scheduler armed (:func:`overlap_xla_flags`) the
  collectives become async ``-start``/``-done`` pairs hoisted into the
  backward instead of a serialized tail;
* the engine selects the bucketed value_and_grad variant
  (``engine._make_overlap_vg``) BEFORE the first lower, like the health
  stats variant, so a comm_overlap run still compiles exactly one
  train-step program (guarded in ``tests/perf/telemetry_overhead.py``).

Even without async collectives (CPU, older TPUs) the bucketing is a
measured win by itself: B bucket-sized reductions replace hundreds of
per-leaf dispatches (``tests/perf/overlap_bench.py`` /
``OVERLAP_BENCH.json`` is the committed proof, with the PR-2 HLO census
as the structural evidence — grad all-reduce count collapses to the
bucket count, and the collective positions spread off the program
tail).

``XLA_FLAGS`` must be set at process start (PR-2 lesson:
``clear_backends`` cannot re-read it), so the engine cannot arm the
scheduler flags itself mid-process — launchers/benches prepend
:func:`overlap_xla_flags` before importing jax; the engine logs the
exact line once when it detects the flags missing on a TPU backend.
"""

from typing import NamedTuple, Tuple

from deepspeed_tpu.utils.logging import logger


class GradBucketSpec(NamedTuple):
    """Static assignment of grad-tree leaves to reduction buckets.

    ``buckets[b]`` holds the ORIGINAL ``jax.tree.leaves`` indices of
    bucket ``b``'s leaves, ordered so bucket 0 is the one the backward
    finishes FIRST (reverse tree order). Built once at engine init from
    the param tree's structure — the traced reduction is a fixed set of
    per-bucket collectives, no dynamic shapes."""
    buckets: Tuple[Tuple[int, ...], ...]
    bucket_bytes: Tuple[int, ...]
    n_leaves: int

    @property
    def n_buckets(self) -> int:
        return len(self.buckets)


def build_grad_bucket_spec(params, bucket_bytes: int) -> GradBucketSpec:
    """Group the param/grad leaves into size-targeted reduction buckets.

    Leaves are walked in REVERSE ``jax.tree.leaves`` order (backward
    produces the deepest layers' grads first) and greedily packed until
    a bucket reaches ``bucket_bytes``; the tail forms a remainder bucket.
    A leaf larger than the target gets a bucket of its own (it is never
    split — the collective is already one op). Float leaves may share a
    bucket regardless of width (the flattened vector reduces in fp32 and
    each leaf is cast back on split); non-float leaves never share.
    ``params`` may be arrays or ShapeDtypeStructs — only shape/dtype are
    read."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    bucket_bytes = max(1, int(bucket_bytes))
    leaves = jax.tree.leaves(params)
    if not leaves:
        return GradBucketSpec((), (), 0)

    def _is_float(dt):
        # numpy's kind is "V" for ml_dtypes extended floats (bf16, fp8) —
        # jnp.issubdtype sees through them
        return dt.kind == "f" or jnp.issubdtype(dt, jnp.floating)

    def leaf_bytes(x):
        size = int(np.prod(x.shape)) if getattr(x, "shape", ()) else 1
        # grads of floating params reduce in fp32 regardless of the
        # master dtype (bucketed_pmean upcasts before the collective);
        # non-float leaves keep their own itemsize
        dt = np.dtype(getattr(x, "dtype", np.float32))
        itemsize = 4 if _is_float(dt) else dt.itemsize
        return size * itemsize

    buckets, sizes = [], []
    cur, cur_bytes = [], 0
    for idx in range(len(leaves) - 1, -1, -1):
        x = leaves[idx]
        if not _is_float(np.dtype(getattr(x, "dtype", np.float32))):
            # non-float leaves never share a bucket: the multi-leaf path
            # flattens in fp32, which would corrupt them. Can't occur in
            # a real grad tree (value_and_grad rejects integer params) —
            # kept as a safe fallback for exotic specs.
            if cur:
                buckets.append(tuple(cur))
                sizes.append(cur_bytes)
                cur, cur_bytes = [], 0
            buckets.append((idx,))
            sizes.append(leaf_bytes(x))
            continue
        b = leaf_bytes(x)
        if cur and cur_bytes + b > bucket_bytes:
            buckets.append(tuple(cur))
            sizes.append(cur_bytes)
            cur, cur_bytes = [], 0
        cur.append(idx)
        cur_bytes += b
    if cur:
        buckets.append(tuple(cur))
        sizes.append(cur_bytes)
    return GradBucketSpec(tuple(buckets), tuple(sizes), len(leaves))


def bucketed_pmean(spec: GradBucketSpec, grads, axis: str):
    """Mean-reduce a grad pytree over ``axis`` with ONE collective per
    bucket. Traced inside a ``shard_map`` body: each bucket's leaves are
    flattened into one contiguous fp32 vector, ``lax.pmean``-ed, and
    split back — a single-leaf bucket skips the flatten entirely (big
    tensors that fill a bucket alone pay no copy). The reduction is
    arithmetically the per-leaf ``pmean`` (sum over ranks / world), so
    loss trajectories match the unbucketed path to float tolerance."""
    import jax
    import jax.numpy as jnp

    from deepspeed_tpu.runtime.optim import flatten_leaves
    flat, treedef = jax.tree_util.tree_flatten(grads)
    assert len(flat) == spec.n_leaves, (
        f"bucket spec built for {spec.n_leaves} leaves but the grad tree "
        f"has {len(flat)} — spec and tree diverged")
    out: list = [None] * len(flat)
    for idxs in spec.buckets:
        if len(idxs) == 1:
            # same fp32-reduction invariant as the multi-leaf path (and as
            # build_grad_bucket_spec's 4 B/elem float accounting): upcast
            # float leaves for the collective, cast back after
            i = idxs[0]
            leaf = flat[i]
            if jnp.issubdtype(leaf.dtype, jnp.floating):
                out[i] = jax.lax.pmean(
                    leaf.astype(jnp.float32), axis).astype(leaf.dtype)
            else:
                out[i] = jax.lax.pmean(leaf, axis)
            continue
        vec = jax.lax.pmean(
            flatten_leaves([flat[i] for i in idxs]), axis)
        off = 0
        for i in idxs:
            n = flat[i].size
            out[i] = vec[off:off + n].reshape(
                flat[i].shape).astype(flat[i].dtype)
            off += n
    return jax.tree_util.tree_unflatten(treedef, out)


# Latency-hiding scheduler flag set (MaxText/AotC lineage): converts the
# per-bucket sync collectives into async -start/-done pairs and lets the
# scheduler hoist the starts into the backward. TPU-only spellings —
# unknown --xla_tpu_* flags are a hard error on non-TPU backends, so the
# helper gates on the backend.
_TPU_OVERLAP_FLAGS = (
    "--xla_tpu_enable_latency_hiding_scheduler=true",
    "--xla_tpu_enable_async_collective_fusion=true",
    "--xla_tpu_enable_async_collective_fusion_fuse_all_gather=true",
    "--xla_tpu_enable_async_collective_fusion_multiple_steps=true",
    "--xla_tpu_overlap_compute_collective_tc=true",
)


def overlap_xla_flags(backend: str = "tpu") -> Tuple[str, ...]:
    """The XLA flag line that arms async-collective overlap on ``backend``
    (empty on backends with no known spelling). Must be in ``XLA_FLAGS``
    BEFORE jax initialises its backend — prepend at launch:

        XLA_FLAGS="$(python -c 'from deepspeed_tpu.runtime.comm_overlap \
import overlap_xla_flags; print(" ".join(overlap_xla_flags()))') \
$XLA_FLAGS" python train.py
    """
    if backend == "tpu":
        return _TPU_OVERLAP_FLAGS
    return ()


def check_scheduler_flags(backend: str) -> bool:
    """True when the overlap flags are already armed for ``backend`` (or
    the backend has none to arm). Pure env inspection — callable after
    backend init, unlike setting the flags. Parses XLA_FLAGS into
    name=value pairs: a flag explicitly set to ``false`` (or a merely
    prefix-colliding name) must NOT count as armed — this is the one
    diagnostic that catches a mis-armed TPU launch. All absl truthy
    spellings count as armed: bare ``--flag``, ``=true``, ``=1``,
    ``=t``, ``=yes`` (any case)."""
    import os
    want = overlap_xla_flags(backend)
    if not want:
        return True
    truthy = {"", "true", "1", "t", "y", "yes"}
    have = {}
    for tok in os.environ.get("XLA_FLAGS", "").split():
        name, _, value = tok.partition("=")
        have[name] = value.lower() in truthy
    return all(have.get(f.partition("=")[0], False) for f in want)


def log_scheduler_flags_hint(backend: str) -> None:
    """One engine-init line naming the exact flags a TPU launch should
    set for the async-overlap half of comm_overlap (the bucketing half
    works regardless)."""
    if check_scheduler_flags(backend):
        return
    logger.info(
        "[comm_overlap] latency-hiding scheduler flags are not set; the "
        "per-bucket collectives stay synchronous (bucketing still "
        "applies). Arm them at process start with XLA_FLAGS=\"%s\"",
        " ".join(overlap_xla_flags(backend)))
