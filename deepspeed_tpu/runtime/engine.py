"""The training engine.

TPU-native rebuild of ``DeepSpeedEngine`` (reference
deepspeed/runtime/engine.py:165). The reference wraps an eager PyTorch
module and imperatively orchestrates precision, ZeRO hooks, collectives and
the optimizer across ``forward``/``backward``/``step``. Here the same user
surface drives ONE pjit-compiled micro-step and ONE compiled apply-step
over a named device mesh:

* ``forward(batch)`` computes the (scaled) loss AND the gradients in a
  single fused compiled call, accumulating fp32 grads into the train state
  (the reference's separate backward exists because autograd is eager; in
  JAX loss and grads come from one ``value_and_grad``). ``backward()``
  advances the micro-step counter; ``step()`` applies the optimizer at the
  gradient-accumulation boundary — matching the reference's
  ``is_gradient_accumulation_boundary`` semantics (engine.py:1747).
* ZeRO stages are sharding rules (runtime/zero/partition.py), not hooks:
  the state carries NamedShardings and XLA inserts the all-gather /
  reduce-scatter traffic that stage_1_and_2.py / stage3.py issue by hand.
* Mixed precision: fp32 master params live in the state; the forward casts
  to bf16/fp16 (``_configure_distributed_model`` engine.py:997 analogue);
  dynamic loss scaling runs inside the compiled step with a ``lax.cond``
  skip — no per-step host sync (reference overflow check engine.py:1747+
  forces D2H).

Checkpoint layout keeps the reference's file naming
(``{tag}/mp_rank_00_model_states.pt``, ``zero_pp_rank_*_optim_states.pt``,
``latest`` tag file — engine.py:2350/:2345/:2889) so downstream tooling and
the zero_to_fp32 converter work unchanged.
"""

import contextlib
import glob
import os
import shutil
import time
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.runtime import optim as optim_lib
from deepspeed_tpu.runtime.config import (
    ADAGRAD_OPTIMIZER, ADAM_OPTIMIZER, ADAMW_OPTIMIZER, DeepSpeedConfig,
    LAMB_OPTIMIZER, ONEBIT_ADAM_OPTIMIZER, ONEBIT_LAMB_OPTIMIZER, SGD_OPTIMIZER)
from deepspeed_tpu.runtime.constants import ROUTE_TRAIN
from deepspeed_tpu.runtime.dataloader import DeepSpeedDataLoader, RepeatingLoader
from deepspeed_tpu.runtime.prefetch import PrefetchIterator, PrefetchLoader
from deepspeed_tpu.runtime.fp16.loss_scaler import (
    LossScaleState, make_scale_state, scale_state_stats, update_scale)
from deepspeed_tpu.runtime.lr_schedules import get_lr_schedule
from deepspeed_tpu.runtime.zero.partition import (
    ModelParallelRules, build_opt_shardings, build_param_shardings,
    grad_constraint_fn)
from deepspeed_tpu.utils import groups
from deepspeed_tpu.utils.logging import log_dist, logger
from deepspeed_tpu.utils.timer import SynchronizedWallClockTimer, ThroughputTimer

# reference timer names (deepspeed/runtime/engine.py:113-123). Under XLA the
# forward and backward are ONE fused vjp program, so the 'forward' timer
# carries the fused fwd+bwd time and 'backward' only the host bookkeeping;
# a one-time log line says so when wall_clock_breakdown is enabled.
FORWARD_GLOBAL_TIMER = "forward"
BACKWARD_GLOBAL_TIMER = "backward"
STEP_GLOBAL_TIMER = "step"

MODEL_FILE_SUFFIX = "_model_states.pt"
OPTIM_FILE_SUFFIX = "_optim_states.pt"
LATEST_FILE = "latest"

# shared no-op for the goodput-disabled ledger paths (nullcontext holds no
# state, so one instance can nest/re-enter freely)
_NULL_CTX = contextlib.nullcontext()


class TrainState(NamedTuple):
    """All mutable training state, as one donated pytree.

    ``step`` counts APPLIED (non-skipped) optimizer steps — it indexes the
    LR schedule inside the compiled apply step. Micro-step and skipped-step
    counters live host-side only (self.micro_steps / self.skipped_steps);
    keeping device copies would create a second source of truth."""
    step: jnp.ndarray          # applied optimizer steps
    params: Any                # fp32 master parameters
    opt_state: Any
    acc_grads: Any             # fp32 accumulation buffer (ZeRO-sharded)
    scale: LossScaleState


def _cast_tree(tree, dtype):
    return jax.tree.map(
        lambda x: x.astype(dtype) if jnp.issubdtype(x.dtype, jnp.floating) else x,
        tree)


def _default_sparse_ids_fn(batch):
    """Token ids whose embedding rows the batch touches (reference: the
    indices of the torch sparse embedding grad)."""
    if isinstance(batch, dict):
        for k in ("input_ids", "ids", "tokens"):
            if k in batch:
                return batch[k]
        raise ValueError(
            "sparse_gradients: could not find token ids in the batch dict "
            f"(keys {list(batch)}); pass sparse_ids_fn=... to initialize()")
    if isinstance(batch, (tuple, list)):
        ids = batch[0]
    else:
        ids = batch
    if not jnp.issubdtype(jnp.asarray(ids).dtype, jnp.integer):
        raise ValueError(
            "sparse_gradients: the first batch element has dtype "
            f"{jnp.asarray(ids).dtype}, not an integer token-id array; "
            "pass sparse_ids_fn=... to initialize()")
    return ids


class _AOTStep:
    """AOT execution wrapper around ONE jitted step entry point.

    jax 0.4.x keeps the eager-jit executable cache and the AOT
    (``lower().compile()``) cache fully separate — asking a live engine
    "what did you compile?" via the AOT path would silently pay a full
    DUPLICATE XLA compile (this is exactly what the old flops profiler
    did). The fix is ownership: when the cost explorer is enabled, the
    engine's first dispatch for a signature goes ``lower -> compile ->
    call`` so the ``jax.stages.Compiled`` artifact is KEPT — same single
    compile the jit would have done, but now ``cost_analysis()`` /
    ``memory_analysis()`` / ``as_text()`` are readable forever at zero
    cost, and the HBM pre-flight can run BETWEEN compile and first
    execution.

    Per-call cost is one tree_flatten signature check (~µs, measured
    +0.6µs vs the raw jit fastpath) — only paid when the cost explorer
    is explicitly enabled. A NEW signature after priming (curriculum
    plateau, eval shape) falls back to the wrapped jit, which retraces
    exactly as before.
    """

    def __init__(self, jit_fn, name, on_compiled=None):
        self._jit = jit_fn
        self._name = name
        self._on_compiled = on_compiled      # callback(name, compiled)
        self._sig = None
        self.compiled = None                 # jax.stages.Compiled once primed
        self._prime_failed = False
        self.fallback_calls = 0
        # unwrap contract: consumers (flops profiler) expect __wrapped__
        # to be the RAW python function, as on the jit itself
        self.__wrapped__ = getattr(jit_fn, "__wrapped__", jit_fn)
        self.__name__ = name

    def lower(self, *args, **kwargs):
        """AOT surface, delegated (lower_train_step-style consumers)."""
        return self._jit.lower(*args, **kwargs)

    def _signature(self, args):
        leaves, treedef = jax.tree_util.tree_flatten(args)
        if any(isinstance(x, jax.core.Tracer) for x in leaves):
            # being traced by an outer transformation (module profiler's
            # jaxpr walk): a Compiled cannot be transformed — the wrapped
            # jit inlines fine, so route there via the sig-less fallback
            return None
        # sharding is None for UNCOMMITTED arrays: like the jit, the
        # Compiled places them to match the executable, so they must not
        # constrain the match (load_checkpoint rebuilds scalar state
        # leaves uncommitted — exact-sharding matching would dump those
        # steps onto the cold fallback jit and pay a fresh compile)
        return (treedef, tuple(
            (getattr(x, "shape", None), getattr(x, "dtype", None),
             getattr(x, "sharding", None)
             if getattr(x, "committed", True) else None) for x in leaves))

    def _matches(self, sig):
        if self._sig is None or sig is None:
            return False
        if sig == self._sig:
            return True
        treedef, leaves = sig
        ptreedef, pleaves = self._sig
        if treedef != ptreedef or len(leaves) != len(pleaves):
            return False
        for (shp, dt, sh), (pshp, pdt, psh) in zip(leaves, pleaves):
            if shp != pshp or dt != pdt:
                return False
            if sh is not None and psh is not None and sh != psh:
                return False
        return True

    def __call__(self, *args):
        try:
            sig = self._signature(args)
        except Exception:
            sig = None
        if self.compiled is not None and self._matches(sig):
            return self.compiled(*args)
        if sig is not None and self.compiled is None \
                and not self._prime_failed:
            try:
                compiled = self._jit.lower(*args).compile()
            except Exception as e:
                logger.warning(
                    "[cost-explorer] AOT compile of %r failed (%s); "
                    "falling back to the plain jit path — explain_step "
                    "will pay a duplicate compile", self._name, e)
                self._prime_failed = True    # never retry priming
                return self._jit(*args)
            self.compiled, self._sig = compiled, sig
            if self._on_compiled is not None:
                try:
                    self._on_compiled(self._name, compiled)
                except Exception as e:       # census must never kill a step
                    logger.warning(
                        "[cost-explorer] census hook for %r failed: %s",
                        self._name, e)
            return compiled(*args)
        self.fallback_calls += 1
        return self._jit(*args)


class DeepSpeedEngine:
    """See module docstring. Constructed via ``deepspeed_tpu.initialize``."""

    def __init__(self,
                 args=None,
                 model=None,
                 optimizer=None,
                 model_parameters=None,
                 training_data=None,
                 lr_scheduler=None,
                 mpu=None,
                 dist_init_required=None,
                 collate_fn=None,
                 config=None,
                 config_params=None,
                 loss_fn=None,
                 sample_batch=None,
                 mp_rules=None,
                 batch_spec=None,
                 dont_change_device=False,
                 sparse_embedding_rules=None,
                 sparse_ids_fn=None,
                 seed=42,
                 abstract_init=False):
        import deepspeed_tpu.comm as dist
        dist.init_distributed(verbose=False)

        self.module = model
        self.model = model
        self.loss_fn = loss_fn
        self.client_optimizer = optimizer
        self.client_lr_scheduler = lr_scheduler
        self.training_data = training_data
        self.collate_fn = collate_fn
        self.mpu = mpu
        # batch PartitionSpec override — sequence-parallel runs shard the
        # SEQ dim of the batch over a mesh axis instead of the batch dim
        # (ops/transformer/ring.py)
        self._batch_spec = batch_spec
        self.global_steps = 0
        self.global_samples = 0
        self.micro_steps = 0
        self.skipped_steps = 0
        self._seed = seed
        # abstract_init: build every step function against
        # ShapeDtypeStructs WITHOUT materialising params/optimizer state —
        # the AOT-lowering mode that proves a config's sharded program
        # builds at true scale (lower_train_step) on meshes far larger
        # than this host could hold in memory
        self._abstract_init = abstract_init

        # ---- mesh (reference: groups.initialize, engine.py:1031) ----------
        if not groups.mesh_is_initialized():
            groups.initialize(mpu=mpu)
        self.mesh = groups.get_mesh()
        self.dp_world_size = groups.get_data_parallel_world_size()
        self.mp_world_size = groups.get_model_parallel_world_size()

        # ---- config -------------------------------------------------------
        if config is None and config_params is not None:
            config = config_params
        if config is None and args is not None:
            config = getattr(args, "deepspeed_config", None)
        assert config is not None, "DeepSpeed requires --deepspeed_config or config dict"
        if isinstance(config, DeepSpeedConfig):
            assert config.world_size == self.dp_world_size, (
                f"pre-built DeepSpeedConfig was triangulated for data-parallel "
                f"world {config.world_size}, but the mesh has {self.dp_world_size}")
            self.config = config
        else:
            self.config = DeepSpeedConfig(config, mpu=None,
                                          data_parallel_size=self.dp_world_size)

        self.zero_stage = self.config.zero_optimization_stage
        self.mp_rules = mp_rules or ModelParallelRules()
        # ZeRO-Offload: optimizer state leaves HBM for host RAM / NVMe
        # (reference cpu_offload stage_1_and_2.py:1003, stage3 swapping)
        self._offload_device = self.config.zero_config.offload_optimizer.device
        self._offload = self._offload_device not in (None, "none")
        self._offload_opt = None
        # set by _configure_optimizer when a 1-bit optimizer runs with the
        # REAL compressed collective (dp > 1): step fns then keep grads
        # rank-local under shard_map (_build_onebit_step_fns)
        self._onebit_dist = False
        # broadcast batch leaves checksum-verified across processes, by
        # (path, shape, dtype) — first occurrence only (_globalize_batch)
        self._broadcast_leaves_checked = set()

        # ---- precision ----------------------------------------------------
        if self.config.fp16_enabled:
            self.compute_dtype = jnp.float16
        elif self.config.bfloat16_enabled:
            self.compute_dtype = jnp.bfloat16
        else:
            self.compute_dtype = jnp.float32
        self._dynamic_scale = (self.config.fp16_enabled
                               and self.config.fp16.dynamic_loss_scale)
        if self.config.fp16_enabled:
            init_scale = (self.config.initial_dynamic_scale
                          if self._dynamic_scale else self.config.loss_scale)
        else:
            init_scale = 1.0
        self._init_scale = float(init_scale)

        # ---- optimizer (reference _configure_basic_optimizer, :1163) ------
        self.optimizer = self._configure_optimizer()

        # ---- sparse embedding gradients (reference engine.py:2196-2268:
        # "sparse_gradients": true ships (indices, values) rows instead of
        # the dense [V, D] embedding grad over the DP group). Like the
        # reference — where only modules explicitly constructed sparse
        # (nn.Embedding(sparse=True)) produce sparse grads — the tables
        # must be DECLARED via sparse_embedding_rules: a declared table's
        # gradient must be supported on the batch's token rows only (an
        # untied lookup table indexed by sparse_ids_fn(batch)). Tied
        # LM-head tables or position/type tables have dense (or
        # differently-indexed) grads and must NOT be declared.
        self._sparse_grad_rules = tuple(sparse_embedding_rules or ())
        self._sparse_ids_fn = sparse_ids_fn or _default_sparse_ids_fn
        self._sparse_grads = (bool(self.config.sparse_gradients_enabled)
                              and self.dp_world_size > 1
                              and not self._onebit_dist)
        if self._sparse_grads and not self._sparse_grad_rules:
            logger.warning(
                "sparse_gradients is enabled but no sparse embedding "
                "tables are declared; pass sparse_embedding_rules=[...] "
                "to initialize() (regexes over param paths of untied, "
                "input-id-indexed lookup tables). Falling back to dense "
                "gradient reduction.")
            self._sparse_grads = False
        if self._sparse_grads:
            bad = []
            if self.zero_stage >= 2:
                # stage>=2 grads live reduce-scattered — the reference has
                # the same envelope (sparse handled only on the
                # buffered_allreduce_fallback path, engine.py:1648)
                bad.append(f"zero stage {self.zero_stage} (need <= 1)")
            if self.mp_world_size != 1:
                bad.append("model parallelism (embedding may be sharded)")
            if self._batch_spec is not None:
                bad.append("custom batch_spec (need the batch dim sharded "
                           "over the data axis)")
            if groups.get_expert_parallel_world_size() != 1:
                bad.append("expert parallelism (shard_map maps only the "
                           "data axis)")
            if groups.get_pipe_parallel_world_size() != 1:
                bad.append("pipeline parallelism")
            if bad:
                raise ValueError("sparse_gradients is incompatible with: "
                                 + "; ".join(bad))

        # ---- comm overlap (runtime/comm_overlap.py) -----------------------
        # bucketed gradient reduction: resolved in _build_step_fns (after
        # the sparse mask can still fall back to dense) so the variant is
        # selected BEFORE the first lower, like the health stats variant
        self._comm_overlap_cfg = self.config.comm_overlap
        self._comm_overlap_on = False
        self._overlap_spec = None
        self._warned_comm_overlap = False

        # ---- lr schedule (reference _configure_lr_scheduler, :790) --------
        self.lr_scheduler, self._lr_fn, self._base_lr = self._configure_lr_scheduler()

        # ---- aux trainers: PLD, curriculum, MoQ (reference engine.py
        # :1571-1583 forward kwarg injection; :1816-1827 MoQ step hook) ----
        self.progressive_layer_drop = None
        if self.config.pld_enabled:
            from deepspeed_tpu.runtime.progressive_layer_drop import \
                ProgressiveLayerDrop
            self.progressive_layer_drop = ProgressiveLayerDrop(
                theta=self.config.pld_config.theta,
                gamma=self.config.pld_config.gamma)
        self.curriculum_scheduler = None
        if self.config.curriculum_enabled:
            from deepspeed_tpu.runtime.data_pipeline.curriculum_scheduler \
                import CurriculumScheduler
            self.curriculum_scheduler = CurriculumScheduler(
                self.config.curriculum_config.params)
        self.quantizer = None
        ev_cfg = self.config.eigenvalue_config
        if getattr(self.config, "quantize_training_enabled", False):
            from deepspeed_tpu.runtime.quantize import Quantizer
            qc = self.config.quantize_training_config
            self.quantizer = Quantizer(
                q_groups=qc.quantize_groups,
                q_mixed_fp16=qc.fp16_mixed_quantize,
                q_change_ratio=qc.quantize_change_ratio,
                q_type=0 if qc.quantize_type == "symmetric" else 1,
                q_rounding=1 if getattr(qc, "rounding", "nearest") ==
                "stochastic" else 0,
                q_start_bits=qc.start_bits, q_target_bits=qc.target_bits,
                q_period=qc.quantize_period,
                q_eigenvalue=self.config.eigenvalue_enabled,
                layer_num=ev_cfg.layer_num if
                self.config.eigenvalue_enabled else 0)
        # eigenvalue-guided MoQ (reference engine.py:316 construction,
        # :1891 per-step block_eigenvalue feed)
        self.eigenvalue = None
        self.block_eigenvalue = {}
        if self.config.eigenvalue_enabled:
            if self.quantizer is None:
                raise ValueError(
                    "eigenvalue.enabled=true has no consumer without "
                    "quantize_training (MoQ): the curvature estimate only "
                    "guides the quantization schedule — enable "
                    "quantize_training or drop the eigenvalue block")
            if ev_cfg.layer_num < 1:
                raise ValueError(
                    "eigenvalue.layer_num must be the model's repeated-"
                    "layer count (>= 1): it sizes the per-block MoQ "
                    "schedule and bounds the block ids parsed from param "
                    "paths")
            from deepspeed_tpu.runtime.eigenvalue import Eigenvalue
            self.eigenvalue = Eigenvalue(
                verbose=ev_cfg.verbose, max_iter=ev_cfg.max_iter,
                tol=ev_cfg.tol, stability=ev_cfg.stability,
                gas_boundary_resolution=ev_cfg.gas_boundary_resolution,
                layer_name=ev_cfg.layer_name, layer_num=ev_cfg.layer_num)

        # ---- telemetry (telemetry/: spans, compile watch, metrics) --------
        # built BEFORE state init so the init work is traceable and the
        # compiled entry points can be compile-watch wrapped right after
        # _build_step_fns constructs them. Rank-0 only; every surface is a
        # no-op when the config block is absent/disabled.
        from deepspeed_tpu.telemetry import TelemetryManager
        self.telemetry = TelemetryManager(self.config.telemetry,
                                          rank=dist.get_rank())

        # ---- goodput ledger (telemetry/ledger.py) -------------------------
        # Host-side wall-clock attribution only — it never changes the
        # compiled programs and never syncs the device, so (unlike the
        # health stats variant) rank-0-only gating through the manager is
        # safe. None when disabled; every call site is None-checked.
        self._goodput = getattr(self.telemetry, "goodput", None)
        self._goodput_cadence = int(
            getattr(self.config.telemetry, "goodput_cadence", 0) or 0)

        # ---- cost explorer (telemetry/cost_explorer.py) -------------------
        # gated on the CONFIG (not the rank-0-only manager) so every rank
        # dispatches through the same _AOTStep code path; census gauges and
        # pre-flight warnings still publish on rank 0 only (the manager's
        # registry is the gate). abstract_init engines never execute, so
        # there is no artifact to own — lower_train_step covers them.
        tcfg = self.config.telemetry
        self._cost_explorer_on = (
            bool(getattr(tcfg, "enabled", False))
            and bool(getattr(tcfg, "cost_explorer_enabled", False))
            and not self._abstract_init)
        self._cost_census = None
        self._cost_census_program = None
        self._first_step_time_ms = None

        # ---- training-health observatory (telemetry/health.py) ------------
        # Like the cost explorer, gated on the CONFIG (not the rank-0-only
        # manager): the stats variant changes the compiled step program, so
        # every rank must build the same one. The host-side HealthMonitor
        # (anomaly rules, HEALTH.json) lives on rank 0 only, inside the
        # manager. abstract_init engines never execute a step.
        self._health_on = (bool(getattr(tcfg, "enabled", False))
                           and bool(getattr(tcfg, "health_enabled", False))
                           and not self._abstract_init)
        self._health_cadence = int(getattr(tcfg, "health_cadence", 0) or 0)
        self._health_spec = None

        # ---- HBM residency observatory (telemetry/memory_observatory) -----
        # Host-side only — the cadence tick fetches the runtime's own
        # allocator bookkeeping (device_memory_profile is a host RPC, not
        # a program change or a device sync), so rank-0-only gating
        # through the manager is safe, like goodput.
        self._memory = getattr(self.telemetry, "memory", None)
        self._memory_cadence = int(getattr(tcfg, "memory_cadence", 0) or 0)
        self._memory_last_obs_step = -1
        self._memory_inventory = None    # cached expected-bytes accounting
        self._memory_budget_checked = False
        self._memory_warned_fetch = False

        # ---- fleet flight recorder (telemetry/fleet.py) -------------------
        # Cross-rank by design: the SHIPPER runs on EVERY rank (per-rank
        # window records into the shared run dir are the whole point), so
        # it is gated on the CONFIG, not the rank-0-only manager. The
        # aggregating MONITOR (skew/desync sentinels, FLEET_HEALTH.json)
        # lives on fleet rank 0 only. The desync checksum program is armed
        # later, in _build_step_fns, once the param tree exists.
        self._fleet = None
        self._fleet_monitor = None
        self._fleet_cadence = 0
        self._fleet_ticks = 0
        self._desync_on = False
        self._desync_every = 1
        self._desync_fn = None
        self._desync_spec = None
        self._warned_desync = False
        if (bool(getattr(tcfg, "enabled", False))
                and bool(getattr(tcfg, "fleet_enabled", False))
                and not self._abstract_init):
            from deepspeed_tpu.telemetry import fleet as _fleet_mod
            frank = int(getattr(tcfg, "fleet_rank", -1))
            if frank < 0:
                frank = dist.get_rank()
            fleet_run_dir = getattr(tcfg, "fleet_run_dir", "") or \
                os.path.join(tcfg.output_path or "telemetry/", "fleet_run")
            self._fleet_cadence = int(getattr(tcfg, "fleet_cadence", 0)
                                      or 0)
            self._desync_every = max(
                1, int(getattr(tcfg, "fleet_desync_cadence", 0) or 1))
            self._fleet = _fleet_mod.FleetShipper(
                fleet_run_dir, rank=frank,
                job_name=tcfg.job_name or "",
                background=bool(getattr(tcfg, "fleet_background_ship",
                                        True)))
            _fleet_mod.set_shipper(self._fleet)
            if self._goodput is not None:
                # window categories come from this rank's own ledger as
                # exact integer-µs diffs; ranks without a ledger fall
                # back to the shipper's own input-wait/checkpoint timers
                self._fleet.attach_ledger(self._goodput)
            if frank == 0:
                self._fleet_monitor = _fleet_mod.FleetMonitor.from_config(
                    tcfg, run_dir=fleet_run_dir,
                    output_path=tcfg.output_path or "telemetry/",
                    job_name=tcfg.job_name or "",
                    registry=self.telemetry.registry,
                    on_escalate=(self.telemetry._force_trace_export
                                 if self.telemetry.enabled and tcfg.trace
                                 else None))
            if self.telemetry.enabled and self.telemetry.tracer.enabled:
                # rank-tagged process metadata: per-rank trace files
                # concatenate into one per-rank-lane view (fleet.py's
                # merge_traces / --merge-traces)
                self.telemetry.tracer.set_process_label(
                    f"rank {frank}", sort_index=frank)

        # ---- run chronicle (telemetry/chronicle.py) -----------------------
        # The causal event timeline every subsystem emits into. Per-rank
        # by design (one atomic JSONL stream per rank in the run dir), so
        # gated on the CONFIG like the fleet shipper, not the rank-0-only
        # manager. Armed BEFORE the guardian so its first action lands in
        # the timeline.
        self._chronicle = None
        self._chronicle_summary_path = None
        self._chronicle_incidents_path = None
        if (bool(getattr(tcfg, "enabled", False))
                and bool(getattr(tcfg, "chronicle_enabled", False))
                and not self._abstract_init):
            from deepspeed_tpu.telemetry import chronicle as _chron_mod
            _chron_out = tcfg.output_path or "telemetry/"
            chron_run_dir = getattr(tcfg, "chronicle_run_dir", "") or \
                os.path.join(_chron_out, "chronicle")
            self._chronicle_summary_path = \
                getattr(tcfg, "chronicle_summary_file", "") or \
                os.path.join(_chron_out, "CHRONICLE.json")
            self._chronicle_incidents_path = \
                getattr(tcfg, "chronicle_incidents_file", "") or \
                os.path.join(_chron_out, "INCIDENTS.json")
            self._chronicle = _chron_mod.RunChronicle(
                run_dir=chron_run_dir, rank=dist.get_rank(),
                job_name=tcfg.job_name or "",
                max_events=int(getattr(tcfg, "chronicle_max_events",
                                       16384)),
                background=bool(getattr(tcfg, "chronicle_background",
                                        True)))
            _chron_mod.set_chronicle(self._chronicle)
        self._chronicle_first_emitted = False

        # ---- self-healing guardian (runtime/guardian.py) ------------------
        # anomaly->action policy engine: the monitors above classify and
        # escalate; the guardian (when armed) subscribes to their
        # on_anomaly hooks and performs bounded actions — emergency
        # checkpoint, rollback, fp16 rescue, serving admission pause.
        # Single-process only for now: a rollback swaps the LIVE train
        # state, and coordinating that across ranks is the multi-replica
        # failover item on the roadmap (this substrate feeds it).
        self._guardian = None
        self._guardian_ckpt_dir = None      # learned from save_checkpoint
        self._guardian_data_iter = None     # learned from train_batch
        gcfg = self.config.guardian
        if bool(getattr(gcfg, "enabled", False)) and not self._abstract_init:
            if dist.get_process_count() > 1:
                logger.warning(
                    "[guardian] enabled but running multi-process; the "
                    "guardian's rollback/rescue actions are single-process "
                    "only — disarming (cross-rank healing is the fleet "
                    "failover roadmap item)")
            else:
                from deepspeed_tpu.runtime.guardian import Guardian
                self._guardian = Guardian.from_config(
                    gcfg, output_path=tcfg.output_path or "telemetry/",
                    job_name=tcfg.job_name or "",
                    registry=self.telemetry.registry)
                self._guardian.emergency_save_fn = \
                    self._guardian_emergency_save
                self._guardian.rollback_fn = self._guardian_rollback
                self._guardian.fp16_rescue_fn = self._guardian_fp16_rescue
                # subscribe to every armed monitor's action hook (the
                # serving observatory is wired by ServingEngine, which
                # shares this instance)
                if self.telemetry.health is not None:
                    self.telemetry.health.on_anomaly = \
                        self._guardian.hook("health")
                if self._goodput is not None:
                    self._goodput.on_anomaly = self._guardian.hook("goodput")
                if self._fleet_monitor is not None:
                    self._fleet_monitor.on_anomaly = \
                        self._guardian.hook("fleet")
                if self._memory is not None:
                    self._memory.on_anomaly = self._guardian.hook("memory")

        # ---- SLO burn-rate monitor (telemetry/slo.py) ---------------------
        # multi-window error-budget alerting over the ledger and the
        # registry histograms — pure host bookkeeping, gated on the
        # rank-0 telemetry manager like the monitors it reads. The
        # page-tier rule (slo_burn_page) is a guardian admission-pause
        # rule, so a sustained burn sheds serving load by itself.
        self._slo = None
        if (self.telemetry.enabled
                and bool(getattr(tcfg, "slo_enabled", False))
                and not self._abstract_init):
            from deepspeed_tpu.telemetry.slo import SloMonitor
            self._slo = SloMonitor.from_config(
                tcfg, output_path=tcfg.output_path or "telemetry/",
                job_name=tcfg.job_name or "",
                registry=self.telemetry.registry, ledger=self._goodput,
                on_escalate=(self.telemetry._force_trace_export
                             if tcfg.trace else None))
            if self._guardian is not None:
                self._slo.on_anomaly = self._guardian.hook("slo")

        # ---- live observability plane (telemetry/obs_server.py) -----------
        # The HTTP scrape/status endpoint, rank-0 with the manager.
        # Providers are MONITOR-LEVEL report() bound methods — each
        # serves its latest HOST-SIDE snapshot; never the engine's
        # *_report wrappers, which force a device tick first. A scrape
        # must never force a device fetch, sync, or compile.
        self._obs_server = None
        if (self.telemetry.enabled
                and bool(getattr(tcfg, "server_enabled", False))
                and not self._abstract_init):
            from deepspeed_tpu.telemetry import incidents as _inc_mod
            from deepspeed_tpu.telemetry import obs_server as _obs_mod
            srv = _obs_mod.ObsServer.from_config(
                tcfg, registry=self.telemetry.registry,
                # rank identity rides every /metrics sample as a const
                # label so a federation aggregator's merged view stays
                # attributable without rewriting scraped text
                identity=({"rank": str(dist.get_rank())}
                          if bool(getattr(tcfg, "federation_enabled",
                                          False)) else None))
            if self.telemetry.health is not None:
                srv.register("health", self.telemetry.health.report)
            if self._goodput is not None:
                led = self._goodput
                srv.register(
                    "goodput", led.report,
                    age_s_fn=lambda: (
                        round(led.elapsed()
                              - (led.last_window["start_s"]
                                 + led.last_window["dur_s"]), 3)
                        if led.last_window else None))
            if self._memory is not None:
                srv.register("memory", self._memory.report)
            if self._fleet_monitor is not None:
                srv.register("fleet", self._fleet_monitor.report,
                             age_s_fn=self._fleet_monitor.last_poll_age_s)
            if self._guardian is not None:
                srv.register("guardian", self._guardian.report)
            if self._chronicle is not None:
                chron = self._chronicle
                srv.register("chronicle", chron.report)
                srv.register(
                    "incidents",
                    lambda: _inc_mod.correlate(
                        chron.snapshot_events(),
                        step_window=getattr(tcfg, "chronicle_step_window",
                                            8),
                        time_window_us=int(
                            getattr(tcfg, "chronicle_time_window_s", 30.0)
                            * 1e6),
                        job_name=tcfg.job_name or ""))
            if self._slo is not None:
                srv.register("slo", self._slo.report,
                             age_s_fn=self._slo.last_eval_age_s)
            self._obs_server = srv
            _obs_mod.set_obs_server(srv)
            log_dist(f"telemetry: obs server live at {srv.url} "
                     f"({len(srv.providers())} provider(s))", ranks=[0])

        # ---- fleet federation (telemetry/federation.py) -------------------
        # Cross-process mission control. EVERY rank with a live plane
        # announces its endpoint into the run-dir peer registry; the
        # aggregator rank (policy: auto -> rank 0) additionally scrapes
        # the whole fleet and serves the merged views off its own obs
        # server (/federation/*, /api/fleet/*). Scraping is host-side
        # HTTP only — zero device work, zero extra compiles on any rank.
        self._fleet_aggregator = None
        if (self._obs_server is not None
                and bool(getattr(tcfg, "federation_enabled", False))):
            fed_run_dir = getattr(tcfg, "federation_run_dir", "") or (
                self._chronicle.run_dir if self._chronicle is not None
                else os.path.join(tcfg.output_path or "telemetry/",
                                  "chronicle"))
            self._obs_server.announce(
                fed_run_dir, rank=dist.get_rank(),
                job_name=tcfg.job_name or "")
            policy = str(getattr(tcfg, "federation_aggregator", "auto"))
            arm_agg = (policy == "always"
                       or (policy == "auto" and dist.get_rank() == 0))
            if arm_agg:
                from deepspeed_tpu.telemetry import federation as _fed_mod
                try:
                    self._fleet_aggregator = \
                        _fed_mod.FleetAggregator.from_config(
                            tcfg,
                            output_path=tcfg.output_path or "telemetry/",
                            run_dir=fed_run_dir,
                            job_name=tcfg.job_name or "")
                    self._fleet_aggregator.attach(self._obs_server)
                    log_dist(
                        "telemetry: fleet aggregator armed "
                        f"(run_dir={fed_run_dir}, "
                        f"{len(self._fleet_aggregator.peers())} peer(s) "
                        "at start)", ranks=[0])
                except Exception as e:
                    # federation is an observer of the fleet, never a
                    # reason a rank fails to come up
                    logger.warning(
                        "[federation] aggregator arming failed: %s", e)
                    self._fleet_aggregator = None

        # ---- parameters / state init --------------------------------------
        with self.telemetry.span("engine/init_state"):
            self._init_state(model_parameters, sample_batch)
        if self.telemetry.compile_watch is not None \
                and not self._abstract_init:
            # retrace reports name the engine's program, not a lambda; the
            # jitted originals stay reachable via _compile_watch_target
            # (lower_train_step unwraps for the AOT .lower surface)
            self._jit_micro = self.telemetry.wrap_compiled(
                self._jit_micro, "micro_step")
            self._jit_train = self.telemetry.wrap_compiled(
                self._jit_train, "fused_train_step")
            self._jit_apply = self.telemetry.wrap_compiled(
                self._jit_apply, "apply_step")
            self._jit_offload_pre = self.telemetry.wrap_compiled(
                self._jit_offload_pre, "offload_pre_step")
            self._jit_eval = self.telemetry.wrap_compiled(
                self._jit_eval, "eval_step")

        # ---- async input pipeline (runtime/prefetch.py) -------------------
        # deepspeed_io wraps its loaders; train_batch wraps user-supplied
        # iterators (cached by identity so the pipeline is built once).
        # close() tears every pipeline down; each also self-registers an
        # atexit close as the leak backstop.
        self._prefetch_cfg = self.config.data_prefetch
        self._prefetchers = []
        self._prefetch_wrap_cache = {}
        self._warned_io_workers = False
        self._warned_prefetch_host_only = False
        self._warned_prefetch_stateful = False

        # ---- async checkpointing (runtime/async_checkpoint.py) ------------
        # snapshot-then-persist: save_checkpoint returns after the
        # device->host snapshot; a background writer persists while
        # training continues. Writer built lazily on the first async save.
        self._ckpt_async = bool(getattr(self.config,
                                        "checkpoint_async_save", False))
        self._ckpt_writer = None

        # ---- dataloader (reference deepspeed_io, :1474) -------------------
        self.training_dataloader = None
        if training_data is not None:
            self.training_dataloader = self.deepspeed_io(training_data)

        # ---- monitor (reference tensorboard wiring, engine.py:510) --------
        from deepspeed_tpu.monitor.monitor import MonitorMaster
        import deepspeed_tpu.comm as _dist
        self.monitor = MonitorMaster(
            self.config.tensorboard, rank=_dist.get_rank(),
            telemetry_config=self.config.telemetry,
            metrics_registry=self.telemetry.registry)

        # ---- flops profiler (reference engine.py:1722 step trigger) -------
        self.flops_profiler = None
        if self.config.flops_profiler_config.enabled:
            from deepspeed_tpu.profiling.flops_profiler.profiler import \
                FlopsProfiler
            self.flops_profiler = FlopsProfiler(ds_engine=self)

        # ---- timers -------------------------------------------------------
        self.timers = SynchronizedWallClockTimer()
        self.tput_timer = ThroughputTimer(
            batch_size=self.train_micro_batch_size_per_gpu() * self.dp_world_size,
            steps_per_output=self.steps_per_print())
        self._breakdown_steps = 0  # global steps since the last breakdown log
        if self._goodput is not None:
            # the goodput report's wall_clock_breakdown section reads the
            # SAME recorded timer intervals the breakdown log prints, so
            # the two reports cannot disagree (satellite: one step loop,
            # one timing system)
            self._goodput.breakdown_fn = self._breakdown_summary
        if self.wall_clock_breakdown():
            log_dist(
                "wall_clock_breakdown: XLA fuses forward+backward into one "
                "vjp program; the 'forward' timer carries the fused fwd+bwd "
                "time ('backward' is host bookkeeping only)", ranks=[0])

        log_dist(
            f"DeepSpeedEngine ready: zero_stage={self.zero_stage} "
            f"dtype={self.compute_dtype.__name__} dp={self.dp_world_size} "
            f"mp={self.mp_world_size} gas={self.gradient_accumulation_steps()}",
            ranks=[0])
        if self.config.dump_state:  # reference engine.py:245 dump_state
            self.config.print("DeepSpeedEngine configuration")
        self._chronicle_emit(
            "init",
            detail=f"zero_stage={self.zero_stage} "
                   f"dtype={self.compute_dtype.__name__} "
                   f"dp={self.dp_world_size} mp={self.mp_world_size} "
                   f"gas={self.gradient_accumulation_steps()}")

    # ------------------------------------------------------------------ config
    def train_batch_size(self):
        return self.config.train_batch_size

    def train_micro_batch_size_per_gpu(self):
        return self.config.train_micro_batch_size_per_gpu

    def gradient_accumulation_steps(self):
        return self.config.gradient_accumulation_steps

    def steps_per_print(self):
        return self.config.steps_per_print

    def wall_clock_breakdown(self):
        """Reference API (engine.py:585). When enabled the gas=1 fused
        program is split back into micro+apply so the phases are separately
        timeable — same trade the reference makes with its cuda syncs."""
        return self.config.wall_clock_breakdown

    def zero_optimization_stage(self):
        return self.zero_stage

    def fp16_enabled(self):
        return self.config.fp16_enabled

    def bfloat16_enabled(self):
        return self.config.bfloat16_enabled

    def gradient_clipping(self):
        return self.config.gradient_clipping

    @property
    def loss_scale(self):
        return float(jax.device_get(self.state.scale.loss_scale))

    def get_lr(self):
        """Current lr — the value the NEXT applied step will use. Indexed by
        successful steps (state.step), matching the scheduler's counter."""
        applied_steps = self.global_steps - self.skipped_steps
        return [float(self._lr_fn(max(0, applied_steps)))]

    def get_global_grad_norm(self):
        """Global grad norm as a host FLOAT (the reference's contract —
        engine.py:477 returns ``self._global_grad_norm``), cached at
        ``steps_per_print`` cadence where the log line already pays the
        device sync. ``None`` until the first cadence fetch, and always
        ``None`` when the step has no reason to compute the norm
        (bf16/fp32 with clipping disabled and ``telemetry.health`` off) —
        returning the live device array here used to hand callers a
        hidden per-call host<->device sync."""
        return self._last_grad_norm

    # --------------------------------------------------------------- optimizer
    def _validate_onebit_config(self, name):
        """The compressed 1-bit data path needs rank-local grads, which is
        incompatible with features that re-layout or pre-reduce them. The
        reference has the same envelope (1-bit Adam requires the plain
        FP16_Optimizer: no ZeRO, no MP — onebit/adam.py:14 docstring)."""
        bad = []
        if self.zero_stage != 0:
            bad.append(f"zero_optimization.stage={self.zero_stage} (need 0)")
        if self.mp_world_size != 1:
            bad.append(f"model parallel size {self.mp_world_size} (need 1)")
        if groups.get_expert_parallel_world_size() != 1:
            bad.append("expert parallelism (need ep=1)")
        if groups.get_pipe_parallel_world_size() != 1:
            bad.append("pipeline parallelism (need pp=1)")
        if self._offload:
            bad.append("optimizer offload")
        if self.config.gradient_clipping > 0:
            bad.append("gradient_clipping (global norm needs an exact "
                       "grad allreduce, defeating the compression)")
        if self._batch_spec is not None:
            bad.append("custom batch_spec (sequence parallelism)")
        if bad:
            raise ValueError(
                f"{name} with the compressed collective (dp="
                f"{self.dp_world_size}) is incompatible with: "
                + "; ".join(bad))

    def _configure_optimizer(self):
        if self.client_optimizer is not None:
            assert isinstance(self.client_optimizer, optim_lib.Optimizer), (
                "client optimizer must be a deepspeed_tpu Optimizer(init, update) pair")
            return self.client_optimizer

        name = self.config.optimizer_name or ADAM_OPTIMIZER
        params = dict(self.config.optimizer_params or {})
        params.pop("lr", None)
        betas = params.pop("betas", (0.9, 0.999))
        torch_adam = params.pop("torch_adam", False)
        params.pop("max_grad_norm", None)
        # "fused": use the Pallas kernel path (ops/adam, ops/lamb) instead
        # of the XLA-fused jnp update; both are bit-compatible.
        use_fused = params.pop("fused", False)
        # "sweep": the whole-state flattened one-pass Adam (clip + update
        # [+ cast] fused over contiguous state — ops/adam fused_adam_sweep)
        use_sweep = params.pop("sweep", False)
        if use_sweep and name not in (ADAM_OPTIMIZER, ADAMW_OPTIMIZER):
            raise ValueError(
                f"optimizer.params.sweep is the whole-state fused-Adam "
                f"path; it does not apply to optimizer {name!r}")

        if name == ONEBIT_ADAM_OPTIMIZER:
            kw = dict(
                b1=betas[0], b2=betas[1], eps=params.get("eps", 1e-8),
                weight_decay=params.get("weight_decay", 0.0),
                freeze_step=params.get("freeze_step", 100),
                adam_w_mode=params.pop("adam_w_mode", True),
                bias_correction=params.get("bias_correction", True))
            if self.dp_world_size > 1:
                # the point of 1-bit Adam is changed WIRE traffic: grads
                # stay rank-local and the momenta travel through the
                # compressed collective (reference onebit/adam.py:14 +
                # comm/nccl.py:47) — see _build_onebit_step_fns
                self._validate_onebit_config(name)
                from deepspeed_tpu.runtime.fp16.onebit.adam import \
                    onebit_adam_engine
                self._onebit_dist = True
                return onebit_adam_engine(
                    groups.DATA_AXIS, self.dp_world_size, **kw)
            from deepspeed_tpu.runtime.fp16.onebit.adam import onebit_adam
            return onebit_adam(**kw)
        if name == ONEBIT_LAMB_OPTIMIZER:
            kw = dict(
                b1=betas[0], b2=betas[1], eps=params.get("eps", 1e-6),
                weight_decay=params.get("weight_decay", 0.0),
                freeze_step=params.get("freeze_step", 100),
                min_coeff=params.get("min_coeff", 0.01),
                max_coeff=params.get("max_coeff", 10.0))
            if self.dp_world_size > 1:
                self._validate_onebit_config(name)
                from deepspeed_tpu.runtime.fp16.onebit.lamb import \
                    onebit_lamb_engine
                self._onebit_dist = True
                return onebit_lamb_engine(
                    groups.DATA_AXIS, self.dp_world_size, **kw)
            from deepspeed_tpu.runtime.fp16.onebit.lamb import onebit_lamb
            return onebit_lamb(**kw)
        if name in (ADAM_OPTIMIZER, ADAMW_OPTIMIZER):
            # Reference: both "adam" and "adamw" route to FusedAdam, which
            # defaults to adam_w_mode=True (ops/adam/fused_adam.py:16).
            adam_w_mode = params.pop("adam_w_mode", True)
            del torch_adam
            kw = dict(b1=betas[0], b2=betas[1],
                      eps=params.get("eps", 1e-8),
                      weight_decay=params.get("weight_decay", 0.0),
                      adam_w_mode=adam_w_mode,
                      bias_correction=params.get("bias_correction", True))
            if use_sweep:
                from deepspeed_tpu.ops.adam.fused_adam import \
                    fused_adam_sweep
                return fused_adam_sweep(**kw)
            if use_fused:
                from deepspeed_tpu.ops.adam.fused_adam import fused_adam
                return fused_adam(**kw)
            return optim_lib.adam(**kw)
        if name == LAMB_OPTIMIZER:
            kw = dict(b1=betas[0], b2=betas[1],
                      eps=params.get("eps", 1e-6),
                      weight_decay=params.get("weight_decay", 0.0),
                      min_coeff=params.get("min_coeff", 0.01),
                      max_coeff=params.get("max_coeff", 10.0),
                      bias_correction=params.get("bias_correction", True))
            if use_fused:
                from deepspeed_tpu.ops.lamb.fused_lamb import fused_lamb
                return fused_lamb(**kw)
            return optim_lib.lamb(**kw)
        if name == SGD_OPTIMIZER:
            return optim_lib.sgd(momentum=params.get("momentum", 0.0),
                                 weight_decay=params.get("weight_decay", 0.0),
                                 nesterov=params.get("nesterov", False))
        if name == ADAGRAD_OPTIMIZER:
            return optim_lib.adagrad(eps=params.get("eps", 1e-8),
                                     weight_decay=params.get("weight_decay", 0.0))
        raise ValueError(f"Unsupported optimizer: {name}")

    def _configure_lr_scheduler(self):
        base_lr = float((self.config.optimizer_params or {}).get("lr", 1e-3))
        if self.client_lr_scheduler is not None:
            sched = self.client_lr_scheduler
            return sched, sched.as_schedule_fn(), base_lr
        if self.config.scheduler_name is not None:
            sched = get_lr_schedule(self.config.scheduler_name,
                                    self.config.scheduler_params)
            return sched, sched.as_schedule_fn(), base_lr
        return None, (lambda step: base_lr), base_lr

    # ------------------------------------------------------------------- state

    def _make_offload_optimizer(self):
        from deepspeed_tpu.runtime.zero.offload import OffloadedOptimizer
        op = dict(self.config.optimizer_params or {})
        nvme_path = None
        if self._offload_device == "nvme":
            nvme_path = (self.config.zero_config.offload_optimizer
                         .nvme_path or "/tmp")
        return OffloadedOptimizer(
            self.state.params, lr=self._base_lr,
            betas=op.get("betas", (0.9, 0.999)),
            eps=op.get("eps", 1e-8),
            weight_decay=op.get("weight_decay", 0.0),
            adam_w_mode=op.get("adam_w_mode", True),
            nvme_path=nvme_path)

    def _init_state(self, model_parameters, sample_batch):
        if self._abstract_init:
            assert sample_batch is not None, (
                "abstract_init needs sample_batch for shape inference")
            assert model_parameters is None, (
                "abstract_init derives shapes from module.init and would "
                "silently ignore model_parameters — pass one or the other")
            assert not (self._offload or self._onebit_dist
                        or self._sparse_grads), (
                "abstract_init supports the monolithic (non-offload, "
                "non-1-bit, dense-grad) engine paths")
            rng = jax.random.PRNGKey(self._seed)
            params = jax.eval_shape(self.module.init, rng, sample_batch)
            if isinstance(params, dict) and set(params.keys()) == {"params"}:
                params = params["params"]
            params = jax.tree.map(
                lambda s: jax.ShapeDtypeStruct(
                    s.shape,
                    jnp.float32 if jnp.issubdtype(s.dtype, jnp.floating)
                    else s.dtype), params)
        elif model_parameters is not None:
            params = _cast_tree(model_parameters, jnp.float32)
        else:
            assert sample_batch is not None, (
                "need model_parameters or sample_batch to initialise the model")
            rng = jax.random.PRNGKey(self._seed)
            # jit, not eager: only the param outputs are live, so jaxpr
            # DCE drops the whole traced forward — init neither executes
            # the model nor lowers its kernels (an eager fp32 init
            # forward VMEM-OOMed the flash kernel at seq 8192)
            params = jax.jit(self.module.init)(rng, sample_batch)
            if isinstance(params, dict) and set(params.keys()) == {"params"}:
                params = params["params"]
            # fp32 master copy (reference FP16_Optimizer master weights)
            params = _cast_tree(params, jnp.float32)

        min_numel = self.config.zero_config.param_persistence_threshold
        self.param_shardings = build_param_shardings(
            params, self.mesh, self.zero_stage, self.mp_rules,
            min_shard_numel=min_numel)

        # persistence threshold only gates stage-3 param sharding (the
        # ds_persist analogue); optimizer/grad shards have no fetch cost so
        # they always shard when divisible.
        if self._offload:
            # optimizer state lives host-side: nothing on the device
            opt_shape = ()
        else:
            opt_shape = jax.eval_shape(self.optimizer.init, params)
        if self._onebit_dist:
            # mu/nu are synchronized by the collective (replicated); the
            # error-feedback buffers are RANK-LOCAL, laid out flat with
            # the rank dim folded in and sharded over the data axis (see
            # onebit_adam_engine); accumulated grads are rank-local too,
            # stored with a leading [dp] dim.
            repl = NamedSharding(self.mesh, P())
            ranked = NamedSharding(self.mesh, P(groups.DATA_AXIS))
            self.opt_shardings = type(opt_shape)(
                step=repl,
                mu=jax.tree.map(lambda _: repl, opt_shape.mu),
                nu=jax.tree.map(lambda _: repl, opt_shape.nu),
                worker_error=jax.tree.map(lambda _: ranked,
                                          opt_shape.worker_error),
                server_error=jax.tree.map(lambda _: ranked,
                                          opt_shape.server_error))
            self.grad_shardings = jax.tree.map(
                lambda p: NamedSharding(
                    self.mesh, P(groups.DATA_AXIS, *([None] * p.ndim))),
                params)
            self._grad_constraint = lambda g: g
        else:
            self.opt_shardings = build_opt_shardings(
                opt_shape, self.mesh, self.zero_stage, self.mp_rules,
                min_shard_numel=0)

            # grads accumulate with the stage>=2 layout (reduce-scattered);
            # stage<2 keeps them like the params (replicated across DP).
            self.grad_shardings = build_opt_shardings(
                jax.eval_shape(lambda p: p, params), self.mesh,
                1 if self.zero_stage >= 2 else 0, self.mp_rules,
                min_shard_numel=0)
            self._grad_constraint = grad_constraint_fn(
                self.mesh, self.zero_stage, self.mp_rules, min_shard_numel=0)

        scalar_sh = NamedSharding(self.mesh, P())
        self.state_shardings = TrainState(
            step=scalar_sh,
            params=self.param_shardings,
            opt_state=self.opt_shardings,
            acc_grads=self.grad_shardings,
            scale=LossScaleState(loss_scale=scalar_sh, good_steps=scalar_sh,
                                 hysteresis=scalar_sh))

        # Build the initial state ON the mesh with one compiled init fn so
        # every leaf is born sharded (no host round-trip of full params).
        dp = self.dp_world_size

        # gradient_accumulation_dtype (reference "data_types" block):
        # fp32 default; bf16/fp16 halve the accumulator's HBM footprint at
        # the cost of accumulation precision. The 1-bit path keeps fp32 —
        # its error-feedback residuals are precision-critical.
        acc_dtype = {None: jnp.float32, "fp32": jnp.float32,
                     "bf16": jnp.bfloat16, "fp16": jnp.float16}[
                         self.config.gradient_accumulation_dtype]

        def make_acc(x):
            if self._onebit_dist:   # rank-local accumulation: [dp, ...]
                return jnp.zeros((dp,) + x.shape, jnp.float32)
            return jnp.zeros_like(x, acc_dtype)

        def make_state(p):
            return TrainState(
                step=jnp.zeros([], jnp.int32),
                params=p,
                opt_state=() if self._offload else self.optimizer.init(p),
                acc_grads=jax.tree.map(make_acc, p),
                scale=make_scale_state(
                    self._init_scale,
                    delayed_shift=self.config.fp16.hysteresis))

        if self._abstract_init:
            # no materialisation: the state is a ShapeDtypeStruct tree the
            # step fns lower against (lower_train_step)
            self.state = jax.eval_shape(make_state, params)
        else:
            with self.mesh:
                params = jax.device_put(params, self.param_shardings)
                self.state = jax.jit(
                    make_state, out_shardings=self.state_shardings)(params)

        if self._offload:
            self._offload_opt = self._make_offload_optimizer()

        if self._sparse_grads:
            self._sparse_mask = self._build_sparse_mask(params)
            if not any(self._sparse_mask):
                logger.warning(
                    "sparse_gradients enabled but no parameter matched "
                    f"{self._sparse_grad_rules}; falling back to dense")
                self._sparse_grads = False

        self._build_step_fns()
        self._pending_loss = None
        self._last_grad_norm = None      # host FLOAT, cached at print cadence
        self._pending_grad_norm = None   # device scalar of the last step
        self._last_batch = None
        self._pending_health_stats = None  # device stats pytree (no sync)
        self._health_last_loss = None      # device scalar loss (no sync)
        self._health_last_obs_step = -1

    def _abstract_step_args(self, batch):
        """(batch_sharded, rng, theta) ShapeDtypeStructs for AOT-lowering
        a step program at this engine's shapes — ``batch`` may be arrays
        or ShapeDtypeStructs; only avals are read."""
        import numpy as _np
        batch_sds = jax.tree.map(
            lambda x: x if isinstance(x, jax.ShapeDtypeStruct)
            else jax.ShapeDtypeStruct(_np.shape(x), _np.asarray(x).dtype),
            batch)
        rng_sds = jax.eval_shape(lambda: jax.random.PRNGKey(0))
        theta_sds = jax.ShapeDtypeStruct((), jnp.float32)
        batch_sharded = jax.tree.map(
            lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype,
                                               sharding=sh),
            batch_sds, self._batch_sharding(batch_sds))
        return batch_sharded, rng_sds, theta_sds

    def lower_train_step(self, batch):
        """AOT-lower the fused global train step (gas=1) at the engine's
        shapes WITHOUT executing anything — the at-scale proof for
        configs (e.g. GPT-2 1.5B ZeRO-3 over 16 chips) that no single
        host could materialise. ``batch`` may be arrays or
        ShapeDtypeStructs. Returns the ``jax.stages.Lowered``; call
        ``.compile().memory_analysis()`` for the per-chip footprint."""
        assert self._abstract_init, (
            "lower_train_step is the abstract_init=True surface; a "
            "materialised engine can just run train_batch")
        assert self._jit_train is not None, (
            "lower_train_step needs the fused gas=1 step (gradient "
            "accumulation > 1 lowers per-microbatch programs instead)")
        with self.mesh:
            batch_sharded, rng_sds, theta_sds = \
                self._abstract_step_args(batch)
            # the compile-watch wrapper (if any) hides the AOT surface
            jit_train = getattr(self._jit_train, "_compile_watch_target",
                                self._jit_train)
            return jit_train.lower(self.state, batch_sharded,
                                   rng_sds, theta_sds)

    def lower_step_programs(self, batch):
        """AOT-lower every program one global step dispatches, WITHOUT
        executing anything: ``{"fused_train_step": Lowered}`` for the
        gas=1 fused config, ``{"micro_step": ..., "apply_step": ...}``
        for gradient accumulation (or wall_clock_breakdown) configs.
        ``batch`` is ONE dispatch's batch (micro_batch x dp samples —
        the same shape ``train_batch`` pulls from its iterator); arrays
        or ShapeDtypeStructs.

        This is the autotuner's stage-1 surface: compile each Lowered
        once, census/prune/rank the candidate, then hand the artifacts
        to a materialised twin engine via ``adopt_compiled_step`` so the
        measured probe compiles nothing."""
        assert self._abstract_init, (
            "lower_step_programs is the abstract_init=True surface; a "
            "materialised engine owns its programs via the cost explorer")
        with self.mesh:
            batch_sharded, rng_sds, theta_sds = \
                self._abstract_step_args(batch)
            out = {}
            if self._jit_train is not None:
                jit_train = getattr(self._jit_train,
                                    "_compile_watch_target",
                                    self._jit_train)
                out["fused_train_step"] = jit_train.lower(
                    self.state, batch_sharded, rng_sds, theta_sds)
            else:
                jit_micro = getattr(self._jit_micro,
                                    "_compile_watch_target",
                                    self._jit_micro)
                out["micro_step"] = jit_micro.lower(
                    self.state, batch_sharded, rng_sds, theta_sds)
                if self._jit_apply is not None and not self._offload:
                    jit_apply = getattr(self._jit_apply,
                                        "_compile_watch_target",
                                        self._jit_apply)
                    out["apply_step"] = jit_apply.lower(self.state)
            return out

    def _build_sparse_mask(self, params):
        """Flat boolean mask over the param leaves: True = embedding table
        whose grad travels the sparse path (name matches
        sparse_embedding_rules and it is a >=2-D table)."""
        import re
        from deepspeed_tpu.runtime.zero.partition import _path_str
        pats = [re.compile(p) for p in self._sparse_grad_rules]
        flat, _ = jax.tree_util.tree_flatten_with_path(params)
        return [leaf.ndim >= 2 and
                any(p.search(_path_str(path)) for p in pats)
                for path, leaf in flat]

    # -------------------------------------------------------- compiled steps
    def _batch_sharding(self, batch):
        if self._batch_spec is not None:
            return jax.tree.map(
                lambda _: NamedSharding(self.mesh, self._batch_spec), batch)
        dp_axes = tuple(a for a in groups.data_parallel_axes()
                        if self.mesh.shape[a] > 1)
        spec = P(dp_axes) if dp_axes else P()
        return jax.tree.map(
            lambda _: NamedSharding(self.mesh, spec), batch)

    def _compute_loss(self, params, batch, rng, pld_theta=None):
        """Forward in compute dtype; returns scalar fp32 loss."""
        cparams = _cast_tree(params, self.compute_dtype)
        model_kwargs = {}
        if rng is not None:
            # "gating" feeds MoE RTS/noisy gating (moe/sharded_moe.py
            # TopKGate); unused rng names are ignored by flax
            model_kwargs["rngs"] = {"dropout": rng,
                                    "gating": jax.random.fold_in(rng, 7)}
        if self.progressive_layer_drop is not None and pld_theta is not None:
            # reference engine.forward kwarg injection (engine.py:1571)
            model_kwargs["progressive_layer_drop"] = True
            model_kwargs["pld_theta"] = pld_theta
        if hasattr(self.module, "apply"):
            out = self.module.apply(
                {"params": cparams} if not (isinstance(cparams, dict)
                                            and "params" in cparams) else cparams,
                batch, **model_kwargs)
        else:
            out = self.module(cparams, batch)
        loss = self.loss_fn(out, batch) if self.loss_fn is not None else out
        return jnp.asarray(loss, jnp.float32)

    def _make_sparse_vg(self):
        """(params, batch, rng, theta, scale) -> (scaled_loss, grads) with
        EXPLICIT DP reduction under shard_map: dense grads pmean over the
        data axis, embedding-table grads as an all-gather of the batch's
        (token-id, row) pairs + scatter-add — the reference
        ``sparse_allreduce_bucket`` dataflow (engine.py:2196-2268). Wire
        cost per table: dp*k*(D+1) elements instead of dp*V*D."""
        import functools

        from deepspeed_tpu.runtime.sparse_tensor import sparse_all_reduce
        from deepspeed_tpu.utils.jax_compat import get_shard_map
        shard_map, smap_kw = get_shard_map()
        axis = groups.DATA_AXIS
        mask = self._sparse_mask
        ids_fn = self._sparse_ids_fn

        def body(params, batch, rng, theta, scale):
            rrng = jax.random.fold_in(rng, jax.lax.axis_index(axis))

            def scaled_loss(p):
                loss = self._compute_loss(p, batch, rrng, theta)
                return loss * scale

            sloss, grads = jax.value_and_grad(scaled_loss)(params)
            ids = jnp.asarray(ids_fn(batch), jnp.int32).reshape(-1)
            # dedup once (table-independent) so the row gather +
            # scatter-add doesn't double count repeated tokens; padding
            # slots get an out-of-range index (dropped by the scatter)
            # and zeroed values
            pad = jnp.iinfo(jnp.int32).max
            uniq = jnp.unique(ids, size=ids.size, fill_value=pad)
            flat, tdef = jax.tree_util.tree_flatten(grads)
            out = []
            for g, is_emb in zip(flat, mask):
                if is_emb:
                    vocab = g.shape[0]
                    uids = jnp.where(uniq == pad, vocab, uniq)
                    valid = uids < vocab
                    vals = jnp.take(g, jnp.where(valid, uids, 0), axis=0)
                    vals = vals * valid.reshape(
                        (-1,) + (1,) * (g.ndim - 1)).astype(g.dtype)
                    out.append(sparse_all_reduce(uids, vals, g.shape, axis,
                                                 op="mean"))
                else:
                    out.append(jax.lax.pmean(g, axis))
            return (jax.lax.pmean(sloss, axis),
                    jax.tree_util.tree_unflatten(tdef, out))

        smap = functools.partial(shard_map, mesh=self.mesh)
        return smap(body, in_specs=(P(), P(axis), P(), P(), P()),
                    out_specs=(P(), P()), **smap_kw)

    def _resolve_comm_overlap(self):
        """Arm the bucketed-reduction variant when the config asks for it
        AND the engine is inside the supported envelope. Outside it the
        engine falls back to the plain GSPMD reduction with ONE warning —
        comm_overlap is a perf knob, not a semantic switch, so a config
        that composes it with an unsupported feature should still train."""
        cfg = self._comm_overlap_cfg
        if not getattr(cfg, "enabled", False):
            return False
        # (_onebit_dist never reaches here: _build_step_fns routes that
        # case to _build_onebit_step_fns with its own warning first)
        bad = []
        if self.dp_world_size < 2:
            bad.append("data-parallel world size 1 (nothing to reduce)")
        if self._sparse_grads:
            bad.append("sparse_gradients (its shard_map owns the "
                       "reduction)")
        if self.zero_stage >= 2:
            bad.append(f"zero stage {self.zero_stage} (grads live "
                       "reduce-scattered; re-replicating them through a "
                       "bucketed psum would undo the partitioning)")
        if self.mp_world_size != 1:
            bad.append("model parallelism (params sharded over the "
                       "model axis; shard_map here maps the data axis "
                       "with replicated params)")
        if groups.get_expert_parallel_world_size() != 1:
            bad.append("expert parallelism")
        if groups.get_pipe_parallel_world_size() != 1:
            bad.append("pipeline parallelism")
        if self._batch_spec is not None:
            bad.append("custom batch_spec (the batch dim must shard "
                       "over the data axis)")
        if bad:
            if not self._warned_comm_overlap:
                self._warned_comm_overlap = True
                logger.warning(
                    "comm_overlap is enabled but falls back to the plain "
                    "GSPMD gradient reduction — incompatible with: "
                    + "; ".join(bad))
            return False
        if getattr(cfg, "scheduler_flags", True):
            from deepspeed_tpu.runtime.comm_overlap import \
                log_scheduler_flags_hint
            log_scheduler_flags_hint(jax.default_backend())
        return True

    def _make_overlap_vg(self):
        """(params, batch, rng, theta, scale) -> (scaled_loss, grads) with
        EXPLICIT bucketed DP reduction under shard_map: each rank computes
        grads from its own batch shard and every size-targeted bucket is
        mean-reduced by ONE psum, issued as soon as the backward has
        produced that bucket's grads (reverse-layer bucket order —
        runtime/comm_overlap.py). Arithmetically identical to the GSPMD
        per-leaf pmean; structurally B collectives instead of one per
        leaf, which is what the latency-hiding scheduler can overlap."""
        import functools

        from deepspeed_tpu.runtime.comm_overlap import bucketed_pmean
        from deepspeed_tpu.utils.jax_compat import get_shard_map
        shard_map, smap_kw = get_shard_map()
        axis = groups.DATA_AXIS
        spec = self._overlap_spec

        def body(params, batch, rng, theta, scale):
            rrng = jax.random.fold_in(rng, jax.lax.axis_index(axis))

            def scaled_loss(p):
                loss = self._compute_loss(p, batch, rrng, theta)
                return loss * scale

            sloss, grads = jax.value_and_grad(scaled_loss)(params)
            grads = bucketed_pmean(spec, grads, axis)
            return jax.lax.pmean(sloss, axis), grads

        smap = functools.partial(shard_map, mesh=self.mesh)
        return smap(body, in_specs=(P(), P(axis), P(), P(), P()),
                    out_specs=(P(), P()), **smap_kw)

    def _build_step_fns(self):
        if self._onebit_dist:
            if getattr(self._comm_overlap_cfg, "enabled", False) \
                    and not self._warned_comm_overlap:
                self._warned_comm_overlap = True
                logger.warning(
                    "comm_overlap has no effect with the compressed 1-bit "
                    "optimizers (grads are rank-local by design); "
                    "disabled for this engine")
            self._build_onebit_step_fns()
            return
        gas = self.gradient_accumulation_steps()
        cfg = self.config

        # health stats variant: selected HERE, before the first lower, so
        # the _AOTStep artifact and the compile watch always see one fixed
        # step signature (never mutated mid-run). The offloaded optimizer
        # applies its update host-side, so the on-device epilogue cannot
        # see the update norm — degrade gracefully (log once, no stats).
        if self._health_on and self._offload:
            logger.warning(
                "[health] in-step stats are not supported with the "
                "offloaded optimizer step (the update runs host-side); "
                "disabling telemetry.health stats for this engine")
            self._health_on = False
        health = self._health_on
        if health:
            from deepspeed_tpu.telemetry.health import (build_bucket_spec,
                                                        bucket_grad_stats)
            self._health_spec = build_bucket_spec(
                self.state.params,
                depth=int(getattr(cfg.telemetry, "health_bucket_depth", 8)))
            self._wire_health_monitor()
            hspec = self._health_spec

        self._comm_overlap_on = self._resolve_comm_overlap()
        if self._comm_overlap_on:
            from deepspeed_tpu.runtime.comm_overlap import \
                build_grad_bucket_spec
            self._overlap_spec = build_grad_bucket_spec(
                self.state.params, self._comm_overlap_cfg.bucket_bytes)
            log_dist(
                f"comm_overlap: {self._overlap_spec.n_leaves} grad "
                f"leaves -> {self._overlap_spec.n_buckets} reduction "
                f"buckets (target "
                f"{self._comm_overlap_cfg.bucket_mb:g} MiB)", ranks=[0])
            if self.telemetry.enabled:
                self.telemetry.registry.gauge(
                    "comm_overlap_buckets",
                    "gradient reduction buckets per step").set(
                        self._overlap_spec.n_buckets)

        if self._fleet is not None and \
                getattr(cfg.telemetry, "fleet_desync", True):
            self._desync_on = self._resolve_desync()
            if self._desync_on:
                from deepspeed_tpu.telemetry.fleet import (
                    build_desync_checksum_fn, build_desync_spec)
                self._desync_spec = build_desync_spec(
                    self.state.params,
                    depth=int(getattr(cfg.telemetry, "health_bucket_depth",
                                      8)))
                self._desync_fn = build_desync_checksum_fn(
                    self.mesh, self._desync_spec, groups.DATA_AXIS)

        if self._sparse_grads:
            value_and_grad = self._make_sparse_vg()
        elif self._comm_overlap_on:
            value_and_grad = self._make_overlap_vg()
        else:
            def value_and_grad(params, batch, rng, theta, scale):
                def scaled_loss(p):
                    loss = self._compute_loss(p, batch, rng, theta)
                    return loss * scale
                return jax.value_and_grad(scaled_loss)(params)

        def micro_step(state, batch, rng, pld_theta):
            sloss, grads = value_and_grad(
                state.params, batch, rng, pld_theta,
                state.scale.loss_scale / gas)
            grads = self._grad_constraint(grads)
            # cast INTO the accumulator dtype (gradient_accumulation_dtype);
            # bare jnp.add would promote and silently widen the buffer
            acc = jax.tree.map(lambda a, g: a + g.astype(a.dtype),
                               state.acc_grads, grads)
            loss = sloss * gas / state.scale.loss_scale
            return state._replace(acc_grads=acc), loss

        # grad_norm is only needed on-device for clipping and for the fp16
        # overflow bookkeeping; in the bf16/fp32 no-clip case computing it
        # costs a full extra read of the grad tree per step, so it is
        # skipped and get_global_grad_norm() returns None. The health
        # observatory needs it as a stat, so health forces it on.
        need_norm = bool(cfg.fp16_enabled or cfg.gradient_clipping > 0
                         or health)
        self._need_norm = need_norm
        # whole-state sweep optimizer: the global-norm clip rides INSIDE
        # its one fused pass (update(clip_coef=...)), so the epilogue must
        # not also scale the grad tree — a separate full read+write of it.
        # The offloaded step applies its update host-side and never sees
        # clip_coef, so there the epilogue clip stays.
        fuse_clip = (bool(getattr(self.optimizer, "fuses_clip", False))
                     and not self._offload)

        def grad_epilogue(state, grads):
            """Shared end-of-accumulation math on an UNSCALED-pending grad
            tree: unscale, overflow check, norm + clip, scale-state update.
            Returns (state-with-new-scale, grads, grad_norm, finite,
            clip_coef, aux); ``aux`` holds the health bucket stats (empty
            dict when off) — computed on the unscaled PRE-clip grads, so a
            clip cannot mask an explosion and the provenance bitmask sees
            the raw values. ``clip_coef`` is the torch-semantics global
            clip coefficient (1.0 when clipping is off); a clip-fusing
            sweep optimizer consumes it instead of the tree-map below."""
            inv_scale = 1.0 / state.scale.loss_scale
            grads = jax.tree.map(lambda g: g * inv_scale, grads)
            finite = jnp.array(True)
            if cfg.fp16_enabled:
                finite = jnp.all(jnp.stack(
                    [jnp.isfinite(g).all() for g in jax.tree.leaves(grads)]))
            grad_norm = (optim_lib.global_norm(grads) if need_norm
                         else jnp.float32(0.0))
            aux = {}
            if health:
                norms, mask = bucket_grad_stats(hspec, grads)
                aux = {"bucket_norms": norms, "nonfinite_mask": mask}
            clip_coef = jnp.float32(1.0)
            if cfg.gradient_clipping > 0:
                # same coefficient clip_by_global_norm computes (the norm
                # is the grad_norm above — XLA CSEs the reduction)
                clip_coef = jnp.minimum(
                    cfg.gradient_clipping / (grad_norm + 1e-6),
                    jnp.float32(1.0))
                if not fuse_clip:
                    grads = jax.tree.map(lambda g: g * clip_coef, grads)
            new_scale = update_scale(
                state.scale, ~finite,
                dynamic=self._dynamic_scale,
                scale_window=cfg.fp16.loss_scale_window,
                min_scale=cfg.fp16.min_loss_scale,
                delayed_shift=cfg.fp16.hysteresis)
            return (state._replace(scale=new_scale), grads, grad_norm,
                    finite, clip_coef, aux)

        def grad_prologue(state):
            """grad_epilogue over the accumulation buffer, which it resets."""
            acc = jax.tree.map(lambda a: a.astype(jnp.float32),
                               state.acc_grads)
            zeros = jax.tree.map(jnp.zeros_like, state.acc_grads)
            return grad_epilogue(state._replace(acc_grads=zeros), acc)

        def optimizer_update(state, grads, finite, clip_coef):
            """Returns (state, update_norm); the norm is a constant 0 when
            health is off (dead output, DCE'd by XLA). ``clip_coef`` only
            reaches a clip-fusing sweep optimizer — everyone else already
            received clipped grads from the epilogue."""
            lr = self._lr_fn_traced(state.step)

            def do_update(operand):
                st, g, cc = operand
                if fuse_clip:
                    updates, new_opt = self.optimizer.update(
                        g, st.opt_state, st.params, lr, clip_coef=cc)
                else:
                    updates, new_opt = self.optimizer.update(
                        g, st.opt_state, st.params, lr)
                new_params = jax.tree.map(jnp.add, st.params, updates)
                un = (optim_lib.global_norm(updates) if health
                      else jnp.float32(0.0))
                return st._replace(step=st.step + 1, params=new_params,
                                   opt_state=new_opt), un

            def skip_update(operand):
                st, _, _ = operand
                return st, jnp.float32(0.0)

            return jax.lax.cond(finite, do_update, skip_update,
                                (state, grads, clip_coef))

        def pack_stats(state, grad_norm, finite, upd_norm, aux):
            """The static-shaped in-step stats pytree (health only). The
            update ratio uses the APPLIED update (optimizer output, lr
            already inside) against the post-update params; a skipped step
            reports 0. loss_scale/good_steps/hysteresis come from the
            POST-update scale state, so the host sees the machine as the
            NEXT step will."""
            pnorm = optim_lib.global_norm(state.params)
            return {
                "grad_norm": grad_norm,
                "param_norm": pnorm,
                "update_ratio": jnp.where(pnorm > 0, upd_norm / pnorm,
                                          jnp.float32(0.0)),
                "bucket_grad_norms": aux["bucket_norms"],
                "nonfinite_buckets": aux["nonfinite_mask"],
                "overflow": ~finite,
                **scale_state_stats(state.scale),
            }

        def apply_step(state):
            (state, grads, grad_norm, finite, clip_coef,
             aux) = grad_prologue(state)
            state, upd_norm = optimizer_update(state, grads, finite,
                                               clip_coef)
            if health:
                return (state, grad_norm, ~finite,
                        pack_stats(state, grad_norm, finite, upd_norm, aux))
            return state, grad_norm, ~finite

        def fused_train_step(state, batch, rng, pld_theta):
            """gas=1 fast path: forward+backward+optimizer in ONE compiled
            program. Skipping the acc_grads round-trip (write grads, read
            them back, write zeros) saves ~3x the grad-tree bytes of HBM
            traffic per step; acc_grads passes through untouched (it is
            all-zeros between steps by invariant, and the donated buffer
            aliases through at zero cost)."""
            sloss, grads = value_and_grad(
                state.params, batch, rng, pld_theta,
                state.scale.loss_scale)
            grads = self._grad_constraint(grads)
            loss = sloss / state.scale.loss_scale
            (state, grads, grad_norm, finite, clip_coef,
             aux) = grad_epilogue(state, grads)
            state, upd_norm = optimizer_update(state, grads, finite,
                                               clip_coef)
            if health:
                return (state, loss, grad_norm, ~finite,
                        pack_stats(state, grad_norm, finite, upd_norm, aux))
            return state, loss, grad_norm, ~finite

        def offload_pre_step(state):
            """Device half of the offloaded step: the shared prologue —
            grads go to the host CPU-Adam; params unchanged. fuse_clip is
            forced off under offload, so the grads here are clipped."""
            state, grads, grad_norm, finite, _, _ = grad_prologue(state)
            return state, grads, grad_norm, ~finite

        sh = self.state_shardings
        scalar = NamedSharding(self.mesh, P())
        # the stats pytree is all replicated scalars (+ one [B] bucket
        # vector); keys must match pack_stats exactly
        stats_sh = {k: scalar for k in (
            "grad_norm", "param_norm", "update_ratio", "bucket_grad_norms",
            "nonfinite_buckets", "overflow", "loss_scale", "good_steps",
            "hysteresis")}
        self._jit_micro = jax.jit(
            micro_step, donate_argnums=0,
            in_shardings=(sh, None, None, None),
            out_shardings=(sh, scalar))
        # gas=1 (the common large-model config): one fused program per
        # global step instead of micro+apply with an HBM acc round-trip
        self._jit_train = None
        if gas == 1 and not self._offload and not cfg.wall_clock_breakdown:
            self._jit_train = jax.jit(
                fused_train_step, donate_argnums=0,
                in_shardings=(sh, None, None, None),
                out_shardings=((sh, scalar, scalar, scalar, stats_sh)
                               if health else
                               (sh, scalar, scalar, scalar)))
        self._jit_offload_pre = jax.jit(
            offload_pre_step, donate_argnums=0,
            in_shardings=(sh,),
            out_shardings=(sh, self.grad_shardings, scalar, scalar))
        self._jit_apply = jax.jit(
            apply_step, donate_argnums=0,
            in_shardings=(sh,),
            out_shardings=((sh, scalar, scalar, stats_sh) if health else
                           (sh, scalar, scalar)))
        self._jit_eval = jax.jit(
            lambda params, batch: self._compute_loss(params, batch, None))
        self._install_aot_steps()

    def _install_aot_steps(self):
        """Cost-explorer mode: own the step programs' compiled artifacts
        (see _AOTStep). The TRAIN entry points only — eval/offload
        auxiliaries are not the program being explained. apply_step rides
        along (gas>1 dispatches it once per global step) so the autotuner
        can hand a gas>1 trial BOTH of its stage-1 artifacts and the probe
        compiles nothing; its census never overwrites the step census
        (_on_step_compiled filters by name)."""
        if not self._cost_explorer_on:
            return
        if self._jit_train is not None:
            self._jit_train = _AOTStep(self._jit_train, "fused_train_step",
                                       self._on_step_compiled)
        self._jit_micro = _AOTStep(self._jit_micro, "micro_step",
                                   self._on_step_compiled)
        if self._jit_apply is not None:
            self._jit_apply = _AOTStep(self._jit_apply, "apply_step",
                                       self._on_step_compiled)

    def _build_onebit_step_fns(self):
        """Step fns for the compressed 1-bit optimizers (reference
        onebit/adam.py:14 + comm/nccl.py:47 compressed_allreduce).

        The normal path lets XLA psum the grads over the data axis — exact
        fp32 reduction, which makes post-freeze "compression" a no-op on
        the wire. Here the whole micro/apply pair runs under ``shard_map``
        over the data axis: each rank computes grads from its OWN batch
        shard, accumulates them rank-locally ([dp, ...] acc layout), and
        the only cross-rank traffic is the optimizer's own collectives —
        an exact pmean during warmup, the sign-packed uint8 wire format
        (comm/compressed.py) after ``freeze_step``.
        """
        gas = self.gradient_accumulation_steps()
        cfg = self.config
        axis = groups.DATA_AXIS
        import functools

        if self._health_on:
            logger.warning(
                "[health] in-step stats are not supported with the "
                "compressed 1-bit optimizers (rank-local shard_map grads, "
                "no global epilogue); disabling telemetry.health stats "
                "for this engine")
            self._health_on = False

        from deepspeed_tpu.utils.jax_compat import get_shard_map
        shard_map, smap_kw = get_shard_map()
        smap = functools.partial(shard_map, mesh=self.mesh)

        opt_spec = type(self.state.opt_state)(
            step=P(), mu=P(), nu=P(),
            worker_error=P(axis), server_error=P(axis))

        def micro_step(state, batch, rng, pld_theta):
            def body(params, acc, scale, batch, rng, theta):
                rrng = jax.random.fold_in(rng, jax.lax.axis_index(axis))

                def scaled_loss(p):
                    loss = self._compute_loss(p, batch, rrng, theta)
                    return loss * scale / gas

                sloss, g = jax.value_and_grad(scaled_loss)(params)
                acc = jax.tree.map(lambda a, gg: a + gg[None], acc, g)
                loss = jax.lax.pmean(sloss, axis) * gas / scale
                return acc, loss

            acc, loss = smap(
                body,
                in_specs=(P(), P(axis), P(), P(axis), P(), P()),
                out_specs=(P(axis), P()), **smap_kw)(
                    state.params, state.acc_grads, state.scale.loss_scale,
                    batch, rng, pld_theta)
            return state._replace(acc_grads=acc), loss

        def apply_step(state):
            lr = self._lr_fn_traced(state.step)

            def body(params, opt_state, acc, inv_scale, lr):
                grads = jax.tree.map(lambda a: a[0] * inv_scale, acc)

                def do(op):
                    p, o = op
                    updates, new_o = self.optimizer.update(grads, o, p, lr)
                    return jax.tree.map(jnp.add, p, updates), new_o

                if cfg.fp16_enabled:
                    bad = sum(
                        (~jnp.isfinite(g).all()).astype(jnp.int32)
                        for g in jax.tree.leaves(grads))
                    finite = jax.lax.psum(bad, axis) == 0
                    new_params, new_opt = jax.lax.cond(
                        finite, do, lambda op: op, (params, opt_state))
                else:
                    finite = jnp.bool_(True)
                    new_params, new_opt = do((params, opt_state))
                zeros = jax.tree.map(jnp.zeros_like, acc)
                return new_params, new_opt, zeros, finite

            new_params, new_opt, zeros, finite = smap(
                body,
                in_specs=(P(), opt_spec, P(axis), P(), P()),
                out_specs=(P(), opt_spec, P(axis), P()),
                **smap_kw)(
                    state.params, state.opt_state, state.acc_grads,
                    1.0 / state.scale.loss_scale, lr)
            new_scale = update_scale(
                state.scale, ~finite,
                dynamic=self._dynamic_scale,
                scale_window=cfg.fp16.loss_scale_window,
                min_scale=cfg.fp16.min_loss_scale,
                delayed_shift=cfg.fp16.hysteresis)
            state = state._replace(
                params=new_params, opt_state=new_opt, acc_grads=zeros,
                scale=new_scale, step=state.step + finite.astype(jnp.int32))
            # grad clipping is excluded by _validate_onebit_config, so no
            # global norm is computed (get_global_grad_norm -> None)
            return state, jnp.float32(0.0), ~finite

        sh = self.state_shardings
        scalar = NamedSharding(self.mesh, P())
        self._jit_micro = jax.jit(
            micro_step, donate_argnums=0,
            in_shardings=(sh, None, None, None),
            out_shardings=(sh, scalar))
        self._jit_apply = jax.jit(
            apply_step, donate_argnums=0,
            in_shardings=(sh,),
            out_shardings=(sh, scalar, scalar))
        self._jit_train = None          # gas loop path drives train_batch
        self._jit_offload_pre = None    # offload excluded by validation
        self._need_norm = False
        self._jit_eval = jax.jit(
            lambda params, batch: self._compute_loss(params, batch, None))
        self._install_aot_steps()

    # ------------------------------------------------------- cost explorer
    def _get_cost_explorer(self):
        """One CostExplorer per engine: chip detection / memory_stats run
        once, and its warn-once pre-flight state persists across calls."""
        if getattr(self, "_cost_explorer_obj", None) is None:
            from deepspeed_tpu.telemetry.cost_explorer import CostExplorer
            self._cost_explorer_obj = CostExplorer.from_config(
                self.config.telemetry, registry=self.telemetry.registry)
        return self._cost_explorer_obj

    def _on_step_compiled(self, name, compiled):
        """First-dispatch hook from _AOTStep: census the artifact and run
        the HBM watermark pre-flight BEFORE the program first executes."""
        from deepspeed_tpu.telemetry.hlo_census import census_compiled
        if name not in ("fused_train_step", "micro_step"):
            # apply_step (and any future auxiliary) is owned for artifact
            # reuse only — the per-step census/pre-flight describe the
            # TRAIN program, which an auxiliary must never overwrite
            return
        # the fused step supersedes the micro census (it is the whole
        # program); a micro census never overwrites a fused one
        if self._cost_census is not None and \
                self._cost_census_program == "fused_train_step":
            return
        self._cost_census = census_compiled(compiled, mesh=self.mesh)
        self._cost_census_program = name
        if not self.telemetry.enabled:
            return
        explorer = self._get_cost_explorer()
        if getattr(self.config.telemetry, "cost_explorer_preflight", True):
            explorer.preflight(self._cost_census, name=name)
        explorer.publish(self._cost_census)

    def _aot_step_for(self, name):
        """The ``_AOTStep`` dispatcher behind a step entry point (unwraps
        the compile-watch layer), or None when the cost explorer is off /
        the program does not exist in this configuration."""
        attr = {"fused_train_step": "_jit_train",
                "micro_step": "_jit_micro",
                "apply_step": "_jit_apply"}.get(name)
        if attr is None:
            return None
        fn = getattr(self, attr, None)
        if fn is None:
            return None
        target = getattr(fn, "_compile_watch_target", fn)
        return target if isinstance(target, _AOTStep) else None

    def adopt_compiled_step(self, compiled_map, batch):
        """Prime this engine's owned-AOT dispatchers with EXTERNALLY
        compiled artifacts (``{program_name: jax.stages.Compiled}`` from
        an abstract twin's ``lower_step_programs().compile()``), so the
        first train step executes them instead of paying a fresh XLA
        compile — the autotuner's stage-1 -> stage-2 handoff, and the
        reason a whole tune run compiles each candidate exactly once.

        ``batch`` is one dispatch's batch (shapes only — used to build
        the signature the dispatcher matches against). Per-program the
        handoff mirrors the census-before-first-step path in
        ``get_cost_census``: signature FIRST, then artifact, then the
        census/pre-flight hook. Returns the set of adopted program
        names; a name is skipped (never an error) when the cost explorer
        is off, the program is already primed, or the signature cannot
        be computed — the dispatcher then falls back to the plain jit,
        which is correct, just not compile-free."""
        adopted = set()
        if not self._cost_explorer_on:
            logger.warning(
                "adopt_compiled_step: telemetry.cost_explorer is off — "
                "no _AOTStep dispatchers to prime; the first step will "
                "compile")
            return adopted
        # signature from ShapeDtypeStructs — _AOTStep._signature only
        # reads shape/dtype/sharding, so nothing is placed on device
        # just to compute a match key (SDS leaves have no `committed`
        # attribute -> sharding unconstrained, same as the uncommitted
        # rng/theta scalars at real dispatch; the batch SDS carries the
        # same NamedSharding _globalize_batch would commit)
        with self.mesh:
            batch_sds, rng_sds, theta_sds = \
                self._abstract_step_args(batch)
        for name, compiled in compiled_map.items():
            aot_step = self._aot_step_for(name)
            if aot_step is None or aot_step.compiled is not None:
                continue
            args = ((self.state,) if name == "apply_step"
                    else (self.state, batch_sds, rng_sds, theta_sds))
            try:
                sig = aot_step._signature(args)
            except Exception:
                sig = None
            if sig is None:
                continue
            aot_step.compiled, aot_step._sig = compiled, sig
            self._on_step_compiled(name, compiled)
            adopted.add(name)
        return adopted

    def get_cost_census(self, batch=None):
        """Static census (flops / bytes / memory / per-axis collectives)
        of the engine's active step program.

        Zero-compile when the cost explorer owns the artifact (the
        ``telemetry.cost_explorer.enabled`` path) — otherwise ONE AOT
        compile of the already-traced program is paid and the result
        memoized (the price the old flops profiler paid on every
        ``start_profile``). ``batch`` is only needed when no step has run
        yet (falls back to ``_last_batch``)."""
        if self._cost_census is not None:
            return self._cost_census
        from deepspeed_tpu.telemetry.hlo_census import census_compiled
        if batch is None:
            batch = self._last_batch
        assert batch is not None, (
            "get_cost_census before any train step needs an example "
            "batch: pass batch=...")
        target, name = self._jit_train, "fused_train_step"
        if target is None:
            target, name = self._jit_micro, "micro_step"
        # unwrap compile-watch, then reach the jit under a possible
        # _AOTStep (whose artifact would have been used above if primed)
        target = getattr(target, "_compile_watch_target", target)
        aot_step = target if isinstance(target, _AOTStep) else None
        if aot_step is not None:
            if aot_step.compiled is not None:
                self._cost_census = census_compiled(aot_step.compiled,
                                                    mesh=self.mesh)
                self._cost_census_program = name
                return self._cost_census
            target = aot_step._jit
        if aot_step is None:
            logger.info(
                "[cost-explorer] no owned compiled artifact (enable "
                "telemetry.cost_explorer to keep one); paying one AOT "
                "compile of %r for the census", name)
        with self.mesh:
            gbatch = self._globalize_batch(batch) \
                if batch is not self._last_batch else batch
            args = (self.state, gbatch, self._next_rng(), jnp.float32(1.0))
            compiled = target.lower(*args).compile()
        if aot_step is not None:
            # census-before-first-step: this compile IS the training
            # compile — hand the artifact to the dispatcher so the first
            # train step reuses it instead of compiling again (the AOT
            # path has no cache of its own), and run the usual
            # census/pre-flight/gauge hook. Signature FIRST: assigning
            # compiled without a matching _sig would half-prime the
            # dispatcher and send every step to the cold fallback jit.
            try:
                sig = aot_step._signature(args)
            except Exception:
                sig = None
            if sig is not None:
                aot_step.compiled, aot_step._sig = compiled, sig
            self._on_step_compiled(name, compiled)
        else:
            self._cost_census = census_compiled(compiled, mesh=self.mesh)
            self._cost_census_program = name
        return self._cost_census

    def explain_step(self, batch=None, step_time_s=None):
        """Explain the compiled step: roofline/MFU attribution, compute/
        memory/comm-bound verdict, per-axis collective bytes, and the HBM
        watermark — joined from the static census and measured step time
        (the telemetry step-time histogram, else the throughput timer,
        else static-only). Returns the report dict; publishes the census
        gauges through the telemetry registry when enabled."""
        census = self.get_cost_census(batch=batch)
        if step_time_s is None:
            reg = self.telemetry.registry
            if reg is not None:
                h = reg.histogram("train_step_time_ms",
                                  "host wall time per train_batch")
                if h.count > 1 and self._first_step_time_ms is not None:
                    # exclude the first step: its wall time is dominated
                    # by XLA compilation, not execution — averaging it in
                    # would understate MFU by the compile/steady ratio
                    step_time_s = ((h.sum - self._first_step_time_ms)
                                   / (h.count - 1) / 1e3)
                elif h.count:
                    step_time_s = h.sum / h.count / 1e3
            if step_time_s is None:
                sps = self.tput_timer.avg_samples_per_sec()
                if sps > 0:
                    step_time_s = self.train_batch_size() / sps
        explorer = self._get_cost_explorer()
        # under gradient accumulation the census covers ONE micro step but
        # the measured step time covers gas of them (+ the small apply
        # program, uncounted) — scale the rate math accordingly
        invocations = (self.gradient_accumulation_steps()
                       if self._cost_census_program == "micro_step" else 1)
        report = explorer.explain(
            census, step_time_s=step_time_s,
            name=self._cost_census_program or "step",
            invocations=invocations)
        report["aot_artifact_owned"] = self._cost_explorer_on
        if self.telemetry.enabled:
            explorer.publish(census, report)
        return report

    # ------------------------------------------------- health observatory
    def _wire_health_monitor(self):
        """Fill the rank-0 HealthMonitor's mesh/config-dependent fields
        once the bucket spec exists (manager built it before the step fns
        were constructed, so it could not know them)."""
        mon = self.telemetry.health
        if mon is None:
            return
        mon.bucket_names = list(self._health_spec.names)
        if self.config.fp16_enabled:
            mon.min_scale = float(self.config.fp16.min_loss_scale)
        mon.census_fn = self._census_header

    def _census_header(self):
        """Compact cost-census header for HEALTH.json (None when the cost
        explorer never censused a program)."""
        c = self._cost_census
        if c is None:
            return None
        return {"program": self._cost_census_program,
                "flops_per_device": c.flops,
                "bytes_accessed": c.bytes_accessed,
                "hbm_watermark_bytes": c.hbm_watermark_bytes,
                "n_devices": c.n_devices}

    def _health_tick(self, force=False):
        """Fetch + observe the pending in-step stats at the health cadence
        (default ``steps_per_print``) — the ONLY host<->device sync in the
        health path; between ticks the host holds device references only.
        Rank 0 only (the monitor gates it); other ranks never fetch."""
        mon = self.telemetry.health
        if (mon is None or not self._health_on
                or self._pending_health_stats is None):
            return None
        cadence = self._health_cadence or self.steps_per_print()
        if not force and self.global_steps % cadence != 0:
            return None
        if self._health_last_obs_step == self.global_steps:
            return mon.last_sample
        self._health_last_obs_step = self.global_steps
        # ONE transfer for the whole tick (stats pytree + loss scalar) —
        # every device_get is a blocking sync, and avoidable round-trips
        # are this engine's cardinal sin. The loss is the last dispatched
        # micro/fused loss (the fused path's loss IS the global loss;
        # under gas>1 it is the last micro's).
        with self._led_attr("device_compute"):
            stats, loss_arr = jax.device_get(
                (self._pending_health_stats, self._health_last_loss))
        loss = (float(np.asarray(loss_arr))
                if loss_arr is not None else None)
        sample = {
            "step": self.global_steps,
            "loss": loss,
            "lr": self.get_lr()[0],
            "skipped_steps": self.skipped_steps,
            "grad_norm": float(stats["grad_norm"]),
            "param_norm": float(stats["param_norm"]),
            "update_ratio": float(stats["update_ratio"]),
            "bucket_grad_norms": [
                float(x) for x in np.asarray(
                    stats["bucket_grad_norms"]).ravel()],
            "nonfinite_buckets": int(stats["nonfinite_buckets"]),
            "loss_scale": float(stats["loss_scale"]),
            "good_steps": int(stats["good_steps"]),
            "hysteresis": int(stats["hysteresis"]),
            "overflow": bool(stats["overflow"]),
        }
        mon.observe(sample)
        reg = self.telemetry.registry
        if reg is not None:
            reg.gauge("train_param_norm",
                      "global param L2 norm (health stats)").set(
                          sample["param_norm"])
            reg.gauge("train_update_ratio",
                      "||applied update|| / ||params|| (health stats)").set(
                          sample["update_ratio"])
            reg.gauge("health_nonfinite_buckets",
                      "non-finite grad provenance bitmask").set(
                          sample["nonfinite_buckets"])
            for name, v in zip(self._health_spec.names,
                               sample["bucket_grad_norms"]):
                reg.gauge("train_grad_norm_bucket",
                          "per-module-bucket grad L2 norm",
                          labels={"bucket": name}).set(v)
        return sample

    def health_report(self, write=False):
        """The training-health forensics report (what HEALTH.json holds):
        verdict, anomaly history, EWMA state, the recent-stats ring and
        the cost-census header. Forces one stats fetch so the report is
        current even between cadences. ``write=True`` also writes the
        snapshot file. ``{"enabled": False}`` when ``telemetry.health``
        is off or this is not rank 0."""
        mon = self.telemetry.health
        if mon is None or not self._health_on:
            return {"enabled": False}
        self._health_tick(force=True)
        if write:
            mon.write_snapshot(force=True)
        return mon.report()

    # ------------------------------------------- HBM residency observatory
    @staticmethod
    def _leaf_device_bytes(arr):
        """Physical device bytes one state leaf pins across this
        process's addressable devices — shard bytes x addressable
        shards. Pure metadata arithmetic (shape/dtype/sharding), never a
        device sync; a replicated leaf on an 8-device mesh costs 8x its
        logical nbytes in HBM, which is what the profile's live total
        sees (plain ``arr.nbytes`` would undercount it 8x)."""
        try:
            sh = arr.sharding
            shard = sh.shard_shape(tuple(arr.shape))
            n = len(sh.addressable_devices)
            return int(np.prod(shard, dtype=np.int64)) * \
                int(arr.dtype.itemsize) * n
        except Exception:
            return int(getattr(arr, "nbytes", 0) or 0)

    def _memory_build_inventory(self):
        """Expected device bytes for the engine-owned pools, split
        through the PR-3 bucket names. Static after init (the accounting
        is shape metadata), so it is built once and cached. Optimizer
        moments and the grad-accumulation pool mirror the param tree, so
        their leaves map back to the same module buckets by path
        component; unmatched leaves fold into ``(other)``."""
        if self._memory_inventory is not None:
            return self._memory_inventory
        from deepspeed_tpu.telemetry.health import (_path_component,
                                                    build_bucket_spec)
        spec = self._health_spec or build_bucket_spec(
            self.state.params,
            depth=int(getattr(self.config.telemetry,
                              "health_bucket_depth", 8)))
        flat, _ = jax.tree_util.tree_flatten_with_path(self.state.params)
        param_buckets = {name: 0 for name in spec.names}
        for (path, leaf), b in zip(flat, spec.leaf_buckets):
            param_buckets[spec.names[b]] += self._leaf_device_bytes(leaf)

        def bucket_of(path):
            comps = {_path_component(e) for e in path}
            for name in spec.names:
                if all(p in comps for p in name.split("/")):
                    return name
            return "(other)"

        opt_buckets = {name: 0 for name in spec.names}
        opt_bytes = 0
        for tree in (self.state.opt_state,
                     getattr(self.state, "acc_grads", None)):
            oflat, _ = jax.tree_util.tree_flatten_with_path(tree)
            for path, leaf in oflat:
                b = self._leaf_device_bytes(leaf)
                opt_bytes += b
                name = bucket_of(path)
                opt_buckets[name] = opt_buckets.get(name, 0) + b
        self._memory_inventory = {
            "totals": {"params": sum(param_buckets.values()),
                       "optimizer_state": opt_bytes,
                       "kv_pool": 0},
            "param_buckets": param_buckets,
            "opt_buckets": {k: v for k, v in opt_buckets.items() if v},
        }
        return self._memory_inventory

    def _memory_arm(self, mon):
        """Fill the monitor's census/mesh-dependent fields lazily: the
        pre-flight watermark prediction (once the cost explorer has
        censused a step program) and the HBM budget — a real device
        ``memory_stats`` limit only; the host-RSS fallbacks are refused
        (warn-once) because process RSS is not an HBM budget."""
        if mon.predicted_bytes is None:
            hdr = self._census_header()
            if hdr and hdr.get("hbm_watermark_bytes"):
                per_dev = int(hdr["hbm_watermark_bytes"])
                n = int(hdr.get("n_devices") or 1)
                mon.set_prediction(
                    per_dev * n, source="cost_explorer.preflight",
                    detail={"hbm_watermark_bytes_per_device": per_dev,
                            "n_devices": n,
                            "program": hdr.get("program")})
        if mon.budget_bytes is None and not self._memory_budget_checked:
            self._memory_budget_checked = True
            from deepspeed_tpu.telemetry.metrics import device_memory_stats
            stats = device_memory_stats()
            src = stats.get("source")
            if src == "device" and stats.get("bytes_limit"):
                mon.set_budget(
                    int(stats["bytes_limit"]) * len(jax.local_devices()),
                    source="jax.memory_stats")
            elif src in ("host_rss", "host_peak_rss"):
                mon.refuse_host_budget(src)

    def _memory_tick(self, force=False):
        """Fetch + attribute one device-memory profile at the memory
        cadence (default ``steps_per_print``) — a host RPC into the
        runtime's allocator bookkeeping, never a device sync and never a
        program change (the train step stays byte-identical; the
        telemetry_overhead guard pins 0 extra compiles). Rank 0 only
        (the monitor gates it)."""
        mon = self._memory
        if mon is None:
            return None
        cadence = self._memory_cadence or self.steps_per_print()
        if not force and self.global_steps % cadence != 0:
            return None
        if self._memory_last_obs_step == self.global_steps:
            return mon.last_sample
        self._memory_last_obs_step = self.global_steps
        self._memory_arm(mon)
        try:
            from deepspeed_tpu.telemetry import memory_observatory as _mo
            from deepspeed_tpu.telemetry import pprof as _pprof
            sample = _mo.profile_sample(_pprof.fetch_device_memory_profile())
        except Exception as e:
            if not self._memory_warned_fetch:
                self._memory_warned_fetch = True
                logger.warning(
                    "[memory] device memory profile unavailable on this "
                    "backend: %s — residency windows disabled", e)
            return None
        inv = self._memory_build_inventory()
        sample["step"] = self.global_steps
        sample["inventory"] = inv["totals"]
        sample["param_buckets"] = inv["param_buckets"]
        sample["opt_buckets"] = inv["opt_buckets"]
        mon.observe(sample)
        reg = self.telemetry.registry
        if reg is not None:
            for name, c in mon.last_attribution["categories"].items():
                reg.gauge("memory_live_bytes",
                          "attributed live device bytes",
                          labels={"category": name}).set(c["bytes"])
            reg.gauge("memory_peak_bytes",
                      "measured peak live device bytes").set(
                          mon.measured_peak_bytes)
        return sample

    def memory_report(self, write=False):
        """The HBM residency report (what MEMORY_ANATOMY.json holds):
        exact-sum category/bucket attribution of the live profile, the
        measured-vs-predicted watermark drift, budget state, anomaly
        history and the window ring. Forces one profile fetch so the
        report is current even between cadences. ``write=True`` also
        writes the report file. ``{"enabled": False}`` when
        ``telemetry.memory`` is off or this is not rank 0."""
        mon = self._memory
        if mon is None:
            return {"enabled": False}
        self._memory_tick(force=True)
        if write:
            mon.write_report()
        return mon.report()

    # --------------------------------------------------- goodput ledger
    def _led_attr(self, category):
        """Goodput wall-clock attribution context for *category*; the
        shared no-op when the ledger is off (sub-µs, like trace_span).
        Ranks whose manager (and therefore ledger) is disabled but whose
        fleet shipper is live still time input-wait and checkpoint
        intervals — the cross-rank skew rules need every rank's numbers,
        not just rank 0's."""
        led = self._goodput
        if led is None:
            if self._fleet is not None and category in (
                    "input_wait", "checkpoint_save"):
                return self._fleet.time_category(category)
            return _NULL_CTX
        return led.attribute(category)

    def _breakdown_summary(self):
        """The goodput report's ``wall_clock_breakdown`` section, read
        from the SAME recorded timer intervals the breakdown log prints
        (``timer_<phase>_ms`` histograms) — one step loop, one timing
        system, two views that cannot disagree."""
        if not self.wall_clock_breakdown():
            return None
        reg = self.telemetry.registry
        if reg is None:
            return None
        phases = {}
        families = reg.collect()
        for name in (FORWARD_GLOBAL_TIMER, BACKWARD_GLOBAL_TIMER,
                     STEP_GLOBAL_TIMER):
            fam = families.get(f"timer_{name}_ms")
            if not fam:
                continue
            h = fam[0]
            phases[name] = {"total_ms": round(h.sum, 3), "count": h.count}
        return {
            "note": "recorded by the wall_clock_breakdown timers; the "
                    "synced phase intervals are attributed to the "
                    "ledger's device_compute category",
            "phases": phases,
        }

    def goodput_report(self, write=False):
        """The wall-clock goodput ledger report (what ``GOODPUT.json``
        holds): per-category seconds summing to elapsed wall time,
        goodput fraction, per-window ring, badput anomalies and the
        profiler-capture state. Closes the current partial window first
        so the report is current. ``write=True`` also writes the
        snapshot file. ``{"enabled": False}`` when ``telemetry.goodput``
        is off or this is not rank 0."""
        led = self._goodput
        if led is None or not led.enabled:
            return {"enabled": False}
        led.tick(self.global_steps, force=True)
        report = led.report()
        if write:
            led.write_snapshot(force=True, report=report)
        return report

    # --------------------------------------------------- step anatomy
    def profile_step(self, steps=None, batch=None, out=None, write=True):
        """Measured device-time attribution for *steps* train steps.

        Runs a bounded ``jax.profiler`` capture around N annotated
        ``train_batch`` calls, post-processes the XSpace trace with the
        dependency-free xplane parser, joins the per-op device events to
        the engine's own compiled HLO (``op_name`` module paths, census
        collectives, CostExplorer roofline floors) and writes the
        schema-pinned ``STEP_ANATOMY.json``. The capture reuses the
        already-primed step signature — zero additional train-step
        compiles. Inert (returns ``{"enabled": False}``) when
        ``telemetry.anatomy`` is off or the profiler is unavailable.

        ``batch`` defaults to the last trained batch; when the engine
        has never stepped, one warmup step runs OUTSIDE the capture
        window so compile time never pollutes the measured anatomy."""
        from deepspeed_tpu.telemetry import step_anatomy
        from deepspeed_tpu.telemetry.ledger import (
            profiler_available, _start_trace, _stop_trace)
        tcfg = self.config.telemetry
        if not getattr(tcfg, "anatomy_enabled", True):
            return {"enabled": False,
                    "reason": "telemetry.anatomy.enabled is false"}
        if not profiler_available():
            return {"enabled": False,
                    "reason": "jax.profiler programmatic capture "
                              "unavailable"}
        steps = int(steps if steps is not None
                    else getattr(tcfg, "anatomy_capture_steps", 3))
        if batch is None:
            batch = self._last_batch
        assert batch is not None, (
            "profile_step before any train step needs an example batch: "
            "pass batch=...")
        if self.global_steps == 0:
            # prime the compiled signature outside the window: the XLA
            # compile would otherwise dominate (and distort) step 0
            self.train_batch(batch=batch)
        outdir = getattr(tcfg, "output_path", "") or "telemetry/"
        trace_dir = os.path.join(outdir, "anatomy_profile")
        os.makedirs(trace_dir, exist_ok=True)
        try:
            _start_trace(trace_dir)
        except Exception as e:
            return {"enabled": False,
                    "reason": f"profiler start_trace failed: {e}"}
        try:
            from jax.profiler import TraceAnnotation
            for i in range(steps):
                with TraceAnnotation(step_anatomy.STEP_MARK, step=i):
                    loss = self.train_batch(batch=batch)
                    # block INSIDE the annotation so the device work of
                    # this step lands inside its window
                    jax.block_until_ready(loss)
        finally:
            try:
                _stop_trace()
            except Exception as e:
                logger.warning("[anatomy] stop_trace failed: %s", e)
        report = step_anatomy.summarize_capture(
            trace_dir, **self._anatomy_join_inputs())
        if report is None:
            return {"enabled": False,
                    "reason": f"profiler wrote no .xplane.pb under "
                              f"{trace_dir}"}
        report["enabled"] = True
        report.setdefault("source", {})["global_step"] = self.global_steps
        if write:
            path = out or getattr(tcfg, "anatomy_report_file", "") \
                or os.path.join(outdir, "STEP_ANATOMY.json")
            step_anatomy.write_report(report, path)
            report["report_path"] = path
        self._export_anatomy_lanes(report, trace_dir, outdir)
        # cap retained raw trace runs (the summary JSON survives)
        keep = int(getattr(tcfg, "anatomy_keep_raw_traces", 2))
        runs = sorted(
            (r for r in glob.glob(os.path.join(
                trace_dir, "plugins", "profile", "*")) if os.path.isdir(r)),
            key=os.path.getmtime, reverse=True)
        for stale in runs[keep:]:
            shutil.rmtree(stale, ignore_errors=True)
        return report

    def _anatomy_join_inputs(self):
        """The engine-owned join inputs for a step-anatomy capture: HLO
        op table + bucket names + roofline floors + census collective
        schedule. Everything is best-effort and NEVER compiles — a
        missing artifact just degrades attribution to name heuristics."""
        op_table = None
        schedule = None
        try:
            aot = (self._aot_step_for("fused_train_step")
                   or self._aot_step_for("micro_step"))
            if aot is not None and aot.compiled is not None:
                from deepspeed_tpu.telemetry import step_anatomy
                from deepspeed_tpu.telemetry.hlo_census import (
                    collective_schedule_positions)
                hlo_text = aot.compiled.as_text()
                op_table = step_anatomy.hlo_op_table(hlo_text)
                schedule = collective_schedule_positions(hlo_text)
        except Exception as e:
            logger.warning("[anatomy] HLO op-table join unavailable: %s", e)
        floors = None
        try:
            if self._cost_census is not None:
                floors = self.explain_step().get("bound_floors_s")
        except Exception as e:
            logger.warning("[anatomy] roofline floors unavailable: %s", e)
        buckets = (list(self._health_spec.names)
                   if self._health_spec is not None else None)
        return {"op_table": op_table, "bucket_names": buckets,
                "predicted_floors": floors,
                "schedule_positions": schedule}

    def _export_anatomy_lanes(self, report, trace_dir, outdir):
        """Merge the capture's per-device lanes into the Chrome trace
        (tracer spans + device lanes, via fleet.merge_traces) when span
        tracing is on. Best-effort: a merge failure only costs the
        merged view, never the report."""
        tel = self.telemetry
        if not (tel.enabled and getattr(tel, "tracer", None) is not None
                and tel.tracer.enabled):
            return
        try:
            from deepspeed_tpu.telemetry import step_anatomy, xplane
            from deepspeed_tpu.telemetry.fleet import merge_traces
            files = xplane.find_xplane_files(trace_dir)
            if not files:
                return
            _, lanes = step_anatomy.extract_events(
                xplane.parse_xspace_file(files[0]))
            if not lanes:
                return
            dev_path = os.path.join(outdir, "anatomy_device.trace.json")
            step_anatomy.write_device_trace(dev_path, lanes)
            host_path = tel.tracer.export(
                os.path.join(outdir, "anatomy_host.trace.json"))
            merged = merge_traces(
                os.path.join(outdir, "anatomy_merged.trace.json"),
                [host_path, dev_path])
            report["merged_trace"] = merged
        except Exception as e:
            logger.warning("[anatomy] device-lane trace merge failed: %s",
                           e)

    # --------------------------------------------------- fleet recorder
    def _resolve_desync(self):
        """Arm the desync sentinel when the engine is inside its
        envelope: data-parallel replicas that are REPLICATED in name
        (zero <= 2, no model/expert/pipe sharding of params) are the
        precondition for cross-replica checksum comparison — a sharded
        param tree diverges across ranks by design. A perf/forensics
        knob, never a semantic switch: outside the envelope the fleet
        still ships, just without checksums (warn once)."""
        bad = []
        if self.dp_world_size < 2:
            bad.append("data-parallel world size 1 (no replicas to "
                       "cross-check)")
        if self.zero_stage >= 3:
            bad.append(f"zero stage {self.zero_stage} (params sharded "
                       "over dp — replicas legitimately differ)")
        if self.mp_world_size != 1:
            bad.append("model parallelism")
        if groups.get_expert_parallel_world_size() != 1:
            bad.append("expert parallelism")
        if groups.get_pipe_parallel_world_size() != 1:
            bad.append("pipeline parallelism")
        if not bad:
            # belt and braces: the checksum shard_map assumes every leaf
            # is fully replicated; any partitioned spec would make the
            # per-device reduction read different (legitimate) slices
            specs = {tuple(s.spec) for s in
                     jax.tree_util.tree_leaves(self.param_shardings)}
            if any(any(e is not None for e in spec) for spec in specs):
                bad.append("partitioned param shardings")
        if bad:
            if not self._warned_desync:
                self._warned_desync = True
                logger.warning(
                    "telemetry.fleet.desync requested but the parameter "
                    "checksum sentinel is disabled — incompatible with: "
                    + "; ".join(bad))
            return False
        return True

    def _fleet_tick(self, force=False):
        """Ship this rank's window record at the fleet cadence (and run
        the rank-0 aggregation poll). The only device access is the
        cadence-gated desync checksum fetch on THIS (main) thread —
        attributed like the health tick; the shipping itself is host
        file I/O on the background writer."""
        fl = self._fleet
        if fl is None:
            return None
        cad = self._fleet_cadence or self.steps_per_print()
        if not force and self.global_steps % cad != 0:
            return None
        desync = None
        if self._desync_on and self._desync_fn is not None and \
                fl.has_pending_steps() and \
                self._fleet_ticks % self._desync_every == 0:
            with self._led_attr("device_compute"), \
                    self.telemetry.span("fleet/desync_checksum"):
                mat = jax.device_get(self._desync_fn(self.state.params))
            desync = {
                "step": self.global_steps,
                "bucket_names": list(self._desync_spec.names),
                "replicas": [[i, [float(v) for v in row]]
                             for i, row in enumerate(mat)],
            }
        mon = self.telemetry.health
        health = mon.last_sample if (mon is not None
                                     and self._health_on) else None
        rec = fl.tick(step=self.global_steps,
                      skipped_steps=self.skipped_steps,
                      desync=desync, health=health, force=force)
        if rec is not None:
            self._fleet_ticks += 1
        if self._fleet_monitor is not None:
            # rank 0 merges whatever every rank (this one included) has
            # shipped so far; pure host file I/O, judged incrementally.
            # Only the forced report path waits for the background
            # writer — draining every cadence tick would park the train
            # thread on the writer's fsync (on a shared fs that can be
            # tens of ms), and the monitor simply judges this rank's
            # window on the next poll once the file lands.
            if force:
                fl.drain()
            self._fleet_monitor.poll(force=force)
        return rec

    def fleet_report(self, write=False):
        """The fleet flight-recorder report (what ``FLEET_HEALTH.json``
        holds): per-rank exact-integer window sums, the merged window
        ring with cross-rank skew views, desync sentinel state and the
        fired anomalies. Ships this rank's partial window first so the
        report is current. On a non-zero fleet rank (no aggregator)
        returns the shipper's own summary. ``{"enabled": False}`` when
        ``telemetry.fleet`` is off."""
        if self._fleet is None:
            return {"enabled": False}
        self._fleet_tick(force=True)
        if self._fleet_monitor is None:
            return {"enabled": True, "role": "shipper",
                    "rank": self._fleet.rank,
                    "windows_shipped": self._fleet.windows_shipped,
                    "ship_errors": self._fleet.ship_errors}
        report = self._fleet_monitor.report()
        if write:
            self._fleet_monitor.write_snapshot(force=True, report=report)
        return report

    # ----------------------------------------------------------- guardian
    def guardian_report(self, write=False):
        """The guardian's action journal (what ``GUARDIAN.json`` holds):
        armed policies, rules seen, every action taken with its trigger
        rule and outcome. ``{"enabled": False}`` when the guardian is
        off (or disarmed by multi-process)."""
        if self._guardian is None:
            return {"enabled": False}
        report = self._guardian.report()
        if write:
            self._guardian.write_journal()
        return report

    # ---------------------------------------------------------- chronicle
    def _chronicle_emit(self, phase, **data):
        """Engine-lifecycle event into the run chronicle. No-op unless
        THIS engine armed one (one attribute test when off — the
        autotuner's trial engines must not cross-chronicle)."""
        if self._chronicle is not None and self._chronicle.enabled:
            self._chronicle.emit("lifecycle", source="engine",
                                 step=int(self.global_steps), phase=phase,
                                 **data)

    def _note_first_compile(self, step_s):
        """The first train_batch is the compile-dominated one — a
        timeline without it misattributes minutes of wait to whatever
        fired next."""
        if not self._chronicle_first_emitted:
            self._chronicle_first_emitted = True
            self._chronicle_emit(
                "first_compile", step_time_ms=round(step_s * 1000.0, 3),
                detail="first train_batch (compile-dominated)")

    def chronicle_report(self, write=False):
        """The run chronicle + correlated incidents (what
        ``CHRONICLE.json`` / ``INCIDENTS.json`` hold): this rank's merged
        causal event timeline, plus the incident chains the correlator
        joins out of it — ordered member events, ranked root cause,
        goodput cost re-added from the ledger's window-diff events.
        Works on a closed engine (reads the in-memory log; ``write=True``
        then writes both artifacts synchronously).
        ``{"enabled": False}`` when ``telemetry.chronicle`` is off."""
        if self._chronicle is None:
            return {"enabled": False}
        from deepspeed_tpu.telemetry import incidents as _inc
        tcfg = self.config.telemetry
        doc = self._chronicle.report()
        doc["incidents"] = _inc.correlate(
            self._chronicle.snapshot_events(),
            step_window=int(getattr(tcfg, "chronicle_step_window", 8)),
            time_window_us=int(round(float(getattr(
                tcfg, "chronicle_time_window_s", 30.0)) * 1e6)),
            job_name=tcfg.job_name or "")
        if write:
            self._chronicle.drain()
            self._chronicle.write_summary(self._chronicle_summary_path)
            _inc.write_incidents(doc["incidents"],
                                 self._chronicle_incidents_path)
        return doc

    def _guardian_emergency_save(self, step):
        """Guardian action (a): an extra checkpoint through the normal
        save path (async writer when configured, one in flight). The tag
        is prefixed so rollback can de-prioritize it — state saved
        BECAUSE something looked wrong is of unknown health."""
        from deepspeed_tpu.runtime.guardian import EMERGENCY_TAG_PREFIX
        save_dir = self._guardian_ckpt_dir
        if save_dir is None:
            raise RuntimeError(
                "no checkpoint directory known yet (the guardian learns "
                "it from the first user save_checkpoint())")
        tag = f"{EMERGENCY_TAG_PREFIX}_step{int(step)}"
        self.save_checkpoint(save_dir, tag=tag,
                             data_iter=self._guardian_data_iter,
                             initiator="guardian")
        return tag

    def _guardian_rollback(self):
        """Guardian action (b): restore the newest intact tag — params,
        optimizer state, loss-scale state and the data-stream position —
        through the normal load path. Prefers user tags over the
        guardian's own emergency tags (those may hold exactly the state
        this rollback exists to escape); the whole interval books as
        ``checkpoint_load`` badput."""
        from deepspeed_tpu.runtime import checkpoint_io
        from deepspeed_tpu.runtime.guardian import EMERGENCY_TAG_PREFIX
        save_dir = self._guardian_ckpt_dir
        if save_dir is None:
            raise RuntimeError(
                "no checkpoint directory known yet (the guardian learns "
                "it from the first user save_checkpoint())")
        try:
            names = os.listdir(save_dir)
        except OSError:
            names = []
        emergency = [n for n in names
                     if n.startswith(EMERGENCY_TAG_PREFIX)]
        tag = checkpoint_io.newest_intact_tag(save_dir, exclude=emergency)
        if tag is None and emergency:
            tag = checkpoint_io.newest_intact_tag(save_dir)
        if tag is None:
            raise RuntimeError(
                f"no intact checkpoint tag under {save_dir} to roll "
                f"back to")
        with self.telemetry.span("guardian/rollback", tag=str(tag)):
            path, _ = self.load_checkpoint(
                save_dir, tag=tag, data_iter=self._guardian_data_iter)
        if path is None:
            raise RuntimeError(f"rollback load of tag {tag!r} failed")
        return tag

    def _guardian_fp16_rescue(self):
        """Guardian action (c): reset the dynamic loss scaler out of
        collapse — an escape scale with fresh good-step count and
        hysteresis. The LR schedule is traced INTO the compiled step
        program, so the scaler state (same shapes/dtypes, zero
        recompiles) is the intervention surface."""
        if not self.config.fp16_enabled:
            raise RuntimeError("fp16_rescue on a non-fp16 engine")
        old_scale = float(jax.device_get(self.state.scale.loss_scale))
        old_hyst = int(jax.device_get(self.state.scale.hysteresis))
        new_scale = max(old_scale * 16.0, 16.0)
        self.state = self.state._replace(scale=LossScaleState(
            loss_scale=jnp.float32(new_scale),
            good_steps=jnp.int32(0),
            hysteresis=jnp.int32(max(old_hyst, 2))))
        return f"loss_scale {old_scale:g} -> {new_scale:g}"

    def _lr_fn_traced(self, step):
        """LR schedule on a traced step: the four built-in schedules are
        written in jnp so they compile straight into the apply step."""
        return jnp.asarray(self._lr_fn(step), jnp.float32)

    # ------------------------------------------------------------------ train
    def _next_rng(self):
        key = jax.random.PRNGKey(self._seed)
        return jax.random.fold_in(key, self.micro_steps)

    def _apply_curriculum(self, batch):
        """Truncate sequence dims to the scheduled difficulty (reference
        engine.py:1577-1583 injects curriculum_seqlen; here the engine
        slices the batch — each plateau compiles once)."""
        from deepspeed_tpu.runtime.data_pipeline.curriculum_scheduler \
            import apply_seqlen_truncation
        return apply_seqlen_truncation(self.curriculum_scheduler,
                                       self.global_steps, batch)

    def forward(self, batch):
        """Compute loss for one micro-batch (and, fused, its gradients).

        Returns the unscaled loss as a jax scalar. The reference's separate
        autograd backward is folded in (see module docstring)."""
        if self.curriculum_scheduler is not None:
            batch = self._apply_curriculum(batch)
        if self.progressive_layer_drop is not None:
            self.progressive_layer_drop.update_state(self.global_steps)
        theta = jnp.float32(
            self.progressive_layer_drop.get_theta()
            if self.progressive_layer_drop is not None else 1.0)
        breakdown = self.wall_clock_breakdown()
        # goodput: with the breakdown syncs on, this region is device-bound
        # wall time (the block_until_ready wait); async, it is dispatch
        with self._led_attr("device_compute" if breakdown
                            else "host_dispatch"):
            if breakdown:
                self.timers(FORWARD_GLOBAL_TIMER).start()
            with self.telemetry.span("forward", micro_step=self.micro_steps):
                with self.mesh:
                    batch = self._globalize_batch(batch)
                    self.state, loss = self._jit_micro(
                        self.state, batch, self._next_rng(), theta)
            if breakdown:
                jax.block_until_ready(loss)
                self.timers(FORWARD_GLOBAL_TIMER).stop(record=True)
        self._pending_loss = loss
        self._last_batch = batch
        if self._health_on:
            self._health_last_loss = loss   # device ref, no sync
        return loss

    def _globalize_batch(self, batch, for_train=True, verify=True):
        """Place the host batch onto the mesh as the GLOBAL batch.

        ``verify=False`` is the background-thread (prefetch device
        stage) contract: placement itself is collective-free — the
        cross-process verification collectives (broadcast-leaf checksum
        allgather, eval row-count agreement) are DEFERRED to
        ``_verify_prefetched_batch`` on the main thread at consumption.
        A background-thread collective racing main-thread collectives is
        a deadlock, which is why PR 5 disabled the device stage on
        multi-process runs; splitting verification out of the placement
        path is what lifted that restriction.

        A scalar, or a dim0==1 leaf in a batch whose OTHER leaves carry
        real rows (a [1,S] broadcast mask, a shared table), is NOT a
        per-row batch slice — it is replicated whole (round-4 advisory:
        the old blanket row check spuriously rejected these, and the
        single-process device_put tried to row-shard them). A batch
        whose every leaf has one row is NOT reinterpreted — that shape
        is a mis-sliced loader, and the loud uneven-rows rejection was
        built for exactly that. Single process: device_put against the
        per-leaf sharding. Multi process: each host holds only its
        slice (deepspeed_io loads global_micro/process_count rows), so
        the global array is assembled from per-process shards —
        device_put would silently treat the local slice as the whole
        batch (ADVICE round 1); broadcast leaves are checksum-verified
        identical across processes before being stamped 'replicated'.

        A batch the prefetcher's device stage already placed (runtime/
        prefetch.py) arrives here as global jax arrays with exactly the
        shardings this function computes — the single-process
        ``device_put`` below then returns the SAME buffers without a
        transfer (verified same-object in jax 0.4.37), so re-entering is
        the cheap, validation-preserving way to "skip" placement."""
        import numpy as _np
        shardings = self._batch_sharding(batch)
        n_proc = jax.process_count()
        global_rows = (self.train_micro_batch_size_per_gpu()
                       * self.dp_world_size)
        expect = global_rows // n_proc  # batch rows each process holds
        repl = NamedSharding(self.mesh, P())
        all_single_row = all(
            _np.ndim(x) == 0 or _np.shape(x)[0] == 1
            for x in jax.tree.leaves(batch))

        def _is_broadcast(x):
            # only on the DEFAULT sharding path: an explicit batch_spec
            # is the user's word and is honored verbatim for every leaf
            if self._batch_spec is not None:
                return False
            if _np.ndim(x) == 0:
                return True
            # eval batches are not bound to the train micro-batch size,
            # so there dim0==1 in a mixed tree is broadcast regardless
            return (_np.shape(x)[0] == 1 and not all_single_row
                    and (expect != 1 or not for_train))

        if (for_train and (self._onebit_dist or self._sparse_grads
                           or self._comm_overlap_on)
                and any(_is_broadcast(x) and _np.ndim(x) > 0
                        for x in jax.tree.leaves(batch))):
            # the 1-bit / sparse-grad / comm-overlap TRAIN step fns
            # shard_map the whole batch tree with in_specs=P(data) — a
            # dim0==1 leaf fails divisibility there with an opaque trace
            # error, so reject it loudly here (eval_batch jits without
            # shard_map and handles replicated leaves fine)
            raise NotImplementedError(
                "broadcast batch leaves (leading dim 1) are not supported "
                "with 1-bit optimizers, sparse_gradients or comm_overlap: "
                "their step functions shard the whole batch over the data "
                "axis; give the leaf the batch's leading dimension")
        shardings = jax.tree.map(
            lambda x, sh: repl if _is_broadcast(x) else sh,
            batch, shardings)
        if n_proc == 1:
            return jax.device_put(batch, shardings)
        # A batch the prefetch device stage already placed arrives as
        # GLOBAL (non-fully-addressable) arrays — re-running placement
        # would np.asarray them, which raises. Run the deferred
        # cross-process verification the background thread skipped
        # (verify=False placement) and hand the same buffers back.
        batch_leaves = jax.tree.leaves(batch)
        if batch_leaves and all(
                isinstance(x, jax.Array) and not x.is_fully_addressable
                for x in batch_leaves):
            # verify=False is the background thread (a user loader can
            # yield pre-placed global arrays straight into the device
            # stage): the verification collectives stay deferred to the
            # main-thread re-globalize at consumption, which lands in
            # this same branch with verify=True
            if verify:
                self._verify_prefetched_batch(batch, for_train=for_train)
            return batch
        # Validate the WHOLE tree before any placement or collective so a
        # uniform loader bug raises on every rank instead of deadlocking
        # a later collective (rank-DIVERGENT tree shapes can still hang —
        # the same failure class as any diverged SPMD program).
        for x, sh in zip(jax.tree.leaves(batch),
                         jax.tree.leaves(shardings)):
            if _is_broadcast(x):
                continue
            # replicated BATCH sharding can't be assembled from differing
            # per-process slices — every host would need the FULL batch
            if sh.is_fully_replicated:
                raise NotImplementedError(
                    "multi-process run with a replicated batch sharding: "
                    "each process only loads its slice (deepspeed_io), so "
                    "a replicated global batch cannot be assembled; use a "
                    "data-parallel mesh axis or load the full batch per "
                    "process via model_parameters/batch_spec")
            rows = _np.shape(x)[0]
            if for_train and rows != expect:
                raise ValueError(
                    f"uneven per-process batch slice: this process holds "
                    f"{rows} rows but the global micro-batch "
                    f"({global_rows}) over {n_proc} processes requires "
                    f"exactly {expect} per process (deepspeed_io slices "
                    f"evenly; feed each rank its own equal slice; "
                    f"broadcast leaves must have leading dim 1)")
            if not for_train and verify:
                # eval batches are not bound to the train micro-batch
                # geometry, but ranks must still agree on the row count —
                # a mismatch would compile divergent programs and hang
                # at the next collective instead of raising. verify=False
                # (background placement) defers this agreement check to
                # _verify_prefetched_batch on the main thread.
                from jax.experimental import multihost_utils
                all_rows = _np.asarray(multihost_utils.process_allgather(
                    _np.asarray([rows], _np.int64)))
                if not (all_rows == rows).all():
                    raise ValueError(
                        f"eval batch slices disagree across processes: "
                        f"row counts {sorted(set(all_rows.ravel().tolist()))}"
                        f" — every rank must feed an equal slice")

        def _place(path, x, sh):
            if _is_broadcast(x) and verify:
                # make_array_from_process_local_data does not cross-check
                # replicated content, so a mis-sliced loader feeding each
                # rank a different single row would silently diverge —
                # checksum-verify the first time each leaf path is seen
                # (steady-state cost zero; content drift after the first
                # batch is the cross-rank-assert debug tier's job).
                # verify=False (background placement) defers the checksum
                # to _verify_prefetched_batch on the main thread — the
                # allgather is a collective and this may be a background
                # thread.
                key = (tuple(str(p) for p in path), _np.shape(x),
                       str(_np.asarray(x).dtype))
                if key not in self._broadcast_leaves_checked:
                    self._broadcast_leaves_checked.add(key)
                    self._assert_identical_across_processes(x)
            return jax.make_array_from_process_local_data(
                sh, _np.asarray(x))

        return jax.tree_util.tree_map_with_path(_place, batch, shardings)

    def _assert_identical_across_processes(self, x):
        """Raise if ``x``'s bytes differ on any process (sha256 checksum
        allgather; guards the replicated broadcast-leaf path)."""
        import hashlib

        import numpy as _np
        from jax.experimental import multihost_utils
        digest = hashlib.sha256(
            _np.ascontiguousarray(_np.asarray(x)).tobytes()).digest()
        h = _np.frombuffer(digest[:8], dtype=_np.uint64)
        all_h = _np.asarray(multihost_utils.process_allgather(h))
        if not (all_h == all_h.ravel()[0]).all():
            raise ValueError(
                "broadcast batch leaf (leading dim 1) differs across "
                "processes — a dim0==1 leaf is replicated whole, so every "
                "process must feed the identical array; if this leaf is "
                "really a per-process batch slice, give it the batch's "
                "leading dimension")

    def _verify_prefetched_batch(self, batch, for_train=True):
        """Main-thread half of the split placement: the cross-process
        verification collectives a ``verify=False`` (background-thread)
        placement deferred — the broadcast-leaf checksum allgather and,
        for eval routes, the row-count agreement check. Runs at
        consumption, BEFORE the batch is dispatched, keyed by the same
        first-occurrence sets the direct placement path uses (steady
        state cost: one set lookup per leaf)."""
        import numpy as _np
        eval_rows = []
        for path, x in jax.tree_util.tree_flatten_with_path(batch)[0]:
            sh = getattr(x, "sharding", None)
            shape = tuple(getattr(x, "shape", ()))
            if sh is not None and getattr(sh, "is_fully_replicated", False):
                key = (tuple(str(p) for p in path), shape, str(x.dtype))
                if key in self._broadcast_leaves_checked:
                    continue
                self._broadcast_leaves_checked.add(key)
                # the local copy of the replicated leaf: this process's
                # own contribution, exactly what placement checksummed
                self._assert_identical_across_processes(
                    _np.asarray(x.addressable_data(0)))
            elif not for_train and shape:
                eval_rows.append(int(shape[0]))
        if eval_rows:
            # UNCONDITIONAL per batch, like the direct placement path's
            # row check: caching this by shape would make the allgather
            # call COUNT diverge across ranks exactly when shapes
            # diverge — the silent-deadlock case the check exists to
            # turn into a clean raise. ONE vector allgather for all
            # leaves (not one per leaf): the per-leaf version taxed
            # every steady-state eval batch L serial round-trips.
            from jax.experimental import multihost_utils
            all_rows = _np.asarray(multihost_utils.process_allgather(
                _np.asarray(eval_rows, _np.int64)))
            if not (all_rows == all_rows.reshape(
                    -1, len(eval_rows))[0]).all():
                raise ValueError(
                    f"eval batch shapes disagree across processes "
                    f"after background placement: global row counts "
                    f"{sorted(set(all_rows.ravel().tolist()))} — "
                    f"every rank must feed an equal slice")

    def backward(self, loss=None, allreduce_gradients=True, release_loss=False):
        """Bookkeeping half of the fused forward/backward (see ``forward``)."""
        assert self._pending_loss is not None, "backward() requires a prior forward()"
        if self.wall_clock_breakdown():
            self.timers(BACKWARD_GLOBAL_TIMER).start()
            self.timers(BACKWARD_GLOBAL_TIMER).stop(record=True)
        self._pending_loss = None
        self.micro_steps += 1
        return loss

    def is_gradient_accumulation_boundary(self):
        return (self.micro_steps % self.gradient_accumulation_steps()) == 0

    def _compute_block_eigenvalues(self):
        """Per-block loss-Hessian eigenvalue ratios at the current params
        over the last trained batch (reference engine.py:1891)."""
        if self._last_batch is None:
            return {}
        batch = self._last_batch

        def loss_fn(p):
            return self._compute_loss(p, batch, jax.random.PRNGKey(0))

        with self.mesh:
            ev = self.eigenvalue.compute_block_eigenvalues(
                loss_fn, self.state.params)
        if ev:
            blocks = sorted({lid for _, lid in ev.values()})
            vals = {lid: r for r, lid in ev.values()}
            if self.monitor.enabled and self.monitor.monitors:
                # reference scalar names (engine.py:1926-1934)
                self.monitor.write_events([
                    (f"Train/Eigenvalues/ModelBlockParam_{i}", vals[i],
                     self.global_samples) for i in blocks])
        return ev

    def _offload_step(self):
        """Host half of the offloaded step: shard-local CPU-Adam."""
        self.state, grads, grad_norm, overflow = self._jit_offload_pre(
            self.state)
        if not bool(jax.device_get(overflow)):
            lr = float(self._lr_fn(max(
                0, self.global_steps - self.skipped_steps)))
            new_params = self._offload_opt.step(
                grads, lr, self.state.params, self.param_shardings)
            self.state = self.state._replace(
                params=new_params, step=self.state.step + 1)
        return grad_norm, overflow

    def step(self, lr_kwargs=None):
        """Optimizer step at the gradient-accumulation boundary
        (reference engine.step, engine.py:1862)."""
        if not self.is_gradient_accumulation_boundary():
            return
        breakdown = self.wall_clock_breakdown()
        with self._led_attr("device_compute" if breakdown
                            else "host_dispatch"):
            if breakdown:
                self.timers(STEP_GLOBAL_TIMER).start()
            with self.telemetry.span("step", global_step=self.global_steps):
                if self._offload:
                    grad_norm, overflow = self._offload_step()
                elif self._health_on:
                    self.state, grad_norm, overflow, stats = self._jit_apply(
                        self.state)
                    self._pending_health_stats = stats   # device refs only
                else:
                    self.state, grad_norm, overflow = self._jit_apply(
                        self.state)
            if breakdown:
                jax.block_until_ready(self.state.step)
                self.timers(STEP_GLOBAL_TIMER).stop(record=True)
            self._post_apply(grad_norm, overflow, lr_kwargs)

    def _post_apply(self, grad_norm, overflow, lr_kwargs=None):
        """Host bookkeeping after an applied (or skipped) optimizer step."""
        # device scalar only — the host float is cached at print cadence
        # (get_global_grad_norm's float contract); None (not a misleading
        # 0.0) when the step skipped computing it
        self._pending_grad_norm = grad_norm if self._need_norm else None
        self.global_steps += 1
        self.global_samples += self.train_batch_size()
        # only fp16 can overflow; skipping the device_get elsewhere keeps
        # the train loop free of a per-step host sync
        if self.config.fp16_enabled:
            with self._led_attr("device_compute"):
                overflowed = bool(jax.device_get(overflow))
        else:
            overflowed = False
        if self.quantizer is not None:
            # MoQ: progressive fake-quantization of the trained params
            # (reference _take_model_step hook, engine.py:1816-1827 —
            # skips on overflow so the bit schedule tracks applied steps).
            # When a precision switch is due and eigenvalue guidance is on,
            # spend a per-block curvature estimate first (reference
            # engine.py:1884-1904): its ratios stretch the next period of
            # sharp (high-curvature) blocks.
            if (self.eigenvalue is not None
                    and self.global_steps %
                    self.eigenvalue.gas_boundary_resolution == 0
                    and self.quantizer.any_precision_switch()):
                self.block_eigenvalue = self._compute_block_eigenvalues()
            quantized = self.quantizer.quantize(
                self.state.params, overflow=overflowed,
                eigenvalue_enabled=self.eigenvalue is not None,
                block_eigenvalue=self.block_eigenvalue)
            if quantized is not self.state.params:
                self.state = self.state._replace(
                    params=jax.device_put(quantized, self.param_shardings))
        if overflowed:
            # reference engine.py:1844-1854: scheduler does NOT advance on a
            # skipped step, keeping it in lock-step with the applied-lr index
            # (state.step, which also only advances on success).
            self.skipped_steps += 1
            log_dist(
                f"[deepspeed] OVERFLOW! skipping step; new loss scale: "
                f"{self.loss_scale}", ranks=[0])
        elif self.lr_scheduler is not None:
            self.lr_scheduler.step(**(lr_kwargs or {}))
        led = self._goodput
        if led is not None:
            if overflowed:
                # the step just burned by the fp16 skip: re-label its
                # still-open wall-clock interval (the train_batch / step
                # wrapper) from good time to overflow_skipped badput
                led.reclassify_open("overflow_skipped")
            led.note_step(self.global_steps, overflowed)
            cad = self._goodput_cadence or self.steps_per_print()
            if self.global_steps % cad == 0:
                # pure host arithmetic — closes a ledger window, runs the
                # badput rules; never touches the device
                led.tick(self.global_steps)
        mon = self.telemetry.health
        if mon is not None and self._health_on:
            # host-only per-step facts (overflow streaks are exact, not
            # sampled); the stats fetch below is cadence-gated
            mon.note_step(self.global_steps, overflowed)
        sample = self._health_tick()
        self._memory_tick()
        if self.global_steps % self.steps_per_print() == 0 \
                and self._pending_grad_norm is not None:
            # the print path pays the device sync anyway; cache the float.
            # A health sample fetched this step already carries the same
            # scalar — reuse it rather than a second blocking device_get.
            self._last_grad_norm = (
                sample["grad_norm"] if sample is not None
                else float(jax.device_get(self._pending_grad_norm)))
        if self._slo is not None:
            # burn-rate evaluation (host arithmetic, self-throttled to
            # eval_interval_s) BEFORE the guardian tick so a page-tier
            # burn fired this step is actionable this step
            self._slo.tick(step=self.global_steps)
        if self._guardian is not None:
            # anomaly->action policies run HERE, on the main thread at
            # the step boundary — the only place swapping the live train
            # state (rollback, fp16 rescue) is safe. One attribute read
            # and a truthiness check when nothing is pending.
            self._guardian.tick(self.global_steps)

    def _fused_train_batch(self, data_iter, batch):
        """gas=1 fast path: one fused compiled program per global step."""
        if batch is not None:
            micro = batch
        else:
            with self._led_attr("input_wait"):
                micro = next(data_iter)
        if self.curriculum_scheduler is not None:
            micro = self._apply_curriculum(micro)
        if self.progressive_layer_drop is not None:
            self.progressive_layer_drop.update_state(self.global_steps)
        theta = jnp.float32(
            self.progressive_layer_drop.get_theta()
            if self.progressive_layer_drop is not None else 1.0)
        with self.telemetry.span("fused_step", global_step=self.global_steps):
            with self.mesh:
                gbatch = self._globalize_batch(micro)
                if self._health_on:
                    (self.state, loss, grad_norm, overflow,
                     stats) = self._jit_train(
                         self.state, gbatch, self._next_rng(), theta)
                    self._pending_health_stats = stats   # device refs only
                    self._health_last_loss = loss
                else:
                    self.state, loss, grad_norm, overflow = self._jit_train(
                        self.state, gbatch, self._next_rng(), theta)
        self._pending_loss = None
        self._last_batch = gbatch   # flops profiler reads this
        self.micro_steps += 1
        self._post_apply(grad_norm, overflow)
        return loss

    def train_batch(self, data_iter=None, batch=None):
        """One full global step: gas micro-batches + optimizer step."""
        if data_iter is not None:
            if self._guardian is not None:
                # rollback rewinds the LIVE loader (the same PR-7 resume
                # machinery as load_checkpoint(data_iter=...)) — keep a
                # handle to the caller's raw iterator, pre-prefetch-wrap
                self._guardian_data_iter = data_iter
            data_iter = self._maybe_prefetch_iter(data_iter)
        tel = self.telemetry
        if not tel.enabled:
            if self._fleet is None:
                return self._train_batch(data_iter, batch)
            # non-zero fleet ranks: the manager (and ledger) are rank-0
            # only, but the fleet needs THIS rank's step wall times —
            # two clock reads, nothing else
            t0 = time.perf_counter()
            mean_loss = self._train_batch(data_iter, batch)
            step_s = time.perf_counter() - t0
            self._fleet.note_step_time(step_s)
            self._note_first_compile(step_s)
            self._fleet_tick()
            return mean_loss
        t0 = time.perf_counter()
        # goodput: the whole step interval is host_dispatch SELF time —
        # nested attributions (input_wait in next(), compile via the
        # backend listener, the print-cadence device fetches) subtract
        # themselves out; an fp16 overflow re-labels it in _post_apply.
        # Step boundary FIRST: the previous step's trailing intervals
        # (its wrapper, the publish fetch) booked after its note_step,
        # and must not be sweepable by THIS step's overflow.
        if self._goodput is not None:
            self._goodput.mark_step_begin()
        with self._led_attr("host_dispatch"):
            with tel.span("train_batch", global_step=self.global_steps):
                mean_loss = self._train_batch(data_iter, batch)
            step_s = time.perf_counter() - t0
            self._note_first_compile(step_s)
            self._publish_step_telemetry(mean_loss, step_s)
        if self._fleet is not None:
            self._fleet.note_step_time(step_s)
            self._fleet_tick()
        return mean_loss

    def _tokens_per_sample(self):
        """Best-effort tokens/sample from the last batch's shape (first
        integer [B, S, ...] leaf); 0 when the workload has no token dim."""
        if self._last_batch is None:
            return 0
        for x in jax.tree.leaves(self._last_batch):
            if getattr(x, "ndim", 0) >= 2 and \
                    jnp.issubdtype(jnp.asarray(x).dtype, jnp.integer):
                return int(x.shape[1])
        return 0

    def _publish_step_telemetry(self, mean_loss, step_s):
        """Per-step metric publication (telemetry enabled only).

        Host-side metrics move EVERY step; gauges that read device values
        (loss, grad norm, loss scale) only publish at ``steps_per_print``
        cadence, where the existing log line already pays the device sync
        — telemetry must not add a per-step host<->device round trip."""
        reg = self.telemetry.registry
        reg.counter("train_steps_total",
                    "global steps (applied + skipped)").inc()
        reg.counter("train_samples_total",
                    "training samples consumed").inc(self.train_batch_size())
        reg.histogram("train_step_time_ms",
                      "host wall time per train_batch").observe(
                          step_s * 1000.0)
        if self._first_step_time_ms is None:
            # remembered so explain_step can exclude the compile-dominated
            # first step from its steady-state step-time estimate
            self._first_step_time_ms = step_s * 1000.0
        if self.global_steps % self.steps_per_print() != 0:
            return
        with self._led_attr("device_compute"):
            # the one blocking loss fetch of the print cadence
            reg.gauge("train_loss", "loss at the last print step").set(
                float(jax.device_get(mean_loss)))
            if self.config.fp16_enabled:
                reg.gauge("train_loss_scale", "dynamic loss scale").set(
                    self.loss_scale)
        reg.gauge("train_lr", "lr of the next applied step").set(
            self.get_lr()[0])
        if self._last_grad_norm is not None:
            # already a host float — _post_apply cached it at this cadence
            reg.gauge("train_grad_norm",
                      "global grad norm of the last applied step").set(
                          self._last_grad_norm)
        reg.gauge("train_skipped_steps",
                  "overflow-skipped optimizer steps").set(self.skipped_steps)
        sps = self.tput_timer.avg_samples_per_sec()
        if sps > 0:
            reg.gauge("samples_per_sec",
                      "running average samples/sec").set(sps)
            tokens = self._tokens_per_sample()
            if tokens:
                reg.gauge("tokens_per_sec",
                          "running average tokens/sec").set(sps * tokens)
        self.telemetry.publish_device_memory()
        self.telemetry.flush()

    def _train_batch(self, data_iter=None, batch=None):
        fp_cfg = self.config.flops_profiler_config
        profiling = (self.flops_profiler is not None
                     and self.global_steps == fp_cfg.profile_step)
        profile_t0 = time.perf_counter() if profiling else 0.0
        self.tput_timer.start()
        if self._jit_train is not None:
            mean_loss = self._fused_train_batch(data_iter, batch)
            self.tput_timer.stop(global_step=True)
        else:
            losses = []
            for _ in range(self.gradient_accumulation_steps()):
                if batch is not None:
                    micro = batch
                else:
                    assert data_iter is not None
                    with self._led_attr("input_wait"):
                        micro = next(data_iter)
                loss = self.forward(micro)
                self.backward(loss)
                losses.append(loss)
            self.step()
            self.tput_timer.stop(global_step=True)
            mean_loss = jnp.mean(jnp.stack(losses))
        if self.global_steps % self.steps_per_print() == 0:
            # float(mean_loss) is a blocking device fetch: wall time spent
            # here is the device catching up — good time, device_compute
            with self._led_attr("device_compute"):
                log_dist(
                    f"step={self.global_steps} loss={float(mean_loss):.6f} "
                    f"lr={self.get_lr()[0]:.3e}", ranks=[0])
        if profiling:
            # one-shot at profile_step (reference engine.py:1722-1952):
            # attribute the just-traced step's flops per module and print
            jax.block_until_ready(mean_loss)
            self.flops_profiler.start_profile()
            self.flops_profiler._duration = time.perf_counter() - profile_t0
            self.flops_profiler.print_model_profile(
                profile_step=fp_cfg.profile_step,
                module_depth=fp_cfg.module_depth,
                top_modules=fp_cfg.top_modules,
                detailed=fp_cfg.detailed,
                output_file=fp_cfg.output_file)
            self.flops_profiler.end_profile()
        if self.wall_clock_breakdown():
            self._breakdown_steps += 1
            if self.global_steps % self.steps_per_print() == 0:
                names = [FORWARD_GLOBAL_TIMER, BACKWARD_GLOBAL_TIMER,
                         STEP_GLOBAL_TIMER]
                if self.monitor.enabled and self.monitor.monitors:
                    means = self.timers.get_mean(
                        names, normalizer=self._breakdown_steps, reset=False)
                    # reference scalar names (engine.py:2015-2037)
                    self.monitor.write_events([
                        (f"Train/Samples/elapsed_time_ms_{n}", means[n],
                         self.global_samples) for n in names if n in means])
                self.timers.log(
                    names, normalizer=self._breakdown_steps,
                    memory_breakdown=self.config.memory_breakdown)
                self._breakdown_steps = 0
        if self.monitor.enabled and self.monitor.monitors \
                and self.global_steps % self.steps_per_print() == 0:
            # reference scalar names (engine.py:1686/:1911), sampled at
            # print cadence: the reference writes per step, but
            # float(mean_loss)/loss_scale force a host<->device sync and
            # per-step syncs are this engine's cardinal sin (see the
            # round-3/4 advisories) — the print step already pays it.
            # float(mean_loss)/loss_scale block on the device: goodput
            # books the wait as device_compute
            with self._led_attr("device_compute"):
                self.monitor.write_events([
                    ("Train/Samples/train_loss", float(mean_loss),
                     self.global_samples),
                    ("Train/Samples/lr", self.get_lr()[0],
                     self.global_samples),
                    ("Train/Samples/loss_scale", self.loss_scale,
                     self.global_samples),
                    # host-side counter that was computed but never
                    # exported (reference writes it via its monitor at
                    # the same point)
                    ("Train/Samples/skipped_steps",
                     float(self.skipped_steps), self.global_samples),
                ])
        return mean_loss

    def eval_batch(self, batch):
        with self._led_attr("eval"), self.telemetry.span("eval_batch"):
            with self.mesh:
                batch = self._globalize_batch(batch, for_train=False)
                return self._jit_eval(self.state.params, batch)

    def __call__(self, batch):
        return self.eval_batch(batch)

    # ------------------------------------------------------------------- data
    def deepspeed_io(self, dataset, batch_size=None, route=None,
                     data_sampler=None, collate_fn=None, num_local_io_workers=None):
        import deepspeed_tpu.comm as dist
        # Each process loads its host's slice of the global micro-batch.
        per_process = (self.train_micro_batch_size_per_gpu() *
                       self.dp_world_size) // dist.get_process_count()
        loader = DeepSpeedDataLoader(
            dataset,
            batch_size=batch_size or per_process,
            shuffle=data_sampler is None,
            drop_last=(True if self.config.dataloader_drop_last is None
                       else self.config.dataloader_drop_last),
            collate_fn=collate_fn or self.collate_fn,
            data_sampler=data_sampler,
            num_local_io_workers=num_local_io_workers,
            process_index=dist.get_rank(),
            process_count=dist.get_process_count())
        if not self._prefetch_cfg.enabled:
            if num_local_io_workers and not self._warned_io_workers:
                # the knob is accepted for reference parity but the
                # synchronous loader collates on the consumer thread —
                # tell the user why nothing got faster
                self._warned_io_workers = True
                logger.warning(
                    f"num_local_io_workers={num_local_io_workers} has no "
                    f"effect while data_prefetch is disabled: the loader "
                    f"collates synchronously on the consumer thread. "
                    f"Enable the 'data_prefetch' config block (or set "
                    f"DS_DATA_PREFETCH=1) to run the input pipeline in "
                    f"the background with that worker count — host "
                    f"collate workers plus the device double-buffering "
                    f"stage, which runs on multi-process meshes too "
                    f"(placement is collective-free; verification stays "
                    f"on the main thread).")
            return loader
        wrapped = PrefetchLoader(
            loader, depth=self._prefetch_cfg.depth,
            num_workers=num_local_io_workers or 1,
            place_fn=self._prefetch_place_fn(
                for_train=route in (None, ROUTE_TRAIN)))
        self._prefetchers.append(wrapped)
        return wrapped

    def _prefetch_place_fn(self, for_train=True):
        """The prefetch device stage's placement fn — ``_globalize_batch``
        with ``verify=False`` on a background thread — or None when the
        stage must stay off (curriculum learning: the scheduled per-step
        truncation happens on the HOST batch after ``next()`` —
        pre-placing would pin the full-length batch and defeat the
        plateau compile; warns once).

        Multi-process runs ARE supported: ``verify=False`` placement is
        collective-free by construction (the broadcast-leaf checksum
        allgather and eval row-count agreement are deferred to
        ``_verify_prefetched_batch`` on the main thread at consumption),
        so the background thread can never race a main-thread collective
        — the deadlock that made PR 5 disable the stage is structurally
        impossible now.

        ``for_train`` follows the loader's route: an eval-route loader
        must place with eval semantics (replicated dim0==1 leaves, no
        train-only broadcast rejection) or the background placement
        would diverge from what ``eval_batch`` does on the main thread."""
        import functools
        pf = self._prefetch_cfg
        if not pf.to_device:
            return None
        if self.curriculum_scheduler is not None:
            if not self._warned_prefetch_host_only:
                self._warned_prefetch_host_only = True
                logger.warning(
                    "data_prefetch: device stage disabled under "
                    "curriculum learning (the scheduled truncation "
                    "slices the host batch per step); host-side "
                    "prefetch stays on")
            return None
        if for_train:
            return functools.partial(self._globalize_batch, verify=False)
        return functools.partial(self._globalize_batch, for_train=False,
                                 verify=False)

    def _maybe_prefetch_iter(self, data_iter):
        """Wrap a user-supplied ``train_batch`` iterator in the prefetch
        pipeline (cached by identity — one pipeline per iterator).
        Already-prefetching sources pass through untouched."""
        if data_iter is None or not self._prefetch_cfg.enabled:
            return data_iter
        if isinstance(data_iter, PrefetchIterator):
            return data_iter
        # a RepeatingLoader over a deepspeed_io-built PrefetchLoader is
        # already prefetch-backed — don't stack a second pipeline on it
        if isinstance(getattr(data_iter, "loader", None), PrefetchLoader):
            return data_iter
        if hasattr(data_iter, "state_dict") \
                and hasattr(data_iter, "load_state_dict"):
            # a STATEFUL iterator (RepeatingLoader) counts its position
            # in __next__ — a background puller wrapped OUTSIDE it would
            # advance (epoch, batch_in_epoch) up to `depth` batches ahead
            # of what training consumed, and save_checkpoint(data_iter=)
            # would record a future position (a resumed run would skip
            # those batches). The correct composition is the pipeline
            # INSIDE the counter: RepeatingLoader over a prefetch-enabled
            # deepspeed_io loader.
            if not self._warned_prefetch_stateful:
                self._warned_prefetch_stateful = True
                logger.warning(
                    f"data_prefetch: not wrapping the stateful iterator "
                    f"{type(data_iter).__name__!r} passed to train_batch "
                    f"(a background puller would advance its resume "
                    f"counters ahead of consumption); build the loader "
                    f"via engine.deepspeed_io(...) and wrap THAT in "
                    f"RepeatingLoader to get prefetch AND deterministic "
                    f"resume")
            return data_iter
        cached = self._prefetch_wrap_cache.get(id(data_iter))
        if cached is not None and cached[0] is data_iter:
            return cached[1]
        # drop closed pipelines so exhausted iterators don't accumulate
        # (the strong source ref in the cache is what keeps id() valid)
        self._prefetch_wrap_cache = {
            k: v for k, v in self._prefetch_wrap_cache.items()
            if not v[1]._closed}
        wrapped = PrefetchIterator(
            data_iter, depth=self._prefetch_cfg.depth,
            place_fn=self._prefetch_place_fn())
        self._prefetch_wrap_cache[id(data_iter)] = (data_iter, wrapped)
        return wrapped

    def close(self):
        """Engine teardown: drain the async checkpoint writer (an
        in-flight save finishes, a failed one re-raises HERE — its last
        chance to surface), stop the prefetch pipelines (joins their
        worker threads) and close the telemetry manager. Idempotent; the
        pipelines and the writer also self-finalize at GC/interpreter
        exit, so this is the orderly path, not the only one."""
        try:
            if self._ckpt_writer is not None:
                with self._led_attr("checkpoint_save"):
                    self._ckpt_writer.close()
        finally:
            if self._fleet_aggregator is not None:
                try:
                    # before the obs server: the aggregator's routes are
                    # mounted on it, and close() persists cursors + the
                    # final fleet snapshot while peers are still known
                    self._fleet_aggregator.close()
                except Exception as e:
                    logger.warning("[federation] close failed: %s", e)
            if self._obs_server is not None:
                from deepspeed_tpu.telemetry import obs_server as _obs_mod
                try:
                    # FIRST: stop serving scrapes before the monitors the
                    # providers point at are torn down underneath them
                    self._obs_server.close()
                except Exception as e:
                    logger.warning("[obs] server close failed: %s", e)
                _obs_mod.reset_obs_server(if_current=self._obs_server)
            for pl in self._prefetchers:
                pl.close()
            for _src, wrapped in list(self._prefetch_wrap_cache.values()):
                wrapped.close()
            self._prefetch_wrap_cache.clear()
            # drop the owned AOT artifacts and cached device refs: a
            # closed engine must not pin compiled executables or batch
            # buffers alive (the autotuner runs many trial engines in one
            # process — leaked artifacts would accumulate per probe)
            for name in ("fused_train_step", "micro_step", "apply_step"):
                aot_step = self._aot_step_for(name)
                if aot_step is not None:
                    aot_step.compiled = None
                    aot_step._sig = None
            self._cost_census = None
            self._cost_census_program = None
            self._last_batch = None
            if self._fleet is not None:
                from deepspeed_tpu.telemetry import fleet as _fleet_mod
                try:
                    # ship the final partial window and judge it before
                    # the writer thread goes away (anomalies whose last
                    # firings rode the snapshot throttle still land)
                    self._fleet_tick(force=True)
                except Exception as e:
                    logger.warning("[fleet] final tick failed: %s", e)
                self._fleet.close()
                _fleet_mod.reset_shipper(if_current=self._fleet)
            if self._fleet_monitor is not None:
                self._fleet_monitor.close()
            if self._guardian is not None:
                try:
                    # final journal (only when there is something to
                    # explain) — before telemetry goes away
                    self._guardian.close()
                except Exception as e:
                    logger.warning("[guardian] final journal failed: %s", e)
            if self._slo is not None:
                try:
                    # final burn snapshot while the registry histograms
                    # and the ledger are still live
                    self._slo.close()
                except Exception as e:
                    logger.warning("[slo] close failed: %s", e)
            self.telemetry.close()
            if self._chronicle is not None:
                from deepspeed_tpu.telemetry import chronicle as _chron_mod
                # AFTER telemetry.close(): the ledger's final forced tick
                # just emitted its last goodput_window — the lifecycle
                # close must be the timeline's final event. Emit before
                # closing (the writer only drains pre-close events), then
                # detach the global so later engines start clean.
                self._chronicle_emit("close")
                try:
                    self._chronicle.close()
                except Exception as e:
                    logger.warning("[chronicle] close failed: %s", e)
                _chron_mod.reset_chronicle(if_current=self._chronicle)

    # ------------------------------------------------------------ checkpoints
    def _get_ckpt_name(self, checkpoints_path, tag):
        mp_rank = (self.mpu.get_model_parallel_rank()
                   if self.mpu is not None else 0)
        return os.path.join(checkpoints_path, str(tag),
                            f"mp_rank_{mp_rank:02d}" + MODEL_FILE_SUFFIX)

    def _get_zero_ckpt_name(self, checkpoints_path, tag):
        import deepspeed_tpu.comm as dist
        pp_rank = dist.get_rank()
        return os.path.join(checkpoints_path, str(tag),
                            f"zero_pp_rank_{pp_rank}_mp_rank_00" + OPTIM_FILE_SUFFIX)

    def save_checkpoint(self, save_dir, tag=None, client_state=None,
                        save_latest=True, data_iter=None, initiator="user"):
        """Shard-aware save: every process writes its addressable shards of
        params + optimizer state to its zero_pp_rank file (reference
        per-rank partition files, engine.py:2345); process 0 additionally
        writes metadata (and full params when it can address them) to the
        model-states file, the per-tag completeness manifest, and the
        'latest' tag pointer (engine.py:2889).

        Two-phase (CheckFreq snapshot-then-persist): the SNAPSHOT copies
        device state to host at the step boundary — the only phase the
        train loop (and the goodput ledger's ``checkpoint_save``
        category) pays for when ``checkpoint.async_save`` is on; the
        PERSIST phase (pickle + fsync + atomic rename + manifest) then
        runs on a background writer while training continues. A second
        save drains the in-flight one first, and a background write
        failure re-raises here (or at close()) rather than vanishing.

        ``data_iter``: a :class:`RepeatingLoader` (or anything exposing
        ``state_dict``) whose stream position is carried in the
        checkpoint, so a preempted run resumes its exact batch stream.

        ``initiator``: who asked for this save — ``"user"`` (default) or
        ``"guardian"`` for the policy engine's emergency saves. Carried
        on the checkpoint spans so a trace distinguishes the two."""
        if tag is None:
            tag = f"global_step{self.global_steps}"
        tag = str(tag)
        if self._guardian is not None and initiator == "user":
            # the guardian's emergency-save / rollback actions need a
            # checkpoint directory; the user's own saves teach it one
            self._guardian_ckpt_dir = save_dir
        if self._ckpt_writer is not None:
            # one save in flight, ever: drain the previous persist so two
            # saves can never interleave files or race the latest pointer
            # (the wait is honest checkpoint badput)
            with self._led_attr("checkpoint_save"):
                self._ckpt_writer.drain()
        with self._led_attr("checkpoint_save"), \
                self.telemetry.span("checkpoint/save", tag=tag,
                                    initiator=initiator):
            self._validate_checkpoint_tag(tag)
            os.makedirs(os.path.join(save_dir, tag), exist_ok=True)
            snapshot = self._snapshot_checkpoint(client_state, data_iter)
        if not self._ckpt_async:
            with self._led_attr("checkpoint_save"), \
                    self.telemetry.span("checkpoint/persist", tag=tag,
                                        initiator=initiator):
                self._persist_checkpoint(save_dir, tag, snapshot,
                                         save_latest)
            log_dist(f"saved checkpoint {save_dir}/{tag}", ranks=[0])
            self._chronicle_emit("checkpoint_save", tag=tag, dir=save_dir,
                                 initiator=initiator, mode="sync")
            return True
        reg = self.telemetry.registry
        if reg is not None:
            reg.counter("checkpoint_async_saves_total",
                        "async (snapshot-then-persist) saves started").inc()
        self._get_ckpt_writer().submit(
            lambda: self._persist_checkpoint(save_dir, tag, snapshot,
                                             save_latest), tag=tag)
        log_dist(f"checkpoint {save_dir}/{tag}: snapshot taken, "
                 f"persisting in background", ranks=[0])
        self._chronicle_emit("checkpoint_save", tag=tag, dir=save_dir,
                             initiator=initiator, mode="background")
        return True

    def _get_ckpt_writer(self):
        if self._ckpt_writer is None:
            from deepspeed_tpu.runtime.async_checkpoint import \
                AsyncCheckpointWriter
            self._ckpt_writer = AsyncCheckpointWriter(
                retries=self.config.checkpoint_persist_retries,
                backoff_s=self.config.checkpoint_persist_backoff_s)
        return self._ckpt_writer

    def _validate_checkpoint_tag(self, tag):
        if not self.config.checkpoint_tag_validation_enabled:
            return
        # reference _checkpoint_tag_validation (engine.py:2693) +
        # stage3's cross-rank consistency asserts: silently diverged
        # hosts must not write a mixed checkpoint. Collectives — always
        # on the main thread, never inside the background persist.
        from deepspeed_tpu.utils.debug import (
            assert_bytes_same_as_other_ranks,
            assert_ints_same_as_other_ranks,
            assert_shapes_same_as_other_ranks)
        try:
            assert_bytes_same_as_other_ranks(str(tag).encode(),
                                             tag="checkpoint-tag")
            assert_ints_same_as_other_ranks(
                [self.global_steps, self.micro_steps],
                tag="save_checkpoint")
            assert_shapes_same_as_other_ranks(self.state.params,
                                              tag="params")
        except AssertionError as e:
            if self.config.checkpoint_tag_validation_fail:
                raise
            log_dist(f"WARNING: cross-rank checkpoint mismatch "
                     f"({e}); writing anyway (validation mode Warn)",
                     ranks=[0])

    def _snapshot_checkpoint(self, client_state, data_iter):
        """Device->host snapshot of everything a save persists. With
        async_save the copies are FORCED (``copy=True`` / deepcopy): the
        train state is donated to the next step, so the background writer
        must own its bytes outright — a view into a donated buffer would
        pickle whatever the next step reused the memory for."""
        from deepspeed_tpu.runtime import checkpoint_io
        import deepspeed_tpu.comm as dist
        copy = self._ckpt_async
        with self.telemetry.span("checkpoint/gather_shards"):
            offload_sd = (self._offload_opt.state_dict()
                          if self._offload_opt else None)
            if copy and offload_sd is not None:
                import copy as _copy
                offload_sd = _copy.deepcopy(offload_sd)
            zero_sd = {
                "format": "shards-v1",
                "optimizer_state_dict": checkpoint_io.tree_local_shards(
                    self.state.opt_state, copy=copy),
                "offload_optimizer_state": offload_sd,
                "param_shards": checkpoint_io.tree_local_shards(
                    self.state.params, copy=copy),
                "scale_state": {k: np.array(jax.device_get(v), copy=True)
                                for k, v in
                                self.state.scale._asdict().items()},
                "zero_stage": self.zero_stage,
                "partition_count": self.dp_world_size,
            }
        snapshot = {"zero_sd": zero_sd, "params_tree": None, "meta": None}
        if dist.get_rank() != 0:
            return snapshot

        fully_addressable = all(
            getattr(x, "is_fully_addressable", True)
            for x in jax.tree.leaves(self.state.params))
        if fully_addressable:
            # the params are ALREADY host-side in param_shards — don't
            # copy them a second time on the critical path; the persist
            # phase reassembles the full model-states tree from the
            # shards (host numpy work, overlapped when async)
            paths, treedef = jax.tree_util.tree_flatten_with_path(
                self.state.params)
            snapshot["params_tree"] = (
                [jax.tree_util.keystr(p) for p, _ in paths], treedef)
        it_state = None
        if data_iter is not None:
            sd_fn = getattr(data_iter, "state_dict", None)
            if sd_fn is not None:
                it_state = sd_fn()
            else:
                logger.warning(
                    "save_checkpoint(data_iter=...): the iterator has no "
                    "state_dict(); the data-stream position is NOT saved "
                    "(wrap the loader in RepeatingLoader for "
                    "deterministic resume)")
        snapshot["meta"] = {
            "global_steps": self.global_steps,
            "global_samples": self.global_samples,
            "skipped_steps": self.skipped_steps,
            "micro_steps": self.micro_steps,
            "dp_world_size": self.dp_world_size,
            "mp_world_size": self.mp_world_size,
            "loss_scale": float(np.asarray(
                jax.device_get(self.state.scale.loss_scale))),
            "lr_scheduler": (self.lr_scheduler.state_dict()
                             if self.lr_scheduler else None),
            "data_iterator": it_state,
            "ds_config": self.config._param_dict,
            "ds_version": "tpu-0.1",
            "client_state": client_state or {},
        }
        return snapshot

    def _persist_checkpoint(self, save_dir, tag, snapshot, save_latest):
        """File half of a save — pure host I/O over the snapshot's
        numpy, safe on the background writer thread (no device access,
        no collectives). Durability order is the crash-consistency
        contract: per-rank shard files (each atomic), model states,
        THEN — after every rank's shard file exists — the completeness
        manifest, and only then the ``latest`` pointer. A kill anywhere
        leaves the previous checkpoint reachable and this tag
        detectably incomplete."""
        from deepspeed_tpu.runtime import checkpoint_io
        import deepspeed_tpu.comm as dist
        tag_dir = os.path.join(save_dir, tag)
        checkpoint_io.dump_file(snapshot["zero_sd"],
                                self._get_zero_ckpt_name(save_dir, tag),
                                kind="zero_states")
        if snapshot["meta"] is None:       # not rank 0
            return
        meta = snapshot["meta"]
        model_np = None
        if snapshot["params_tree"] is not None:
            # reassemble the full params from the snapshotted shards
            # (bit-identical to a direct device_get: the shards carry
            # their global indices)
            pstrs, treedef = snapshot["params_tree"]
            merged = checkpoint_io.assemble([snapshot["zero_sd"]
                                             ["param_shards"]])
            model_np = jax.tree_util.tree_unflatten(
                treedef, [merged[p] for p in pstrs])
        # MoE expert params get the reference's per-expert file layout
        # (engine.py:2780 _save_moe_checkpoint): one
        # layer_{L}_expert_{E}_mp_rank_XX file per global expert, with the
        # non-moe state in the model-states file
        moe_prefixes, moe_counts = [], []
        if model_np is not None and isinstance(model_np, dict):
            model_np, moe_prefixes, moe_counts = \
                checkpoint_io.save_moe_experts(tag_dir, model_np)
        sd = {
            "module": model_np,
            "has_moe_layers": bool(moe_prefixes),
            "moe_layer_prefixes": moe_prefixes,
            "moe_expert_counts": moe_counts,
            **meta,
        }
        checkpoint_io.dump_file(sd, self._get_ckpt_name(save_dir, tag),
                                kind="model_states")
        # durability gate: all ranks' shard files, via the shared
        # filesystem (file polling, deliberately collective-free — this
        # may be a background thread)
        n_proc = dist.get_process_count()
        expected = [os.path.join(
            tag_dir, f"zero_pp_rank_{r}_mp_rank_00" + OPTIM_FILE_SUFFIX)
            for r in range(n_proc)]
        checkpoint_io.wait_for_files(
            expected, timeout_s=self.config.checkpoint_wait_timeout_s,
            describe=f"all {n_proc} ranks' shard files of tag {tag!r}")
        # re-saving an existing tag from a SMALLER world must not leave
        # the old run's extra rank files behind: load's zero_pp_rank_*
        # glob would mix shards from two different optimizer states, and
        # the manifest below would certify the mix as intact
        import glob as _glob
        import re as _re
        for f in _glob.glob(os.path.join(
                tag_dir, "zero_pp_rank_*" + OPTIM_FILE_SUFFIX)):
            m = _re.search(r"zero_pp_rank_(\d+)_", os.path.basename(f))
            if m and int(m.group(1)) >= n_proc:
                os.remove(f)
        checkpoint_io.write_manifest(tag_dir, meta={
            "tag": tag,
            "global_steps": meta["global_steps"],
            "dp_world_size": meta["dp_world_size"],
            "processes": n_proc,
        })
        if save_latest:
            checkpoint_io.write_latest(save_dir, LATEST_FILE, tag)

    def _verify_load_tag(self, load_dir, tag, explicit_tag):
        """Gate every load on the tag's completeness manifest. An intact
        tag passes; a legacy (manifest-less) tag loads with a warning
        (per-file atomicity still rules out truncated pickles); a
        missing/empty/corrupt tag raises a clear error naming the tag
        and directory — or, for implicit (``latest``-resolved) loads
        with ``checkpoint.fallback_to_intact`` on, recovers to the
        newest intact tag."""
        from deepspeed_tpu.runtime import checkpoint_io
        tag_dir = os.path.join(load_dir, tag)
        status, detail = checkpoint_io.verify_tag(tag_dir)
        if status == "intact":
            return tag
        if status == "legacy":
            logger.warning(
                f"checkpoint tag {tag!r} at {tag_dir} has no completeness "
                f"manifest ({detail}); loading with per-file checks only")
            return tag
        source = ("requested" if explicit_tag
                  else "named by the 'latest' pointer")
        msg = (f"checkpoint tag {tag!r} ({source}) at {tag_dir} is not "
               f"loadable: {detail}")
        if explicit_tag or not self.config.checkpoint_fallback:
            raise (FileNotFoundError(msg) if status == "missing"
                   else RuntimeError(msg))
        fallback = checkpoint_io.newest_intact_tag(load_dir, exclude=(tag,))
        if fallback is None:
            raise (FileNotFoundError if status == "missing"
                   else RuntimeError)(
                msg + "; no intact fallback tag exists under "
                + str(load_dir))
        logger.warning(f"{msg}; falling back to the newest intact tag "
                       f"{fallback!r}")
        return fallback

    def load_checkpoint(self, load_dir, tag=None, load_module_strict=True,
                        load_optimizer_states=True, load_lr_scheduler_states=True,
                        load_module_only=False, data_iter=None):
        # the WHOLE restore interval books as checkpoint_load badput:
        # shard reassembly and device_put after the file reads used to
        # land in the unattributed residual (attribution is nesting-safe
        # — the inner read intervals just shrink this one's self time)
        with self._led_attr("checkpoint_load"):
            return self._load_checkpoint(
                load_dir, tag=tag, load_module_strict=load_module_strict,
                load_optimizer_states=load_optimizer_states,
                load_lr_scheduler_states=load_lr_scheduler_states,
                load_module_only=load_module_only, data_iter=data_iter)

    def _load_checkpoint(self, load_dir, tag=None, load_module_strict=True,
                         load_optimizer_states=True,
                         load_lr_scheduler_states=True,
                         load_module_only=False, data_iter=None):
        if self._ckpt_writer is not None:
            # an in-flight async save must be durable before tags are
            # read — and its failure must surface here, not be read over
            with self._led_attr("checkpoint_load"):
                self._ckpt_writer.drain()
        explicit_tag = tag is not None
        if tag is None:
            latest = os.path.join(load_dir, LATEST_FILE)
            if not os.path.isfile(latest):
                logger.warning(f"no 'latest' file at {latest}; nothing loaded")
                return None, {}
            with open(latest) as f:
                tag = f.read().strip()
        tag = self._verify_load_tag(load_dir, str(tag), explicit_tag)

        from deepspeed_tpu.runtime import checkpoint_io
        import glob as _glob
        path = self._get_ckpt_name(load_dir, tag)
        with self._led_attr("checkpoint_load"), \
                self.telemetry.span("checkpoint/load", tag=str(tag)):
            sd = checkpoint_io.load_file(path, kind="model_states")
            zero_paths = sorted(_glob.glob(os.path.join(
                load_dir, str(tag), "zero_pp_rank_*" + OPTIM_FILE_SUFFIX)))
            zero_payloads = [checkpoint_io.load_file(p, kind="zero_states")
                             for p in zero_paths]
        saved_dp = (zero_payloads[0].get("partition_count")
                    if zero_payloads else None)
        if saved_dp is not None and saved_dp != self.dp_world_size:
            # elastic resize (reference stage_1_and_2.py:2023
            # _restore_from_elastic_fp32_weights / the 'universal
            # checkpoint' load path): shards carry their GLOBAL indices,
            # so restore_tree reassembles the full tree from the saved
            # world size and re-slices it onto the current one — every
            # checkpoint here is 'universal'; load_universal_checkpoint
            # is honored by construction.
            log_dist(
                f"elastic checkpoint load: saved at dp={saved_dp}, "
                f"resuming at dp={self.dp_world_size} (shard reassembly)",
                ranks=[0])
            self._chronicle_emit(
                "elastic_resume", tag=str(tag), saved_dp=int(saved_dp),
                dp=int(self.dp_world_size),
                detail=f"shard reassembly dp={saved_dp}->"
                       f"{self.dp_world_size}")

        if sd.get("module") is not None:
            module_np = sd["module"]
            if sd.get("has_moe_layers"):
                module_np = checkpoint_io.restore_moe_experts(
                    os.path.join(load_dir, str(tag)), module_np,
                    sd.get("moe_layer_prefixes", []),
                    expert_counts=sd.get("moe_expert_counts"))
            params = jax.device_put(module_np, self.param_shardings)
        else:
            # reassemble sharded params from the per-process files
            params = checkpoint_io.restore_tree(
                self.state.params,
                [z["param_shards"] for z in zero_payloads],
                self.param_shardings)
        new_state = self.state._replace(params=params)

        client_state = sd.get("client_state", {})
        if not load_module_only:
            self.global_steps = sd.get("global_steps", 0)
            self.global_samples = sd.get("global_samples", 0)
            self.skipped_steps = sd.get("skipped_steps", 0)
            self.micro_steps = sd.get("micro_steps", 0)
            # state.step counts APPLIED steps only (it indexes the LR
            # schedule), so skipped steps must be subtracted on restore.
            new_state = new_state._replace(
                step=jnp.asarray(self.global_steps - self.skipped_steps,
                                 jnp.int32),
                scale=new_state.scale._replace(
                    loss_scale=jnp.float32(sd.get("loss_scale", 1.0))))
            if load_lr_scheduler_states and self.lr_scheduler is not None \
                    and sd.get("lr_scheduler") is not None:
                self.lr_scheduler.load_state_dict(sd["lr_scheduler"])

            if load_optimizer_states:
                if not zero_payloads:
                    logger.warning(
                        f"no zero_pp_rank files under {load_dir}/{tag}; "
                        f"resuming with FRESH optimizer state and loss scale")
                elif self._offload:
                    # host-optimizer moments are SHARD-LOCAL: restore only
                    # from THIS process's own zero file; another rank's
                    # moments belong to different param slices. Routed
                    # through checkpoint_io.load_file so this read gets
                    # the same span / byte-counter / ledger attribution
                    # as every other checkpoint read (it used to be a
                    # bare open()+pickle.load, invisible to telemetry)
                    own = self._get_zero_ckpt_name(load_dir, tag)
                    if os.path.isfile(own):
                        self._pending_offload_sd = checkpoint_io.load_file(
                            own, kind="zero_states").get(
                                "offload_optimizer_state")
                    else:
                        logger.warning(
                            f"offload moments for this rank missing "
                            f"({own}); resuming with FRESH moments")
                        self._pending_offload_sd = None
                elif zero_payloads[0].get("format") != "shards-v1":
                    # pre-shard-format checkpoint: raw pytree per file
                    opt_state = jax.device_put(
                        jax.tree.map(jnp.asarray,
                                     zero_payloads[0]["optimizer_state_dict"]),
                        self.opt_shardings)
                    new_state = new_state._replace(opt_state=opt_state)
                else:
                    opt_state = checkpoint_io.restore_tree(
                        self.state.opt_state,
                        [z["optimizer_state_dict"] for z in zero_payloads],
                        self.opt_shardings)
                    new_state = new_state._replace(opt_state=opt_state)
                # full dynamic-scaler state so a resumed run is
                # bit-identical to an uninterrupted one (all formats)
                ss = (zero_payloads[0].get("scale_state")
                      if zero_payloads else None)
                if ss is not None:
                    new_state = new_state._replace(
                        scale=LossScaleState(
                            loss_scale=jnp.float32(ss["loss_scale"]),
                            good_steps=jnp.int32(ss["good_steps"]),
                            hysteresis=jnp.int32(ss["hysteresis"])))

            # deterministic data-pipeline resume: rewind the caller's
            # loader to the exact (epoch, batch offset) the save
            # captured — composes with the prefetcher (the skip lives in
            # the index plan) and set_epoch shuffle semantics
            if data_iter is not None:
                it_state = sd.get("data_iterator")
                restore = getattr(data_iter, "load_state_dict", None)
                if it_state is None:
                    logger.warning(
                        "load_checkpoint(data_iter=...): the checkpoint "
                        "carries no data-iterator state (saved without "
                        "data_iter=); the stream is NOT rewound")
                elif restore is None:
                    logger.warning(
                        "load_checkpoint(data_iter=...): the iterator has "
                        "no load_state_dict(); the stream is NOT rewound")
                else:
                    restore(it_state)

        self.state = new_state
        if self._offload:
            # rebuild host masters from the freshly loaded params, then
            # restore the host optimizer moments
            self._offload_opt = self._make_offload_optimizer()
            sd_off = getattr(self, "_pending_offload_sd", None)
            if sd_off is not None:
                self._offload_opt.load_state_dict(sd_off)
                self._pending_offload_sd = None
        log_dist(f"loaded checkpoint {load_dir}/{tag}", ranks=[0])
        # after the counters are restored: the event's step IS the
        # resumed position, which is what a timeline reader wants
        self._chronicle_emit("checkpoint_load", tag=str(tag),
                             dir=load_dir)
        return path, client_state

    # ------------------------------------------------- consolidated exports
    def _consolidated_16bit_state_dict(self):
        """Gathered bit16 copy of the params (reference
        _zero3_consolidated_16bit_state_dict, engine.py:3025)."""
        dtype = (jnp.bfloat16 if self.compute_dtype == jnp.float32
                 else self.compute_dtype)
        fully_addressable = all(
            getattr(x, "is_fully_addressable", True)
            for x in jax.tree.leaves(self.state.params))
        if fully_addressable:
            gathered = jax.device_get(self.state.params)
        else:
            # multi-host ZeRO-3: all-gather across processes first
            from jax.experimental import multihost_utils
            gathered = multihost_utils.process_allgather(self.state.params)
        return jax.tree.map(
            lambda x: np.asarray(x).astype(dtype)
            if np.issubdtype(np.asarray(x).dtype, np.floating) else
            np.asarray(x), gathered)

    def save_16bit_model(self, save_dir, save_filename="pytorch_model.bin"):
        """Reference engine.save_16bit_model (engine.py:3098): one
        consolidated bit16 weight file for HF-style interchange."""
        import deepspeed_tpu.comm as dist
        from deepspeed_tpu.runtime import checkpoint_io
        os.makedirs(save_dir, exist_ok=True)
        if dist.get_rank() == 0:
            with self._led_attr("checkpoint_save"), \
                    self.telemetry.span("checkpoint/save_16bit_model"):
                checkpoint_io.dump_file(
                    self._consolidated_16bit_state_dict(),
                    os.path.join(save_dir, save_filename), kind="bit16")
        return True
