"""Shard-aware checkpoint serialization.

The reference persists per-rank partition files
(``zero_pp_rank_X_mp_rank_XX_optim_states.pt`` — engine.py:2345) because
each rank owns a slice of the flat fp32 partition. The jax analogue: every
process saves only its ADDRESSABLE shards of each ``jax.Array`` (with the
global index of each shard), and load reassembles from whichever files
cover the global shape, then ``device_put``s onto the target shardings.
Single-process saves degenerate to one file holding full arrays;
dp-resharded loads (elastic resume, reference stage_1_and_2.py:2023) work
because reassembly is index-based, not rank-based.
"""

import pickle
from typing import Any, Callable, Dict, List, Tuple

import jax
import numpy as np


def _index_to_key(index, shape) -> Tuple:
    """Normalise a shard index (tuple of slices) to a hashable key."""
    key = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        key.append((start, stop))
    return tuple(key)


def tree_local_shards(tree) -> Dict[str, dict]:
    """{leaf_path: {"shape", "dtype", "shards": [(key, ndarray)]}} for the
    shards addressable by THIS process (deduplicated by index)."""
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    out = {}
    for path, leaf in flat:
        pstr = jax.tree_util.keystr(path)
        if not isinstance(leaf, jax.Array):
            out[pstr] = {"shape": getattr(leaf, "shape", ()),
                         "dtype": str(getattr(leaf, "dtype", "float32")),
                         "shards": [((), np.asarray(leaf))]}
            continue
        shards = []
        seen = set()
        for shard in leaf.addressable_shards:
            key = _index_to_key(shard.index, leaf.shape)
            if key in seen:      # replicated copies: save once
                continue
            seen.add(key)
            shards.append((key, np.asarray(shard.data)))
        out[pstr] = {"shape": tuple(leaf.shape), "dtype": str(leaf.dtype),
                     "shards": shards}
    return out


def save_tree(tree, path: str):
    with open(path, "wb") as f:
        pickle.dump(tree_local_shards(tree), f)


def assemble(files_payloads: List[Dict[str, dict]]) -> Dict[str, np.ndarray]:
    """Merge shard payloads (from one or more files) into full ndarrays."""
    merged: Dict[str, np.ndarray] = {}
    filled: Dict[str, np.ndarray] = {}
    for payload in files_payloads:
        for pstr, rec in payload.items():
            shape = tuple(rec["shape"])
            if pstr not in merged:
                merged[pstr] = np.zeros(shape, dtype=rec["dtype"])
                filled[pstr] = np.zeros(shape, dtype=bool) if shape else \
                    np.zeros((), dtype=bool)
            for key, data in rec["shards"]:
                if key == ():
                    merged[pstr] = np.asarray(data)
                    filled[pstr] = np.ones_like(filled[pstr])
                    continue
                slices = tuple(slice(a, b) for a, b in key)
                merged[pstr][slices] = data
                filled[pstr][slices] = True
    for pstr, mask in filled.items():
        if not mask.all():
            raise ValueError(
                f"checkpoint incomplete: leaf {pstr} missing shards "
                f"({mask.sum()}/{mask.size} elements covered)")
    return merged


def restore_tree(template, files_payloads: List[Dict[str, dict]],
                 shardings=None):
    """Rebuild a pytree shaped like *template* from shard payloads; put
    leaves onto *shardings* (same-structure pytree) when given."""
    merged = assemble(files_payloads)
    flat_t = jax.tree_util.tree_flatten_with_path(template)
    leaves = []
    for path, leaf in flat_t[0]:
        pstr = jax.tree_util.keystr(path)
        if pstr not in merged:
            raise KeyError(f"checkpoint missing leaf {pstr}")
        leaves.append(merged[pstr])
    tree = jax.tree_util.tree_unflatten(flat_t[1], leaves)
    if shardings is not None:
        tree = jax.device_put(tree, shardings)
    return tree


def load_payload(path: str) -> Dict[str, dict]:
    with open(path, "rb") as f:
        return pickle.load(f)
