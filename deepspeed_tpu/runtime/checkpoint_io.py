"""Shard-aware checkpoint serialization.

The reference persists per-rank partition files
(``zero_pp_rank_X_mp_rank_XX_optim_states.pt`` — engine.py:2345) because
each rank owns a slice of the flat fp32 partition. The jax analogue: every
process saves only its ADDRESSABLE shards of each ``jax.Array`` (with the
global index of each shard), and load reassembles from whichever files
cover the global shape, then ``device_put``s onto the target shardings.
Single-process saves degenerate to one file holding full arrays;
dp-resharded loads (elastic resume, reference stage_1_and_2.py:2023) work
because reassembly is index-based, not rank-based.
"""

import json
import os
import pickle
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import numpy as np

from deepspeed_tpu.telemetry import trace_span
from deepspeed_tpu.telemetry.ledger import get_ledger
from deepspeed_tpu.telemetry.metrics import get_registry

# every durable artifact goes through tmp-file + fsync + atomic rename, so
# a file either exists COMPLETE or not at all — a crash can truncate only
# a ``*.tmp.<pid>`` sibling, which every reader here ignores
_TMP_MARK = ".tmp."

MANIFEST_FILE = "manifest.json"
MANIFEST_SCHEMA = "deepspeed_tpu.ckpt_manifest/1"


def _fsync_dir(dirname: str):
    """Durability for the rename itself: fsync the containing directory
    (best-effort — not every filesystem hands out dir fds)."""
    try:
        fd = os.open(dirname or ".", os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _atomic_write(path: str, write_fn) -> int:
    """Write via ``write_fn(fileobj)`` to a tmp sibling, fsync, then
    atomically rename into place. Returns the written byte count."""
    tmp = f"{path}{_TMP_MARK}{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            write_fn(f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    finally:
        # failure cleanup only — after the rename the tmp name is gone.
        # (A real SIGKILL leaves the stray tmp behind; readers skip it.)
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass
    _fsync_dir(os.path.dirname(path))
    return os.path.getsize(path)


def dump_file(obj, path: str, kind: str = "checkpoint") -> int:
    """``pickle.dump`` wrapped in an I/O trace span, with the written
    bytes counted into ``checkpoint_write_bytes_total{kind=...}``. All
    checkpoint writers (engine + this module) route through here so the
    telemetry byte accounting covers every file of a save. The goodput
    ledger books the same interval as ``checkpoint_save`` wall time
    (nesting-safe under the engine's own checkpoint attribution).

    Crash-consistent: the bytes land in a tmp sibling, are fsynced, and
    renamed into place — a kill mid-write can never leave a truncated
    pickle under the real name for ``load_file`` to explode on."""
    with get_ledger().attribute("checkpoint_save"), \
            trace_span(f"checkpoint/write/{kind}",
                       path=os.path.basename(path)):
        nbytes = _atomic_write(path, lambda f: pickle.dump(obj, f))
    get_registry().counter("checkpoint_write_bytes_total",
                           "bytes written by checkpoint saves",
                           labels={"kind": kind}).inc(nbytes)
    return nbytes


def load_file(path: str, kind: str = "checkpoint"):
    """``pickle.load`` counterpart of ``dump_file`` (read span + bytes)."""
    with get_ledger().attribute("checkpoint_load"), \
            trace_span(f"checkpoint/read/{kind}",
                       path=os.path.basename(path)):
        with open(path, "rb") as f:
            obj = pickle.load(f)
    get_registry().counter("checkpoint_read_bytes_total",
                           "bytes read by checkpoint loads",
                           labels={"kind": kind}).inc(os.path.getsize(path))
    return obj


# ---------------------------------------------------------------------------
# Tag completeness: a manifest written LAST (after every rank's files are
# durable) makes "this tag is loadable" a checked property instead of a
# hope. The ``latest`` pointer only moves after the manifest exists, so a
# crash at ANY point of a save leaves the previous checkpoint reachable
# and the half-written tag detectably incomplete (CheckFreq-style
# snapshot-then-persist needs exactly this: the persist phase can die at
# any file boundary).
# ---------------------------------------------------------------------------


def write_manifest(tag_dir: str, meta: Optional[dict] = None) -> dict:
    """Write the per-tag completeness manifest (atomically, LAST): every
    durable file in *tag_dir* with its byte size. ``meta`` (tag,
    world sizes, step counters) is merged in for the fallback scan."""
    files = {}
    for name in sorted(os.listdir(tag_dir)):
        if name == MANIFEST_FILE or _TMP_MARK in name:
            continue
        path = os.path.join(tag_dir, name)
        if os.path.isfile(path):
            files[name] = os.path.getsize(path)
    doc = {"schema": MANIFEST_SCHEMA, "files": files}
    doc.update(meta or {})
    payload = json.dumps(doc, indent=2, sort_keys=True).encode()
    _atomic_write(os.path.join(tag_dir, MANIFEST_FILE),
                  lambda f: f.write(payload))
    return doc


def load_manifest(tag_dir: str):
    path = os.path.join(tag_dir, MANIFEST_FILE)
    if not os.path.isfile(path):
        return None
    with open(path) as f:
        return json.load(f)


def verify_tag(tag_dir: str) -> Tuple[str, str]:
    """Is the tag at *tag_dir* loadable? Returns ``(status, detail)``:

    * ``"intact"``  — manifest present, every listed file exists at its
      recorded size;
    * ``"legacy"``  — files but no manifest (a pre-manifest-era save, or
      one interrupted before the manifest — indistinguishable; per-file
      atomicity still rules out truncated pickles);
    * ``"missing"`` — no directory, or an empty one;
    * ``"corrupt"`` — manifest present but contradicted on disk.
    """
    if not os.path.isdir(tag_dir):
        return "missing", "no such directory"
    entries = [n for n in os.listdir(tag_dir) if _TMP_MARK not in n]
    if not entries:
        return "missing", "directory is empty"
    if MANIFEST_FILE not in entries:
        return "legacy", ("no completeness manifest (pre-manifest save or "
                          "a save interrupted before the manifest write)")
    try:
        doc = load_manifest(tag_dir)
        files = doc["files"]
    except Exception as e:
        return "corrupt", f"manifest unreadable: {e}"
    mismatch = _manifest_mismatch(tag_dir, files)
    if mismatch:
        return "corrupt", mismatch
    return "intact", ""


def _manifest_mismatch(tag_dir, files):
    """First contradiction between a manifest's file map and the disk
    (None when everything checks out)."""
    for name, size in files.items():
        path = os.path.join(tag_dir, name)
        if not os.path.isfile(path):
            return f"manifest lists {name!r} but it is missing"
        if size is not None and os.path.getsize(path) != size:
            return (f"{name!r} is {os.path.getsize(path)} bytes but the "
                    f"manifest recorded {size}")
    return None


def newest_intact_tag(load_dir: str, exclude=()):
    """The newest manifest-verified tag under *load_dir* (by recorded
    global step, then manifest mtime) — the fallback target when the
    ``latest`` pointer names a broken tag. ``None`` when nothing intact
    exists. Legacy (manifest-less) tags are never chosen: they cannot be
    distinguished from an interrupted save."""
    exclude = set(str(t) for t in (exclude or ()))
    best = None
    try:
        names = os.listdir(load_dir)
    except OSError:
        return None
    for name in names:
        if name in exclude:
            continue
        tag_dir = os.path.join(load_dir, name)
        if not os.path.isdir(tag_dir):
            continue
        try:
            doc = load_manifest(tag_dir)
            files = doc["files"]
        except Exception:
            continue        # no/unreadable manifest: not a candidate
        if _manifest_mismatch(tag_dir, files):
            continue
        key = (doc.get("global_steps", -1),
               os.path.getmtime(os.path.join(tag_dir, MANIFEST_FILE)))
        if best is None or key > best[0]:
            best = (key, name)
    return best[1] if best else None


def write_latest(save_dir: str, latest_file: str, tag: str):
    """Atomically update the ``latest`` pointer — readers see the old tag
    or the new one, never a torn write."""
    payload = str(tag).encode()
    _atomic_write(os.path.join(save_dir, latest_file),
                  lambda f: f.write(payload))


def wait_for_files(paths, timeout_s: float = 300.0, poll_s: float = 0.05,
                   describe: str = "checkpoint files"):
    """Block until every path exists (rank 0's durability gate before the
    manifest: other ranks' shard files appear via their own atomic
    renames — file-based coordination, deliberately collective-free so
    it is safe on the async writer's background thread)."""
    deadline = time.monotonic() + timeout_s
    missing = [p for p in paths if not os.path.isfile(p)]
    while missing:
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"timed out after {timeout_s:.0f}s waiting for {describe}: "
                f"missing {[os.path.basename(p) for p in missing[:4]]}"
                f"{' ...' if len(missing) > 4 else ''}")
        time.sleep(poll_s)
        missing = [p for p in missing if not os.path.isfile(p)]


def _index_to_key(index, shape) -> Tuple:
    """Normalise a shard index (tuple of slices) to a hashable key."""
    key = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        key.append((start, stop))
    return tuple(key)


def tree_local_shards(tree, copy: bool = False) -> Dict[str, dict]:
    """{leaf_path: {"shape", "dtype", "shards": [(key, ndarray)]}} for the
    shards addressable by THIS process (deduplicated by index).

    ``copy=True`` forces a host-owned copy of every shard — required when
    the payload outlives this call while training continues (the async
    checkpoint snapshot): the engine's train state is DONATED to the next
    step, and on the CPU backend ``np.asarray`` of a jax array may alias
    the device buffer, so a background writer pickling a view would read
    memory the next step already reused."""
    conv = (lambda x: np.array(x, copy=True)) if copy else np.asarray
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    out = {}
    for path, leaf in flat:
        pstr = jax.tree_util.keystr(path)
        if not isinstance(leaf, jax.Array):
            out[pstr] = {"shape": getattr(leaf, "shape", ()),
                         "dtype": str(getattr(leaf, "dtype", "float32")),
                         "shards": [((), conv(leaf))]}
            continue
        shards = []
        seen = set()
        for shard in leaf.addressable_shards:
            key = _index_to_key(shard.index, leaf.shape)
            if key in seen:      # replicated copies: save once
                continue
            seen.add(key)
            shards.append((key, conv(shard.data)))
        out[pstr] = {"shape": tuple(leaf.shape), "dtype": str(leaf.dtype),
                     "shards": shards}
    return out


def save_tree(tree, path: str):
    with trace_span("checkpoint/shard_tree"):
        payload = tree_local_shards(tree)
    dump_file(payload, path, kind="shards")


def assemble(files_payloads: List[Dict[str, dict]]) -> Dict[str, np.ndarray]:
    """Merge shard payloads (from one or more files) into full ndarrays."""
    merged: Dict[str, np.ndarray] = {}
    filled: Dict[str, np.ndarray] = {}
    for payload in files_payloads:
        for pstr, rec in payload.items():
            shape = tuple(rec["shape"])
            if pstr not in merged:
                merged[pstr] = np.zeros(shape, dtype=rec["dtype"])
                filled[pstr] = np.zeros(shape, dtype=bool) if shape else \
                    np.zeros((), dtype=bool)
            for key, data in rec["shards"]:
                if key == ():
                    merged[pstr] = np.asarray(data)
                    filled[pstr] = np.ones_like(filled[pstr])
                    continue
                slices = tuple(slice(a, b) for a, b in key)
                merged[pstr][slices] = data
                filled[pstr][slices] = True
    for pstr, mask in filled.items():
        if not mask.all():
            raise ValueError(
                f"checkpoint incomplete: leaf {pstr} missing shards "
                f"({mask.sum()}/{mask.size} elements covered)")
    return merged


def restore_tree(template, files_payloads: List[Dict[str, dict]],
                 shardings=None):
    """Rebuild a pytree shaped like *template* from shard payloads; put
    leaves onto *shardings* (same-structure pytree) when given."""
    merged = assemble(files_payloads)
    flat_t = jax.tree_util.tree_flatten_with_path(template)
    leaves = []
    for path, leaf in flat_t[0]:
        pstr = jax.tree_util.keystr(path)
        if pstr not in merged:
            raise KeyError(f"checkpoint missing leaf {pstr}")
        leaves.append(merged[pstr])
    tree = jax.tree_util.tree_unflatten(flat_t[1], leaves)
    if shardings is not None:
        tree = jax.device_put(tree, shardings)
    return tree


def load_payload(path: str) -> Dict[str, dict]:
    return load_file(path, kind="shards")


# ---------------------------------------------------------------------------
# MoE expert file layout (reference engine.py:2780 _save_moe_checkpoint /
# :2381 _get_expert_ckpt_name): each MoE layer's experts are saved one file
# per GLOBAL expert id as
# ``layer_{L}_expert_{E}_mp_rank_{MP:02d}_model_states.pt``, and the
# model-states file keeps only the non-expert ("non-moe") state. Here the
# stacked [E, ...] expert leaves are sliced per expert on save and
# re-stacked on load.
# ---------------------------------------------------------------------------

MOE_EXPERT_KEY = "deepspeed_experts"


def moe_expert_file(tag_dir, layer_id, expert_id, mp_rank=0):
    import os
    return os.path.join(
        tag_dir,
        f"layer_{layer_id}_expert_{expert_id}_mp_rank_{mp_rank:02d}"
        "_model_states.pt")


def _walk_paths(tree, prefix=()):
    if isinstance(tree, dict):
        for k, v in tree.items():
            yield from _walk_paths(v, prefix + (str(k),))
    else:
        yield "/".join(prefix), tree


def split_moe_state(params_np):
    """(non_moe_tree, prefixes, experts) where ``experts`` maps
    layer_id -> {path_under_model: stacked [E, ...] array} and
    ``prefixes`` orders the MoE layers (the reference's named_modules walk
    order becomes sorted path order)."""
    by_prefix = {}
    for path, leaf in _walk_paths(params_np):
        if MOE_EXPERT_KEY in path.split("/"):
            prefix = path.split("/" + MOE_EXPERT_KEY)[0]
            by_prefix.setdefault(prefix, {})[path] = leaf
    prefixes = sorted(by_prefix)

    def strip(tree):
        if isinstance(tree, dict):
            return {k: strip(v) for k, v in tree.items()
                    if str(k) != MOE_EXPERT_KEY}
        return tree

    return strip(params_np), prefixes, [by_prefix[p] for p in prefixes]


def save_moe_experts(tag_dir, params_np, mp_rank=0):
    """Write the per-expert files; returns (non_moe_tree, prefixes,
    expert_counts) for the model-states metadata. Stale expert files from
    a previous save of the same tag are removed first (re-saving a fixed
    tag with fewer experts must not leave orphans for restore to glob)."""
    import glob as _glob
    import os
    non_moe, prefixes, experts = split_moe_state(params_np)
    if experts:
        # scope the cleanup to THIS mp_rank's files: with mp>1 every rank
        # saves into the same tag dir, and a rank-wide glob would delete
        # the other ranks' freshly written experts
        for f in _glob.glob(os.path.join(
                tag_dir,
                f"layer_*_expert_*_mp_rank_{mp_rank:02d}_model_states.pt")):
            os.remove(f)
    counts = []
    with trace_span("checkpoint/save_moe_experts"):
        for lid, layer in enumerate(experts):
            num = next(iter(layer.values())).shape[0]
            counts.append(num)
            for eid in range(num):
                sd = {path: np.asarray(leaf[eid])
                      for path, leaf in layer.items()}
                dump_file(sd, moe_expert_file(tag_dir, lid, eid, mp_rank),
                          kind="moe_expert")
    return non_moe, prefixes, counts


def restore_moe_experts(tag_dir, module_np, prefixes, mp_rank=0,
                        expert_counts=None):
    """Re-stack the per-expert files into the module tree (inverse of
    save_moe_experts). ``module_np`` is the stripped non-moe tree; returns
    a tree with the ``deepspeed_experts`` subtrees back in place.

    Expert ids must be contiguous from 0 (a missing file would otherwise
    silently index-shift every later expert); when ``expert_counts`` (from
    the checkpoint metadata) is given, the file count must match it."""
    import glob as _glob
    import os
    import re

    for lid in range(len(prefixes)):
        pat = os.path.join(
            tag_dir, f"layer_{lid}_expert_*_mp_rank_{mp_rank:02d}"
            "_model_states.pt")
        files = _glob.glob(pat)
        if not files:
            raise FileNotFoundError(
                f"MoE checkpoint is missing expert files: {pat}")
        by_eid = sorted(
            (int(re.search(r"_expert_(\d+)_", os.path.basename(f)).group(1)),
             f) for f in files)
        eids = [e for e, _ in by_eid]
        if eids != list(range(len(eids))):
            raise FileNotFoundError(
                f"MoE checkpoint layer {lid} has non-contiguous expert "
                f"files (ids {eids}); a partial checkpoint would silently "
                "index-shift experts")
        if expert_counts is not None and len(eids) != expert_counts[lid]:
            raise FileNotFoundError(
                f"MoE checkpoint layer {lid} has {len(eids)} expert files "
                f"but the checkpoint metadata records "
                f"{expert_counts[lid]} experts")
        payloads = [load_file(f, kind="moe_expert") for _, f in by_eid]
        for path in payloads[0]:
            stacked = np.stack([p[path] for p in payloads], axis=0)
            node = module_np
            parts = path.split("/")
            for k in parts[:-1]:
                node = node.setdefault(k, {})
            node[parts[-1]] = stacked
    return module_np
