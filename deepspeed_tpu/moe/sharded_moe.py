"""GShard-style top-k gating and the sharded MoE layer.

TPU-native rebuild of deepspeed/moe/sharded_moe.py (``top1gating`` :170,
``top2gating`` :271, ``TopKGate`` :343, ``MOELayer`` :473). The gating math
is identical tensor algebra; the transport differs: the reference wraps
``dist.all_to_all_single`` in an autograd function (``_AllToAll`` :84),
while here the dispatched [E, C, M] tensor carries a
``with_sharding_constraint(P("expert", ...))`` and XLA lowers the
resharding to an ICI all-to-all (and its transpose in the backward pass) —
the GSPMD formulation of the same exchange.

Capacity is static (derived from shapes), so the whole layer jits with
fixed shapes; token overflow drops follow the reference's policy.
"""

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.utils import groups


def _capacity(num_tokens: int, num_experts: int, capacity_factor: float,
              min_capacity: int) -> int:
    """Static per-expert capacity (reference sharded_moe.py:120)."""
    cap = int(np.ceil(num_tokens / num_experts * capacity_factor))
    return max(cap, min_capacity)


def _one_hot(idx, num):
    return jax.nn.one_hot(idx, num, dtype=jnp.float32)


def _expert_constraint(x):
    """Shard dim 0 (experts) over the expert mesh axis when a mesh is
    active — this is the all-to-all insertion point."""
    if not groups.mesh_is_initialized():
        return x
    mesh = groups.get_mesh()
    if mesh.shape[groups.EXPERT_AXIS] == 1:
        return x
    spec = P(groups.EXPERT_AXIS, *([None] * (x.ndim - 1)))
    return jax.lax.with_sharding_constraint(
        x, jax.sharding.NamedSharding(mesh, spec))


def top1gating(logits, capacity_factor=1.0, min_capacity=4,
               noisy_gate_policy: Optional[str] = None, noise_rng=None,
               drop_tokens=True, use_rts=True, used_token=None,
               sparse=False):
    """Top-1 gating (reference sharded_moe.py:170).

    logits: [S, E]. Returns (l_aux, combine_weights [S,E,C],
    dispatch_mask [S,E,C] bool, exp_counts [E]); with ``sparse=True``
    the dense [S,E,C] tensors are never built and the routing comes back
    factored as (l_aux, [(expert_s, slot_s, gate_s, valid_s)], C,
    exp_counts) — same math, O(S) memory instead of O(S*E*C)."""
    S, E = logits.shape
    # drop_tokens=False must never drop: the reference grows capacity to
    # max(exp_counts) at runtime (sharded_moe.py:207); under jit capacity
    # must be static, so use the worst case (all tokens on one expert).
    C = S if not drop_tokens else _capacity(S, E, capacity_factor,
                                            min_capacity)

    if noisy_gate_policy == "RSample" and noise_rng is not None:
        logits_w_noise = logits + jax.random.normal(noise_rng, logits.shape)
    else:
        logits_w_noise = logits

    gates = jax.nn.softmax(logits, axis=1)
    indices1_s = jnp.argmax(logits_w_noise, axis=1)
    mask1 = _one_hot(indices1_s, E)
    if used_token is not None:
        mask1 = mask1 * used_token[:, None]

    exp_counts = jnp.sum(mask1, axis=0)

    # load-balancing auxiliary loss (GShard eq. 4; reference :225)
    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(mask1, axis=0)
    l_aux = jnp.sum(me * ce) * E

    # Random Token Selection: prioritise tokens by uniform noise instead of
    # sequence order when over capacity (reference :238-247)
    if use_rts and noise_rng is not None:
        rts_key = jax.random.fold_in(noise_rng, 1)
        priority = jax.random.uniform(rts_key, (S,))
    else:
        priority = -jnp.arange(S, dtype=jnp.float32)  # earlier tokens win

    # rank tokens per expert by priority: position of each token within its
    # expert's queue (stable ordering via sorted cumsum)
    order = jnp.argsort(-priority)               # high priority first
    mask1_sorted = mask1[order]
    loc_sorted = jnp.cumsum(mask1_sorted, axis=0) - 1.0
    inv = jnp.argsort(order)
    locations1 = jnp.sum(loc_sorted[inv] * mask1, axis=1)  # [S]

    if drop_tokens:
        keep = locations1 < C
        mask1 = mask1 * keep[:, None]

    gates1_s = jnp.sum(gates * mask1, axis=1)              # [S]
    if sparse:
        # factored routing: each token's (expert, slot, gate, alive) —
        # the [S,E,C] tensors below are rank-1 products of exactly these
        valid = jnp.sum(mask1, axis=1) > 0
        routing = [(indices1_s.astype(jnp.int32),
                    locations1.astype(jnp.int32), gates1_s, valid)]
        return l_aux, routing, C, exp_counts
    locations1_sc = _one_hot(locations1.astype(jnp.int32), C)  # [S, C]
    combine = gates1_s[:, None, None] * mask1[:, :, None] * \
        locations1_sc[:, None, :]                          # [S, E, C]
    dispatch = combine.astype(bool)
    return l_aux, combine, dispatch, exp_counts


def top2gating(logits, capacity_factor=1.0, min_capacity=4, noise_rng=None,
               sparse=False):
    """Top-2 gating (reference sharded_moe.py:271): second expert chosen
    after masking the first; gate pair renormalised. ``sparse=True`` as
    in :func:`top1gating`, with two routing entries (one per choice)."""
    S, E = logits.shape
    C = _capacity(S, E, capacity_factor * 2, min_capacity)

    gates = jax.nn.softmax(logits, axis=1)
    indices1_s = jnp.argmax(gates, axis=1)
    mask1 = _one_hot(indices1_s, E)

    if noise_rng is not None:
        logits_w_noise = logits + jax.random.gumbel(noise_rng, logits.shape)
    else:
        # DELIBERATE deviation from the reference, which gumbel-samples
        # the second expert even at eval (gumbel_rsample, :271): without
        # an rng (eval / _jit_eval) we use the noise-free argmax — a
        # fixed jit-able key would reuse ONE noise matrix across every
        # layer and batch, biasing routing by position. Training passes
        # the engine's fresh "gating" rng and matches the reference.
        logits_w_noise = logits
    logits_except1 = jnp.where(mask1.astype(bool), -jnp.inf, logits_w_noise)
    indices2_s = jnp.argmax(logits_except1, axis=1)
    mask2 = _one_hot(indices2_s, E)

    locations1 = jnp.cumsum(mask1, axis=0) - 1.0
    locations2 = jnp.cumsum(mask2, axis=0) - 1.0 + \
        jnp.sum(mask1, axis=0, keepdims=True)

    exp_counts = jnp.sum(mask1 + mask2, axis=0)

    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(mask1, axis=0)
    l_aux = jnp.sum(me * ce) * E

    loc1_s = jnp.sum(locations1 * mask1, axis=1)
    loc2_s = jnp.sum(locations2 * mask2, axis=1)
    mask1 = mask1 * (loc1_s < C)[:, None]
    mask2 = mask2 * (loc2_s < C)[:, None]

    gates1_s = jnp.sum(gates * mask1, axis=1)
    gates2_s = jnp.sum(gates * mask2, axis=1)
    denom = gates1_s + gates2_s
    denom = jnp.where(denom < 1e-9, 1.0, denom)
    gates1_s /= denom
    gates2_s /= denom

    if sparse:
        routing = [(indices1_s.astype(jnp.int32), loc1_s.astype(jnp.int32),
                    gates1_s, jnp.sum(mask1, axis=1) > 0),
                   (indices2_s.astype(jnp.int32), loc2_s.astype(jnp.int32),
                    gates2_s, jnp.sum(mask2, axis=1) > 0)]
        return l_aux, routing, C, exp_counts
    combine = (gates1_s[:, None, None] * mask1[:, :, None] *
               _one_hot(loc1_s.astype(jnp.int32), C)[:, None, :] +
               gates2_s[:, None, None] * mask2[:, :, None] *
               _one_hot(loc2_s.astype(jnp.int32), C)[:, None, :])
    dispatch = combine.astype(bool)
    return l_aux, combine, dispatch, exp_counts


_warned_grouped_ep = False

# dw = x^T @ dy contracted over the RAGGED token dim, grouped output
# [E, in, out] — the '[m,k],[k,n]->[g,m,n]' ragged_dot_general mode.
# jax < 0.5 has ragged_dot but not ragged_dot_general / its dimension-
# numbers type; _ragged_dw falls back to a one-hot-membership einsum
# there (same contraction, E x the flops — a compat path, not the fast
# one) so importing this module never crashes on an older jax.
_DW_DIMS = None
if hasattr(jax.lax, "RaggedDotDimensionNumbers") \
        and hasattr(jax.lax, "ragged_dot_general"):
    _DW_DIMS = jax.lax.RaggedDotDimensionNumbers(
        dot_dimension_numbers=(((0,), (0,)), ((), ())),
        lhs_ragged_dimensions=[0], rhs_group_dimensions=[])


def _ragged_dw(lhs, rhs, group_sizes, out_dtype):
    """Grouped weight-grad contraction: ``dw[e] = lhs[rows of group e]^T
    @ rhs[rows of group e]`` -> [E, M, N], accumulated in fp32."""
    if _DW_DIMS is not None:
        return jax.lax.ragged_dot_general(
            lhs, rhs, group_sizes, _DW_DIMS,
            preferred_element_type=jnp.float32).astype(out_dtype)
    # one-hot group membership from the ragged boundaries; rows past
    # sum(group_sizes) belong to no group, matching ragged semantics
    ends = jnp.cumsum(group_sizes)
    starts = ends - group_sizes
    rows = jnp.arange(lhs.shape[0])
    member = ((rows[:, None] >= starts[None, :])
              & (rows[:, None] < ends[None, :])).astype(jnp.float32)
    return jnp.einsum("se,sm,sn->emn", member, lhs.astype(jnp.float32),
                      rhs.astype(jnp.float32)).astype(out_dtype)


@jax.custom_vjp
def _grouped_expert_mlp(sorted_x, group_sizes, sorted_eid, w1, b1, w2, b2):
    """Megablocks-style grouped expert MLP: tokens arrive SORTED by
    expert, and each matmul is one ``jax.lax.ragged_dot`` over the
    contiguous per-expert groups — S*k rows total, NO capacity padding
    (the padded [E, C, M] form computes capacity_factor x as many rows).
    Dropped tokens still flow through (per-row MLPs make their compute
    side-effect-free) and are discarded by the combine's valid mask —
    identical outputs to the padded form.

    Custom VJP: jax's built-in ragged_dot transpose lowers
    catastrophically on TPU (measured 88 ms vs 1.4 ms for the same math
    at the bench shape); the hand-written backward keeps dx on
    ragged_dot with transposed per-expert weights and dw on the
    ragged-contraction ragged_dot_general mode."""
    out, _ = _grouped_mlp_fwd(sorted_x, group_sizes, sorted_eid,
                              w1, b1, w2, b2)
    return out


def _grouped_mlp_fwd(sorted_x, group_sizes, sorted_eid, w1, b1, w2, b2):
    h1 = jax.lax.ragged_dot(sorted_x, w1.astype(sorted_x.dtype),
                            group_sizes)
    h1 = h1 + b1.astype(h1.dtype)[sorted_eid]
    a, gelu_vjp = jax.vjp(lambda t: nn.gelu(t, approximate=True), h1)
    out = jax.lax.ragged_dot(a, w2.astype(a.dtype), group_sizes)
    out = out + b2.astype(out.dtype)[sorted_eid]
    return out, (sorted_x, group_sizes, sorted_eid, w1, w2, a, gelu_vjp)


def _grouped_mlp_bwd(res, g):
    sorted_x, gs, eid_s, w1, w2, a, gelu_vjp = res
    E = w1.shape[0]
    db2 = jax.ops.segment_sum(g.astype(jnp.float32), eid_s,
                              num_segments=E).astype(w2.dtype)
    da = jax.lax.ragged_dot(g, w2.transpose(0, 2, 1).astype(g.dtype), gs)
    dh1 = gelu_vjp(da)[0]
    db1 = jax.ops.segment_sum(dh1.astype(jnp.float32), eid_s,
                              num_segments=E).astype(w1.dtype)
    dw2 = _ragged_dw(a, g, gs, w2.dtype)
    dw1 = _ragged_dw(sorted_x, dh1, gs, w1.dtype)
    dx = jax.lax.ragged_dot(
        dh1, w1.transpose(0, 2, 1).astype(dh1.dtype), gs
    ).astype(sorted_x.dtype)
    return dx, None, None, dw1, db1, dw2, db2


_grouped_expert_mlp.defvjp(
    lambda sorted_x, gs, eid_s, w1, b1, w2, b2:
    _grouped_mlp_fwd(sorted_x, gs, eid_s, w1, b1, w2, b2),
    _grouped_mlp_bwd)


class TopKGate(nn.Module):
    """Gate network (reference TopKGate :343): fp32 linear + top-k."""
    num_experts: int
    k: int = 1
    capacity_factor: float = 1.0
    eval_capacity_factor: float = 1.0
    min_capacity: int = 4
    noisy_gate_policy: Optional[str] = None
    drop_tokens: bool = True
    use_rts: bool = True

    @nn.compact
    def __call__(self, x, train=True, used_token=None, sparse=False):
        # gate runs in fp32 always (reference :368 autocast exemption)
        wg = self.param("wg", nn.initializers.lecun_normal(),
                        (x.shape[-1], self.num_experts))
        logits = jnp.dot(x.astype(jnp.float32), wg.astype(jnp.float32))
        rng = None
        if train and (self.use_rts or self.noisy_gate_policy):
            if self.has_rng("gating"):
                rng = self.make_rng("gating")
        cf = self.capacity_factor if train else self.eval_capacity_factor
        if self.k == 1:
            return top1gating(logits, cf, self.min_capacity,
                              self.noisy_gate_policy if train else None,
                              rng, self.drop_tokens, self.use_rts,
                              used_token=used_token, sparse=sparse)
        return top2gating(logits, cf, self.min_capacity, rng, sparse=sparse)


class MOELayer(nn.Module):
    """Dispatch → experts → combine (reference MOELayer :473).

    ``expert_fn`` is a flax module class for ONE expert; it is vmapped over
    a leading expert axis with split params, giving stacked [E, ...] expert
    weights that shard over the mesh's expert axis."""
    expert_module: type
    expert_kwargs: dict
    num_experts: int
    k: int = 1
    capacity_factor: float = 1.0
    eval_capacity_factor: float = 1.0
    min_capacity: int = 4
    noisy_gate_policy: Optional[str] = None
    drop_tokens: bool = True
    use_rts: bool = True
    # "scatter" (default): route tokens by index — each token owns a
    # unique (expert, slot) pair, so a scatter-add builds [E,C,M] and a
    # gather reads it back, moving O(S*M) bytes. "einsum": the reference
    # GShard formulation through dense [S,E,C] masks — O(S*E*C) memory
    # traffic (335 MB fp32 per combine at the bench shape), kept for
    # cross-checking. Bit-identical results (slots are unique, adding
    # zeros is exact): tests/unit/test_moe.py locks parity and the golden
    # loss curves pass under both.
    dispatch_impl: str = "scatter"

    @nn.compact
    def __call__(self, x, train=True, used_token=None):
        orig_shape = x.shape
        M = orig_shape[-1]
        xf = x.reshape(-1, M)                                # [S, M]
        if used_token is not None:
            used_token = used_token.reshape(-1)

        gate = TopKGate(
            num_experts=self.num_experts, k=self.k,
            capacity_factor=self.capacity_factor,
            eval_capacity_factor=self.eval_capacity_factor,
            min_capacity=self.min_capacity,
            noisy_gate_policy=self.noisy_gate_policy,
            drop_tokens=self.drop_tokens, use_rts=self.use_rts,
            name="gate")
        E = self.num_experts
        if self.dispatch_impl not in ("grouped", "scatter", "einsum"):
            raise ValueError(
                f"dispatch_impl must be 'grouped', 'scatter' or 'einsum', "
                f"got {self.dispatch_impl!r}")

        if self.dispatch_impl == "grouped":
            # sort-based grouped GEMM (megablocks-style): no [E, C, M]
            # operand, no capacity padding — per-step expert compute is
            # S*k rows instead of E*C = capacity_factor*S*k
            from deepspeed_tpu.moe.layer import MLPExpert
            if (groups.mesh_is_initialized()
                    and groups.get_mesh().shape[groups.EXPERT_AXIS] > 1):
                # no [E, ...] activation exists on this path, so there is
                # no constraint point to force the expert all-to-all —
                # XLA resolves the ragged GEMMs by gathering the expert
                # weights instead. Correct (the ep goldens pass) but it
                # forfeits EP's bandwidth win; say so once.
                global _warned_grouped_ep
                if not _warned_grouped_ep:
                    _warned_grouped_ep = True
                    from deepspeed_tpu.utils.logging import logger
                    logger.warning(
                        "dispatch_impl='grouped' under expert parallelism "
                        "gathers expert weights instead of exchanging "
                        "tokens (no all-to-all constraint point); use "
                        "'scatter' for ep>1 performance")
            if self.expert_module is not MLPExpert:
                raise NotImplementedError(
                    "dispatch_impl='grouped' implements the standard "
                    "MLPExpert (fc1-gelu-fc2) as ragged grouped matmuls; "
                    f"expert {self.expert_module.__name__} needs "
                    "dispatch_impl='scatter'")
            l_aux, routing, C, exp_counts = gate(
                xf, train, used_token=used_token, sparse=True)
            S = xf.shape[0]
            eid = jnp.concatenate([r[0] for r in routing])       # [S*k]
            gate_w = jnp.concatenate(
                [r[2] * r[3] for r in routing])                  # gate*valid
            tok = jnp.tile(jnp.arange(S), len(routing))
            order = jnp.argsort(eid)
            sorted_eid = eid[order]
            sorted_tok = tok[order]
            group_sizes = jnp.bincount(eid, length=E).astype(jnp.int32)
            # params come from the SAME vmapped module as the padded
            # impls — bound on a zero-row dummy (free), so init values,
            # tree layout, and checkpoints are identical across impls
            experts = nn.vmap(
                self.expert_module,
                in_axes=0, out_axes=0,
                variable_axes={"params": 0},
                split_rngs={"params": True},
                metadata_params={nn.PARTITION_NAME: "expert"},
            )(name="deepspeed_experts", **self.expert_kwargs)
            experts(jnp.zeros((E, 0, M), xf.dtype))
            ev = experts.variables["params"]
            expert_out = _grouped_expert_mlp(
                xf[sorted_tok], group_sizes, sorted_eid,
                ev["fc1"]["kernel"], ev["fc1"]["bias"],
                ev["fc2"]["kernel"], ev["fc2"]["bias"])
            combined = jnp.zeros((S, M), expert_out.dtype).at[
                sorted_tok].add(
                    gate_w[order][:, None].astype(expert_out.dtype)
                    * expert_out)
            return combined.reshape(orig_shape), l_aux, exp_counts

        if self.dispatch_impl == "scatter":
            l_aux, routing, C, exp_counts = gate(
                xf, train, used_token=used_token, sparse=True)
            # one extra trash row swallows dropped tokens
            buf = jnp.zeros((E * C + 1, M), xf.dtype)
            for e_s, loc_s, _, valid in routing:
                slot = jnp.where(valid, e_s * C + loc_s, E * C)
                buf = buf.at[slot].add(xf)
            dispatched = buf[:E * C].reshape(E, C, M)
        else:
            l_aux, combine, dispatch, exp_counts = gate(
                xf, train, used_token=used_token)
            # dispatch: [S,E,C] × [S,M] → [E,C,M]
            dispatched = jnp.einsum("sec,sm->ecm",
                                    dispatch.astype(xf.dtype), xf)
        # the expert-axis constraint makes XLA insert the all-to-all
        # (reference _AllToAll :84/:507)
        dispatched = _expert_constraint(dispatched)

        experts = nn.vmap(
            self.expert_module,
            in_axes=0, out_axes=0,
            variable_axes={"params": 0},
            split_rngs={"params": True},
            metadata_params={nn.PARTITION_NAME: "expert"},
        )(name="deepspeed_experts", **self.expert_kwargs)
        expert_out = experts(dispatched)                     # [E, C, M]
        expert_out = _expert_constraint(expert_out)

        if self.dispatch_impl == "scatter":
            flat = expert_out.reshape(E * C, M)
            combined = jnp.zeros((xf.shape[0], M), expert_out.dtype)
            for e_s, loc_s, gate_s, valid in routing:
                slot = jnp.where(valid, e_s * C + loc_s, 0)
                combined = combined + (
                    gate_s * valid)[:, None].astype(expert_out.dtype) \
                    * flat[slot]
        else:
            combined = jnp.einsum("sec,ecm->sm",
                                  combine.astype(expert_out.dtype),
                                  expert_out)
        return combined.reshape(orig_shape), l_aux, exp_counts
