"""User-facing MoE API.

Rebuild of deepspeed/moe/layer.py (``MoE`` :18): same constructor surface
(hidden_size, expert, num_experts, k, capacity factors, noisy gating, RTS,
use_residual for MoS) as a flax module. Where the reference mutates global
process groups on first use (layer.py:40 ``initialize`` call), here the
expert axis already exists on the mesh (utils/groups.py) and the stacked
expert params shard over it declaratively (moe/sharding rules below).
"""

from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.moe.sharded_moe import MOELayer


class MLPExpert(nn.Module):
    """Default FFN expert (what DeepSpeedExamples passes as ``expert``)."""
    hidden_size: int
    intermediate_size: Optional[int] = None

    @nn.compact
    def __call__(self, x):
        inner = self.intermediate_size or 4 * self.hidden_size
        h = nn.Dense(inner, name="fc1")(x)
        h = nn.gelu(h, approximate=True)
        return nn.Dense(self.hidden_size, name="fc2")(h)


class MoE(nn.Module):
    """Mixture of experts layer (reference moe/layer.py:18).

    Returns (output, l_aux, exp_counts) exactly like the reference's
    ``MoE.forward`` (layer.py:98).

    Memory note: ``drop_tokens=False`` sets capacity C = S (tokens) since
    jit needs static shapes where the reference grows capacity to the
    observed max (sharded_moe.py:207) — the [S, E, C] dispatch/combine
    tensors then scale as S²·E. Prefer ``drop_tokens=True`` with a
    ``capacity_factor`` margin for long sequences; C is then
    S·k·factor/E."""
    hidden_size: int
    expert: Any = None                  # flax module CLASS for one expert
    expert_kwargs: Optional[dict] = None
    num_experts: int = 1
    ep_size: int = 1                    # informational; mesh axis rules
    k: int = 1
    capacity_factor: float = 1.0
    eval_capacity_factor: float = 1.0
    min_capacity: int = 4
    use_residual: bool = False          # MoS (residual MoE)
    noisy_gate_policy: Optional[str] = None
    drop_tokens: bool = True
    use_rts: bool = True
    # reference ctor parity (moe/layer.py:30): tutel's optimization IS
    # index-routed dispatch, which this build always has — True maps to
    # the scatter impl, False keeps whatever dispatch_impl says
    use_tutel: bool = False
    dispatch_impl: str = "scatter"      # see MOELayer.dispatch_impl

    @nn.compact
    def __call__(self, hidden_states, used_token=None, train=True):
        expert_cls = self.expert or MLPExpert
        kwargs = dict(self.expert_kwargs or {})
        if expert_cls is MLPExpert and "hidden_size" not in kwargs:
            kwargs["hidden_size"] = self.hidden_size
        dispatch_impl = ("scatter" if self.use_tutel
                         else self.dispatch_impl)

        out, l_aux, exp_counts = MOELayer(
            expert_module=expert_cls,
            expert_kwargs=kwargs,
            num_experts=self.num_experts,
            k=self.k,
            capacity_factor=self.capacity_factor,
            eval_capacity_factor=self.eval_capacity_factor,
            min_capacity=self.min_capacity,
            noisy_gate_policy=self.noisy_gate_policy,
            drop_tokens=self.drop_tokens,
            use_rts=self.use_rts,
            dispatch_impl=dispatch_impl,
            name="deepspeed_moe")(hidden_states, train,
                                  used_token=used_token)

        if self.use_residual:
            # Mixture-of-Students residual path (reference layer.py:98-113)
            mlp_out = MLPExpert(self.hidden_size, name="mlp")(hidden_states)
            coef = nn.Dense(2, name="coefficient")(hidden_states)
            coef = nn.softmax(coef, axis=-1)
            out = out * coef[..., 0:1] + mlp_out * coef[..., 1:2]
        return out, l_aux, exp_counts


def moe_sharding_rules():
    """ModelParallelRules entries for stacked expert params: the leading
    expert dim shards over the mesh expert axis (the EP analogue of
    reference groups initialize_expert_parallel)."""
    return [(r"deepspeed_experts.*", P("expert"))]


def is_moe_param(path: str) -> bool:
    """Reference moe/utils.py:18 checks param.allreduce == False; here
    expert params are identified by their module path."""
    return "deepspeed_experts" in path
