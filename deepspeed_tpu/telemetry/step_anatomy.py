"""Step anatomy — measured device-time attribution from profiler traces.

Everything the repo previously said about *where device time goes* was a
static prediction (CostExplorer rooflines over the HLO census).  This
module reads back the traces ``jax.profiler`` writes (via the
dependency-free ``telemetry.xplane`` wire parser) and joins the measured
per-op device events to the programs the engine already owns:

* per-op device seconds bucketed into six categories
  (matmul/convolution, collective, scatter/gather, elementwise/fusion,
  host-transfer, idle-gap), with the invariant that category seconds sum
  to the captured device wall time *exactly* (a per-lane coverage sweep
  splits every lane window into busy + idle with no double counting);
* attribution to model modules via HLO ``op_name`` metadata paths
  (``jit(step)/.../h_1/ln_2/mul`` → module ``h_1/ln_2``) and the PR-3
  health-bucket spec names;
* steps delimited by ``TraceAnnotation`` span marks
  (``ds_anatomy_step``) that ``engine.profile_step`` emits;
* measured-vs-predicted rows against CostExplorer's roofline floors
  (drift flagged when > 25%), and a measured collective-overlap fraction
  compared against the census's static schedule positions;
* per-device Chrome-trace lanes that ``fleet.merge_traces`` can join
  with the host tracer's spans.

CLI: ``python -m deepspeed_tpu.telemetry.step_anatomy --render PATH`` /
``--demo [--out PATH]``.
"""

import argparse
import json
import os
import re
import sys
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

ANATOMY_SCHEMA = "deepspeed_tpu.step_anatomy/1"

# annotation name profile_step/profile_window wrap each captured step in
STEP_MARK = "ds_anatomy_step"

BUSY_CATEGORIES = (
    "matmul_convolution",
    "collective",
    "scatter_gather",
    "elementwise_fusion",
    "host_transfer",
)
CATEGORIES = BUSY_CATEGORIES + ("idle_gap",)

_PS = 1e-12  # picoseconds → seconds

# ---------------------------------------------------------------------------
# categorisation
# ---------------------------------------------------------------------------

_COLLECTIVE_TOKENS = (
    "all-reduce", "all-gather", "reduce-scatter", "all-to-all",
    "collective-permute", "collective-broadcast", "ragged-all-to-all",
)
_MATMUL_TOKENS = ("dot", "convolution", "conv", "gemm", "einsum", "matmul")
_SCATTER_TOKENS = ("scatter", "gather", "dynamic-slice",
                   "dynamic-update-slice", "select-and-scatter")
_TRANSFER_TOKENS = ("copy", "copy-start", "copy-done", "infeed", "outfeed",
                    "send", "send-done", "recv", "recv-done")

_TOKEN_SPLIT = re.compile(r"[._]")


def _tokens(name: str) -> List[str]:
    """Split an HLO instruction name into match tokens.

    ``bitcast_dot_fusion`` → [bitcast, dot, fusion]; a trailing ``.12``
    suffix drops out as a numeric token.  Hyphenated opcodes
    (``dynamic-update-slice``) stay whole so 'slice' alone can't
    misfire, but we also test the raw name for hyphenated tokens.
    """
    return [t for t in _TOKEN_SPLIT.split(name.lower()) if t]


def categorize(name: str, opcode: Optional[str] = None) -> str:
    """Map an HLO instruction (executor event) to a busy category.

    Uses the real opcode when an HLO op table is available; falls back
    to name heuristics (fusion names embed their root ops:
    ``loop_dot_fusion``, ``dynamic-slice_concatenate_fusion``).  Order
    matters: collectives first (``all-gather`` contains 'gather'),
    transfers before matmul so ``copy`` never misfires.
    """
    probe = (opcode or name).lower()
    toks = set(_tokens(probe))
    for t in _COLLECTIVE_TOKENS:
        if t in probe:
            return "collective"
    hyphen_toks = {t for t in re.split(r"[-._]", probe) if t}
    if (toks | hyphen_toks) & {"copy", "infeed", "outfeed", "send", "recv"}:
        # hyphen split catches async pairs (copy-start / recv-done);
        # collectives already returned above, so 'reduce' etc. can't leak
        return "host_transfer"
    if opcode:
        ol = opcode.lower()
        if ol in ("dot", "convolution"):
            return "matmul_convolution"
        if ol in ("scatter", "gather", "dynamic-slice",
                  "dynamic-update-slice", "select-and-scatter"):
            return "scatter_gather"
        if ol == "fusion":
            # fusion: fall through to the *name* heuristics below
            probe = name.lower()
            toks = set(_tokens(probe))
        elif ol == "custom-call":
            probe = name.lower()
            toks = set(_tokens(probe))
        else:
            return "elementwise_fusion"
    if toks & set(_MATMUL_TOKENS):
        return "matmul_convolution"
    for t in _SCATTER_TOKENS:
        if t in probe:
            return "scatter_gather"
    if toks & {"scatter", "gather"}:
        return "scatter_gather"
    return "elementwise_fusion"


# ---------------------------------------------------------------------------
# HLO op table (join key: instruction name → opcode + op_name metadata)
# ---------------------------------------------------------------------------

_HLO_INSTR = re.compile(
    r"^\s*(?:ROOT\s+)?%?(?P<name>[\w.-]+)\s*=\s*[^=]*?\s"
    r"(?P<opcode>[\w-]+)\(")
_HLO_OPNAME = re.compile(r'op_name="(?P<op>[^"]*)"')
_WRAPPER = re.compile(r"^(jit|pjit|jvp|vjp|vmap|transpose|remat|custom_jvp|"
                      r"custom_vjp|checkpoint|named)\(.*\)$")


def hlo_op_table(hlo_text: str) -> Dict[str, Tuple[str, str]]:
    """Parse HLO text into {instruction_name: (opcode, op_name)}.

    The profiler's executor events are named by HLO instruction name
    (``dot.4``, ``broadcast_maximum_fusion``); the compiled module's
    text carries each instruction's opcode and its ``op_name`` metadata
    path — the join that turns raw timings into model-module
    attribution.
    """
    table: Dict[str, Tuple[str, str]] = {}
    for line in hlo_text.splitlines():
        if "=" not in line:
            continue
        m = _HLO_INSTR.match(line)
        if not m:
            continue
        om = _HLO_OPNAME.search(line)
        table[m.group("name")] = (m.group("opcode"),
                                  om.group("op") if om else "")
    return table


def module_from_op_name(op_name: str) -> str:
    """Reduce an ``op_name`` metadata path to its model-module path.

    ``jit(step)/jit(main)/transpose(jvp(GPT2LMHeadModel))/h_1/ln_2/mul``
    → ``h_1/ln_2`` (tracing wrappers stripped, trailing primitive
    dropped).  Empty string when nothing module-like remains.
    """
    if not op_name:
        return ""
    parts = [p for p in op_name.split("/") if p and not _WRAPPER.match(p)]
    if len(parts) >= 2:
        parts = parts[:-1]          # drop the primitive (mul, dot_general)
    return "/".join(parts)


# ---------------------------------------------------------------------------
# event model + extraction from a parsed XSpace
# ---------------------------------------------------------------------------

@dataclass
class LaneEvent:
    name: str
    start_ps: int
    end_ps: int


def extract_events(space, step_mark: str = STEP_MARK):
    """Pull (steps, lanes) out of a parsed XSpace.

    Device lanes are either lines of a ``/device:`` plane or host-plane
    executor lines where ≥ half the events carry an ``hlo_op`` stat
    (CPU jax runs XLA:CPU executors on host threads — ``tf_XLAEigen`` /
    ``tf_XLATfrtCpuClient`` lines; the ``python`` line's few hlo-op
    events are annotation echoes and stay excluded).  Steps come from
    *step_mark* annotation events anywhere in the trace.

    Returns ``(steps, lanes)`` where steps is
    ``[(label, start_ps, end_ps)]`` and lanes is
    ``{lane_name: [LaneEvent, ...]}`` with absolute-ps timestamps
    (line timestamp_ns · 1000 + offset).
    """
    steps: List[Tuple[object, int, int]] = []
    lanes: Dict[str, List[LaneEvent]] = {}
    for plane in space.planes:
        is_device = plane.name.startswith("/device:")
        for line in plane.lines:
            if not line.events:
                continue
            base = line.timestamp_ns * 1000
            hlo_events = []
            for ev in line.events:
                name = plane.event_name(ev)
                if name == step_mark:
                    stats = plane.event_stats(ev)
                    label = stats.get("step")
                    start = base + ev.offset_ps
                    steps.append((label, start, start + ev.duration_ps))
                elif is_device or "hlo_op" in plane.event_stats(ev):
                    hlo_events.append(LaneEvent(
                        name, base + ev.offset_ps,
                        base + ev.offset_ps + ev.duration_ps))
            if not hlo_events:
                continue
            if not is_device and len(hlo_events) < 0.5 * len(line.events):
                continue    # host line with incidental hlo stats
            lane = f"{plane.name}/{line.display_name or line.name}"
            lanes.setdefault(lane, []).extend(hlo_events)
    steps.sort(key=lambda s: s[1])
    return steps, lanes


# ---------------------------------------------------------------------------
# core attribution
# ---------------------------------------------------------------------------

def _merge_intervals(ivals: List[Tuple[int, int]]) -> List[Tuple[int, int]]:
    out: List[Tuple[int, int]] = []
    for s, e in sorted(ivals):
        if e <= s:
            continue
        if out and s <= out[-1][1]:
            if e > out[-1][1]:
                out[-1] = (out[-1][0], e)
        else:
            out.append((s, e))
    return out


def _overlap_with(ivals: List[Tuple[int, int]], s: int, e: int) -> int:
    """Length of [s,e) ∩ (merged, sorted) *ivals*."""
    import bisect
    total = 0
    i = bisect.bisect_left(ivals, (s,)) - 1
    i = max(0, i)
    while i < len(ivals) and ivals[i][0] < e:
        total += max(0, min(e, ivals[i][1]) - max(s, ivals[i][0]))
        i += 1
    return total


def analyze_events(steps, lanes, op_table=None, bucket_names=None,
                   predicted_floors=None, schedule_positions=None,
                   top_k: int = 12):
    """Join device-lane events to categories/modules; build the report.

    ``steps``: [(label, start_ps, end_ps)] capture windows (empty →
    one window spanning all events).  ``lanes``: {name: [LaneEvent]}.
    ``op_table``: {instr_name: (opcode, op_name)} from ``hlo_op_table``.
    ``predicted_floors``: CostExplorer ``bound_floors_s`` dict
    (per-step seconds, values may be None on hosts without chip specs).
    """
    op_table = op_table or {}
    bucket_names = list(bucket_names or [])
    windows = [(lbl, s, e) for lbl, s, e in steps if e > s]
    if not windows:
        lo = min((ev.start_ps for evs in lanes.values() for ev in evs),
                 default=0)
        hi = max((ev.end_ps for evs in lanes.values() for ev in evs),
                 default=0)
        if hi > lo:
            windows = [(None, lo, hi)]

    cat_ps = {c: 0 for c in CATEGORIES}
    per_op: Dict[str, dict] = {}
    module_ps: Dict[str, Dict[str, int]] = {c: {} for c in BUSY_CATEGORIES}
    lane_rows = []
    step_rows = {i: {"step": lbl, "span_ps": 0, "busy_ps": 0}
                 for i, (lbl, _, _) in enumerate(windows)}
    collective_ivals: List[Tuple[int, int]] = []
    other_ivals: List[Tuple[int, int]] = []
    device_wall_ps = 0

    def _resolve(name):
        entry = op_table.get(name)
        if entry is None and name.endswith("..."):  # truncated display names
            entry = None
        opcode, op_name = entry if entry else (None, "")
        return categorize(name, opcode), module_from_op_name(op_name)

    resolve_cache: Dict[str, Tuple[str, str]] = {}

    for lane_name in sorted(lanes):
        events = sorted(lanes[lane_name], key=lambda ev: ev.start_ps)
        lane_busy = 0
        lane_n = 0
        for wi, (lbl, ws, we) in enumerate(windows):
            span = we - ws
            device_wall_ps += span
            step_rows[wi]["span_ps"] += span
            coverage = ws       # high-water mark: no double counting when
            busy = 0            # pool threads re-report overlapping ops
            for ev in events:
                if ev.end_ps <= ws or ev.start_ps >= we:
                    continue
                contrib = (min(ev.end_ps, we)
                           - max(ev.start_ps, ws, coverage))
                if contrib <= 0:
                    # fully shadowed by an earlier event — still record
                    # the op's presence for counts/overlap, zero seconds
                    contrib = 0
                cached = resolve_cache.get(ev.name)
                if cached is None:
                    cached = resolve_cache[ev.name] = _resolve(ev.name)
                cat, module = cached
                cat_ps[cat] += contrib
                busy += contrib
                rec = per_op.setdefault(
                    ev.name, {"name": ev.name, "category": cat,
                              "module": module, "total_ps": 0, "count": 0})
                rec["total_ps"] += contrib
                rec["count"] += 1
                if module:
                    module_ps[cat][module] = (
                        module_ps[cat].get(module, 0) + contrib)
                cs, ce = max(ev.start_ps, ws), min(ev.end_ps, we)
                (collective_ivals if cat == "collective"
                 else other_ivals).append((cs, ce))
                coverage = max(coverage, min(ev.end_ps, we))
                lane_n += 1
            cat_ps["idle_gap"] += span - busy
            step_rows[wi]["busy_ps"] += busy
            lane_busy += busy
        lane_rows.append({"name": lane_name, "busy_s": lane_busy * _PS,
                          "events": lane_n})

    # ------------------------------------------------ collective overlap
    comp_union = _merge_intervals(other_ivals)
    coll_union = _merge_intervals(collective_ivals)
    coll_total = sum(e - s for s, e in coll_union)
    hidden = sum(_overlap_with(comp_union, s, e) for s, e in coll_union)
    overlap = {
        "collective_s": coll_total * _PS,
        "hidden_behind_compute_s": hidden * _PS,
        "exposed_s": (coll_total - hidden) * _PS,
        "overlap_fraction": (hidden / coll_total) if coll_total else None,
        "census_schedule_positions": schedule_positions,
    }

    # ------------------------------------------- measured vs predicted
    n_steps = len(windows)
    busy_non_coll_ps = sum(cat_ps[c] for c in BUSY_CATEGORIES
                           if c not in ("collective", "host_transfer"))
    measured_by = {
        "compute": busy_non_coll_ps * _PS,
        "memory": busy_non_coll_ps * _PS,
        "comm": cat_ps["collective"] * _PS,
    }
    mvp = []
    for cat in sorted(set(predicted_floors or {"compute", "memory", "comm"})
                      | set(measured_by)):
        floor = (predicted_floors or {}).get(cat)
        predicted = (floor * n_steps) if isinstance(floor, (int, float)) \
            else None
        measured = measured_by.get(cat)
        drift = ((measured / predicted) - 1.0) if (
            predicted and measured is not None) else None
        mvp.append({
            "category": cat,
            "predicted_s": predicted,
            "measured_s": measured,
            "drift": drift,
            "flagged": bool(drift is not None and abs(drift) > 0.25),
        })

    # ----------------------------------------------- module attribution
    attribution = {}
    for cat in BUSY_CATEGORIES:
        rows = sorted(module_ps[cat].items(), key=lambda kv: -kv[1])[:top_k]
        total = cat_ps[cat] or 1
        attribution[cat] = [
            {"module": mod, "seconds": ps * _PS, "share": ps / total,
             "bucket": _match_bucket(mod, bucket_names)}
            for mod, ps in rows]

    top_ops = sorted(per_op.values(), key=lambda r: -r["total_ps"])[:top_k]
    top_ops = [{"name": r["name"], "category": r["category"],
                "module": r["module"], "seconds": r["total_ps"] * _PS,
                "count": r["count"]} for r in top_ops]

    steps_out = []
    for i in range(len(windows)):
        row = step_rows[i]
        steps_out.append({
            "step": row["step"],
            "span_s": row["span_ps"] * _PS,
            "busy_s": row["busy_ps"] * _PS,
            "idle_s": (row["span_ps"] - row["busy_ps"]) * _PS,
        })

    return {
        "schema": ANATOMY_SCHEMA,
        "captured_steps": len(windows),
        "device_wall_s": device_wall_ps * _PS,
        "categories_s": {c: cat_ps[c] * _PS for c in CATEGORIES},
        "category_fractions": {
            c: (cat_ps[c] / device_wall_ps) if device_wall_ps else 0.0
            for c in CATEGORIES},
        "steps": steps_out,
        "lanes": lane_rows,
        "top_ops": top_ops,
        "module_attribution": attribution,
        "collective_overlap": overlap,
        "measured_vs_predicted": mvp,
        "ops_joined_to_hlo": sum(1 for r in per_op.values()
                                 if r["name"] in op_table),
        "ops_total": len(per_op),
        "notes": [],
    }


def _match_bucket(module: str, bucket_names: Sequence[str]) -> Optional[str]:
    """Join a module path to a PR-3 health-bucket spec name (best
    effort: the bucket whose name shares the module's deepest path
    component)."""
    if not module or not bucket_names:
        return None
    tail = module.split("/")[-1]
    for b in bucket_names:
        if module in b or b in module:
            return b
    for b in bucket_names:
        if tail and tail in b:
            return b
    return None


# ---------------------------------------------------------------------------
# trace-dir driver
# ---------------------------------------------------------------------------

def summarize_capture(trace_dir, op_table=None, bucket_names=None,
                      predicted_floors=None, schedule_positions=None,
                      step_mark: str = STEP_MARK):
    """Parse the newest ``.xplane.pb`` under *trace_dir* and attribute
    it.  Returns the report dict, or ``None`` when no parseable capture
    exists (caller treats that as 'profiler wrote nothing')."""
    from deepspeed_tpu.telemetry import xplane
    files = xplane.find_xplane_files(trace_dir)
    if not files:
        return None
    path = files[0]
    try:
        space = xplane.parse_xspace_file(path)
    except (OSError, xplane.XplaneParseError) as exc:
        return {"schema": ANATOMY_SCHEMA, "error": str(exc),
                "source": {"trace": path}}
    steps, lanes = extract_events(space, step_mark=step_mark)
    report = analyze_events(
        steps, lanes, op_table=op_table, bucket_names=bucket_names,
        predicted_floors=predicted_floors,
        schedule_positions=schedule_positions)
    report["source"] = {
        "trace": path,
        "hostnames": space.hostnames,
        "planes": [p.name for p in space.planes],
        "step_mark": step_mark,
        "marked_steps": len(steps),
    }
    if not steps:
        report["notes"].append(
            "no step annotations found — whole capture treated as one "
            "window")
    return report


# ---------------------------------------------------------------------------
# Chrome-trace device lanes
# ---------------------------------------------------------------------------

def device_trace_events(lanes, process_label="xplane device lanes"):
    """Render extracted lanes as Chrome-trace events (ts/dur in µs,
    capture-relative) on registry-allocated tids, ready for
    ``fleet.merge_traces``.  Timestamps are capture-relative — profiler
    and host-tracer clocks share no epoch, so these merge as their own
    process lane rather than interleaving with host spans."""
    from deepspeed_tpu.telemetry.tracer import allocate_lane_tid
    pid = os.getpid()
    events = [{"name": "process_name", "ph": "M", "pid": pid,
               "args": {"name": process_label}}]
    t0 = min((ev.start_ps for evs in lanes.values() for ev in evs),
             default=0)
    for lane_name in sorted(lanes):
        tid = allocate_lane_tid(("xplane", lane_name))
        events.append({"name": "thread_name", "ph": "M", "pid": pid,
                       "tid": tid, "args": {"name": lane_name}})
        for ev in lanes[lane_name]:
            events.append({
                "name": ev.name, "ph": "X",
                "ts": (ev.start_ps - t0) / 1e6,
                "dur": (ev.end_ps - ev.start_ps) / 1e6,
                "pid": pid, "tid": tid})
    return events


def write_device_trace(out_path, lanes, process_label="xplane device lanes"):
    """Write lanes as a standalone Chrome-trace JSON file; returns the
    path (input format for ``fleet.merge_traces``)."""
    doc = {"traceEvents": device_trace_events(lanes, process_label),
           "displayTimeUnit": "ms"}
    d = os.path.dirname(out_path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = out_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, out_path)
    return out_path


# ---------------------------------------------------------------------------
# report IO + rendering
# ---------------------------------------------------------------------------

def write_report(report, path):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(report, f, indent=1, allow_nan=False, default=repr)
    os.replace(tmp, path)
    return path


def render(report):
    """Human-readable rendering of a STEP_ANATOMY.json dict."""
    lines = []
    if report.get("error"):
        lines.append(f"step anatomy: PARSE ERROR — {report['error']}")
        return "\n".join(lines)
    wall = report.get("device_wall_s", 0.0)
    lines.append(
        f"step anatomy: {report.get('captured_steps', 0)} step(s), "
        f"device wall {wall * 1e3:.2f} ms across "
        f"{len(report.get('lanes', []))} lane(s)")
    cats = report.get("categories_s", {})
    fr = report.get("category_fractions", {})
    for cat in CATEGORIES:
        if cat in cats:
            lines.append(f"  {cat:20s} {cats[cat] * 1e3:10.3f} ms  "
                         f"({fr.get(cat, 0.0):6.1%})")
    ov = report.get("collective_overlap") or {}
    if ov.get("collective_s"):
        frac = ov.get("overlap_fraction")
        lines.append(
            f"  collective overlap: {ov['collective_s'] * 1e3:.3f} ms "
            f"total, {ov.get('hidden_behind_compute_s', 0) * 1e3:.3f} ms "
            f"hidden" + (f" ({frac:.0%})" if frac is not None else ""))
    for row in report.get("measured_vs_predicted", []):
        pred = row.get("predicted_s")
        meas = row.get("measured_s")
        drift = row.get("drift")
        lines.append(
            "  {}{:8s} predicted {} measured {}{}".format(
                "! " if row.get("flagged") else "  ",
                row.get("category", "?"),
                f"{pred * 1e3:9.3f} ms" if pred is not None
                else "      (n/a)",
                f"{meas * 1e3:9.3f} ms" if meas is not None
                else "      (n/a)",
                f"  drift {drift:+.0%}" if drift is not None else ""))
    for op in report.get("top_ops", [])[:8]:
        lines.append(
            f"  top op {op['name']:40s} {op['seconds'] * 1e3:9.3f} ms "
            f"[{op['category']}]"
            + (f" <- {op['module']}" if op.get("module") else ""))
    att = (report.get("module_attribution") or {}).get(
        "matmul_convolution") or []
    for row in att[:5]:
        lines.append(
            f"  matmul module {row['module']:35s} "
            f"{row['seconds'] * 1e3:9.3f} ms ({row['share']:.0%})"
            + (f" [bucket {row['bucket']}]" if row.get("bucket") else ""))
    for note in report.get("notes", []):
        lines.append(f"  note: {note}")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# demo (synthetic capture exercising every category + the full schema)
# ---------------------------------------------------------------------------

def _demo_report():
    """Build a deterministic synthetic anatomy: 3 steps × 2 lanes with
    every category represented and op_name-based module attribution —
    exercises exactly the schema the engine writes."""
    op_table = {}
    lanes = {"demo/device:0": [], "demo/device:1": []}
    steps = []
    us = 1_000_000  # 1 µs in ps
    t = 0
    for s in range(3):
        start = t
        for lane_i, lane in enumerate(sorted(lanes)):
            lt = start
            plan = [
                ("dot.%d" % (s * 10 + lane_i), "dot",
                 "jit(train_step)/transpose(jvp(DemoNet))/h_0/attn/"
                 "dot_general", 300),
                ("loop_dot_fusion.%d" % s, "fusion",
                 "jit(train_step)/jvp(DemoNet)/h_1/mlp/dot_general", 200),
                ("all-reduce.%d" % s, "all-reduce",
                 "jit(train_step)/all_reduce", 150),
                ("dynamic-update-slice.%d" % s, "dynamic-update-slice",
                 "jit(train_step)/h_0/cache/dynamic_update_slice", 60),
                ("copy.%d" % (s * 10 + lane_i), "copy", "", 40),
                ("broadcast_maximum_fusion.%d" % s, "fusion",
                 "jit(train_step)/jvp(DemoNet)/h_0/attn/softmax/max", 120),
            ]
            for name, opcode, op_name, dur_us in plan:
                op_table[name] = (opcode, op_name)
                lanes[lane].append(
                    LaneEvent(name, lt, lt + dur_us * us))
                lt += dur_us * us
            # deliberate idle tail so idle_gap is non-zero
            lt += 80 * us
        steps.append((s, start, lt))
        t = lt
    report = analyze_events(
        steps, lanes, op_table=op_table,
        bucket_names=["h_0/attn", "h_1/mlp", "embeddings"],
        predicted_floors={"compute": 1.3e-3, "memory": 0.9e-3,
                          "comm": 0.2e-3},
        schedule_positions={"interleaved": 1, "trailing": 0})
    report["source"] = {"trace": "(synthetic demo)", "hostnames": [],
                        "planes": ["demo"], "step_mark": STEP_MARK,
                        "marked_steps": 3}
    report["notes"].append(
        "demo-mode synthetic events — run engine.profile_step(n) on a "
        "real engine for measured numbers")
    return report


# --------------------------------------------------------------------- CLI

def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m deepspeed_tpu.telemetry.step_anatomy",
        description="Render or generate step-anatomy reports.")
    ap.add_argument("--render", metavar="PATH",
                    help="render a STEP_ANATOMY.json report, or analyze a "
                         "profiler trace directory / .xplane.pb file")
    ap.add_argument("--demo", action="store_true",
                    help="emit a synthetic demo report")
    ap.add_argument("--out", metavar="PATH", default=None,
                    help="also write the report JSON here")
    args = ap.parse_args(argv)
    if not args.render and not args.demo:
        ap.print_help()
        return 2
    if args.demo:
        report = _demo_report()
    else:
        path = args.render
        if os.path.isdir(path):
            report = summarize_capture(path)
            if report is None:
                print(f"no .xplane.pb files under {path}", file=sys.stderr)
                return 1
        elif path.endswith(".pb"):
            from deepspeed_tpu.telemetry import xplane
            space = xplane.parse_xspace_file(path)
            steps, lanes = extract_events(space)
            report = analyze_events(steps, lanes)
            report["source"] = {"trace": path, "hostnames": space.hostnames,
                                "planes": [p.name for p in space.planes],
                                "step_mark": STEP_MARK,
                                "marked_steps": len(steps)}
        else:
            with open(path) as f:
                report = json.load(f)
    if args.out:
        write_report(report, args.out)
    print(render(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
