"""``python -m deepspeed_tpu.telemetry.explain`` — EXPLAIN.json emitter.

Builds a GPT-2 engine at the requested geometry with the cost explorer
enabled, primes the step program through the AOT-owning dispatch path
(one compile — the same compile training would pay), optionally times a
few steps, and writes the full "explain this step" report:

* XLA-counted flops / bytes-accessed of the compiled per-chip program;
* roofline + MFU attribution against the chip peak (configurable);
* compute / memory / comm bound-ness verdict;
* per-mesh-axis collective wire bytes;
* HBM watermark pre-flight (args + outputs - alias + temps vs HBM).

Examples::

    python -m deepspeed_tpu.telemetry.explain                 # tiny smoke
    python -m deepspeed_tpu.telemetry.explain --model gpt2 \
        --batch-size 8 --seq 512 --zero 1 --devices 8 --steps 3
    python -m deepspeed_tpu.telemetry.explain --peak-tflops 197 \
        --hbm-gb 16 --out EXPLAIN.json

On CPU (tests, laptops) there is no meaningful chip peak, so rate fields
are null unless ``--peak-tflops``/``--peak-hbm-gbps`` are given; the
census, collectives and watermark are exact regardless.
"""

import argparse
import json
import os
import sys
import time


def _parse_args(argv=None):
    p = argparse.ArgumentParser(
        prog="python -m deepspeed_tpu.telemetry.explain",
        description="Cost-explorer report for a compiled train step")
    p.add_argument("--model", default="tiny",
                   help="tiny | gpt2 | gpt2-medium | gpt2-xl (default tiny)")
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--seq", type=int, default=128)
    p.add_argument("--zero", type=int, default=0, help="ZeRO stage (0-3)")
    p.add_argument("--devices", type=int, default=0,
                   help="force N virtual CPU devices (0 = whatever exists)")
    p.add_argument("--steps", type=int, default=2,
                   help="timed steps after priming (0 = static-only)")
    p.add_argument("--peak-tflops", type=float, default=0)
    p.add_argument("--peak-hbm-gbps", type=float, default=0)
    p.add_argument("--ici-gbps", type=float, default=0)
    p.add_argument("--hbm-gb", type=float, default=0)
    p.add_argument("--out", default="EXPLAIN.json")
    return p.parse_args(argv)


def main(argv=None):
    args = _parse_args(argv)
    if args.devices:
        # must land before any jax backend initialises
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.devices}")
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    import jax.numpy as jnp  # noqa: F401  (jax init before deepspeed)

    import deepspeed_tpu
    from deepspeed_tpu.models.gpt2 import (GPT2Config, GPT2LMHeadModel,
                                           PRESETS, synthetic_batch)
    from deepspeed_tpu.utils import groups

    if args.model == "tiny":
        cfg = GPT2Config(vocab_size=2048, n_positions=max(256, args.seq),
                         n_embd=128, n_layer=2, n_head=4)
    else:
        import dataclasses as _dc
        cfg = PRESETS[args.model]
        if args.seq > cfg.n_positions:
            cfg = _dc.replace(cfg, n_positions=args.seq)

    groups.destroy()
    groups.initialize()
    ce_block = {"enabled": True, "preflight": True}
    for key, val in (("peak_tflops", args.peak_tflops),
                     ("peak_hbm_gbps", args.peak_hbm_gbps),
                     ("ici_gbps", args.ici_gbps), ("hbm_gb", args.hbm_gb)):
        if val:
            ce_block[key] = val
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=GPT2LMHeadModel(cfg),
        config={
            "train_batch_size": args.batch_size,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
            "zero_optimization": {"stage": args.zero},
            "bf16": {"enabled": True},
            "steps_per_print": 10 ** 9,
            "telemetry": {"enabled": True, "trace": False,
                          "jsonl": False, "prometheus": False,
                          "cost_explorer": ce_block},
        },
        sample_batch=synthetic_batch(args.batch_size, args.seq,
                                     cfg.vocab_size))

    batch = synthetic_batch(args.batch_size, args.seq, cfg.vocab_size,
                            seed=1)
    step_time_s = None
    if args.steps > 0:
        engine.train_batch(batch=batch)          # prime (the one compile)
        jax.device_get(engine.state.step)
        t0 = time.perf_counter()
        for _ in range(args.steps):
            engine.train_batch(batch=batch)
        jax.device_get(engine.state.step)
        step_time_s = (time.perf_counter() - t0) / args.steps
    report = engine.explain_step(batch=batch, step_time_s=step_time_s)
    report["config"] = {
        "model": args.model, "batch_size": args.batch_size,
        "seq": args.seq, "zero_stage": args.zero,
        "n_devices": jax.device_count(),
        "n_params": int(sum(x.size for x in
                            jax.tree.leaves(engine.state.params))),
    }
    with open(args.out, "w") as f:
        json.dump(report, f, indent=1)
    print(json.dumps(report, indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
