"""CostExplorer — join the static HLO census with runtime step timing.

Answers the three questions the runtime telemetry (PR 1) alone cannot:

* **how fast is fast?** achieved TFLOPS vs the chip's roofline and MFU
  against a configurable peak;
* **what is the step bound by?** compute / memory / comm verdict from
  the census's flops, bytes-accessed and per-axis collective wire bytes
  against the chip's peak flops, HBM bandwidth and ICI bandwidth;
* **will it fit?** HBM watermark pre-flight (census argument + output -
  alias + temp bytes vs device HBM) BEFORE the first step executes.

The explorer is pure host-side arithmetic over an ``HloCensus`` — it
never touches the device, never compiles, and publishes its numbers as
gauges in the PR-1 metrics registry so the JSONL/Prometheus sinks carry
``model_flops_per_step``, ``hbm_watermark_bytes`` and
``collective_bytes{axes=...}`` with zero extra wiring.

Chip peaks: looked up from ``jax.devices()[0].device_kind`` for known
TPUs, overridable via the ``telemetry.cost_explorer`` config block
(``peak_tflops`` / ``peak_hbm_gbps`` / ``ici_gbps`` / ``hbm_gb``) — on
CPU (tests, virtual meshes) there is no meaningful peak, so rate-based
fields are reported as null unless overridden.
"""

from typing import Any, Dict, Optional

from deepspeed_tpu.telemetry.hlo_census import HloCensus
from deepspeed_tpu.utils.logging import logger

# device_kind substring -> (bf16 TFLOPS, HBM GB/s, ICI GB/s per link,
# HBM GiB). Public chip specs; ICI is the per-direction per-chip figure.
KNOWN_CHIPS = {
    # real hardware reports device_kind "TPU v5 lite" / "TPU v6 lite",
    # which normalizes to "v5lite"/"v6lite" — both spellings must match
    "v6lite": (918.0, 1640.0, 448.0, 32.0),
    "v6e": (918.0, 1640.0, 448.0, 32.0),
    "v5p": (459.0, 2765.0, 600.0, 95.0),
    "v5lite": (197.0, 819.0, 400.0, 16.0),
    "v5e": (197.0, 819.0, 400.0, 16.0),
    "v4": (275.0, 1228.0, 300.0, 32.0),
    "v3": (123.0, 900.0, 140.0, 32.0),
    "v2": (45.0, 700.0, 100.0, 16.0),
}


def detect_chip(device=None) -> Optional[Dict[str, float]]:
    """Peak spec dict for the local accelerator, or None (CPU/unknown)."""
    try:
        import jax
        d = device if device is not None else jax.local_devices()[0]
        kind = (getattr(d, "device_kind", "") or "").lower()
    except Exception:
        return None
    for key, (tf, hbm, ici, gib) in KNOWN_CHIPS.items():
        if key in kind.replace(" ", "").replace("tpu", ""):
            return {"device_kind": kind, "peak_tflops": tf,
                    "peak_hbm_gbps": hbm, "ici_gbps": ici,
                    "hbm_bytes": gib * 1024 ** 3}
    return None


def device_hbm_bytes(device=None) -> Optional[int]:
    """Device memory capacity: the allocator's own ``bytes_limit`` when
    the backend reports one, else the chip table, else None (CPU)."""
    try:
        import jax
        d = device if device is not None else jax.local_devices()[0]
        stats = d.memory_stats() or {}
        if stats.get("bytes_limit"):
            return int(stats["bytes_limit"])
    except Exception:
        pass
    chip = detect_chip(device)
    return int(chip["hbm_bytes"]) if chip else None


class CostExplorer:
    """Explains one compiled step program. Constructed from the parsed
    ``telemetry.cost_explorer`` config block (or bare, with overrides)."""

    def __init__(self, peak_tflops=None, peak_hbm_gbps=None, ici_gbps=None,
                 hbm_bytes=None, preflight_threshold=0.95, registry=None):
        chip = detect_chip() or {}
        self._preflight_warned = set()       # program names warned once
        self.device_kind = chip.get("device_kind", "unknown")
        self.peak_tflops = (float(peak_tflops) if peak_tflops
                            else chip.get("peak_tflops"))
        self.peak_hbm_gbps = (float(peak_hbm_gbps) if peak_hbm_gbps
                              else chip.get("peak_hbm_gbps"))
        self.ici_gbps = float(ici_gbps) if ici_gbps else chip.get("ici_gbps")
        self.hbm_bytes = (int(hbm_bytes) if hbm_bytes
                          else device_hbm_bytes())
        self.preflight_threshold = float(preflight_threshold)
        self.registry = registry

    @classmethod
    def from_config(cls, ce_config, registry=None):
        """Build from a ``DeepSpeedTelemetryConfig``'s cost-explorer
        fields (``None``/0 entries fall back to chip detection)."""
        return cls(
            peak_tflops=getattr(ce_config, "cost_explorer_peak_tflops", None),
            peak_hbm_gbps=getattr(ce_config, "cost_explorer_peak_hbm_gbps",
                                  None),
            ici_gbps=getattr(ce_config, "cost_explorer_ici_gbps", None),
            hbm_bytes=(int(ce_config.cost_explorer_hbm_gb * 1024 ** 3)
                       if getattr(ce_config, "cost_explorer_hbm_gb", 0)
                       else None),
            preflight_threshold=getattr(
                ce_config, "cost_explorer_preflight_threshold", 0.95),
            registry=registry)

    # ------------------------------------------------------------ pre-flight
    def preflight(self, census: HloCensus, name="step"):
        """HBM watermark check BEFORE the first execution. Returns the
        report dict; logs one warning line when the watermark crosses
        ``preflight_threshold`` x HBM (it will run — XLA already
        allocated it a budget — but with no headroom for the allocator,
        fragmentation, or a second program)."""
        wm = census.hbm_watermark_bytes
        report = {
            "hbm_watermark_bytes": wm,
            "hbm_watermark_gb": round(wm / 1024 ** 3, 3),
            "hbm_bytes": self.hbm_bytes,
            "hbm_utilization": (round(wm / self.hbm_bytes, 4)
                                if self.hbm_bytes else None),
            "fits": (wm <= self.hbm_bytes * self.preflight_threshold
                     if self.hbm_bytes else None),
        }
        if self.hbm_bytes and wm > self.hbm_bytes * self.preflight_threshold \
                and name not in self._preflight_warned:
            # once per program: explain() re-runs preflight for the report
            # numbers, and repeating the multi-line warning every call
            # would drown a per-epoch explain loop
            self._preflight_warned.add(name)
            logger.warning(
                "[cost-explorer] HBM pre-flight: %r needs %.2f GiB of "
                "%.2f GiB HBM (%.0f%% > %.0f%% threshold) — args+outputs-"
                "alias %.2f GiB, temps %.2f GiB. Expect allocator "
                "pressure or OOM; consider remat, a smaller micro-batch, "
                "or a higher ZeRO stage.", name, wm / 1024 ** 3,
                self.hbm_bytes / 1024 ** 3, 100.0 * wm / self.hbm_bytes,
                100.0 * self.preflight_threshold,
                (census.argument_bytes + census.output_bytes
                 - census.alias_bytes) / 1024 ** 3,
                census.temp_bytes / 1024 ** 3)
        return report

    # --------------------------------------------------------------- explain
    def explain(self, census: HloCensus, step_time_s=None,
                name="step", invocations=1) -> Dict[str, Any]:
        """The "explain this step" report: roofline attribution of the
        census against this chip's peaks, plus achieved-vs-peak when a
        measured ``step_time_s`` is supplied.

        ``invocations``: how many times the censused program runs per
        measured step — under gradient accumulation the census covers ONE
        micro step but ``step_time_s`` covers ``gas`` of them, so rates
        computed without the multiplier would be ~gas x too low. Scales
        the rate math only; the HBM watermark is per-program."""
        flops = census.flops * invocations
        total_bytes = census.bytes_accessed * invocations
        total_wire = census.total_wire_bytes * invocations
        peak_flops = (self.peak_tflops or 0.0) * 1e12
        hbm_bw = (self.peak_hbm_gbps or 0.0) * 1e9
        ici_bw = (self.ici_gbps or 0.0) * 1e9

        # per-phase floors: what the program CANNOT run faster than
        t_compute = flops / peak_flops if peak_flops else None
        t_memory = total_bytes / hbm_bw if hbm_bw else None
        t_comm = total_wire / ici_bw if ici_bw else None
        bounds = {"compute": t_compute, "memory": t_memory, "comm": t_comm}
        known = {k: v for k, v in bounds.items() if v}
        verdict = max(known, key=known.get) if known else "unknown"

        intensity = flops / total_bytes if total_bytes else None
        ridge = (peak_flops / hbm_bw if peak_flops and hbm_bw else None)

        achieved_tflops = mfu = None
        if step_time_s and step_time_s > 0 and flops:
            # 6 significant digits: CPU-scale numbers (1e-5 TFLOPS) must
            # survive; fixed decimal rounding would zero them
            achieved_tflops = float(f"{flops / step_time_s / 1e12:.6g}")
            if self.peak_tflops:
                mfu = float(f"{achieved_tflops / self.peak_tflops:.4g}")

        report = {
            "program": name,
            "program_invocations_per_step": invocations,
            "device_kind": self.device_kind,
            "n_devices": census.n_devices,
            "flops_per_step_per_device": flops,
            "bytes_accessed_per_step": total_bytes,
            "arithmetic_intensity_flops_per_byte": (
                round(intensity, 3) if intensity else None),
            "roofline_ridge_flops_per_byte": (
                round(ridge, 3) if ridge else None),
            "peak_tflops": self.peak_tflops,
            "peak_hbm_gbps": self.peak_hbm_gbps,
            "ici_gbps": self.ici_gbps,
            "step_time_s": step_time_s,
            "achieved_tflops": achieved_tflops,
            "mfu": mfu,
            "bound_floors_s": {k: (round(v, 6) if v else None)
                               for k, v in bounds.items()},
            "verdict": verdict,
            "collectives": {
                "counts": census.collective_counts,
                "wire_bytes": {k: v * invocations for k, v in
                               census.collective_wire_bytes.items()},
                "bytes_by_axis": {k: v * invocations for k, v in
                                  census.collective_bytes_by_axis.items()},
                "total_wire_bytes": total_wire,
            },
            "preflight": self.preflight(census, name=name),
        }
        if step_time_s and known:
            # how much of the measured step each floor explains
            report["floor_fractions_of_step"] = {
                k: round(v / step_time_s, 4)
                for k, v in known.items()}
        return report

    # --------------------------------------------------------------- publish
    def publish(self, census: HloCensus, report=None):
        """Gauge the census (and report, when given) into the metrics
        registry so the existing JSONL/Prometheus sinks export it."""
        reg = self.registry
        if reg is None:
            return
        reg.gauge("model_flops_per_step",
                  "XLA-counted flops of the compiled step program "
                  "(per device)").set(census.flops)
        reg.gauge("model_bytes_accessed_per_step",
                  "XLA-counted bytes accessed by the step program").set(
                      census.bytes_accessed)
        reg.gauge("hbm_watermark_bytes",
                  "static HBM watermark of the step program "
                  "(args + outputs - alias + temps)").set(
                      census.hbm_watermark_bytes)
        for axes, nbytes in census.collective_bytes_by_axis.items():
            reg.gauge("collective_bytes",
                      "per-participant collective wire bytes per step, "
                      "by mesh axis", labels={"axes": axes}).set(nbytes)
        for kind, count in census.collective_counts.items():
            reg.gauge("collective_ops",
                      "collective instructions in the step program",
                      labels={"kind": kind}).set(count)
        if report:
            if report.get("mfu") is not None:
                reg.gauge("model_mfu",
                          "achieved / peak flops of the step program").set(
                              report["mfu"])
            if report.get("achieved_tflops") is not None:
                reg.gauge("achieved_tflops",
                          "measured model TFLOPS per device").set(
                              report["achieved_tflops"])
