"""Structured census of a compiled XLA program.

The repo grew three disconnected XLA-introspection paths — string-counting
collectives in ``zero/aot_check.py``, a from-scratch recompile in the
flops profiler, and a purely analytic FLOPs formula in ``bench.py``. This
module is the shared substrate all of them now stand on: ONE pass over a
``jax.stages.Compiled`` artifact producing

* compiler cost analysis (flops / transcendentals / bytes accessed);
* compiler memory analysis (argument / output / alias / temp bytes) and
  the derived **HBM watermark** (args + outputs - aliased + temps: the
  static lower bound on live HBM while the program runs);
* a real parse of the post-optimization HLO text extracting every
  collective op with its **result byte volume, replica-group structure,
  and the mesh axis (or axes) it runs over** — replacing
  ``txt.count(op + "(")``, which could neither see bytes nor axes and
  miscounted on substring collisions (``all-gather`` vs
  ``all-gather-start``).

Parsing notes (verified against this jax/XLA's output):

* collective lines look like
  ``%all-reduce.1 = f32[] all-reduce(...), channel_id=5,
  replica_groups=[2,4]<=[8], use_global_device_ids=true, ...``;
* ``replica_groups`` comes in the explicit form ``{{0,4},{1,5}}`` and the
  iota ("v2") form ``[G,S]<=[N]`` with an optional reshape+transpose
  ``[G,S]<=[4,2]T(1,0)`` — all three appear in real programs;
* async pairs (``all-gather-start``/``-done``) describe ONE transfer: the
  ``-start`` is counted, the ``-done`` is not;
* ``collective-permute`` carries ``source_target_pairs`` instead of
  groups.

Everything here is static analysis of an ALREADY-compiled artifact:
calling it never traces, lowers, or compiles anything (``census_fn`` is
the explicit compile-from-scratch fallback for callers with no artifact).
"""

import dataclasses
import re
from typing import Any, Dict, List, Optional, Tuple

# dtype token -> itemsize, per the HLO shape grammar (f8 variants share
# one byte; opaque/token shapes carry no data and parse to 0)
_DTYPE_BYTES = {
    "pred": 1, "s2": 1, "u2": 1, "s4": 1, "u4": 1,
    "s8": 1, "u8": 1, "s16": 2, "u16": 2, "s32": 4, "u32": 4,
    "s64": 8, "u64": 8,
    "f8e5m2": 1, "f8e4m3": 1, "f8e4m3fn": 1, "f8e4m3b11fnuz": 1,
    "f8e5m2fnuz": 1, "f8e4m3fnuz": 1, "f8e3m4": 1, "f8e8m0fnu": 1,
    "bf16": 2, "f16": 2, "f32": 4, "f64": 8,
    "c64": 8, "c128": 16,
}

# one HLO array shape: dtype[dims]{layout}  (layout optional)
_SHAPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\](?:\{[^}]*\})?")

_COLLECTIVE_KINDS = ("all-gather", "all-reduce", "reduce-scatter",
                     "all-to-all", "collective-permute",
                     "collective-broadcast", "ragged-all-to-all")

# "%name = SHAPES kind(" where SHAPES is one shape or a (tuple, of, them).
# The kind is matched with lookahead "(" so fused instruction NAMES that
# merely contain a collective substring can't false-positive, and async
# "-start"/"-done" suffixes are captured explicitly.
_COLLECTIVE_LINE_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?[\w.\-]+\s*=\s*"
    r"(\([^)]*\)|[a-z][a-z0-9]*\[[0-9,]*\](?:\{[^}]*\})?)\s+"
    r"(" + "|".join(re.escape(k) for k in _COLLECTIVE_KINDS) +
    r")(-start|-done)?\(")

_REPLICA_GROUPS_RE = re.compile(
    r"replica_groups=(\{\{[^}]*(?:\},\{[^}]*)*\}\}|\{[0-9, ]*\}|"
    r"\[[0-9,]+\]<=\[[0-9,]+\](?:T\([0-9,]+\))?)")

_SOURCE_TARGET_RE = re.compile(r"source_target_pairs=\{([^}]*(?:\},\{[^}]*)*)\}")

_CHANNEL_RE = re.compile(r"channel_id=(\d+)")

_DIM_ATTR_RE = re.compile(r"dimensions=\{(\d+)\}")


def parse_shape_bytes(shape_str: str) -> Tuple[int, List[Tuple[str, Tuple[int, ...]]]]:
    """Total bytes + [(dtype, dims)] of one HLO result shape (array or
    tuple-of-arrays). Unknown dtypes contribute 0 bytes (opaque/token)."""
    elements = _shape_elements(shape_str)
    return (sum(b for _, _, b in elements),
            [(d, s) for d, s, _ in elements])


def _shape_elements(shape_str):
    """[(dtype, dims, bytes)] for each array in an HLO (tuple) shape."""
    out = []
    for dtype, dims in _SHAPE_RE.findall(shape_str):
        dim_t = tuple(int(d) for d in dims.split(",") if d != "")
        n = 1
        for d in dim_t:
            n *= d
        out.append((dtype, dim_t, n * _DTYPE_BYTES.get(dtype, 0)))
    return out


def _async_result_bytes(kind, elements):
    """Payload bytes of an async ``-start`` op, whose TUPLE result carries
    the operand(s) alongside the actual result (plus tiny u32/s32 context
    scalars on some backends) — summing the tuple would double count.
    Context scalars are excluded first; the result is then the largest
    element, except reduce-scatter where the result is the 1/g SHARD and
    the largest element is the unreduced input."""
    payload = [b for dtype, dims, b in elements
               if b > 0 and not (len(dims) == 0 and dtype in ("u32", "s32"))]
    if not payload:
        return 0
    if kind == "reduce-scatter":
        return min(payload)
    return max(payload)


def parse_replica_groups(attr: str) -> List[Tuple[int, ...]]:
    """Parse either replica-group syntax into explicit device-id groups.

    Explicit: ``{{0,4},{1,5}}`` (or the degenerate one-group ``{0,1,2}``).
    Iota v2: ``[G,S]<=[N]`` — ids ``0..N-1`` reshaped to [G, S]; the
    optional ``<=[a,b,..]T(p)`` first lays the ids out as [a,b,..],
    transposes by permutation p, then reshapes to [G, S].
    """
    attr = attr.strip()
    if attr.startswith("{"):
        inner = attr.strip("{}")
        if not inner:
            return []
        if "},{" in inner:
            return [tuple(int(x) for x in grp.split(",") if x.strip() != "")
                    for grp in inner.split("},{")]
        return [tuple(int(x) for x in inner.split(",") if x.strip() != "")]
    m = re.match(r"\[([0-9,]+)\]<=\[([0-9,]+)\](?:T\(([0-9,]+)\))?$", attr)
    if not m:
        raise ValueError(f"unrecognised replica_groups syntax: {attr!r}")
    out_shape = [int(x) for x in m.group(1).split(",")]
    src_shape = [int(x) for x in m.group(2).split(",")]
    n = 1
    for d in src_shape:
        n *= d
    try:
        import numpy as np
        ids = np.arange(n).reshape(src_shape)
        if m.group(3):
            ids = ids.transpose([int(x) for x in m.group(3).split(",")])
        ids = ids.reshape(out_shape)
        return [tuple(int(x) for x in row) for row in ids]
    except Exception as e:  # pragma: no cover - numpy is a hard dep anyway
        raise ValueError(f"bad iota replica_groups {attr!r}: {e}")


def _mesh_axis_partitions(mesh) -> Dict[str, frozenset]:
    """For every non-empty subset of mesh axes (sizes > 1), the partition
    of device ids a collective over exactly those axes would use: groups
    vary along the subset's axes and are constant along the rest.

    Returned as {axis-label: frozenset-of-frozenset-groups}; the label is
    the comma-joined axis names ("data" / "data,expert"). Mesh axis count
    is <= ~4 in this repo, so the 2^k subsets stay tiny.
    """
    import itertools

    import numpy as np
    ids = np.vectorize(lambda d: d.id)(mesh.devices)
    names = list(mesh.axis_names)
    real = [i for i, n in enumerate(names) if ids.shape[i] > 1]
    out = {}
    for r in range(1, len(real) + 1):
        for combo in itertools.combinations(real, r):
            moved = np.moveaxis(ids, combo, range(len(combo)))
            flat = moved.reshape(
                int(np.prod([ids.shape[i] for i in combo])), -1)
            groups = frozenset(frozenset(int(x) for x in flat[:, j])
                               for j in range(flat.shape[1]))
            out[",".join(names[i] for i in combo)] = groups
    return out


def _attr_axes(groups: List[Tuple[int, ...]],
               partitions: Dict[str, frozenset]) -> str:
    """Mesh-axis label for a collective's replica groups; 'unknown' when
    no axis subset matches, '' when no mesh was given."""
    if not partitions or not groups:
        return ""
    gset = frozenset(frozenset(g) for g in groups)
    for label, part in partitions.items():
        if gset == part:
            return label
    # subset match: op groups over FEWER devices than the mesh (e.g. a
    # program compiled over a mesh slice) — report containment
    for label, part in partitions.items():
        if all(any(g <= p for p in part) for g in gset):
            return label + "?"
    return "unknown"


@dataclasses.dataclass
class CollectiveOp:
    """One collective instruction of the compiled (per-device) program."""
    kind: str                    # all-gather / all-reduce / ...
    result_bytes: int            # bytes of the instruction's result shape
    shapes: List[Tuple[str, Tuple[int, ...]]]
    group_size: int              # participants per replica group
    n_groups: int
    axes: str                    # mesh-axis label ("data", "data,expert",
    #                              "unknown", "" when no mesh given)
    channel_id: Optional[int] = None
    dimension: Optional[int] = None

    @property
    def wire_bytes(self) -> int:
        """Estimated bytes ONE participant moves over the interconnect
        (ring algorithm accounting; exact for the standard algorithms):

        * all-gather: receives (g-1)/g of the gathered result;
        * reduce-scatter: result is the 1/g shard — sends/combines
          (g-1) x result;
        * all-reduce: reduce-scatter + all-gather = 2(g-1)/g x result;
        * all-to-all / collective-broadcast: (g-1)/g of the result;
        * collective-permute: the full result crosses a link.
        """
        g = max(self.group_size, 1)
        r = self.result_bytes
        if self.kind in ("all-gather", "all-to-all", "collective-broadcast",
                         "ragged-all-to-all"):
            return r * (g - 1) // g
        if self.kind == "reduce-scatter":
            return r * (g - 1)
        if self.kind == "all-reduce":
            return 2 * r * (g - 1) // g
        return r                               # collective-permute

    def to_dict(self):
        return {"kind": self.kind, "result_bytes": self.result_bytes,
                "wire_bytes": self.wire_bytes,
                "shapes": [f"{d}[{','.join(map(str, s))}]"
                           for d, s in self.shapes],
                "group_size": self.group_size, "n_groups": self.n_groups,
                "axes": self.axes, "channel_id": self.channel_id}


def parse_hlo_collectives(hlo_text: str, mesh=None) -> List[CollectiveOp]:
    """Extract every collective op (with bytes + mesh-axis attribution)
    from post-optimization HLO text. ``-done`` halves of async pairs are
    skipped — the ``-start`` carries the transfer."""
    partitions = _mesh_axis_partitions(mesh) if mesh is not None else {}
    mesh_size = int(getattr(mesh, "size", 0) or 0)
    ops = []
    for line in hlo_text.splitlines():
        m = _COLLECTIVE_LINE_RE.match(line)
        if not m or m.group(3) == "-done":
            continue
        shape_str, kind = m.group(1), m.group(2)
        elements = _shape_elements(shape_str)
        shapes = [(d, s) for d, s, _ in elements]
        if m.group(3) == "-start" and len(elements) > 1:
            result_bytes = _async_result_bytes(kind, elements)
        else:
            result_bytes = sum(b for _, _, b in elements)
        if kind == "collective-permute":
            pairs = []
            pm = _SOURCE_TARGET_RE.search(line)
            if pm:
                pairs = [tuple(int(x) for x in p.strip("{} ").split(","))
                         for p in pm.group(1).replace("},{", "|").split("|")
                         if p.strip("{} ")]
            groups, group_size = pairs, 2
        else:
            gm = _REPLICA_GROUPS_RE.search(line)
            groups = parse_replica_groups(gm.group(1)) if gm else []
            if not groups and mesh_size:
                # replica_groups={} is XLA's "every participant in one
                # group" — without the expansion the op would carry
                # group_size 1 / wire_bytes 0 and vanish from the
                # comm accounting
                groups = [tuple(range(mesh_size))]
            group_size = len(groups[0]) if groups else 1
        cm = _CHANNEL_RE.search(line)
        dm = _DIM_ATTR_RE.search(line)
        ops.append(CollectiveOp(
            kind=kind, result_bytes=result_bytes, shapes=shapes,
            group_size=group_size, n_groups=len(groups),
            axes=_attr_axes(groups, partitions),
            channel_id=int(cm.group(1)) if cm else None,
            dimension=int(dm.group(1)) if dm else None))
    return ops


@dataclasses.dataclass
class HloCensus:
    """The full static census of one compiled program.

    ``flops`` / ``bytes_accessed`` are the compiler's own cost analysis of
    the PER-DEVICE program (an SPMD module is the single-device slice, so
    these are per-chip numbers — multiply by device count for the global
    figure). ``hbm_watermark_bytes`` = arguments + outputs - aliased +
    temps: what must be simultaneously live in device memory, before any
    scheduler refinement."""
    flops: float = 0.0
    transcendentals: float = 0.0
    bytes_accessed: float = 0.0
    argument_bytes: int = 0
    output_bytes: int = 0
    alias_bytes: int = 0
    temp_bytes: int = 0
    generated_code_bytes: int = 0
    collectives: List[CollectiveOp] = dataclasses.field(default_factory=list)
    n_devices: int = 1

    @property
    def hbm_watermark_bytes(self) -> int:
        return (self.argument_bytes + self.output_bytes
                - self.alias_bytes + self.temp_bytes)

    @property
    def collective_counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for op in self.collectives:
            out[op.kind] = out.get(op.kind, 0) + 1
        return out

    @property
    def collective_result_bytes(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for op in self.collectives:
            out[op.kind] = out.get(op.kind, 0) + op.result_bytes
        return out

    @property
    def collective_wire_bytes(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for op in self.collectives:
            out[op.kind] = out.get(op.kind, 0) + op.wire_bytes
        return out

    @property
    def collective_bytes_by_axis(self) -> Dict[str, int]:
        """Per-participant wire bytes, keyed by mesh-axis label."""
        out: Dict[str, int] = {}
        for op in self.collectives:
            key = op.axes or "unattributed"
            out[key] = out.get(key, 0) + op.wire_bytes
        return out

    @property
    def total_wire_bytes(self) -> int:
        return sum(op.wire_bytes for op in self.collectives)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "flops": self.flops,
            "transcendentals": self.transcendentals,
            "bytes_accessed": self.bytes_accessed,
            "memory": {
                "argument_bytes": self.argument_bytes,
                "output_bytes": self.output_bytes,
                "alias_bytes": self.alias_bytes,
                "temp_bytes": self.temp_bytes,
                "generated_code_bytes": self.generated_code_bytes,
                "hbm_watermark_bytes": self.hbm_watermark_bytes,
            },
            "n_devices": self.n_devices,
            "collectives": {
                "counts": self.collective_counts,
                "result_bytes": self.collective_result_bytes,
                "wire_bytes": self.collective_wire_bytes,
                "bytes_by_axis": self.collective_bytes_by_axis,
                "total_wire_bytes": self.total_wire_bytes,
                "ops": [op.to_dict() for op in self.collectives],
            },
        }


def census_compiled(compiled, mesh=None) -> HloCensus:
    """Census a ``jax.stages.Compiled`` (or anything exposing
    ``cost_analysis`` / ``memory_analysis`` / ``as_text``). Pure reading:
    never triggers tracing or compilation. Each analysis is best-effort —
    a backend refusing one (some remote clients) zeroes that section
    instead of failing the census."""
    from deepspeed_tpu.utils.logging import logger
    census = HloCensus()
    try:
        costs = compiled.cost_analysis()
        if isinstance(costs, (list, tuple)):   # older jax returns [dict]
            costs = costs[0] if costs else {}
        costs = dict(costs or {})
        census.flops = float(costs.get("flops", 0.0))
        census.transcendentals = float(costs.get("transcendentals", 0.0))
        census.bytes_accessed = float(costs.get("bytes accessed", 0.0))
    except Exception as e:
        logger.warning("[hlo-census] cost_analysis unavailable (%s); "
                       "flops/bytes report 0", e)
    try:
        ma = compiled.memory_analysis()
        census.argument_bytes = int(ma.argument_size_in_bytes)
        census.output_bytes = int(ma.output_size_in_bytes)
        census.alias_bytes = int(ma.alias_size_in_bytes)
        census.temp_bytes = int(ma.temp_size_in_bytes)
        census.generated_code_bytes = int(
            getattr(ma, "generated_code_size_in_bytes", 0))
    except Exception as e:
        logger.warning("[hlo-census] memory_analysis unavailable (%s); "
                       "watermark reports 0", e)
    try:
        census.collectives = parse_hlo_collectives(compiled.as_text(),
                                                   mesh=mesh)
    except Exception as e:
        logger.warning("[hlo-census] HLO text parse failed (%s); "
                       "collectives report empty", e)
    if mesh is not None:
        census.n_devices = getattr(mesh, "size", 1)
    return census


def collective_schedule_positions(hlo_text: str) -> List[Dict[str, Any]]:
    """Normalized instruction positions of the collectives inside the
    ENTRY computation — the tail-clustering evidence for comm overlap.

    Each collective (``-done`` halves skipped, as everywhere in this
    module) is reported as ``{"kind", "pos"}`` with ``pos`` = its index
    over the entry computation's instruction count, in [0, 1]. A program
    whose gradient reductions are serialized behind the whole backward
    shows them clustered near 1.0; per-bucket reductions issued as the
    backward produces each bucket spread across the stream. The dump
    order is the dependency/schedule order XLA prints post-optimization
    — structural evidence, not a measured timeline (the measured half is
    the off/on step time next to it in ``OVERLAP_BENCH.json``)."""
    lines = hlo_text.splitlines()
    entry, depth = [], 0
    in_entry = False
    for line in lines:
        if not in_entry and line.lstrip().startswith("ENTRY "):
            in_entry = True
            depth = line.count("{") - line.count("}")
            continue
        if not in_entry:
            continue
        depth += line.count("{") - line.count("}")
        if "=" in line:
            entry.append(line)
        if depth <= 0:
            break
    total = len(entry)
    out: List[Dict[str, Any]] = []
    for i, line in enumerate(entry):
        m = _COLLECTIVE_LINE_RE.match(line)
        if not m or m.group(3) == "-done":
            continue
        out.append({"kind": m.group(2) + (m.group(3) or ""),
                    "pos": round(i / max(total - 1, 1), 4)})
    return out


def census_fn(fn, *args, mesh=None, static_argnums=()) -> HloCensus:
    """Compile-from-scratch fallback: jit + lower + compile ``fn(*args)``
    and census the artifact. This PAYS ONE XLA COMPILE — callers holding
    an engine should go through ``engine.get_cost_census()``, which reads
    the engine's own compiled step program instead."""
    import jax
    compiled = jax.jit(fn, static_argnums=static_argnums).lower(
        *args).compile()
    return census_compiled(compiled, mesh=mesh)
