"""Training-health observatory — in-step numerics telemetry + forensics.

The run-time telemetry (PR 1) watches the *system* and the cost explorer
(PR 2) watches the *compiled program*; this module watches the *numerics*.
Three pieces:

* **In-step stats** (``build_bucket_spec`` / ``bucket_grad_stats``): the
  engine's grad epilogue — already compiled into the train step — emits a
  small static-shaped stats pytree on-device: global grad/param norms,
  update ratio, per-top-level-module grad-norm *buckets* (grouped, never
  per-leaf, so the payload is bounded by ``bucket_depth``), the dynamic
  loss-scale scalars, and a non-finite **provenance bitmask** saying which
  module bucket went inf/nan. Zero extra host syncs: the host holds only
  device references and fetches at ``cadence`` (default
  ``steps_per_print``), where the print path already pays the sync.
* **Anomaly detection** (:class:`HealthMonitor`): host-side EWMA/z-score
  rules — loss spike, grad-norm explosion, sustained overflow-skip
  streak, loss-scale collapse to ``min_scale``, stalled loss — that
  escalate warn → structured ``HEALTH.json`` snapshot (ring buffer of
  recent samples + verdict + the cost-census header) → optional forced
  trace export, so a diverging run explains itself from its artifacts
  instead of from a rerun. The reference ships the same scalars through
  its monitor (loss scale / grad norm / skipped steps); here they also
  carry provenance.
* **CLI**: ``python -m deepspeed_tpu.telemetry.health --render HEALTH.json``
  pretty-prints a snapshot; ``--demo`` builds a tiny fp16 engine, injects
  a non-finite gradient into ONE module bucket and writes the resulting
  forensics file (the committed repo-root ``HEALTH.json`` example).

Everything here is pure stdlib + jnp; when ``telemetry.health`` is off the
engine's step programs are byte-identical to before.
"""

import json
import math
import os
import time
from collections import deque
from typing import NamedTuple, Tuple

from deepspeed_tpu.telemetry import escalation
from deepspeed_tpu.utils.logging import logger

# the provenance bitmask is a uint32: at most 32 buckets, ever
MAX_BUCKETS = 32
OVERFLOW_BUCKET = "(other)"

HEALTH_SCHEMA = "deepspeed_tpu.health/1"

# rule name -> severity tier (worst tier seen decides the verdict)
RULE_SEVERITY = {
    "nonfinite_grads": "critical",
    "overflow_streak": "critical",
    "loss_scale_collapse": "critical",
    "loss_spike": "warning",
    "grad_norm_spike": "warning",
    "loss_stall": "watch",
}
_SEVERITY_ORDER = ("critical", "warning", "watch")


class BucketSpec(NamedTuple):
    """Static grouping of param-tree leaves into named module buckets.

    ``names[i]`` labels bucket ``i``; ``leaf_buckets[j]`` is the bucket of
    the j-th leaf in ``jax.tree.leaves`` order. Built ONCE at engine init
    from the param tree's structure, so the traced stats computation is a
    fixed unrolled reduction — no dynamic shapes, no retraces."""
    names: Tuple[str, ...]
    leaf_buckets: Tuple[int, ...]


def _path_component(entry):
    for attr in ("key", "idx", "name"):
        if hasattr(entry, attr):
            return str(getattr(entry, attr))
    return str(entry)


def build_bucket_spec(params, depth=8) -> BucketSpec:
    """Group param leaves by their top-level module path component.

    A tree whose top level is a single container (e.g. everything under
    ``"transformer"``) descends one extra level so the buckets carry
    information. More than ``depth`` distinct modules: the first
    ``depth - 1`` keep their names and the rest fold into ``(other)`` —
    the payload must stay bounded for 48-layer models too."""
    import jax
    depth = max(1, min(int(depth), MAX_BUCKETS))
    flat, _ = jax.tree_util.tree_flatten_with_path(params)
    if not flat:
        return BucketSpec(("<empty>",), ())
    tops = {(_path_component(p[0]) if p else "<root>") for p, _ in flat}
    descend = len(tops) < 2 and any(len(p) >= 2 for p, _ in flat)

    def name_for(path):
        if not path:
            return "<root>"
        if descend and len(path) >= 2:
            return f"{_path_component(path[0])}/{_path_component(path[1])}"
        return _path_component(path[0])

    raw = [name_for(p) for p, _ in flat]
    order = list(dict.fromkeys(raw))
    if len(order) > depth:
        names = order[:depth - 1] + [OVERFLOW_BUCKET]
        index = {n: i for i, n in enumerate(order[:depth - 1])}
        mapping = {n: index.get(n, depth - 1) for n in order}
    else:
        names = order
        mapping = {n: i for i, n in enumerate(order)}
    return BucketSpec(tuple(names), tuple(mapping[n] for n in raw))


def bucket_grad_stats(spec: BucketSpec, grads):
    """Traced: per-bucket grad L2 norms (f32[B]) + non-finite provenance
    bitmask (uint32, bit i set = bucket i holds an inf/nan leaf).

    Runs INSIDE the already-compiled step on the unscaled, pre-clip
    gradient tree; one full read of the grad tree, fused by XLA with the
    epilogue's existing finite-check / global-norm reductions."""
    import jax
    import jax.numpy as jnp
    leaves = jax.tree_util.tree_leaves(grads)
    assert len(leaves) == len(spec.leaf_buckets), (
        f"bucket spec built for {len(spec.leaf_buckets)} leaves but the "
        f"grad tree has {len(leaves)} — spec and tree diverged")
    n = len(spec.names)
    sq = [jnp.float32(0.0)] * n
    bad = [jnp.bool_(False)] * n
    for leaf, b in zip(leaves, spec.leaf_buckets):
        g = leaf.astype(jnp.float32)
        sq[b] = sq[b] + jnp.sum(g * g)
        bad[b] = bad[b] | ~jnp.all(jnp.isfinite(leaf))
    norms = jnp.sqrt(jnp.stack(sq))
    mask = jnp.uint32(0)
    for i, flag in enumerate(bad):
        mask = mask | jnp.where(flag, jnp.uint32(1 << i), jnp.uint32(0))
    return norms, mask


def decode_nonfinite_mask(mask, names):
    """Bucket names whose provenance bit is set."""
    mask = int(mask)
    return [n for i, n in enumerate(names) if mask & (1 << i)]


def json_safe(obj):
    """Recursively replace non-finite floats with their string names.

    ``json.dump`` would otherwise emit bare ``Infinity``/``NaN`` tokens —
    Python-only extensions that jq / JSON.parse / Go reject — and a
    forensics file about inf/nan gradients is EXACTLY where those values
    appear. Strings keep them readable and the file valid JSON."""
    if isinstance(obj, float):
        if math.isnan(obj):
            return "NaN"
        if math.isinf(obj):
            return "Infinity" if obj > 0 else "-Infinity"
        return obj
    if isinstance(obj, dict):
        return {k: json_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [json_safe(v) for v in obj]
    return obj


class Ewma:
    """Exponentially-weighted mean + variance (West's EW recurrence)."""

    def __init__(self, alpha=0.1):
        self.alpha = float(alpha)
        self.mean = None
        self.var = 0.0
        self.n = 0

    def zscore(self, x, rel_floor=0.0):
        """z of ``x`` against the CURRENT state (call before update).
        None while there is no history; +inf for a non-finite sample.
        ``rel_floor`` floors the sd at that fraction of ``|mean|`` — the
        EW variance starts near zero, and without a floor the first few
        samples of ordinary noise read as double-digit sigmas."""
        if self.mean is None or self.n < 2:
            return None
        if not math.isfinite(x):
            return float("inf")
        sd = math.sqrt(max(self.var, 0.0))
        sd = max(sd, rel_floor * abs(self.mean))
        if sd <= 0:
            return 0.0 if x == self.mean else float("inf")
        return (x - self.mean) / sd

    def update(self, x):
        if not math.isfinite(x):
            return   # an inf/nan sample must not poison the baseline
        if self.mean is None:
            self.mean = float(x)
        else:
            d = float(x) - self.mean
            self.mean += self.alpha * d
            self.var = (1.0 - self.alpha) * (self.var + self.alpha * d * d)
        self.n += 1

    def state(self):
        return {"mean": self.mean, "var": self.var, "n": self.n}


class HealthMonitor:
    """Host-side anomaly detection + forensics over the in-step stats.

    Two inputs, two cadences:

    * :meth:`note_step` — every global step, host-only facts (did the step
      overflow-skip?). Free: no device sync. Drives the overflow-streak
      rule exactly, not sampled.
    * :meth:`observe` — one fetched stats sample at the engine's health
      cadence. Drives the EWMA/z-score rules and fills the ring buffer.

    Escalation on a firing rule: one warning log per rule (later firings
    only counted), a throttled ``HEALTH.json`` snapshot write, the
    ``on_escalate`` hook (the engine wires the tracer's forced export),
    and a ``health_anomalies_total{rule=...}`` counter in the registry.
    """

    SNAPSHOT_MIN_INTERVAL_S = 5.0
    MAX_ANOMALY_HISTORY = 100
    # sd floor as a fraction of |EWMA mean|: at z=6 a spike must ALSO sit
    # >= 30% above the baseline — real explosions are orders of magnitude
    Z_SD_FLOOR_REL = 0.05

    def __init__(self, job_name="", snapshot_path="HEALTH.json",
                 bucket_names=(), ewma_alpha=0.1, loss_spike_zscore=6.0,
                 grad_spike_zscore=6.0, warmup_samples=8, overflow_streak=4,
                 min_scale=1.0, stall_window=50, stall_rel_delta=1e-3,
                 ring_size=256, registry=None, on_escalate=None,
                 on_anomaly=None, census_fn=None, log_fn=None):
        self.job_name = job_name
        self.snapshot_path = snapshot_path
        self.bucket_names = list(bucket_names)
        self.loss_spike_zscore = float(loss_spike_zscore)
        self.grad_spike_zscore = float(grad_spike_zscore)
        self.warmup_samples = int(warmup_samples)
        self.overflow_streak_threshold = int(overflow_streak)
        self.min_scale = float(min_scale)
        self.stall_window = int(stall_window)
        self.stall_rel_delta = float(stall_rel_delta)
        self.registry = registry
        self.on_escalate = on_escalate
        self.on_anomaly = on_anomaly
        self.census_fn = census_fn
        self._log = log_fn or logger.warning

        self.ewma_loss = Ewma(ewma_alpha)
        self.ewma_grad = Ewma(ewma_alpha)
        self.ring = deque(maxlen=int(ring_size))
        self.anomalies = []          # bounded history, most recent last
        self.rule_counts = {}        # rule -> total firings
        self.steps_seen = 0
        self.samples_seen = 0
        self.skipped_seen = 0
        self.overflow_streak = 0
        self.max_overflow_streak = 0
        self.last_sample = None
        self.last_step = -1
        self._stall_ring = deque(maxlen=max(2, self.stall_window))
        self._stall_active = False
        self._snapshots_written = 0
        self._last_snapshot_t = float("-inf")

    @classmethod
    def from_config(cls, tconfig, output_path="telemetry/", job_name="",
                    registry=None, on_escalate=None, on_anomaly=None):
        """Build from a parsed ``DeepSpeedTelemetryConfig``'s ``health_*``
        fields (the engine fills mesh-dependent attributes — bucket
        names, fp16 ``min_scale``, the census header — after its step
        functions exist)."""
        snap = getattr(tconfig, "health_snapshot_file", "") or "HEALTH.json"
        if not os.path.isabs(snap):
            snap = os.path.join(output_path or ".", snap)
        return cls(
            job_name=job_name,
            snapshot_path=snap,
            ewma_alpha=getattr(tconfig, "health_ewma_alpha", 0.1),
            loss_spike_zscore=getattr(tconfig, "health_loss_spike_zscore",
                                      6.0),
            grad_spike_zscore=getattr(tconfig, "health_grad_spike_zscore",
                                      6.0),
            warmup_samples=getattr(tconfig, "health_warmup_samples", 8),
            overflow_streak=getattr(tconfig, "health_overflow_streak", 4),
            stall_window=getattr(tconfig, "health_stall_window", 50),
            stall_rel_delta=getattr(tconfig, "health_stall_rel_delta", 1e-3),
            ring_size=getattr(tconfig, "health_ring_size", 256),
            registry=registry, on_escalate=on_escalate,
            on_anomaly=on_anomaly)

    # ------------------------------------------------------------ per step
    def note_step(self, step, overflowed):
        """Host-only per-step bookkeeping (no device sync). The overflow
        streak is tracked HERE, per step, so a sustained skip run fires at
        exactly ``overflow_streak`` steps even between cadence fetches —
        the hysteresis=2 failure mode (first overflow: no scale change, no
        signal) is invisible at any sampled cadence."""
        self.steps_seen += 1
        if overflowed:
            self.skipped_seen += 1
            self.overflow_streak += 1
            self.max_overflow_streak = max(self.max_overflow_streak,
                                           self.overflow_streak)
            if self.overflow_streak == self.overflow_streak_threshold:
                self._escalate([{
                    "rule": "overflow_streak", "step": int(step),
                    "severity": RULE_SEVERITY["overflow_streak"],
                    "detail": f"{self.overflow_streak} consecutive "
                              f"overflow-skipped optimizer steps",
                }])
        else:
            self.overflow_streak = 0

    # ------------------------------------------------------------ cadence
    def observe(self, sample):
        """Evaluate the anomaly rules on one fetched stats sample (a plain
        dict of host floats — see the engine's ``_health_tick``). Returns
        the list of anomalies that fired on THIS sample."""
        step = int(sample.get("step", -1))
        anoms = []

        loss = sample.get("loss")
        if loss is not None:
            z = self.ewma_loss.zscore(loss, rel_floor=self.Z_SD_FLOOR_REL)
            if (z is not None and self.samples_seen >= self.warmup_samples
                    and z > self.loss_spike_zscore):
                anoms.append({
                    "rule": "loss_spike", "step": step,
                    "severity": RULE_SEVERITY["loss_spike"],
                    "detail": f"loss {loss:.6g} is {z:.1f} sigma above the "
                              f"EWMA {self.ewma_loss.mean:.6g}",
                    "zscore": None if math.isinf(z) else round(z, 2)})
            self.ewma_loss.update(loss)
            # stalled loss: the EWMA moved < stall_rel_delta (relative)
            # across the whole stall window of observations
            if self.ewma_loss.mean is not None and self.stall_window > 1:
                self._stall_ring.append(self.ewma_loss.mean)
                if len(self._stall_ring) == self._stall_ring.maxlen:
                    first, last = self._stall_ring[0], self._stall_ring[-1]
                    rel = abs(last - first) / max(abs(first), 1e-12)
                    if rel < self.stall_rel_delta and not self._stall_active:
                        self._stall_active = True
                        anoms.append({
                            "rule": "loss_stall", "step": step,
                            "severity": RULE_SEVERITY["loss_stall"],
                            "detail": f"loss EWMA moved {rel:.2e} (rel) over "
                                      f"the last {self.stall_window} health "
                                      f"samples"})
                    elif rel >= self.stall_rel_delta:
                        self._stall_active = False

        gn = sample.get("grad_norm")
        if gn is not None:
            z = self.ewma_grad.zscore(gn, rel_floor=self.Z_SD_FLOOR_REL)
            if (z is not None and self.samples_seen >= self.warmup_samples
                    and z > self.grad_spike_zscore):
                anoms.append({
                    "rule": "grad_norm_spike", "step": step,
                    "severity": RULE_SEVERITY["grad_norm_spike"],
                    "detail": f"grad norm {gn:.6g} is {z:.1f} sigma above "
                              f"the EWMA {self.ewma_grad.mean:.6g}",
                    "zscore": None if math.isinf(z) else round(z, 2)})
            self.ewma_grad.update(gn)

        mask = int(sample.get("nonfinite_buckets") or 0)
        if mask:
            buckets = decode_nonfinite_mask(mask, self.bucket_names) or \
                [f"bit{i}" for i in range(MAX_BUCKETS) if mask & (1 << i)]
            anoms.append({
                "rule": "nonfinite_grads", "step": step,
                "severity": RULE_SEVERITY["nonfinite_grads"],
                "detail": "non-finite gradients first seen in module "
                          f"bucket(s): {', '.join(buckets)}",
                "buckets": buckets})

        scale = sample.get("loss_scale")
        if (sample.get("overflow") and scale is not None
                and scale <= self.min_scale):
            anoms.append({
                "rule": "loss_scale_collapse", "step": step,
                "severity": RULE_SEVERITY["loss_scale_collapse"],
                "detail": f"dynamic loss scale collapsed to min_scale "
                          f"({scale:g}) and the step still overflows — "
                          f"the run cannot make progress in fp16"})

        self.samples_seen += 1
        self.last_sample = sample
        self.last_step = step
        self.ring.append(sample)
        if anoms:
            self._escalate(anoms)
        return anoms

    # ---------------------------------------------------------- escalation
    def _escalate(self, anoms):
        # the shared protocol (telemetry/escalation.py): warn-once ->
        # counters -> bounded history -> forced-first snapshot ->
        # chronicle emit -> hooks
        escalation.escalate(self, anoms, tag="health",
                            counter="health_anomalies_total",
                            counter_help="training-health anomaly rule "
                                         "firings")

    # ------------------------------------------------------------- outputs
    def verdict(self):
        if not self.samples_seen and not self.steps_seen:
            return "unknown"
        seen = {RULE_SEVERITY.get(r, "warning") for r in self.rule_counts}
        for tier in _SEVERITY_ORDER:
            if tier in seen:
                return tier
        return "healthy"

    def report(self):
        """The full forensics dict (what ``HEALTH.json`` holds)."""
        census = None
        if self.census_fn is not None:
            try:
                census = self.census_fn()
            except Exception:
                census = None
        return {
            "schema": HEALTH_SCHEMA,
            "enabled": True,
            "job_name": self.job_name,
            "verdict": self.verdict(),
            "rules": {
                "loss_spike_zscore": self.loss_spike_zscore,
                "grad_spike_zscore": self.grad_spike_zscore,
                "warmup_samples": self.warmup_samples,
                "overflow_streak": self.overflow_streak_threshold,
                "min_scale": self.min_scale,
                "stall_window": self.stall_window,
                "stall_rel_delta": self.stall_rel_delta,
                "ewma_alpha": self.ewma_loss.alpha,
            },
            "bucket_names": list(self.bucket_names),
            "counters": {
                "steps_seen": self.steps_seen,
                "samples_seen": self.samples_seen,
                "skipped_steps": self.skipped_seen,
                "overflow_streak": self.overflow_streak,
                "max_overflow_streak": self.max_overflow_streak,
                "anomaly_counts": dict(self.rule_counts),
            },
            "ewma": {"loss": self.ewma_loss.state(),
                     "grad_norm": self.ewma_grad.state()},
            "anomalies": list(self.anomalies),
            "last_sample": self.last_sample,
            "ring": list(self.ring),
            "cost_census": census,
        }

    def write_snapshot(self, path=None, force=False):
        """Write ``HEALTH.json``. Periodic (escalation-driven) writes are
        throttled like the trace export — re-serialising the ring every
        anomaly during a death spiral would stall the train thread."""
        if not force and (time.monotonic() - self._last_snapshot_t
                          < self.SNAPSHOT_MIN_INTERVAL_S):
            return None
        self._last_snapshot_t = time.monotonic()
        path = path or self.snapshot_path
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(json_safe(self.report()), f, indent=1, default=repr,
                      allow_nan=False)
        self._snapshots_written += 1
        return path

    def close(self):
        """Final snapshot — only when there is something to explain."""
        if self.anomalies:
            self.write_snapshot(force=True)


# --------------------------------------------------------------------- CLI

def render(report):
    """Human-readable rendering of a HEALTH.json report dict."""
    lines = []
    c = report.get("counters", {})
    lines.append(f"health verdict: {report.get('verdict', '?').upper()}"
                 f"  (job {report.get('job_name') or '-'})")
    lines.append(f"  steps seen {c.get('steps_seen', 0)}, samples "
                 f"{c.get('samples_seen', 0)}, skipped "
                 f"{c.get('skipped_steps', 0)}, max overflow streak "
                 f"{c.get('max_overflow_streak', 0)}")
    ew = report.get("ewma", {})
    for k in ("loss", "grad_norm"):
        s = ew.get(k) or {}
        if s.get("mean") is not None:
            lines.append(f"  ewma {k}: {s['mean']:.6g} "
                         f"(var {s.get('var', 0):.3g}, n {s.get('n', 0)})")
    for a in report.get("anomalies", []):
        extra = f" buckets={a['buckets']}" if a.get("buckets") else ""
        lines.append(f"  [{a.get('severity', '?'):8s}] step "
                     f"{a.get('step')}: {a.get('rule')} — "
                     f"{a.get('detail')}{extra}")
    if not report.get("anomalies"):
        lines.append("  no anomalies recorded")
    s = report.get("last_sample") or {}
    if s:
        lines.append(
            f"  last sample @ step {s.get('step')}: loss={s.get('loss')}, "
            f"grad_norm={s.get('grad_norm')}, "
            f"update_ratio={s.get('update_ratio')}, "
            f"loss_scale={s.get('loss_scale')}")
    cen = report.get("cost_census")
    if cen:
        lines.append(f"  program {cen.get('program')}: "
                     f"{cen.get('flops_per_device', 0):.3g} flops/device, "
                     f"HBM watermark {cen.get('hbm_watermark_bytes', 0)} B, "
                     f"{cen.get('n_devices')} devices")
    return "\n".join(lines)


def _demo(args):
    """Build a tiny fp16 engine, inject an inf into ONE module bucket's
    accumulated gradient, and write the resulting forensics snapshot —
    the committed repo-root HEALTH.json example comes from here."""
    if args.devices:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.devices}")
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    import jax.numpy as jnp
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models.simple import SimpleModel, sample_batch
    from deepspeed_tpu.utils import groups

    groups.destroy()
    groups.initialize()
    hidden = 32
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=hidden, nlayers=2),
        config={
            "train_batch_size": 16,
            "train_micro_batch_size_per_gpu": 1,
            "gradient_accumulation_steps": 2,
            "steps_per_print": 1,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
            "fp16": {"enabled": True, "loss_scale": 0,
                     "initial_scale_power": 8},
            "telemetry": {"enabled": True, "trace": False,
                          "jsonl": False, "prometheus": False,
                          "cost_explorer": {"enabled": True},
                          "health": {"enabled": True, "cadence": 1,
                                     "warmup_samples": 2,
                                     "snapshot_file": os.path.abspath(
                                         args.out)}},
        },
        sample_batch=sample_batch(8, hidden))
    rng = np.random.default_rng(0)

    def micro(seed):
        x = rng.standard_normal((8, hidden)).astype(np.float32)
        y = rng.standard_normal((8, hidden)).astype(np.float32)
        return (x, y)

    for step in range(args.steps):
        inject = step == args.steps - 1
        for _ in range(2):
            loss = engine.forward(micro(step))
            engine.backward(loss)
        if inject:
            # poison exactly ONE module bucket: Dense_1's accumulated grads
            acc = jax.tree_util.tree_map_with_path(
                lambda p, x: jax.device_put(
                    jnp.full_like(x, jnp.inf), x.sharding)
                if "Dense_1" in jax.tree_util.keystr(p) else x,
                engine.state.acc_grads)
            engine.state = engine.state._replace(acc_grads=acc)
        engine.step()
    report = engine.health_report(write=True)
    print(render(report))
    print(f"\nwrote {args.out}")
    return 0


def main(argv=None):
    import argparse
    p = argparse.ArgumentParser(
        prog="python -m deepspeed_tpu.telemetry.health",
        description="Render a HEALTH.json snapshot, or run the forensics "
                    "demo (tiny fp16 engine + injected non-finite grad)")
    p.add_argument("--render", metavar="HEALTH.json",
                   help="pretty-print an existing snapshot and exit")
    p.add_argument("--demo", action="store_true",
                   help="build a tiny engine, inject an inf into one "
                        "module bucket, write the snapshot")
    p.add_argument("--steps", type=int, default=12)
    p.add_argument("--devices", type=int, default=8,
                   help="virtual CPU devices for the demo (0 = existing)")
    p.add_argument("--out", default="HEALTH.json")
    args = p.parse_args(argv)
    if args.render:
        with open(args.render) as f:
            print(render(json.load(f)))
        return 0
    if args.demo:
        return _demo(args)
    p.print_help()
    return 2


if __name__ == "__main__":
    import sys
    sys.exit(main())
