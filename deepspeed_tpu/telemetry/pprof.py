"""Dependency-free pprof Profile protobuf reader.

``jax.profiler.device_memory_profile()`` returns a gzip-compressed
``perftools.profiles.Profile`` protobuf — the pprof format — describing
every live device allocation (one sample per buffer/executable, with a
byte count and an allocation stack). Reading it back normally requires
the ``pprof`` tool or a protobuf runtime; this module instead decodes
the wire format by hand (varint + length-delimited scanning, same house
style as ``xplane.py``) so the memory observatory can attribute live HBM
with zero extra dependencies.

It intentionally imports neither ``tensorflow`` nor ``pprof``/protobuf
(a static guard in ``tests/unit/test_pprof.py`` pins this).

Field numbers (stable since the schema is append-only upstream):

    Profile:    sample_type=1 sample=2 mapping=3 location=4 function=5
                string_table=6 time_nanos=9 duration_nanos=10
                period_type=11 period=12 default_sample_type=14
    ValueType:  type=1 unit=2             (string-table indices)
    Sample:     location_id=1 value=2     (packed varints)
                label=3
    Label:      key=1 str=2 num=3 num_unit=4
    Location:   id=1 mapping_id=2 address=3 line=4
    Line:       function_id=1 line=2
    Function:   id=1 name=2 system_name=3 filename=4 start_line=5

jax's device-memory profile carries two sample types —
``(allocations, count)`` and ``(space, bytes)`` — and labels each sample
with ``kind`` (``buffer`` | ``executable``) and ``device``.

All error offsets are absolute positions in the DECOMPRESSED stream
(the gzip envelope is stripped before decoding).
"""

import gzip
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

__all__ = [
    "PprofParseError",
    "ValueType",
    "Label",
    "Sample",
    "Location",
    "Function",
    "Profile",
    "parse_profile",
    "parse_profile_file",
    "live_bytes_by_kind",
    "summarize_samples",
]


class PprofParseError(ValueError):
    """Raised when the wire stream is malformed or truncated.

    The message always names the absolute byte offset (into the
    decompressed stream) at which decoding failed so a corrupt profile
    can be triaged with a hex dump.
    """


# ---------------------------------------------------------------------------
# wire-format primitives
# ---------------------------------------------------------------------------

_WIRE_VARINT = 0
_WIRE_64BIT = 1
_WIRE_LEN = 2
_WIRE_32BIT = 5

_GZIP_MAGIC = b"\x1f\x8b"


def _read_varint(buf: bytes, pos: int, end: int) -> Tuple[int, int]:
    """Decode one base-128 varint; returns (value, new_pos)."""
    result = 0
    shift = 0
    start = pos
    while True:
        if pos >= end:
            raise PprofParseError(
                f"truncated varint at byte offset {start}")
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not (b & 0x80):
            return result, pos
        shift += 7
        if shift > 63:
            raise PprofParseError(
                f"varint wider than 64 bits at byte offset {start}")


def _int64_signed(value: int) -> int:
    """Reinterpret a 64-bit varint as two's-complement int64.

    (pprof int64 fields are NOT zigzag on the wire — negative values are
    sent as 10-byte two's-complement varints.)
    """
    if value >= 1 << 63:
        value -= 1 << 64
    return value


def _iter_fields(buf: bytes, pos: int, end: int):
    """Yield (field_number, wire_type, payload, value_offset) tuples.

    ``payload`` is an int for varint fields, a ``(start, end)`` span
    tuple for length-delimited fields, a bytes slice for fixed fields.
    """
    while pos < end:
        key, pos = _read_varint(buf, pos, end)
        field_no = key >> 3
        wire = key & 0x7
        if field_no == 0:
            raise PprofParseError(
                f"illegal field number 0 at byte offset {pos}")
        if wire == _WIRE_VARINT:
            val, pos = _read_varint(buf, pos, end)
            yield field_no, wire, val, pos
        elif wire == _WIRE_LEN:
            length, pos = _read_varint(buf, pos, end)
            if pos + length > end:
                raise PprofParseError(
                    f"length-delimited field overruns buffer at byte "
                    f"offset {pos} (need {length} bytes, have {end - pos})")
            yield field_no, wire, (pos, pos + length), pos
            pos += length
        elif wire == _WIRE_64BIT:
            if pos + 8 > end:
                raise PprofParseError(
                    f"truncated fixed64 at byte offset {pos}")
            yield field_no, wire, buf[pos:pos + 8], pos
            pos += 8
        elif wire == _WIRE_32BIT:
            if pos + 4 > end:
                raise PprofParseError(
                    f"truncated fixed32 at byte offset {pos}")
            yield field_no, wire, buf[pos:pos + 4], pos
            pos += 4
        else:
            raise PprofParseError(
                f"unsupported wire type {wire} at byte offset {pos}")


def _decode_str(buf: bytes, span: Tuple[int, int], where: str) -> str:
    try:
        return bytes(buf[span[0]:span[1]]).decode("utf-8", "replace")
    except Exception as exc:  # pragma: no cover - decode("replace") is total
        raise PprofParseError(
            f"undecodable {where} string at byte offset {span[0]}: {exc}")


def _decode_packed_int64s(buf: bytes, span: Tuple[int, int],
                          signed: bool) -> List[int]:
    """Packed repeated varints (proto3 packs repeated scalars by default;
    an unpacked encoder is still legal — the per-field decoders below
    accept both)."""
    out = []
    pos, end = span
    while pos < end:
        v, pos = _read_varint(buf, pos, end)
        out.append(_int64_signed(v) if signed else v)
    return out


# ---------------------------------------------------------------------------
# decoded model (string-typed fields hold STRING-TABLE INDICES — the
# string table may follow the samples on the wire, so resolution happens
# through Profile.string() after the whole message is decoded)
# ---------------------------------------------------------------------------

@dataclass
class ValueType:
    type: int = 0       # string-table index
    unit: int = 0       # string-table index


@dataclass
class Label:
    key: int = 0        # string-table index
    str: int = 0        # string-table index (0 = unset)
    num: int = 0
    num_unit: int = 0   # string-table index


@dataclass
class Sample:
    location_ids: List[int] = field(default_factory=list)
    values: List[int] = field(default_factory=list)
    labels: List[Label] = field(default_factory=list)


@dataclass
class Location:
    id: int = 0
    mapping_id: int = 0
    address: int = 0
    function_ids: List[int] = field(default_factory=list)  # leaf first


@dataclass
class Function:
    id: int = 0
    name: int = 0        # string-table index
    system_name: int = 0
    filename: int = 0
    start_line: int = 0


@dataclass
class Profile:
    sample_types: List[ValueType] = field(default_factory=list)
    samples: List[Sample] = field(default_factory=list)
    locations: Dict[int, Location] = field(default_factory=dict)
    functions: Dict[int, Function] = field(default_factory=dict)
    string_table: List[str] = field(default_factory=list)
    time_nanos: int = 0
    duration_nanos: int = 0
    period_type: Optional[ValueType] = None
    period: int = 0
    default_sample_type: int = 0

    # -------------------------------------------------------- resolution
    def string(self, idx: int) -> str:
        """String-table lookup; out-of-range indices resolve to '' (the
        empty string is index 0 by pprof convention)."""
        if 0 <= idx < len(self.string_table):
            return self.string_table[idx]
        return ""

    def value_index(self, unit: str = "bytes") -> Optional[int]:
        """Index into ``Sample.values`` of the sample type measured in
        ``unit`` (the device-memory profile has ``count`` and ``bytes``).
        None when no sample type carries that unit."""
        for i, vt in enumerate(self.sample_types):
            if self.string(vt.unit) == unit:
                return i
        return None

    def sample_labels(self, sample: Sample) -> Dict[str, object]:
        """Resolve a sample's labels to {key: str-or-int}."""
        out = {}
        for lb in sample.labels:
            key = self.string(lb.key)
            if not key:
                continue
            out[key] = self.string(lb.str) if lb.str else lb.num
        return out

    def sample_stack(self, sample: Sample) -> List[str]:
        """Function names along the sample's location chain, leaf first.
        Locations without line info contribute their address as hex."""
        names = []
        for loc_id in sample.location_ids:
            loc = self.locations.get(loc_id)
            if loc is None:
                continue
            if not loc.function_ids:
                names.append(f"0x{loc.address:x}")
                continue
            for fid in loc.function_ids:
                fn = self.functions.get(fid)
                names.append(self.string(fn.name) if fn else "")
        return names


# ---------------------------------------------------------------------------
# message decoders
# ---------------------------------------------------------------------------

def _decode_value_type(buf: bytes, span: Tuple[int, int]) -> ValueType:
    vt = ValueType()
    for fno, wire, payload, off in _iter_fields(buf, span[0], span[1]):
        if fno == 1 and wire == _WIRE_VARINT:
            vt.type = _int64_signed(payload)
        elif fno == 2 and wire == _WIRE_VARINT:
            vt.unit = _int64_signed(payload)
    return vt


def _decode_label(buf: bytes, span: Tuple[int, int]) -> Label:
    lb = Label()
    for fno, wire, payload, off in _iter_fields(buf, span[0], span[1]):
        if fno == 1 and wire == _WIRE_VARINT:
            lb.key = _int64_signed(payload)
        elif fno == 2 and wire == _WIRE_VARINT:
            lb.str = _int64_signed(payload)
        elif fno == 3 and wire == _WIRE_VARINT:
            lb.num = _int64_signed(payload)
        elif fno == 4 and wire == _WIRE_VARINT:
            lb.num_unit = _int64_signed(payload)
    return lb


def _decode_sample(buf: bytes, span: Tuple[int, int]) -> Sample:
    s = Sample()
    for fno, wire, payload, off in _iter_fields(buf, span[0], span[1]):
        if fno == 1 and wire == _WIRE_LEN:
            s.location_ids += _decode_packed_int64s(buf, payload,
                                                    signed=False)
        elif fno == 1 and wire == _WIRE_VARINT:     # unpacked encoder
            s.location_ids.append(payload)
        elif fno == 2 and wire == _WIRE_LEN:
            s.values += _decode_packed_int64s(buf, payload, signed=True)
        elif fno == 2 and wire == _WIRE_VARINT:
            s.values.append(_int64_signed(payload))
        elif fno == 3 and wire == _WIRE_LEN:
            s.labels.append(_decode_label(buf, payload))
    return s


def _decode_line_function_id(buf: bytes, span: Tuple[int, int]) -> int:
    fid = 0
    for fno, wire, payload, off in _iter_fields(buf, span[0], span[1]):
        if fno == 1 and wire == _WIRE_VARINT:
            fid = payload
    return fid


def _decode_location(buf: bytes, span: Tuple[int, int]) -> Location:
    loc = Location()
    for fno, wire, payload, off in _iter_fields(buf, span[0], span[1]):
        if fno == 1 and wire == _WIRE_VARINT:
            loc.id = payload
        elif fno == 2 and wire == _WIRE_VARINT:
            loc.mapping_id = payload
        elif fno == 3 and wire == _WIRE_VARINT:
            loc.address = payload
        elif fno == 4 and wire == _WIRE_LEN:
            loc.function_ids.append(
                _decode_line_function_id(buf, payload))
    return loc


def _decode_function(buf: bytes, span: Tuple[int, int]) -> Function:
    fn = Function()
    for fno, wire, payload, off in _iter_fields(buf, span[0], span[1]):
        if fno == 1 and wire == _WIRE_VARINT:
            fn.id = payload
        elif fno == 2 and wire == _WIRE_VARINT:
            fn.name = _int64_signed(payload)
        elif fno == 3 and wire == _WIRE_VARINT:
            fn.system_name = _int64_signed(payload)
        elif fno == 4 and wire == _WIRE_VARINT:
            fn.filename = _int64_signed(payload)
        elif fno == 5 and wire == _WIRE_VARINT:
            fn.start_line = _int64_signed(payload)
    return fn


def parse_profile(data: bytes) -> Profile:
    """Decode a serialized pprof Profile from memory.

    Accepts both the gzip envelope ``device_memory_profile`` returns and
    a bare serialized Profile (the two are distinguished by the gzip
    magic, not by trial decompression).
    """
    if data[:2] == _GZIP_MAGIC:
        try:
            data = gzip.decompress(data)
        except Exception as exc:
            raise PprofParseError(f"corrupt gzip envelope: {exc}")
    prof = Profile()
    for fno, wire, payload, off in _iter_fields(data, 0, len(data)):
        if fno == 1 and wire == _WIRE_LEN:
            prof.sample_types.append(_decode_value_type(data, payload))
        elif fno == 2 and wire == _WIRE_LEN:
            prof.samples.append(_decode_sample(data, payload))
        elif fno == 4 and wire == _WIRE_LEN:
            loc = _decode_location(data, payload)
            prof.locations[loc.id] = loc
        elif fno == 5 and wire == _WIRE_LEN:
            fn = _decode_function(data, payload)
            prof.functions[fn.id] = fn
        elif fno == 6 and wire == _WIRE_LEN:
            prof.string_table.append(_decode_str(data, payload,
                                                 "string table"))
        elif fno == 9 and wire == _WIRE_VARINT:
            prof.time_nanos = _int64_signed(payload)
        elif fno == 10 and wire == _WIRE_VARINT:
            prof.duration_nanos = _int64_signed(payload)
        elif fno == 11 and wire == _WIRE_LEN:
            prof.period_type = _decode_value_type(data, payload)
        elif fno == 12 and wire == _WIRE_VARINT:
            prof.period = _int64_signed(payload)
        elif fno == 14 and wire == _WIRE_VARINT:
            prof.default_sample_type = _int64_signed(payload)
    return prof


def parse_profile_file(path: str) -> Profile:
    with open(path, "rb") as f:
        return parse_profile(f.read())


# ---------------------------------------------------------------------------
# device-memory summaries
# ---------------------------------------------------------------------------

def live_bytes_by_kind(profile: Profile) -> Dict[str, int]:
    """Total live bytes per ``kind`` label (``buffer`` holds array
    allocations, ``executable`` compiled programs; unlabeled samples land
    under ``(unlabeled)``). Empty dict when the profile carries no
    bytes-typed sample values."""
    bi = profile.value_index("bytes")
    if bi is None:
        return {}
    out: Dict[str, int] = {}
    for s in profile.samples:
        if bi >= len(s.values):
            continue
        kind = profile.sample_labels(s).get("kind") or "(unlabeled)"
        out[kind] = out.get(kind, 0) + s.values[bi]
    return out


def summarize_samples(profile: Profile, top: int = 10) -> List[dict]:
    """The ``top`` largest samples by bytes: {bytes, count, kind, device,
    stack} — the forensics view the observatory embeds in its report."""
    bi = profile.value_index("bytes")
    ci = profile.value_index("count")
    if bi is None:
        return []
    rows = []
    for s in profile.samples:
        if bi >= len(s.values):
            continue
        labels = profile.sample_labels(s)
        rows.append({
            "bytes": s.values[bi],
            "count": (s.values[ci]
                      if ci is not None and ci < len(s.values) else None),
            "kind": labels.get("kind") or "(unlabeled)",
            "device": labels.get("device"),
            "stack": profile.sample_stack(s)[:4],
        })
    rows.sort(key=lambda r: -r["bytes"])
    return rows[:top]


def fetch_device_memory_profile() -> bytes:
    """The one deliberately jax-touching helper: fetch the live pprof
    profile from the backend (gzip bytes). Host-side runtime query — no
    compilation, no device compute — but NOT free; callers fetch at
    cadence only. Raises whatever jax raises when no backend exists."""
    import jax.profiler
    return jax.profiler.device_memory_profile()


def _main(argv=None):  # pragma: no cover - thin debugging CLI
    import argparse
    import json
    p = argparse.ArgumentParser(
        prog="python -m deepspeed_tpu.telemetry.pprof",
        description="Decode a pprof device-memory profile "
                    "(.pb / .pb.gz) and print a summary.")
    p.add_argument("path")
    p.add_argument("--top", type=int, default=10)
    args = p.parse_args(argv)
    prof = parse_profile_file(args.path)
    print(json.dumps({
        "sample_types": [(prof.string(v.type), prof.string(v.unit))
                         for v in prof.sample_types],
        "samples": len(prof.samples),
        "live_bytes_by_kind": live_bytes_by_kind(prof),
        "top": summarize_samples(prof, args.top),
    }, indent=1))
    return 0


if __name__ == "__main__":  # pragma: no cover
    import sys
    sys.exit(_main())
