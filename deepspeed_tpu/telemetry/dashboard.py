"""Mission-control terminal dashboard — one screen over the live plane.

``python -m deepspeed_tpu.telemetry.dashboard --url http://host:port``
polls an :mod:`obs_server` endpoint (``/api/report/*``); ``--dir
telemetry/`` reads the same reports off the artifact dir instead
(GOODPUT.json, SLO_REPORT.json, SERVING_HEALTH.json, INCIDENTS.json) —
the offline post-mortem view of the exact same screen. Stdlib-only
ANSI rendering (no curses dependency — works over any dumb ssh tty):

* header — job, source, uptime, scrape age;
* throughput sparkline — steps/s (training) or tok/s (serving),
  accumulated across polls;
* goodput category bars — where the wall-clock went;
* SLO burn gauges — per objective, fast/slow windows, tier;
* fleet lanes — one per federated rank (status, staleness, scrape
  health) + fleet-scope burn, when the federation aggregator is live;
* last incidents — id, severity, root cause, rules.

Rendering is pure (``render_frame(reports, ...) -> str``) so the unit
tests drive it with canned reports. The default renders ONE frame and
exits (scriptable; ``--once`` kept as an explicit alias); ``--watch``
auto-refreshes every ``--interval`` seconds and exits cleanly on
Ctrl-C. ``--plain`` pins the no-ANSI render the tests drive."""

import argparse
import json
import os
import time
from collections import deque

BLOCKS = " ▁▂▃▄▅▆▇█"
BOLD, DIM, RESET = "\033[1m", "\033[2m", "\033[0m"
RED, YELLOW, GREEN = "\033[91m", "\033[93m", "\033[92m"
CLEAR = "\033[2J\033[H"

# goodput categories worth a bar, in ledger order
_GOODPUT_GOOD = ("device_compute", "host_dispatch")


def _color(s, c, plain=False):
    return s if plain else f"{c}{s}{RESET}"


def sparkline(values, width=48):
    """Unicode sparkline of the last *width* values (empty-safe)."""
    vals = list(values)[-width:]
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    span = (hi - lo) or 1.0
    return "".join(
        BLOCKS[1 + int((v - lo) / span * (len(BLOCKS) - 2))]
        for v in vals)


def bar(frac, width=30):
    frac = min(1.0, max(0.0, frac))
    n = int(round(frac * width))
    return "█" * n + "·" * (width - n)


def fetch_url(base, name, token="", timeout=3.0):
    """One ``/api/report/<name>`` poll; None on any failure (a dashboard
    must survive its server restarting)."""
    import urllib.request
    req = urllib.request.Request(
        f"{base.rstrip('/')}/api/report/{name}")
    if token:
        req.add_header("Authorization", f"Bearer {token}")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return json.loads(r.read().decode())
    except Exception:
        return None


def fetch_dir(dirpath, name):
    """The artifact-dir counterpart: the committed snapshot files."""
    files = {"goodput": "GOODPUT.json", "slo": "SLO_REPORT.json",
             "serving": "SERVING_HEALTH.json",
             "incidents": "INCIDENTS.json", "health": "HEALTH.json",
             "federation": "FLEET_CONTROL.json"}
    path = os.path.join(dirpath, files.get(name, f"{name}.json"))
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def gather(source, is_url, token=""):
    names = ("goodput", "slo", "serving", "incidents", "health",
             "federation")
    if is_url:
        return {n: fetch_url(source, n, token=token) for n in names}
    reports = {n: fetch_dir(source, n) for n in names}
    # SLO_REPORT.json embeds its demo incident chain; surface it when
    # the dir has no standalone INCIDENTS.json
    slo = reports.get("slo")
    if reports.get("incidents") is None and isinstance(slo, dict):
        reports["incidents"] = slo.get("incidents")
    return reports


# ------------------------------------------------------------- rendering

def _throughput_line(reports, history, width, plain):
    """Update *history* from this poll's reports; render the sparkline.
    Serving tok/s when a serving report is live, else training steps."""
    serving = reports.get("serving") or {}
    goodput = reports.get("goodput") or {}
    label, value = None, None
    totals = serving.get("totals") or {}
    if totals.get("tokens"):
        label, value = "tok", totals.get("tokens")
    elif goodput.get("steps_seen"):
        label, value = "steps", goodput.get("steps_seen")
    if value is not None:
        history.append(float(value))
    deltas = [b - a for a, b in zip(history, list(history)[1:])]
    line = sparkline(deltas or list(history), width=width - 20)
    cur = f"{deltas[-1]:g}" if deltas else "-"
    return (f"{label or 'throughput':>10} {line} "
            f"{_color(cur, BOLD, plain)}/poll")


def _goodput_lines(goodput, width, plain):
    if not goodput or not goodput.get("enabled", True):
        return [f"{DIM if not plain else ''}goodput: not armed"
                f"{RESET if not plain else ''}"]
    totals = goodput.get("totals") or {}
    elapsed = goodput.get("elapsed_s") or sum(totals.values()) or 1.0
    frac = goodput.get("goodput_fraction")
    head = "goodput"
    if frac is not None:
        c = GREEN if frac >= 0.7 else YELLOW if frac >= 0.4 else RED
        head += f" {_color(f'{frac:.1%}', c, plain)}"
    lines = [head]
    for cat, secs in sorted(totals.items(), key=lambda kv: -kv[1])[:6]:
        f = secs / max(elapsed, 1e-9)
        mark = "+" if cat in _GOODPUT_GOOD else "-"
        lines.append(f"  {mark} {cat:<18} {bar(f, width=width - 40)} "
                     f"{f:6.1%}")
    return lines


def _slo_lines(slo, width, plain):
    if not slo or not slo.get("enabled", True):
        return [f"{DIM if not plain else ''}slo: not armed"
                f"{RESET if not plain else ''}"]
    lines = [f"slo burn ({slo.get('evals', 0)} evals)"]
    for name, o in sorted((slo.get("objectives") or {}).items()):
        tier = o.get("tier", "ok")
        c = {"page": RED, "fast": YELLOW}.get(tier, GREEN)
        lines.append(f"  {name:<18} target {o.get('target'):g} "
                     f"{_color(tier.upper(), c, plain)}")
        for wname in ("fast", "slow"):
            w = (o.get("windows") or {}).get(wname)
            if not w:
                continue
            burn = w.get("burn")
            # gauge scale: full bar at 10x budget burn
            lines.append(
                f"    {wname:>4} {w.get('window_s'):>6g}s "
                f"{bar((burn or 0.0) / 10.0, width=width - 44)} "
                f"{'-' if burn is None else f'{burn:5.2f}x'}"
                f"{' BURNING' if w.get('burning') else ''}")
    return lines


def _fleet_lines(federation, width, plain):
    """The fleet view: one lane per rank (status, last-seen age, scrape
    health) + the fleet-scope burn gauges from the aggregator's merged
    SLO. Rendered only when a federation report is live — a
    single-process plane keeps its single-process screen."""
    if not federation or not federation.get("enabled", True):
        return []
    peers = federation.get("peers") or []
    n_stale = federation.get("n_stale", 0)
    c = RED if n_stale else GREEN
    lines = [f"fleet ({len(peers)} peer(s), "
             f"{_color(str(n_stale), c, plain)} stale, "
             f"{federation.get('n_merged_events', federation.get('counters', {}).get('events_merged_total', 0))} "
             f"merged event(s))"]
    for p in peers:
        status = p.get("status", "?")
        sc = {"ok": GREEN, "stale": RED}.get(status, YELLOW)
        age = p.get("last_seen_age_s")
        lines.append(
            f"  r{p.get('rank')!s:<4} {_color(f'{status:<5}', sc, plain)} "
            f"{p.get('url', ''):<28} "
            f"seen {'never' if age is None else f'{age:5.1f}s ago'}  "
            f"{p.get('events_held', 0):>5} ev  "
            f"{p.get('errors', 0)} err")
    fleet_slo = federation.get("slo")
    if fleet_slo:
        lines += _slo_lines(fleet_slo, width, plain)
    return lines


def _incident_lines(incidents, plain):
    incs = (incidents or {}).get("incidents") or []
    if not incs:
        return [f"{DIM if not plain else ''}incidents: none"
                f"{RESET if not plain else ''}"]
    lines = [f"incidents ({len(incs)})"]
    for i in incs[-3:]:
        rc = i.get("root_cause") or {}
        sev = i.get("severity") or "-"
        c = RED if sev == "critical" else YELLOW
        lines.append(
            f"  #{i.get('id')} {_color(sev, c, plain)} "
            f"{rc.get('kind')}/{rc.get('source')} "
            f"{rc.get('rule') or rc.get('chaos') or ''} "
            f"rules={','.join(i.get('rules') or [])}")
    return lines


def render_frame(reports, history=None, width=80, plain=False,
                 source=""):
    """One dashboard frame from a ``{name: report-or-None}`` dict.
    Pure — the unit tests feed canned reports."""
    history = history if history is not None else deque(maxlen=120)
    slo = reports.get("slo") or {}
    job = slo.get("job_name") or (reports.get("goodput") or {}).get(
        "job_name") or "-"
    lines = [
        _color(f" deepspeed_tpu mission control — job {job} "
               f"[{source or 'local'}]", BOLD, plain),
        "─" * min(width, 100),
        _throughput_line(reports, history, width, plain),
        "",
    ]
    lines += _goodput_lines(reports.get("goodput"), width, plain)
    lines.append("")
    lines += _slo_lines(reports.get("slo"), width, plain)
    lines.append("")
    fleet = _fleet_lines(reports.get("federation"), width, plain)
    if fleet:
        lines += fleet
        lines.append("")
    lines += _incident_lines(reports.get("incidents"), plain)
    return "\n".join(lines)


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="terminal dashboard over the live observability "
                    "plane (or an artifact dir)")
    src = ap.add_mutually_exclusive_group(required=True)
    src.add_argument("--url", help="obs_server base url "
                                   "(http://127.0.0.1:PORT)")
    src.add_argument("--dir", help="artifact dir with the JSON "
                                   "snapshots (offline view)")
    ap.add_argument("--token", default="", help="bearer token")
    ap.add_argument("--interval", type=float, default=2.0)
    ap.add_argument("--width", type=int, default=100)
    ap.add_argument("--once", action="store_true",
                    help="render one frame and exit (the default; kept "
                         "for scripts that pinned the flag)")
    ap.add_argument("--watch", action="store_true",
                    help="auto-refresh every --interval seconds until "
                         "Ctrl-C (clean exit, no traceback)")
    ap.add_argument("--plain", action="store_true",
                    help="no ANSI colors (pipes/tests); the render pin "
                         "the frame tests drive")
    args = ap.parse_args(argv)
    source = args.url or args.dir
    history = deque(maxlen=240)
    try:
        while True:
            reports = gather(source, is_url=bool(args.url),
                             token=args.token)
            frame = render_frame(reports, history=history,
                                 width=args.width, plain=args.plain,
                                 source=source)
            if not args.watch:
                print(frame)
                return 0
            print((frame if args.plain else CLEAR + frame), flush=True)
            time.sleep(max(0.2, args.interval))
    except KeyboardInterrupt:
        # a watch session ends at the keyboard; that is not an error
        print("", flush=True)
        return 0


if __name__ == "__main__":
    raise SystemExit(main())
