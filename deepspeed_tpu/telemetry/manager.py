"""TelemetryManager — one object owning the per-run telemetry state.

Constructed by the engine from the parsed ``telemetry`` config block.
Rank-0 only (like ``MonitorMaster``): non-zero ranks get the disabled
manager whose every surface is a no-op, so engine call sites need no rank
checks. When enabled it:

* installs its ``Tracer`` / ``MetricsRegistry`` as the process globals so
  library code (``checkpoint_io``, timers) reaches them via
  ``telemetry.trace_span`` / ``metrics.get_registry`` without plumbing;
* arms the compile watch (wrapping happens at the engine, which knows its
  jitted entry points) and the jax.monitoring backend-compile listener;
* exports the Chrome trace on ``flush()`` (the engine calls it at
  ``steps_per_print`` cadence) and once more at interpreter exit, so a
  crashed or un-torn-down run still leaves a readable trace.

File layout under ``<output_path>/``: ``<job>.trace.json`` (Chrome trace),
``<job>.jsonl`` + ``<job>.prom`` (written by the MonitorMaster sinks which
share this manager's registry).
"""

import atexit
import os

from deepspeed_tpu.telemetry import compile_watch as _cw
from deepspeed_tpu.telemetry import metrics as _metrics
from deepspeed_tpu.telemetry import tracer as _tracer_mod
from deepspeed_tpu.telemetry.metrics import device_memory_stats


class TelemetryManager:
    def __init__(self, config=None, rank=0):
        self.config = config
        self.enabled = bool(config is not None
                            and getattr(config, "enabled", False)
                            and rank == 0)
        if not self.enabled:
            self.tracer = _tracer_mod.Tracer(enabled=False)
            self.registry = None
            self.compile_watch = None
            self.trace_path = None
            self.health = None
            self.goodput = None
            self.memory = None
            return

        out = config.output_path or "telemetry/"
        job = config.job_name or "DeepSpeedJobName"
        os.makedirs(out, exist_ok=True)
        self.output_path = out
        self.job_name = job
        self.trace_path = os.path.join(out, f"{job}.trace.json")

        self.registry = _metrics.MetricsRegistry()
        _metrics.set_registry(self.registry)
        self.tracer = _tracer_mod.Tracer(
            enabled=bool(config.trace),
            jax_annotations=bool(config.jax_annotations),
            max_events=int(config.max_trace_events))
        _tracer_mod.set_tracer(self.tracer)
        self.compile_watch = (_cw.CompileWatch(self.registry)
                              if config.compile_watch else None)
        if config.compile_watch:
            _cw.install_global_listener(self.registry)
        # training-health observatory (telemetry/health.py): the monitor is
        # rank-0/host-side like everything here; the engine fills in the
        # mesh-dependent attributes (bucket names, fp16 min_scale, census
        # header) once its step functions exist, and feeds note_step /
        # observe from its train loop.
        self.health = None
        if getattr(config, "health_enabled", False):
            from deepspeed_tpu.telemetry.health import HealthMonitor
            on_escalate = (self._force_trace_export
                           if getattr(config, "health_trace_on_anomaly",
                                      True) and config.trace else None)
            self.health = HealthMonitor.from_config(
                config, output_path=out, job_name=job,
                registry=self.registry, on_escalate=on_escalate)
        # goodput ledger (telemetry/ledger.py): wall-clock attribution.
        # Installed as the process-global ledger so library code
        # (dataloader next(), checkpoint_io, the compile watch's
        # backend-compile listener) attributes without plumbing; the
        # engine wires the step-loop call sites and drives the ticks.
        self.goodput = None
        if getattr(config, "goodput_enabled", False):
            from deepspeed_tpu.telemetry import ledger as _ledger_mod
            self.goodput = _ledger_mod.GoodputLedger.from_config(
                config, output_path=out, job_name=job,
                registry=self.registry,
                on_escalate=(self._force_trace_export
                             if config.trace else None))
            _ledger_mod.set_ledger(self.goodput)
        # HBM residency observatory (telemetry/memory_observatory.py):
        # host-side like the health monitor; the engine fills in the
        # watermark prediction / HBM budget once its census exists and
        # feeds observe() from the cadence tick.
        self.memory = None
        if getattr(config, "memory_enabled", False):
            from deepspeed_tpu.telemetry.memory_observatory import \
                MemoryMonitor
            self.memory = MemoryMonitor.from_config(
                config, output_path=out, job_name=job,
                registry=self.registry,
                on_escalate=(self._force_trace_export
                             if config.trace else None))
        self._closed = False
        self._last_export_t = float("-inf")
        self._last_export_n = -1
        # process-global handle, mirroring tracer/metrics/ledger: code
        # that has no engine reference (the serving observatory's
        # trace-flush escalation) reaches the live manager through it
        set_manager(self)
        atexit.register(self.close)

    # ---------------------------------------------------------------- spans
    def span(self, name, **args):
        return self.tracer.span(name, **args)

    def instant(self, name, **args):
        self.tracer.instant(name, **args)

    # -------------------------------------------------------------- compile
    def wrap_compiled(self, fn, name):
        """Compile-watch instrumentation for a jitted entry point; identity
        when disabled (or fn is None)."""
        if fn is None or self.compile_watch is None:
            return fn
        return self.compile_watch.wrap(fn, name)

    # -------------------------------------------------------------- metrics
    def publish_device_memory(self):
        """Gauge the accelerator (or host-RSS fallback) memory stats."""
        if not self.enabled or not getattr(self.config, "memory_metrics",
                                           True):
            return
        stats = device_memory_stats()
        src = stats.pop("source", "none")
        # one canonical label vocabulary: a real backend memory_stats()
        # publishes as source=hbm; the psutil/resource fallbacks keep
        # their host_* names so dashboards can never mistake process RSS
        # for device residency (the autotuner/observatory refuse them).
        label = {"device": "hbm"}.get(src, src)
        for k, v in stats.items():
            self.registry.gauge(f"device_memory_{k}",
                                f"memory stat '{k}'",
                                labels={"source": label}).set(v)

    # ----------------------------------------------------------------- sinks
    # re-serialising the whole trace buffer is O(events); at print cadence
    # on a long run that would stall the train thread. Periodic flushes
    # are therefore throttled (skip if nothing new, at most one export per
    # interval); close()/atexit force the final complete export.
    EXPORT_MIN_INTERVAL_S = 5.0

    def flush(self, force=False):
        if not (self.enabled and self.config.trace):
            return
        import time
        n = self.tracer.event_count()
        if not force:
            if n == self._last_export_n:
                return
            if time.monotonic() - self._last_export_t < \
                    self.EXPORT_MIN_INTERVAL_S:
                return
        self._last_export_n = n
        self._last_export_t = time.monotonic()
        self.tracer.export(self.trace_path)

    def _force_trace_export(self):
        """Anomaly-escalation hook: flush the trace NOW (still subject to
        the flush throttle's 5 s floor between repeated anomalies)."""
        self.flush()

    def close(self):
        if not self.enabled or self._closed:
            return
        self._closed = True
        if self.health is not None:
            self.health.close()
        if self.memory is not None:
            self.memory.close()
        if self.goodput is not None:
            from deepspeed_tpu.telemetry import ledger as _ledger_mod
            self.goodput.close()
            _ledger_mod.reset_ledger(if_current=self.goodput)
        self.flush(force=True)
        _cw.uninstall_global_listener()
        reset_manager(if_current=self)
        atexit.unregister(self.close)


# Process-global manager handle. ``None`` until an enabled
# TelemetryManager installs itself; close() restores None (only if it is
# still the installed one, so a newer engine's manager is not clobbered).
_GLOBAL = None


def get_manager():
    return _GLOBAL


def set_manager(manager):
    """Install *manager* as the process-global handle; returns the old."""
    global _GLOBAL
    old, _GLOBAL = _GLOBAL, manager
    return old


def reset_manager(if_current=None):
    global _GLOBAL
    if if_current is None or _GLOBAL is if_current:
        _GLOBAL = None
