"""Telemetry sinks — JSONL event writer and Prometheus text exporter.

``JSONLMonitor`` / ``PrometheusMonitor`` speak the monitor-backend
protocol (``write_scalar(name, value, step)`` + ``flush()`` + ``close()``)
so ``MonitorMaster`` fans existing ``write_events`` call sites out to them
unchanged.

Prometheus side: scalars keep their slash-y reference names
("Train/Samples/train_loss") by living as ONE family
``deepspeed_scalar{name="..."}`` — the original name goes through label
escaping instead of being mangled into a metric name. Registry metrics
(counters/gauges/histograms) render under their own sanitised names. The
.prom file is the *text-file-collector* pattern: node_exporter (or any
scraper of textfile directories) picks it up; no HTTP server needed on a
TPU host. For direct scraping, :mod:`telemetry.obs_server` serves the
same :func:`render_prometheus` output at ``GET /metrics``.
"""

import json
import os
import re
import time
import zlib

from deepspeed_tpu.telemetry.metrics import Histogram, MetricsRegistry
from deepspeed_tpu.utils.logging import logger

_NAME_OK = re.compile(r"[^a-zA-Z0-9_:]")

# sanitized names whose collision has already been warned about — the
# render runs at scrape/flush cadence, the warning is once per process
_COLLISION_WARNED = set()


def sanitize_metric_name(name):
    """Coerce to the Prometheus metric-name charset
    ``[a-zA-Z_:][a-zA-Z0-9_:]*``. Lossy: distinct registry families can
    sanitize to the same name (``a/b`` and ``a.b`` both become
    ``a_b``) — :func:`render_prometheus` detects that at render time
    and de-collides deterministically rather than silently merging two
    families' samples into one."""
    name = _NAME_OK.sub("_", str(name))
    if not name or not (name[0].isalpha() or name[0] in "_:"):
        name = "_" + name
    return name


def _sanitized_family_names(families):
    """``{raw family -> rendered name}`` with collision repair: when two
    registry families sanitize to the same Prometheus name, the first in
    sorted order keeps the base name and every other collider gets a
    deterministic ``_<crc32-of-raw-name>`` suffix (stable across renders
    and processes — dashboards keep working). Warned once per base."""
    by_base = {}
    for fam in sorted(families):
        by_base.setdefault(sanitize_metric_name(fam), []).append(fam)
    out = {}
    for base, fams in by_base.items():
        out[fams[0]] = base
        for fam in fams[1:]:
            out[fam] = f"{base}_{zlib.crc32(fam.encode()):08x}"
        if len(fams) > 1 and base not in _COLLISION_WARNED:
            _COLLISION_WARNED.add(base)
            logger.warning(
                "[sinks] %d metric families sanitize to %r (%s); "
                "keeping %r as %r and suffixing the rest — rename the "
                "families to distinct sanitized names",
                len(fams), base, ", ".join(map(repr, fams)), fams[0],
                base)
    return out


def escape_label_value(value):
    r"""Label-value escaping per the exposition format: ``\`` -> ``\\``,
    ``"`` -> ``\"``, newline -> ``\n``."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def escape_help(text):
    r"""HELP-line escaping: ``\`` -> ``\\``, newline -> ``\n``."""
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_value(v):
    if v != v:                      # NaN
        return "NaN"
    if v in (float("inf"), float("-inf")):
        return "+Inf" if v > 0 else "-Inf"
    if float(v).is_integer():
        return str(int(v))
    return repr(float(v))


def _labels_str(labels, extra=None):
    items = dict(labels or {})
    if extra:
        items.update(extra)
    if not items:
        return ""
    inner = ",".join(
        f'{sanitize_metric_name(k)}="{escape_label_value(v)}"'
        for k, v in sorted(items.items()))
    return "{" + inner + "}"


SUMMARY_QUANTILES = (0.5, 0.9, 0.99)


def render_prometheus(registry, extra_labels=None):
    """Render *registry* in the Prometheus text exposition format v0.0.4.

    Histograms additionally render a sibling ``<name>_summary`` family of
    TYPE summary carrying p50/p90/p99 estimates
    (:meth:`~deepspeed_tpu.telemetry.metrics.Histogram.quantile` — linear
    interpolation inside the bucket), so TTFT / step-time percentiles
    reach scrape sinks directly instead of living only in the JSON
    artifacts. Empty histograms render no summary (a quantile of nothing
    is a lie, not a zero).

    Family names that sanitize to the same Prometheus name are
    de-collided (:func:`_sanitized_family_names`) — the exposition
    format forbids a duplicate TYPE line, and merging two families'
    samples under one name corrupts both series.

    ``extra_labels`` stamps constant labels (e.g. ``{"rank": 3}``) onto
    EVERY sample line at render time — the fleet-federation identity
    injection: a peer renders its own exposition already labelled, so
    the aggregator's merge never re-parses sample text. A metric's own
    label wins a key collision (per-sample truth beats the const
    stamp)."""
    lines = []
    collected = registry.collect()
    names = _sanitized_family_names(collected)
    if extra_labels:
        # metric-level labels override the const stamp on key collision:
        # _labels_str applies `extra` (the metric's labels) LAST
        def _ls(labels, extra=None):
            merged = dict(extra_labels)
            merged.update(labels or {})
            if extra:
                merged.update(extra)
            return _labels_str(merged)
    else:
        _ls = _labels_str
    for family, ms in sorted(collected.items()):
        name = names[family]
        help_text = next((m.help for m in ms if m.help), "")
        if help_text:
            lines.append(f"# HELP {name} {escape_help(help_text)}")
        lines.append(f"# TYPE {name} {ms[0].kind}")
        summaries = []
        for m in ms:
            if isinstance(m, Histogram):
                cum = m.cumulative_counts()
                for le, c in zip([*m.buckets, float("inf")], cum):
                    lines.append(
                        f"{name}_bucket"
                        f"{_ls(m.labels, {'le': _fmt_value(float(le))})}"
                        f" {c}")
                lines.append(
                    f"{name}_sum{_ls(m.labels)} {_fmt_value(m.sum)}")
                lines.append(
                    f"{name}_count{_ls(m.labels)} {m.count}")
                if m.count:
                    summaries.append(m)
            else:
                lines.append(
                    f"{name}{_ls(m.labels)} {_fmt_value(m.value)}")
        if summaries:
            sname = f"{name}_summary"
            lines.append(f"# TYPE {sname} summary")
            for m in summaries:
                for q in SUMMARY_QUANTILES:
                    v = m.quantile(q)
                    lines.append(
                        f"{sname}"
                        f"{_ls(m.labels, {'quantile': _fmt_value(q)})}"
                        f" {_fmt_value(v)}")
                lines.append(
                    f"{sname}_sum{_ls(m.labels)} "
                    f"{_fmt_value(m.sum)}")
                lines.append(f"{sname}_count{_ls(m.labels)} "
                             f"{m.count}")
    return "\n".join(lines) + "\n"


class JSONLSink:
    """Append-only structured event log; one JSON object per line."""

    def __init__(self, path):
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self.path = path
        self._file = open(path, "a")

    def write(self, event_type, **fields):
        rec = {"ts": round(time.time(), 6), "event": event_type}
        rec.update(fields)
        self._file.write(json.dumps(rec, default=repr) + "\n")

    def flush(self):
        if not self._file.closed:
            self._file.flush()

    def close(self):
        if not self._file.closed:
            self._file.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class PrometheusSink:
    """Atomically (re)writes a .prom text file from a registry.

    This is the *textfile-collector* half of the Prometheus story: a
    node_exporter (or any textfile-directory scraper) on the host picks
    the file up — no port, no server, works on locked-down TPU hosts.
    The *direct-scrape* half is :class:`telemetry.obs_server.ObsServer`,
    whose ``GET /metrics`` renders the same registry live over HTTP;
    arm it with the ``telemetry.server`` config block when Prometheus
    can reach the trainer. Both render through
    :func:`render_prometheus`, so the two views never disagree."""

    def __init__(self, path, registry):
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self.path = path
        self.registry = registry

    def write(self):
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            f.write(render_prometheus(self.registry))
        os.replace(tmp, self.path)  # scrapers never see a partial file
        return self.path

    def close(self):
        self.write()


# ------------------------------------------------------------ monitor glue

class JSONLMonitor:
    """MonitorMaster backend: scalars as JSONL events."""

    def __init__(self, output_path="runs/", job_name="DeepSpeedJobName"):
        self.sink = JSONLSink(os.path.join(output_path,
                                           f"{job_name}.jsonl"))
        self.path = self.sink.path

    def write_scalar(self, name, value, step):
        self.sink.write("scalar", name=name, value=float(value),
                        step=int(step))

    def flush(self):
        self.sink.flush()

    def close(self):
        self.sink.close()


class PrometheusMonitor:
    """MonitorMaster backend: scalars as one labelled gauge family,
    flushed to a text-format file the registry's other metrics share.

    File-based by design (see :class:`PrometheusSink` for when to prefer
    the live ``/metrics`` endpoint instead): when the obs server is
    armed on the same registry, the scalars written here are ALSO
    visible on the scrape route for free — the monitor writes into the
    registry first and flushes the file second."""

    SCALAR_FAMILY = "deepspeed_scalar"

    def __init__(self, output_path="runs/", job_name="DeepSpeedJobName",
                 registry=None, path=None):
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        self.sink = PrometheusSink(
            path or os.path.join(output_path, f"{job_name}.prom"),
            self.registry)
        self.path = self.sink.path

    def write_scalar(self, name, value, step):
        self.registry.gauge(self.SCALAR_FAMILY,
                            "monitor scalars (reference names as labels)",
                            labels={"name": str(name)}).set(value)
        self.registry.gauge("deepspeed_scalar_step",
                            "last step at which the scalar was written",
                            labels={"name": str(name)}).set(step)

    def flush(self):
        self.sink.write()

    def close(self):
        self.sink.write()
