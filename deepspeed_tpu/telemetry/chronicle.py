"""Run chronicle — ONE causally-ordered event timeline for the whole run.

Every instrument so far escalates into its own siloed artifact: HEALTH /
GOODPUT / SERVING_HEALTH / FLEET_HEALTH / MEMORY_ANATOMY snapshots, the
guardian's GUARDIAN.json journal, the compile watch's log lines. A single
production incident — input stall -> loss spike -> guardian rollback ->
TTFT breach on the co-located replica — is therefore scattered across
five files with no shared clock and no causal join. This module is the
merge point:

* :class:`RunChronicle` — an append-only structured event log. Every
  event carries a **monotone per-rank sequence number** and an **integer
  microsecond stamp on the shared monotonic axis**
  (:func:`deepspeed_tpu.telemetry.clock.monotonic_us`), so a merged
  timeline is strictly ordered with no wall-vs-monotonic confusion and
  no float drift. Emitters reach it through the process-global
  :func:`get_chronicle` (the tracer/registry/ledger pattern):

  ========== ============================================================
  kind        emitted by
  ========== ============================================================
  anomaly     every monitor's rule firing, at ``escalation.escalate``
              time (one emit site for all five observatories)
  action      the guardian's ``_act`` — action, triggering rule, outcome
  lifecycle   the engine: init / first_compile / checkpoint_save+load /
              elastic_resume / close (+ the ServingEngine counterparts)
  retrace     the compile watch's recompile culprit reports
  serving     admission pause/resume, preemption, livelock last rites
  chaos       the PR-12 chaos harness naming its own injections — a
              chaos-driven run self-documents its ground truth
  goodput_window  the ledger's window ticks (integer-µs category diffs),
              so an incident's goodput cost is computable — and
              re-addable — from chronicle events alone
  ========== ============================================================

* Persistence: one JSONL stream per rank under a run dir
  (``<run_dir>/events_rank_00000.jsonl``), rewritten atomically
  (tmp+fsync+rename — the PR-7/11 discipline; a reader sees a COMPLETE
  prefix of the log or nothing) by a background writer thread that holds
  only a :class:`_WriterState` (weakref.finalize GC, PR-5/7 thread
  discipline) and runs under ``suppress_attribution`` so shipping the
  chronicle can never book badput into the ledger it is chronicling.

* The in-memory log is bounded (``max_events``): past the cap NEW events
  are dropped from the ring and counted (``dropped``) — append-only
  means the committed prefix, with the earliest (causally richest)
  events, is never rewritten out from under a reader. When a stream is
  armed, overflow events are still APPENDED to the on-disk JSONL
  (``overflow_shipped`` counts them), so a resumable consumer
  (:meth:`RunChronicle.events_since`, the obs server's ``/api/events``)
  never silently loses the tail — the ring bounds memory, not the
  record. An elastically-resumed rank continues its sequence numbering
  from the existing stream instead of restarting at 0 (the fleet
  shipper's window-resume discipline), so a SIGKILL + restart keeps the
  merged fleet timeline strictly ordered.

* :meth:`RunChronicle.report` -> CHRONICLE.json summary; the
  :class:`deepspeed_tpu.telemetry.incidents.IncidentCorrelator` joins
  the same events into INCIDENTS.json (``engine.chronicle_report``).

Disabled is near-free: ``emit`` on the shared disabled instance is one
attribute check (guarded < 2 µs in tests/perf/telemetry_overhead.py),
and the module imports no jax — pure host bookkeeping.

CLI: ``python -m deepspeed_tpu.telemetry.chronicle --render
CHRONICLE.json`` (or a run dir) pretty-prints the merged timeline;
``--demo`` replays the guardian's chaos scenario — DivergenceChaos
poison -> nonfinite_grads -> automatic rollback — and writes the
committed repo-root CHRONICLE.json + INCIDENTS.json, whose correlator
output is exactly ONE incident rooted at the poison step.
"""

import argparse
import json
import math
import os
import threading
import weakref
from collections import deque

from deepspeed_tpu.telemetry import clock as _clk
from deepspeed_tpu.utils.logging import logger

CHRONICLE_SCHEMA = "deepspeed_tpu.chronicle/1"

_TMP_MARK = ".tmp."          # the checkpoint_io sibling-marker convention
_STREAM_FMT = "events_rank_{:05d}.jsonl"

SEVERITY_ORDER = ("critical", "warning", "watch", "info")


def _severity_rank(sev):
    try:
        return SEVERITY_ORDER.index(sev)
    except ValueError:
        return len(SEVERITY_ORDER)


def _fsync_dir(dirname):
    """Durability for the rename (best-effort — mirrors fleet._fsync_dir,
    re-implemented so this module imports nothing that imports the
    escalation helper back)."""
    try:
        fd = os.open(dirname or ".", os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _atomic_write_bytes(path, payload):
    """tmp sibling + fsync + atomic rename (+ dir fsync)."""
    tmp = f"{path}{_TMP_MARK}{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass
    _fsync_dir(os.path.dirname(path))


def _append_bytes(path, payload):
    """Durable append (overflow lines past the ring cap). Not a rename —
    the committed prefix is already on disk whole; a torn final line is
    tolerated by every stream reader."""
    with open(path, "ab") as f:
        f.write(payload)
        f.flush()
        os.fsync(f.fileno())


def _read_stream(path):
    """Parse a rank JSONL stream, tolerating a torn final line (an
    append interrupted by SIGKILL)."""
    events = []
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except OSError:
        return events
    for line in raw.decode(errors="replace").splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            events.append(json.loads(line))
        except ValueError:
            continue            # torn tail — the committed prefix stands
    return events


def _json_sane(obj):
    """Make *obj* strictly-JSON-serialisable: non-finite floats become
    strings (the health.json_safe contract, local copy to keep the
    import graph acyclic), unknown objects their repr."""
    if isinstance(obj, float):
        return obj if math.isfinite(obj) else str(obj)
    if isinstance(obj, dict):
        return {str(k): _json_sane(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_json_sane(v) for v in obj]
    if obj is None or isinstance(obj, (bool, int, str)):
        return obj
    return repr(obj)


class _WriterState:
    """Everything the background writer thread may touch — the thread
    holds ONLY this (never the chronicle), so an abandoned chronicle is
    reclaimed via weakref.finalize. ``busy`` spans dequeue-to-written,
    so ``drain`` means durably on disk."""
    __slots__ = ("queue", "cond", "stopped", "busy", "errors", "warned")

    def __init__(self):
        self.queue = deque()
        self.cond = threading.Condition()
        self.stopped = False
        self.busy = False
        self.errors = 0
        self.warned = False


def _writer_loop(state):
    # chronicling a run must never book wall time into the run's own
    # goodput ledger (lazy import: the ledger imports the escalation
    # helper which imports this module)
    from deepspeed_tpu.telemetry.ledger import suppress_attribution
    with suppress_attribution():
        while True:
            with state.cond:
                state.busy = False
                state.cond.notify_all()
                while not state.queue and not state.stopped:
                    state.cond.wait(timeout=0.5)
                if not state.queue and state.stopped:
                    return
                mode, path, payload = state.queue.popleft()
                state.busy = True
            try:
                if mode == "append":
                    _append_bytes(path, payload)
                else:
                    _atomic_write_bytes(path, payload)
            except Exception as e:   # forensics must never kill a run
                state.errors += 1
                if not state.warned:
                    state.warned = True
                    logger.warning("[chronicle] background write failed: "
                                   "%s", e)


def _finalize_writer(state, thread):
    with state.cond:
        state.stopped = True
        state.cond.notify_all()
    if thread.is_alive():
        thread.join(timeout=5.0)


class RunChronicle:
    """The per-process run chronicle. See the module docstring.

    ``emit`` is thread-safe (monitors escalate on the train thread, the
    serving scheduler and prefetch workers on theirs); each emit appends
    one event and enqueues a full-log rewrite for the background writer
    (coalesced: at most one pending rewrite rides the queue per stream).
    """

    def __init__(self, run_dir=None, rank=0, job_name="", enabled=True,
                 max_events=16384, background=True, log_fn=None):
        self.enabled = bool(enabled)
        self.rank = int(rank)
        self.job_name = job_name
        self.dropped = 0
        self.overflow_shipped = 0
        self.resumed_seq = None
        if not self.enabled:
            return
        self.run_dir = run_dir
        self.max_events = max(1, int(max_events))
        self._log = log_fn or logger.warning
        self._lock = threading.Lock()
        self._seq = 0
        self.events = []
        self._closed = False
        self.stream_path = None
        self._wstate = None
        self._wthread = None
        if run_dir:
            os.makedirs(run_dir, exist_ok=True)
            self.stream_path = os.path.join(
                run_dir, _STREAM_FMT.format(self.rank))
            if os.path.isfile(self.stream_path):
                # elastic resume: continue the sequence numbering behind
                # the pre-crash stream (the fleet shipper's window-resume
                # discipline) — a restarted-at-zero rank would collide
                # seqs and break the merged fleet timeline's strict
                # (t_us, seq, rank) order. Prior events reload into the
                # ring (up to the cap) so rewrites keep the whole record;
                # past the cap the old file stays the committed prefix
                # and new events ride the overflow-append path.
                prior = _read_stream(self.stream_path)
                if prior:
                    self.resumed_seq = max(e.get("seq", -1) for e in prior)
                    self._seq = self.resumed_seq + 1
                    self.events = prior[:self.max_events]
            if background:
                self._wstate = _WriterState()
                self._wthread = threading.Thread(
                    target=_writer_loop, args=(self._wstate,),
                    name=f"ds-chronicle-r{self.rank}", daemon=True)
                self._wthread.start()
                self._finalizer = weakref.finalize(
                    self, _finalize_writer, self._wstate, self._wthread)

    # -------------------------------------------------------------- emitting
    def emit(self, kind, source, step=None, severity=None, detail=None,
             **data):
        """Append one event. Returns the event dict (None when disabled
        or dropped). The stamp is taken INSIDE the lock so (t_us, seq)
        is monotone even under concurrent emitters."""
        if not self.enabled or self._closed:
            # post-close emits drop (the writer is gone; an enqueue
            # nobody drains would just dangle)
            return None
        with self._lock:
            overflow = len(self.events) >= self.max_events
            if overflow and self.stream_path is None:
                # append-only: past the cap the committed prefix wins
                # and NEW events drop (counted — a summary with
                # dropped>0 says "timeline truncated", never "rewritten")
                self.dropped += 1
                return None
            t_us = _clk.monotonic_us()
            event = {"seq": self._seq, "t_us": t_us,
                     "unix_us": _clk.to_unix_us(t_us),
                     "kind": kind, "source": source, "rank": self.rank}
            if step is not None:
                event["step"] = int(step)
            if severity is not None:
                event["severity"] = severity
            if detail is not None:
                event["detail"] = str(detail)
            for k, v in data.items():
                if v is not None:
                    event[k] = _json_sane(v)
            self._seq += 1
            if overflow:
                # the ring bounds MEMORY, not the record: the event drops
                # from the in-memory log (counted) but still APPENDS to
                # the committed stream, so events_since / the obs
                # server's disk fallback can serve it to a resumed
                # consumer. Shipped under the lock so the writer queue
                # preserves seq order against the ring-fill rewrite.
                self.dropped += 1
                self.overflow_shipped += 1
                self._ship_locked("append", self._payload([event]))
            else:
                self.events.append(event)
                if self.stream_path:
                    self._ship_locked("rewrite",
                                      self._payload(self.events))
        return event

    def _payload(self, events):
        return ("\n".join(json.dumps(e, sort_keys=True, allow_nan=False)
                          for e in events) + "\n").encode()

    def _ship_locked(self, mode, payload):
        """Enqueue (or perform) one stream write. Called with ``_lock``
        held so the writer queue preserves seq order — the ring-fill
        rewrite always precedes the overflow appends that follow it."""
        if self._wstate is not None:
            with self._wstate.cond:
                if mode == "rewrite":
                    # coalesce: a newer full-log rewrite supersedes any
                    # queued one — the stream is always written whole.
                    # Appends are never discarded (each carries an event
                    # that lives nowhere else).
                    self._wstate.queue = deque(
                        op for op in self._wstate.queue
                        if op[0] != "rewrite")
                self._wstate.queue.append((mode, self.stream_path,
                                           payload))
                self._wstate.cond.notify_all()
        else:
            try:
                if mode == "append":
                    _append_bytes(self.stream_path, payload)
                else:
                    _atomic_write_bytes(self.stream_path, payload)
            except OSError as e:
                self._log("[chronicle] stream write failed: %s", e)

    # --------------------------------------------------------------- reading
    def snapshot_events(self):
        """A consistent copy of the event log (ordered by (t_us, seq))."""
        if not self.enabled:
            return []
        with self._lock:
            return list(self.events)

    def events_since(self, since_seq, limit=None):
        """Events with ``seq > since_seq`` — the resumable-consumer read.

        Serves from the in-memory ring when it still holds the requested
        range; once events have overflowed past the cap (or a resume
        preloaded only a prefix), falls back to the on-disk JSONL stream
        so a consumer that paused across the drop horizon still gets the
        FULL tail instead of a silent gap plus a ``dropped`` counter.
        Returned events are seq-ordered; ``limit`` (when set) keeps the
        NEWEST events, mirroring the obs server's tail semantics."""
        if not self.enabled:
            return []
        since = int(since_seq)
        with self._lock:
            ring = list(self.events)
            # _seq counts every RECORDED event (drop-without-stream never
            # increments it), so the ring is the whole record iff it
            # holds _seq events — overflow and resume-truncation both
            # break that equality.
            ring_complete = len(ring) == self._seq
            stream = self.stream_path
        if not ring_complete and stream:
            # the ring dropped (or never held) part of the range — the
            # committed stream is the whole record. Drain first so every
            # queued append is readable.
            self.drain(timeout=2.0)
            disk = _read_stream(stream)
            if disk:
                ring = disk
        out = [e for e in ring if e.get("seq", -1) > since]
        out.sort(key=lambda e: e.get("seq", 0))
        if limit is not None and len(out) > int(limit):
            out = out[-int(limit):]
        return out

    def drain(self, timeout=10.0):
        """Block until every queued stream write is durably on disk."""
        if not self.enabled or self._wstate is None:
            return
        deadline = _clk.monotonic_s() + timeout
        with self._wstate.cond:
            while ((self._wstate.queue or self._wstate.busy)
                   and _clk.monotonic_s() < deadline):
                self._wstate.cond.wait(timeout=0.2)

    def report(self):
        """The CHRONICLE.json summary dict."""
        if not self.enabled:
            return {"schema": CHRONICLE_SCHEMA, "enabled": False}
        events = self.snapshot_events()
        by_kind, by_source = {}, {}
        for e in events:
            by_kind[e["kind"]] = by_kind.get(e["kind"], 0) + 1
            by_source[e["source"]] = by_source.get(e["source"], 0) + 1
        return {
            "schema": CHRONICLE_SCHEMA,
            "job_name": self.job_name,
            "rank": self.rank,
            "run_dir": self.run_dir,
            "n_events": len(events),
            "dropped": self.dropped,
            "overflow_shipped": self.overflow_shipped,
            "resumed_seq": self.resumed_seq,
            "counts_by_kind": by_kind,
            "counts_by_source": by_source,
            "first_t_us": events[0]["t_us"] if events else None,
            "last_t_us": events[-1]["t_us"] if events else None,
            "events": events,
        }

    def write_summary(self, path):
        doc = self.report()
        payload = json.dumps(doc, indent=1, default=repr,
                             allow_nan=False).encode()
        _atomic_write_bytes(path, payload)
        return path

    def close(self):
        """Final stream write + writer join. Idempotent."""
        if not self.enabled or self._closed:
            return
        self._closed = True
        if self.stream_path is not None and self.overflow_shipped == 0:
            # belt-and-braces final rewrite — but ONLY while the stream
            # is ring-shaped: once overflow appends ride behind the last
            # ring rewrite, a full rewrite of the ring would truncate
            # them off the committed record.
            with self._lock:
                self._ship_locked("rewrite", self._payload(self.events))
        self.drain()
        if self._wstate is not None:
            _finalize_writer(self._wstate, self._wthread)


# Process-global chronicle. The shared disabled instance (never None)
# keeps every emit site a plain attribute check — the ledger's
# _DISABLED/_GLOBAL pattern.
_DISABLED = RunChronicle(enabled=False)
_GLOBAL = _DISABLED


def get_chronicle():
    return _GLOBAL


def set_chronicle(chronicle):
    """Install *chronicle* as the process global; returns the old one."""
    global _GLOBAL
    old, _GLOBAL = _GLOBAL, (chronicle if chronicle is not None
                             else _DISABLED)
    return old


def reset_chronicle(if_current=None):
    global _GLOBAL
    if if_current is None or _GLOBAL is if_current:
        _GLOBAL = _DISABLED


# --------------------------------------------------------------------- CLI

def load_events(path):
    """Events from a CHRONICLE.json summary, a rank JSONL stream, or a
    run dir of streams (merged, ordered on the shared µs axis)."""
    if os.path.isdir(path):
        events = []
        for f in sorted(os.listdir(path)):
            if f.startswith("events_rank_") and f.endswith(".jsonl") \
                    and _TMP_MARK not in f:
                events.extend(load_events(os.path.join(path, f)))
        events.sort(key=lambda e: (e["t_us"], e.get("rank", 0),
                                   e["seq"]))
        return events
    with open(path) as f:
        if path.endswith(".jsonl"):
            return [json.loads(line) for line in f if line.strip()]
        return json.load(f).get("events", [])


def render(events):
    """Human-readable merged timeline."""
    if not events:
        return "chronicle: no events"
    t0 = events[0]["t_us"]
    lines = [f"chronicle: {len(events)} event(s) across "
             f"{len({e.get('rank', 0) for e in events})} rank(s)"]
    for e in events:
        dt_ms = (e["t_us"] - t0) / 1e3
        step = f"step {e['step']}" if "step" in e else "-"
        what = e.get("rule") or e.get("phase") \
            or e.get("event") or e.get("chaos") or ""
        if e.get("action"):
            # the rule->action causal edge, rendered as one
            what = (f"{what}->{e['action']}" if what else e["action"])
        sev = f" [{e['severity']}]" if "severity" in e else ""
        detail = e.get("detail", "")
        if len(detail) > 72:
            detail = detail[:69] + "..."
        lines.append(f"  +{dt_ms:10.1f}ms r{e.get('rank', 0)} "
                     f"{e['kind']:>14}/{e['source']:<10} {step:>9} "
                     f"{what}{sev} {detail}".rstrip())
    return "\n".join(lines)


def render_incidents(doc):
    """Human-readable incident chains (an INCIDENTS.json document)."""
    incs = doc.get("incidents", [])
    lines = [f"incidents: {len(incs)} over {doc.get('n_events', 0)} "
             f"event(s) (job {doc.get('job_name') or '-'})"]
    for inc in incs:
        dur_ms = inc["duration_us"] / 1e3
        lines.append(
            f"  #{inc['id']} [{inc['severity']}] steps "
            f"{inc['start_step']}-{inc['end_step']} over {dur_ms:.1f}ms "
            f"badput {inc['goodput_cost']['badput_total_us'] / 1e3:.1f}ms")
        rc = inc["root_cause"]
        if rc:
            what = rc.get("rule") or rc.get("chaos") or rc.get("kind")
            lines.append(f"     root cause: {rc['kind']}/{what} at step "
                         f"{rc.get('step', '-')} — {rc['why']}")
        if inc["rules"]:
            lines.append(f"     rules:   {', '.join(inc['rules'])}")
        if inc["actions"]:
            lines.append(f"     actions: {', '.join(inc['actions'])}")
        for a in inc["artifacts"]:
            lines.append(f"     artifact: {a}")
    return "\n".join(lines)


def _demo(args):
    """The committed-artifact scenario: the guardian demo's chaos run
    with the chronicle armed — a DivergenceChaos poison, the health
    observatory's nonfinite_grads/loss_spike firings and the guardian's
    rollback collapse into ONE incident naming the poison step."""
    if args.devices:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.devices}")
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import tempfile

    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.testing.chaos import DivergenceChaos
    from deepspeed_tpu.models.simple import SimpleModel, sample_batch
    from deepspeed_tpu.utils import groups

    import jax

    groups.destroy()
    groups.initialize()
    hidden = 32
    ndev = jax.device_count()
    ckpt_dir = tempfile.mkdtemp(prefix="chronicle_demo_ckpt_")
    run_dir = tempfile.mkdtemp(prefix="chronicle_demo_run_")
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=hidden, nlayers=2),
        config={
            "train_batch_size": 8,
            "train_micro_batch_size_per_gpu": 8 // ndev,
            "gradient_accumulation_steps": 1,
            "steps_per_print": 1,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
            "fp16": {"enabled": True, "loss_scale": 0,
                     "initial_scale_power": 8},
            "checkpoint": {"async_save": True},
            "guardian": {"enabled": True, "action_cooldown_steps": 1,
                         "divergence_streak": 2},
            "telemetry": {"enabled": True, "trace": False,
                          "jsonl": False, "prometheus": False,
                          "health": {"enabled": True, "cadence": 1,
                                     "warmup_samples": 2},
                          "goodput": {"enabled": True, "cadence": 2},
                          "chronicle": {"enabled": True,
                                        "run_dir": run_dir,
                                        "summary_file":
                                            os.path.abspath(args.out),
                                        "incidents_file":
                                            os.path.abspath(
                                                args.incidents_out)}},
        },
        sample_batch=sample_batch(8, hidden))
    rng = np.random.default_rng(0)

    def batches():
        while True:
            x = rng.standard_normal((8, hidden)).astype(np.float32)
            yield (x, x * 0.5)

    it = batches()
    for step in range(1, args.steps + 1):
        if step == 3:        # the tag the guardian's rollback restores
            engine.save_checkpoint(ckpt_dir)
        engine.train_batch(data_iter=it)
    # chaos: poison the params -> loss_spike + nonfinite streak ->
    # automatic rollback; the injector chronicles its own ground truth
    chaos = DivergenceChaos(engine, at_call=1)
    with chaos:
        engine.train_batch(data_iter=it)
    for _ in range(3):
        engine.train_batch(data_iter=it)
    engine.close()       # emits the lifecycle close + final stream write
    doc = engine.chronicle_report(write=True)
    print(render(doc["events"]))
    inc = doc.get("incidents") or {}
    print(f"\n{len(inc.get('incidents', []))} incident(s); "
          f"poisoned step(s): {chaos.poisoned_steps}")
    print(f"wrote {args.out} + {args.incidents_out}")
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="run-chronicle timeline/demo CLI")
    ap.add_argument("--render", metavar="PATH",
                    help="CHRONICLE.json, INCIDENTS.json, a rank .jsonl "
                         "stream, or a run dir — print the merged "
                         "timeline (or the incident chains)")
    ap.add_argument("--demo", action="store_true",
                    help="run the chaos-driven demo and write the "
                         "committed CHRONICLE.json + INCIDENTS.json")
    ap.add_argument("--out", default="CHRONICLE.json")
    ap.add_argument("--incidents-out", default="INCIDENTS.json")
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--devices", type=int, default=0)
    args = ap.parse_args(argv)
    if args.demo:
        return _demo(args)
    if args.render:
        if os.path.isfile(args.render) and args.render.endswith(".json"):
            with open(args.render) as f:
                doc = json.load(f)
            if isinstance(doc, dict) and \
                    str(doc.get("schema", "")).startswith(
                        "deepspeed_tpu.incidents/"):
                print(render_incidents(doc))
                return 0
        print(render(load_events(args.render)))
        return 0
    ap.error("one of --render / --demo is required")


if __name__ == "__main__":
    raise SystemExit(main())
