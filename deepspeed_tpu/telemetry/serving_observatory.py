"""Serving observatory — per-request tracing, slot-step ledger, SLO rules.

The training loop already explains itself (PR-1 spans, PR-2 compiled-cost
census, PR-3 health rules, PR-4 goodput ledger); the PR-6 serving engine
only exposed flat aggregate counters. This module is the serving-side
counterpart, three pieces sharing one window clock:

* **Per-request lifecycle timelines** (:class:`RequestTimeline`): every
  request accumulates a timestamped event list — ``queued`` → ``admitted``
  → ``prefill_chunk`` × N → ``decode_begin`` → ``first_token`` →
  ``preempted``/``requeued`` (recompute resume loops back to ``admitted``)
  → ``finished``/``failed`` — returned structurally from
  ``ServingEngine.serving_report()`` and, when the PR-1 tracer is live,
  exported as **per-slot lanes** in the Chrome trace (synthetic tids, one
  lane per batch slot plus a queue-wait lane, so chrome://tracing shows
  slot occupancy the way a GPU timeline shows streams).

* **Slot-step ledger** (:class:`SlotStepLedger`): each scheduler step the
  engine runs ``max_batch`` slots for ``decode_steps`` compiled
  micro-steps; the ledger books every one of those ``max_batch × K``
  integer micro-units into exactly one category —

  ==================  ====================================================
  ``decode_useful``   a kept generated token (the goodput of serving)
  ``cached_prefill``  caching prompt tokens for a request whose prefix
                      was partly mapped read-only from the prefix cache
                      (prefill the cache already shortened)
  ``prefill``         caching fresh prompt tokens
  ``recompute``       re-caching tokens a preemption evicted (the chunk
                      re-covers previously-cached positions)
  ``frozen``          a slot burned compute without forward progress:
                      budget-exhausted micro-steps of a multi-step
                      dispatch, tokens discarded past eos, or an occupied
                      slot the step never dispatched
  ``idle``            an empty slot (the static batch ran it anyway)
  ==================  ====================================================

  Categories sum to ``steps × max_batch × K`` **by construction** (every
  slot books exactly K units per step — integers, so the sum is exact,
  the same discipline as the PR-4 wall-clock ledger), and
  ``wasted = idle + frozen + recompute`` is the serving analogue of the
  bench's ``wasted_decode_frac``: the instrument that catches a
  regression back toward the static baseline's measured 76% waste.

* **SLO monitor**: windowed rules over the ledger + per-window series
  (queue depth, KV occupancy/fragmentation, TTFT observations) —
  ``ttft_slo_breach``, ``queue_growth``, ``preemption_thrash``,
  ``decode_stall`` and the exact per-step ``no_progress`` streak —
  escalating warn-once → throttled ``SERVING_HEALTH.json`` snapshot →
  trace flush (the PR-3/PR-4 protocol), plus
  ``serving_anomalies_total{rule=...}`` in the metrics registry.

Everything here is **pure host bookkeeping**: the observatory never
imports jax at module scope and never touches a device value — its
inputs are host ints/floats the server already holds after its one
existing per-dispatch sync (guarded in tests/perf/telemetry_overhead.py,
which also pins "observability on = still exactly one compiled decode
program, zero retraces").

CLI: ``python -m deepspeed_tpu.telemetry.serving_observatory --render
SERVING_HEALTH.json`` pretty-prints a snapshot; ``--demo`` drives a tiny
serving engine through a preemption-heavy burst with a deliberately
unmeetable TTFT SLO and writes the committed repo-root example.
"""

import json
import os
import time
from collections import deque

from deepspeed_tpu.telemetry import escalation
from deepspeed_tpu.telemetry import tracer as _tracer_mod
from deepspeed_tpu.telemetry.health import json_safe
from deepspeed_tpu.utils.logging import logger

SERVING_HEALTH_SCHEMA = "deepspeed_tpu.serving_health/3"

# cached_prefill: prompt tokens a chunk advanced for a request whose
# prefix was partly served read-only from the prefix cache — useful
# work, split out so hit-rate shows up in the ledger, not just counters
# drafted_rejected: speculative draft positions the verify pass refused —
# the booked price of speculation (distinct from frozen: the slot DID
# run those positions through the target, they just didn't advance it)
SLOT_CATEGORIES = ("decode_useful", "cached_prefill", "prefill",
                   "recompute", "frozen", "idle", "drafted_rejected")
# wasted = everything that burned a slot without advancing a request
WASTE_CATEGORIES = ("recompute", "frozen", "idle", "drafted_rejected")

RULE_SEVERITY = {
    "ttft_slo_breach": "warning",
    "queue_growth": "warning",
    "preemption_thrash": "warning",
    "decode_stall": "critical",
    "no_progress": "critical",
    "speculation_waste": "warning",
}
_SEVERITY_ORDER = ("critical", "warning", "watch")

# synthetic Chrome-trace lane tids come from the tracer's process-scoped
# registry (tracer.allocate_lane_tid), so slot lanes can never collide
# with fleet-rank or profiler device lanes in a merged trace


def _flush_trace():
    """Default escalation hook: force the TelemetryManager's Chrome-trace
    export NOW (throttle still applies) so the forensics file and the
    trace cover the same incident. No-op without a live manager."""
    from deepspeed_tpu.telemetry import manager as _mgr
    m = _mgr.get_manager()
    if m is not None:
        m.flush()


class RequestTimeline:
    """Ordered, timestamped lifecycle events for one request.

    ``events`` is a list of ``{"t_ms", "event", ...detail}`` dicts with
    ``t_ms`` relative to the observatory's start — append-only, bounded
    (a pathological request cannot grow the report without bound)."""

    MAX_EVENTS = 512
    __slots__ = ("req_id", "events", "dropped", "decoding", "wait_start")

    def __init__(self, req_id):
        self.req_id = req_id
        self.events = []
        self.dropped = 0
        self.decoding = False     # has this admission seen a decode yet?
        self.wait_start = None    # perf_counter at last queue entry
        # (submit OR requeue) — what the queue-wait lane measures

    def add(self, t_ms, event, **detail):
        if len(self.events) >= self.MAX_EVENTS:
            self.dropped += 1
            return
        ev = {"t_ms": round(t_ms, 3), "event": event}
        if detail:
            ev.update(detail)
        self.events.append(ev)

    def as_dict(self):
        d = {"req_id": self.req_id, "events": list(self.events)}
        if self.dropped:
            d["dropped_events"] = self.dropped
        return d


class SlotStepLedger:
    """Integer micro-unit slot-step accounting.

    One scheduler step books exactly ``max_batch × decode_steps`` units
    (each slot: K units), so ``sum(units) == steps × max_batch × K``
    holds by construction — there is no residual to drift."""

    def __init__(self, max_batch, decode_steps):
        self.max_batch = int(max_batch)
        self.K = int(decode_steps)
        self.units = {c: 0 for c in SLOT_CATEGORIES}
        self.steps = 0

    def account(self, acts, occupied):
        """Book one scheduler step. ``acts`` maps slot →
        ``("prefill"|"cached_prefill"|"recompute", n_valid)`` or
        ``("decode", delivered)`` or — with speculation on —
        ``("decode", delivered, drafted_rejected)``;
        ``occupied`` is the set of slots still holding a request (a slot
        neither acted nor occupied is idle; occupied-but-unscheduled is
        frozen — an invariant breach worth seeing, not hiding)."""
        K = self.K
        u = self.units
        for i in range(self.max_batch):
            a = acts.get(i)
            if a is None:
                u["frozen" if i in occupied else "idle"] += K
            elif a[0] == "decode":
                d = min(max(int(a[1]), 0), K)
                # 3-tuple: the speculative engine splits the non-useful
                # remainder into verify-rejected drafts vs frozen budget
                r = min(max(int(a[2]), 0), K - d) if len(a) > 2 else 0
                u["decode_useful"] += d
                u["drafted_rejected"] += r
                u["frozen"] += K - d - r
            else:
                u[a[0]] += K
        self.steps += 1

    def totals(self):
        """``(units_by_category, steps)`` — units are cumulative ints."""
        return dict(self.units), self.steps

    def total_units(self):
        return sum(self.units.values())

    def wasted_fraction(self):
        total = self.total_units()
        if not total:
            return 0.0
        return sum(self.units[c] for c in WASTE_CATEGORIES) / total

    def as_dict(self):
        total = self.total_units()
        K = self.K
        return {
            "steps": self.steps,
            "max_batch": self.max_batch,
            "decode_steps": K,
            "units": dict(self.units),
            "total_units": total,
            "slot_steps": {c: self.units[c] / K for c in SLOT_CATEGORIES},
            "total_slot_steps": total / K,   # == steps * max_batch
            "wasted_frac": round(self.wasted_fraction(), 6),
        }


class ServingObservatory:
    """Host-side serving observability: timelines + ledger + SLO rules.

    The server drives it synchronously from its step loop (record_* /
    ``end_step``) and the scheduler through the observer hooks
    (``on_admit`` / ``on_preempt`` / ``on_admission_fail``); everything
    it consumes is already host data, so it adds zero device syncs."""

    SNAPSHOT_MIN_INTERVAL_S = 5.0
    MAX_ANOMALY_HISTORY = 100

    def __init__(self, max_batch, decode_steps=1, job_name="",
                 snapshot_path="SERVING_HEALTH.json", window=32,
                 warmup_windows=1, ttft_slo_ms=1000.0, ttft_breach_frac=0.5,
                 queue_growth_windows=3, preemption_thrash=8,
                 no_progress_steps=200, timeline_ring=64, window_ring=128,
                 trace_lanes=True, spec_acceptance_floor=None,
                 registry=None, on_escalate=None,
                 on_anomaly=None, engine_state_fn=None, log_fn=None):
        self.max_batch = int(max_batch)
        self.job_name = job_name
        self.snapshot_path = snapshot_path
        self.window = max(1, int(window))
        self.warmup_windows = int(warmup_windows)
        self.ttft_slo_ms = float(ttft_slo_ms)
        self.ttft_breach_frac = float(ttft_breach_frac)
        self.queue_growth_windows = int(queue_growth_windows)
        self.preemption_thrash = int(preemption_thrash)
        self.no_progress_steps = int(no_progress_steps)
        self.trace_lanes = bool(trace_lanes)
        # None = speculation off (or unguarded): the speculation_waste
        # rule only arms when the server hands over a floor
        self.spec_acceptance_floor = (None if spec_acceptance_floor is None
                                      else float(spec_acceptance_floor))
        self.registry = registry
        self.on_escalate = on_escalate if on_escalate is not None \
            else _flush_trace
        self.on_anomaly = on_anomaly
        self.engine_state_fn = engine_state_fn
        self._log = log_fn or logger.warning

        self.ledger = SlotStepLedger(max_batch, decode_steps)
        self._t0 = time.perf_counter()
        self.active = {}                       # req_id -> RequestTimeline
        self.recent = deque(maxlen=max(1, int(timeline_ring)))
        self.windows = deque(maxlen=max(1, int(window_ring)))
        self.anomalies = []
        self.rule_counts = {}
        self.windows_closed = 0      # cadence (unforced) windows only
        self._window_seq = 0         # every window, forced included
        self.steps_seen = 0
        self.requests_submitted = 0
        self.requests_finished = {}            # reason -> count
        self.preemptions_by_reason = {}
        self.recompute_tokens = 0
        self.tokens_delivered = 0
        self.first_tokens = 0
        self.no_progress_streak = 0
        self.max_no_progress_streak = 0
        self._snapshots_written = 0
        self._last_snapshot_t = float("-inf")
        self._lanes_named = False
        self._queue_means = deque(
            maxlen=max(2, self.queue_growth_windows + 1))
        # last engine samples (end_step feeds these; report() reads them)
        self._last_queue_depth = 0
        self._last_active = 0
        self._last_kv_occupancy = 0.0
        self._last_kv_frag = 0.0
        self._reset_window()

    @classmethod
    def from_config(cls, obs_config, max_batch, decode_steps=1,
                    job_name="", spec_acceptance_floor=None,
                    registry=None, on_escalate=None,
                    on_anomaly=None, engine_state_fn=None):
        """Build from a parsed ``serving.observability`` block
        (:class:`~deepspeed_tpu.runtime.config.
        DeepSpeedServingObservabilityConfig`)."""
        return cls(
            max_batch=max_batch, decode_steps=decode_steps,
            job_name=job_name,
            snapshot_path=obs_config.snapshot_file,
            window=obs_config.window,
            warmup_windows=obs_config.warmup_windows,
            ttft_slo_ms=obs_config.ttft_slo_ms,
            ttft_breach_frac=obs_config.ttft_breach_frac,
            queue_growth_windows=obs_config.queue_growth_windows,
            preemption_thrash=obs_config.preemption_thrash,
            no_progress_steps=obs_config.no_progress_steps,
            timeline_ring=obs_config.timeline_ring,
            window_ring=obs_config.window_ring,
            trace_lanes=obs_config.trace_lanes,
            spec_acceptance_floor=spec_acceptance_floor,
            registry=registry, on_escalate=on_escalate,
            on_anomaly=on_anomaly, engine_state_fn=engine_state_fn)

    # ------------------------------------------------------------- clock
    def _now_ms(self):
        return (time.perf_counter() - self._t0) * 1e3

    def _timeline(self, req_id):
        tl = self.active.get(req_id)
        if tl is None:
            tl = self.active[req_id] = RequestTimeline(req_id)
        return tl

    # ----------------------------------------------------- Chrome lanes
    def _lane_tid(self, slot):
        # slot lanes 0..max_batch-1; the queue-wait lane sits after them
        from deepspeed_tpu.telemetry.tracer import allocate_lane_tid
        return allocate_lane_tid(("serving", "queue" if slot is None
                                  else int(slot)))

    def _name_lanes(self, tracer):
        """One-time thread_name metadata so the lanes read as
        'serving slot N' / 'serving queue' in chrome://tracing."""
        pid = os.getpid()
        for slot in range(self.max_batch):
            tracer.emit({"name": "thread_name", "ph": "M", "pid": pid,
                         "tid": self._lane_tid(slot),
                         "args": {"name": f"serving slot {slot}"}})
        tracer.emit({"name": "thread_name", "ph": "M", "pid": pid,
                     "tid": self._lane_tid(None),
                     "args": {"name": "serving queue"}})
        self._lanes_named = True

    def _lane_span(self, slot, name, t0_ns, t1_ns, **args):
        if not self.trace_lanes:
            return
        tracer = _tracer_mod.get_tracer()
        if not tracer.enabled:
            return
        if not self._lanes_named:
            self._name_lanes(tracer)
        ev = {"name": name, "ph": "X", "ts": t0_ns // 1000,
              "dur": max(0, (t1_ns - t0_ns) // 1000),
              "pid": os.getpid(), "tid": self._lane_tid(slot)}
        if args:
            ev["args"] = args
        tracer.emit(ev)

    def _lane_instant(self, slot, name, **args):
        if not self.trace_lanes:
            return
        tracer = _tracer_mod.get_tracer()
        if not tracer.enabled:
            return
        if not self._lanes_named:
            self._name_lanes(tracer)
        ev = {"name": name, "ph": "i", "s": "t",
              "ts": time.perf_counter_ns() // 1000,
              "pid": os.getpid(), "tid": self._lane_tid(slot)}
        if args:
            ev["args"] = args
        tracer.emit(ev)

    # -------------------------------------------------- lifecycle hooks
    def record_submit(self, req):
        self.requests_submitted += 1
        tl = self._timeline(req.req_id)
        tl.wait_start = time.perf_counter()
        tl.add(self._now_ms(), "queued", prompt_len=len(req.prompt),
               max_new_tokens=req.max_new_tokens)

    # scheduler observer protocol -------------------------------------
    def on_admit(self, req):
        tl = self._timeline(req.req_id)
        tl.decoding = False
        tl.add(self._now_ms(), "admitted", slot=req.slot,
               blocks=len(req.block_table))
        # queue-wait lane span: submit (or re-queue) -> admission — a
        # re-admitted request's wait starts at its REQUEUE, not zero
        # (preemption churn is exactly what this lane exists to show)
        if self.trace_lanes:
            now_ns = time.perf_counter_ns()
            start = (tl.wait_start if tl.wait_start is not None
                     else req.submit_t)
            wait_ns = int(max(0.0, time.perf_counter() - start) * 1e9)
            self._lane_span(None, f"req{req.req_id} queued",
                            now_ns - wait_ns, now_ns)

    def on_preempt(self, req, reason, evicted_tokens):
        self.preemptions_by_reason[reason] = \
            self.preemptions_by_reason.get(reason, 0) + 1
        self._win["preemptions"] += 1
        t = self._now_ms()
        tl = self._timeline(req.req_id)
        tl.add(t, "preempted", reason=reason,
               evicted_tokens=int(evicted_tokens), slot=req.slot)
        tl.add(t, "requeued")
        tl.wait_start = time.perf_counter()
        self._lane_instant(req.slot, f"req{req.req_id} preempted",
                           reason=reason,
                           evicted_tokens=int(evicted_tokens))

    def on_admission_fail(self, req):
        # an admission failure IS a finish (the server drains it into its
        # finished queue with reason "capacity") — book it, or the report
        # counters diverge from serving_requests_finished_total
        self.requests_finished["capacity"] = \
            self.requests_finished.get("capacity", 0) + 1
        tl = self._timeline(req.req_id)
        tl.add(self._now_ms(), "failed", reason="capacity")
        self._finish_timeline(req.req_id, "capacity")

    # server step hooks -----------------------------------------------
    def record_prefill(self, req, slot, start, n_valid, n_recompute,
                       t0_ns, t1_ns, done):
        self.recompute_tokens += int(n_recompute)
        self._win["recompute_tokens"] += int(n_recompute)
        self._timeline(req.req_id).add(
            self._now_ms(), "prefill_chunk", slot=slot, start=int(start),
            n_valid=int(n_valid), recompute=int(n_recompute),
            done=bool(done))
        kind = ("recompute" if n_recompute else
                ("cached_prefill" if getattr(req, "prefix_hit_blocks", 0)
                 else "prefill"))
        self._lane_span(slot, kind, t0_ns, t1_ns, tokens=int(n_valid),
                        recompute=int(n_recompute))

    def record_decode(self, dispatch_by_slot, t0_ns, t1_ns):
        """One decode dispatch, BEFORE token delivery (so each
        timeline's ``decode_begin`` precedes its ``first_token``).
        ``dispatch_by_slot`` maps slot → ``(req, budget)``; the kept
        token counts arrive with ``end_step``'s acts."""
        t = self._now_ms()
        for slot, (req, budget) in dispatch_by_slot.items():
            tl = self._timeline(req.req_id)
            if not tl.decoding:
                tl.decoding = True
                tl.add(t, "decode_begin", slot=slot)
            self._lane_span(slot, "decode", t0_ns, t1_ns,
                            budget=int(budget))

    def record_first_token(self, req, ttft_ms):
        self.first_tokens += 1
        self._win["ttft_ms"].append(float(ttft_ms))
        self._timeline(req.req_id).add(self._now_ms(), "first_token",
                                       ttft_ms=round(float(ttft_ms), 3))
        self._lane_instant(req.slot, f"req{req.req_id} first_token",
                           ttft_ms=round(float(ttft_ms), 3))

    def record_finish(self, req, reason, slot):
        self.requests_finished[reason] = \
            self.requests_finished.get(reason, 0) + 1
        tl = self._timeline(req.req_id)
        tl.add(self._now_ms(), "finished", reason=reason,
               tokens=len(req.output_tokens),
               preemptions=req.preemptions)
        self._lane_instant(slot, f"req{req.req_id} finished",
                           reason=reason)
        self._finish_timeline(req.req_id, reason)

    def _finish_timeline(self, req_id, reason):
        tl = self.active.pop(req_id, None)
        if tl is None:
            return
        d = tl.as_dict()
        d["finish_reason"] = reason
        self.recent.append(d)

    # ------------------------------------------------------------ steps
    def _reset_window(self):
        self._win = {
            "steps": 0,
            "units0": dict(self.ledger.units),
            "queue_sum": 0.0, "queue_max": 0, "queue_first": None,
            "active_sum": 0.0, "active_max": 0,
            "occ_sum": 0.0, "occ_peak": 0.0, "frag_sum": 0.0,
            "preemptions": 0, "recompute_tokens": 0,
            "tokens": 0, "ttft_ms": [],
        }

    def end_step(self, acts, occupied, queue_depth, active, kv_occupancy,
                 kv_fragmentation, progress):
        """Close one scheduler step: book the slot units, sample the
        window series, track the exact no-progress streak, and close the
        window every ``window`` steps."""
        self.ledger.account(acts, occupied)
        self.steps_seen += 1
        w = self._win
        w["steps"] += 1
        for a in acts.values():
            if a[0] == "decode":
                self.tokens_delivered += int(a[1])
                w["tokens"] += int(a[1])
        if w["queue_first"] is None:
            w["queue_first"] = int(queue_depth)
        w["queue_sum"] += queue_depth
        w["queue_max"] = max(w["queue_max"], int(queue_depth))
        w["active_sum"] += active
        w["active_max"] = max(w["active_max"], int(active))
        w["occ_sum"] += kv_occupancy
        w["occ_peak"] = max(w["occ_peak"], float(kv_occupancy))
        w["frag_sum"] += kv_fragmentation
        self._last_queue_depth = int(queue_depth)
        self._last_active = int(active)
        self._last_kv_occupancy = float(kv_occupancy)
        self._last_kv_frag = float(kv_fragmentation)
        if progress:
            self.no_progress_streak = 0
        else:
            self.no_progress_streak += 1
            self.max_no_progress_streak = max(self.max_no_progress_streak,
                                              self.no_progress_streak)
        # cadence close BEFORE any no-progress escalation: the
        # escalation's snapshot re-enters report(), which force-closes
        # the in-flight window — a boundary-step escalation would turn
        # this cadence window into a forced (rule-skipped, unpublished)
        # one out from under the stale local accumulator reference
        if w["steps"] >= self.window:
            self._close_window(forced=False)
        if not progress and \
                self.no_progress_streak == self.no_progress_steps:
            self._escalate([{
                "rule": "no_progress", "step": self.steps_seen,
                "severity": RULE_SEVERITY["no_progress"],
                "detail": f"{self.no_progress_streak} consecutive "
                          f"scheduler steps made no progress "
                          f"(waiting={queue_depth} active={active}) — "
                          f"livelock-adjacent; the serve_forever hard "
                          f"guard raises at 1000"}])

    def _close_window(self, forced):
        w = self._win
        steps = w["steps"]
        if steps <= 0:
            return None
        units = {c: self.ledger.units[c] - w["units0"][c]
                 for c in SLOT_CATEGORIES}
        total = sum(units.values())
        K = self.ledger.K
        ttfts = w["ttft_ms"]
        window = {
            "index": self._window_seq,
            "end_step": self.steps_seen,
            "steps": steps,
            "slot_units": units,
            "total_units": total,
            "wasted_frac": round(
                sum(units[c] for c in WASTE_CATEGORIES) / total, 6)
            if total else 0.0,
            "queue_depth": {
                "first": w["queue_first"], "last": self._last_queue_depth,
                "mean": round(w["queue_sum"] / steps, 3),
                "max": w["queue_max"]},
            "active": {"mean": round(w["active_sum"] / steps, 3),
                       "max": w["active_max"]},
            "kv": {"occupancy_mean": round(w["occ_sum"] / steps, 4),
                   "occupancy_peak": round(w["occ_peak"], 4),
                   "fragmentation_mean": round(w["frag_sum"] / steps, 4)},
            "preemptions": w["preemptions"],
            "recompute_tokens": w["recompute_tokens"],
            "tokens": w["tokens"],
            "first_tokens": len(ttfts),
            "ttft_ms": {
                "count": len(ttfts),
                "max": round(max(ttfts), 3) if ttfts else None,
                "over_slo": sum(t > self.ttft_slo_ms for t in ttfts)},
        }
        self._window_seq += 1
        if forced:
            # report-path partial window: ring only, no rules, not
            # counted toward warmup (the PR-4 forced-window discipline)
            window["forced"] = True
            self.windows.append(window)
            return window
        self.windows.append(window)
        self.windows_closed += 1
        self._queue_means.append(window["queue_depth"]["mean"])
        self._publish(window)
        # fleet flight recorder: when this process also ships fleet
        # records, closed serving SLO windows ride along in the next
        # rank record (fleet.py is host-only, so this stays device-free)
        from deepspeed_tpu.telemetry import fleet as _fleet_mod
        shipper = _fleet_mod.get_shipper()
        if shipper is not None:
            shipper.note_serving_window(window)
        # reset BEFORE the rules run: escalation re-enters report() (the
        # snapshot), and report() force-closes any partial window — with
        # the accumulators still live it would ring-append the window
        # just closed a second time as a forced duplicate
        self._reset_window()
        if self.windows_closed > self.warmup_windows:
            self._check_rules(window)
        return window

    def _publish(self, window):
        reg = self.registry
        if reg is None:
            return
        for c in SLOT_CATEGORIES:
            n = window["slot_units"][c]
            if n > 0:
                reg.counter(
                    "serving_slot_units_total",
                    "slot-step micro-units by category (decode_steps "
                    "units per slot per scheduler step)",
                    labels={"category": c}).inc(n)
        reg.gauge("serving_window_wasted_frac",
                  "wasted (idle+frozen+recompute) fraction of the last "
                  "closed slot-step window").set(window["wasted_frac"])
        reg.gauge("serving_kv_fragmentation",
                  "allocated-but-unwritten fraction of live KV blocks "
                  "(window mean)").set(
                      window["kv"]["fragmentation_mean"])

    # ------------------------------------------------------------- rules
    def _check_rules(self, window):
        anoms = []
        tt = window["ttft_ms"]
        if tt["count"]:
            frac = tt["over_slo"] / tt["count"]
            # >= so the boundary is reachable: breach_frac=1.0 means
            # "fire when EVERY first token breaches", not a dead rule
            if frac >= self.ttft_breach_frac:
                anoms.append({
                    "rule": "ttft_slo_breach", "step": window["end_step"],
                    "severity": RULE_SEVERITY["ttft_slo_breach"],
                    "fraction": round(frac, 4),
                    "detail": f"{tt['over_slo']}/{tt['count']} first "
                              f"tokens in the window exceeded the "
                              f"{self.ttft_slo_ms:g} ms TTFT SLO "
                              f"(threshold "
                              f"{self.ttft_breach_frac:.0%}; worst "
                              f"{tt['max']:g} ms)"})
        qm = self._queue_means
        if (len(qm) == qm.maxlen and qm[-1] >= 1
                and all(b > a for a, b in zip(qm, list(qm)[1:]))):
            anoms.append({
                "rule": "queue_growth", "step": window["end_step"],
                "severity": RULE_SEVERITY["queue_growth"],
                "detail": f"mean queue depth grew monotonically across "
                          f"the last {len(qm)} windows "
                          f"({', '.join(f'{q:.1f}' for q in qm)}) — "
                          f"arrivals outpace service"})
        if window["preemptions"] >= self.preemption_thrash:
            anoms.append({
                "rule": "preemption_thrash", "step": window["end_step"],
                "severity": RULE_SEVERITY["preemption_thrash"],
                "detail": f"{window['preemptions']} preemptions in one "
                          f"{window['steps']}-step window (threshold "
                          f"{self.preemption_thrash}) burned "
                          f"{window['recompute_tokens']} recompute "
                          f"tokens — the KV pool is too small for the "
                          f"admitted load"})
        useful = (window["slot_units"]["decode_useful"]
                  + window["slot_units"]["cached_prefill"]
                  + window["slot_units"]["prefill"]
                  + window["slot_units"]["recompute"])
        if window["active"]["max"] > 0 and useful == 0:
            anoms.append({
                "rule": "decode_stall", "step": window["end_step"],
                "severity": RULE_SEVERITY["decode_stall"],
                "detail": f"slots were occupied (peak "
                          f"{window['active']['max']}) for a whole "
                          f"{window['steps']}-step window but zero "
                          f"slot-units advanced any request — the "
                          f"scheduler's forward-progress invariant "
                          f"broke"})
        # speculation_waste: the window's decode work split badly between
        # kept tokens and verify-rejected drafts. Only armed when the
        # server configured a floor (speculation on), and only judged on
        # windows that actually speculated (rejections booked — an
        # all-accepted window has nothing to complain about).
        if self.spec_acceptance_floor is not None:
            kept = window["slot_units"]["decode_useful"]
            rej = window["slot_units"]["drafted_rejected"]
            if rej > 0:
                acc = kept / (kept + rej)
                if acc < self.spec_acceptance_floor:
                    anoms.append({
                        "rule": "speculation_waste",
                        "step": window["end_step"],
                        "severity": RULE_SEVERITY["speculation_waste"],
                        "acceptance": round(acc, 4),
                        "detail": f"windowed speculative acceptance "
                                  f"{acc:.1%} fell below the "
                                  f"{self.spec_acceptance_floor:.0%} "
                                  f"floor ({kept} kept vs {rej} "
                                  f"rejected draft units) — draft work "
                                  f"is costing more than it saves; the "
                                  f"guardian can disable speculation"})
        if anoms:
            self._escalate(anoms)

    # -------------------------------------------------------- escalation
    def _escalate(self, anoms):
        # the shared protocol (telemetry/escalation.py)
        escalation.escalate(self, anoms, tag="serving",
                            counter="serving_anomalies_total",
                            counter_help="serving SLO/health rule "
                                         "firings")

    # ----------------------------------------------------------- outputs
    def verdict(self):
        if not self.steps_seen:
            return "unknown"
        seen = {RULE_SEVERITY.get(r, "warning") for r in self.rule_counts}
        for tier in _SEVERITY_ORDER:
            if tier in seen:
                return tier
        return "healthy"

    def report(self):
        """The full forensics dict (what ``SERVING_HEALTH.json`` holds).
        Closes the in-flight partial window as a ``forced`` ring entry
        (no rules run on it, PR-4 style) so the report is current."""
        if self._win["steps"] > 0:
            self._close_window(forced=True)
            # forced close keeps the accumulators: restart the window
            # from the current ledger state so cadence windows stay
            # contiguous with what was just reported
            self._reset_window()
        engine_state = None
        if self.engine_state_fn is not None:
            try:
                engine_state = self.engine_state_fn()
            except Exception:
                engine_state = None
        return {
            "schema": SERVING_HEALTH_SCHEMA,
            "enabled": True,
            "job_name": self.job_name,
            "verdict": self.verdict(),
            "rules": {
                "window": self.window,
                "warmup_windows": self.warmup_windows,
                "ttft_slo_ms": self.ttft_slo_ms,
                "ttft_breach_frac": self.ttft_breach_frac,
                "queue_growth_windows": self.queue_growth_windows,
                "preemption_thrash": self.preemption_thrash,
                "no_progress_steps": self.no_progress_steps,
                "spec_acceptance_floor": self.spec_acceptance_floor,
            },
            "slot_ledger": self.ledger.as_dict(),
            "counters": {
                "steps_seen": self.steps_seen,
                "requests_submitted": self.requests_submitted,
                "requests_finished": dict(self.requests_finished),
                "preemptions_by_reason": dict(self.preemptions_by_reason),
                "recompute_tokens": self.recompute_tokens,
                "tokens_delivered": self.tokens_delivered,
                "first_tokens": self.first_tokens,
                "max_no_progress_streak": self.max_no_progress_streak,
                "anomaly_counts": dict(self.rule_counts),
            },
            "queue": {"depth": self._last_queue_depth,
                      "active": self._last_active},
            "kv": {"occupancy": round(self._last_kv_occupancy, 4),
                   "fragmentation": round(self._last_kv_frag, 4)},
            "anomalies": list(self.anomalies),
            "windows": list(self.windows),
            "timelines": {
                "active": [tl.as_dict() for tl in self.active.values()],
                "recent": list(self.recent),
            },
            "engine_state": engine_state,
        }

    def write_snapshot(self, path=None, force=False, report=None):
        """Write ``SERVING_HEALTH.json`` (throttled like the health/
        goodput snapshots — re-serialising timelines on every anomaly of
        a thrash storm must not stall the serving loop)."""
        if not force and (time.monotonic() - self._last_snapshot_t
                          < self.SNAPSHOT_MIN_INTERVAL_S):
            return None
        self._last_snapshot_t = time.monotonic()
        path = path or self.snapshot_path
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(json_safe(report if report is not None
                                else self.report()),
                      f, indent=1, default=repr, allow_nan=False)
        self._snapshots_written += 1
        return path

    def close(self):
        """Final snapshot — only when there is something to explain."""
        if self.anomalies:
            self.write_snapshot(force=True)


# --------------------------------------------------------------------- CLI

def render(report):
    """Human-readable rendering of a SERVING_HEALTH.json report dict."""
    lines = []
    lines.append(f"serving verdict: {report.get('verdict', '?').upper()}"
                 + (f"  (job {report['job_name']})"
                    if report.get("job_name") else ""))
    led = report.get("slot_ledger") or {}
    total = led.get("total_units") or 0
    lines.append(f"  slot-step ledger: {led.get('steps', 0)} steps x "
                 f"{led.get('max_batch', '?')} slots x "
                 f"K={led.get('decode_steps', '?')} = {total} units "
                 f"(wasted {led.get('wasted_frac', 0):.1%})")
    for c in SLOT_CATEGORIES:
        n = (led.get("units") or {}).get(c, 0)
        if total:
            bar = "#" * int(round(n / total * 40))
            lines.append(f"  {c:14s} {n:8d}  {n / total:6.1%}  {bar}")
    c = report.get("counters", {})
    fin = c.get("requests_finished", {})
    lines.append(f"  requests: {c.get('requests_submitted', 0)} submitted"
                 f", finished {sum(fin.values())} "
                 f"({', '.join(f'{k}={v}' for k, v in fin.items())})")
    pre = c.get("preemptions_by_reason", {})
    if pre:
        lines.append(f"  preemptions: "
                     f"{', '.join(f'{k}={v}' for k, v in pre.items())} "
                     f"(recompute tokens burned "
                     f"{c.get('recompute_tokens', 0)})")
    for a in report.get("anomalies", []):
        lines.append(f"  [{a.get('severity', '?'):8s}] step "
                     f"{a.get('step')}: {a.get('rule')} — "
                     f"{a.get('detail')}")
    if not report.get("anomalies"):
        lines.append("  no serving anomalies recorded")
    kv = report.get("kv") or {}
    lines.append(f"  kv: occupancy {kv.get('occupancy', 0):.1%}, "
                 f"fragmentation {kv.get('fragmentation', 0):.1%}; "
                 f"queue depth {report.get('queue', {}).get('depth', 0)}")
    return "\n".join(lines)


def _demo(args):
    """Tiny serving engine + an undersized KV pool + an unmeetable TTFT
    SLO: the burst forces preemption/recompute and breaches the SLO, so
    the committed repo-root SERVING_HEALTH.json example demonstrates the
    rules actually firing (the artifact pin rejects a clean file)."""
    if args.devices:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.devices}")
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    import jax.numpy as jnp
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
    from deepspeed_tpu.utils import groups

    groups.destroy()
    groups.initialize()
    cfg = GPT2Config(vocab_size=256, n_positions=96, n_embd=32,
                     n_layer=2, n_head=2)
    model = GPT2LMHeadModel(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        {"input_ids": jnp.zeros((1, 8), jnp.int32)})["params"]
    eng = deepspeed_tpu.init_inference(model, params=params,
                                       dtype=jnp.float32)
    srv = deepspeed_tpu.init_serving(engine=eng, config={"serving": {
        "max_batch": 3,
        "block_size": 8,
        # undersized pool: three 30+-token requests contend for 9
        # usable blocks -> eviction + recompute churn
        "num_blocks": 10,
        "prefill_chunk": 8,
        "observability": {
            "enabled": True,
            "window": 8,
            "warmup_windows": 1,
            # sub-millisecond SLO: every first token on this model
            # breaches it -> the demo file carries a ttft_slo_breach
            "ttft_slo_ms": 0.5,
            "ttft_breach_frac": 0.25,
            # one eviction per window already counts as thrash at demo
            # scale, so the example also demonstrates preemption cost
            "preemption_thrash": 1,
            "snapshot_file": os.path.abspath(args.out),
        },
    }})
    rng = np.random.default_rng(0)
    for i in range(args.requests):
        plen = int(rng.integers(8, 25))
        srv.submit(rng.integers(0, cfg.vocab_size, (plen,)),
                   max_new_tokens=int(rng.integers(8, 21)))
    srv.serve_forever()
    report = srv.serving_report(write=True)
    srv.close()
    print(render(report))
    print(f"\nwrote {args.out}")
    return 0


def main(argv=None):
    import argparse
    p = argparse.ArgumentParser(
        prog="python -m deepspeed_tpu.telemetry.serving_observatory",
        description="Render a SERVING_HEALTH.json snapshot, or run the "
                    "serving forensics demo (tiny engine, undersized KV "
                    "pool, unmeetable TTFT SLO)")
    p.add_argument("--render", metavar="SERVING_HEALTH.json",
                   help="pretty-print an existing snapshot and exit")
    p.add_argument("--demo", action="store_true",
                   help="drive a preemption-heavy burst through a tiny "
                        "serving engine and write the snapshot")
    p.add_argument("--requests", type=int, default=10)
    p.add_argument("--devices", type=int, default=8,
                   help="virtual CPU devices for the demo (0 = existing)")
    p.add_argument("--out", default="SERVING_HEALTH.json")
    args = p.parse_args(argv)
    if args.render:
        with open(args.render) as f:
            print(render(json.load(f)))
        return 0
    if args.demo:
        return _demo(args)
    p.print_help()
    return 2


if __name__ == "__main__":
    import sys
    sys.exit(main())
