"""Live observability plane — the HTTP scrape/status endpoint.

Every observatory in this repo speaks *files*: the Prometheus exporter
is a text-file-collector ``.prom`` sink and the goodput / health /
serving / fleet / memory / chronicle reports are throttled JSON
snapshots read after the fact. A fleet is operated through a scrape
endpoint and a status API — this module is that endpoint, zero
dependencies (stdlib :class:`http.server.ThreadingHTTPServer`):

========================  =================================================
route                     serves
========================  =================================================
``GET /metrics``          :func:`sinks.render_prometheus` over the live
                          registry — a REAL scrape target (the ``.prom``
                          file sink remains the node_exporter
                          textfile-collector path)
``GET /healthz``          liveness + armed-monitor inventory with
                          last-tick ages (no auth — LB probes)
``GET /readyz``           readiness: 200 once at least one monitor is
                          registered, 503 before/after
``GET /api/report/<x>``   each armed monitor's ``report()`` — its latest
                          HOST-SIDE snapshot
``GET /api/events``       bounded chronicle tail, ``?since_seq=``
                          resumable (poll-friendly); seqs the in-memory
                          ring has drop-NEW'd are served from the rank's
                          on-disk JSONL stream when ``run_dir`` is armed
                          (:meth:`RunChronicle.events_since`)
========================  =================================================

Federation hooks: ``identity={"rank": N}`` stamps every ``/metrics``
family with the rank label (:func:`sinks.render_prometheus`
``extra_labels``), :meth:`ObsServer.add_route` mounts the aggregator's
merged ``/federation/*`` + ``/api/fleet/*`` views, and
:meth:`ObsServer.announce` writes the endpoint into the run-dir peer
registry so a :class:`telemetry.federation.FleetAggregator` discovers
ranks without static config.

The load-bearing contract: **a scrape must NEVER force a device fetch,
a sync, or a compile**. Providers are monitor-level bound ``report()``
methods (pure host bookkeeping) — never the engine's ``health_report``/
``memory_report`` wrappers, which force a device tick before reporting.
The serving thread runs under the ledger's ``suppress_attribution`` so
answering a scrape can never book badput into the run it is exposing.

Thread discipline (the chronicle/PR-5 pattern): the serving thread and
``weakref.finalize`` hold only the stdlib server object and a
:class:`_ObsState`, never the :class:`ObsServer` wrapper — an abandoned
server is reclaimed and its port released without an explicit
``close()``. ``port=0`` auto-picks a free port; the bound address is on
``server.url``. An optional bearer token guards everything except the
two probe routes.
"""

import json
import math
import os
import threading
import weakref
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlsplit

from deepspeed_tpu.telemetry import chronicle as _chronicle
from deepspeed_tpu.telemetry import clock as _clk
from deepspeed_tpu.telemetry import metrics as _metrics
from deepspeed_tpu.utils.logging import logger

OBS_SERVER_SCHEMA = "deepspeed_tpu.obs_server/1"

# every route the API exposes; /api/report/<name> 404s with this
# inventory so an operator's typo is self-diagnosing
ROUTES = ("/metrics", "/healthz", "/readyz", "/api/events",
          "/api/report/<name>")


def _json_sane(obj):
    """Strictly-JSON-serialisable copy: non-finite floats become strings
    (the chronicle contract), unknown objects their repr."""
    if isinstance(obj, float):
        return obj if math.isfinite(obj) else str(obj)
    if isinstance(obj, dict):
        return {str(k): _json_sane(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_json_sane(v) for v in obj]
    if obj is None or isinstance(obj, (bool, int, str)):
        return obj
    return repr(obj)


class _ObsState:
    """Everything the request handlers may touch — the server thread and
    the handlers hold ONLY this (never the ObsServer), so finalize-based
    teardown works."""

    def __init__(self, registry=None, token="", events_tail=256,
                 identity=None):
        self.registry = registry
        self.token = str(token or "")
        self.events_tail = max(1, int(events_tail))
        self.identity = dict(identity or {})
        self.lock = threading.Lock()
        self.providers = {}          # name -> report() callable
        self.age_fns = {}            # name -> seconds-since-last-tick fn
        self.routes = {}             # exact extra path -> handler fn
        self.prefix_routes = {}      # path prefix -> handler fn
        self.requests_total = 0
        self.requests_by_route = {}
        self.errors_total = 0
        self.started_us = _clk.monotonic_us()


class _Handler(BaseHTTPRequestHandler):
    # one handler class shared by every ObsServer; state rides the
    # stdlib server instance (attached in ObsServer.__init__)
    server_version = "ds-obs/1"
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):      # scrapes are not log lines
        logger.debug("[obs_server] " + fmt, *args)

    # ------------------------------------------------------------ replies
    def _reply(self, code, payload, content_type="application/json"):
        if isinstance(payload, bytes):
            body = payload
        else:
            # compact separators and a strict-dump fast path: the scrape
            # path must stay cheap under load (the serving bench pins its
            # tok/s cost), so the recursive _json_sane copy only runs when
            # the payload actually holds NaN/Inf or a non-JSON object
            try:
                body = json.dumps(payload, separators=(",", ":"),
                                  allow_nan=False).encode()
            except (ValueError, TypeError):
                body = json.dumps(_json_sane(payload),
                                  separators=(",", ":"),
                                  allow_nan=False).encode()
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        try:
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            pass                             # scraper went away mid-write

    def _authorized(self, state):
        if not state.token:
            return True
        return (self.headers.get("Authorization", "")
                == f"Bearer {state.token}")

    # ------------------------------------------------------------- routes
    def do_GET(self):                                   # noqa: N802
        state = self.server._obs_state
        split = urlsplit(self.path)
        path = split.path.rstrip("/") or "/"
        with state.lock:
            state.requests_total += 1
            state.requests_by_route[path] = \
                state.requests_by_route.get(path, 0) + 1
        # the two probe routes skip auth: LB health checks can't carry
        # bearer headers, and they expose armed-ness, not data
        if path not in ("/healthz", "/readyz") \
                and not self._authorized(state):
            self._reply(401, {"error": "unauthorized",
                              "detail": "Authorization: Bearer <token> "
                                        "required"})
            return
        try:
            if path == "/metrics":
                self._metrics(state)
            elif path == "/healthz":
                self._healthz(state, ready=False)
            elif path == "/readyz":
                self._healthz(state, ready=True)
            elif path == "/api/events":
                self._events(state, parse_qs(split.query))
            elif path.startswith("/api/report/"):
                self._report(state, path[len("/api/report/"):])
            else:
                with state.lock:
                    fn = state.routes.get(path)
                    if fn is None:
                        for pref, pfn in state.prefix_routes.items():
                            if path.startswith(pref):
                                fn = pfn
                                break
                    extra = sorted(state.routes) + [
                        p + "<...>" for p in sorted(state.prefix_routes)]
                if fn is not None:
                    self._extra(fn, path, parse_qs(split.query))
                else:
                    self._reply(404, {"error": "unknown route",
                                      "routes": list(ROUTES) + extra})
        except Exception as e:   # a broken provider must not kill serving
            with state.lock:
                state.errors_total += 1
            logger.warning("[obs_server] %s failed: %s", path, e)
            try:
                self._reply(500, {"error": f"{type(e).__name__}: {e}"})
            except Exception:
                pass

    def _extra(self, fn, path, query):
        """Dispatch one registered extra route (the federation hook).
        The handler returns either a JSON payload (200) or a
        ``(code, payload, content_type)`` tuple for full control."""
        out = fn(path, query)
        if isinstance(out, tuple):
            code, payload, ctype = out
            self._reply(code, payload, content_type=ctype)
        else:
            self._reply(200, out)

    def _metrics(self, state):
        from deepspeed_tpu.telemetry.sinks import render_prometheus
        reg = state.registry if state.registry is not None \
            else _metrics.get_registry()
        # identity labels ride EVERY family so a federated aggregator's
        # merge needs no exposition re-parse (fleet satellite 2)
        self._reply(200, render_prometheus(
            reg, extra_labels=state.identity or None).encode(),
            content_type="text/plain; version=0.0.4")

    def _healthz(self, state, ready):
        with state.lock:
            names = sorted(state.providers)
            age_fns = dict(state.age_fns)
        monitors = {}
        for n in names:
            age_fn = age_fns.get(n)
            age = None
            if age_fn is not None:
                try:
                    age = age_fn()
                except Exception:
                    age = None
            monitors[n] = {"armed": True, "last_tick_age_s": age}
        doc = {
            "status": "ok",
            "ready": bool(names),
            "uptime_s": round(
                (_clk.monotonic_us() - state.started_us) / 1e6, 3),
            "monitors": monitors,
            "requests_total": state.requests_total,
        }
        if ready and not names:
            self._reply(503, dict(doc, status="no monitors registered"))
        else:
            self._reply(200, doc)

    def _report(self, state, name):
        with state.lock:
            fn = state.providers.get(name)
            known = sorted(state.providers)
        if fn is None:
            self._reply(404, {"error": f"unknown report {name!r}",
                              "known": known})
            return
        self._reply(200, fn())

    def _events(self, state, query):
        chron = _chronicle.get_chronicle()
        if not chron.enabled:
            self._reply(200, {"enabled": False, "events": [],
                              "last_seq": -1})
            return
        try:
            since = int(query.get("since_seq", ["-1"])[0])
            limit = int(query.get("limit", [state.events_tail])[0])
        except (TypeError, ValueError):
            self._reply(400, {"error": "since_seq/limit must be ints"})
            return
        limit = max(1, min(limit, state.events_tail))
        # events_since falls back to the on-disk JSONL stream when the
        # bounded ring has drop-NEW'd part of the requested range — a
        # resumed consumer gets the FULL tail, not a silent gap
        events = chron.events_since(since)
        truncated = len(events) > limit
        # ?oldest=1 pages forward from the cursor (gapless catch-up —
        # the federation scraper's mode); the default keeps the
        # dashboard-friendly newest-tail view
        oldest = (query.get("oldest", ["0"])[0] in ("1", "true"))
        events = events[:limit] if oldest else events[-limit:]
        self._reply(200, {
            "enabled": True,
            "events": events,
            "n": len(events),
            "truncated": truncated,
            "last_seq": events[-1]["seq"] if events else since,
            "dropped": chron.dropped,
        })


def _serve_loop(httpd):
    # answering a scrape must never book wall time into the goodput
    # ledger of the run being scraped (lazy import: the ledger imports
    # the escalation helper, which imports the chronicle)
    from deepspeed_tpu.telemetry.ledger import suppress_attribution
    with suppress_attribution():
        httpd.serve_forever(poll_interval=0.2)


def _finalize_server(httpd, thread):
    try:
        httpd.shutdown()
    except Exception:
        pass
    if thread.is_alive():
        thread.join(timeout=5.0)
    try:
        httpd.server_close()
    except Exception:
        pass


class ObsServer:
    """The live observability endpoint. Construction binds the socket
    and starts the serving thread; ``close()`` (idempotent — also run by
    ``weakref.finalize`` on abandonment) releases the port.

    ``register(name, report_fn, age_s_fn=None)`` arms one monitor on the
    status API: *report_fn* must be the monitor-level ``report()`` bound
    method (host-side snapshot — the no-device-fetch contract above),
    *age_s_fn* an optional seconds-since-last-tick probe for /healthz.
    """

    def __init__(self, registry=None, host="127.0.0.1", port=0,
                 token="", events_tail=256, identity=None, log_fn=None):
        self._log = log_fn or logger.warning
        self._state = _ObsState(registry=registry, token=token,
                                events_tail=events_tail,
                                identity=identity)
        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self._httpd._obs_state = self._state
        self.host, self.port = self._httpd.server_address[:2]
        self._thread = threading.Thread(
            target=_serve_loop, args=(self._httpd,),
            name=f"ds-obs-server-{self.port}", daemon=True)
        self._thread.start()
        self._closed = False
        self._finalizer = weakref.finalize(
            self, _finalize_server, self._httpd, self._thread)

    @classmethod
    def from_config(cls, tcfg, registry=None, identity=None):
        """Build from a parsed :class:`DeepSpeedTelemetryConfig`
        (``telemetry.server`` block)."""
        return cls(registry=registry, host=tcfg.server_host,
                   port=tcfg.server_port, token=tcfg.server_token,
                   events_tail=tcfg.server_events_tail,
                   identity=identity)

    @property
    def url(self):
        return f"http://{self.host}:{self.port}"

    # --------------------------------------------------------- providers
    def register(self, name, report_fn, age_s_fn=None):
        with self._state.lock:
            self._state.providers[str(name)] = report_fn
            if age_s_fn is not None:
                self._state.age_fns[str(name)] = age_s_fn
        return self

    def unregister(self, name):
        with self._state.lock:
            self._state.providers.pop(name, None)
            self._state.age_fns.pop(name, None)

    def add_route(self, path, handler, prefix=False):
        """Mount *handler* at *path* (exact, or every path under it when
        ``prefix=True``) — how :mod:`telemetry.federation` serves its
        merged ``/federation/*`` and ``/api/fleet/*`` views from the
        rank's own endpoint. *handler* is called as ``handler(path,
        query)`` (query already ``parse_qs``-parsed) and returns a JSON
        payload (200) or a ``(code, payload, content_type)`` tuple; it
        runs on the serving thread, so the no-device-fetch scrape
        contract applies to it too."""
        with self._state.lock:
            if prefix:
                self._state.prefix_routes[str(path)] = handler
            else:
                self._state.routes[str(path)] = handler
        return self

    def announce(self, run_dir, rank=0, job_name="", extra=None):
        """Write this endpoint into the run-dir peer registry
        (``<run_dir>/peers/peer_rank_<rank>.json``, tmp+fsync+rename) so
        a :class:`telemetry.federation.FleetAggregator` scanning the
        shared run dir discovers the rank without static config. Returns
        the registry path (None on write failure — announcing is
        forensics, never fatal)."""
        doc = {"url": self.url, "rank": int(rank),
               "job_name": job_name, "pid": os.getpid(),
               "started_unix_us": _clk.to_unix_us(
                   self._state.started_us)}
        if extra:
            doc.update(extra)
        path = os.path.join(run_dir, "peers",
                            f"peer_rank_{int(rank):05d}.json")
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            _chronicle._atomic_write_bytes(
                path, json.dumps(doc, sort_keys=True,
                                 allow_nan=False).encode())
        except OSError as e:
            self._log("[obs_server] peer announce failed: %s", e)
            return None
        return path

    def providers(self):
        with self._state.lock:
            return sorted(self._state.providers)

    # ------------------------------------------------------------ report
    def report(self):
        st = self._state
        with st.lock:
            by_route = dict(st.requests_by_route)
            extra_routes = sorted(st.routes) + sorted(st.prefix_routes)
        return {
            "schema": OBS_SERVER_SCHEMA,
            "enabled": True,
            "closed": self._closed,
            "url": self.url,
            "host": self.host,
            "port": self.port,
            "auth": bool(st.token),
            "events_tail": st.events_tail,
            "identity": dict(st.identity),
            "extra_routes": extra_routes,
            "providers": self.providers(),
            "requests_total": st.requests_total,
            "requests_by_route": by_route,
            "errors_total": st.errors_total,
            "uptime_s": round(
                (_clk.monotonic_us() - st.started_us) / 1e6, 3),
        }

    def close(self):
        """Stop serving, join the thread, release the port. Idempotent."""
        if self._closed:
            return
        self._closed = True
        self._finalizer()


# Process-global handle (the tracer/registry/chronicle pattern) so
# ds_report can show the armed state + bound address without an engine.
_GLOBAL = None


def get_obs_server():
    return _GLOBAL


def set_obs_server(server):
    """Install *server* as the process global; returns the old one."""
    global _GLOBAL
    old, _GLOBAL = _GLOBAL, server
    return old


def reset_obs_server(if_current=None):
    global _GLOBAL
    if if_current is None or _GLOBAL is if_current:
        _GLOBAL = None
