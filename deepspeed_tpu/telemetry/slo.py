"""SLO burn-rate monitor — multi-window error-budget alerting.

The observatories classify *point* anomalies (a TTFT breach window, a
goodput regression). An operator pages on something else: **error-budget
burn rate** — "at the current bad-fraction, how fast is the SLO's
budget being spent?" — evaluated over TWO windows (SRE multi-window
multi-burn alerting):

* ``burn = bad_fraction / (1 - target)`` — 1.0x means the budget is
  being spent exactly as fast as the SLO allows; 10x means a 30-day
  budget dies in 3 days.
* a **fast** window (~5 min) catches the onset, a **slow** window
  (~1 h) proves it is sustained. Both burning -> page-tier anomaly
  (``slo_burn_page``, critical — the guardian's admission-pause rule);
  fast-only -> ``slo_burn_fast`` (warning). The two-window AND is what
  keeps a 30-second blip from paging anyone.

Objectives are declarative dicts:

* ``{"name": "serving_ttft", "kind": "latency", "metric":
  "serving_ttft_ms", "threshold_ms": 500, "target": 0.99}`` — good =
  observations at or under the threshold, read from the registry
  histogram's cumulative buckets (the effective threshold snaps to the
  smallest bucket edge >= the asked one, and is reported);
* ``{"name": "training_goodput", "kind": "goodput", "target": 0.9}`` —
  good = the ledger's GOOD_CATEGORIES seconds, bad = everything else
  (the badput the GOODPUT.json ring books).

Samples are cumulative ``(t_us, bad, total)`` tuples on the shared
integer-µs axis (:func:`clock.monotonic_us`); a window's burn is the
delta between its newest sample and the last sample at/before the
window start, so the spans re-add exactly (``span_us == t_newest_us -
t_anchor_us`` — pinned by the artifact tests). A window only becomes
*eligible* to burn once samples span at least half of it: two seconds
into a run, one bad request is not a one-hour trend.

Escalation rides the shared :func:`escalation.escalate` protocol
(warn-once -> throttled ``SLO_REPORT.json`` -> ``slo_anomalies_total``
counter -> chronicle event -> guardian ``on_anomaly``), plus per-
objective ``slo_burn_total{objective,window}`` counters and live
``slo_burn_rate`` gauges. Everything is host-side: a tick never
touches the device, and a disabled monitor's tick is one attribute
check (guarded < 2 µs in tests/perf/telemetry_overhead.py).

CLI: ``python -m deepspeed_tpu.telemetry.slo --demo`` injects a TTFT
degradation against shrunk windows, burns fast+slow, delivers the page
to a live guardian (admission pause) and correlates the incident chain
— the committed repo-root SLO_REPORT.json comes from here.
"""

import argparse
import json
import os
import threading
from collections import deque

from deepspeed_tpu.telemetry import chronicle as _chronicle
from deepspeed_tpu.telemetry import clock as _clk
from deepspeed_tpu.telemetry import escalation as _escalation
from deepspeed_tpu.telemetry import ledger as _ledger
from deepspeed_tpu.utils.logging import logger

SLO_SCHEMA = "deepspeed_tpu.slo/1"

WINDOWS = ("fast", "slow")
RULE_PAGE = "slo_burn_page"
RULE_FAST = "slo_burn_fast"
# a window must span at least this fraction of itself before it may
# burn — the guard that keeps run-start noise from paging
MIN_SPAN_FRAC = 0.5


def normalize_objective(obj):
    """Validate one declarative objective dict; returns a normalized
    copy. Raises ``ValueError`` with the offending field named."""
    if not isinstance(obj, dict):
        raise ValueError(f"objective must be a dict, got {type(obj)}")
    name = obj.get("name")
    if not name or not isinstance(name, str):
        raise ValueError("objective needs a non-empty string 'name'")
    kind = obj.get("kind")
    if kind not in ("latency", "goodput"):
        raise ValueError(f"objective {name!r}: kind must be 'latency' or "
                         f"'goodput', got {kind!r}")
    target = obj.get("target")
    if not isinstance(target, (int, float)) or not 0.0 < target < 1.0:
        raise ValueError(f"objective {name!r}: target must be in (0, 1), "
                         f"got {target!r}")
    out = {"name": name, "kind": kind, "target": float(target)}
    if kind == "latency":
        metric = obj.get("metric")
        if not metric or not isinstance(metric, str):
            raise ValueError(f"objective {name!r}: latency objectives "
                             f"need a 'metric' histogram family")
        thresh = obj.get("threshold_ms")
        if not isinstance(thresh, (int, float)) or thresh <= 0:
            raise ValueError(f"objective {name!r}: threshold_ms must be "
                             f"> 0, got {thresh!r}")
        out["metric"] = metric
        out["threshold_ms"] = float(thresh)
    return out


class SloMonitor:
    """Burn-rate evaluation over declarative objectives. See the module
    docstring. ``tick()`` is the only hot entry point — call it at step
    cadence; it self-throttles to ``eval_interval_s``."""

    MAX_ANOMALY_HISTORY = 256
    SNAPSHOT_MIN_INTERVAL_S = 5.0

    def __init__(self, objectives=(), enabled=True, fast_window_s=300.0,
                 slow_window_s=3600.0, burn_threshold=1.0,
                 eval_interval_s=10.0, snapshot_path=None, registry=None,
                 ledger=None, job_name="", on_escalate=None,
                 on_anomaly=None, log_fn=None, now_us=None):
        self.enabled = bool(enabled)
        if not self.enabled:
            return
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.burn_threshold = float(burn_threshold)
        self.eval_interval_s = float(eval_interval_s)
        self.snapshot_path = snapshot_path
        self.registry = registry
        self.ledger = ledger
        self.job_name = job_name
        self.on_escalate = on_escalate
        self.on_anomaly = on_anomaly
        self._log = log_fn or logger.warning
        self._now_us = now_us or _clk.monotonic_us
        self._lock = threading.Lock()
        self._closed = False
        self.evals = 0
        self.rule_counts = {}
        self.anomalies = []
        self._last_eval_us = None
        self._last_snapshot_s = None
        # enough cumulative samples to anchor the slow window at eval
        # cadence, bounded so a test-tiny interval can't grow unbounded
        depth = min(65536, max(16, int(
            self.slow_window_s / max(self.eval_interval_s, 1e-3)) + 8))
        self.objectives = []
        self.serving_defaults = ()   # from_config fills from tcfg knobs
        self._samples = {}           # name -> deque[(t_us, bad, total)]
        self._state = {}             # name -> last evaluation dict
        for obj in objectives:
            self.add_objective(obj, _depth=depth)

    @classmethod
    def from_config(cls, tcfg, output_path="telemetry/", job_name="",
                    registry=None, ledger=None, on_escalate=None):
        """Build from a parsed :class:`DeepSpeedTelemetryConfig`
        (``telemetry.slo`` block). With no explicit objectives, a
        training-goodput objective is armed when the ledger is; the
        ServingEngine adds the serving latency objectives when it arms.
        The snapshot lands under the telemetry output dir unless the
        configured name is absolute (never a bare CWD default — the
        committed-artifact clobber lesson)."""
        snap = tcfg.slo_snapshot_file or "SLO_REPORT.json"
        if not os.path.isabs(snap):
            snap = os.path.join(output_path or "telemetry/", snap)
        objectives = [normalize_objective(o) for o in tcfg.slo_objectives]
        if not objectives and ledger is not None and ledger.enabled:
            objectives = [{"name": "training_goodput", "kind": "goodput",
                           "target": tcfg.slo_goodput_target}]
        mon = cls(objectives=objectives,
                  fast_window_s=tcfg.slo_fast_window_s,
                  slow_window_s=tcfg.slo_slow_window_s,
                  burn_threshold=tcfg.slo_burn_threshold,
                  eval_interval_s=tcfg.slo_eval_interval_s,
                  snapshot_path=snap, registry=registry, ledger=ledger,
                  job_name=job_name, on_escalate=on_escalate)
        # the ServingEngine arms these via add_objective() when it comes
        # up — it holds no telemetry config, so the knobs ride here
        mon.serving_defaults = (
            {"name": "serving_ttft", "kind": "latency",
             "metric": "serving_ttft_ms",
             "threshold_ms": tcfg.slo_ttft_threshold_ms,
             "target": tcfg.slo_ttft_target},
            {"name": "serving_e2e", "kind": "latency",
             "metric": "serving_e2e_latency_ms",
             "threshold_ms": tcfg.slo_e2e_threshold_ms,
             "target": tcfg.slo_e2e_target},
        )
        return mon

    # -------------------------------------------------------- objectives
    def add_objective(self, obj, _depth=None):
        """Arm one more objective (the ServingEngine's path for the
        ttft/e2e latency objectives). Duplicate names replace."""
        obj = normalize_objective(obj)
        if _depth is None:
            _depth = min(65536, max(16, int(
                self.slow_window_s / max(self.eval_interval_s, 1e-3)) + 8))
        with self._lock:
            self.objectives = [o for o in self.objectives
                               if o["name"] != obj["name"]] + [obj]
            self._samples[obj["name"]] = deque(maxlen=_depth)
            self._state[obj["name"]] = {"tier": "ok"}
        return obj

    # ---------------------------------------------------------- sampling
    def _sample(self, obj):
        """Cumulative ``(bad, total)`` for one objective, or None while
        its source is not armed. Host-side only."""
        if obj["kind"] == "goodput":
            led = self.ledger
            if led is None or not led.enabled:
                return None
            elapsed = led.elapsed()
            totals = led.totals()
            good = sum(totals.get(c, 0.0)
                       for c in _ledger.GOOD_CATEGORIES)
            return (max(0.0, elapsed - good), elapsed)
        if self.registry is None:
            return None
        fams = self.registry.collect().get(obj["metric"])
        if not fams:
            return None
        bad = total = 0
        eff = None
        for h in fams:
            if getattr(h, "kind", None) != "histogram":
                return None
            cum = h.cumulative_counts()
            # the effective threshold snaps to the smallest bucket edge
            # that covers the asked one (+Inf when none does)
            idx = next((i for i, b in enumerate(h.buckets)
                        if b >= obj["threshold_ms"]), len(h.buckets))
            if eff is None and idx < len(h.buckets):
                eff = float(h.buckets[idx])
            total += h.count
            bad += h.count - cum[idx]
        obj["effective_threshold_ms"] = eff
        return (bad, total)

    def _burn(self, dq, now_us, window_s):
        """Burn over one window from the cumulative sample deque."""
        window_us = int(window_s * 1e6)
        start = now_us - window_us
        newest = dq[-1]
        # the anchor is the last sample at/before the window start — the
        # delta then covers the whole window, not a ragged suffix
        anchor = dq[0]
        for s in dq:
            if s[0] <= start:
                anchor = s
            else:
                break
        span_us = newest[0] - anchor[0]
        d_bad = newest[1] - anchor[1]
        d_total = newest[2] - anchor[2]
        eligible = (span_us >= MIN_SPAN_FRAC * window_us and d_total > 0)
        # cumulative bad can DIP between samples (goodput attribution
        # catches up asynchronously with elapsed), so the delta is
        # clamped — a negative burn rate is meaningless
        bad_frac = (max(0, d_bad) / d_total) if d_total > 0 else None
        return {
            "window_s": window_s,
            "window_us": window_us,
            "t_newest_us": newest[0],
            "t_anchor_us": anchor[0],
            "span_us": span_us,
            "samples": len(dq),
            "delta_bad": d_bad,
            "delta_total": d_total,
            "bad_frac": bad_frac,
            "eligible": eligible,
        }

    # -------------------------------------------------------------- tick
    def tick(self, step=None, force=False):
        """Evaluate every objective; escalate tier *transitions* (a
        sustained burn pages once, not every eval). Self-throttled."""
        if not self.enabled or self._closed:
            return
        now = self._now_us()
        if not force and self._last_eval_us is not None and \
                now - self._last_eval_us < self.eval_interval_s * 1e6:
            return
        self._last_eval_us = now
        anoms = []
        with self._lock:
            objectives = list(self.objectives)
        for obj in objectives:
            name = obj["name"]
            sample = self._sample(obj)
            if sample is None:
                self._state[name] = {"tier": "ok", "active": False}
                continue
            dq = self._samples[name]
            dq.append((now, sample[0], sample[1]))
            budget = 1.0 - obj["target"]
            windows = {}
            for wname, w_s in (("fast", self.fast_window_s),
                               ("slow", self.slow_window_s)):
                w = self._burn(dq, now, w_s)
                burn = (w["bad_frac"] / budget
                        if w["bad_frac"] is not None else None)
                w["burn"] = burn
                w["burning"] = bool(w["eligible"] and burn is not None
                                    and burn >= self.burn_threshold)
                windows[wname] = w
                if self.registry is not None:
                    self.registry.gauge(
                        "slo_burn_rate",
                        "error-budget burn rate (1.0 = spending exactly "
                        "the budget)",
                        labels={"objective": name, "window": wname}).set(
                            burn if burn is not None else 0.0)
                    if w["burning"]:
                        self.registry.counter(
                            "slo_burn_total",
                            "evaluations where a window burned over "
                            "threshold",
                            labels={"objective": name,
                                    "window": wname}).inc()
            tier = ("page" if windows["fast"]["burning"]
                    and windows["slow"]["burning"]
                    else "fast" if windows["fast"]["burning"] else "ok")
            prev = self._state.get(name, {}).get("tier", "ok")
            st = {"tier": tier, "active": True, "windows": windows,
                  "totals": {"bad": sample[0], "total": sample[1]}}
            st["pages"] = self._state.get(name, {}).get("pages", 0)
            st["warns"] = self._state.get(name, {}).get("warns", 0)
            rank = {"ok": 0, "fast": 1, "page": 2}
            if rank[tier] > rank[prev]:       # escalate on the edge only
                bf = windows["fast"]["burn"]
                bs = windows["slow"]["burn"]
                if tier == "page":
                    st["pages"] += 1
                    anoms.append({
                        "rule": RULE_PAGE, "severity": "critical",
                        "step": step, "objective": name, "t_us": now,
                        "burn_fast": bf, "burn_slow": bs,
                        "detail": f"SLO {name!r} burning fast+slow "
                                  f"windows: {bf:.2f}x / "
                                  f"{bs:.2f}x of error budget "
                                  f"(target {obj['target']:g})"})
                else:
                    st["warns"] += 1
                    anoms.append({
                        "rule": RULE_FAST, "severity": "warning",
                        "step": step, "objective": name, "t_us": now,
                        "burn_fast": bf, "burn_slow": bs,
                        "detail": f"SLO {name!r} burning the fast "
                                  f"window at {bf:.2f}x of error "
                                  f"budget (target {obj['target']:g})"})
            self._state[name] = st
        self.evals += 1
        if anoms:
            self._escalate(anoms, step)

    def last_eval_age_s(self):
        """Seconds since the last evaluation (the obs server's /healthz
        last-tick age probe); None before the first tick."""
        if not self.enabled or self._last_eval_us is None:
            return None
        return round((self._now_us() - self._last_eval_us) / 1e6, 3)

    def _escalate(self, anoms, step):
        _escalation.escalate(
            self, anoms, tag="slo", counter="slo_anomalies_total",
            counter_help="slo burn-rate anomaly firings", step=step)

    # ------------------------------------------------------------ output
    def report(self):
        if not self.enabled:
            return {"schema": SLO_SCHEMA, "enabled": False}
        with self._lock:
            objectives = list(self.objectives)
            state = {k: dict(v) for k, v in self._state.items()}
        objs = {}
        for obj in objectives:
            st = state.get(obj["name"], {"tier": "ok", "active": False})
            entry = {"kind": obj["kind"], "target": obj["target"],
                     "error_budget": round(1.0 - obj["target"], 10)}
            for k in ("metric", "threshold_ms", "effective_threshold_ms"):
                if k in obj:
                    entry[k] = obj[k]
            entry.update(st)
            objs[obj["name"]] = entry
        return {
            "schema": SLO_SCHEMA,
            "enabled": True,
            "closed": self._closed,
            "job_name": self.job_name,
            "clock": "monotonic_us",
            "params": {
                "fast_window_s": self.fast_window_s,
                "slow_window_s": self.slow_window_s,
                "burn_threshold": self.burn_threshold,
                "eval_interval_s": self.eval_interval_s,
                "min_span_frac": MIN_SPAN_FRAC,
            },
            "evals": self.evals,
            "objectives": objs,
            "rule_counts": dict(self.rule_counts),
            "anomalies": list(self.anomalies),
        }

    def write_snapshot(self, path=None, force=False, report=None):
        """Throttled JSON snapshot (the monitors' shared discipline);
        forced on first firings by the escalation protocol."""
        if not self.enabled:
            return None
        path = path or self.snapshot_path
        if path is None:
            return None
        now_s = _clk.monotonic_s()
        if not force and self._last_snapshot_s is not None and \
                now_s - self._last_snapshot_s < self.SNAPSHOT_MIN_INTERVAL_S:
            return None
        self._last_snapshot_s = now_s
        doc = report if report is not None else self.report()
        try:
            _chronicle._atomic_write_bytes(
                path, json.dumps(doc, indent=1, default=repr,
                                 allow_nan=False).encode())
        except OSError as e:    # forensics must never kill a step
            self._log("[slo] snapshot write failed: %s", e)
            return None
        return path

    def close(self):
        """Final snapshot when there is something to explain. Idempotent;
        ``report()`` keeps working after."""
        if not self.enabled or self._closed:
            return
        self._closed = True
        if self.evals and (self.rule_counts or self.anomalies):
            self.write_snapshot(force=True)


# --------------------------------------------------------------------- CLI

def render(report):
    """Human-readable rendering of an SLO_REPORT.json dict."""
    if not report.get("enabled", True):
        return "slo: disabled"
    lines = [f"slo: {len(report.get('objectives', {}))} objective(s), "
             f"{report.get('evals', 0)} eval(s)"]
    for name, o in sorted(report.get("objectives", {}).items()):
        tier = o.get("tier", "ok")
        lines.append(f"  {name} [{o.get('kind')}] target "
                     f"{o.get('target'):g} -> {tier.upper()}")
        for wname in WINDOWS:
            w = (o.get("windows") or {}).get(wname)
            if not w:
                continue
            burn = w.get("burn")
            lines.append(
                f"    {wname:>4} {w['window_s']:g}s: burn "
                f"{'-' if burn is None else f'{burn:.2f}x'}"
                f"{' BURNING' if w.get('burning') else ''} "
                f"({w['samples']} sample(s) over "
                f"{w['span_us'] / 1e6:.1f}s)")
    for a in report.get("anomalies", []):
        lines.append(f"  {a.get('severity')}: {a.get('detail')}")
    return "\n".join(lines)


def _demo(args):
    """The committed-artifact scenario: a serving TTFT objective against
    demo-shrunk windows; healthy traffic first, then an injected
    degradation pushes most requests over the threshold — the fast
    window burns (warn), then the slow window joins (page), the live
    guardian pauses admission, and the incident correlator joins the
    anomaly -> action chain naming the objective. Host-only: the
    histogram is fed synthetic latencies; no engine, no device."""
    import tempfile
    import time as _time

    from deepspeed_tpu.runtime.guardian import Guardian
    from deepspeed_tpu.telemetry import incidents as _incidents
    from deepspeed_tpu.telemetry.metrics import MetricsRegistry

    registry = MetricsRegistry()
    run_dir = tempfile.mkdtemp(prefix="slo_demo_chronicle_")
    chron = _chronicle.RunChronicle(run_dir=run_dir, rank=0,
                                    job_name="slo_demo")
    old_chron = _chronicle.set_chronicle(chron)
    guardian = Guardian(job_name="slo_demo", journal_path=None,
                        action_cooldown_steps=1, registry=registry)
    pauses = []
    guardian.pause_fn = pauses.append
    slo = SloMonitor(
        objectives=[{"name": "serving_ttft", "kind": "latency",
                     "metric": "serving_ttft_ms", "threshold_ms": 100.0,
                     "target": 0.95}],
        fast_window_s=args.fast_window, slow_window_s=args.slow_window,
        burn_threshold=1.0, eval_interval_s=args.fast_window / 10.0,
        snapshot_path=os.path.abspath(args.out), registry=registry,
        job_name="slo_demo")
    slo.on_anomaly = guardian.hook("slo")
    hist = registry.histogram("serving_ttft_ms",
                              "submit -> first generated token")
    step = 0
    deadline = _clk.monotonic_s() + 2.0 * args.slow_window
    # phase 1 — healthy: every TTFT lands under the threshold until the
    # slow window is spanned and provably NOT burning
    while _clk.monotonic_s() < deadline:
        hist.observe(40.0)
        step += 1
        slo.tick(step=step, force=True)
        guardian.serving_tick(step)
        st = slo._state.get("serving_ttft", {})
        w = (st.get("windows") or {}).get("slow", {})
        if w.get("eligible"):
            break
        _time.sleep(args.fast_window / 20.0)
    healthy_evals = slo.evals
    # phase 2 — injected degradation: ~90% of first tokens now land
    # over the threshold (against a 95% target = 18x burn), until both
    # windows burn and the guardian pages
    deadline = _clk.monotonic_s() + 4.0 * args.slow_window
    while _clk.monotonic_s() < deadline:
        for _ in range(9):
            hist.observe(900.0)
        hist.observe(40.0)
        step += 1
        slo.tick(step=step, force=True)
        guardian.serving_tick(step)
        if guardian.admission_paused:
            break
        _time.sleep(args.fast_window / 20.0)
    chron.drain()
    report = slo.report()
    report["demo"] = {
        "healthy_evals": healthy_evals,
        "degraded_evals": slo.evals - healthy_evals,
        "observations": hist.count,
        "guardian_received": sorted(guardian.rules_seen),
        "admission_paused": guardian.admission_paused,
        "pause_rules_fired": [str(r) for r in pauses],
        "guardian_actions": list(guardian.actions),
    }
    report["incidents"] = _incidents.correlate(
        chron.snapshot_events(), job_name="slo_demo")
    slo.write_snapshot(force=True, report=report)
    chron.close()
    _chronicle.set_chronicle(old_chron)
    print(render(report))
    inc = report["incidents"]["incidents"]
    print(f"\nguardian: admission_paused={guardian.admission_paused}, "
          f"{len(guardian.actions)} action(s); {len(inc)} incident(s)")
    print(f"wrote {args.out}")
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="SLO burn-rate monitor demo/reporting CLI")
    ap.add_argument("--demo", action="store_true",
                    help="run the TTFT-degradation burn demo and write "
                         "the committed SLO_REPORT.json")
    ap.add_argument("--render", metavar="PATH",
                    help="render an existing SLO_REPORT.json")
    ap.add_argument("--out", default="SLO_REPORT.json")
    ap.add_argument("--fast-window", type=float, default=0.5,
                    help="demo fast window seconds (prod default 300)")
    ap.add_argument("--slow-window", type=float, default=2.0,
                    help="demo slow window seconds (prod default 3600)")
    args = ap.parse_args(argv)
    if args.demo:
        return _demo(args)
    if args.render:
        with open(args.render) as f:
            print(render(json.load(f)))
        return 0
    ap.error("one of --demo / --render is required")


if __name__ == "__main__":
    raise SystemExit(main())
