"""HBM residency observatory — buffer-level device-memory attribution.

The cost explorer's ``memory_analysis`` watermark (PR 2) is a
compile-time *prediction*; this module is the measured side: the live
pprof profile ``jax.profiler.device_memory_profile()`` emits (decoded by
the dependency-free ``pprof.py`` reader) joined to engine-owned state,
so every live byte lands in exactly one of five categories —

    params | optimizer_state | kv_pool | activations_workspace | other

— with params/optimizer_state further bucketed through the PR-3
``build_bucket_spec`` module names. The attribution is EXACT by
construction (the goodput/anatomy invariant discipline): known
categories are attributed ``min(expected, remaining)`` in priority
order, the workspace category is the remainder, so per-category bytes
re-add to the profile's live total with integer arithmetic — any
engine-vs-profile mismatch surfaces as an explicit ``shortfall_bytes``,
never as silent drift.

On top sits :class:`MemoryMonitor`, a windowed monitor with the
established warn-once -> throttled ``MEMORY_HEALTH.json`` ->
on_anomaly-hook escalation and four rules:

* ``hbm_leak`` — live bytes grew strictly monotonically across
  ``leak_windows`` consecutive post-warmup windows;
* ``watermark_drift`` — measured peak vs the pre-flight prediction
  beyond ``drift_threshold`` in EITHER direction (an over-prediction
  wastes autotuner headroom, an under-prediction hides OOM risk);
* ``kv_fragmentation`` — the serving allocator's fragmentation (the
  SAME numbers ``serving_report()`` books) above ``frag_threshold``;
* ``oom_risk`` — live bytes crossing ``headroom x budget`` (critical).
  The budget is a real HBM limit only: host-RSS fallbacks are refused
  (warn-once) — process RSS is not an HBM budget.

The module is pure host-side bookkeeping: no jax import outside the CLI
demo (``tests/perf/telemetry_overhead.py`` pins this statically), so it
cannot add device syncs; the profile fetch
(``pprof.fetch_device_memory_profile``) happens on the engine/serving
tick at cadence only. ``python -m deepspeed_tpu.telemetry
.memory_observatory --demo`` regenerates the committed repo-root
``MEMORY_ANATOMY.json`` example; ``--render`` pretty-prints one.
"""

import json
import os
import time
from collections import deque

from deepspeed_tpu.telemetry import escalation
from deepspeed_tpu.telemetry import pprof
from deepspeed_tpu.telemetry.health import json_safe
from deepspeed_tpu.utils.logging import logger

MEMORY_SCHEMA = "deepspeed_tpu.memory_anatomy/1"

# category attribution order: specific, engine-known pools first; the
# workspace remainder is computed, never estimated
CATEGORIES = ("params", "optimizer_state", "kv_pool",
              "activations_workspace", "other")

RULE_SEVERITY = {
    "oom_risk": "critical",
    "hbm_leak": "warning",
    "watermark_drift": "warning",
    "kv_fragmentation": "warning",
}
_SEVERITY_ORDER = ("critical", "warning", "watch")


# ---------------------------------------------------------------------------
# exact-sum attribution
# ---------------------------------------------------------------------------

def attribute_live_bytes(live_total_bytes, inventory, executable_bytes=0):
    """Attribute a profile's live total across the five categories.

    ``inventory`` holds the engine-expected byte counts for the pools
    the engine owns ({params, optimizer_state, kv_pool}); compiled
    programs (``executable_bytes``) land in ``other``. Each known
    category is granted ``min(expected, remaining)`` in declaration
    order and ``activations_workspace`` takes the remainder — so the
    category bytes sum EXACTLY to ``live_total_bytes`` by construction,
    and any capping (profile smaller than the engine's own accounting,
    e.g. a donated buffer the allocator already released) is recorded as
    that category's ``shortfall_bytes`` instead of corrupting the sum.
    """
    live_total_bytes = max(0, int(live_total_bytes))
    remaining = live_total_bytes
    cats = {}
    expected = {
        "params": int(inventory.get("params", 0) or 0),
        "optimizer_state": int(inventory.get("optimizer_state", 0) or 0),
        "kv_pool": int(inventory.get("kv_pool", 0) or 0),
        "other": int(executable_bytes or 0),
    }
    for name in ("params", "optimizer_state", "kv_pool", "other"):
        want = max(0, expected[name])
        got = min(want, remaining)
        remaining -= got
        cats[name] = {"bytes": got, "expected_bytes": want,
                      "shortfall_bytes": want - got}
    cats["activations_workspace"] = {
        "bytes": remaining, "expected_bytes": None, "shortfall_bytes": 0}
    # re-order to the canonical tuple for stable artifacts
    ordered = {name: cats[name] for name in CATEGORIES}
    assert sum(c["bytes"] for c in ordered.values()) == live_total_bytes
    return {"live_total_bytes": live_total_bytes, "categories": ordered}


def attribute_buckets(total_bytes, bucket_bytes):
    """Distribute a category's attributed bytes across its module
    buckets with the same min-cap walk, so the bucket values sum EXACTLY
    to ``total_bytes``. ``bucket_bytes`` is an ordered {name: expected}
    mapping (PR-3 bucket names, leaf nbytes pre-summed per bucket); any
    surplus the buckets cannot explain lands in ``(other)``."""
    total_bytes = max(0, int(total_bytes))
    remaining = total_bytes
    out = {}
    for name, want in bucket_bytes.items():
        got = min(max(0, int(want or 0)), remaining)
        remaining -= got
        out[name] = got
    if remaining:
        out["(other)"] = out.get("(other)", 0) + remaining
    assert sum(out.values()) == total_bytes
    return out


def profile_sample(data):
    """Decode raw ``device_memory_profile`` bytes into the host-side
    numbers one monitor window needs: live totals split by sample kind,
    the buffer count, and the top samples for forensics."""
    prof = pprof.parse_profile(data)
    kinds = pprof.live_bytes_by_kind(prof)
    buffer_bytes = int(kinds.get("buffer", 0))
    executable_bytes = int(sum(v for k, v in kinds.items()
                               if k != "buffer"))
    ci = prof.value_index("count")
    buffer_count = 0
    if ci is not None:
        for s in prof.samples:
            if ci < len(s.values) and \
                    prof.sample_labels(s).get("kind") == "buffer":
                buffer_count += s.values[ci]
    return {
        "live_total_bytes": buffer_bytes + executable_bytes,
        "buffer_bytes": buffer_bytes,
        "executable_bytes": executable_bytes,
        "buffer_count": buffer_count,
        "top_samples": pprof.summarize_samples(prof, 8),
        "source": "jax.profiler.device_memory_profile",
    }


# ---------------------------------------------------------------------------
# the windowed monitor
# ---------------------------------------------------------------------------

class MemoryMonitor:
    """Windowed device-memory residency monitor.

    One input, one cadence: :meth:`observe` — a sample dict built by the
    engine/serving tick (profile totals + engine inventory + optional
    KV-pool numbers). Everything here is host arithmetic; the device was
    touched exactly once, at the cadence fetch.

    Escalation on a firing rule mirrors ``HealthMonitor``: one warning
    log per rule (later firings only counted), a throttled
    ``MEMORY_HEALTH.json`` snapshot, the ``on_escalate`` /
    ``on_anomaly`` hooks, and a ``memory_anomalies_total{rule=...}``
    counter. Level-triggered rules (drift / fragmentation / oom) carry
    hysteresis: they fire on crossing and re-arm only after the signal
    drops back under its threshold, so a persistently-drifted run
    produces one anomaly, not one per window.
    """

    SNAPSHOT_MIN_INTERVAL_S = 5.0
    MAX_ANOMALY_HISTORY = 100

    def __init__(self, job_name="", snapshot_path="MEMORY_HEALTH.json",
                 report_path="MEMORY_ANATOMY.json", leak_windows=4,
                 warmup_windows=2, drift_threshold=0.25,
                 frag_threshold=0.5, headroom=0.92, budget_bytes=None,
                 ring_size=64, registry=None, on_escalate=None,
                 on_anomaly=None, log_fn=None):
        self.job_name = job_name
        self.snapshot_path = snapshot_path
        self.report_path = report_path
        self.leak_windows = max(2, int(leak_windows))
        self.warmup_windows = max(0, int(warmup_windows))
        self.drift_threshold = float(drift_threshold)
        self.frag_threshold = float(frag_threshold)
        self.headroom = float(headroom)
        self.budget_bytes = int(budget_bytes) if budget_bytes else None
        self.budget_source = "config" if budget_bytes else None
        self.registry = registry
        self.on_escalate = on_escalate
        self.on_anomaly = on_anomaly
        self._log = log_fn or logger.warning

        self.predicted_bytes = None
        self.prediction_source = None
        self.prediction_detail = None
        self.measured_peak_bytes = 0
        self.peak_step = -1
        self.windows_seen = 0
        self.anomalies = []          # bounded history, most recent last
        self.rule_counts = {}        # rule -> total firings
        self.ring = deque(maxlen=int(ring_size))
        self._live_history = deque(maxlen=self.leak_windows + 1)
        self.last_sample = None
        self.last_attribution = None
        self.last_buckets = None
        self.last_step = -1
        self._leak_active = False
        self._drift_active = False
        self._frag_active = False
        self._oom_active = False
        self._host_budget_refused = False
        self._snapshots_written = 0
        self._last_snapshot_t = float("-inf")

    @classmethod
    def from_config(cls, tconfig, output_path="telemetry/", job_name="",
                    registry=None, on_escalate=None, on_anomaly=None):
        """Build from a parsed ``DeepSpeedTelemetryConfig``'s
        ``memory_*`` fields (the engine fills the prediction and the
        HBM budget after its step programs / census exist)."""
        snap = getattr(tconfig, "memory_snapshot_file", "") or \
            "MEMORY_HEALTH.json"
        if not os.path.isabs(snap):
            snap = os.path.join(output_path or ".", snap)
        rep = getattr(tconfig, "memory_report_file", "") or \
            "MEMORY_ANATOMY.json"
        if not os.path.isabs(rep):
            rep = os.path.join(output_path or ".", rep)
        return cls(
            job_name=job_name,
            snapshot_path=snap,
            report_path=rep,
            leak_windows=getattr(tconfig, "memory_leak_windows", 4),
            warmup_windows=getattr(tconfig, "memory_warmup_windows", 2),
            drift_threshold=getattr(tconfig, "memory_drift_threshold",
                                    0.25),
            frag_threshold=getattr(tconfig, "memory_frag_threshold", 0.5),
            headroom=getattr(tconfig, "memory_headroom", 0.92),
            budget_bytes=getattr(tconfig, "memory_budget_bytes", 0) or None,
            ring_size=getattr(tconfig, "memory_ring_size", 64),
            registry=registry, on_escalate=on_escalate,
            on_anomaly=on_anomaly)

    # ------------------------------------------------------------- wiring
    def set_prediction(self, predicted_bytes, source="", detail=None):
        """Install the PR-2 pre-flight watermark the drift rule measures
        against (total bytes across the devices the profile covers)."""
        if predicted_bytes and predicted_bytes > 0:
            self.predicted_bytes = int(predicted_bytes)
            self.prediction_source = source or None
            self.prediction_detail = detail

    def set_budget(self, budget_bytes, source=""):
        """Install the HBM budget the oom_risk rule guards. Host-RSS
        derived numbers must never reach here — call
        :meth:`refuse_host_budget` instead so the refusal is recorded."""
        if budget_bytes and budget_bytes > 0:
            self.budget_bytes = int(budget_bytes)
            self.budget_source = source or None

    def refuse_host_budget(self, source="host_rss"):
        """Record (warn-once) that budget detection only found host-RSS
        numbers: process RSS is not an HBM limit, so oom_risk stays
        disarmed rather than firing on a meaningless threshold."""
        if not self._host_budget_refused:
            self._host_budget_refused = True
            self._log("[memory] device-memory budget detection found only "
                      "%s — refusing to treat host RSS as an HBM budget; "
                      "oom_risk stays disarmed (set telemetry.memory."
                      "budget_bytes to arm it explicitly)", source)

    # ------------------------------------------------------------ cadence
    def observe(self, sample):
        """Evaluate the rules on one cadence sample. ``sample`` is a
        plain dict of host numbers: the ``profile_sample`` totals plus
        ``step``, ``inventory`` ({params, optimizer_state, kv_pool}
        expected bytes), optional ``param_buckets`` / ``opt_buckets``
        (ordered {bucket: bytes}) and optional ``kv``
        ({pool_bytes, free_blocks, usable_blocks, fragmentation}).
        Returns the list of anomalies that fired on THIS sample."""
        step = int(sample.get("step", -1))
        live = int(sample.get("live_total_bytes", 0))
        att = attribute_live_bytes(
            live, sample.get("inventory") or {},
            executable_bytes=sample.get("executable_bytes", 0))
        buckets = {
            "params": attribute_buckets(
                att["categories"]["params"]["bytes"],
                sample.get("param_buckets") or {}),
            "optimizer_state": attribute_buckets(
                att["categories"]["optimizer_state"]["bytes"],
                sample.get("opt_buckets") or {}),
        }
        warmed = self.windows_seen >= self.warmup_windows
        anoms = []

        if live > self.measured_peak_bytes:
            self.measured_peak_bytes = live
            self.peak_step = step
        self._live_history.append(live)

        # hbm_leak: strict monotone growth across the whole window ring
        if warmed and len(self._live_history) == self._live_history.maxlen:
            hist = list(self._live_history)
            growing = all(b > a for a, b in zip(hist, hist[1:]))
            if growing and not self._leak_active:
                self._leak_active = True
                anoms.append({
                    "rule": "hbm_leak", "step": step,
                    "severity": RULE_SEVERITY["hbm_leak"],
                    "detail": f"live bytes grew monotonically across the "
                              f"last {self.leak_windows} windows: "
                              f"{hist[0]} -> {hist[-1]} "
                              f"(+{hist[-1] - hist[0]} B)",
                    "history": hist})
            elif not growing:
                self._leak_active = False

        # watermark_drift: measured peak vs the pre-flight, BOTH ways
        drift = self.drift()
        if warmed and drift is not None:
            if abs(drift) > self.drift_threshold and not self._drift_active:
                self._drift_active = True
                direction = "above" if drift > 0 else "below"
                anoms.append({
                    "rule": "watermark_drift", "step": step,
                    "severity": RULE_SEVERITY["watermark_drift"],
                    "detail": f"measured peak {self.measured_peak_bytes} B "
                              f"is {abs(drift):.0%} {direction} the "
                              f"pre-flight prediction "
                              f"{self.predicted_bytes} B "
                              f"({self.prediction_source})",
                    "drift": round(drift, 4)})
            elif abs(drift) <= self.drift_threshold:
                self._drift_active = False

        # kv_fragmentation: the allocator's own numbers, unmodified
        kv = sample.get("kv")
        if warmed and kv and kv.get("pool_bytes"):
            frag = float(kv.get("fragmentation") or 0.0)
            if frag > self.frag_threshold and not self._frag_active:
                self._frag_active = True
                anoms.append({
                    "rule": "kv_fragmentation", "step": step,
                    "severity": RULE_SEVERITY["kv_fragmentation"],
                    "detail": f"KV pool fragmentation {frag:.0%} exceeds "
                              f"{self.frag_threshold:.0%} "
                              f"({kv.get('free_blocks')} free of "
                              f"{kv.get('usable_blocks')} usable blocks, "
                              f"pool {kv['pool_bytes']} B)",
                    "fragmentation": round(frag, 4)})
            elif frag <= self.frag_threshold:
                self._frag_active = False

        # oom_risk: critical, never warmed up — headroom exists exactly
        # so the alarm beats the allocator to the cliff
        if self.budget_bytes:
            limit = self.headroom * self.budget_bytes
            if live > limit and not self._oom_active:
                self._oom_active = True
                anoms.append({
                    "rule": "oom_risk", "step": step,
                    "severity": RULE_SEVERITY["oom_risk"],
                    "detail": f"live bytes {live} crossed "
                              f"{self.headroom:.0%} of the "
                              f"{self.budget_bytes} B HBM budget "
                              f"({self.budget_source})",
                    "live_bytes": live, "limit_bytes": int(limit)})
            elif live <= limit:
                self._oom_active = False

        self.windows_seen += 1
        self.last_sample = sample
        self.last_attribution = att
        self.last_buckets = buckets
        self.last_step = step
        self.ring.append({"step": step, "live_total_bytes": live,
                          "buffer_count": sample.get("buffer_count")})
        if anoms:
            self._escalate(anoms)
        return anoms

    def drift(self):
        """Measured-peak vs predicted watermark, or None while either
        side is missing."""
        if not self.predicted_bytes or not self.measured_peak_bytes:
            return None
        return self.measured_peak_bytes / self.predicted_bytes - 1.0

    # ---------------------------------------------------------- escalation
    def _escalate(self, anoms):
        # the shared protocol (telemetry/escalation.py)
        escalation.escalate(self, anoms, tag="memory",
                            counter="memory_anomalies_total",
                            counter_help="device-memory anomaly rule "
                                         "firings")

    # ------------------------------------------------------------- outputs
    def verdict(self):
        if not self.windows_seen:
            return "unknown"
        seen = {RULE_SEVERITY.get(r, "warning") for r in self.rule_counts}
        for tier in _SEVERITY_ORDER:
            if tier in seen:
                return tier
        return "healthy"

    def report(self):
        """The full residency dict (what ``MEMORY_ANATOMY.json`` and the
        escalation snapshot both hold)."""
        drift = self.drift()
        sample = self.last_sample or {}
        return {
            "schema": MEMORY_SCHEMA,
            "enabled": True,
            "job_name": self.job_name,
            "verdict": self.verdict(),
            "source": sample.get("source"),
            "step": self.last_step,
            "live_total_bytes": (self.last_attribution or {}).get(
                "live_total_bytes", 0),
            "buffer_count": sample.get("buffer_count"),
            "categories": (self.last_attribution or {}).get(
                "categories", {}),
            "buckets": self.last_buckets or {},
            "watermark": {
                "predicted_bytes": self.predicted_bytes,
                "prediction_source": self.prediction_source,
                "prediction_detail": self.prediction_detail,
                "measured_peak_bytes": self.measured_peak_bytes,
                "peak_step": self.peak_step,
                "drift": None if drift is None else round(drift, 4),
                "threshold": self.drift_threshold,
                "flagged": (drift is not None
                            and abs(drift) > self.drift_threshold),
            },
            "budget": {
                "bytes": self.budget_bytes,
                "source": self.budget_source,
                "headroom": self.headroom,
                "host_budget_refused": self._host_budget_refused,
            },
            "kv": sample.get("kv"),
            "rules": {
                "leak_windows": self.leak_windows,
                "warmup_windows": self.warmup_windows,
                "drift_threshold": self.drift_threshold,
                "frag_threshold": self.frag_threshold,
                "headroom": self.headroom,
            },
            "counters": {
                "windows_seen": self.windows_seen,
                "anomaly_counts": dict(self.rule_counts),
                "snapshots_written": self._snapshots_written,
            },
            "top_samples": sample.get("top_samples") or [],
            "anomalies": list(self.anomalies),
            "ring": list(self.ring),
        }

    def write_snapshot(self, path=None, force=False):
        """Write the throttled escalation snapshot (MEMORY_HEALTH.json).
        Re-serialising the report every anomaly during a leak spiral
        would stall the train thread, so repeats ride the throttle."""
        if not force and (time.monotonic() - self._last_snapshot_t
                          < self.SNAPSHOT_MIN_INTERVAL_S):
            return None
        self._last_snapshot_t = time.monotonic()
        path = path or self.snapshot_path
        self._write(path)
        self._snapshots_written += 1
        return path

    def write_report(self, path=None):
        """Write the residency report (MEMORY_ANATOMY.json) — the
        explicit ``memory_report(write=True)`` / CLI path, unthrottled."""
        path = path or self.report_path
        self._write(path)
        return path

    def _write(self, path):
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(json_safe(self.report()), f, indent=1, default=repr,
                      allow_nan=False)

    def close(self):
        """Final snapshot — only when there is something to explain."""
        if self.anomalies:
            self.write_snapshot(force=True)


# --------------------------------------------------------------------- CLI

def _fmt_bytes(n):
    if n is None:
        return "(n/a)"
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return (f"{n} {unit}" if unit == "B"
                    else f"{n:.2f} {unit}")
        n /= 1024.0
    return f"{n:.2f} GiB"  # pragma: no cover


def render(report):
    """Human-readable rendering of a MEMORY_ANATOMY.json dict."""
    lines = []
    if not report.get("enabled", True):
        return "memory observatory: disabled"
    total = report.get("live_total_bytes", 0)
    lines.append(f"memory verdict: {report.get('verdict', '?').upper()}"
                 f"  (job {report.get('job_name') or '-'}, step "
                 f"{report.get('step')}, live {_fmt_bytes(total)}, "
                 f"{report.get('buffer_count')} buffers)")
    for name in CATEGORIES:
        c = (report.get("categories") or {}).get(name)
        if c is None:
            continue
        frac = c["bytes"] / total if total else 0.0
        short = (f"  (shortfall {_fmt_bytes(c['shortfall_bytes'])})"
                 if c.get("shortfall_bytes") else "")
        lines.append(f"  {name:22s} {_fmt_bytes(c['bytes']):>12s} "
                     f"({frac:6.1%}){short}")
    for cat in ("params", "optimizer_state"):
        bks = (report.get("buckets") or {}).get(cat) or {}
        for bname, b in sorted(bks.items(), key=lambda kv: -kv[1])[:6]:
            if b:
                lines.append(f"    {cat[:6]} bucket {bname:26s} "
                             f"{_fmt_bytes(b):>12s}")
    wm = report.get("watermark") or {}
    if wm.get("predicted_bytes"):
        d = wm.get("drift")
        lines.append(
            f"  watermark: measured peak "
            f"{_fmt_bytes(wm.get('measured_peak_bytes'))} vs predicted "
            f"{_fmt_bytes(wm.get('predicted_bytes'))}"
            + (f", drift {d:+.1%}" if d is not None else "")
            + (" [FLAGGED]" if wm.get("flagged") else ""))
    bud = report.get("budget") or {}
    if bud.get("bytes"):
        lines.append(f"  budget: {_fmt_bytes(bud['bytes'])} "
                     f"({bud.get('source')}) x headroom "
                     f"{bud.get('headroom'):.0%}")
    kv = report.get("kv")
    if kv:
        lines.append(f"  kv pool: {_fmt_bytes(kv.get('pool_bytes'))}, "
                     f"{kv.get('free_blocks')} free / "
                     f"{kv.get('usable_blocks')} usable blocks, "
                     f"fragmentation {kv.get('fragmentation', 0):.1%}")
    for a in report.get("anomalies", []):
        lines.append(f"  [{a.get('severity', '?'):8s}] step "
                     f"{a.get('step')}: {a.get('rule')} — "
                     f"{a.get('detail')}")
    if not report.get("anomalies"):
        lines.append("  no anomalies recorded")
    for row in report.get("top_samples", [])[:4]:
        stack = " <- ".join(row.get("stack") or []) or "?"
        lines.append(f"  top {row['kind']:10s} "
                     f"{_fmt_bytes(row['bytes']):>12s}  {stack}")
    return "\n".join(lines)


def _demo(args):
    """Build a tiny engine with the observatory armed at cadence 1, run
    a few steps, and write the measured residency report — the committed
    repo-root MEMORY_ANATOMY.json example comes from here. On CPU jax
    the profile is real (TFRT CPU buffers), so the categories, buckets
    and the measured-vs-predicted drift are all measured numbers."""
    if args.devices:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.devices}")
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models.simple import SimpleModel, sample_batch
    from deepspeed_tpu.utils import groups

    groups.destroy()
    groups.initialize()
    hidden = 64
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=hidden, nlayers=4),
        config={
            "train_batch_size": 16,
            "steps_per_print": 1,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "telemetry": {"enabled": True, "trace": False,
                          "jsonl": False, "prometheus": False,
                          "cost_explorer": {"enabled": True},
                          "memory": {"enabled": True, "cadence": 1,
                                     "warmup_windows": 1}},
        },
        sample_batch=sample_batch(16, hidden))
    rng = np.random.default_rng(0)
    for _ in range(args.steps):
        x = rng.standard_normal((16, hidden)).astype(np.float32)
        y = rng.standard_normal((16, hidden)).astype(np.float32)
        engine.train_batch(batch=(x, y))
    report = engine.memory_report(write=False)
    mon = engine.telemetry.memory
    out = os.path.abspath(args.out)
    mon.write_report(out)
    print(render(report))
    print(f"\nwrote {out}")
    return 0


def main(argv=None):
    import argparse
    p = argparse.ArgumentParser(
        prog="python -m deepspeed_tpu.telemetry.memory_observatory",
        description="Render a MEMORY_ANATOMY.json report, or run the "
                    "residency demo (tiny engine, measured attribution "
                    "+ watermark drift)")
    p.add_argument("--render", metavar="MEMORY_ANATOMY.json",
                   help="pretty-print an existing report and exit")
    p.add_argument("--demo", action="store_true",
                   help="build a tiny engine with the observatory armed "
                        "and write the measured report")
    p.add_argument("--steps", type=int, default=8)
    p.add_argument("--devices", type=int, default=8,
                   help="virtual CPU devices for the demo (0 = existing)")
    p.add_argument("--out", default="MEMORY_ANATOMY.json")
    args = p.parse_args(argv)
    if args.render:
        with open(args.render) as f:
            print(render(json.load(f)))
        return 0
    if args.demo:
        return _demo(args)
    p.print_help()
    return 2


if __name__ == "__main__":
    import sys
    sys.exit(main())
