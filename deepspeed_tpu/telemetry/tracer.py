"""Span tracer — nested ``with trace_span("fwd")`` contexts.

Emits Chrome-trace/Perfetto-compatible "X" (complete) events
(``{"name", "ph", "ts", "dur", "pid", "tid", "args"}``, timestamps in
microseconds) and can forward each span to ``jax.profiler.TraceAnnotation``
so host-side phases line up with device traces in the XLA profiler UI.

The disabled path is the hot path: ``trace_span`` on a disabled tracer
returns one shared no-op context manager — no allocation, no clock read
(tests/perf/telemetry_overhead.py asserts < 2 µs/span). Enabled spans cost
two ``perf_counter_ns`` reads and one locked list append.
"""

import json
import os
import threading
import time


class _NullSpan:
    """Shared no-op context manager for the disabled tracer."""
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("_tracer", "name", "args", "_t0", "_ann")

    def __init__(self, tracer, name, args):
        self._tracer = tracer
        self.name = name
        self.args = args
        self._ann = None

    def __enter__(self):
        if self._tracer._annotate:
            try:
                from jax.profiler import TraceAnnotation
                self._ann = TraceAnnotation(self.name)
                self._ann.__enter__()
            except Exception:
                self._ann = None
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter_ns()
        if self._ann is not None:
            self._ann.__exit__(*exc)
        self._tracer._record(self.name, self._t0, t1, self.args)
        return False


class Tracer:
    """Collects spans into a bounded in-memory buffer; ``export`` writes
    the Chrome-trace JSON (loadable in chrome://tracing / Perfetto)."""

    def __init__(self, enabled=False, jax_annotations=False,
                 max_events=100_000):
        self.enabled = enabled
        self._annotate = jax_annotations
        self.max_events = max_events
        self.dropped = 0
        self._events = []
        self._lock = threading.Lock()
        self._pid = os.getpid()
        self._process_label = None
        self._process_sort = None

    def set_process_label(self, name, sort_index=None):
        """Rank-tag this process's trace: ``export`` will prepend
        ``process_name`` / ``process_sort_index`` metadata, so per-rank
        trace files carry their identity and concatenate cleanly into
        one per-rank-lane view (telemetry/fleet.py's ``merge_traces``)."""
        self._process_label = str(name)
        self._process_sort = sort_index

    def span(self, name, **args):
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, args or None)

    def _record(self, name, t0_ns, t1_ns, args):
        ev = {"name": name, "ph": "X", "ts": t0_ns // 1000,
              "dur": max(0, (t1_ns - t0_ns) // 1000),
              "pid": self._pid, "tid": threading.get_ident()}
        if args:
            ev["args"] = args
        with self._lock:
            if len(self._events) >= self.max_events:
                self.dropped += 1
                return
            self._events.append(ev)

    def emit(self, event):
        """Append a pre-built Chrome-trace event dict verbatim. The
        serving observatory synthesizes per-slot lane events with its
        own pid/tid (and "M" metadata naming the lanes) — those cannot
        go through span()/instant(), which stamp the CURRENT thread."""
        if not self.enabled:
            return
        with self._lock:
            if len(self._events) >= self.max_events:
                self.dropped += 1
                return
            self._events.append(event)

    def instant(self, name, **args):
        """Zero-duration marker event (ph="i")."""
        if not self.enabled:
            return
        ev = {"name": name, "ph": "i", "s": "t",
              "ts": time.perf_counter_ns() // 1000,
              "pid": self._pid, "tid": threading.get_ident()}
        if args:
            ev["args"] = args
        with self._lock:
            if len(self._events) >= self.max_events:
                self.dropped += 1
                return
            self._events.append(ev)

    def events(self):
        with self._lock:
            return list(self._events)

    def event_count(self):
        return len(self._events)   # len() is atomic; no copy needed

    def clear(self):
        with self._lock:
            self._events.clear()
        self.dropped = 0

    def export(self, path):
        """Write the Chrome-trace JSON object format; returns the path."""
        events = self.events()
        if self._process_label is not None:
            meta = [{"name": "process_name", "ph": "M", "pid": self._pid,
                     "args": {"name": self._process_label}}]
            if self._process_sort is not None:
                meta.append({"name": "process_sort_index", "ph": "M",
                             "pid": self._pid,
                             "args": {"sort_index":
                                      int(self._process_sort)}})
            events = meta + events
        doc = {"traceEvents": events, "displayTimeUnit": "ms"}
        if self.dropped:
            doc["metadata"] = {"dropped_events": self.dropped}
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)   # readers never see a half-written trace
        return path


# ---------------------------------------------------------------- lane tids
#
# Synthetic trace lanes (serving slots, fleet ranks, profiler device
# lanes) need tids that cannot collide with real thread idents or with
# each other — two subsystems both hard-coding "base + index" produced
# duplicate (pid, tid) pairs with conflicting thread_name metadata in
# merged traces. One process-scoped registry hands out a stable tid per
# lane key instead: the same key always maps to the same tid, distinct
# keys never share one.

_LANE_TID_BASE = 1_000_000
_LANE_LOCK = threading.Lock()
_LANE_TIDS = {}
_LANE_NEXT = [_LANE_TID_BASE]


def allocate_lane_tid(key):
    """Return the process-unique synthetic tid for lane *key* (any
    hashable; idempotent — repeated calls with the same key return the
    same tid)."""
    with _LANE_LOCK:
        tid = _LANE_TIDS.get(key)
        if tid is None:
            tid = _LANE_NEXT[0]
            _LANE_NEXT[0] += 1
            _LANE_TIDS[key] = tid
        return tid


def _reset_lane_tids():
    """Test hook: forget all lane-tid assignments."""
    with _LANE_LOCK:
        _LANE_TIDS.clear()
        _LANE_NEXT[0] = _LANE_TID_BASE


# Module-level default tracer: DISABLED until a TelemetryManager (or a
# test) installs an enabled one. Library code (engine, checkpoint_io)
# calls ``trace_span`` unconditionally; the cost without telemetry is one
# global lookup + a shared no-op context manager.
_GLOBAL = Tracer(enabled=False)


def get_tracer():
    return _GLOBAL


def set_tracer(tracer):
    """Install *tracer* as the process-global default; returns the old."""
    global _GLOBAL
    old, _GLOBAL = _GLOBAL, tracer
    return old


def trace_span(name, **args):
    return _GLOBAL.span(name, **args)
