"""XLA compile/retrace watch.

On TPU the dominant *silent* perf killer is retracing: a jitted entry
point fed a new shape/dtype/tree-structure quietly recompiles (seconds to
minutes) instead of erroring. ``CompileWatch.wrap`` instruments a callable
with per-call signature tracking:

* first signature -> counted as the expected compile;
* every NEW signature after that -> counted as a retrace and reported with
  a ONE-line culprit report naming the function and the argument path
  whose abstract value (shape/dtype) changed, e.g.::

    [compile-watch] retrace #1 of 'micro_step': arg batch['input_ids']
    aval changed int32[8,128] -> int32[8,256] (2 signatures seen)

Each distinct signature is reported exactly once — a steady alternation
between two shapes warns on first sight of each, then stays quiet (the
cache serves both programs; the *report* is about new compilations).

The fast path is one shape/dtype tuple build over the call's leaves
(~µs for step-sized trees); the with-path diff runs only when a new
signature is actually seen. ``install_global_listener`` additionally taps
``jax.monitoring`` so compiles triggered outside wrapped entry points
still move the ``xla_compiles_total`` counter.
"""

import functools

from deepspeed_tpu.telemetry import chronicle as _chronicle
from deepspeed_tpu.telemetry import ledger as _ledger
from deepspeed_tpu.telemetry import metrics as _metrics
from deepspeed_tpu.utils.logging import logger


def _leaf_sig(x):
    """Abstract-value descriptor for one call-argument leaf. The dtype
    stays an object (np.dtype hashes/compares fine) — stringifying it per
    leaf per call measurably taxes hot serving/step loops; ``_fmt`` does
    the prettification only when a retrace is actually reported."""
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        return ("aval", tuple(shape), dtype)
    # static leaf: identity by value when hashable, else by repr
    try:
        hash(x)
        return ("static", x)
    except TypeError:
        return ("static", repr(x))


def _fmt(sig):
    if sig[0] == "aval":
        _, shape, dtype = sig
        dtype = str(dtype)
        short = {"float32": "f32", "float16": "f16", "bfloat16": "bf16",
                 "int32": "i32", "int64": "i64", "uint32": "u32",
                 "int8": "i8", "uint8": "u8", "bool": "pred"}.get(dtype,
                                                                  dtype)
        return f"{short}[{','.join(str(d) for d in shape)}]"
    return f"static:{sig[1]!r}"


class CompileWatch:
    """Tracks compilations/retraces across any number of wrapped fns."""

    def __init__(self, registry=None, log_fn=None):
        self.registry = registry if registry is not None \
            else _metrics.get_registry()
        self.log_fn = log_fn or logger.warning
        self.compiles = 0
        self.retraces = 0
        self._per_fn = {}

    def wrap(self, fn, name=None):
        """Return *fn* instrumented with signature tracking. The original
        is kept on ``wrapped._compile_watch_target`` (AOT surfaces like
        ``.lower`` live on the jitted original, not the wrapper —
        ``__wrapped__`` won't do, jax.jit objects carry their own)."""
        import jax
        name = name or getattr(fn, "__name__", repr(fn))
        state = self._per_fn.setdefault(name, {"sigs": set(), "last": None})

        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            treedef = None
            try:
                leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
                sig = (treedef, tuple(_leaf_sig(x) for x in leaves))
            except Exception:
                sig = None
            if sig is not None and sig not in state["sigs"]:
                first = not state["sigs"]
                state["sigs"].add(sig)
                self.compiles += 1
                self.registry.counter(
                    "xla_compiles_total",
                    "compilations observed by wrapped jit entry points",
                    labels={"fn": name}).inc()
                if not first:
                    self.retraces += 1
                    self.registry.counter(
                        "xla_retraces_total",
                        "NEW signatures after the first (retraces)",
                        labels={"fn": name}).inc()
                    report = self._report(name, state["last"], sig)
                    self.log_fn(report)
                    chron = _chronicle.get_chronicle()
                    if chron.enabled:
                        chron.emit("retrace", source="compile_watch",
                                   severity="watch", fn=name,
                                   retraces=self.retraces, detail=report)
                state["last"] = sig
            return fn(*args, **kwargs)

        wrapped._compile_watch_target = fn
        # preserve the unwrap contract of jit-wrapped targets: consumers
        # (flops profiler) expect .__wrapped__ to be the RAW python
        # function (jax.jit sets it), not the jitted/donating callable
        # functools.wraps just pointed it at
        wrapped.__wrapped__ = getattr(fn, "__wrapped__", fn)
        return wrapped

    def _report(self, name, prev, cur):
        """One-line culprit report: diff *cur* against the previously seen
        signature and name the offending arg path + avals."""
        import jax
        head = (f"[compile-watch] retrace #{self.retraces} of {name!r}")
        tail = f" ({len(self._per_fn[name]['sigs'])} signatures seen)"
        if prev is None or prev[0] != cur[0]:
            return head + ": call tree structure changed" + tail
        diffs = [(i, a, b) for i, (a, b)
                 in enumerate(zip(prev[1], cur[1])) if a != b]
        if not diffs:
            return head + tail
        # resolve leaf index -> human path via the treedef's unflatten
        paths = None
        try:
            dummy = jax.tree_util.tree_unflatten(
                cur[0], list(range(len(cur[1]))))
            flat = jax.tree_util.tree_flatten_with_path(dummy)[0]
            paths = {leaf: jax.tree_util.keystr(path) for path, leaf in flat}
        except Exception:
            pass
        i, a, b = diffs[0]
        where = paths.get(i, f"#{i}") if paths else f"#{i}"
        more = f" (+{len(diffs) - 1} more)" if len(diffs) > 1 else ""
        return (head + f": arg {where} aval changed "
                f"{_fmt(a)} -> {_fmt(b)}{more}" + tail)


# --------------------------------------------------------------------------
# Global backend-compile listener: jax.monitoring publishes
# '/jax/backend_compile' durations for EVERY XLA compilation, including
# ones no wrapped entry point saw. Registered at most once per process
# (jax has no unregister API); the listener routes through this mutable
# holder so it can be retargeted or disabled (holder[0] = None).
# --------------------------------------------------------------------------

_LISTENER_TARGET = [None]
_LISTENER_INSTALLED = False


def install_global_listener(registry):
    """Count backend compiles + compile seconds into *registry*. Returns
    True when the listener is active (now or from a prior install)."""
    global _LISTENER_INSTALLED
    _LISTENER_TARGET[0] = registry
    if _LISTENER_INSTALLED:
        return True
    try:
        from jax import monitoring

        def _on_duration(event, duration, **kw):
            reg = _LISTENER_TARGET[0]
            if reg is None or "compile" not in event:
                return
            # never raise into jax's dispatch path; note the persistent
            # compilation cache reports cache HITS as negative durations
            try:
                if duration < 0:
                    reg.counter(
                        "xla_compile_cache_hits_total",
                        "persistent-cache hits (negative-duration "
                        "monitoring events)").inc()
                    return
                reg.counter("xla_backend_compiles_total",
                            "XLA backend compilations (jax.monitoring)"
                            ).inc()
                reg.counter("xla_backend_compile_seconds_total",
                            "time spent in XLA compilation").inc(duration)
                # goodput ledger: the same measured seconds move from the
                # enclosing interval (the dispatching step) into the
                # 'compile' wall-clock category — a no-op unless a
                # TelemetryManager installed an enabled ledger. BACKEND
                # compiles only: the jaxpr-trace / mlir-lowering phase
                # events NEST (a sub-jaxpr's trace fires inside the
                # outer one), so summing every 'compile' event would
                # double-book wall time and drive the ledger's residual
                # negative.
                if "backend_compile" in event:
                    _ledger.get_ledger().observe_compile(duration)
            except Exception:
                pass

        monitoring.register_event_duration_secs_listener(_on_duration)
        _LISTENER_INSTALLED = True
        return True
    except Exception:
        return False


def uninstall_global_listener():
    """Disarm (the registration itself stays; it becomes a no-op)."""
    _LISTENER_TARGET[0] = None
