"""Structured telemetry subsystem.

Four pieces (see the per-module docstrings):

* ``tracer`` — nested ``trace_span`` contexts -> Chrome-trace JSON
  (+ optional ``jax.profiler.TraceAnnotation`` forwarding);
* ``compile_watch`` — XLA compile counting + retrace culprit reports;
* ``metrics`` — counters / gauges / histograms + device-memory stats;
* ``sinks`` — JSONL event writer and Prometheus text-format exporter
  (both also usable as ``MonitorMaster`` backends).

``TelemetryManager`` (manager.py) wires them per engine run, behind the
``telemetry`` config block (see CONFIG.md). Everything is importable and
near-free when disabled: ``trace_span`` on the default (disabled) global
tracer is a shared no-op context manager.
"""

from deepspeed_tpu.telemetry.tracer import (Tracer, get_tracer, set_tracer,
                                            trace_span)
from deepspeed_tpu.telemetry.metrics import (Counter, Gauge, Histogram,
                                             MetricsRegistry,
                                             device_memory_stats,
                                             get_registry, set_registry)
from deepspeed_tpu.telemetry.compile_watch import CompileWatch
from deepspeed_tpu.telemetry.sinks import (JSONLMonitor, JSONLSink,
                                           PrometheusMonitor,
                                           PrometheusSink,
                                           render_prometheus)
from deepspeed_tpu.telemetry.manager import TelemetryManager

__all__ = [
    "Tracer", "get_tracer", "set_tracer", "trace_span",
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "device_memory_stats", "get_registry", "set_registry",
    "CompileWatch", "JSONLMonitor", "JSONLSink", "PrometheusMonitor",
    "PrometheusSink", "render_prometheus", "TelemetryManager",
]
