"""Structured telemetry subsystem.

Four pieces (see the per-module docstrings):

* ``tracer`` — nested ``trace_span`` contexts -> Chrome-trace JSON
  (+ optional ``jax.profiler.TraceAnnotation`` forwarding);
* ``compile_watch`` — XLA compile counting + retrace culprit reports;
* ``metrics`` — counters / gauges / histograms + device-memory stats;
* ``sinks`` — JSONL event writer and Prometheus text-format exporter
  (both also usable as ``MonitorMaster`` backends);
* ``hlo_census`` — structured census of a compiled XLA program: cost /
  memory analysis + a real HLO parser for per-collective byte volumes
  and mesh-axis attribution;
* ``cost_explorer`` — joins the census with runtime timings: roofline /
  MFU attribution, bound-ness verdicts, HBM watermark pre-flight
  (``python -m deepspeed_tpu.telemetry.explain`` is the CLI);
* ``health`` — training-health observatory: in-step numerics stats
  (grad/param/update norms, per-module buckets, loss-scale state,
  non-finite provenance), EWMA/z-score anomaly rules, HEALTH.json
  forensics (``python -m deepspeed_tpu.telemetry.health`` is the CLI);
* ``ledger`` — goodput ledger: wall-clock attribution into named
  categories that sum to elapsed time, input-stall / unattributed-
  residual rules, GOODPUT.json forensics and on-anomaly programmatic
  profiler capture (``python -m deepspeed_tpu.telemetry.ledger``);
* ``serving_observatory`` — the serving-side counterpart: per-request
  lifecycle timelines (per-slot Chrome-trace lanes), the slot-step
  attribution ledger (categories sum to steps x max_batch x
  decode_steps by construction), windowed SLO rules and
  SERVING_HEALTH.json forensics
  (``python -m deepspeed_tpu.telemetry.serving_observatory``);
* ``fleet`` — the cross-rank flight recorder: every rank ships atomic
  window records into a shared run dir, rank 0 merges them and runs the
  straggler/input/checkpoint skew sentinels plus the desync sentinel
  (cross-replica parameter checksums), escalating to
  FLEET_HEALTH.json; ``merge_traces`` joins per-rank Chrome traces into
  per-rank process lanes (``python -m deepspeed_tpu.telemetry.fleet``);
* ``xplane`` / ``step_anatomy`` — measured device-time attribution:
  a dependency-free wire-format parser for the XSpace protobuf
  ``jax.profiler`` writes, and the StepAnatomy join (per-op device
  seconds -> categories/modules vs the CostExplorer roofline) behind
  ``engine.profile_step`` / ``ServingEngine.profile_window`` ->
  STEP_ANATOMY.json (``python -m deepspeed_tpu.telemetry.step_anatomy``
  is the CLI). Deliberately NOT imported here: the parser only loads
  when a capture is post-processed (lazy ``__getattr__`` below), so
  engine init never pays for it — tests/perf/telemetry_overhead.py
  pins that;
* ``pprof`` / ``memory_observatory`` — measured device-MEMORY
  attribution: a dependency-free parser for the gzip+protobuf pprof
  profile ``jax.profiler.device_memory_profile()`` emits, and the HBM
  residency observatory (exact-sum buffer attribution into
  params / optimizer_state / kv_pool / activations_workspace / other,
  leak / watermark-drift / kv-fragmentation / oom-risk sentinels) behind
  ``telemetry.memory`` + ``engine.memory_report`` -> MEMORY_ANATOMY.json
  (``python -m deepspeed_tpu.telemetry.memory_observatory`` is the
  CLI). Lazy like xplane/step_anatomy — only loads at the first cadence
  tick;
* ``bench_diff`` — bench-regression differ over committed BENCH_r*.json
  rounds (``python -m deepspeed_tpu.telemetry.bench_diff`` exits
  non-zero past the regression threshold);
* ``clock`` — the shared monotonic integer-µs axis every cross-stream
  timestamp joins on (plus the one wall anchor for rendering);
* ``escalation`` — the ONE escalation protocol all five observatories
  share (warn-once, counters, history cap, snapshot, chronicle emit,
  fenced hooks);
* ``chronicle`` / ``incidents`` — the run chronicle (one causally-
  ordered event timeline across monitors, guardian, engine lifecycle,
  serving and chaos; per-rank atomic JSONL streams) and the incident
  correlator joining it into INCIDENTS.json chains with ranked root
  cause and per-incident goodput cost
  (``python -m deepspeed_tpu.telemetry.chronicle`` is the CLI);
* ``obs_server`` — the live observability plane: a zero-dependency
  HTTP endpoint (``telemetry.server`` config block) serving /metrics
  (a real Prometheus scrape target), /healthz + /readyz probes, every
  armed monitor's host-side report under /api/report/<name>, and the
  resumable chronicle tail under /api/events — a scrape never forces a
  device fetch, sync, or compile. Lazy like xplane (below);
* ``slo`` — the SLO burn-rate monitor (``telemetry.slo`` block):
  multi-window error-budget burn over declarative latency/goodput
  objectives; fast+slow both burning pages ``slo_burn_page`` (a
  guardian admission-pause rule) -> SLO_REPORT.json
  (``python -m deepspeed_tpu.telemetry.slo --demo`` is the CLI). Lazy;
* ``dashboard`` — the mission-control terminal dashboard over either a
  live ``obs_server`` URL or an artifact dir
  (``python -m deepspeed_tpu.telemetry.dashboard --url/--dir``). Lazy;
* ``federation`` — fleet federation (``telemetry.federation`` block):
  every rank's obs server announces itself into a run-dir peer
  registry; the aggregator rank scrapes each peer's /metrics, reports
  and resumable /api/events over keep-alive HTTP and serves the
  rank-labelled merged scrape, one (t_us, seq, rank)-ordered fleet
  timeline, fleet-scope SLO burn with per-rank attribution and
  cross-rank incident chains under /federation/* and /api/fleet/* ->
  FLEET_CONTROL.json
  (``python -m deepspeed_tpu.telemetry.federation --demo``). Lazy.

``TelemetryManager`` (manager.py) wires them per engine run, behind the
``telemetry`` config block (see CONFIG.md). Everything is importable and
near-free when disabled: ``trace_span`` on the default (disabled) global
tracer is a shared no-op context manager.
"""

from deepspeed_tpu.telemetry.tracer import (Tracer, get_tracer, set_tracer,
                                            trace_span)
from deepspeed_tpu.telemetry.metrics import (Counter, Gauge, Histogram,
                                             MetricsRegistry,
                                             device_memory_stats,
                                             get_registry, set_registry)
from deepspeed_tpu.telemetry.compile_watch import CompileWatch
from deepspeed_tpu.telemetry.sinks import (JSONLMonitor, JSONLSink,
                                           PrometheusMonitor,
                                           PrometheusSink,
                                           render_prometheus)
from deepspeed_tpu.telemetry.hlo_census import (CollectiveOp, HloCensus,
                                                census_compiled, census_fn,
                                                parse_hlo_collectives,
                                                parse_replica_groups)
from deepspeed_tpu.telemetry.cost_explorer import CostExplorer, detect_chip
from deepspeed_tpu.telemetry.health import (BucketSpec, HealthMonitor,
                                            bucket_grad_stats,
                                            build_bucket_spec,
                                            decode_nonfinite_mask)
from deepspeed_tpu.telemetry.ledger import (GoodputIterator, GoodputLedger,
                                            get_ledger, set_ledger)
from deepspeed_tpu.telemetry.serving_observatory import (RequestTimeline,
                                                         ServingObservatory,
                                                         SlotStepLedger)
from deepspeed_tpu.telemetry.fleet import (FleetMonitor, FleetShipper,
                                           build_desync_checksum_fn,
                                           get_shipper, merge_traces,
                                           set_shipper)
from deepspeed_tpu.telemetry.chronicle import (RunChronicle, get_chronicle,
                                               reset_chronicle,
                                               set_chronicle)
from deepspeed_tpu.telemetry.incidents import (IncidentCorrelator,
                                               correlate, write_incidents)
from deepspeed_tpu.telemetry.manager import (TelemetryManager, get_manager,
                                             set_manager)

__all__ = [
    "Tracer", "get_tracer", "set_tracer", "trace_span",
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "device_memory_stats", "get_registry", "set_registry",
    "CompileWatch", "JSONLMonitor", "JSONLSink", "PrometheusMonitor",
    "PrometheusSink", "render_prometheus", "TelemetryManager",
    "CollectiveOp", "HloCensus", "census_compiled", "census_fn",
    "parse_hlo_collectives", "parse_replica_groups",
    "CostExplorer", "detect_chip",
    "BucketSpec", "HealthMonitor", "bucket_grad_stats",
    "build_bucket_spec", "decode_nonfinite_mask",
    "GoodputIterator", "GoodputLedger", "get_ledger", "set_ledger",
    "RequestTimeline", "ServingObservatory", "SlotStepLedger",
    "FleetMonitor", "FleetShipper", "build_desync_checksum_fn",
    "get_shipper", "merge_traces", "set_shipper",
    "get_manager", "set_manager",
    "RunChronicle", "get_chronicle", "set_chronicle", "reset_chronicle",
    "IncidentCorrelator", "correlate", "write_incidents",
    "xplane", "step_anatomy", "pprof", "memory_observatory",
    "obs_server", "slo", "dashboard", "federation",
]


def __getattr__(name):
    # lazy submodule access (PEP 562): telemetry.xplane / .step_anatomy /
    # .pprof / .memory_observatory stay un-imported until a capture or a
    # residency window is actually post-processed; obs_server / slo /
    # dashboard / federation until the mission-control plane is armed
    if name in ("xplane", "step_anatomy", "pprof", "memory_observatory",
                "obs_server", "slo", "dashboard", "federation"):
        import importlib
        return importlib.import_module(f"deepspeed_tpu.telemetry.{name}")
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")
