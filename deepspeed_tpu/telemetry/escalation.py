"""Shared monitor escalation protocol (one copy instead of five).

Every observatory escalates firing rules the same way — the protocol the
health monitor established in PR 3 and the ledger / serving / fleet /
memory monitors then hand-copied (the PR-4 "deliberate duplication"
note, grown to five copies):

1. warn ONCE per rule (the first firing logs; repeats stay quiet),
2. count the firing (``owner.rule_counts`` + the registry counter),
3. append to the bounded ``owner.anomalies`` forensics list,
4. throttled snapshot, FORCED when any rule fired for the first time,
5. optional per-monitor follow-up (the ledger's one-shot profiler
   capture) — ``after_snapshot(any_first)``,
6. ``on_escalate`` / ``on_anomaly`` hooks, each fenced so a throwing
   hook (trace export, guardian delivery) can never kill the step that
   surfaced the anomaly.

This helper IS that protocol; the monitors' ``_escalate`` methods are
now one-line delegations. It deliberately mutates the owner's existing
``rule_counts`` / ``anomalies`` objects IN PLACE (``del list[:-N]``, not
reassignment) — tests and reports hold references to them.

Step 2.5 is the one new behavior every monitor gains at once: each
anomaly is emitted into the process-global run chronicle
(:mod:`deepspeed_tpu.telemetry.chronicle`), which is how five siloed
JSON artifacts become one causally-ordered timeline. The emit is a
no-op dict-build skip when no chronicle is armed.
"""

from deepspeed_tpu.telemetry import chronicle as _chronicle
from deepspeed_tpu.utils.logging import logger


def escalate(owner, anoms, *, tag, counter, counter_help, step=None,
             after_snapshot=None):
    """Run the escalation protocol for *owner* over *anoms*.

    *owner* supplies the per-monitor state and surfaces: ``rule_counts``,
    ``anomalies``, ``MAX_ANOMALY_HISTORY``, ``registry``, ``_log``,
    ``snapshot_path``, ``write_snapshot(force=)``, ``on_escalate``,
    ``on_anomaly``. *tag* is the log prefix (``health``/``goodput``/...),
    *counter*/*counter_help* the registry counter identity. *step* is the
    ledger's variant (its rules know the window-closing step better than
    the per-anomaly dicts); ``after_snapshot(any_first)`` is the
    monitor-specific step 5.
    """
    chron = _chronicle.get_chronicle()
    any_first = False
    for a in anoms:
        rule = a["rule"]
        first = rule not in owner.rule_counts
        any_first = any_first or first
        owner.rule_counts[rule] = owner.rule_counts.get(rule, 0) + 1
        owner.anomalies.append(a)
        if first:
            owner._log("[%s] %s (%s) at step %s: %s — snapshot -> %s",
                       tag, rule, a["severity"],
                       step if step is not None else a.get("step"),
                       a["detail"], owner.snapshot_path)
        if owner.registry is not None:
            owner.registry.counter(counter, counter_help,
                                   labels={"rule": rule}).inc()
        if chron.enabled:
            chron.emit("anomaly", source=tag,
                       step=step if step is not None else a.get("step"),
                       severity=a.get("severity"), rule=rule,
                       detail=a.get("detail"),
                       artifact=owner.snapshot_path)
    del owner.anomalies[:-owner.MAX_ANOMALY_HISTORY]
    owner.write_snapshot(force=any_first)
    if after_snapshot is not None:
        after_snapshot(any_first)
    if owner.on_escalate is not None:
        try:
            owner.on_escalate()
        except Exception as e:   # forensics must never kill a step
            logger.warning("[%s] on_escalate hook failed: %s", tag, e)
    if owner.on_anomaly is not None:
        try:
            owner.on_anomaly(anoms)
        except Exception as e:   # a policy engine must not either
            logger.warning("[%s] on_anomaly hook failed: %s", tag, e)
