"""Dependency-free XSpace/XPlane protobuf reader.

``jax.profiler.stop_trace`` serialises a ``tensorflow.profiler.XSpace``
protobuf to ``<logdir>/plugins/profile/<run>/<host>.xplane.pb``.  Reading
it back normally requires tensorflow or tensorboard-plugin-profile; this
module instead decodes the protobuf *wire format* by hand (varint +
length-delimited scanning, same house style as the HLO-text parser in
``hlo_census``) so the repo can post-process its own traces with zero
extra dependencies.

It intentionally imports neither ``tensorflow`` nor ``tensorboard`` (a
static guard in ``tests/perf/telemetry_overhead.py`` pins this).

Field numbers (stable since the schema is append-only upstream):

    XSpace:         planes=1 errors=2 warnings=3 hostnames=4
    XPlane:         id=1 name=2 lines=3 event_metadata=4 (map)
                    stat_metadata=5 (map) stats=6
    XLine:          id=1 name=2 timestamp_ns=3 events=4 duration_ps=9
                    display_id=10 display_name=11
    XEvent:         metadata_id=1 offset_ps=2 duration_ps=3 stats=4
                    num_occurrences=5 (oneof with offset_ps)
    XStat:          metadata_id=1 double=2 uint64=3 int64=4 str=5
                    bytes=6 ref=7
    XEventMetadata: id=1 name=2 metadata=3 display_name=4 stats=5
                    child_id=6
    XStatMetadata:  id=1 name=2 description=3

Proto map entries are repeated messages with key=1, value=2.
"""

import glob
import os
import struct
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple, Union

__all__ = [
    "XplaneParseError",
    "XStat",
    "XEvent",
    "XLine",
    "XPlane",
    "XSpace",
    "parse_xspace",
    "parse_xspace_file",
    "find_xplane_files",
]


class XplaneParseError(ValueError):
    """Raised when the wire stream is malformed or truncated.

    The message always names the absolute byte offset at which decoding
    failed so a corrupt capture can be triaged with a hex dump.
    """


# ---------------------------------------------------------------------------
# wire-format primitives
# ---------------------------------------------------------------------------

_WIRE_VARINT = 0
_WIRE_64BIT = 1
_WIRE_LEN = 2
_WIRE_32BIT = 5


def _read_varint(buf: bytes, pos: int, end: int) -> Tuple[int, int]:
    """Decode one base-128 varint; returns (value, new_pos)."""
    result = 0
    shift = 0
    start = pos
    while True:
        if pos >= end:
            raise XplaneParseError(
                f"truncated varint at byte offset {start}")
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not (b & 0x80):
            return result, pos
        shift += 7
        if shift > 63:
            raise XplaneParseError(
                f"varint wider than 64 bits at byte offset {start}")


def _zigzag_signed(value: int) -> int:
    """Reinterpret a 64-bit varint as two's-complement int64.

    (int64 fields are NOT zigzag on the wire — negative values are sent
    as 10-byte two's-complement varints.)
    """
    if value >= 1 << 63:
        value -= 1 << 64
    return value


def _iter_fields(buf: bytes, pos: int, end: int):
    """Yield (field_number, wire_type, payload, value_offset) tuples.

    ``payload`` is an int for varint fields, a memoryview-compatible
    bytes slice for length-delimited / fixed fields.
    """
    while pos < end:
        key, pos = _read_varint(buf, pos, end)
        field_no = key >> 3
        wire = key & 0x7
        if field_no == 0:
            raise XplaneParseError(
                f"illegal field number 0 at byte offset {pos}")
        if wire == _WIRE_VARINT:
            val, pos = _read_varint(buf, pos, end)
            yield field_no, wire, val, pos
        elif wire == _WIRE_LEN:
            length, pos = _read_varint(buf, pos, end)
            if pos + length > end:
                raise XplaneParseError(
                    f"length-delimited field overruns buffer at byte "
                    f"offset {pos} (need {length} bytes, have {end - pos})")
            yield field_no, wire, (pos, pos + length), pos
            pos += length
        elif wire == _WIRE_64BIT:
            if pos + 8 > end:
                raise XplaneParseError(
                    f"truncated fixed64 at byte offset {pos}")
            yield field_no, wire, buf[pos:pos + 8], pos
            pos += 8
        elif wire == _WIRE_32BIT:
            if pos + 4 > end:
                raise XplaneParseError(
                    f"truncated fixed32 at byte offset {pos}")
            yield field_no, wire, buf[pos:pos + 4], pos
            pos += 4
        else:
            raise XplaneParseError(
                f"unsupported wire type {wire} at byte offset {pos}")


# ---------------------------------------------------------------------------
# decoded model
# ---------------------------------------------------------------------------

@dataclass
class XStat:
    metadata_id: int = 0
    value: Union[int, float, str, bytes, None] = None
    # for ref_value stats the value is the *referenced stat-metadata name*
    is_ref: bool = False


@dataclass
class XEvent:
    metadata_id: int = 0
    offset_ps: int = 0
    duration_ps: int = 0
    num_occurrences: int = 0
    stats: List[XStat] = field(default_factory=list)


@dataclass
class XLine:
    id: int = 0
    name: str = ""
    display_name: str = ""
    timestamp_ns: int = 0
    duration_ps: int = 0
    events: List[XEvent] = field(default_factory=list)


@dataclass
class XPlane:
    id: int = 0
    name: str = ""
    lines: List[XLine] = field(default_factory=list)
    event_metadata: Dict[int, dict] = field(default_factory=dict)
    stat_metadata: Dict[int, str] = field(default_factory=dict)
    stats: List[XStat] = field(default_factory=list)

    def event_name(self, event: XEvent) -> str:
        md = self.event_metadata.get(event.metadata_id)
        return md["name"] if md else ""

    def event_stats(self, event: XEvent) -> Dict[str, object]:
        """Resolve an event's stats to {stat_name: python value}."""
        out = {}
        for st in event.stats:
            name = self.stat_metadata.get(st.metadata_id, "")
            if not name:
                continue
            if st.is_ref and isinstance(st.value, int):
                out[name] = self.stat_metadata.get(st.value, "")
            else:
                out[name] = st.value
        return out


@dataclass
class XSpace:
    planes: List[XPlane] = field(default_factory=list)
    hostnames: List[str] = field(default_factory=list)
    errors: List[str] = field(default_factory=list)
    warnings: List[str] = field(default_factory=list)

    def find_plane(self, name: str) -> Optional[XPlane]:
        for p in self.planes:
            if p.name == name:
                return p
        return None


# ---------------------------------------------------------------------------
# message decoders
# ---------------------------------------------------------------------------

def _decode_str(buf: bytes, span: Tuple[int, int], where: str) -> str:
    try:
        return bytes(buf[span[0]:span[1]]).decode("utf-8", "replace")
    except Exception as exc:  # pragma: no cover - decode("replace") is total
        raise XplaneParseError(
            f"undecodable {where} string at byte offset {span[0]}: {exc}")


def _decode_stat(buf: bytes, span: Tuple[int, int]) -> XStat:
    stat = XStat()
    for fno, wire, payload, off in _iter_fields(buf, span[0], span[1]):
        if fno == 1 and wire == _WIRE_VARINT:
            stat.metadata_id = payload
        elif fno == 2 and wire == _WIRE_64BIT:
            stat.value = struct.unpack("<d", payload)[0]
        elif fno == 3 and wire == _WIRE_VARINT:
            stat.value = payload
        elif fno == 4 and wire == _WIRE_VARINT:
            stat.value = _zigzag_signed(payload)
        elif fno == 5 and wire == _WIRE_LEN:
            stat.value = _decode_str(buf, payload, "stat")
        elif fno == 6 and wire == _WIRE_LEN:
            stat.value = bytes(buf[payload[0]:payload[1]])
        elif fno == 7 and wire == _WIRE_VARINT:
            stat.value = payload
            stat.is_ref = True
    return stat


def _decode_event(buf: bytes, span: Tuple[int, int]) -> XEvent:
    ev = XEvent()
    for fno, wire, payload, off in _iter_fields(buf, span[0], span[1]):
        if fno == 1 and wire == _WIRE_VARINT:
            ev.metadata_id = payload
        elif fno == 2 and wire == _WIRE_VARINT:
            ev.offset_ps = _zigzag_signed(payload)
        elif fno == 3 and wire == _WIRE_VARINT:
            ev.duration_ps = _zigzag_signed(payload)
        elif fno == 4 and wire == _WIRE_LEN:
            ev.stats.append(_decode_stat(buf, payload))
        elif fno == 5 and wire == _WIRE_VARINT:
            ev.num_occurrences = _zigzag_signed(payload)
    return ev


def _decode_line(buf: bytes, span: Tuple[int, int]) -> XLine:
    line = XLine()
    for fno, wire, payload, off in _iter_fields(buf, span[0], span[1]):
        if fno == 1 and wire == _WIRE_VARINT:
            line.id = _zigzag_signed(payload)
        elif fno == 2 and wire == _WIRE_LEN:
            line.name = _decode_str(buf, payload, "line name")
        elif fno == 3 and wire == _WIRE_VARINT:
            line.timestamp_ns = _zigzag_signed(payload)
        elif fno == 4 and wire == _WIRE_LEN:
            line.events.append(_decode_event(buf, payload))
        elif fno == 9 and wire == _WIRE_VARINT:
            line.duration_ps = _zigzag_signed(payload)
        elif fno == 11 and wire == _WIRE_LEN:
            line.display_name = _decode_str(buf, payload, "display name")
    return line


def _decode_event_metadata(buf: bytes, span: Tuple[int, int]) -> dict:
    md = {"id": 0, "name": "", "display_name": ""}
    for fno, wire, payload, off in _iter_fields(buf, span[0], span[1]):
        if fno == 1 and wire == _WIRE_VARINT:
            md["id"] = _zigzag_signed(payload)
        elif fno == 2 and wire == _WIRE_LEN:
            md["name"] = _decode_str(buf, payload, "event metadata name")
        elif fno == 4 and wire == _WIRE_LEN:
            md["display_name"] = _decode_str(buf, payload, "display name")
    return md


def _decode_map_entry(buf: bytes, span: Tuple[int, int]):
    """Proto map entry: key=1 (varint here), value=2 (message span)."""
    key = 0
    value_span = None
    for fno, wire, payload, off in _iter_fields(buf, span[0], span[1]):
        if fno == 1 and wire == _WIRE_VARINT:
            key = _zigzag_signed(payload)
        elif fno == 2 and wire == _WIRE_LEN:
            value_span = payload
    return key, value_span


def _decode_plane(buf: bytes, span: Tuple[int, int]) -> XPlane:
    plane = XPlane()
    for fno, wire, payload, off in _iter_fields(buf, span[0], span[1]):
        if fno == 1 and wire == _WIRE_VARINT:
            plane.id = _zigzag_signed(payload)
        elif fno == 2 and wire == _WIRE_LEN:
            plane.name = _decode_str(buf, payload, "plane name")
        elif fno == 3 and wire == _WIRE_LEN:
            plane.lines.append(_decode_line(buf, payload))
        elif fno == 4 and wire == _WIRE_LEN:
            key, vspan = _decode_map_entry(buf, payload)
            if vspan is not None:
                plane.event_metadata[key] = _decode_event_metadata(buf, vspan)
        elif fno == 5 and wire == _WIRE_LEN:
            key, vspan = _decode_map_entry(buf, payload)
            if vspan is not None:
                name = ""
                for f2, w2, p2, _ in _iter_fields(buf, vspan[0], vspan[1]):
                    if f2 == 2 and w2 == _WIRE_LEN:
                        name = _decode_str(buf, p2, "stat metadata name")
                plane.stat_metadata[key] = name
        elif fno == 6 and wire == _WIRE_LEN:
            plane.stats.append(_decode_stat(buf, payload))
    return plane


def parse_xspace(data: bytes) -> XSpace:
    """Decode a serialized XSpace protobuf from memory."""
    space = XSpace()
    for fno, wire, payload, off in _iter_fields(data, 0, len(data)):
        if fno == 1 and wire == _WIRE_LEN:
            space.planes.append(_decode_plane(data, payload))
        elif fno == 2 and wire == _WIRE_LEN:
            space.errors.append(_decode_str(data, payload, "error"))
        elif fno == 3 and wire == _WIRE_LEN:
            space.warnings.append(_decode_str(data, payload, "warning"))
        elif fno == 4 and wire == _WIRE_LEN:
            space.hostnames.append(_decode_str(data, payload, "hostname"))
    return space


def parse_xspace_file(path: str) -> XSpace:
    with open(path, "rb") as f:
        return parse_xspace(f.read())


def find_xplane_files(logdir: str) -> List[str]:
    """Locate ``.xplane.pb`` files under a profiler logdir.

    ``jax.profiler.stop_trace`` writes
    ``<logdir>/plugins/profile/<run>/<host>.xplane.pb``; bare files
    directly under ``logdir`` are accepted too (test fixtures).  Newest
    run first.
    """
    hits = sorted(
        glob.glob(os.path.join(logdir, "plugins", "profile",
                               "*", "*.xplane.pb")),
        key=os.path.getmtime, reverse=True)
    hits += sorted(glob.glob(os.path.join(logdir, "*.xplane.pb")),
                   key=os.path.getmtime, reverse=True)
    return hits
