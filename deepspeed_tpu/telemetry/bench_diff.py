"""Bench regression differ — compare consecutive ``BENCH_r*.json`` rounds.

The repo commits its measured trajectory (``BENCH_r01.json`` ..): every
round records step_ms / tok/s / MFU (+ the tunnel-health probes that
caught the round-3 poisoned environment). Nothing, however, FAILED when a
round regressed — a slower regen could land silently. This differ makes
the trajectory self-guarding:

``python -m deepspeed_tpu.telemetry.bench_diff`` compares the last two
rounds (or an explicit file list, or ``--all`` for the whole chain) and
**exits non-zero** when a tracked metric regressed past the threshold —
wired into tier-1 via ``tests/unit/test_bench_diff.py`` so the committed
trajectory cannot silently regress.

Environment honesty: a round whose ``tunnel_healthy`` flag is ``False``
measured the tunnel, not the engine (the BENCH_r03 lesson — identical
code, 62 then 2.2 TFLOPS hours apart). Comparisons involving such a
round are reported ``unmeasurable`` and do NOT fail, unless ``--strict``.

Pure stdlib — usable from CI without jax installed.
"""

import glob
import json
import os
import re
import sys

# metric -> direction ("down" = lower is better). ``input_wait_frac`` is
# tracked informationally (it appears from PR 5 on); missing-on-either-
# side metrics are skipped, never failed.
METRICS = {
    "step_time_ms": "down",
    "tokens_per_s": "up",
    "value": "up",            # the headline TFLOPS/chip
    "mfu": "up",
    "input_wait_frac": "down",
    # measured HBM residency (appears from the BENCH_MEMORY rounds on):
    # a peak-bytes growth is a memory regression like a step-time one;
    # watermark_drift compares |drift| — the pre-flight calibration can
    # miss in either direction, and -5% -> +5% is no worse
    "hbm_peak_bytes": "down",
    "watermark_drift": "down",
}

# metrics judged on magnitude: sign only says which SIDE the miss was on
_ABS_METRICS = ("watermark_drift",)

DEFAULT_THRESHOLD = 0.10      # 10% relative regression fails


def load_round(path):
    """A bench round: either the raw one-line bench JSON or the committed
    ``{"n", "cmd", "parsed": {...}}`` wrapper. Returns (metrics_dict,
    note) — metrics None when the round carries no parsed payload (the
    round-1 seed failure is such a file)."""
    with open(path) as f:
        doc = json.load(f)
    parsed = doc.get("parsed", doc)
    if not isinstance(parsed, dict) or "step_time_ms" not in parsed:
        return None, "no parsed bench payload"
    return parsed, None


def diff_rounds(prev, cur, threshold=DEFAULT_THRESHOLD):
    """Compare two parsed rounds. Returns the verdict dict:
    ``status`` is ``ok`` | ``regression`` | ``unmeasurable``; ``fields``
    holds per-metric before/after/delta; ``regressions`` the offenders."""
    for side, name in ((prev, "previous"), (cur, "current")):
        if side.get("tunnel_healthy") is False:
            return {"status": "unmeasurable",
                    "why": f"the {name} round's tunnel-health probe "
                           f"failed — it measured a degraded "
                           f"environment, not the engine",
                    "fields": {}, "regressions": []}
    fields = {}
    regressions = []
    for name, direction in METRICS.items():
        a, b = prev.get(name), cur.get(name)
        if not isinstance(a, (int, float)) or \
                not isinstance(b, (int, float)):
            continue
        if name in _ABS_METRICS:
            a, b = abs(a), abs(b)
        if a == 0:
            continue
        rel = (b - a) / abs(a)
        worse = rel > threshold if direction == "down" \
            else rel < -threshold
        fields[name] = {"prev": a, "cur": b,
                        "delta_frac": round(rel, 4),
                        "direction": direction,
                        "regressed": worse}
        if worse:
            regressions.append(name)
    return {"status": "regression" if regressions else "ok",
            "threshold": threshold,
            "fields": fields,
            "regressions": regressions}


def _round_key(path):
    m = re.search(r"BENCH_r(\d+)", os.path.basename(path))
    return (int(m.group(1)) if m else 0, path)


def find_rounds(root="."):
    return sorted(glob.glob(os.path.join(root, "BENCH_r*.json")),
                  key=_round_key)


def render(prev_name, cur_name, verdict):
    lines = [f"bench diff: {os.path.basename(prev_name)} -> "
             f"{os.path.basename(cur_name)}  [{verdict['status'].upper()}]"]
    if verdict.get("why"):
        lines.append(f"  {verdict['why']}")
    for name, row in verdict["fields"].items():
        arrow = "v" if row["delta_frac"] < 0 else "^"
        flag = "  << REGRESSED" if row["regressed"] else ""
        lines.append(
            f"  {name:16s} {row['prev']:>10g} -> {row['cur']:>10g}  "
            f"{arrow}{abs(row['delta_frac']):.1%}{flag}")
    return "\n".join(lines)


def main(argv=None):
    import argparse
    p = argparse.ArgumentParser(
        prog="python -m deepspeed_tpu.telemetry.bench_diff",
        description="Compare consecutive BENCH_r*.json rounds; exit "
                    "non-zero when step_ms / tok/s / MFU / "
                    "input_wait_frac regressed past the threshold")
    p.add_argument("files", nargs="*",
                   help="explicit round files (chronological); default: "
                        "all BENCH_r*.json under --root, last two")
    p.add_argument("--root", default=".",
                   help="directory holding the BENCH_r*.json rounds")
    p.add_argument("--threshold", type=float, default=DEFAULT_THRESHOLD,
                   help=f"relative regression threshold (default "
                        f"{DEFAULT_THRESHOLD:.0%})")
    p.add_argument("--all", action="store_true",
                   help="compare EVERY consecutive pair of the chain, "
                        "not just the last two")
    p.add_argument("--strict", action="store_true",
                   help="treat unmeasurable (tunnel-degraded) rounds as "
                        "failures instead of skipping them")
    args = p.parse_args(argv)

    paths = args.files or find_rounds(args.root)
    rounds = []
    for path in paths:
        parsed, note = load_round(path)
        if parsed is None:
            print(f"# skipping {os.path.basename(path)}: {note}")
            continue
        rounds.append((path, parsed))
    if len(rounds) < 2:
        print("bench_diff: need at least two parseable rounds "
              f"(got {len(rounds)})")
        return 2
    pairs = list(zip(rounds, rounds[1:])) if args.all \
        else [(rounds[-2], rounds[-1])]
    rc = 0
    for (pname, prev), (cname, cur) in pairs:
        verdict = diff_rounds(prev, cur, threshold=args.threshold)
        print(render(pname, cname, verdict))
        if verdict["status"] == "regression":
            rc = 1
        elif verdict["status"] == "unmeasurable" and args.strict:
            rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
