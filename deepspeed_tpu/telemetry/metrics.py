"""Metrics registry — counters, gauges, histograms.

Prometheus-shaped data model (a counter only goes up; a histogram is
cumulative buckets + sum + count) kept deliberately tiny: everything is
host-side Python floats updated from the train loop at step cadence, so
there is no contention worth optimising beyond one lock per metric family.

``device_memory_stats`` reads the accelerator's own allocator counters
(``Device.memory_stats()`` — populated on TPU/GPU backends) and falls back
to host RSS where the backend reports nothing (CPU), so the device-memory
gauge is always publishable.
"""

import threading
import time

# Prometheus histogram default buckets are latency-in-seconds oriented;
# step/phase times here are milliseconds, so the default ladder spans
# 0.1 ms .. 100 s.
DEFAULT_BUCKETS = (0.1, 0.5, 1, 5, 10, 50, 100, 500, 1000, 5000, 10000,
                   50000, 100000)


def _label_key(labels):
    return tuple(sorted((labels or {}).items()))


class _Metric:
    kind = "untyped"

    def __init__(self, name, help="", labels=None):
        self.name = name
        self.help = help
        self.labels = dict(labels or {})
        self._lock = threading.Lock()


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name, help="", labels=None):
        super().__init__(name, help, labels)
        self.value = 0.0

    def inc(self, amount=1.0):
        if amount < 0:
            raise ValueError("counters only go up; use a gauge")
        with self._lock:
            self.value += amount


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, name, help="", labels=None):
        super().__init__(name, help, labels)
        self.value = 0.0

    def set(self, value):
        with self._lock:
            self.value = float(value)

    def inc(self, amount=1.0):
        with self._lock:
            self.value += amount

    def dec(self, amount=1.0):
        self.inc(-amount)


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help="", labels=None, buckets=DEFAULT_BUCKETS):
        super().__init__(name, help, labels)
        self.buckets = tuple(sorted(buckets))
        self.counts = [0] * (len(self.buckets) + 1)  # +1 for +Inf
        self.sum = 0.0
        self.count = 0

    def observe(self, value):
        value = float(value)
        with self._lock:
            self.sum += value
            self.count += 1
            for i, b in enumerate(self.buckets):
                if value <= b:
                    self.counts[i] += 1
                    break
            else:
                self.counts[-1] += 1

    def cumulative_counts(self):
        """Prometheus buckets are cumulative: count of observations <= le."""
        out, acc = [], 0
        with self._lock:
            for c in self.counts:
                acc += c
                out.append(acc)
        return out

    def quantile(self, q):
        """Prometheus-style quantile estimate: linear interpolation
        inside the bucket the rank falls into (the +Inf bucket clamps to
        the last finite edge). ``None`` while the histogram is empty —
        callers must handle it (e.g. a serving run whose decode_steps
        covers every generation records no inter-token latencies)."""
        cum = self.cumulative_counts()
        total = self.count
        if total == 0:
            return None
        rank = q * total
        edges = [0.0] + [float(b) for b in self.buckets]
        for i, c in enumerate(cum):
            if c >= rank:
                if i >= len(self.buckets):          # +Inf bucket
                    return edges[-1]
                lo = edges[i]
                hi = float(self.buckets[i])
                prev = cum[i - 1] if i else 0
                frac = (rank - prev) / max(1, c - prev)
                return lo + (hi - lo) * frac
        return edges[-1]


class MetricsRegistry:
    """Name+labels -> metric instance. ``get_or_create`` semantics so call
    sites can be one-liners (``reg.counter("x").inc()``); a kind clash on
    an existing name raises instead of silently corrupting the series."""

    def __init__(self):
        self._metrics = {}
        self._lock = threading.Lock()

    def _get(self, cls, name, help, labels, **kw):
        key = (name, _label_key(labels))
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = cls(name, help, labels, **kw)
                self._metrics[key] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}")
            return m

    def counter(self, name, help="", labels=None):
        return self._get(Counter, name, help, labels)

    def gauge(self, name, help="", labels=None):
        return self._get(Gauge, name, help, labels)

    def histogram(self, name, help="", labels=None, buckets=DEFAULT_BUCKETS):
        return self._get(Histogram, name, help, labels, buckets=buckets)

    def collect(self):
        """All metrics, grouped by family name (Prometheus exposition
        wants one HELP/TYPE header per family)."""
        with self._lock:
            metrics = list(self._metrics.values())
        families = {}
        for m in metrics:
            families.setdefault(m.name, []).append(m)
        return families

    def snapshot(self):
        """Plain-dict dump (JSON-friendly) for bench artifacts."""
        out = {}
        for name, ms in self.collect().items():
            rows = []
            for m in ms:
                row = {"labels": m.labels, "kind": m.kind}
                if isinstance(m, Histogram):
                    row.update(sum=m.sum, count=m.count,
                               buckets=dict(zip(
                                   [str(b) for b in m.buckets] + ["+Inf"],
                                   m.cumulative_counts())))
                else:
                    row["value"] = m.value
                rows.append(row)
            out[name] = rows
        return out

    def clear(self):
        with self._lock:
            self._metrics.clear()


def device_memory_stats(device=None):
    """Best-effort memory stats dict.

    TPU/GPU: the backend allocator's ``memory_stats()``
    (``bytes_in_use``, ``peak_bytes_in_use``, ``bytes_limit`` when
    present). CPU or unsupported backends: host RSS via psutil, then the
    stdlib ``resource`` module. Never raises; empty dict worst case."""
    try:
        import jax
        d = device if device is not None else jax.local_devices()[0]
        stats = d.memory_stats()
        if stats:
            keep = {k: v for k, v in stats.items()
                    if isinstance(v, (int, float))}
            if keep:
                keep["source"] = "device"
                return keep
    except Exception:
        pass
    try:
        import psutil
        return {"host_rss_bytes": psutil.Process().memory_info().rss,
                "source": "host_rss"}
    except Exception:
        pass
    try:
        import resource
        # ru_maxrss is KiB on Linux
        peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
        return {"host_peak_rss_bytes": peak, "source": "host_peak_rss"}
    except Exception:
        return {}


# Process-global registry, mirroring tracer.py's global: library code
# records into whichever registry is installed; without telemetry the
# records land in a registry nobody exports (cheap, not free — call sites
# are step/checkpoint cadence, never per-element).
_GLOBAL = MetricsRegistry()


def get_registry():
    return _GLOBAL


def set_registry(registry):
    global _GLOBAL
    old, _GLOBAL = _GLOBAL, registry
    return old
