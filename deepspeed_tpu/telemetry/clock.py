"""Shared monotonic integer-µs clock for every telemetry stream.

Before this module each instrument picked its own time source —
``time.monotonic`` (ledger intervals), ``time.perf_counter`` (fleet
shipper windows), ``time.time`` (guardian journal entries) — which is
fine inside one file and fatal the moment streams are JOINED: the run
chronicle orders events from every monitor, the guardian and the engine
lifecycle on ONE axis, and comparing a wall-clock stamp to a monotonic
stamp silently mis-orders the causal chain (NTP slews wall clock; the
monotonic origin is boot-arbitrary).

The contract here:

* :func:`monotonic_us` — the ONE ordering axis: integer microseconds on
  the process monotonic clock (``time.monotonic_ns() // 1000``).
  Integer so equality/ordering survive JSON round-trips with no float
  drift (the PR-11 exact-sum discipline applied to time stamps).
* :func:`to_unix_us` / :func:`unix_us` — RENDERING only: a wall-clock
  anchor is sampled once at import (one ``(monotonic, unix)`` pair), so
  any monotonic stamp converts to an approximate wall time through the
  same fixed offset. Conversions are for humans reading a timeline;
  joins and ordering must always use the monotonic stamps.

Host-only, stdlib-only — importable from the no-jax monitors without
breaking their module-scope import guards.
"""

import time

# One anchor pair for the whole process, sampled back-to-back at import:
# every renderer maps monotonic -> wall through the SAME offset, so two
# streams' stamps keep their relative order after conversion. (The pair
# itself is ~µs-skewed — irrelevant for rendering, which is why ordering
# never uses converted values.)
_ANCHOR_MONO_US = time.monotonic_ns() // 1000
_ANCHOR_UNIX_US = time.time_ns() // 1000


def monotonic_us():
    """Integer microseconds on the process monotonic clock — the shared
    ordering axis for chronicle events, ledger windows and fleet
    records."""
    return time.monotonic_ns() // 1000


def monotonic_s():
    """The same clock as :func:`monotonic_us`, in float seconds — for
    call sites that keep second-resolution arithmetic (ledger interval
    math) but must stay on the shared axis."""
    return time.monotonic_ns() / 1e9


def to_unix_us(t_us):
    """Render a :func:`monotonic_us` stamp as approximate unix µs
    (fixed process-wide offset; rendering only, never ordering)."""
    return int(t_us) - _ANCHOR_MONO_US + _ANCHOR_UNIX_US


def unix_us():
    """Approximate unix µs of *now*, via the same anchor."""
    return to_unix_us(monotonic_us())


def from_unix_us(u_us):
    """Inverse of :func:`to_unix_us`: map a unix-µs stamp back onto THIS
    process's monotonic axis through the same fixed anchor pair. This is
    the cross-process rebase the fleet federation merge rides: two
    ranks' raw ``t_us`` values are NOT comparable (each process's
    monotonic origin is boot-arbitrary), but every chronicle event also
    carries its ``unix_us`` rendering — converting that back through the
    aggregator's anchor puts every peer's events on ONE ordering axis,
    skewed only by cross-host wall-clock error (NTP-bounded), never by
    origin mismatch (unbounded)."""
    return int(u_us) - _ANCHOR_UNIX_US + _ANCHOR_MONO_US
