"""Fleet federation — cross-process mission control.

PR 18's observability plane (ObsServer + SLO burn + dashboard) and
PR 17's causal timeline are strictly single-process; PR 11's
FleetMonitor merges ranks but only through post-mortem run-dir files.
ROADMAP item 2's collective self-healing needs the missing quadrant:
a LIVE merged view of N ranks — one scrape target, one timeline, one
burn figure — before any rank-0 policy can act on fleet evidence. This
module is that aggregation-before-action layer:

* **Discovery** — a static ``telemetry.federation.peers`` URL list,
  plus the run-dir peer registry every rank's ObsServer writes
  (:meth:`ObsServer.announce`, tmp+fsync+atomic-rename): drop N ranks
  on one run dir and the aggregator finds them all, surviving restarts
  that re-bind ports (the registry file is re-announced; the worker
  reconnects to the new URL).

* **Scraping** — one worker thread per peer over keep-alive HTTP
  (stdlib ``http.client``) with a per-request timeout: ``/healthz``
  (provider inventory), ``/metrics`` (exposition text, already stamped
  with the peer's ``rank`` identity label by
  :func:`sinks.render_prometheus` ``extra_labels``),
  ``/api/report/<name>`` for every armed monitor, and the resumable
  ``/api/events?since_seq=<cursor>``. A dead or HANGING peer times out
  on its own thread, is marked ``stale`` with its last-seen age, and
  never blocks another peer's scrape or the merge.

* **Merged views**, mounted on any ObsServer via :meth:`attach`:

  =============================  ====================================
  ``/federation/metrics``        every peer's families concatenated
                                 (HELP/TYPE deduped, rank label
                                 guaranteed) + the aggregator's own
                                 fleet registry as rank ``fleet``
  ``/federation/status``         peer inventory + staleness
  ``/api/fleet/report/<name>``   per-rank report merge (``slo`` /
                                 ``incidents`` serve the FLEET-level
                                 documents)
  ``/api/fleet/events``          ONE strictly ``(t_us, seq, rank)``-
                                 ordered timeline, ``?cursor=``
                                 resumable
  =============================  ====================================

* **One time axis** — raw ``t_us`` stamps are NOT comparable across
  processes (boot-arbitrary monotonic origins), so every merged event
  is rebased through its ``unix_us`` rendering onto the aggregator's
  own monotonic axis (:func:`clock.from_unix_us` — NTP-bounded skew,
  never origin-unbounded); the peer's original stamp survives as
  ``src_t_us``. The aggregator's per-peer scrape cursors persist to
  ``<run_dir>/peers/aggregator_cursors.json``, so an aggregator
  restart resumes each peer exactly where it left off — the peer's
  chronicle serves ring-dropped seqs from its on-disk stream.

* **Fleet SLO** — a :class:`slo.SloMonitor` subclass whose samples are
  the UNION of peer samples: ``fleet_goodput`` re-adds every peer's
  ledger seconds, ``fleet_ttft`` every peer's TTFT totals. Burn is the
  fleet's burn; each escalation carries **per-rank attribution** (which
  peer dominates the window's bad delta) so "the fleet is burning"
  always arrives with "and rank 2 is why".

* **Cross-rank incidents** — :func:`incidents.correlate` over the
  merged timeline: a chaos SIGKILL on rank 2 roots the
  ``step_time_skew`` anomalies every OTHER rank fires, and the root
  cause names the rank (the correlator's cross-rank join).

``report()`` is the FLEET_CONTROL.json document; the committed
repo-root artifact comes from ``--demo`` (3 subprocess ranks, one
injected SIGKILL fault — the chaos-harness self-documenting pattern).
A scrape of a peer costs that peer ZERO device work: every scraped
route is host-side by the obs-server contract, pinned by
tests/perf/telemetry_overhead.py.

CLI: ``python -m deepspeed_tpu.telemetry.federation --demo`` writes
FLEET_CONTROL.json; ``--simulate-peer N --run-dir D`` runs one
synthetic rank (a real ObsServer + chronicle; the subprocess harness
the tests and the demo share); ``--render FLEET_CONTROL.json``
pretty-prints the fleet view.
"""

import argparse
import json
import os
import threading
import weakref
from collections import deque
from http.client import HTTPConnection
from urllib.parse import urlsplit

from deepspeed_tpu.telemetry import chronicle as _chronicle
from deepspeed_tpu.telemetry import clock as _clk
from deepspeed_tpu.telemetry import incidents as _incidents
from deepspeed_tpu.telemetry import slo as _slo
from deepspeed_tpu.telemetry.ledger import GOOD_CATEGORIES
from deepspeed_tpu.utils.logging import logger

FLEET_CONTROL_SCHEMA = "deepspeed_tpu.fleet_control/1"

_CURSOR_FILE = "aggregator_cursors.json"
_PEERS_DIR = "peers"
_PEER_FMT = "peer_rank_{:05d}.json"

# fleet objective names the _FleetSlo sampler dispatches on
FLEET_GOODPUT = "fleet_goodput"
FLEET_TTFT = "fleet_ttft"

# how many catch-up /api/events fetches one scrape pass may chain when
# the peer reports a truncated tail (bounds a worker's time inside one
# pass; the next pass continues from the cursor)
_EVENTS_CATCHUP_FETCHES = 20


# ------------------------------------------------------------------ HTTP

def _http_get(peer, path, timeout_s, token=""):
    """One keep-alive GET against *peer* (a :class:`_Peer`). Returns
    ``(status, body_bytes)``; raises on transport errors (caller marks
    the peer). The connection is rebuilt when the peer's URL changed
    (a restarted rank re-announcing on a new port)."""
    parts = urlsplit(peer.url)
    conn = peer.conn
    if conn is None or peer.conn_netloc != parts.netloc:
        if conn is not None:
            try:
                conn.close()
            except Exception:
                pass
        conn = HTTPConnection(parts.hostname, parts.port or 80,
                              timeout=timeout_s)
        peer.conn = conn
        peer.conn_netloc = parts.netloc
    headers = {}
    if token:
        headers["Authorization"] = f"Bearer {token}"
    try:
        conn.request("GET", path, headers=headers)
        resp = conn.getresponse()
        body = resp.read()
        return resp.status, body
    except Exception:
        # a broken keep-alive socket poisons every later request on it
        try:
            conn.close()
        except Exception:
            pass
        peer.conn = None
        raise


def _http_get_json(peer, path, timeout_s, token=""):
    status, body = _http_get(peer, path, timeout_s, token)
    if status != 200:
        raise RuntimeError(f"GET {path} -> {status}")
    return json.loads(body)


# ------------------------------------------------------------ peer state

class _Peer:
    """Everything one peer's worker thread maintains. Mutated by the
    worker, read under the state lock by the merge/report paths."""
    __slots__ = ("key", "url", "rank", "job_name", "conn", "conn_netloc",
                 "last_seen_us", "scrapes", "errors", "last_error",
                 "cursor", "events", "metrics_text", "reports",
                 "providers", "dropped", "static")

    def __init__(self, key, url, rank=None, job_name="", cursor=-1,
                 events_ring=4096, static=False):
        self.key = key
        self.url = url
        self.rank = rank
        self.job_name = job_name
        self.conn = None
        self.conn_netloc = None
        self.last_seen_us = None
        self.scrapes = 0
        self.errors = 0
        self.last_error = None
        self.cursor = int(cursor)      # last chronicle seq fetched
        self.events = deque(maxlen=events_ring)
        self.metrics_text = ""
        self.reports = {}
        self.providers = ()
        self.dropped = 0
        self.static = static

    def status(self, now_us, stale_after_s):
        if self.last_seen_us is None:
            return "never"
        age = (now_us - self.last_seen_us) / 1e6
        return "stale" if age > stale_after_s else "ok"

    def last_seen_age_s(self, now_us):
        if self.last_seen_us is None:
            return None
        return round((now_us - self.last_seen_us) / 1e6, 3)


class _AggState:
    """Everything the aggregator's threads may touch — workers and the
    tick thread hold ONLY this (never the FleetAggregator), the
    chronicle/obs-server finalize discipline."""

    def __init__(self):
        self.lock = threading.Lock()
        self.stop = threading.Event()
        self.peers = {}              # key -> _Peer
        self.saved_cursors = {}      # key -> persisted resume seq
        self.threads = []
        self.scrapes_total = 0
        self.scrape_errors_total = 0
        self.events_merged_total = 0
        self.last_tick_us = None
        self.started_us = _clk.monotonic_us()
        # filled by FleetAggregator.__init__ before threads start
        self.run_dir = None
        self.peers_dir = None
        self.cursor_path = None
        self.static_peers = ()
        self.token = ""
        self.timeout_s = 2.0
        self.scrape_interval_s = 1.0
        self.stale_after_s = 10.0
        self.events_ring = 4096
        self.job_name = ""
        self.slo = None              # _FleetSlo
        self.contrib = {}            # objective -> {rank: deque[(t,b,tot)]}
        self.log = logger.warning


# --------------------------------------------------------------- scraping

def _scrape_peer(state, peer):
    """One full scrape pass against one peer: inventory, metrics,
    reports, resumable events. Any transport error marks the peer and
    returns — staleness is judged by last-seen age, and the worker
    retries next interval."""
    t = state.timeout_s
    tok = state.token
    try:
        healthz = _http_get_json(peer, "/healthz", t, tok)
        providers = tuple(sorted((healthz.get("monitors") or {})))
        _status, metrics_body = _http_get(peer, "/metrics", t, tok)
        reports = {}
        for name in providers:
            reports[name] = _http_get_json(
                peer, f"/api/report/{name}", t, tok)
        new_events, dropped = [], 0
        cursor = peer.cursor
        for _ in range(_EVENTS_CATCHUP_FETCHES):
            # oldest=1: gapless pagination from the cursor (the default
            # tail view would skip the middle of a large backlog)
            doc = _http_get_json(
                peer, f"/api/events?since_seq={cursor}&oldest=1", t, tok)
            if not doc.get("enabled", False):
                break
            evs = doc.get("events", [])
            dropped = int(doc.get("dropped", 0))
            new_events.extend(evs)
            cursor = int(doc.get("last_seq", cursor))
            if not doc.get("truncated"):
                break
    except Exception as e:
        with state.lock:
            peer.errors += 1
            peer.last_error = f"{type(e).__name__}: {e}"
            state.scrape_errors_total += 1
        return
    now = _clk.monotonic_us()
    with state.lock:
        peer.scrapes += 1
        peer.last_seen_us = now
        peer.last_error = None
        peer.providers = providers
        peer.metrics_text = metrics_body.decode(errors="replace")
        peer.reports = reports
        peer.dropped = dropped
        if peer.rank is None:
            # static peers learn their rank from the first event
            for e in new_events:
                if "rank" in e:
                    peer.rank = int(e["rank"])
                    break
        rank = peer.rank if peer.rank is not None else -1
        for e in new_events:
            ev = dict(e)
            ev["src_t_us"] = e.get("t_us")
            # one ordering axis: rebase through the peer's wall-clock
            # rendering onto THIS process's monotonic anchor
            if "unix_us" in e:
                ev["t_us"] = _clk.from_unix_us(e["unix_us"])
            ev.setdefault("rank", rank)
            peer.events.append(ev)
        peer.cursor = cursor
        state.scrapes_total += 1
        state.events_merged_total += len(new_events)


def _peer_loop(state, key):
    # scraping a co-resident rank must never book badput into the run
    # being scraped (lazy import: ledger imports escalation imports
    # chronicle)
    from deepspeed_tpu.telemetry.ledger import suppress_attribution
    with suppress_attribution():
        while not state.stop.is_set():
            with state.lock:
                peer = state.peers.get(key)
            if peer is None:
                return
            _scrape_peer(state, peer)
            if state.stop.wait(state.scrape_interval_s):
                return


def _load_cursors(state):
    if not state.cursor_path or not os.path.isfile(state.cursor_path):
        return {}
    try:
        with open(state.cursor_path) as f:
            doc = json.load(f)
        return {str(k): int(v) for k, v in
                (doc.get("cursors") or {}).items()}
    except (OSError, ValueError):
        return {}


def _persist_cursors(state):
    if not state.cursor_path:
        return
    with state.lock:
        cursors = {p.key: p.cursor for p in state.peers.values()}
    doc = {"schema": "deepspeed_tpu.fleet_cursors/1", "cursors": cursors}
    try:
        _chronicle._atomic_write_bytes(
            state.cursor_path,
            json.dumps(doc, sort_keys=True).encode())
    except OSError as e:
        state.log("[federation] cursor persist failed: %s", e)


def _discover(state):
    """Merge the static peer list and the run-dir registry into the
    peer table; spawn a worker for every NEW peer. A re-announced rank
    (restart on a new port) updates the existing peer's URL in place —
    its worker reconnects on the next pass. New peers resume from any
    persisted cursor (aggregator-restart continuity)."""
    found = []
    for i, url in enumerate(state.static_peers):
        found.append((f"static:{i}", str(url).rstrip("/"), None, ""))
    if state.peers_dir and os.path.isdir(state.peers_dir):
        for fname in sorted(os.listdir(state.peers_dir)):
            if not fname.startswith("peer_rank_") \
                    or not fname.endswith(".json") \
                    or _chronicle._TMP_MARK in fname:
                continue
            try:
                with open(os.path.join(state.peers_dir, fname)) as f:
                    doc = json.load(f)
                found.append((f"rank:{int(doc['rank'])}",
                              str(doc["url"]).rstrip("/"),
                              int(doc["rank"]),
                              doc.get("job_name", "")))
            except (OSError, ValueError, KeyError):
                continue          # torn or foreign file — skip, re-scan
    spawned = []
    with state.lock:
        for key, url, rank, job in found:
            peer = state.peers.get(key)
            if peer is None:
                cursor = state.saved_cursors.get(key, -1)
                peer = _Peer(key, url, rank=rank, job_name=job,
                             cursor=cursor,
                             events_ring=state.events_ring,
                             static=key.startswith("static:"))
                state.peers[key] = peer
                spawned.append(key)
            elif peer.url != url:
                peer.url = url    # restarted rank, new port
    for key in spawned:
        th = threading.Thread(target=_peer_loop, args=(state, key),
                              name=f"ds-fed-{key}", daemon=True)
        th.start()
        state.threads.append(th)


def _tick_loop(state):
    from deepspeed_tpu.telemetry.ledger import suppress_attribution
    with suppress_attribution():
        while not state.stop.wait(state.scrape_interval_s):
            try:
                _discover(state)
                if state.slo is not None:
                    state.slo.tick()
                _persist_cursors(state)
                state.last_tick_us = _clk.monotonic_us()
            except Exception as e:   # forensics must never die loudly
                state.log("[federation] tick failed: %s", e)


def _finalize_agg(state):
    state.stop.set()
    for th in state.threads:
        if th.is_alive():
            th.join(timeout=state.timeout_s + 2.0)


# -------------------------------------------------------------- fleet SLO

def _fleet_sample(state, obj):
    """Cumulative ``(bad, total)`` for one fleet objective — the UNION
    of every peer's samples, re-added from their scraped reports. Also
    books each rank's contribution for burn attribution. None until at
    least one peer exposes the source."""
    name = obj["name"]
    now = _clk.monotonic_us()
    with state.lock:
        peers = list(state.peers.values())
        contrib = state.contrib.setdefault(name, {})
    bad = total = 0.0
    seen = False
    for p in peers:
        if name == FLEET_GOODPUT:
            rep = p.reports.get("goodput")
            if not rep or not rep.get("enabled", True):
                continue
            elapsed = float(rep.get("elapsed_s") or 0.0)
            good = sum(float((rep.get("categories_s") or {}).get(c, 0.0))
                       for c in GOOD_CATEGORIES)
            p_bad, p_total = max(0.0, elapsed - good), elapsed
        elif name == FLEET_TTFT:
            rep = p.reports.get("slo")
            totals = (((rep or {}).get("objectives") or {})
                      .get("serving_ttft") or {}).get("totals")
            if not totals:
                continue
            p_bad = float(totals.get("bad", 0))
            p_total = float(totals.get("total", 0))
        else:
            continue
        seen = True
        bad += p_bad
        total += p_total
        rank = p.rank if p.rank is not None else p.key
        with state.lock:
            dq = contrib.setdefault(rank, deque(maxlen=512))
            dq.append((now, p_bad, p_total))
    return (bad, total) if seen else None


def _attribute(state, anom):
    """Enrich one fleet burn anomaly with per-rank attribution: which
    peer dominates the bad delta over the fast window."""
    name = anom.get("objective")
    window_us = int(state.slo.fast_window_s * 1e6) if state.slo else 0
    now = anom.get("t_us") or _clk.monotonic_us()
    with state.lock:
        contrib = {r: list(dq) for r, dq in
                   state.contrib.get(name, {}).items()}
    deltas = {}
    for rank, samples in contrib.items():
        if not samples:
            continue
        newest = samples[-1]
        anchor = samples[0]
        for s in samples:
            if s[0] <= now - window_us:
                anchor = s
            else:
                break
        deltas[rank] = round(max(0.0, newest[1] - anchor[1]), 6)
    if deltas:
        dominant = max(deltas, key=deltas.get)
        anom["dominant_rank"] = dominant
        anom["rank_bad_deltas"] = deltas
        anom["detail"] = (anom.get("detail", "")
                          + f" [dominant rank {dominant}]")
    return anom


class _FleetSlo(_slo.SloMonitor):
    """SloMonitor whose sample source is the merged fleet view instead
    of the local registry/ledger, and whose escalations carry per-rank
    attribution. Everything else — multi-window burn, tier edges, the
    shared escalation protocol — is inherited unchanged."""

    def __init__(self, state, **kwargs):
        self._fed_state = state
        super().__init__(**kwargs)

    def _sample(self, obj):
        return _fleet_sample(self._fed_state, obj)

    def _escalate(self, anoms, step):
        for a in anoms:
            _attribute(self._fed_state, a)
        super()._escalate(anoms, step)


# ------------------------------------------------------------- aggregator

class FleetAggregator:
    """The cross-process mission-control aggregator. See the module
    docstring. Construction loads persisted cursors, discovers peers
    and starts the scrape/tick threads; :meth:`attach` mounts the
    merged routes on an ObsServer; ``close()`` (idempotent, also run by
    ``weakref.finalize``) stops every thread and persists cursors."""

    def __init__(self, peers=(), run_dir=None, registry=None,
                 scrape_interval_s=1.0, timeout_s=2.0, stale_after_s=10.0,
                 events_ring=4096, snapshot_path=None, token="",
                 job_name="", enabled=True, goodput_target=0.9,
                 ttft_target=0.99, fast_window_s=300.0,
                 slow_window_s=3600.0, burn_threshold=1.0,
                 eval_interval_s=10.0, log_fn=None):
        self.enabled = bool(enabled)
        if not self.enabled:
            return
        from deepspeed_tpu.telemetry.metrics import MetricsRegistry
        self._log = log_fn or logger.warning
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        self.snapshot_path = snapshot_path
        self.job_name = job_name
        self._closed = False
        self._last_snapshot_s = None
        st = _AggState()
        st.run_dir = run_dir
        st.static_peers = tuple(peers or ())
        st.token = str(token or "")
        st.timeout_s = float(timeout_s)
        st.scrape_interval_s = float(scrape_interval_s)
        st.stale_after_s = float(stale_after_s)
        st.events_ring = max(16, int(events_ring))
        st.job_name = job_name
        st.log = self._log
        if run_dir:
            st.peers_dir = os.path.join(run_dir, _PEERS_DIR)
            os.makedirs(st.peers_dir, exist_ok=True)
            st.cursor_path = os.path.join(st.peers_dir, _CURSOR_FILE)
        st.slo = _FleetSlo(
            st,
            objectives=[
                {"name": FLEET_GOODPUT, "kind": "goodput",
                 "target": float(goodput_target)},
                {"name": FLEET_TTFT, "kind": "goodput",
                 "target": float(ttft_target)},
            ],
            fast_window_s=fast_window_s, slow_window_s=slow_window_s,
            burn_threshold=burn_threshold,
            eval_interval_s=eval_interval_s,
            registry=self.registry, job_name=job_name, log_fn=self._log)
        self._state = st
        st.saved_cursors = _load_cursors(st)
        _discover(st)
        tick = threading.Thread(target=_tick_loop, args=(st,),
                                name="ds-fed-tick", daemon=True)
        tick.start()
        st.threads.append(tick)
        self._finalizer = weakref.finalize(self, _finalize_agg, st)

    @classmethod
    def from_config(cls, tcfg, output_path="telemetry/", run_dir=None,
                    registry=None, job_name="", log_fn=None):
        """Build from a parsed :class:`DeepSpeedTelemetryConfig`
        (``telemetry.federation`` block). The snapshot lands under the
        telemetry output dir unless the configured name is absolute
        (never a bare CWD default)."""
        snap = tcfg.federation_snapshot_file or "FLEET_CONTROL.json"
        if not os.path.isabs(snap):
            snap = os.path.join(output_path or "telemetry/", snap)
        return cls(peers=tcfg.federation_peers,
                   run_dir=run_dir or tcfg.federation_run_dir,
                   registry=registry,
                   scrape_interval_s=tcfg.federation_scrape_interval_s,
                   timeout_s=tcfg.federation_timeout_s,
                   stale_after_s=tcfg.federation_stale_after_s,
                   events_ring=tcfg.federation_events_ring,
                   snapshot_path=snap, token=tcfg.server_token,
                   job_name=job_name,
                   goodput_target=tcfg.federation_goodput_target,
                   ttft_target=tcfg.federation_ttft_target,
                   fast_window_s=tcfg.slo_fast_window_s,
                   slow_window_s=tcfg.slo_slow_window_s,
                   burn_threshold=tcfg.slo_burn_threshold,
                   eval_interval_s=tcfg.slo_eval_interval_s,
                   log_fn=log_fn)

    # ---------------------------------------------------------- the merge
    def peers(self):
        """Peer inventory with live staleness judgement."""
        if not self.enabled:
            return []
        now = _clk.monotonic_us()
        st = self._state
        with st.lock:
            peers = list(st.peers.values())
        out = []
        for p in sorted(peers, key=lambda p: (p.rank is None,
                                              p.rank, p.key)):
            out.append({
                "key": p.key, "url": p.url, "rank": p.rank,
                "job_name": p.job_name, "static": p.static,
                "status": p.status(now, st.stale_after_s),
                "last_seen_age_s": p.last_seen_age_s(now),
                "scrapes": p.scrapes, "errors": p.errors,
                "last_error": p.last_error, "cursor": p.cursor,
                "events_held": len(p.events),
                "peer_dropped": p.dropped,
                "providers": list(p.providers),
            })
        return out

    def merged_events(self, cursor=None, limit=None):
        """The fleet timeline: every peer's events on the aggregator's
        rebased axis, strictly ``(t_us, seq, rank)``-ordered.
        *cursor* is an opaque ``"t_us:seq:rank"`` string from a prior
        response — only strictly-later events return (resumable)."""
        if not self.enabled:
            return []
        st = self._state
        with st.lock:
            events = [e for p in st.peers.values() for e in p.events]
        events.sort(key=_order_key)
        if cursor:
            after = _parse_cursor(cursor)
            events = [e for e in events if _order_key(e) > after]
        if limit is not None and len(events) > int(limit):
            events = events[-int(limit):]
        return events

    def merged_metrics(self):
        """One exposition document for the whole fleet: every peer's
        scraped ``/metrics`` text (already identity-stamped at the
        source when the peer runs with ``identity=``; any line still
        missing a ``rank`` label gets one injected here) plus the
        aggregator's own fleet registry as rank ``fleet``. HELP/TYPE
        lines are deduped per family — the exposition format forbids
        repeating them."""
        from deepspeed_tpu.telemetry.sinks import render_prometheus
        st = self._state
        with st.lock:
            texts = [(p.rank if p.rank is not None else p.key,
                      p.metrics_text) for p in st.peers.values()]
        texts.append(("fleet", render_prometheus(
            self.registry, extra_labels={"rank": "fleet"})))
        lines, seen_meta = [], set()
        for rank, text in texts:
            stamp = f'rank="{rank}"'
            for line in text.splitlines():
                if not line.strip():
                    continue
                if line.startswith("#"):
                    if line not in seen_meta:
                        seen_meta.add(line)
                        lines.append(line)
                    continue
                lines.append(_stamp_sample_line(line, stamp))
        return "\n".join(lines) + "\n"

    def fleet_incidents(self):
        """Cross-rank incident correlation over the merged timeline."""
        return _incidents.correlate(self.merged_events(),
                                    job_name=self.job_name)

    def fleet_report(self, name):
        """``/api/fleet/report/<name>``: the FLEET-level document for
        ``slo`` / ``incidents`` / ``status``; otherwise every peer's
        scraped report for *name*, keyed by rank."""
        if name == "slo":
            return self._state.slo.report()
        if name == "incidents":
            return self.fleet_incidents()
        if name == "status":
            return self.status()
        st = self._state
        with st.lock:
            docs = {str(p.rank if p.rank is not None else p.key):
                    p.reports[name] for p in st.peers.values()
                    if name in p.reports}
        if not docs:
            known = sorted({n for p in self.peers()
                            for n in p["providers"]}
                           | {"slo", "incidents", "status"})
            return (404, {"error": f"unknown fleet report {name!r}",
                          "known": known}, "application/json")
        return {"report": name, "peers": docs}

    def status(self):
        """The ``/federation/status`` document."""
        st = self._state
        peers = self.peers()
        n_stale = sum(1 for p in peers if p["status"] != "ok")
        return {
            "schema": FLEET_CONTROL_SCHEMA,
            "enabled": self.enabled,
            "closed": self._closed,
            "job_name": self.job_name,
            "params": {
                "scrape_interval_s": st.scrape_interval_s,
                "timeout_s": st.timeout_s,
                "stale_after_s": st.stale_after_s,
                "events_ring": st.events_ring,
                "run_dir": st.run_dir,
            },
            "n_peers": len(peers),
            "n_stale": n_stale,
            "peers": peers,
            "counters": {
                "scrapes_total": st.scrapes_total,
                "scrape_errors_total": st.scrape_errors_total,
                "events_merged_total": st.events_merged_total,
            },
            "uptime_s": round(
                (_clk.monotonic_us() - st.started_us) / 1e6, 3),
        }

    def last_scrape_age_s(self):
        """Seconds since the last aggregator tick (the obs server's
        /healthz age probe); None before the first."""
        if not self.enabled or self._state.last_tick_us is None:
            return None
        return round(
            (_clk.monotonic_us() - self._state.last_tick_us) / 1e6, 3)

    # ------------------------------------------------------------- routes
    def attach(self, server):
        """Mount the merged views on *server* (an ObsServer). The
        handlers run on the serving thread and read only scraped state
        — a fleet scrape never touches any rank's device."""
        server.add_route("/federation/metrics", self._route_metrics)
        server.add_route("/federation/status",
                         lambda path, q: self.status())
        server.add_route("/api/fleet/events", self._route_events)
        server.add_route("/api/fleet/report/", self._route_report,
                         prefix=True)
        server.register("federation", self.report,
                        age_s_fn=self.last_scrape_age_s)
        return self

    def _route_metrics(self, path, query):
        return (200, self.merged_metrics().encode(),
                "text/plain; version=0.0.4")

    def _route_events(self, path, query):
        cursor = (query.get("cursor") or [None])[0]
        try:
            limit = int((query.get("limit")
                         or [self._state.events_ring])[0])
        except (TypeError, ValueError):
            return (400, {"error": "limit must be an int"},
                    "application/json")
        events = self.merged_events(cursor=cursor)
        truncated = len(events) > limit
        events = events[-limit:]
        return {
            "enabled": True,
            "events": events,
            "n": len(events),
            "truncated": truncated,
            "cursor": _format_cursor(events[-1]) if events
                      else (cursor or ""),
        }

    def _route_report(self, path, query):
        return self.fleet_report(path[len("/api/fleet/report/"):])

    # ------------------------------------------------------------- output
    def report(self):
        """The FLEET_CONTROL.json document."""
        if not self.enabled:
            return {"schema": FLEET_CONTROL_SCHEMA, "enabled": False}
        doc = self.status()
        events = self.merged_events()
        doc["slo"] = self._state.slo.report()
        doc["incidents"] = _incidents.correlate(events,
                                                job_name=self.job_name)
        doc["n_merged_events"] = len(events)
        doc["events_tail"] = events[-256:]
        return doc

    def write_snapshot(self, path=None, force=False, report=None):
        """Throttled FLEET_CONTROL.json write (the monitors' shared
        discipline)."""
        if not self.enabled:
            return None
        path = path or self.snapshot_path
        if path is None:
            return None
        now_s = _clk.monotonic_s()
        if not force and self._last_snapshot_s is not None \
                and now_s - self._last_snapshot_s < 5.0:
            return None
        self._last_snapshot_s = now_s
        doc = report if report is not None else self.report()
        try:
            _chronicle._atomic_write_bytes(
                path, json.dumps(doc, indent=1, default=repr,
                                 allow_nan=False).encode())
        except (OSError, ValueError) as e:
            self._log("[federation] snapshot write failed: %s", e)
            return None
        return path

    def close(self):
        """Stop every worker, persist cursors, final snapshot when the
        fleet saw anything. Idempotent; ``report()`` keeps working."""
        if not self.enabled or self._closed:
            return
        self._closed = True
        self._finalizer()
        _persist_cursors(self._state)
        if self._state.scrapes_total:
            self.write_snapshot(force=True)
        self._state.slo.close()


# ----------------------------------------------------- merge helpers

def _order_key(e):
    return (e.get("t_us", 0), e.get("seq", 0), _rank_key(e.get("rank")))


def _rank_key(rank):
    # ranks are ints for announced peers, strings for static strangers;
    # a mixed fleet must still sort deterministically
    return (0, rank, "") if isinstance(rank, int) else (1, -1, str(rank))


def _format_cursor(e):
    return f"{e.get('t_us', 0)}:{e.get('seq', 0)}:{e.get('rank', '')}"


def _parse_cursor(cursor):
    try:
        t, s, r = str(cursor).split(":", 2)
        try:
            rank = int(r)
        except ValueError:
            rank = r
        return (int(t), int(s), _rank_key(rank))
    except (TypeError, ValueError):
        return (-1, -1, _rank_key(-1))


def _stamp_sample_line(line, stamp):
    """Inject an identity label into one exposition sample line UNLESS
    it already carries a ``rank`` label (the extra_labels fast path —
    peers running with ``identity=`` never take the parse branch)."""
    brace = line.find("{")
    space = line.find(" ")
    if space < 0:
        return line
    if 0 <= brace < space:
        close = line.find("}", brace)
        inner = line[brace + 1:close]
        if "rank=" in inner:
            return line
        merged = f"{inner},{stamp}" if inner else stamp
        return f"{line[:brace + 1]}{merged}{line[close:]}"
    return f"{line[:space]}{{{stamp}}}{line[space:]}"


# --------------------------------------------------------------------- CLI

def render(doc):
    """Human-readable fleet view of a FLEET_CONTROL.json document."""
    if not doc.get("enabled", True):
        return "federation: disabled"
    lines = [f"fleet: {doc.get('n_peers', 0)} peer(s), "
             f"{doc.get('n_stale', 0)} stale, "
             f"{doc.get('n_merged_events', 0)} merged event(s)"]
    for p in doc.get("peers", []):
        age = p.get("last_seen_age_s")
        lines.append(
            f"  rank {p.get('rank')!s:>5} [{p['status']:>5}] "
            f"{p['url']} seen "
            f"{'never' if age is None else f'{age:.1f}s ago'} "
            f"({p['scrapes']} scrape(s), {p['errors']} error(s), "
            f"cursor {p['cursor']})")
    slo_doc = doc.get("slo") or {}
    for name, o in sorted((slo_doc.get("objectives") or {}).items()):
        lines.append(f"  slo {name}: tier {o.get('tier', 'ok').upper()}")
    incs = (doc.get("incidents") or {}).get("incidents", [])
    lines.append(f"  incidents: {len(incs)}")
    for inc in incs:
        rc = inc.get("root_cause") or {}
        lines.append(
            f"    #{inc['id']} [{inc.get('severity') or '-'}] root "
            f"{rc.get('kind')}/{rc.get('rule') or rc.get('chaos') or ''} "
            f"rank {rc.get('rank')} step {rc.get('step')}")
    return "\n".join(lines)


def _simulate_peer(args):
    """One synthetic rank: a REAL ObsServer + RunChronicle + registry,
    announced into the shared run dir — the subprocess harness the
    federation tests and ``--demo`` drive (the PR-11 _simulate_rank
    pattern). Emits step lifecycle + goodput reports; at
    ``--fault-step``, the fault rank chronicles a chaos event (the
    injector self-documents, PR-12) and every OTHER rank fires a
    ``step_time_skew`` anomaly one step later."""
    import time as _time

    from deepspeed_tpu.telemetry.metrics import MetricsRegistry
    from deepspeed_tpu.telemetry.obs_server import ObsServer

    rank = int(args.simulate_peer)
    registry = MetricsRegistry()
    chron = _chronicle.RunChronicle(run_dir=args.run_dir, rank=rank,
                                    job_name=args.job,
                                    max_events=args.chronicle_ring)
    _chronicle.set_chronicle(chron)
    state = {"step": 0, "elapsed": 0.0, "good": 0.0,
             "ttft_bad": 0, "ttft_total": 0}

    def goodput_report():
        return {"schema": "deepspeed_tpu.goodput/1", "enabled": True,
                "elapsed_s": round(state["elapsed"], 6),
                "categories_s": {"device_compute": round(state["good"],
                                                         6)},
                "goodput_fraction": (state["good"] / state["elapsed"]
                                     if state["elapsed"] else None),
                "counters": {"steps_seen": state["step"]}}

    def slo_report():
        return {"schema": "deepspeed_tpu.slo/1", "enabled": True,
                "objectives": {"serving_ttft": {
                    "kind": "latency", "tier": "ok",
                    "totals": {"bad": state["ttft_bad"],
                               "total": state["ttft_total"]}}}}

    srv = ObsServer(registry=registry, port=args.port,
                    identity={"rank": rank})
    srv.announce(args.run_dir, rank=rank, job_name=args.job)
    srv.register("goodput", goodput_report)
    srv.register("slo", slo_report)
    registry.counter("sim_steps_total", "synthetic steps").inc(0)
    if chron.resumed_seq is None:
        chron.emit("lifecycle", "engine", step=0, phase="init")
    else:
        chron.emit("lifecycle", "engine", step=0, phase="elastic_resume",
                   detail=f"resumed after seq {chron.resumed_seq}")
    print(f"PEER_READY rank={rank} url={srv.url}", flush=True)
    step_s = args.step_ms / 1e3
    for _ in range(args.steps):
        _time.sleep(step_s)
        state["step"] += 1
        step = state["step"]
        state["elapsed"] += step_s
        state["good"] += step_s * (1.0 - args.bad_frac)
        state["ttft_total"] += 10
        state["ttft_bad"] += int(10 * args.bad_frac)
        registry.counter("sim_steps_total", "synthetic steps").inc()
        chron.emit("lifecycle", "engine", step=step, phase="step")
        if args.fault_step and step == args.fault_step \
                and rank == args.fault_rank:
            chron.emit("chaos", "chaos", step=step,
                       chaos="sigkill", severity="critical",
                       detail="injected SIGKILL (fault rank)")
        elif args.fault_step and step == args.fault_step + 1 \
                and rank != args.fault_rank:
            # one step AFTER the injection — the observers react to the
            # fault, so the merged axis keeps the causal order
            chron.emit("anomaly", "health", step=step,
                       rule="step_time_skew", severity="warning",
                       detail=f"step time skewed vs rank "
                              f"{args.fault_rank}")
    chron.drain()
    print(f"PEER_DONE rank={rank} seq={chron._seq}", flush=True)
    # keep serving scrapes until the parent is done with us
    _time.sleep(args.linger_s)
    chron.close()
    srv.close()
    return 0


def _spawn_peer(run_dir, rank, steps=40, step_ms=25.0, bad_frac=0.0,
                fault_step=0, fault_rank=-1, linger_s=60.0, job="fed",
                chronicle_ring=16384):
    import subprocess
    import sys
    cmd = [sys.executable, "-m", "deepspeed_tpu.telemetry.federation",
           "--simulate-peer", str(rank), "--run-dir", run_dir,
           "--steps", str(steps), "--step-ms", str(step_ms),
           "--bad-frac", str(bad_frac), "--fault-step", str(fault_step),
           "--fault-rank", str(fault_rank), "--linger-s", str(linger_s),
           "--job", job, "--chronicle-ring", str(chronicle_ring)]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.Popen(cmd, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True, env=env)


def _demo(args):
    """The committed-artifact scenario: N simulated ranks on one run
    dir, a chaos SIGKILL injected on one of them (chronicled by the
    victim, then the process REALLY killed so the fleet view shows a
    stale peer), the others firing ``step_time_skew`` — the aggregator
    merges one ordered timeline, roots the cross-rank incident at the
    fault rank, and writes FLEET_CONTROL.json."""
    import signal as _signal
    import tempfile
    import time as _time

    run_dir = tempfile.mkdtemp(prefix="federation_demo_")
    n = max(3, args.peers)
    fault_rank = n - 1
    fault_step = args.steps // 2
    procs = [
        _spawn_peer(run_dir, r, steps=args.steps, step_ms=args.step_ms,
                    bad_frac=(0.6 if r == 1 else 0.05),
                    fault_step=fault_step, fault_rank=fault_rank,
                    job="federation_demo")
        for r in range(n)]
    agg = FleetAggregator(
        run_dir=run_dir, job_name="federation_demo",
        scrape_interval_s=0.2, timeout_s=2.0,
        stale_after_s=args.step_ms * args.steps / 1e3,
        snapshot_path=os.path.abspath(args.out),
        fast_window_s=1.0, slow_window_s=4.0, eval_interval_s=0.1)
    # let every rank pass the fault step, then REALLY kill the victim —
    # the chaos event is already on its stream (the injector
    # self-documented before dying), and the fleet view must degrade it
    # to stale without blocking the others
    deadline = _clk.monotonic_s() + 60.0
    fault_seen = False
    while _clk.monotonic_s() < deadline and not fault_seen:
        _time.sleep(0.3)
        fault_seen = any(e.get("chaos") == "sigkill"
                         for e in agg.merged_events())
    procs[fault_rank].send_signal(_signal.SIGKILL)
    deadline = _clk.monotonic_s() + 60.0
    while _clk.monotonic_s() < deadline:
        _time.sleep(0.3)
        peers = {p["rank"]: p for p in agg.peers()}
        victim = peers.get(fault_rank)
        others_done = all(
            any(e.get("step") == args.steps and e.get("rank") == r
                for e in agg.merged_events())
            for r in range(n) if r != fault_rank)
        if victim and victim["status"] == "stale" and others_done:
            break
    agg._state.slo.tick(force=True)
    doc = agg.report()
    agg.write_snapshot(force=True, report=doc)
    agg.close()
    for p in procs:
        try:
            p.kill()
            p.wait(timeout=10)
        except Exception:
            pass
    print(render(doc))
    print(f"wrote {args.out}")
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="fleet federation aggregator demo/CLI")
    ap.add_argument("--demo", action="store_true",
                    help="run the N-rank chaos demo and write the "
                         "committed FLEET_CONTROL.json")
    ap.add_argument("--render", metavar="PATH",
                    help="render an existing FLEET_CONTROL.json")
    ap.add_argument("--simulate-peer", type=int, default=None,
                    metavar="RANK", help="run one synthetic rank "
                    "(subprocess harness; used by --demo and tests)")
    ap.add_argument("--run-dir", default=None)
    ap.add_argument("--out", default="FLEET_CONTROL.json")
    ap.add_argument("--peers", type=int, default=3)
    ap.add_argument("--steps", type=int, default=40)
    ap.add_argument("--step-ms", type=float, default=25.0)
    ap.add_argument("--bad-frac", type=float, default=0.0)
    ap.add_argument("--fault-step", type=int, default=0)
    ap.add_argument("--fault-rank", type=int, default=-1)
    ap.add_argument("--linger-s", type=float, default=60.0)
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--job", default="fed")
    ap.add_argument("--chronicle-ring", type=int, default=16384)
    args = ap.parse_args(argv)
    if args.simulate_peer is not None:
        if not args.run_dir:
            ap.error("--simulate-peer requires --run-dir")
        return _simulate_peer(args)
    if args.demo:
        return _demo(args)
    if args.render:
        with open(args.render) as f:
            print(render(json.load(f)))
        return 0
    ap.error("one of --demo / --render / --simulate-peer is required")


if __name__ == "__main__":
    raise SystemExit(main())
