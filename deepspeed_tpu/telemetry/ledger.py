"""Goodput ledger — wall-clock attribution + input-stall forensics.

The cost explorer (PR 2) explains what a step *costs* and the health
observatory (PR 3) whether training is *numerically healthy*; this module
explains **where the wall-clock goes**. Every second of host wall time
since the ledger armed is decomposed into named categories:

==================  =======================================================
``device_compute``  host blocked waiting on device results (the print-
                    cadence loss fetch, health-stats fetch, the
                    wall_clock_breakdown phase syncs) — the device was
                    the bottleneck, which is GOOD time
``host_dispatch``   executing the train loop's Python: tracing, dispatch,
                    bookkeeping (also good — steps are being made)
``compile``         XLA backend compilation (fed by the compile watch's
                    ``jax.monitoring`` listener; persistent-cache hits
                    arrive as negative durations and are skipped)
``input_wait``      blocked in ``next(data_iter)`` — an input-bound run
``checkpoint_save`` / ``checkpoint_load`` — checkpoint I/O pauses
``eval``            evaluation batches
``overflow_skipped`` steps burned by an fp16 overflow skip (the step's
                    wall time is *re-classified* here by the engine)
``unattributed``    the residual — categories ALWAYS sum to elapsed wall
                    time by construction (the residual is what is left)
==================  =======================================================

Attribution is a nesting-aware interval stack (:meth:`GoodputLedger.
attribute`): a nested interval's time is excluded from its parent's
self-time, so wrapping ``next(data_iter)`` inside the step wrapper books
the wait to ``input_wait``, not twice. Everything is host-side wall-clock
arithmetic — the ledger NEVER touches the device and adds zero
host<->device syncs (guarded in ``tests/perf/telemetry_overhead.py``).

Escalation mirrors the health observatory: at each window ``tick`` (the
engine drives it at ``telemetry.goodput.cadence``, default
``steps_per_print``) the per-window breakdown lands in a ring buffer and
the rules run — ``input_stall`` (window ``input_wait`` fraction over
threshold) and ``unattributed_residual``. A firing rule warns once,
snapshots ``GOODPUT.json`` (ring + verdict naming the dominant badput
category), and can trigger ONE bounded programmatic ``jax.profiler``
capture (``start_trace``/``stop_trace`` around the next N steps,
rate-limited per run) so the evidence is collected *in the failing run*.

CLI: ``python -m deepspeed_tpu.telemetry.ledger --render GOODPUT.json``
pretty-prints a snapshot; ``--demo`` builds a tiny engine, injects a
sleep into the data iterator and writes the resulting ledger (the
committed repo-root ``GOODPUT.json`` example).
"""

import glob
import json
import os
import shutil
import threading
import time
from collections import deque

from deepspeed_tpu.telemetry import chronicle as _chronicle
from deepspeed_tpu.telemetry import clock as _clk
from deepspeed_tpu.telemetry import escalation
from deepspeed_tpu.utils.logging import logger

GOODPUT_SCHEMA = "deepspeed_tpu.goodput/1"

CATEGORIES = (
    "device_compute", "compile", "input_wait", "host_dispatch",
    "checkpoint_save", "checkpoint_load", "eval", "overflow_skipped",
    "unattributed",
)
# the goodput numerator: time spent making training progress. Everything
# else — compile, input waits, checkpoint pauses, eval, burned steps and
# the unexplained residual — is badput.
GOOD_CATEGORIES = frozenset({"device_compute", "host_dispatch"})

RULE_SEVERITY = {
    "input_stall": "warning",
    "unattributed_residual": "watch",
}


class _NullAttr:
    """Shared no-op interval for the disabled ledger (the hot path)."""
    __slots__ = ()
    category = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_ATTR = _NullAttr()

# Per-thread attribution mute. The ledger decomposes the MAIN thread's
# wall clock; a background pipeline (runtime/prefetch.py workers) running
# the same instrumented iterators would book its own concurrent seconds
# into the shared totals, over-counting the categories and driving the
# unattributed residual negative. Worker threads wrap their pulls in
# ``suppress_attribution()`` — overlapped input work books NOTHING, which
# is exactly the ledger's contract (the consumer's near-zero ``next()``
# wait is the real input_wait).
_SUPPRESS_TLS = threading.local()


def _suppressed():
    return getattr(_SUPPRESS_TLS, "on", False)


class suppress_attribution:
    """Context manager muting ledger attribution on the CURRENT thread
    (re-entrant; applies to every ledger instance, global or direct)."""

    def __enter__(self):
        self._prev = getattr(_SUPPRESS_TLS, "on", False)
        _SUPPRESS_TLS.on = True
        return self

    def __exit__(self, *exc):
        _SUPPRESS_TLS.on = self._prev
        return False


class _Attr:
    """One open attribution interval. ``category`` is mutable until exit —
    the engine re-classifies a finished-but-overflowed step's interval to
    ``overflow_skipped`` before it closes."""
    __slots__ = ("_ledger", "category", "_t0", "_child")

    def __init__(self, ledger, category):
        self._ledger = ledger
        self.category = category
        self._child = 0.0

    def __enter__(self):
        self._t0 = self._ledger._clock()
        self._ledger._stack().append(self)
        return self

    def __exit__(self, *exc):
        t1 = self._ledger._clock()
        stack = self._ledger._stack()
        if stack and stack[-1] is self:
            stack.pop()
        else:                      # unbalanced exit: drop up to self
            while stack and stack[-1] is not self:
                stack.pop()
            if stack:
                stack.pop()
        dt = max(0.0, t1 - self._t0)
        self._ledger._add(self.category, max(0.0, dt - self._child))
        if stack:
            stack[-1]._child += dt
        return False


class GoodputIterator:
    """Wrap any iterator so time blocked in ``next()`` is attributed to
    ``input_wait``. With no explicit ledger the process-global one is
    resolved per call (so a later ``set_ledger`` takes effect)."""
    __slots__ = ("_it", "_ledger")

    def __init__(self, it, ledger=None):
        self._it = iter(it)
        self._ledger = ledger

    def __iter__(self):
        return self

    def __next__(self):
        led = self._ledger if self._ledger is not None else _GLOBAL
        with led.attribute("input_wait"):
            return next(self._it)


def profiler_available():
    """Can this jax do programmatic trace capture?"""
    try:
        from jax import profiler
        return (hasattr(profiler, "start_trace")
                and hasattr(profiler, "stop_trace"))
    except Exception:
        return False


def _start_trace(logdir):            # split out for tests to monkeypatch
    from jax import profiler
    profiler.start_trace(logdir)


def _stop_trace():
    from jax import profiler
    profiler.stop_trace()


class GoodputLedger:
    """Host-side wall-clock ledger. See the module docstring.

    Invariant: ``sum(totals().values()) == elapsed()`` — ``unattributed``
    is computed as the residual, never measured. Disabled instances are
    inert: ``attribute`` returns one shared no-op context manager and
    every other surface returns immediately.
    """

    SNAPSHOT_MIN_INTERVAL_S = 5.0
    MAX_ANOMALY_HISTORY = 100

    def __init__(self, enabled=True, job_name="",
                 snapshot_path="GOODPUT.json", cadence=0,
                 input_wait_frac=0.25, unattributed_frac=0.5,
                 warmup_windows=1, window_ring=128,
                 profiler_capture=True, profiler_capture_steps=5,
                 profiler_max_captures=1, profiler_dir="goodput_profile",
                 keep_raw_traces=2,
                 registry=None, on_escalate=None, on_anomaly=None,
                 log_fn=None):
        self.enabled = bool(enabled)
        self.job_name = job_name
        self.snapshot_path = snapshot_path
        self.cadence = int(cadence)
        self.input_wait_frac = float(input_wait_frac)
        self.unattributed_frac = float(unattributed_frac)
        self.warmup_windows = int(warmup_windows)
        self.profiler_capture = bool(profiler_capture)
        self.profiler_capture_steps = int(profiler_capture_steps)
        self.profiler_max_captures = int(profiler_max_captures)
        self.profiler_dir = profiler_dir
        self.keep_raw_traces = int(keep_raw_traces)
        self.registry = registry
        self.on_escalate = on_escalate
        self.on_anomaly = on_anomaly
        self.breakdown_fn = None     # engine wires wall_clock_breakdown
        self._log = log_fn or logger.warning
        # the shared telemetry axis (clock.py): ledger windows must be
        # joinable against chronicle events with no wall/monotonic mix
        self._clock = _clk.monotonic_s
        if not self.enabled:
            return

        self._lock = threading.Lock()
        self._tls = threading.local()
        self._t_start = self._clock()
        self._totals = {c: 0.0 for c in CATEGORIES if c != "unattributed"}
        # good seconds booked since the last note_step: an overflow-
        # skipped step transfers them to overflow_skipped, so the burned
        # micro-batch work of a gas>1 step doesn't inflate goodput
        self._step_good = {c: 0.0 for c in GOOD_CATEGORIES}
        self.ring = deque(maxlen=max(1, int(window_ring)))
        self.anomalies = []
        self.rule_counts = {}
        self.steps_seen = 0
        self.overflow_steps = 0
        self.windows_closed = 0      # cadence (unforced) windows only
        self._window_seq = 0         # every window, forced included
        self.last_window = None
        self._win_totals = dict(self._totals)
        self._win_elapsed = 0.0
        self._snapshots_written = 0
        self._last_snapshot_t = float("-inf")
        self._capture_active = False
        self._captures_done = 0
        self._capture_stop_after = -1
        self._capture_warned = False
        self._last_capture_report = None
        self._last_capture_top = None

    @classmethod
    def from_config(cls, tconfig, output_path="telemetry/", job_name="",
                    registry=None, on_escalate=None, on_anomaly=None):
        """Build from a parsed ``DeepSpeedTelemetryConfig``'s
        ``goodput_*`` fields."""
        snap = getattr(tconfig, "goodput_snapshot_file", "") \
            or "GOODPUT.json"
        if not os.path.isabs(snap):
            snap = os.path.join(output_path or ".", snap)
        pdir = getattr(tconfig, "goodput_profiler_dir", "") \
            or os.path.join(output_path or ".", "goodput_profile")
        return cls(
            enabled=True,
            job_name=job_name,
            snapshot_path=snap,
            cadence=getattr(tconfig, "goodput_cadence", 0),
            input_wait_frac=getattr(tconfig, "goodput_input_wait_frac",
                                    0.25),
            unattributed_frac=getattr(tconfig, "goodput_unattributed_frac",
                                      0.5),
            warmup_windows=getattr(tconfig, "goodput_warmup_windows", 1),
            window_ring=getattr(tconfig, "goodput_window_ring", 128),
            profiler_capture=getattr(tconfig, "goodput_profiler_capture",
                                     True),
            profiler_capture_steps=getattr(
                tconfig, "goodput_profiler_capture_steps", 5),
            profiler_max_captures=getattr(
                tconfig, "goodput_profiler_max_captures", 1),
            profiler_dir=pdir,
            keep_raw_traces=getattr(tconfig, "anatomy_keep_raw_traces", 2),
            registry=registry, on_escalate=on_escalate,
            on_anomaly=on_anomaly)

    # ---------------------------------------------------------- attribution
    def _stack(self):
        s = getattr(self._tls, "stack", None)
        if s is None:
            s = self._tls.stack = []
        return s

    def _add(self, category, seconds):
        if seconds <= 0.0:
            return
        with self._lock:
            self._totals[category] += seconds
            if category in GOOD_CATEGORIES:
                self._step_good[category] += seconds

    def attribute(self, category):
        """Context manager attributing the interval's SELF time (nested
        intervals excluded) to *category*."""
        if not self.enabled or _suppressed():
            return _NULL_ATTR
        return _Attr(self, category)

    def add_seconds(self, category, seconds):
        """Book *seconds* (measured elsewhere, e.g. a jax.monitoring
        compile duration) to *category*, and as child time of the
        innermost open interval so its self-time shrinks — the seconds
        were spent INSIDE it."""
        if not self.enabled or seconds <= 0.0:
            return
        self._add(category, float(seconds))
        stack = self._stack()
        if stack:
            stack[-1]._child += float(seconds)

    def observe_compile(self, seconds):
        """Compile-watch hook: one XLA backend-compile duration.
        Negative durations are persistent-cache HITS — no wall time was
        actually spent, so they are skipped."""
        if seconds > 0:
            self.add_seconds("compile", seconds)

    def reclassify_open(self, to_category):
        """Re-label the innermost open GOOD-category interval (the step
        wrapper) — the engine calls this when the step it just ran turned
        out to be an fp16 overflow skip. Returns True when an interval
        was found."""
        if not self.enabled:
            return False
        for attr in reversed(self._stack()):
            if attr.category in GOOD_CATEGORIES:
                attr.category = to_category
                return True
        return False

    # -------------------------------------------------------------- reading
    def elapsed(self):
        if not self.enabled:
            return 0.0
        return max(0.0, self._clock() - self._t_start)

    def totals(self):
        """Per-category seconds including the ``unattributed`` residual;
        sums to ``elapsed()`` by construction."""
        if not self.enabled:
            return {c: 0.0 for c in CATEGORIES}
        elapsed = self.elapsed()
        with self._lock:
            out = dict(self._totals)
        out["unattributed"] = elapsed - sum(out.values())
        return out

    @staticmethod
    def goodput_fraction(totals, elapsed):
        if elapsed <= 0:
            return None
        return sum(totals[c] for c in GOOD_CATEGORIES) / elapsed

    # ------------------------------------------------------------- per step
    def mark_step_begin(self):
        """Reset the per-step good-seconds accumulator at a step
        BOUNDARY. The previous step's wrapper/fetch intervals close
        after its ``note_step`` ran, so their seconds land in the
        accumulator afterwards — without this reset an overflow at step
        N+1 would sweep step N's trailing good time into
        ``overflow_skipped``."""
        if not self.enabled:
            return
        with self._lock:
            for c in self._step_good:
                self._step_good[c] = 0.0

    def note_step(self, step, overflowed=False):
        """Host-only per-step facts (no device sync): overflow-burned
        steps, and the stop condition of an active profiler capture.

        An overflowed step transfers the good seconds booked since the
        previous step into ``overflow_skipped``: with gas>1 the micro
        forward/backward intervals already CLOSED before the host could
        see the overflow, and ``reclassify_open`` only reaches the
        still-open wrapper — without the transfer a run skipping every
        step would still report its burned work as goodput."""
        if not self.enabled:
            return
        self.steps_seen += 1
        if overflowed:
            self.overflow_steps += 1
        with self._lock:
            if overflowed:
                moved = sum(self._step_good.values())
                if moved > 0:
                    for c, s in self._step_good.items():
                        self._totals[c] -= s
                    self._totals["overflow_skipped"] += moved
            for c in self._step_good:
                self._step_good[c] = 0.0
        if self._capture_active and step >= self._capture_stop_after:
            self._stop_capture()

    # -------------------------------------------------------------- windows
    def tick(self, step=None, force=False):
        """Close the current window: ring-append its per-category
        breakdown and (periodic ticks only) run the badput rules. The
        engine drives this at the goodput cadence; ``force=True`` is the
        report path closing a partial window without running rules."""
        if not self.enabled:
            return None
        elapsed = self.elapsed()
        totals = self.totals()
        dur = elapsed - self._win_elapsed
        if dur <= 0.0:
            return None
        cats = {c: round(totals[c] - self._win_totals.get(c, 0.0), 6)
                for c in CATEGORIES}
        gf = self.goodput_fraction(
            {c: cats[c] for c in GOOD_CATEGORIES}, dur)
        window = {
            "index": self._window_seq,
            "end_step": step,
            "start_s": round(self._win_elapsed, 6),
            "dur_s": round(dur, 6),
            "categories_s": cats,
            "goodput_fraction": round(gf, 6) if gf is not None else None,
        }
        if force:
            # report-path partial window: marked, kept out of the
            # cadence count so repeated reports can neither arm the
            # rules early nor shrink the windows they judge
            window["forced"] = True
        self._win_totals = totals
        self._win_elapsed = elapsed
        self._window_seq += 1
        self.ring.append(window)
        self.last_window = window
        self._publish(totals, elapsed, window)
        chron = _chronicle.get_chronicle()
        if chron.enabled:
            # integer-µs category diffs so an incident's goodput cost is
            # computable (and re-addable) from chronicle events alone
            chron.emit(
                "goodput_window", source="goodput", step=step,
                index=window["index"],
                dur_us=int(round(dur * 1e6)),
                categories_us={c: int(round(s * 1e6))
                               for c, s in cats.items()},
                goodput_fraction=window["goodput_fraction"],
                forced=bool(force) or None)
        if not force:
            self.windows_closed += 1
            if self.windows_closed > self.warmup_windows:
                self._check_rules(window, step)
        return window

    def _check_rules(self, window, step):
        dur = window["dur_s"]
        anoms = []
        iw = window["categories_s"]["input_wait"] / dur
        if iw > self.input_wait_frac:
            anoms.append({
                "rule": "input_stall", "step": step,
                "severity": RULE_SEVERITY["input_stall"],
                "fraction": round(iw, 4),
                "detail": f"{iw:.0%} of the last {dur:.3g}s window was "
                          f"spent blocked in next(data_iter) "
                          f"(threshold {self.input_wait_frac:.0%}) — the "
                          f"input pipeline is starving the device"})
        un = window["categories_s"]["unattributed"] / dur
        if un > self.unattributed_frac:
            anoms.append({
                "rule": "unattributed_residual", "step": step,
                "severity": RULE_SEVERITY["unattributed_residual"],
                "fraction": round(un, 4),
                "detail": f"{un:.0%} of the last {dur:.3g}s window is "
                          f"unattributed host time (threshold "
                          f"{self.unattributed_frac:.0%}) — something "
                          f"outside the instrumented paths is eating "
                          f"wall-clock"})
        if anoms:
            self._escalate(anoms, step)

    def _publish(self, totals, elapsed, window):
        """Gauges/counters into the metrics registry (visible through the
        JSONL/Prometheus MonitorMaster sinks). Host-only."""
        reg = self.registry
        if reg is None:
            return
        gf = self.goodput_fraction(totals, elapsed)
        if gf is not None:
            reg.gauge("goodput_fraction",
                      "fraction of wall time spent making training "
                      "progress (device_compute + host_dispatch)").set(gf)
        wgf = window.get("goodput_fraction")
        if wgf is not None and not window.get("forced"):
            # partial report-path windows must not pollute the gauge;
            # the badput counters below still take their deltas (the
            # seconds are real and must not vanish from the series)
            reg.gauge("goodput_window_fraction",
                      "goodput fraction of the last closed window").set(wgf)
        for c in CATEGORIES:
            if c in GOOD_CATEGORIES:
                continue
            delta = window["categories_s"][c]
            if delta > 0:
                reg.counter("badput_seconds_total",
                            "wall-clock seconds NOT spent making training "
                            "progress, by category",
                            labels={"category": c}).inc(delta)

    # ------------------------------------------------------------ escalation
    def _escalate(self, anoms, step):
        # the shared protocol (telemetry/escalation.py) + the ledger's
        # step 5: a first-time rule starts the one-shot profiler capture
        escalation.escalate(
            self, anoms, tag="goodput",
            counter="goodput_anomalies_total",
            counter_help="goodput-ledger badput rule firings",
            step=step,
            after_snapshot=lambda any_first: (
                self._maybe_start_capture(step) if any_first else None))

    # ------------------------------------------------------ profiler capture
    def _maybe_start_capture(self, step):
        """Start ONE bounded programmatic jax.profiler capture so the
        evidence for the badput verdict is collected in the failing run.
        Rate-limited (``profiler_max_captures``, default 1/run)."""
        if (not self.profiler_capture or self._capture_active
                or self._captures_done >= self.profiler_max_captures):
            return False
        try:
            os.makedirs(self.profiler_dir, exist_ok=True)
            _start_trace(self.profiler_dir)
        except Exception as e:
            if not self._capture_warned:
                self._capture_warned = True
                self._log("[goodput] programmatic profiler capture "
                          "unavailable (%s); continuing without it", e)
            self.profiler_capture = False
            return False
        self._capture_active = True
        self._captures_done += 1
        self._capture_stop_after = (step or self.steps_seen) \
            + self.profiler_capture_steps
        self._log("[goodput] jax.profiler capture started -> %s "
                  "(stops after step %d)", self.profiler_dir,
                  self._capture_stop_after)
        return True

    def _stop_capture(self):
        if not self._capture_active:
            return
        self._capture_active = False
        try:
            _stop_trace()
        except Exception as e:
            logger.warning("[goodput] stop_trace failed: %s", e)
            return
        self._postprocess_capture()

    def _postprocess_capture(self):
        """Raw trace dirs used to dead-end on disk (write-only: nothing
        in the repo could read them back). Post-process the capture into
        an attributed step-anatomy summary, reference it from the
        escalation entry that triggered it, and cap retained raw dirs."""
        try:
            from deepspeed_tpu.telemetry import step_anatomy
            report = step_anatomy.summarize_capture(self.profiler_dir)
            if report is not None:
                path = os.path.join(self.profiler_dir,
                                    "CAPTURE_ANATOMY.json")
                step_anatomy.write_report(report, path)
                cats = {c: s for c, s in
                        (report.get("categories_s") or {}).items()
                        if c != "idle_gap"}
                top = max(cats, key=cats.get) if any(
                    v > 0 for v in cats.values()) else None
                self._last_capture_report = path
                self._last_capture_top = top
                if self.anomalies:
                    # the newest anomaly is the one whose escalation
                    # started this capture (captures are 1-at-a-time)
                    self.anomalies[-1]["capture_report"] = path
                    self.anomalies[-1]["capture_top_category"] = top
                self._log("[goodput] capture post-processed -> %s "
                          "(top device category: %s)", path, top)
                self.write_snapshot(force=True)
            self._prune_raw_traces()
        except Exception as e:   # forensics must never kill a step
            logger.warning("[goodput] capture post-process failed: %s", e)

    def _prune_raw_traces(self, keep=None):
        """Delete all but the newest *keep* raw profiler run dirs under
        ``profiler_dir/plugins/profile/`` (the summary JSON survives)."""
        keep = self.keep_raw_traces if keep is None else int(keep)
        runs = glob.glob(os.path.join(
            self.profiler_dir, "plugins", "profile", "*"))
        runs = [r for r in runs if os.path.isdir(r)]
        runs.sort(key=os.path.getmtime, reverse=True)
        for stale in runs[keep:]:
            shutil.rmtree(stale, ignore_errors=True)

    # --------------------------------------------------------------- outputs
    def verdict(self, totals=None, elapsed=None):
        if not self.enabled:
            return {"status": "disabled"}
        totals = totals if totals is not None else self.totals()
        elapsed = elapsed if elapsed is not None else self.elapsed()
        # dominant badput from the POST-warmup windows when there are
        # any: the verdict is about steady state, and the one-time
        # startup compile would otherwise mask a persistent input stall.
        # Warmup is counted in CADENCE windows — forced (report-path)
        # partial windows ride along once warmup has passed.
        steady, cadence_seen = [], 0
        for w in self.ring:
            if not w.get("forced"):
                cadence_seen += 1
                if cadence_seen > self.warmup_windows:
                    steady.append(w)
            elif cadence_seen >= self.warmup_windows:
                steady.append(w)
        source = totals
        if steady:
            source = {c: sum(w["categories_s"][c] for w in steady)
                      for c in CATEGORIES}
        bad = {c: source[c] for c in CATEGORIES
               if c not in GOOD_CATEGORIES}
        dominant = max(bad, key=bad.get) if any(
            v > 0 for v in bad.values()) else None
        if not self.windows_closed:
            status = "unknown"
        elif self.rule_counts:
            status = "degraded"
        else:
            status = "healthy"
        gf = self.goodput_fraction(totals, elapsed)
        return {"status": status,
                "dominant_badput": dominant,
                "goodput_fraction": round(gf, 6) if gf is not None
                else None}

    def report(self):
        """The full ledger dict (what ``GOODPUT.json`` holds)."""
        if not self.enabled:
            return {"schema": GOODPUT_SCHEMA, "enabled": False}
        totals = self.totals()
        elapsed = self.elapsed()
        breakdown = None
        if self.breakdown_fn is not None:
            try:
                breakdown = self.breakdown_fn()
            except Exception:
                breakdown = None
        verdict = self.verdict(totals, elapsed)
        return {
            "schema": GOODPUT_SCHEMA,
            "enabled": True,
            "job_name": self.job_name,
            "elapsed_s": round(elapsed, 6),
            "categories_s": {c: round(totals[c], 6) for c in CATEGORIES},
            "goodput_fraction": verdict["goodput_fraction"],
            "verdict": verdict,
            "thresholds": {
                "input_wait_frac": self.input_wait_frac,
                "unattributed_frac": self.unattributed_frac,
                "warmup_windows": self.warmup_windows,
            },
            "counters": {
                "steps_seen": self.steps_seen,
                "overflow_steps": self.overflow_steps,
                "windows_closed": self.windows_closed,
                "anomaly_counts": dict(self.rule_counts),
            },
            "profiler": {
                "available": profiler_available(),
                "capture_enabled": self.profiler_capture,
                "captures": self._captures_done,
                "active": self._capture_active,
                "capture_steps": self.profiler_capture_steps,
                "max_captures": self.profiler_max_captures,
                "dir": self.profiler_dir,
                "last_capture_report": self._last_capture_report,
                "last_capture_top_category": self._last_capture_top,
            },
            "anomalies": list(self.anomalies),
            "windows": list(self.ring),
            "wall_clock_breakdown": breakdown,
        }

    def write_snapshot(self, path=None, force=False, report=None):
        """Write ``GOODPUT.json`` (throttled like the health snapshot —
        re-serialising the ring every anomaly must not stall the train
        thread). ``report`` lets a caller that already built the report
        dict reuse it instead of paying a second O(ring) pass."""
        if not self.enabled:
            return None
        if not force and (self._clock() - self._last_snapshot_t
                          < self.SNAPSHOT_MIN_INTERVAL_S):
            return None
        self._last_snapshot_t = self._clock()
        path = path or self.snapshot_path
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(report if report is not None else self.report(),
                      f, indent=1, default=repr, allow_nan=False)
        self._snapshots_written += 1
        return path

    def close(self):
        """Stop any live capture, final snapshot when there is something
        to explain (an anomaly fired), then DISABLE the ledger: engines
        hold a direct reference besides the process-global one, and a
        closed ledger must not keep ticking, snapshotting or starting
        profiler captures with nothing left to stop them."""
        if not self.enabled:
            return
        self._stop_capture()
        if self.anomalies:
            self.write_snapshot(force=True)
        self.enabled = False


# Process-global ledger, mirroring tracer/metrics: library code
# (dataloader, checkpoint_io, compile watch) attributes into whichever
# ledger is installed; the default is disabled (shared no-op intervals).
_DISABLED = GoodputLedger(enabled=False)
_GLOBAL = _DISABLED


def get_ledger():
    return _GLOBAL


def set_ledger(ledger):
    """Install *ledger* as the process-global default; returns the old."""
    global _GLOBAL
    old, _GLOBAL = _GLOBAL, ledger
    return old


def reset_ledger(if_current=None):
    """Restore the disabled default (only when *if_current* is still the
    installed one, so a newer engine's ledger is not clobbered)."""
    global _GLOBAL
    if if_current is None or _GLOBAL is if_current:
        _GLOBAL = _DISABLED


# --------------------------------------------------------------------- CLI

def render(report):
    """Human-readable rendering of a GOODPUT.json report dict."""
    lines = []
    v = report.get("verdict") or {}
    gf = report.get("goodput_fraction")
    lines.append(
        f"goodput: {v.get('status', '?').upper()}"
        + (f"  {gf:.1%} of wall-clock is training progress"
           if isinstance(gf, (int, float)) else "")
        + (f"  (job {report['job_name']})" if report.get("job_name")
           else ""))
    if v.get("dominant_badput"):
        lines.append(f"  dominant badput: {v['dominant_badput']}")
    elapsed = report.get("elapsed_s", 0) or 0
    cats = report.get("categories_s", {})
    for c in CATEGORIES:
        s = cats.get(c, 0.0)
        if s <= 0:
            continue
        frac = s / elapsed if elapsed else 0.0
        bar = "#" * int(round(frac * 40))
        lines.append(f"  {c:18s} {s:9.3f}s  {frac:6.1%}  {bar}")
    c = report.get("counters", {})
    lines.append(f"  steps {c.get('steps_seen', 0)}, windows "
                 f"{c.get('windows_closed', 0)}, overflow-skipped "
                 f"{c.get('overflow_steps', 0)}")
    for a in report.get("anomalies", []):
        lines.append(f"  [{a.get('severity', '?'):8s}] step "
                     f"{a.get('step')}: {a.get('rule')} — "
                     f"{a.get('detail')}")
    if not report.get("anomalies"):
        lines.append("  no badput anomalies recorded")
    prof = report.get("profiler") or {}
    if prof.get("captures"):
        lines.append(f"  profiler captures: {prof['captures']} -> "
                     f"{prof.get('dir')}")
    bd = report.get("wall_clock_breakdown")
    if bd:
        for name, row in bd.get("phases", {}).items():
            lines.append(f"  timer {name}: {row.get('total_ms', 0):.1f} ms "
                         f"over {row.get('count', 0)} intervals")
    return "\n".join(lines)


class _StallingIterator:
    """Demo helper: a repeating loader whose every ``next`` first sleeps —
    the injected input stall the ledger must attribute to input_wait."""

    def __init__(self, loader, stall_s):
        from deepspeed_tpu.runtime.dataloader import RepeatingLoader
        self._it = RepeatingLoader(loader)
        self.stall_s = stall_s

    def __iter__(self):
        return self

    def __next__(self):
        time.sleep(self.stall_s)
        return next(self._it)


def _demo(args):
    """Tiny engine + injected input stall -> the committed repo-root
    GOODPUT.json example (input_wait must dominate the verdict)."""
    if args.devices:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.devices}")
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import deepspeed_tpu
    from deepspeed_tpu.models.simple import SimpleModel, random_dataset, \
        sample_batch
    from deepspeed_tpu.utils import groups

    groups.destroy()
    groups.initialize()
    hidden = 32
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=hidden, nlayers=2),
        config={
            "train_batch_size": 8,
            "steps_per_print": 4,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
            "telemetry": {"enabled": True, "trace": False,
                          "jsonl": False, "prometheus": False,
                          # capture off for the COMMITTED example: the
                          # one-time jax.profiler start cost (~seconds of
                          # TF profiler init) would dwarf the injected
                          # stall and muddy the category story. Pass
                          # --capture to see the real escalation path.
                          "goodput": {"enabled": True, "cadence": 2,
                                      "warmup_windows": 1,
                                      "profiler_capture": args.capture,
                                      "profiler_capture_steps": 2,
                                      "snapshot_file": os.path.abspath(
                                          args.out)}},
        },
        sample_batch=sample_batch(8, hidden))
    loader = engine.deepspeed_io(random_dataset(64, hidden))
    it = _StallingIterator(loader, args.stall_ms / 1e3)
    for _ in range(args.steps):
        engine.train_batch(data_iter=it)
    report = engine.goodput_report(write=True)
    print(render(report))
    print(f"\nwrote {args.out}")
    return 0


def main(argv=None):
    import argparse
    p = argparse.ArgumentParser(
        prog="python -m deepspeed_tpu.telemetry.ledger",
        description="Render a GOODPUT.json snapshot, or run the goodput "
                    "demo (tiny engine + injected input stall)")
    p.add_argument("--render", metavar="GOODPUT.json",
                   help="pretty-print an existing snapshot and exit")
    p.add_argument("--demo", action="store_true",
                   help="build a tiny engine, inject a sleep into the "
                        "data iterator, write the ledger snapshot")
    p.add_argument("--steps", type=int, default=16)
    p.add_argument("--stall-ms", type=float, default=30.0)
    p.add_argument("--capture", action="store_true",
                   help="demo: also trigger the real on-anomaly "
                        "jax.profiler capture (its one-time start cost "
                        "lands in the enclosing step's category)")
    p.add_argument("--devices", type=int, default=8,
                   help="virtual CPU devices for the demo (0 = existing)")
    p.add_argument("--out", default="GOODPUT.json")
    args = p.parse_args(argv)
    if args.render:
        with open(args.render) as f:
            print(render(json.load(f)))
        return 0
    if args.demo:
        return _demo(args)
    p.print_help()
    return 2


if __name__ == "__main__":
    import sys
    sys.exit(main())
