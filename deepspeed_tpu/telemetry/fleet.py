"""Fleet flight recorder — cross-rank telemetry aggregation + sentinels.

Every instrument so far — tracer (PR 1), cost explorer (PR 2), health
observatory (PR 3), goodput ledger (PR 4), serving observatory (PR 9) —
sees exactly ONE process. The moment the mesh spans hosts, the dominant
failure modes are *relative*: one straggler host serializing every
collective, one replica silently diverging, one rank's checkpoint persist
stalling the manifest barrier. This module is the cross-rank layer, three
pieces sharing one window clock:

* **Per-rank shipping** (:class:`FleetShipper`): EVERY process writes
  rank-tagged window records into a shared run directory —
  ``<run_dir>/rank_00007/win_00000042.json`` — using the PR-7
  tmp+fsync+atomic-rename discipline, so the aggregator never reads a
  torn file (``*.tmp.*`` siblings are invisible to the scanner). A record
  is pure host data: window wall time, per-step wall stats, input-wait /
  checkpoint seconds, the goodput ledger's category breakdown (exact
  integer microseconds — ``sum(categories_us) == wall_us`` BY
  CONSTRUCTION, the residual is computed, never measured), the last
  health sample, recent serving SLO windows when a serving engine runs in
  this process, and the desync checksum rows. Shipping happens on a
  background writer thread (``suppress_attribution`` — the PR-5
  discipline — and never a device handle, so the shipper thread can
  neither skew the ledger nor sync the device); the hot loop pays two
  clock reads and a dict update per step.

* **Rank-0 aggregation + sentinels** (:class:`FleetMonitor`): merges
  windows across ranks (join key = the per-rank window sequence number,
  identical across ranks because every rank ships at the same step
  cadence) and runs the cross-rank rules —

  ======================== ================================================
  ``step_time_skew``       straggler attribution: in a synchronous data-
                           parallel step every rank waits for the slowest,
                           so ``(slow-fast)/slow`` of fleet step time is
                           straggler-induced badput ≈ what the fast ranks
                           book as collective wait. Names the slow rank
                           AND what that rank's own ledger says it was
                           doing (input_wait -> input-bound host;
                           device_compute -> genuinely slow chip).
  ``input_wait_skew``      one rank's input pipeline starving while the
                           others overlap fine (a per-host storage/DNS
                           problem, invisible in any single-rank ledger).
  ``checkpoint_persist_skew`` one rank's persist dominating the save: the
                           PR-7 manifest waits for every rank's shard
                           files, so the slowest persist gates the tag.
  ``desync``               the **desync sentinel** (critical): per-bucket
                           parameter checksums disagree across data-
                           parallel replicas — silent divergence, with
                           module-bucket provenance (the PR-3
                           ``build_bucket_spec`` buckets).
  ======================== ================================================

  Escalation is the established protocol: one warning log per rule →
  throttled ``FLEET_HEALTH.json`` snapshot (forced for first-time rules)
  → trace-flush hook + ``fleet_anomalies_total{rule=...}``.

* **Flight recorder**: ``engine.fleet_report(write=True)`` and the CLI
  (``--render`` / ``--demo`` / ``--aggregate`` / ``--merge-traces``)
  produce the unified artifact; ``merge_traces`` concatenates per-rank
  Chrome traces into one file with per-rank *process* lanes (the Tracer's
  process-label metadata keeps rank identity through the merge).

The desync checksum itself is traced device code (one cheap reduction per
module bucket, per-replica rows extracted via ``shard_map`` on the data
axis); it lives in :func:`build_desync_checksum_fn` behind a
function-local jax import. Everything else in this module is **pure host
bookkeeping** — no jax import at module scope (statically guarded in
tests/perf/telemetry_overhead.py, the serving_observatory pattern), so
the shipper cannot add device syncs to any step.

CLI: ``python -m deepspeed_tpu.telemetry.fleet --render FLEET_HEALTH.json``
pretty-prints a snapshot; ``--demo`` runs the committed-example scenario
(one real dp=8 engine rank with an injected 20 ms input stall and a
perturbed replica + three subprocess-simulated ranks) and writes the
repo-root ``FLEET_HEALTH.json``.
"""

import json
import os
import threading
import time
import weakref
from collections import deque

from deepspeed_tpu.telemetry import clock as _clk
from deepspeed_tpu.telemetry import escalation
from deepspeed_tpu.telemetry.health import build_bucket_spec, json_safe
from deepspeed_tpu.telemetry.ledger import suppress_attribution
from deepspeed_tpu.utils.logging import logger

FLEET_SCHEMA = "deepspeed_tpu.fleet_health/1"
RECORD_SCHEMA = "deepspeed_tpu.fleet_record/1"

# categories a rank record may carry (the goodput ledger's, as exact
# integer microseconds); kept as a local tuple so this module never
# imports the ledger's jnp-adjacent machinery at record-read time
RECORD_CATEGORIES = (
    "device_compute", "compile", "input_wait", "host_dispatch",
    "checkpoint_save", "checkpoint_load", "eval", "overflow_skipped",
    "unattributed",
)
_GOOD_CATEGORIES = frozenset({"device_compute", "host_dispatch"})

RULE_SEVERITY = {
    "desync": "critical",
    "step_time_skew": "warning",
    "input_wait_skew": "warning",
    "checkpoint_persist_skew": "warning",
}
_SEVERITY_ORDER = ("critical", "warning", "watch")

_TMP_MARK = ".tmp."          # the checkpoint_io sibling-marker convention
_RANK_DIR_FMT = "rank_{:05d}"
_WIN_FILE_FMT = "win_{:08d}.json"


def _fsync_dir(dirname):
    """Durability for the rename itself (best-effort — mirrors
    checkpoint_io._fsync_dir, re-implemented here because checkpoint_io
    imports jax at module scope and this module must stay host-only)."""
    try:
        fd = os.open(dirname or ".", os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write_bytes(path, payload):
    """tmp sibling + fsync + atomic rename (+ dir fsync): a reader sees
    the file COMPLETE or not at all; a kill mid-write strands only a
    ``*.tmp.<pid>`` sibling every scanner here ignores."""
    tmp = f"{path}{_TMP_MARK}{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass
    _fsync_dir(os.path.dirname(path))


class _NullTimer:
    """Shared no-op context for the disabled shipper (the hot path)."""
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_TIMER = _NullTimer()


class _CatTimer:
    """Times one interval into a shipper category accumulator (µs)."""
    __slots__ = ("_acc", "_cat", "_t0")

    def __init__(self, acc, cat):
        self._acc = acc
        self._cat = cat

    def __enter__(self):
        self._t0 = _clk.monotonic_s()
        return self

    def __exit__(self, *exc):
        us = int((_clk.monotonic_s() - self._t0) * 1e6)
        if us > 0:
            self._acc[self._cat] += us
        return False


class _WriterState:
    """Everything the background writer thread may touch. The thread
    holds ONLY this object (never the shipper), so an abandoned shipper
    is reclaimed by GC via weakref.finalize — the PR-5/PR-7 thread
    discipline. ``busy`` is True from dequeue to write-complete, so
    ``drain`` means durably-on-disk, not merely queue-empty."""
    __slots__ = ("queue", "cond", "stopped", "busy", "errors", "warned")

    def __init__(self):
        self.queue = deque()
        self.cond = threading.Condition()
        self.stopped = False
        self.busy = False
        self.errors = 0
        self.warned = False


def _writer_loop(state):
    # shipping must never book wall time into the (thread-local muted)
    # ledger: the writer's seconds are overlapped, not the train loop's
    with suppress_attribution():
        while True:
            with state.cond:
                state.busy = False
                state.cond.notify_all()
                while not state.queue and not state.stopped:
                    state.cond.wait(timeout=0.5)
                if not state.queue and state.stopped:
                    return
                path, payload = state.queue.popleft()
                state.busy = True
            try:
                atomic_write_bytes(path, payload)
            except Exception as e:       # forensics must never kill a run
                state.errors += 1
                if not state.warned:
                    state.warned = True
                    logger.warning("[fleet] background ship failed: %s", e)


def _finalize_writer(state, thread):
    with state.cond:
        state.stopped = True
        state.cond.notify_all()
    if thread.is_alive():
        thread.join(timeout=5.0)


class FleetShipper:
    """Per-rank window-record shipper (pure host bookkeeping).

    The engine drives it: ``note_step_time`` every global step (two clock
    reads), ``time_category`` around input-wait / checkpoint intervals on
    ranks that have no goodput ledger, and ``tick`` at the fleet cadence
    — which builds one record from whatever sources this rank has (the
    attached ledger's category diff when present, the shipper's own
    accumulators otherwise) and ships it atomically into
    ``<run_dir>/rank_XXXXX/``.

    Exactness contract: every duration in a record is an integer
    microsecond count, and when the ledger is attached the categories are
    diffs of its totals with ``unattributed`` recomputed as the residual,
    so ``sum(categories_us.values()) == wall_us`` holds EXACTLY per
    window and per-rank sums re-add exactly across windows (the PR-4 /
    PR-9 sum-by-construction discipline, now integer-valued so there is
    no float drift across files)."""

    def __init__(self, run_dir, rank, job_name="", background=True,
                 serving_ring=8, enabled=True, log_fn=None):
        self.enabled = bool(enabled)
        self.rank = int(rank)
        self.windows_shipped = 0
        if not self.enabled:
            return
        self.run_dir = run_dir
        self.job_name = job_name
        self.rank_dir = os.path.join(run_dir, _RANK_DIR_FMT.format(self.rank))
        os.makedirs(self.rank_dir, exist_ok=True)
        # an elastically-resumed rank continues its window sequence
        # instead of overwriting win_00000000.json onward — the monitor
        # scans by filename, so a restarted-at-zero shipper would be
        # invisible behind its own pre-crash files
        existing = []
        for f in os.listdir(self.rank_dir):
            if f.startswith("win_") and f.endswith(".json") \
                    and _TMP_MARK not in f:
                try:
                    existing.append(int(f[4:-5]))
                except ValueError:
                    pass
        if existing:
            self.windows_shipped = max(existing) + 1
        self._log = log_fn or logger.warning
        self._ledger = None
        self._led_totals = None
        self._led_elapsed = 0.0
        # the shared telemetry axis (clock.py) — shipper windows join
        # against chronicle events and ledger windows with no
        # perf_counter/monotonic mix
        self._t_last = _clk.monotonic_s()
        self._step_sum_us = 0
        self._step_max_us = 0
        self._step_n = 0
        self._acc = {"input_wait": 0, "checkpoint_save": 0}
        self._skipped_last = 0
        self._serving = deque(maxlen=max(1, int(serving_ring)))
        self.ship_errors = 0
        self._warned_ship = False
        self._closed = False
        self._wstate = None
        self._wthread = None
        if background:
            self._wstate = _WriterState()
            self._wthread = threading.Thread(
                target=_writer_loop, args=(self._wstate,),
                name=f"ds-fleet-ship-r{self.rank}", daemon=True)
            self._wthread.start()
            self._finalizer = weakref.finalize(
                self, _finalize_writer, self._wstate, self._wthread)

    # ------------------------------------------------------------- feeding
    def attach_ledger(self, ledger):
        """Source the window category breakdown from *ledger* (the rank's
        goodput ledger) instead of the shipper's own accumulators."""
        if not self.enabled:
            return
        self._ledger = ledger
        self._led_totals = ledger.totals()
        self._led_elapsed = ledger.elapsed()

    def note_step_time(self, seconds):
        """One global step's wall time (the whole ``train_batch``)."""
        if not self.enabled:
            return
        us = int(seconds * 1e6)
        self._step_sum_us += us
        if us > self._step_max_us:
            self._step_max_us = us
        self._step_n += 1

    def time_category(self, category):
        """Context manager timing an interval into the shipper's own
        ``input_wait`` / ``checkpoint_save`` accumulators — the fallback
        source on ranks whose manager (and therefore ledger) is disabled.
        The shared no-op when the shipper is disabled."""
        if not self.enabled or category not in self._acc:
            return _NULL_TIMER
        return _CatTimer(self._acc, category)

    def add_category_us(self, category, us):
        """Book *us* microseconds directly (the subprocess simulator and
        tests use this; the engine goes through ``time_category``)."""
        if self.enabled and category in self._acc and us > 0:
            self._acc[category] += int(us)

    def note_serving_window(self, window):
        """A closed serving-observatory window (rides along in the next
        shipped record, bounded ring)."""
        if self.enabled:
            self._serving.append(window)

    def has_pending_steps(self):
        """True when at least one step accumulated since the last ship —
        the engine's report path skips the desync device fetch when a
        forced tick would ship nothing anyway."""
        return self.enabled and self._step_n > 0

    # ------------------------------------------------------------ shipping
    def tick(self, step, skipped_steps=0, desync=None, health=None,
             force=False):
        """Close the current window and ship its record. Returns the
        record dict, or None when no step completed since the last tick
        (an empty window carries no information and would desynchronise
        the cross-rank window join)."""
        if not self.enabled or self._step_n == 0:
            return None
        now = _clk.monotonic_s()
        categories_us = None
        goodput_fraction = None
        if self._ledger is not None and self._ledger.enabled:
            led_elapsed = self._ledger.elapsed()
            totals = self._ledger.totals()
            wall_us = int(round((led_elapsed - self._led_elapsed) * 1e6))
            categories_us = {
                c: int(round((totals[c] - self._led_totals.get(c, 0.0))
                             * 1e6))
                for c in RECORD_CATEGORIES if c != "unattributed"}
            # the residual is COMPUTED so the integer sum is exact by
            # construction (independent rounding may make it a few µs
            # negative — honest jitter, never drift)
            categories_us["unattributed"] = \
                wall_us - sum(categories_us.values())
            good = sum(categories_us[c] for c in _GOOD_CATEGORIES)
            goodput_fraction = (round(good / wall_us, 6)
                                if wall_us > 0 else None)
            input_wait_us = categories_us["input_wait"]
            ckpt_us = categories_us["checkpoint_save"]
            self._led_totals = totals
            self._led_elapsed = led_elapsed
        else:
            wall_us = int(round((now - self._t_last) * 1e6))
            input_wait_us = self._acc["input_wait"]
            ckpt_us = self._acc["checkpoint_save"]
        record = {
            "schema": RECORD_SCHEMA,
            "rank": self.rank,
            "window": self.windows_shipped,
            "job_name": self.job_name,
            "end_step": int(step),
            "steps": self._step_n,
            "skipped_steps": int(skipped_steps) - self._skipped_last,
            "wall_us": wall_us,
            "step_time_us": {"sum": self._step_sum_us,
                             "max": self._step_max_us,
                             "count": self._step_n},
            "input_wait_us": int(input_wait_us),
            "checkpoint_save_us": int(ckpt_us),
            "categories_us": categories_us,
            "goodput_fraction": goodput_fraction,
            "health": health,
            "desync": desync,
            "serving": list(self._serving) or None,
            # t_us is the join stamp (shared monotonic axis); ts renders
            # it as wall time through the process-wide anchor
            "t_us": _clk.monotonic_us(),
            "ts": round(_clk.unix_us() / 1e6, 3),
        }
        if force:
            record["forced"] = True
        self._skipped_last = int(skipped_steps)
        self._step_sum_us = self._step_max_us = self._step_n = 0
        self._acc = {k: 0 for k in self._acc}
        self._serving.clear()
        self._t_last = now
        self._ship(record)
        self.windows_shipped += 1
        return record

    def _ship(self, record):
        path = os.path.join(self.rank_dir,
                            _WIN_FILE_FMT.format(record["window"]))
        try:
            # serialise on the caller's thread so a non-JSON-able value
            # surfaces deterministically; the file I/O overlaps
            payload = json.dumps(json_safe(record), allow_nan=False,
                                 default=repr).encode()
        except Exception as e:
            self.ship_errors += 1
            if not self._warned_ship:
                self._warned_ship = True
                self._log("[fleet] record serialisation failed: %s", e)
            return
        if self._wstate is not None and not self._closed:
            with self._wstate.cond:
                self._wstate.queue.append((path, payload))
                self._wstate.cond.notify()
            return
        try:
            atomic_write_bytes(path, payload)
        except Exception as e:
            self.ship_errors += 1
            if not self._warned_ship:
                self._warned_ship = True
                self._log("[fleet] ship failed: %s", e)

    def drain(self):
        """Block until every queued record is durably on disk (queue
        empty AND the in-flight write, if any, completed — the forced
        report path polls the monitor right after this)."""
        if not self.enabled or self._wstate is None:
            return
        deadline = time.monotonic() + 10.0
        with self._wstate.cond:
            while (self._wstate.queue or self._wstate.busy) \
                    and time.monotonic() < deadline:
                self._wstate.cond.wait(timeout=0.1)

    def close(self):
        if not self.enabled or self._closed:
            return
        self._closed = True
        self.drain()
        if self._wstate is not None:
            self._finalizer()
        self.ship_errors += getattr(self._wstate, "errors", 0) or 0


# Process-global shipper handle, mirroring tracer/metrics/ledger: library
# code with no engine reference (the serving observatory's window close)
# reaches the live shipper through it. None until an engine installs one.
_GLOBAL = None


def get_shipper():
    return _GLOBAL


def set_shipper(shipper):
    global _GLOBAL
    old, _GLOBAL = _GLOBAL, shipper
    return old


def reset_shipper(if_current=None):
    global _GLOBAL
    if if_current is None or _GLOBAL is if_current:
        _GLOBAL = None


# --------------------------------------------------------------- desync fn

def build_desync_spec(params, depth=8):
    """The PR-3 module-bucket spec, reused so desync provenance speaks
    the same bucket names HEALTH.json does."""
    return build_bucket_spec(params, depth=depth)


def build_desync_checksum_fn(mesh, spec, axis="data"):
    """Traced per-replica per-bucket parameter checksum.

    Returns a jitted ``fn(params) -> f32[dp, n_buckets]`` where row ``i``
    is data-parallel replica ``i``'s LOCAL checksum of each module
    bucket: ``sum(x) + sum(x*x)`` over the bucket's leaves in fp32 — a
    cheap projection, not a cryptographic hash, but identical replicas
    running identical programs produce bit-identical rows, so ANY
    cross-row difference is real divergence. ``shard_map`` with
    replicated in_specs makes each device reduce its OWN buffer (exactly
    what a replicated-in-name-only param tree breaks), and
    ``out_specs=P(axis)`` stacks the per-replica rows.

    jax is imported inside this function on purpose: the rest of this
    module is statically host-only (see telemetry_overhead.py's guard)."""
    import functools

    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from deepspeed_tpu.utils.jax_compat import get_shard_map
    shard_map, smap_kw = get_shard_map()
    n = len(spec.names)
    leaf_buckets = spec.leaf_buckets

    def body(params):
        leaves = jax.tree_util.tree_leaves(params)
        assert len(leaves) == len(leaf_buckets), (
            f"desync spec built for {len(leaf_buckets)} leaves but the "
            f"param tree has {len(leaves)}")
        sums = [jnp.float32(0.0)] * n
        for leaf, b in zip(leaves, leaf_buckets):
            x = leaf.astype(jnp.float32)
            sums[b] = sums[b] + jnp.sum(x) + jnp.sum(x * x)
        return jnp.stack(sums)[None, :]      # local [1, B] row

    smap = functools.partial(shard_map, mesh=mesh)
    fn = smap(body, in_specs=(P(),), out_specs=P(axis), **smap_kw)
    return jax.jit(fn)


# ---------------------------------------------------------------- monitor

class FleetMonitor:
    """Rank-0 cross-rank aggregator + sentinels. See module docstring.

    Pure host file I/O: ``poll()`` scans the run directory for new rank
    records (incremental — each rank directory remembers how many window
    files it has consumed), judges every window index all known ranks
    have shipped, and runs the skew/desync rules on the merged view.
    ``force=True`` (the report path) also judges windows some ranks have
    not shipped yet, marking them partial."""

    SNAPSHOT_MIN_INTERVAL_S = 5.0
    MAX_ANOMALY_HISTORY = 100
    MIN_SKEW_RANKS = 2

    def __init__(self, run_dir, job_name="", snapshot_path=None,
                 step_time_skew_frac=0.25, input_wait_skew_frac=0.25,
                 checkpoint_skew_frac=0.5, checkpoint_skew_floor_ms=50.0,
                 warmup_windows=1, window_ring=128,
                 registry=None, on_escalate=None, on_anomaly=None,
                 log_fn=None):
        self.run_dir = run_dir
        self.job_name = job_name
        if snapshot_path is None:
            # NEVER default into the current directory: an anomaly-firing
            # monitor (e.g. a unit test) running from the repo root would
            # silently overwrite the committed FLEET_HEALTH.json example —
            # the PR-4 GOODPUT clobber, which DID recur here before this
            # default was moved next to the run dir it aggregates
            snapshot_path = os.path.join(run_dir, "FLEET_HEALTH.json")
        self.snapshot_path = snapshot_path
        self.step_time_skew_frac = float(step_time_skew_frac)
        self.input_wait_skew_frac = float(input_wait_skew_frac)
        self.checkpoint_skew_frac = float(checkpoint_skew_frac)
        self.checkpoint_skew_floor_us = float(checkpoint_skew_floor_ms) * 1e3
        self.warmup_windows = int(warmup_windows)
        self.registry = registry
        self.on_escalate = on_escalate
        self.on_anomaly = on_anomaly
        self._log = log_fn or logger.warning

        self._rank_next = {}          # rank -> next window index to read
        self._pending = {}            # window idx -> {rank: record}
        self._judged = set()
        self.windows = deque(maxlen=max(1, int(window_ring)))
        self.windows_dropped = 0
        self.rank_totals = {}         # rank -> exact integer sums
        self.anomalies = []
        self.rule_counts = {}
        self.records_loaded = 0
        self.late_records = 0
        self._warned_late = False
        self.windows_judged = 0
        self.desync_checks = 0
        self.desync_mismatches = 0
        self.last_desync = None
        self._snapshots_written = 0
        self._last_snapshot_t = float("-inf")
        self._last_poll_t = None

    @classmethod
    def from_config(cls, tconfig, run_dir, output_path="telemetry/",
                    job_name="", registry=None, on_escalate=None,
                    on_anomaly=None):
        """Build from a parsed ``DeepSpeedTelemetryConfig``'s ``fleet_*``
        fields."""
        snap = getattr(tconfig, "fleet_snapshot_file", "") \
            or "FLEET_HEALTH.json"
        if not os.path.isabs(snap):
            snap = os.path.join(output_path or ".", snap)
        return cls(
            run_dir=run_dir,
            job_name=job_name,
            snapshot_path=snap,
            step_time_skew_frac=getattr(
                tconfig, "fleet_step_time_skew_frac", 0.25),
            input_wait_skew_frac=getattr(
                tconfig, "fleet_input_wait_skew_frac", 0.25),
            checkpoint_skew_frac=getattr(
                tconfig, "fleet_checkpoint_skew_frac", 0.5),
            checkpoint_skew_floor_ms=getattr(
                tconfig, "fleet_checkpoint_skew_floor_ms", 50.0),
            warmup_windows=getattr(tconfig, "fleet_warmup_windows", 1),
            window_ring=getattr(tconfig, "fleet_window_ring", 128),
            registry=registry, on_escalate=on_escalate,
            on_anomaly=on_anomaly)

    # ------------------------------------------------------------ scanning
    def scan(self):
        """Incrementally load new rank records from the run directory.

        Each rank's records are probed SEQUENTIALLY (``win_%08d`` —
        every shipper writes its windows in FIFO order through one
        writer, and an elastic resume continues the numbering), so a
        poll costs O(new files), not O(all files ever written): the
        per-rank cursor is one integer, and the only directory listing
        is the run dir itself (O(ranks)). Torn/half-written files can
        never be seen (atomic renames); a record that fails to parse is
        logged and skipped — one bad record must not blind the fleet."""
        try:
            names = sorted(os.listdir(self.run_dir))
        except OSError:
            return 0
        loaded = 0
        for name in names:
            if not name.startswith("rank_"):
                continue
            rank_dir = os.path.join(self.run_dir, name)
            if not os.path.isdir(rank_dir):
                continue
            try:
                rank = int(name.split("_", 1)[1])
            except ValueError:
                continue
            nxt = self._rank_next.setdefault(rank, 0)
            while True:
                path = os.path.join(rank_dir, _WIN_FILE_FMT.format(nxt))
                if not os.path.isfile(path):
                    break
                nxt += 1
                try:
                    with open(path) as f:
                        rec = json.load(f)
                except Exception as e:
                    self._log("[fleet] unreadable record %s: %s",
                              path, e)
                    continue
                self._ingest(rank, rec)
                loaded += 1
            self._rank_next[rank] = nxt
        return loaded

    def _ingest(self, rank, rec):
        self.records_loaded += 1
        idx = int(rec.get("window", -1))
        if idx < 0:
            return
        if idx in self._judged:
            # the window was already judged (force-judged partial, or a
            # rank's directory appeared late) — folding the record in
            # now would desynchronise the per-rank totals from the
            # merged window ring, breaking the exact re-add invariant
            # the artifact pin enforces. Count it instead of hiding it.
            self.late_records += 1
            if not self._warned_late:
                self._warned_late = True
                self._log("[fleet] rank %s shipped window %s after it "
                          "was judged (forced report or late-joining "
                          "rank); counting as late_records", rank, idx)
            return
        self._pending.setdefault(idx, {})[rank] = rec

    # ------------------------------------------------------------- judging
    # a rank this many windows behind the newest pending one is treated
    # as a straggler/dead host: its window is judged partial rather than
    # letting one silent rank blind every live rule forever
    STRAGGLER_GRACE_WINDOWS = 2

    def poll(self, force=False):
        """Scan + judge. Returns the number of windows judged.

        A window is judged once every known rank has shipped it; a rank
        that falls ``STRAGGLER_GRACE_WINDOWS`` behind the newest pending
        window stops being waited for (judged partial) — a dead host
        must not disable the very sentinels that exist to catch it.
        ``force=True`` (the report path) judges everything pending."""
        self._last_poll_t = time.monotonic()
        self.scan()
        known = set(self._rank_next)
        newest = max(self._pending, default=-1)
        judged = 0
        for idx in sorted(self._pending):
            if idx in self._judged:
                continue
            recs = self._pending[idx]
            complete = known and set(recs) >= known
            if not complete and not force and \
                    newest - idx < self.STRAGGLER_GRACE_WINDOWS:
                # wait (briefly) for the stragglers' files — judging
                # early would bias every skew rule toward whoever ships
                # fastest
                break
            self._judge(idx, recs, partial=not complete)
            judged += 1
        for idx in list(self._pending):
            if idx in self._judged:
                del self._pending[idx]
        return judged

    def last_poll_age_s(self):
        """Seconds since the last ``poll()`` — the obs server's
        freshness stamp for the fleet provider (None before the first
        poll, matching the other monitors' age semantics)."""
        if self._last_poll_t is None:
            return None
        return round(time.monotonic() - self._last_poll_t, 3)

    def _accumulate_totals(self, rank, rec):
        """Per-rank exact integer sums — accumulated at JUDGE time from
        the records actually merged into the window ring, so the
        report's totals and its windows re-add exactly by construction
        on every path (live cadence, forced report, partial judges)."""
        tot = self.rank_totals.setdefault(rank, {
            "windows": 0, "steps": 0, "skipped_steps": 0, "wall_us": 0,
            "step_time_us": 0, "input_wait_us": 0, "checkpoint_save_us": 0,
            "categories_us": {},
        })
        tot["windows"] += 1
        tot["steps"] += int(rec.get("steps", 0))
        tot["skipped_steps"] += int(rec.get("skipped_steps", 0))
        tot["wall_us"] += int(rec.get("wall_us", 0))
        st = rec.get("step_time_us") or {}
        tot["step_time_us"] += int(st.get("sum", 0))
        tot["input_wait_us"] += int(rec.get("input_wait_us", 0))
        tot["checkpoint_save_us"] += int(rec.get("checkpoint_save_us", 0))
        cats = rec.get("categories_us")
        if cats:
            for c, v in cats.items():
                tot["categories_us"][c] = \
                    tot["categories_us"].get(c, 0) + int(v)

    def _judge(self, idx, recs, partial=False):
        self._judged.add(idx)
        self.windows_judged += 1
        per_rank = {}
        for rank, rec in sorted(recs.items()):
            self._accumulate_totals(rank, rec)
            per_rank[str(rank)] = {
                "end_step": rec.get("end_step"),
                "steps": rec.get("steps"),
                "skipped_steps": rec.get("skipped_steps", 0),
                "wall_us": rec.get("wall_us"),
                "step_time_us": rec.get("step_time_us"),
                "input_wait_us": rec.get("input_wait_us"),
                "checkpoint_save_us": rec.get("checkpoint_save_us"),
                "categories_us": rec.get("categories_us"),
                "goodput_fraction": rec.get("goodput_fraction"),
            }
        window = {
            "index": idx,
            "end_step": max((r.get("end_step") or 0)
                            for r in recs.values()),
            "ranks": sorted(recs),
            "per_rank": per_rank,
            "skew": self._skew_view(recs),
        }
        if partial:
            window["partial"] = True
        if len(self.windows) == self.windows.maxlen:
            self.windows_dropped += 1
        self.windows.append(window)
        anoms = []
        # the desync sentinel is a CORRECTNESS check — it never warms up
        anoms += self._check_desync(idx, recs, window)
        if self.windows_judged > self.warmup_windows:
            anoms += self._check_skew(idx, recs, window)
        self._publish(window)
        if anoms:
            self._escalate(anoms)

    @staticmethod
    def _mean_step_us(rec):
        st = rec.get("step_time_us") or {}
        n = int(st.get("count", 0))
        return (st.get("sum", 0) / n) if n else None

    def _skew_view(self, recs):
        """The merged window's cross-rank extremes (always recorded, so
        the artifact shows the skew trajectory, not just firings)."""
        view = {}
        means = {r: m for r, rec in recs.items()
                 if (m := self._mean_step_us(rec)) is not None}
        if len(means) >= 2:
            slow = max(means, key=means.get)
            fast = min(means, key=means.get)
            view["step_time"] = {
                "slow_rank": slow, "fast_rank": fast,
                "slow_mean_us": round(means[slow], 1),
                "fast_mean_us": round(means[fast], 1),
                "skew_frac": round(
                    (means[slow] - means[fast]) / means[slow], 4)
                if means[slow] > 0 else 0.0,
            }
        iw = {r: rec.get("input_wait_us", 0) / rec["wall_us"]
              for r, rec in recs.items() if rec.get("wall_us")}
        if len(iw) >= 2:
            hi, lo = max(iw, key=iw.get), min(iw, key=iw.get)
            view["input_wait"] = {
                "max_rank": hi, "max_frac": round(iw[hi], 4),
                "min_rank": lo, "min_frac": round(iw[lo], 4),
            }
        ck = {r: int(rec.get("checkpoint_save_us", 0))
              for r, rec in recs.items()}
        if any(ck.values()):
            hi, lo = max(ck, key=ck.get), min(ck, key=ck.get)
            view["checkpoint_save"] = {
                "max_rank": hi, "max_us": ck[hi],
                "min_rank": lo, "min_us": ck[lo],
            }
        return view

    @staticmethod
    def _dominant_badput(rec):
        cats = rec.get("categories_us")
        if not cats:
            return None
        bad = {c: v for c, v in cats.items() if c not in _GOOD_CATEGORIES}
        if not bad or all(v <= 0 for v in bad.values()):
            return None
        return max(bad, key=bad.get)

    def _check_skew(self, idx, recs, window):
        anoms = []
        if len(recs) < self.MIN_SKEW_RANKS:
            return anoms
        step = window["end_step"]
        st = window["skew"].get("step_time")
        if st and st["skew_frac"] > self.step_time_skew_frac:
            dom = self._dominant_badput(recs[st["slow_rank"]])
            dom_txt = (f"; rank {st['slow_rank']}'s own ledger books the "
                       f"window dominantly as {dom}" if dom
                       else "; no per-category ledger on that rank — "
                            "likely device-side (collective/compute)")
            anoms.append({
                "rule": "step_time_skew", "step": step, "window": idx,
                "severity": RULE_SEVERITY["step_time_skew"],
                "slow_rank": int(st["slow_rank"]),
                "fast_rank": int(st["fast_rank"]),
                "slow_mean_us": st["slow_mean_us"],
                "fast_mean_us": st["fast_mean_us"],
                "badput_share": st["skew_frac"],
                "slow_rank_dominant_badput": dom,
                "detail": (
                    f"rank {st['slow_rank']} is the straggler: mean step "
                    f"{st['slow_mean_us'] / 1e3:.1f} ms vs fastest rank "
                    f"{st['fast_rank']}'s {st['fast_mean_us'] / 1e3:.1f} "
                    f"ms — in a synchronous step every other rank waits, "
                    f"so ~{st['skew_frac']:.0%} of fleet step time is "
                    f"straggler-induced collective wait" + dom_txt)})
        iw = window["skew"].get("input_wait")
        if iw and (iw["max_frac"] - iw["min_frac"]
                   > self.input_wait_skew_frac):
            anoms.append({
                "rule": "input_wait_skew", "step": step, "window": idx,
                "severity": RULE_SEVERITY["input_wait_skew"],
                "rank": int(iw["max_rank"]),
                "max_frac": iw["max_frac"], "min_frac": iw["min_frac"],
                "detail": (
                    f"rank {iw['max_rank']} spent {iw['max_frac']:.0%} of "
                    f"the window blocked on input while rank "
                    f"{iw['min_rank']} spent {iw['min_frac']:.0%} — a "
                    f"per-host input problem (storage, network, collate), "
                    f"invisible in any single-rank ledger")})
        ck = window["skew"].get("checkpoint_save")
        if ck and ck["max_us"] >= self.checkpoint_skew_floor_us \
                and ck["max_us"] > 0 \
                and (ck["max_us"] - ck["min_us"]) / ck["max_us"] \
                > self.checkpoint_skew_frac:
            anoms.append({
                "rule": "checkpoint_persist_skew", "step": step,
                "window": idx,
                "severity": RULE_SEVERITY["checkpoint_persist_skew"],
                "rank": int(ck["max_rank"]),
                "max_us": ck["max_us"], "min_us": ck["min_us"],
                "detail": (
                    f"rank {ck['max_rank']} spent "
                    f"{ck['max_us'] / 1e3:.0f} ms in checkpoint_save this "
                    f"window vs {ck['min_us'] / 1e3:.0f} ms on rank "
                    f"{ck['min_rank']} — the manifest waits for every "
                    f"rank's shard files, so the slowest persist gates "
                    f"the whole tag")})
        return anoms

    def _check_desync(self, idx, recs, window):
        """Compare parameter checksum rows across every replica that
        shipped one this window (rows within one record are the
        single-process virtual-mesh dp path; rows across records are the
        multi-process path). Mismatch = silent divergence, critical."""
        groups = {}          # bucket_names tuple -> [(rank, replica, row)]
        for rank, rec in recs.items():
            d = rec.get("desync")
            if not d:
                continue
            names = tuple(d.get("bucket_names") or ())
            for rep in d.get("replicas") or []:
                rep_idx, values = rep[0], rep[1]
                groups.setdefault(names, []).append(
                    (rank, int(rep_idx), list(values)))
        anoms = []
        checked = False
        for names, rows in groups.items():
            if len(rows) < 2 or not names:
                continue
            checked = True
            self.desync_checks += 1
            mismatched = []
            ambiguous = False
            for j, bucket in enumerate(names):
                vals = {}
                for rank, rep, values in rows:
                    vals.setdefault(repr(values[j]), []).append(
                        (rank, rep))
                if len(vals) <= 1:
                    continue
                by_size = sorted(vals.values(), key=len, reverse=True)
                if len(by_size[0]) == len(by_size[1]):
                    # even split (e.g. dp=2): there IS no majority —
                    # naming one side would deterministically blame
                    # whichever replica happened to hash second, and an
                    # operator restoring 'the healthy one' could keep
                    # the corrupt one. List every split replica instead.
                    ambiguous = True
                    outliers = [rr for v in vals.values() for rr in v]
                else:
                    majority = by_size[0]
                    outliers = [rr for v in vals.values()
                                if v is not majority for rr in v]
                mismatched.append((bucket, outliers))
            self.last_desync = {
                "window": idx,
                "replicas": len(rows),
                "buckets": list(names),
                "mismatch_buckets": [b for b, _ in mismatched],
            }
            window["desync"] = self.last_desync
            if not mismatched:
                continue
            self.desync_mismatches += 1
            buckets = [b for b, _ in mismatched]
            outliers = sorted({rr for _, out in mismatched
                               for rr in out})
            who = ", ".join(f"rank {r} replica {p}" for r, p in outliers)
            anoms.append({
                "rule": "desync", "step": window["end_step"],
                "window": idx,
                "severity": RULE_SEVERITY["desync"],
                "buckets": buckets,
                "ambiguous": ambiguous,
                "replicas": [{"rank": int(r), "replica": int(p)}
                             for r, p in outliers],
                "detail": (
                    f"parameter desync: module bucket(s) "
                    f"{', '.join(buckets)} checksum-diverge across "
                    f"data-parallel replicas ("
                    + (f"replicas split EVENLY — cannot attribute which "
                       f"side diverged; involved: {who}" if ambiguous
                       else f"outlier {who}")
                    + ") — the replicas are silently training different "
                      "models; checkpoint and investigate NOW")})
        if checked and self.registry is not None:
            self.registry.counter(
                "fleet_desync_checks_total",
                "cross-replica parameter checksum comparisons").inc()
        return anoms

    # ------------------------------------------------------------ metrics
    def _publish(self, window):
        reg = self.registry
        if reg is None:
            return
        reg.gauge("fleet_ranks",
                  "ranks shipping fleet records").set(
                      len(self._rank_next))
        reg.counter("fleet_windows_judged_total",
                    "cross-rank windows merged and judged").inc()
        st = window["skew"].get("step_time")
        if st:
            reg.gauge("fleet_step_time_skew_frac",
                      "(slowest-fastest)/slowest mean step time of the "
                      "last judged window").set(st["skew_frac"])

    # ---------------------------------------------------------- escalation
    def _escalate(self, anoms):
        # the shared protocol (telemetry/escalation.py)
        escalation.escalate(self, anoms, tag="fleet",
                            counter="fleet_anomalies_total",
                            counter_help="fleet cross-rank rule firings")

    # -------------------------------------------------------------- output
    def verdict(self):
        if not self.windows_judged:
            return "unknown"
        seen = {RULE_SEVERITY.get(r, "warning") for r in self.rule_counts}
        for tier in _SEVERITY_ORDER:
            if tier in seen:
                return tier
        return "healthy"

    def report(self):
        """The full fleet forensics dict (what ``FLEET_HEALTH.json``
        holds)."""
        return {
            "schema": FLEET_SCHEMA,
            "enabled": True,
            "job_name": self.job_name,
            "run_dir": self.run_dir,
            "verdict": self.verdict(),
            "rules": {
                "step_time_skew_frac": self.step_time_skew_frac,
                "input_wait_skew_frac": self.input_wait_skew_frac,
                "checkpoint_skew_frac": self.checkpoint_skew_frac,
                "checkpoint_skew_floor_ms":
                    self.checkpoint_skew_floor_us / 1e3,
                "warmup_windows": self.warmup_windows,
            },
            "n_ranks": len(self._rank_next),
            "ranks": {str(r): dict(t, categories_us=dict(
                t["categories_us"]))
                for r, t in sorted(self.rank_totals.items())},
            "counters": {
                "records_loaded": self.records_loaded,
                "late_records": self.late_records,
                "windows_judged": self.windows_judged,
                "windows_in_ring": len(self.windows),
                "windows_dropped": self.windows_dropped,
                "desync_checks": self.desync_checks,
                "desync_mismatches": self.desync_mismatches,
                "anomaly_counts": dict(self.rule_counts),
            },
            "desync": self.last_desync,
            "anomalies": list(self.anomalies),
            "windows": list(self.windows),
        }

    def write_snapshot(self, path=None, force=False, report=None):
        """Write ``FLEET_HEALTH.json`` (throttled like every other
        forensics snapshot; strict JSON via json_safe/allow_nan)."""
        if not force and (time.monotonic() - self._last_snapshot_t
                          < self.SNAPSHOT_MIN_INTERVAL_S):
            return None
        self._last_snapshot_t = time.monotonic()
        path = path or self.snapshot_path
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(json_safe(report if report is not None
                                else self.report()),
                      f, indent=1, default=repr, allow_nan=False)
        self._snapshots_written += 1
        return path

    def close(self):
        """Final snapshot — only when there is something to explain."""
        if self.anomalies:
            self.write_snapshot(force=True)


# ------------------------------------------------------------ trace merge

def merge_traces(out_path, trace_paths):
    """Concatenate per-rank Chrome traces into ONE file with per-rank
    process lanes: each input file's events are re-pidded to its rank id
    (parsed from the file's ``process_name`` metadata when the Tracer
    stamped one, else the file's position), and process_name /
    process_sort_index metadata keep the lanes labelled and ordered in
    chrome://tracing / Perfetto."""
    merged = []
    for i, path in enumerate(trace_paths):
        with open(path) as f:
            doc = json.load(f)
        events = doc.get("traceEvents", [])
        rank = i
        label = None
        for ev in events:
            if ev.get("ph") == "M" and ev.get("name") == "process_name":
                label = (ev.get("args") or {}).get("name")
                if isinstance(label, str) and label.startswith("rank "):
                    try:
                        rank = int(label.split()[1])
                    except (ValueError, IndexError):
                        pass
                break
        merged.append({"name": "process_name", "ph": "M", "pid": rank,
                       "args": {"name": label
                                or f"rank {rank} ({os.path.basename(path)})"
                                }})
        merged.append({"name": "process_sort_index", "ph": "M",
                       "pid": rank, "args": {"sort_index": rank}})
        for ev in events:
            if ev.get("ph") == "M" and ev.get("name") in (
                    "process_name", "process_sort_index"):
                continue
            ev = dict(ev)
            ev["pid"] = rank
            merged.append(ev)
    payload = json.dumps({"traceEvents": merged,
                          "displayTimeUnit": "ms"}).encode()
    atomic_write_bytes(out_path, payload)
    return out_path


# --------------------------------------------------------------------- CLI

def render(report):
    """Human-readable rendering of a FLEET_HEALTH.json report dict."""
    lines = []
    lines.append(f"fleet verdict: {report.get('verdict', '?').upper()}"
                 f"  ({report.get('n_ranks', 0)} ranks"
                 + (f", job {report['job_name']}"
                    if report.get("job_name") else "") + ")")
    c = report.get("counters", {})
    lines.append(f"  windows judged {c.get('windows_judged', 0)} "
                 f"({c.get('records_loaded', 0)} records), desync checks "
                 f"{c.get('desync_checks', 0)} "
                 f"(mismatches {c.get('desync_mismatches', 0)})")
    for r, t in sorted((report.get("ranks") or {}).items(),
                       key=lambda kv: int(kv[0])):
        steps = t.get("steps", 0)
        mean = (t.get("step_time_us", 0) / steps / 1e3) if steps else 0.0
        wall = t.get("wall_us", 0)
        iwf = (t.get("input_wait_us", 0) / wall) if wall else 0.0
        lines.append(
            f"  rank {r}: {steps} steps, mean step {mean:.1f} ms, "
            f"input-wait {iwf:.0%}, checkpoint "
            f"{t.get('checkpoint_save_us', 0) / 1e3:.0f} ms, "
            f"{t.get('windows', 0)} windows")
    for a in report.get("anomalies", []):
        lines.append(f"  [{a.get('severity', '?'):8s}] step "
                     f"{a.get('step')}: {a.get('rule')} — "
                     f"{a.get('detail')}")
    if not report.get("anomalies"):
        lines.append("  no fleet anomalies recorded")
    return "\n".join(lines)


def _simulate_rank(args):
    """Subprocess-writer rank simulator: a REAL FleetShipper driven by a
    synthetic-but-wall-clock-honest step loop (each step actually sleeps
    its step time, so window wall / fraction arithmetic stays
    consistent). The multi-rank e2e tests and the demo use it as the
    'other hosts' — pure host code, no jax import, sub-second."""
    sh = FleetShipper(args.run_dir, rank=args.rank, job_name=args.job,
                      background=False)
    step_s = args.step_ms / 1e3
    for w in range(args.windows):
        for _ in range(args.steps_per_window):
            t0 = time.perf_counter()
            time.sleep(step_s)
            dt = time.perf_counter() - t0
            sh.note_step_time(dt)
            if args.input_wait_frac > 0:
                sh.add_category_us("input_wait",
                                   int(dt * 1e6 * args.input_wait_frac))
        if args.ckpt_ms > 0 and w == args.ckpt_window:
            sh.add_category_us("checkpoint_save", int(args.ckpt_ms * 1e3))
        sh.tick(step=(w + 1) * args.steps_per_window)
    sh.close()
    return 0


def _demo(args):
    """The committed-example scenario: three subprocess-simulated fast
    ranks (rank 3 with a slow checkpoint persist) + ONE real dp=8
    virtual-mesh engine as fleet rank 0, whose data iterator carries an
    injected 20 ms stall (making it both the step-time straggler and the
    input-wait outlier) and whose Dense_1 parameters get one replica
    perturbed mid-run (firing the desync sentinel with bucket
    provenance). All four cross-rank rules fire on real shipped files."""
    import subprocess
    import sys
    import tempfile

    if args.devices:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.devices}")
        os.environ.setdefault("JAX_PLATFORMS", "cpu")

    run_dir = args.run_dir or tempfile.mkdtemp(prefix="ds_fleet_demo_")
    tel_dir = tempfile.mkdtemp(prefix="ds_fleet_demo_tel_")
    steps, cadence = args.steps, 2
    windows = steps // cadence
    # ---- the simulated fast ranks (subprocess writers) ----------------
    procs = []
    for rank in (1, 2, 3):
        cmd = [sys.executable, "-m", "deepspeed_tpu.telemetry.fleet",
               "--simulate-rank", str(rank), "--run-dir", run_dir,
               "--windows", str(windows),
               "--steps-per-window", str(cadence),
               "--step-ms", "5", "--input-wait-frac", "0.05",
               "--job", "fleet_demo"]
        if rank == 3:
            cmd += ["--ckpt-ms", "250", "--ckpt-window",
                    str(windows // 2)]
        procs.append(subprocess.Popen(cmd))
    for p in procs:
        assert p.wait(timeout=120) == 0, "rank simulator failed"

    # ---- the real engine (fleet rank 0, the straggler) ----------------
    import jax
    import jax.numpy as jnp
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models.simple import SimpleModel, random_dataset, \
        sample_batch
    from deepspeed_tpu.utils import groups

    groups.destroy()
    groups.initialize()
    hidden = 32
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=hidden, nlayers=2),
        config={
            "train_batch_size": 8,
            "steps_per_print": cadence,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
            "telemetry": {
                "enabled": True, "trace": False,
                "jsonl": False, "prometheus": False,
                "job_name": "fleet_demo",
                "output_path": tel_dir,
                "goodput": {"enabled": True, "cadence": cadence,
                            "profiler_capture": False},
                # the engine's LIVE monitor snapshots into scratch: the
                # sim ranks finished long before the engine compiled, so
                # its live view (correctly) judges their early windows
                # partial under the straggler grace; the COMMITTED
                # artifact is the offline post-mortem aggregation below,
                # where every window is complete
                "fleet": {"enabled": True, "run_dir": run_dir,
                          "cadence": cadence, "rank": 0,
                          "warmup_windows": 1,
                          "snapshot_file": os.path.join(
                              tel_dir, "FLEET_HEALTH.live.json")},
            },
        },
        sample_batch=sample_batch(8, hidden))
    loader = engine.deepspeed_io(random_dataset(64, hidden))

    class _Stall:
        def __init__(self, it, stall_s):
            from deepspeed_tpu.runtime.dataloader import RepeatingLoader
            self._it = RepeatingLoader(it)
            self.stall_s = stall_s

        def __iter__(self):
            return self

        def __next__(self):
            time.sleep(self.stall_s)
            return next(self._it)

    it = _Stall(loader, args.stall_ms / 1e3)
    for step in range(steps):
        if step == steps - 2:
            # silently diverge ONE data-parallel replica of Dense_1: same
            # logical (replicated) array, one device's buffer perturbed —
            # exactly the failure the sentinel exists to catch
            def perturb(path, leaf):
                if "Dense_1" not in jax.tree_util.keystr(path) \
                        or getattr(leaf, "ndim", 0) != 2:
                    return leaf
                bufs = []
                for j, d in enumerate(leaf.sharding.mesh.devices.ravel()):
                    arr = np.array(leaf.addressable_data(j), copy=True)
                    if j == 3:
                        arr[0, 0] += 1.0
                    bufs.append(jax.device_put(arr, d))
                return jax.make_array_from_single_device_arrays(
                    leaf.shape, leaf.sharding, bufs)
            engine.state = engine.state._replace(
                params=jax.tree_util.tree_map_with_path(
                    perturb, engine.state.params))
        engine.train_batch(data_iter=it)
    engine.close()       # drains the writer: every record is on disk
    # the flight-recorder post-mortem: a FRESH monitor over the complete
    # run dir (the --aggregate path) — every window joins all 4 ranks
    mon = FleetMonitor(run_dir, job_name="fleet_demo",
                       snapshot_path=os.path.abspath(args.out),
                       warmup_windows=1)
    mon.poll(force=True)
    report = mon.report()
    mon.write_snapshot(force=True, report=report)
    print(render(report))
    print(f"\nwrote {args.out} (run dir: {run_dir})")
    return 0


def main(argv=None):
    import argparse
    p = argparse.ArgumentParser(
        prog="python -m deepspeed_tpu.telemetry.fleet",
        description="Render a FLEET_HEALTH.json snapshot, aggregate a "
                    "fleet run directory, merge per-rank Chrome traces, "
                    "or run the fleet-forensics demo")
    p.add_argument("--render", metavar="FLEET_HEALTH.json",
                   help="pretty-print an existing snapshot and exit")
    p.add_argument("--aggregate", metavar="RUN_DIR",
                   help="offline aggregation of a fleet run directory")
    p.add_argument("--merge-traces", nargs="+", metavar="TRACE",
                   help="merge per-rank Chrome traces (first arg after "
                        "--merge-out) into one per-rank-process-lane "
                        "file")
    p.add_argument("--merge-out", default="fleet_trace.json")
    p.add_argument("--demo", action="store_true",
                   help="subprocess-simulated ranks + one real dp=8 "
                        "engine with an injected straggler stall and a "
                        "perturbed replica; writes the snapshot")
    p.add_argument("--simulate-rank", type=int, default=None,
                   help="(internal) run one subprocess rank simulator")
    p.add_argument("--run-dir", default=None)
    p.add_argument("--windows", type=int, default=8)
    p.add_argument("--steps-per-window", type=int, default=2)
    p.add_argument("--step-ms", type=float, default=5.0)
    p.add_argument("--input-wait-frac", type=float, default=0.0)
    p.add_argument("--ckpt-ms", type=float, default=0.0)
    p.add_argument("--ckpt-window", type=int, default=0)
    p.add_argument("--job", default="")
    p.add_argument("--steps", type=int, default=16)
    p.add_argument("--stall-ms", type=float, default=20.0)
    p.add_argument("--devices", type=int, default=8,
                   help="virtual CPU devices for the demo (0 = existing)")
    p.add_argument("--out", default="FLEET_HEALTH.json")
    args = p.parse_args(argv)
    if args.render:
        with open(args.render) as f:
            print(render(json.load(f)))
        return 0
    if args.simulate_rank is not None:
        args.rank = args.simulate_rank
        assert args.run_dir, "--simulate-rank needs --run-dir"
        return _simulate_rank(args)
    if args.aggregate:
        mon = FleetMonitor(args.aggregate, snapshot_path=args.out)
        mon.poll(force=True)
        report = mon.report()
        print(render(report))
        mon.write_snapshot(force=True, report=report)
        print(f"\nwrote {args.out}")
        return 0
    if args.merge_traces:
        out = merge_traces(args.merge_out, args.merge_traces)
        print(f"merged {len(args.merge_traces)} traces -> {out}")
        return 0
    if args.demo:
        return _demo(args)
    p.print_help()
    return 2


if __name__ == "__main__":
    import sys
    sys.exit(main())
