"""Incident correlator — chronicle events -> causal incident chains.

The run chronicle gives every subsystem one ordered event axis; this
module answers the operator's actual question: *"what happened, in what
order, starting where, and what did it cost?"*. It joins chronicle
events into **incidents** — maximal chains of causally-related events —
and writes INCIDENTS.json.

Join rules (:class:`IncidentCorrelator`): an event joins the open
incident when ANY of

* **causal hint** — it shares a join key with a member: the same
  ``rule`` (an anomaly firing and the guardian action it triggered — the
  rule->action edge), the same ``request_id`` (a serving request's
  admission/preemption trail), or the same ``tag`` (a checkpoint save
  and the rollback that restored it);
* **step window** — its ``step`` is within ``step_window`` of a member
  step (a poison at step 8 and the nonfinite firing it causes at 9);
* **time window** — it lands within ``time_window_us`` of the incident's
  last member (wall-adjacent cascades with no step, e.g. serving).

Only *symptom* kinds (anomaly / action / chaos / serving / retrace) form
incidents; lifecycle and goodput_window events are context. Root-cause
ranking: the EARLIEST causally-linked anomaly-or-chaos event wins, ties
broken by severity (a chaos injection outranks everything it caused by
construction — it is first on the shared µs axis, so a poison-then-
diverge run names the poison step, not the loud rollback).

Per-incident **goodput cost** comes from the ledger's ``goodput_window``
events (integer-µs category diffs): every window overlapping the
incident's time span contributes its badput microseconds, category by
category — so the cost figure re-adds exactly against the ledger's own
window ring (pinned by the artifact tests). Incidents also link the
sibling snapshot artifacts (HEALTH/GOODPUT/GUARDIAN/...) that member
events escalated into, so the flat JSON families become navigable from
the timeline.

Host-only, stdlib-only.
"""

import json

from deepspeed_tpu.telemetry.chronicle import (_atomic_write_bytes,
                                               _severity_rank)

INCIDENTS_SCHEMA = "deepspeed_tpu.incidents/1"

# kinds that can MAKE an incident; everything else is context
MEMBER_KINDS = frozenset({"anomaly", "action", "chaos", "serving",
                          "retrace"})
# badput = every goodput-ledger category except the good two
GOOD_CATEGORIES = frozenset({"device_compute", "host_dispatch"})


def _join_keys(event):
    keys = set()
    for field in ("rule", "request_id", "tag"):
        v = event.get(field)
        if v is not None:
            keys.add((field, v))
    return keys


class IncidentCorrelator:
    """Correlate an event list (one rank's chronicle or a merged run
    dir) into incidents. Pure function of its inputs — construct, call
    :meth:`correlate`, discard."""

    def __init__(self, events, step_window=8, time_window_us=30_000_000):
        self.events = sorted(events,
                             key=lambda e: (e["t_us"], e.get("rank", 0),
                                            e["seq"]))
        self.step_window = int(step_window)
        self.time_window_us = int(time_window_us)

    # ------------------------------------------------------------ clustering
    def _joins(self, incident, event):
        if _join_keys(event) & incident["keys"]:
            return True
        step = event.get("step")
        if step is not None and incident["steps"]:
            if min(abs(step - s) for s in incident["steps"]) \
                    <= self.step_window:
                return True
            return False     # a known-far step never time-joins
        return event["t_us"] - incident["end_t_us"] <= self.time_window_us

    def correlate(self):
        incidents = []
        for e in self.events:
            if e["kind"] not in MEMBER_KINDS:
                continue
            open_inc = incidents[-1] if incidents else None
            if open_inc is not None and self._joins(open_inc, e):
                open_inc["members"].append(e)
                open_inc["keys"] |= _join_keys(e)
                if e.get("step") is not None:
                    open_inc["steps"].add(e["step"])
                open_inc["end_t_us"] = e["t_us"]
            else:
                incidents.append({
                    "members": [e], "keys": _join_keys(e),
                    "steps": ({e["step"]} if e.get("step") is not None
                              else set()),
                    "start_t_us": e["t_us"], "end_t_us": e["t_us"],
                })
        return [self._finish(i, n) for n, i in enumerate(incidents)]

    # ------------------------------------------------------------- finishing
    def _root_cause(self, members):
        causes = [m for m in members if m["kind"] in ("anomaly", "chaos")]
        if not causes:
            causes = members
        best = min(causes, key=lambda m: (m["t_us"],
                                          _severity_rank(m.get("severity")),
                                          m["seq"]))
        rc = {k: best[k] for k in ("seq", "t_us", "kind", "source")}
        # rank rides along so a MERGED (cross-rank) timeline's root cause
        # names WHICH rank the fault landed on, not just when
        for k in ("step", "rule", "chaos", "severity", "detail", "rank"):
            if k in best:
                rc[k] = best[k]
        rc["why"] = ("earliest causally-linked "
                     f"{'chaos injection' if best['kind'] == 'chaos' else 'anomaly'}"
                     " on the shared µs axis"
                     + (", severity tie-break" if len(
                         [c for c in causes
                          if c["t_us"] == best["t_us"]]) > 1 else ""))
        return rc

    def _goodput_cost(self, start_us, end_us):
        """Badput µs from every goodput_window overlapping the span.
        Each window event covers [t_us - dur_us, t_us]."""
        windows, badput = [], {}
        for e in self.events:
            if e["kind"] != "goodput_window":
                continue
            w_end, w_start = e["t_us"], e["t_us"] - int(e["dur_us"])
            if w_end < start_us or w_start > end_us:
                continue
            windows.append(e.get("index"))
            for c, us in e.get("categories_us", {}).items():
                if c not in GOOD_CATEGORIES:
                    badput[c] = badput.get(c, 0) + int(us)
        if not windows:
            return None
        return {"window_indices": windows,
                "badput_us": badput,
                "badput_total_us": sum(badput.values())}

    def _finish(self, inc, n):
        members = inc["members"]
        sev = min((m.get("severity") for m in members
                   if m.get("severity")), key=_severity_rank,
                  default=None)
        artifacts = []
        for m in members:
            a = m.get("artifact")
            if a and a not in artifacts:
                artifacts.append(a)
        steps = sorted(inc["steps"])
        return {
            "id": n,
            "start_t_us": inc["start_t_us"],
            "end_t_us": inc["end_t_us"],
            "duration_us": inc["end_t_us"] - inc["start_t_us"],
            "start_step": steps[0] if steps else None,
            "end_step": steps[-1] if steps else None,
            "severity": sev,
            "rules": sorted({m["rule"] for m in members if "rule" in m}),
            "actions": sorted({m["action"] for m in members
                               if "action" in m}),
            "root_cause": self._root_cause(members),
            "goodput_cost": self._goodput_cost(inc["start_t_us"],
                                               inc["end_t_us"]),
            "artifacts": artifacts,
            "events": members,
        }


def correlate(events, step_window=8, time_window_us=30_000_000,
              job_name=""):
    """One-call front door: events -> the INCIDENTS.json document."""
    incidents = IncidentCorrelator(
        events, step_window=step_window,
        time_window_us=time_window_us).correlate()
    return {
        "schema": INCIDENTS_SCHEMA,
        "job_name": job_name,
        "n_events": len(events),
        "params": {"step_window": int(step_window),
                   "time_window_us": int(time_window_us)},
        "incidents": incidents,
    }


def write_incidents(doc, path):
    _atomic_write_bytes(path, json.dumps(doc, indent=1, default=repr,
                                         allow_nan=False).encode())
    return path


def render(doc):
    """Human-readable rendering of an INCIDENTS.json document."""
    incs = doc.get("incidents", [])
    lines = [f"incidents: {len(incs)} from {doc.get('n_events', 0)} "
             f"event(s)"]
    for i in incs:
        rc = i.get("root_cause") or {}
        cost = i.get("goodput_cost") or {}
        lines.append(
            f"  #{i['id']} [{i.get('severity') or '-'}] steps "
            f"{i.get('start_step')}–{i.get('end_step')}, "
            f"{i['duration_us'] / 1e3:.1f} ms, {len(i['events'])} "
            f"event(s)")
        lines.append(
            f"      root cause: {rc.get('kind')}/{rc.get('source')} "
            f"{rc.get('rule') or rc.get('chaos') or ''} at step "
            f"{rc.get('step')} — {rc.get('why')}")
        if cost:
            lines.append(
                f"      goodput cost: "
                f"{cost.get('badput_total_us', 0) / 1e6:.3f} s badput "
                f"across windows {cost.get('window_indices')}")
        for a in i.get("artifacts", []):
            lines.append(f"      artifact: {a}")
    return "\n".join(lines)
