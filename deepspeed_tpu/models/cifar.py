"""CIFAR-10 CNN — BASELINE.json config #1 ("CIFAR-10 CNN
(DeepSpeedExamples/cifar) — ZeRO stage 0, fp32, single process").

The DeepSpeedExamples net (two conv+pool blocks, three fc layers — the
classic PyTorch-tutorial CNN) as a flax module following this package's
engine convention: ``__call__(batch)`` returns the mean cross-entropy.
Batch: (images [B, 32, 32, 3] float, labels [B] int32).
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


class CifarNet(nn.Module):
    """conv5x5(6) → pool → conv5x5(16) → pool → fc120 → fc84 → fc10."""
    num_classes: int = 10

    @nn.compact
    def __call__(self, batch, return_logits: bool = False):
        if isinstance(batch, (tuple, list)):
            images, labels = batch[0], (batch[1] if len(batch) > 1
                                        else None)
        else:
            images, labels = batch["images"], batch.get("labels")
        x = jnp.asarray(images)
        x = nn.relu(nn.Conv(6, (5, 5), padding="VALID", name="conv1")(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(nn.Conv(16, (5, 5), padding="VALID", name="conv2")(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape(x.shape[0], -1)
        x = nn.relu(nn.Dense(120, name="fc1")(x))
        x = nn.relu(nn.Dense(84, name="fc2")(x))
        logits = nn.Dense(self.num_classes, name="fc3")(x)
        if return_logits or labels is None:
            return logits
        logp = jax.nn.log_softmax(logits, axis=-1)
        ll = jnp.take_along_axis(logp, labels[:, None], axis=-1)
        return -jnp.mean(ll)


def synthetic_cifar_batch(batch_size, seed=0):
    rng = np.random.default_rng(seed)
    return (jnp.asarray(rng.standard_normal(
                (batch_size, 32, 32, 3)).astype(np.float32)),
            jnp.asarray(rng.integers(0, 10, batch_size, dtype=np.int32)))
