"""GPT-2 family — the flagship causal-LM for the BASELINE.json configs
("GPT-2 125M/350M/1.5B — ZeRO stages 1/2/3 + FusedAdam, fp16").

The reference trains GPT-2 through external Megatron-LM scripts
(tests/model/Megatron_GPT2/); the model itself is not in-tree. Here it is a
first-class flax module designed for the TPU compute path:

* attention runs through :func:`deepspeed_tpu.ops.transformer.attention`
  (Pallas flash kernel on TPU — O(seq) memory, MXU-shaped blocks);
* vocab padded to a multiple of 128 so the logits matmul tiles the MXU;
* ``remat`` wraps each block in ``jax.checkpoint`` (the activation-
  checkpointing analogue of the reference's
  runtime/activation_checkpointing);
* :func:`gpt2_tp_rules` gives megatron-style tensor-parallel
  PartitionSpecs (column-parallel QKV/fc1, row-parallel proj/fc2, vocab-
  sharded embedding) consumed by the engine's ModelParallelRules.

Batch convention: dict with ``input_ids`` [B, S] (int32); optional
``labels`` (defaults to next-token on input_ids). ``__call__`` returns the
scalar mean cross-entropy loss (the engine convention).
"""

import dataclasses
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.ops.quantizer.int8_linear import QuantDense
from deepspeed_tpu.ops.transformer.attention import attention


def _pad_vocab(v: int, multiple: int = 128) -> int:
    return ((v + multiple - 1) // multiple) * multiple


@dataclasses.dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50257
    n_positions: int = 1024
    n_embd: int = 768
    n_layer: int = 12
    n_head: int = 12
    dropout: float = 0.0
    remat: bool = False
    use_flash: Optional[bool] = None   # None = auto (Pallas on TPU)
    pp_stages: int = 1                 # pipeline stages for the block stack
    pp_microbatches: int = 1           # GPipe microbatches when pp_stages>1
    # sequence/context parallelism: "ring:<axis>" or "ulysses:<axis>"
    # shards the SEQUENCE over the named mesh axis (SURVEY.md §5.7 — the
    # modern long-context equivalent of the reference's sparse attention);
    # "sparse" / "sparse:<window_tokens>/<block>" runs block-sparse
    # attention (unidirectional Fixed layout through the round-5 fused
    # kernels — the reference applied sparse attention to GPT-style
    # models via SparseAttentionUtils too)
    attention_mode: str = "auto"
    # MoE-GPT (BASELINE.json config #4): >0 turns every
    # ``moe_expert_interval``-th block's MLP into a deepspeed MoE layer
    # (the reference Megatron-Deepspeed MoE-GPT recipe: experts on
    # alternate layers, aux loss added to the LM loss)
    moe_num_experts: int = 0
    moe_expert_interval: int = 2
    moe_k: int = 1
    moe_capacity_factor: float = 1.25
    moe_eval_capacity_factor: float = 1.25
    moe_aux_loss_coef: float = 0.01
    moe_dispatch_impl: str = "scatter"  # 'grouped'|'scatter'|'einsum'
    # "auto" keeps K/V in the activation dtype; "int8" stores the decode
    # cache quantized (per-row absmax scales) — half the cache HBM, the
    # dequant folds into the decode kernel's matmuls
    kv_cache_dtype: str = "auto"
    # "learned" = GPT-2 wpe table; "rope" = rotary embeddings on q/k
    # (ops/transformer/rotary.py — the reference apply_rotary_pos_emb
    # surface; interleaved-pair GPT-J convention)
    position_embedding: str = "learned"
    dtype: jnp.dtype = jnp.float32     # activation compute dtype is set by
                                       # the engine via param cast; this is
                                       # only for explicitly built models

    @property
    def padded_vocab(self) -> int:
        return _pad_vocab(self.vocab_size)

    def num_params(self) -> int:
        wpe = 0 if self.position_embedding == "rope" \
            else self.n_positions * self.n_embd
        wte = self.padded_vocab * self.n_embd
        per_layer = (12 * self.n_embd ** 2          # qkv+proj+fc1+fc2 kernels
                     + 13 * self.n_embd)            # biases + 2 LN
        return wte + wpe + self.n_layer * per_layer + 2 * self.n_embd


# Reference GPT-2 family sizes (125M/350M/774M/1.5B) — the BASELINE configs.
PRESETS = {
    "tiny": GPT2Config(vocab_size=512, n_positions=128, n_embd=64,
                       n_layer=2, n_head=4),
    "gpt2": GPT2Config(n_embd=768, n_layer=12, n_head=12),            # 125M
    "gpt2-medium": GPT2Config(n_embd=1024, n_layer=24, n_head=16),    # 350M
    "gpt2-large": GPT2Config(n_embd=1280, n_layer=36, n_head=20),     # 774M
    "gpt2-xl": GPT2Config(n_embd=1600, n_layer=48, n_head=25),        # 1.5B
}


class CausalSelfAttention(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(self, x, deterministic=True, decode=False):
        cfg = self.config
        B, S, E = x.shape
        H, D = cfg.n_head, E // cfg.n_head
        qkv = QuantDense(3 * E, name="qkv",
                         kernel_init=nn.initializers.normal(0.02))(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, S, H, D).transpose(0, 2, 1, 3)
        k = k.reshape(B, S, H, D).transpose(0, 2, 1, 3)
        v = v.reshape(B, S, H, D).transpose(0, 2, 1, 3)
        if cfg.position_embedding == "rope":
            from deepspeed_tpu.ops.transformer.rotary import \
                apply_rotary_pos_emb
        if not decode and cfg.position_embedding == "rope":
            q, k = apply_rotary_pos_emb(q, k, offset=0)
        if decode:
            # KV-cache path (reference: softmax_context_* KV-cache attention,
            # csrc/transformer/inference/csrc/pt_binding.cpp:829; the cache
            # itself replaces the global workspace of inference context.h).
            # First call = prefill (cache vars absent): allocate [B,H,T,D]
            # caches, write the prompt's K/V, run normal causal flash.
            # Later calls = one-token steps: append at cache_index, run the
            # decode kernel over the live prefix.
            from deepspeed_tpu.ops.transformer.decode import (
                aligned_cache_len, decode_attention,
                decode_attention_quantized, quantize_kv)
            is_step = self.has_variable("cache", "cached_key")
            assert cfg.kv_cache_dtype in ("auto", "int8"), (
                f"kv_cache_dtype must be 'auto' or 'int8', got "
                f"{cfg.kv_cache_dtype!r}")
            int8_cache = cfg.kv_cache_dtype == "int8"
            # block-aligned allocation: avoids a whole-cache pad copy per
            # decode step inside decode_attention
            T = aligned_cache_len(cfg.n_positions)
            cache_dtype = jnp.int8 if int8_cache else k.dtype
            ck = self.variable("cache", "cached_key", jnp.zeros,
                               (B, H, T, D), cache_dtype)
            cv = self.variable("cache", "cached_value", jnp.zeros,
                               (B, H, T, D), cache_dtype)
            ci = self.variable("cache", "cache_index",
                               lambda: jnp.zeros((), jnp.int32))
            if int8_cache:
                cks = self.variable("cache", "cached_key_scale", jnp.zeros,
                                    (B, H, T), jnp.float32)
                cvs = self.variable("cache", "cached_value_scale",
                                    jnp.zeros, (B, H, T), jnp.float32)

            def write(pos, k_new, v_new):
                if int8_cache:
                    kq, ks = quantize_kv(k_new)
                    vq, vs = quantize_kv(v_new)
                    ck.value = jax.lax.dynamic_update_slice(
                        ck.value, kq, (0, 0, pos, 0))
                    cv.value = jax.lax.dynamic_update_slice(
                        cv.value, vq, (0, 0, pos, 0))
                    cks.value = jax.lax.dynamic_update_slice(
                        cks.value, ks, (0, 0, pos))
                    cvs.value = jax.lax.dynamic_update_slice(
                        cvs.value, vs, (0, 0, pos))
                else:
                    ck.value = jax.lax.dynamic_update_slice(
                        ck.value, k_new, (0, 0, pos, 0))
                    cv.value = jax.lax.dynamic_update_slice(
                        cv.value, v_new, (0, 0, pos, 0))

            if not is_step:
                if cfg.position_embedding == "rope":
                    q, k = apply_rotary_pos_emb(q, k, offset=0)
                write(0, k, v)
                ci.value = jnp.asarray(S, jnp.int32)
                out = attention(q, k, v, causal=True, use_flash=cfg.use_flash)
            else:
                assert S == 1, f"decode steps take one token, got {S}"
                idx = ci.value
                if cfg.position_embedding == "rope":
                    q, k = apply_rotary_pos_emb(q, k, offset=idx)
                write(idx, k, v)
                ci.value = idx + 1
                if int8_cache:
                    out = decode_attention_quantized(
                        q, ck.value, cks.value, cv.value, cvs.value,
                        idx + 1, use_flash=cfg.use_flash)
                else:
                    out = decode_attention(q, ck.value, cv.value, idx + 1,
                                           use_flash=cfg.use_flash)
        elif cfg.attention_mode.startswith(("ring:", "ulysses:")):
            from deepspeed_tpu.ops.transformer.ring import (
                ring_attention, ulysses_attention)
            from deepspeed_tpu.utils import groups
            kind, axis = cfg.attention_mode.split(":")
            fn = ring_attention if kind == "ring" else ulysses_attention
            out = fn(q, k, v, groups.get_mesh(), axis, causal=True,
                     use_flash=cfg.use_flash)
        elif cfg.attention_mode.startswith("sparse"):
            # causal block-sparse: unidirectional Fixed layout through
            # the fused LUT kernels; "sparse:<window_tokens>/<block>"
            # (default 1024/128 — the measured long-seq optimum)
            from deepspeed_tpu.ops.sparse_attention.fused_kernels import (
                block_sparse_attention_fused, sparse_mode_layout)
            layout, blk = sparse_mode_layout(cfg.attention_mode, H, S)
            out = block_sparse_attention_fused(q, k, v, layout, block=blk,
                                               causal=True)
        else:
            out = attention(q, k, v, causal=True, use_flash=cfg.use_flash)
        out = out.transpose(0, 2, 1, 3).reshape(B, S, E)
        out = QuantDense(E, name="proj",
                         kernel_init=nn.initializers.normal(
                             0.02 / np.sqrt(2 * cfg.n_layer)))(out)
        if cfg.dropout > 0:
            out = nn.Dropout(cfg.dropout)(out, deterministic=deterministic)
        return out


class MLP(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(self, x, deterministic=True):
        cfg = self.config
        E = x.shape[-1]
        h = QuantDense(4 * E, name="fc",
                       kernel_init=nn.initializers.normal(0.02))(x)
        h = nn.gelu(h, approximate=True)
        h = QuantDense(E, name="proj",
                       kernel_init=nn.initializers.normal(
                           0.02 / np.sqrt(2 * cfg.n_layer)))(h)
        if cfg.dropout > 0:
            h = nn.Dropout(cfg.dropout)(h, deterministic=deterministic)
        return h


class Block(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(self, x, deterministic=True, decode=False):
        x = x + CausalSelfAttention(self.config, name="attn")(
            nn.LayerNorm(epsilon=1e-5, name="ln_1")(x), deterministic, decode)
        x = x + MLP(self.config, name="mlp")(
            nn.LayerNorm(epsilon=1e-5, name="ln_2")(x), deterministic)
        return x


class MoEBlock(nn.Module):
    """Transformer block whose MLP is a mixture of experts; returns
    (x, l_aux) so the model can add the load-balancing loss."""
    config: GPT2Config

    @nn.compact
    def __call__(self, x, deterministic=True, decode=False):
        cfg = self.config
        x = x + CausalSelfAttention(cfg, name="attn")(
            nn.LayerNorm(epsilon=1e-5, name="ln_1")(x), deterministic,
            decode)
        from deepspeed_tpu.moe.layer import MoE
        h = nn.LayerNorm(epsilon=1e-5, name="ln_2")(x)
        B, S, E = h.shape
        out, l_aux, _ = MoE(hidden_size=E,
                            num_experts=cfg.moe_num_experts,
                            k=cfg.moe_k,
                            capacity_factor=cfg.moe_capacity_factor,
                            eval_capacity_factor=(
                                cfg.moe_eval_capacity_factor),
                            dispatch_impl=cfg.moe_dispatch_impl,
                            name="moe")(h.reshape(B * S, E),
                                        train=not deterministic)
        return x + out.reshape(B, S, E), l_aux


class _PipeBlock(nn.Module):
    """Block adapted to the GPipe stage-body signature (single tensor
    arg); the deterministic flag is baked in at construction."""
    config: GPT2Config
    deterministic: bool = True

    @nn.compact
    def __call__(self, x):
        return Block(self.config, name="block")(x, self.deterministic)


class _OffloadEmbed(nn.Module):
    """First layer of the beyond-HBM decomposition: ids -> hidden."""
    config: GPT2Config

    @nn.compact
    def __call__(self, input_ids):
        cfg = self.config
        S = input_ids.shape[1]
        wte = self.param("wte", nn.initializers.normal(0.02),
                         (cfg.padded_vocab, cfg.n_embd))
        x = wte[input_ids]
        if cfg.position_embedding != "rope":
            wpe = self.param("wpe", nn.initializers.normal(0.01),
                             (cfg.n_positions, cfg.n_embd))
            x = x + wpe[None, :S].astype(wte.dtype)
        return x


class _OffloadHead(nn.Module):
    """Loss head of the beyond-HBM decomposition: (hidden, batch) -> CE.

    The LM head is UNTIED from the input embedding — the layer-streamed
    engine requires disjoint per-layer param sets (the reference's
    zero.Init partitions tied weights once but gathers them twice; here
    untying keeps each layer's working set independently streamable)."""
    config: GPT2Config

    @nn.compact
    def __call__(self, x, batch):
        cfg = self.config
        if isinstance(batch, (tuple, list)):
            ids, labels = batch[0], (batch[1] if len(batch) > 1 else None)
        else:
            ids, labels = batch["input_ids"], batch.get("labels")
        shift_labels = (ids if labels is None else labels)[:, 1:]
        x = nn.LayerNorm(epsilon=1e-5, name="ln_f")(x)
        head = self.param("lm_head", nn.initializers.normal(0.02),
                          (cfg.padded_vocab, cfg.n_embd))
        shift_logits = jnp.einsum("bse,ve->bsv", x[:, :-1], head,
                                  preferred_element_type=jnp.float32)
        from deepspeed_tpu.models.common import masked_next_token_ce
        return masked_next_token_ce(shift_logits, shift_labels)


def gpt2_offload_layers(cfg: GPT2Config, deterministic: bool = True):
    """LayerSpec decomposition for the ``Zero3OffloadEngine`` (params
    beyond one chip's HBM, streamed from host/NVMe): body layers map
    ``x -> x``; the last maps ``(x, batch) -> loss``. Drive via
    ``deepspeed_tpu.initialize(model=gpt2_offload_layers(cfg), config=
    {"zero_optimization": {"stage": 3, "offload_param": {"device":
    "cpu"}}, ...}, sample_batch=..., input_fn=lambda b: b["input_ids"])``.
    """
    return ([_OffloadEmbed(cfg)] +
            [_PipeBlock(cfg, deterministic) for _ in range(cfg.n_layer)] +
            [_OffloadHead(cfg)])


class GPT2LMHeadModel(nn.Module):
    """GPT-2 causal LM; returns mean next-token cross-entropy."""
    config: GPT2Config

    @nn.compact
    def __call__(self, batch, deterministic: Optional[bool] = None,
                 decode: bool = False, return_logits: bool = False):
        cfg = self.config
        if isinstance(batch, (tuple, list)):
            input_ids, labels = batch[0], (batch[1] if len(batch) > 1 else None)
        else:
            input_ids = batch["input_ids"]
            labels = batch.get("labels")
        if deterministic is None:
            deterministic = not self.has_rng("dropout")

        B, S = input_ids.shape
        wte = self.param("wte", nn.initializers.normal(0.02),
                         (cfg.padded_vocab, cfg.n_embd))
        assert cfg.position_embedding in ("learned", "rope"), (
            f"position_embedding must be 'learned' or 'rope', got "
            f"{cfg.position_embedding!r}")
        rope = cfg.position_embedding == "rope"
        wpe = None if rope else self.param(
            "wpe", nn.initializers.normal(0.01),
            (cfg.n_positions, cfg.n_embd))
        if decode:
            assert cfg.pp_stages == 1, "KV-cache decode incompatible with pp"
            assert not cfg.attention_mode.startswith(("ring:", "ulysses:")), \
                "KV-cache decode incompatible with sequence parallelism"
            assert not cfg.attention_mode.startswith("sparse"), (
                "KV-cache decode would silently run DENSE attention on a "
                "sparse-trained model; decode with attention_mode='auto'")
            return_logits = True
            is_step = self.has_variable("cache", "pos_index")
            pi = self.variable("cache", "pos_index",
                               lambda: jnp.zeros((), jnp.int32))
            if not is_step:
                pos_emb = None if rope else wpe[None, :S]
                pi.value = jnp.asarray(S, jnp.int32)
            else:
                pos_emb = None if rope else jax.lax.dynamic_slice(
                    wpe, (pi.value, 0), (S, cfg.n_embd))[None]
                pi.value = pi.value + S
            x = wte[input_ids]
            if pos_emb is not None:
                x = x + pos_emb.astype(wte.dtype)
        else:
            x = wte[input_ids]
            if not rope:
                x = x + wpe[None, :S].astype(wte.dtype)
        if cfg.dropout > 0:
            x = nn.Dropout(cfg.dropout)(x, deterministic=deterministic)

        moe_aux = jnp.float32(0.0)
        if cfg.moe_num_experts > 0:
            assert cfg.pp_stages == 1, (
                "MoE blocks are not expressible in the uniform GPipe "
                "stack; use the host-loop PipelineEngine for MoE + pp")
        if cfg.pp_stages > 1:
            # pipelined middle: blocks stream over the mesh pipe axis
            # (embedding/head stay outside, like the reference's first/last
            # stage LayerSpecs — runtime/pipe/module.py)
            from deepspeed_tpu.runtime.pipe.spmd import GPipe
            assert cfg.n_layer % cfg.pp_stages == 0
            x = GPipe(block_cls=_PipeBlock,
                      block_kwargs={"config": cfg,
                                    "deterministic": deterministic},
                      num_stages=cfg.pp_stages,
                      layers_per_stage=cfg.n_layer // cfg.pp_stages,
                      num_microbatches=cfg.pp_microbatches,
                      remat=cfg.remat,
                      name="pipe")(x)
        else:
            block = Block
            moe_block = MoEBlock
            if cfg.remat:
                block = nn.remat(Block, static_argnums=(2, 3))
                moe_block = nn.remat(MoEBlock, static_argnums=(2, 3))
            for i in range(cfg.n_layer):
                # every interval-th block, counting from the first so
                # interval=1 means every block (Megatron-Deepspeed places
                # experts on alternate layers with interval=2)
                is_moe = (cfg.moe_num_experts > 0 and
                          (i + 1) % cfg.moe_expert_interval == 0)
                if is_moe:
                    x, l_aux = moe_block(cfg, name=f"h_{i}")(
                        x, deterministic, decode)
                    moe_aux = moe_aux + l_aux
                else:
                    x = block(cfg, name=f"h_{i}")(x, deterministic, decode)
        x = nn.LayerNorm(epsilon=1e-5, name="ln_f")(x)

        # tied LM head; fp32 logits for a stable softmax
        if return_logits:
            return jnp.einsum("bse,ve->bsv", x, wte,
                              preferred_element_type=jnp.float32)

        if labels is None:
            shift_labels = input_ids[:, 1:]
        else:
            shift_labels = labels[:, 1:]
        # Slice BEFORE the LM-head matmul (the last position predicts
        # nothing) so the [B,S,V] fp32 logits tensor is never copied, and
        # use the logsumexp-minus-gold form of cross-entropy: it writes
        # only [B,S] intermediates where log_softmax+gather would
        # materialise a second full [B,S,V] fp32 array — at bench shape
        # that is ~3.3 GB of HBM traffic per micro-step saved.
        shift_logits = jnp.einsum("bse,ve->bsv", x[:, :-1], wte,
                                  preferred_element_type=jnp.float32)
        from deepspeed_tpu.models.common import masked_next_token_ce
        ce = masked_next_token_ce(shift_logits, shift_labels)
        return ce + cfg.moe_aux_loss_coef * moe_aux


def gpt2_tp_rules():
    """Megatron-style tensor-parallel rules for this model family.

    Column-parallel: qkv + mlp/fc kernels split on the output dim.
    Row-parallel: attn/proj + mlp/proj split on the input dim (XLA inserts
    the psum the reference's RowParallelLinear issues by hand).
    Embedding: vocab-sharded (megatron VocabParallelEmbedding).
    """
    return [
        (r"\bwte$", P("model", None)),
        (r"attn/qkv/kernel", P(None, "model")),
        (r"attn/qkv/bias", P("model",)),
        (r"attn/proj/kernel", P("model", None)),
        (r"mlp/fc/kernel", P(None, "model")),
        (r"mlp/fc/bias", P("model",)),
        (r"mlp/proj/kernel", P("model", None)),
    ]


def gpt2_pp_rules():
    """Sharding rules for the PIPELINED model (pp_stages > 1): stacked
    stage params carry a leading [n_stages] dim, so TP specs shift right
    one position behind the pipe axis. Order matters — these must precede
    the plain TP rules (ModelParallelRules takes the first match)."""
    return [
        (r"pipe_loop.*attn/qkv/kernel", P("pipe", None, "model")),
        (r"pipe_loop.*attn/qkv/bias", P("pipe", "model")),
        (r"pipe_loop.*attn/proj/kernel", P("pipe", "model", None)),
        (r"pipe_loop.*mlp/fc/kernel", P("pipe", None, "model")),
        (r"pipe_loop.*mlp/fc/bias", P("pipe", "model")),
        (r"pipe_loop.*mlp/proj/kernel", P("pipe", "model", None)),
        (r"pipe_loop.*", P("pipe")),   # LN params etc: pipe-stacked only
    ]


def synthetic_batch(batch_size: int, seq_len: int, vocab_size: int, seed=0):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, vocab_size, (batch_size, seq_len), dtype=np.int32)
    return {"input_ids": jnp.asarray(ids)}
