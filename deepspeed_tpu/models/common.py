"""Shared model-family helpers."""

import jax
import jax.numpy as jnp


def masked_next_token_ce(shift_logits, shift_labels):
    """Fused next-token cross-entropy: logsumexp-minus-gold with the
    ignore_index=-100 masking convention.

    This is the perf-critical CE form (no second [B, S, V] fp32 array is
    materialised, unlike log_softmax+gather); both GPT-2 and GPT-J route
    through here so numerical/masking fixes land once. Inputs are already
    shifted: ``shift_logits[b, s]`` predicts ``shift_labels[b, s]``."""
    shift_logits = shift_logits.astype(jnp.float32)
    lse = jax.scipy.special.logsumexp(shift_logits, axis=-1)
    gold = jnp.take_along_axis(
        shift_logits, jnp.maximum(shift_labels, 0)[..., None],
        axis=-1)[..., 0]
    valid = (shift_labels >= 0).astype(jnp.float32)
    return ((lse - gold) * valid).sum() / jnp.maximum(valid.sum(), 1.0)
