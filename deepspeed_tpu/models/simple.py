"""Test-fixture models.

The analogue of the reference's ``tests/unit/simple_model.py``
(``SimpleModel`` :14, ``LinearStack`` :67, random-data loaders) as flax
modules that return the loss directly from ``__call__(batch)`` — matching
the DeepSpeed convention where the wrapped module computes its own loss.
"""

from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


class SimpleModel(nn.Module):
    """hidden→hidden linear + CE-ish loss (reference SimpleModel)."""
    hidden_dim: int
    nlayers: int = 1

    @nn.compact
    def __call__(self, batch):
        x, y = batch
        for _ in range(self.nlayers):
            x = nn.Dense(self.hidden_dim)(x)
        # squared error against targets (reference uses CrossEntropy on
        # random labels; MSE keeps the fixture dtype-agnostic)
        return jnp.mean((x - y) ** 2)


class LinearStack(nn.Module):
    """Deep stack of equal Linear layers (reference LinearStack :67)."""
    input_dim: int = 128
    hidden_dim: int = 128
    output_dim: int = 128
    num_layers: int = 4

    @nn.compact
    def __call__(self, batch):
        x, y = batch
        x = nn.Dense(self.hidden_dim, use_bias=False)(x)
        for _ in range(self.num_layers):
            x = nn.relu(nn.Dense(self.hidden_dim, use_bias=False)(x))
        x = nn.Dense(self.output_dim, use_bias=False)(x)
        return jnp.mean((x - y) ** 2)


class EmbeddingModel(nn.Module):
    """Embedding table + head — the sparse-gradient fixture (analogue of
    the reference's nn.Embedding(sparse=True) models in test sparse
    allreduce paths). The table's grad touches only the batch's token
    rows."""
    vocab: int
    dim: int

    @nn.compact
    def __call__(self, batch):
        ids, y = batch["input_ids"], batch["targets"]
        x = nn.Embed(self.vocab, self.dim, name="wte")(ids)
        x = x.mean(axis=1)
        x = nn.Dense(self.dim)(x)
        return jnp.mean((x - y) ** 2)


def random_dataset(total_samples, hidden_dim, seed=0, dtype=np.float32):
    """(x, y) pairs of gaussian vectors (reference random_dataset)."""
    rng = np.random.default_rng(seed)
    xs = rng.standard_normal((total_samples, hidden_dim)).astype(dtype)
    ys = rng.standard_normal((total_samples, hidden_dim)).astype(dtype)
    return [(xs[i], ys[i]) for i in range(total_samples)]


def random_dataloader(model_engine, total_samples, hidden_dim, seed=0,
                      dtype=np.float32):
    batch_size = model_engine.train_micro_batch_size_per_gpu() * \
        model_engine.dp_world_size
    ds = random_dataset(total_samples, hidden_dim, seed=seed, dtype=dtype)
    from deepspeed_tpu.runtime.dataloader import DeepSpeedDataLoader
    return DeepSpeedDataLoader(ds, batch_size=batch_size)


def sample_batch(batch_size, hidden_dim, dtype=jnp.float32):
    return (jnp.zeros((batch_size, hidden_dim), dtype),
            jnp.zeros((batch_size, hidden_dim), dtype))
