"""GPT-J model family (flax) — the injection target for HF GPT-J layers.

The reference handles GPT-J via kernel injection
(deepspeed/module_inject/replace_policy.py:147 ``GPTJLayerPolicy``:
rotary_dim + mlp_after_attn=False into DeepSpeedTransformerInference).
Here the TPU-native equivalent is a flax model built on this package's
ops (flash attention + partial rotary): ``hf_gptj_to_params`` maps an HF
``GPTJForCausalLM`` state dict onto it, logits-parity tested against
transformers.

Architecture (HF modeling_gptj.py): no learned positions (partial rotary
on the leading ``rotary_dim`` features, interleaved-pair convention),
q/k/v/out projections without bias, PARALLEL residual
(x + attn(ln(x)) + mlp(ln(x))), untied biased LM head.
"""

import dataclasses
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.ops.transformer.attention import attention
from deepspeed_tpu.ops.transformer.rotary import apply_rotary_pos_emb


@dataclasses.dataclass(frozen=True)
class GPTJConfig:
    vocab_size: int = 50400
    n_positions: int = 2048
    n_embd: int = 4096
    n_layer: int = 28
    n_head: int = 16
    rotary_dim: int = 64
    n_inner: Optional[int] = None       # default 4*n_embd
    layer_norm_epsilon: float = 1e-5
    use_flash: bool = True

    @property
    def inner(self):
        return self.n_inner or 4 * self.n_embd


class GPTJBlock(nn.Module):
    config: GPTJConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        B, S, E = x.shape
        H, D = cfg.n_head, E // cfg.n_head
        h = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, name="ln_1")(x)

        qkv = nn.Dense(3 * E, use_bias=False, name="qkv")(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, S, H, D).transpose(0, 2, 1, 3)
        k = k.reshape(B, S, H, D).transpose(0, 2, 1, 3)
        v = v.reshape(B, S, H, D).transpose(0, 2, 1, 3)
        q, k = apply_rotary_pos_emb(q, k, rotary_dim=cfg.rotary_dim)
        attn_out = attention(q, k, v, causal=True, use_flash=cfg.use_flash)
        attn_out = attn_out.transpose(0, 2, 1, 3).reshape(B, S, E)
        attn_out = nn.Dense(E, use_bias=False, name="out_proj")(attn_out)

        m = nn.Dense(cfg.inner, name="fc_in")(h)
        m = nn.gelu(m, approximate=True)
        m = nn.Dense(E, name="fc_out")(m)

        # parallel residual (mlp_after_attn=False in the reference policy)
        return x + attn_out + m


class GPTJForCausalLM(nn.Module):
    """Causal LM; returns mean next-token CE, or logits with
    ``return_logits=True`` (InferenceEngine recompute-generate protocol)."""
    config: GPTJConfig

    @nn.compact
    def __call__(self, batch, return_logits: bool = False):
        cfg = self.config
        if isinstance(batch, (tuple, list)):
            input_ids, labels = batch[0], (batch[1] if len(batch) > 1
                                           else None)
        else:
            input_ids = batch["input_ids"]
            labels = batch.get("labels") if isinstance(batch, dict) else None

        wte = self.param("wte", nn.initializers.normal(0.02),
                         (cfg.vocab_size, cfg.n_embd))
        x = wte[input_ids]
        for i in range(cfg.n_layer):
            x = GPTJBlock(cfg, name=f"h_{i}")(x)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, name="ln_f")(x)
        if return_logits:
            return nn.Dense(cfg.vocab_size, name="lm_head")(x)
        # slice before the head matmul (gpt2.py loss convention: the last
        # position predicts nothing) and share the fused masked CE
        from deepspeed_tpu.models.common import masked_next_token_ce
        shift_labels = (input_ids if labels is None else labels)[:, 1:]
        shift_logits = nn.Dense(cfg.vocab_size, name="lm_head")(x[:, :-1])
        return masked_next_token_ce(shift_logits, shift_labels)


def gptj_tp_rules():
    """Megatron-style TP rules for the GPT-J blocks (column-shard qkv +
    fc_in, row-shard out_proj + fc_out) — the tensor-slicing half of the
    reference GPTJLayerPolicy."""
    from jax.sharding import PartitionSpec as P
    return [
        (r"h_\d+/qkv/kernel", P(None, "model")),
        (r"h_\d+/fc_in/kernel", P(None, "model")),
        (r"h_\d+/fc_in/bias", P("model")),
        (r"h_\d+/out_proj/kernel", P("model", None)),
        (r"h_\d+/fc_out/kernel", P("model", None)),
    ]


def is_hf_gptj_state_dict(sd) -> bool:
    """HF GPT-J naming: transformer.h.N.attn.q_proj (no .attention. level,
    unlike GPT-Neo) + rotary (no wpe)."""
    keys = list(sd)
    return (any(".attn.q_proj.weight" in k for k in keys)
            and not any(".attn.attention." in k for k in keys))


def hf_gptj_to_params(state_dict, config: GPTJConfig):
    """Map an HF ``GPTJForCausalLM`` state dict onto :class:`GPTJForCausalLM`
    params. torch Linear stores [out, in] -> transpose to flax [in, out];
    q/k/v concatenate into the fused qkv kernel."""
    from deepspeed_tpu.runtime.state_dict_factory import (_hf_get,
                                                          _hf_layer_count)

    def get(name):
        return _hf_get(state_dict, name)

    ckpt_layers = _hf_layer_count(state_dict)
    assert ckpt_layers == config.n_layer, (
        f"checkpoint has {ckpt_layers} layers, config says "
        f"n_layer={config.n_layer}")

    p = {"wte": get("wte.weight"),
         "ln_f": {"scale": get("ln_f.weight"), "bias": get("ln_f.bias")},
         "lm_head": {"kernel": np.asarray(state_dict["lm_head.weight"],
                                          np.float32).T,
                     "bias": np.asarray(state_dict["lm_head.bias"],
                                        np.float32)}}
    for i in range(config.n_layer):
        pre = f"h.{i}"
        qkv = np.concatenate(
            [get(f"{pre}.attn.q_proj.weight").T,
             get(f"{pre}.attn.k_proj.weight").T,
             get(f"{pre}.attn.v_proj.weight").T], axis=1)
        p[f"h_{i}"] = {
            "ln_1": {"scale": get(f"{pre}.ln_1.weight"),
                     "bias": get(f"{pre}.ln_1.bias")},
            "qkv": {"kernel": qkv},
            "out_proj": {"kernel": get(f"{pre}.attn.out_proj.weight").T},
            "fc_in": {"kernel": get(f"{pre}.mlp.fc_in.weight").T,
                      "bias": get(f"{pre}.mlp.fc_in.bias")},
            "fc_out": {"kernel": get(f"{pre}.mlp.fc_out.weight").T,
                       "bias": get(f"{pre}.mlp.fc_out.bias")},
        }
    return p
