"""BERT family — the BASELINE config #3 model ("BERT-large pretrain —
csrc/transformer fused kernel + FusedLamb + sparse_attn").

The reference trains BERT through DeepSpeedExamples' bing_bert scripts
with the fused DeepSpeedTransformerLayer injected (tests vendor the HF
implementation in tests/unit/modeling.py). Here the encoder layer IS the
fused layer (ops/transformer/transformer.py), stacked with embeddings and
an MLM head. Batch convention: dict with ``input_ids`` [B, S],
``attention_mask`` optional, ``labels`` optional (-100 = ignore; default
is masked-LM on input positions where labels given).
"""

import dataclasses
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.ops.transformer.transformer import (
    DeepSpeedTransformerConfig, DeepSpeedTransformerLayer,
    transformer_tp_rules)


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    hidden_dropout_prob: float = 0.0
    attention_probs_dropout_prob: float = 0.0
    layer_norm_eps: float = 1e-12
    pre_layer_norm: bool = False       # classic BERT is post-LN
    remat: bool = False
    # block-sparse attention (reference sparse_attention_utils.py
    # replace_model_self_attention): None = dense fused layer; else one of
    # fixed|variable|bigbird|bslongformer|dense with the block geometry
    sparse_attention_mode: Optional[str] = None
    sparse_block: int = 16
    sparse_num_local_blocks: int = 4
    sparse_num_global_blocks: int = 1

    @property
    def padded_vocab(self):
        return ((self.vocab_size + 127) // 128) * 128


PRESETS = {
    "bert-base": BertConfig(),
    "bert-large": BertConfig(hidden_size=1024, num_hidden_layers=24,
                             num_attention_heads=16, intermediate_size=4096),
    "tiny": BertConfig(vocab_size=512, hidden_size=64, num_hidden_layers=2,
                       num_attention_heads=4, intermediate_size=256,
                       max_position_embeddings=128),
}


class BertLayer(nn.Module):
    """Thin named wrapper so injection policies can match it."""
    hidden_size: int
    num_heads: int
    intermediate_size: int
    pre_layer_norm: bool = False
    dropout: float = 0.0
    attn_dropout: float = 0.0
    layer_norm_eps: float = 1e-12

    @nn.compact
    def __call__(self, x, mask=None, deterministic=True):
        cfg = DeepSpeedTransformerConfig(
            hidden_size=self.hidden_size,
            heads=self.num_heads,
            intermediate_size=self.intermediate_size,
            pre_layer_norm=self.pre_layer_norm,
            hidden_dropout_ratio=self.dropout,
            attn_dropout_ratio=self.attn_dropout,
            layer_norm_eps=self.layer_norm_eps)
        return DeepSpeedTransformerLayer(cfg, name="layer")(
            x, mask, deterministic)


class BertSparseLayer(nn.Module):
    """Encoder layer whose self-attention is block-sparse — the model-side
    substitution the reference performs with
    sparse_attention_utils.replace_model_self_attention +
    BertSparseSelfAttention. Classic post-LN arrangement."""
    hidden_size: int
    num_heads: int
    intermediate_size: int
    sparsity_mode: str = "fixed"
    block: int = 16
    num_local_blocks: int = 4
    num_global_blocks: int = 1
    layer_norm_eps: float = 1e-12
    dropout: float = 0.0

    def _sparsity_config(self):
        from deepspeed_tpu.ops.sparse_attention.sparsity_config import (
            BigBirdSparsityConfig, BSLongformerSparsityConfig,
            DenseSparsityConfig, FixedSparsityConfig,
            VariableSparsityConfig)
        mode = self.sparsity_mode
        if mode == "fixed":
            return FixedSparsityConfig(
                num_heads=self.num_heads, block=self.block,
                num_local_blocks=self.num_local_blocks,
                num_global_blocks=self.num_global_blocks)
        if mode == "bigbird":
            return BigBirdSparsityConfig(num_heads=self.num_heads,
                                         block=self.block)
        if mode == "bslongformer":
            return BSLongformerSparsityConfig(num_heads=self.num_heads,
                                              block=self.block)
        if mode == "variable":
            return VariableSparsityConfig(num_heads=self.num_heads,
                                          block=self.block)
        if mode == "dense":
            return DenseSparsityConfig(num_heads=self.num_heads,
                                       block=self.block)
        raise ValueError(f"unknown sparse attention mode {mode!r}")

    @nn.compact
    def __call__(self, x, mask=None, deterministic=True):
        from deepspeed_tpu.ops.sparse_attention.sparse_self_attention import \
            BertSparseSelfAttention
        init = nn.initializers.normal(0.02)   # BERT convention, matching
        ctx = BertSparseSelfAttention(          # the dense fused layer
            hidden_size=self.hidden_size,
            num_attention_heads=self.num_heads,
            sparsity_config=self._sparsity_config(),
            name="attention")(x, mask)
        attn_out = nn.Dense(self.hidden_size, kernel_init=init,
                            name="attn_out")(ctx)
        if self.dropout > 0:
            attn_out = nn.Dropout(self.dropout)(attn_out, deterministic)
        x = nn.LayerNorm(epsilon=self.layer_norm_eps,
                         name="attn_ln")(x + attn_out)
        h = nn.Dense(self.intermediate_size, kernel_init=init,
                     name="fc")(x)
        h = nn.gelu(h, approximate=True)
        h = nn.Dense(self.hidden_size, kernel_init=init, name="out")(h)
        if self.dropout > 0:
            h = nn.Dropout(self.dropout)(h, deterministic)
        return nn.LayerNorm(epsilon=self.layer_norm_eps,
                            name="out_ln")(x + h)


class BertForPreTraining(nn.Module):
    """Embeddings + fused encoder stack + tied MLM head; returns the MLM
    cross-entropy (next-sentence head omitted — modern practice and the
    perf-relevant path)."""
    config: BertConfig

    @nn.compact
    def __call__(self, batch, deterministic: Optional[bool] = None):
        cfg = self.config
        if isinstance(batch, (tuple, list)):
            input_ids, labels = batch[0], (batch[1] if len(batch) > 1
                                           else None)
            mask = None
        else:
            input_ids = batch["input_ids"]
            labels = batch.get("labels")
            mask = batch.get("attention_mask")
        if deterministic is None:
            deterministic = not self.has_rng("dropout")
        B, S = input_ids.shape

        wte = self.param("word_embeddings", nn.initializers.normal(0.02),
                         (cfg.padded_vocab, cfg.hidden_size))
        wpe = self.param("position_embeddings", nn.initializers.normal(0.02),
                         (cfg.max_position_embeddings, cfg.hidden_size))
        tte = self.param("token_type_embeddings",
                         nn.initializers.normal(0.02),
                         (cfg.type_vocab_size, cfg.hidden_size))
        x = wte[input_ids] + wpe[None, :S] + tte[0][None, None]
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, name="emb_ln")(x)
        if cfg.hidden_dropout_prob > 0:
            x = nn.Dropout(cfg.hidden_dropout_prob)(x, deterministic)

        if cfg.sparse_attention_mode is not None:
            assert cfg.attention_probs_dropout_prob == 0, (
                "the block-sparse kernel has no attention-dropout input; "
                "set attention_probs_dropout_prob=0 for sparse mode")
            assert not cfg.pre_layer_norm, (
                "BertSparseLayer is post-LN (classic BERT); pre_layer_norm "
                "is not implemented for sparse mode")
            sparse_cls = BertSparseLayer
            if cfg.remat:
                sparse_cls = nn.remat(BertSparseLayer, static_argnums=(3,))
            for i in range(cfg.num_hidden_layers):
                x = sparse_cls(
                    hidden_size=cfg.hidden_size,
                    num_heads=cfg.num_attention_heads,
                    intermediate_size=cfg.intermediate_size,
                    sparsity_mode=cfg.sparse_attention_mode,
                    block=cfg.sparse_block,
                    num_local_blocks=cfg.sparse_num_local_blocks,
                    num_global_blocks=cfg.sparse_num_global_blocks,
                    layer_norm_eps=cfg.layer_norm_eps,
                    dropout=cfg.hidden_dropout_prob,
                    name=f"layer_{i}")(x, mask, deterministic)
        else:
            layer_cls = BertLayer
            if cfg.remat:
                layer_cls = nn.remat(BertLayer, static_argnums=(3,))
            for i in range(cfg.num_hidden_layers):
                x = layer_cls(hidden_size=cfg.hidden_size,
                              num_heads=cfg.num_attention_heads,
                              intermediate_size=cfg.intermediate_size,
                              pre_layer_norm=cfg.pre_layer_norm,
                              dropout=cfg.hidden_dropout_prob,
                              attn_dropout=cfg.attention_probs_dropout_prob,
                              layer_norm_eps=cfg.layer_norm_eps,
                              name=f"layer_{i}")(x, mask, deterministic)

        # MLM transform + tied decoder (BertLMPredictionHead). When the
        # batch carries masked_lm_positions (the reference pretraining
        # data format, max_predictions_per_seq positions per sequence),
        # the whole head runs ONLY on those P << S positions — the
        # [B,S,V] logits tensor never exists; at seq 128 / P 20 that is
        # 6.4x less head matmul and ~1 GB less fp32 HBM traffic per step.
        positions = (batch.get("masked_positions")
                     if isinstance(batch, dict) else None)
        if positions is not None:
            labels = batch["masked_labels"]
            x = jnp.take_along_axis(x, positions[..., None], axis=1)
        h = nn.Dense(cfg.hidden_size, name="mlm_dense")(x)
        h = nn.gelu(h, approximate=True)
        h = nn.LayerNorm(epsilon=cfg.layer_norm_eps, name="mlm_ln")(h)
        logits = jnp.einsum("bsh,vh->bsv", h, wte,
                            preferred_element_type=jnp.float32)
        logits = logits + self.param("mlm_bias", nn.initializers.zeros,
                                     (cfg.padded_vocab,))

        if labels is None:
            return logits
        # fused logsumexp-minus-gold CE with -100 masking: no second
        # [B,S,V] fp32 array — at the bench shape (64x128x30k) that array
        # alone is 1 GB of HBM traffic per micro-step
        from deepspeed_tpu.models.common import masked_next_token_ce
        return masked_next_token_ce(logits, labels)


def bert_tp_rules():
    rules = [(r"word_embeddings$",
              __import__("jax").sharding.PartitionSpec("model", None))]
    return rules + transformer_tp_rules()


def synthetic_mlm_batch(batch_size, seq_len, vocab_size, mask_prob=0.15,
                        seed=0, masked_positions_format=False):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, vocab_size, (batch_size, seq_len), dtype=np.int32)
    if masked_positions_format:
        # the reference pretraining data format: a FIXED number of masked
        # positions per sequence (max_predictions_per_seq) so the MLM head
        # runs on [B, P] gathered positions, not the full sequence
        P = max(1, int(round(seq_len * mask_prob)))
        positions = np.stack([
            np.sort(rng.choice(seq_len, size=P, replace=False))
            for _ in range(batch_size)]).astype(np.int32)
        labels = np.take_along_axis(ids, positions, axis=1)
        np.put_along_axis(ids, positions, 103, axis=1)  # [MASK]
        return {"input_ids": jnp.asarray(ids),
                "masked_positions": jnp.asarray(positions),
                "masked_labels": jnp.asarray(labels)}
    labels = np.full_like(ids, -100)
    mask = rng.random((batch_size, seq_len)) < mask_prob
    labels[mask] = ids[mask]
    ids[mask] = 103  # [MASK]
    return {"input_ids": jnp.asarray(ids), "labels": jnp.asarray(labels)}
