"""Deterministic chaos/fault-injection harness (``deepspeed_tpu.testing.chaos``).

Test-support code only: nothing in the runtime imports this package, so a
production process never pays for (or accidentally arms) an injector.
"""

from deepspeed_tpu.testing.chaos import (   # noqa: F401
    ChaosFault,
    DivergenceChaos,
    FaultSchedule,
    FilesystemChaos,
    Injector,
    PoolStarvationChaos,
    SigkillChaos,
    SlowCollateIterator,
)
