"""Deterministic chaos harness: seeded, budgeted fault injectors.

The guardian (``runtime/guardian.py``) closes the anomaly->action loop;
this module is the other half of the proof — a way to MAKE the anomalies
happen, deterministically, so an e2e test can assert that each policy
fires, acts, and the run actually recovers.

Design rules every injector follows:

* **seeded** — the fault schedule is a pure function of the seed and the
  call sequence (``random.Random(seed)``, never global randomness), so a
  failing chaos test replays bit-identically;
* **budgeted** — an injector stops firing after ``budget`` faults; an
  exhausted schedule is the "transient failure" shape retry logic must
  survive (and tests assert exhaustion explicitly);
* **reversible** — patched call sites are recorded and restored in
  reverse order by ``uninstall()`` (or context-manager exit); teardown
  leaves the process exactly as found, and the unit suite asserts it.

Nothing in the runtime imports this module — chaos is pulled in by tests
(and the guardian demo CLI) only.
"""

import errno
import os
import random
import signal
import time

from deepspeed_tpu.telemetry import chronicle as _chronicle
from deepspeed_tpu.utils.logging import logger


def _chronicle_chaos(name, step=None, detail=None, **data):
    """Every injector names its own ground truth in the run chronicle:
    a chaos-driven run's incident timeline starts at the injection, so
    the correlator can rank the poison — not the loudest symptom — as
    root cause."""
    chron = _chronicle.get_chronicle()
    if chron.enabled:
        chron.emit("chaos", source="chaos", step=step,
                   severity="critical", chaos=name, detail=detail,
                   **data)


class ChaosFault(OSError):
    """The synthetic failure an injector raises. An ``OSError`` on
    purpose: retry/fallback paths must treat it exactly like the real
    transient I/O error it stands in for."""


class FaultSchedule:
    """Seeded fire/don't-fire decision stream with an error budget.

    ``should_fire()`` is called once per guarded operation: it fires with
    probability ``p`` (1.0 = every call) once ``start_after`` calls have
    passed, and never more than ``budget`` times total. Two schedules
    built with the same arguments make identical decisions.
    """

    def __init__(self, seed=0, p=1.0, budget=1, start_after=0):
        self._rng = random.Random(seed)
        self.p = float(p)
        self.budget = int(budget)
        self.start_after = int(start_after)
        self.calls = 0
        self.fired = 0

    def should_fire(self):
        self.calls += 1
        if self.calls <= self.start_after or self.exhausted:
            return False
        # the RNG is consumed only on eligible calls so start_after does
        # not shift the decision stream
        if self.p >= 1.0 or self._rng.random() < self.p:
            self.fired += 1
            return True
        return False

    @property
    def exhausted(self):
        return self.fired >= self.budget

    def describe(self):
        return {"calls": self.calls, "fired": self.fired,
                "budget": self.budget, "exhausted": self.exhausted}


class Injector:
    """Reversible monkey-patching base.

    Subclasses implement ``_install()`` (declaring patches through
    ``self._patch(obj, name, replacement)``) and optionally
    ``_uninstall()`` for non-attribute resources (e.g. held pool
    blocks). ``uninstall`` restores every patched attribute in reverse
    order and is idempotent; the context-manager form guarantees
    restoration even when the test body throws.
    """

    def __init__(self):
        self._patches = []           # (obj, name, original), applied order
        self.installed = False

    def install(self):
        if not self.installed:
            self._install()
            self.installed = True
        return self

    def uninstall(self):
        if not self.installed:
            return
        try:
            self._uninstall()
        finally:
            while self._patches:
                obj, name, original = self._patches.pop()
                setattr(obj, name, original)
            self.installed = False

    def _install(self):
        raise NotImplementedError

    def _uninstall(self):
        pass

    def _patch(self, obj, name, replacement):
        original = getattr(obj, name)
        self._patches.append((obj, name, original))
        setattr(obj, name, replacement)
        return original

    def __enter__(self):
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()
        return False


class FilesystemChaos(Injector):
    """Budgeted checkpoint write/rename failures.

    Patches ``checkpoint_io._atomic_write`` — the single seam every
    checkpoint byte goes through (tmp write + fsync + rename) — so a
    fired fault aborts with :class:`ChaosFault` and the real file name is
    never touched. ``op="write"`` fails before any bytes land;
    ``op="rename"`` lands the bytes in a tmp sibling first and then
    fails, leaving exactly the stray-tmp debris a real rename failure
    leaves (readers skip tmp-marked names by contract).
    """

    def __init__(self, seed=0, p=1.0, budget=2, start_after=0, op="write"):
        super().__init__()
        if op not in ("write", "rename"):
            raise ValueError(f"op must be 'write' or 'rename', got {op!r}")
        self.schedule = FaultSchedule(seed=seed, p=p, budget=budget,
                                      start_after=start_after)
        self.op = op

    def _install(self):
        from deepspeed_tpu.runtime import checkpoint_io
        orig = checkpoint_io._atomic_write

        def _chaotic_atomic_write(path, write_fn):
            if self.schedule.should_fire():
                if self.op == "rename":
                    tmp = f"{path}{checkpoint_io._TMP_MARK}chaos"
                    with open(tmp, "wb") as f:
                        write_fn(f)
                _chronicle_chaos(
                    "filesystem",
                    detail=f"injected {self.op} failure for "
                           f"{os.path.basename(path)}")
                raise ChaosFault(
                    errno.EIO,
                    f"chaos: injected {self.op} failure "
                    f"({self.schedule.fired}/{self.schedule.budget}) for "
                    f"{os.path.basename(path)}")
            return orig(path, write_fn)

        self._patch(checkpoint_io, "_atomic_write", _chaotic_atomic_write)


class DivergenceChaos(Injector):
    """Poison the model parameters with inf/NaN before a chosen step.

    Patches the engine instance's ``train_batch`` so the Nth call (1-based
    ``at_call``) first overwrites every leaf of one param bucket with
    ``value``. The next forward produces a non-finite loss and the grad
    census flags the bucket — the exact "run diverged" signature the
    guardian's rollback policy confirms on (loss_spike + nonfinite_grads
    streak). Restoring the checkpointed params is the only cure, which is
    what makes this the honest rollback proof.
    """

    def __init__(self, engine, at_call, value=float("inf"), budget=1):
        super().__init__()
        self.engine = engine
        self.at_call = int(at_call)
        self.value = float(value)
        self.budget = int(budget)
        self.calls = 0
        self.poisoned_steps = []

    def _poison(self):
        import jax
        import jax.numpy as jnp
        eng = self.engine
        # poison the FIRST param leaf only: a realistic partial corruption
        # (one module's weights), and the census names its bucket
        leaves, treedef = jax.tree_util.tree_flatten(eng.state.params)
        poisoned = [jax.device_put(jnp.full_like(leaves[0], self.value),
                                   leaves[0].sharding)] + leaves[1:]
        eng.state = eng.state._replace(
            params=jax.tree_util.tree_unflatten(treedef, poisoned))
        self.poisoned_steps.append(int(eng.global_steps))
        _chronicle_chaos(
            "divergence", step=int(eng.global_steps),
            detail=f"params poisoned with {self.value} before "
                   f"train_batch call {self.calls}")
        logger.warning(
            f"chaos: poisoned params with {self.value} before train_batch "
            f"call {self.calls} (global_step {eng.global_steps})")

    def _install(self):
        eng = self.engine
        orig = eng.train_batch

        def _chaotic_train_batch(*args, **kwargs):
            self.calls += 1
            if self.calls == self.at_call \
                    and len(self.poisoned_steps) < self.budget:
                self._poison()
            return orig(*args, **kwargs)

        self._patch(eng, "train_batch", _chaotic_train_batch)


class SlowCollateIterator:
    """Wrap a data iterator so chosen ``__next__`` calls stall.

    The injected sleep happens where a slow collate/storage stall would:
    inside ``next()``, which the engine books as ``input_wait`` — the
    goodput ledger's input-bound badput rules fire on exactly this.
    State-dict passthrough keeps the wrapped loader resumable (the PR-7
    rewind machinery sees the underlying iterator's position).
    """

    def __init__(self, base, delay_s=0.05, seed=0, p=1.0, budget=1,
                 start_after=0):
        self._base = base
        self.delay_s = float(delay_s)
        self.schedule = FaultSchedule(seed=seed, p=p, budget=budget,
                                      start_after=start_after)

    def __iter__(self):
        return self

    def __next__(self):
        if self.schedule.should_fire():
            time.sleep(self.delay_s)
        return next(self._base)

    def state_dict(self):
        fn = getattr(self._base, "state_dict", None)
        return fn() if fn is not None else None

    def load_state_dict(self, sd):
        fn = getattr(self._base, "load_state_dict", None)
        if fn is not None:
            fn(sd)


class SigkillChaos:
    """SIGKILL the current process at a chosen step.

    Only meaningful inside a sacrificial subprocess: the parent test
    launches a run that calls ``maybe_kill(step)`` each step, observes
    the kill, then asserts the NEXT run resumes from the last intact tag
    (the crash-consistency contract checkpoint_io already pins). Not an
    :class:`Injector` — there is nothing to restore after a SIGKILL.
    """

    def __init__(self, at_step):
        self.at_step = int(at_step)

    def maybe_kill(self, step):
        if int(step) == self.at_step:
            logger.warning(f"chaos: SIGKILL at step {step}")
            _chronicle_chaos("sigkill", step=int(step),
                             detail="SIGKILL injected (no teardown)")
            # SIGKILL means no atexit: push the event to disk first so
            # the post-mortem stream ends with its own cause of death
            _chronicle.get_chronicle().drain(timeout=2.0)
            os.kill(os.getpid(), signal.SIGKILL)


class PoolStarvationChaos(Injector):
    """Seize KV-cache blocks from a serving allocator so admission
    starves.

    Holding ``hold_blocks`` (or ``hold_frac`` of the usable pool) makes
    waiting requests inadmissible: the queue grows, TTFT breaches — the
    overload signature the guardian's admission-pause policy keys on.
    ``uninstall`` returns every held block (the allocator's double-free
    guard makes a leak loud, so the teardown assertion is structural).
    """

    def __init__(self, allocator, hold_blocks=None, hold_frac=0.9):
        super().__init__()
        self.allocator = allocator
        if hold_blocks is None:
            hold_blocks = int(allocator.num_usable * float(hold_frac))
        self.hold_blocks = int(hold_blocks)
        self.held = None

    def _install(self):
        n = min(self.hold_blocks, self.allocator.num_free)
        self.held = self.allocator.allocate(n)
        if self.held is None:      # all-or-nothing pool: hold what exists
            self.held = []
        logger.warning(
            f"chaos: holding {len(self.held)} of "
            f"{self.allocator.num_usable} KV blocks")

    def _uninstall(self):
        if self.held:
            self.allocator.free(self.held)
        self.held = None
