"""Module injection — swap model layers for TPU-optimised equivalents.

Rebuild of deepspeed/module_inject/replace_module.py
(``replace_transformer_layer`` :123, generic walker ``replace_module``
:651, ``ReplaceWithTensorSlicing`` :41) and replace_policy.py. The
reference mutates an eager torch module tree, swapping HF layer instances
for fused-CUDA modules or tensor-sliced linears. Flax modules are
immutable dataclasses, so injection is a CONFIG transform: policies map a
module class to (replacement class, kwargs transform), and
``replace_module`` rebuilds the module tree with replacements applied.
Tensor slicing is not a module swap at all on TPU — it is the
ModelParallelRules PartitionSpec table (zero/partition.py), which the
policies provide via ``tp_rules()``.
"""

import dataclasses
from typing import Callable, Dict, Optional, Type

import flax.linen as nn

from deepspeed_tpu.utils.logging import logger


class ReplacePolicy:
    """Base policy (reference replace_policy.py DSPolicy)."""
    source_class: Optional[Type] = None

    def match(self, module) -> bool:
        return self.source_class is not None and \
            isinstance(module, self.source_class)

    def replacement(self, module):
        raise NotImplementedError

    def tp_rules(self):
        """PartitionSpec rules implementing the reference's tensor-slicing
        injection (ReplaceWithTensorSlicing / LinearAllreduce)."""
        return []


class GPT2BlockPolicy(ReplacePolicy):
    """Policy for this package's GPT-2 blocks: already Pallas-backed, so
    replacement is identity; provides the megatron TP rules
    (reference HFGPT2LayerPolicy)."""

    def __init__(self):
        from deepspeed_tpu.models import gpt2
        self.source_class = gpt2.Block

    def replacement(self, module):
        return module

    def tp_rules(self):
        from deepspeed_tpu.models.gpt2 import gpt2_tp_rules
        return gpt2_tp_rules()


class BertLayerPolicy(ReplacePolicy):
    """Reference HFBertLayerPolicy: swap an encoder layer for the fused
    DeepSpeedTransformerLayer (ops/transformer/transformer.py)."""

    def __init__(self):
        try:
            from deepspeed_tpu.models import bert
            self.source_class = bert.BertLayer
        except Exception:  # model family not present
            self.source_class = None

    def replacement(self, module):
        from deepspeed_tpu.ops.transformer.transformer import (
            DeepSpeedTransformerConfig, DeepSpeedTransformerLayer)
        cfg = DeepSpeedTransformerConfig(
            hidden_size=module.hidden_size,
            heads=module.num_heads,
            intermediate_size=getattr(module, "intermediate_size",
                                      4 * module.hidden_size),
            pre_layer_norm=getattr(module, "pre_layer_norm", False))
        return DeepSpeedTransformerLayer(cfg)

    def tp_rules(self):
        from deepspeed_tpu.models.bert import bert_tp_rules
        return bert_tp_rules()


GENERIC_POLICIES = [GPT2BlockPolicy, BertLayerPolicy]


def replace_module(model: nn.Module, policies=None) -> nn.Module:
    """Rebuild *model* with policy replacements applied (reference :651).

    Flax modules are frozen dataclasses; submodules declared as fields are
    replaced via dataclasses.replace. Compact-style models (submodules
    created inside __call__) can't be walked — they're already built on
    this package's ops, which is what injection would install anyway."""
    policies = [p() if isinstance(p, type) else p
                for p in (policies or GENERIC_POLICIES)]

    def transform(mod):
        for pol in policies:
            if pol.match(mod):
                return pol.replacement(mod)
        if dataclasses.is_dataclass(mod):
            updates = {}
            for f in dataclasses.fields(mod):
                try:
                    v = getattr(mod, f.name)
                except AttributeError:
                    continue
                if isinstance(v, nn.Module):
                    new_v = transform(v)
                    if new_v is not v:
                        updates[f.name] = new_v
            if updates:
                return dataclasses.replace(mod, **updates)
        return mod

    return transform(model)


def replace_transformer_layer(orig_layer_impl, model, policy=None,
                              micro_batch_size=-1, config=None, seed=-1,
                              max_seq_length=512, **kwargs):
    """API-parity wrapper (reference :123)."""
    return replace_module(model, policies=[policy] if policy else None)


class _RevertPolicy(ReplacePolicy):
    """Inverse of BertLayerPolicy: fused layer -> original layer class."""

    def __init__(self, orig_layer_impl, preln=False, config=None):
        from deepspeed_tpu.ops.transformer.transformer import \
            DeepSpeedTransformerLayer
        self.source_class = DeepSpeedTransformerLayer
        self.orig_layer_impl = orig_layer_impl
        self.preln = preln
        self.config = config

    def replacement(self, module):
        if self.config is not None:
            # reference pattern: the original layer takes one config
            # object (replace_module.py:595 orig_layer_impl(config))
            return self.orig_layer_impl(self.config)
        c = module.config
        return self.orig_layer_impl(
            hidden_size=c.hidden_size,
            num_heads=c.heads,
            intermediate_size=c.intermediate,   # resolved (-1 -> 4*hidden)
            pre_layer_norm=self.preln or c.pre_layer_norm)


def revert_transformer_layer(orig_layer_impl, model, config=None,
                             preln=False):
    """Swap fused ``DeepSpeedTransformerLayer`` modules back to the
    original layer class (reference replace_module.py:583), reusing the
    replace_module tree walker. The fused layer's params live under the
    same structure the wrapped original used, so re-initialised trees
    remain checkpoint-compatible."""
    return replace_module(
        model, policies=[_RevertPolicy(orig_layer_impl, preln, config)])


def tensor_slicing_rules(policies=None):
    """Collect the TP PartitionSpec rules from all policies — the
    declarative form of ReplaceWithTensorSlicing (reference :41)."""
    rules = []
    for p in (policies or GENERIC_POLICIES):
        pol = p() if isinstance(p, type) else p
        try:
            rules.extend(pol.tp_rules())
        except Exception as e:
            logger.warning(f"policy {p}: tp_rules unavailable ({e})")
    return rules


# ---------------------------------------------------------------------------
# Checkpoint-level policies for HF / Megatron architectures (reference
# replace_policy.py:44 HFBertLayerPolicy, :103 GPTNEOLayerPolicy,
# :147 GPTJLayerPolicy, MegatronLayerPolicy, HFGPT2LayerPolicy).
#
# The reference policies read attention/mlp/layernorm weights out of an
# eager HF module and hand them to the fused CUDA inference layer. The
# flax analogue is a STATE-DICT transform: each policy detects its
# architecture's checkpoint naming, converts the weights into this
# package's TPU layer params, and supplies the TP PartitionSpec rules.
# ---------------------------------------------------------------------------


class CheckpointPolicy:
    """Detect + convert one architecture's checkpoint into TPU params."""

    name: str = "base"

    @staticmethod
    def matches(sd) -> bool:
        raise NotImplementedError

    @staticmethod
    def convert(sd, config, **ctx):
        """``ctx`` carries conversion context (e.g. checkpoint_version for
        Megatron layouts); policies ignore keys they don't use."""
        raise NotImplementedError

    @staticmethod
    def target_model(config):
        raise NotImplementedError

    @staticmethod
    def tp_rules():
        return []


class HFGPT2LayerPolicy(CheckpointPolicy):
    """reference replace_policy.py HFGPT2LayerPolicy."""
    name = "hf-gpt2"

    @staticmethod
    def matches(sd):
        from deepspeed_tpu.runtime.state_dict_factory import \
            is_hf_gpt2_state_dict
        return is_hf_gpt2_state_dict(sd)

    @staticmethod
    def convert(sd, config, **ctx):
        from deepspeed_tpu.runtime.state_dict_factory import hf_gpt2_to_params
        return hf_gpt2_to_params(sd, config)

    @staticmethod
    def target_model(config):
        from deepspeed_tpu.models.gpt2 import GPT2LMHeadModel
        return GPT2LMHeadModel(config)

    @staticmethod
    def tp_rules():
        from deepspeed_tpu.models.gpt2 import gpt2_tp_rules
        return gpt2_tp_rules()


class GPTNEOLayerPolicy(CheckpointPolicy):
    """reference replace_policy.py:103 — separate un-biased q/k/v,
    UNSCALED attention (folded into the q kernel by the converter)."""
    name = "hf-gptneo"

    @staticmethod
    def matches(sd):
        from deepspeed_tpu.runtime.state_dict_factory import \
            is_hf_gptneo_state_dict
        return is_hf_gptneo_state_dict(sd)

    @staticmethod
    def convert(sd, config, **ctx):
        from deepspeed_tpu.runtime.state_dict_factory import \
            hf_gptneo_to_params
        return hf_gptneo_to_params(sd, config)

    @staticmethod
    def target_model(config):
        from deepspeed_tpu.models.gpt2 import GPT2LMHeadModel
        return GPT2LMHeadModel(config)

    @staticmethod
    def tp_rules():
        from deepspeed_tpu.models.gpt2 import gpt2_tp_rules
        return gpt2_tp_rules()


class GPTJLayerPolicy(CheckpointPolicy):
    """reference replace_policy.py:147 — rotary_dim, parallel residual
    (mlp_after_attn=False), un-biased projections, biased untied head."""
    name = "hf-gptj"

    @staticmethod
    def matches(sd):
        from deepspeed_tpu.models.gptj import is_hf_gptj_state_dict
        return is_hf_gptj_state_dict(sd)

    @staticmethod
    def convert(sd, config, **ctx):
        from deepspeed_tpu.models.gptj import hf_gptj_to_params
        return hf_gptj_to_params(sd, config)

    @staticmethod
    def target_model(config):
        from deepspeed_tpu.models.gptj import GPTJForCausalLM
        return GPTJForCausalLM(config)

    @staticmethod
    def tp_rules():
        from deepspeed_tpu.models.gptj import gptj_tp_rules
        return gptj_tp_rules()


class MegatronLayerPolicy(CheckpointPolicy):
    """reference replace_policy.py MegatronLayerPolicy: fused QKV with
    version-dependent head layouts (handled by megatron_to_gpt2_params'
    checkpoint_version logic)."""
    name = "megatron"

    @staticmethod
    def matches(sd):
        return any("attention.query_key_value.weight" in k for k in sd)

    @staticmethod
    def convert(sd, config, checkpoint_version=0, **ctx):
        from deepspeed_tpu.runtime.state_dict_factory import \
            megatron_to_gpt2_params
        return megatron_to_gpt2_params(
            sd, config, checkpoint_version=checkpoint_version)

    @staticmethod
    def target_model(config):
        from deepspeed_tpu.models.gpt2 import GPT2LMHeadModel
        return GPT2LMHeadModel(config)

    @staticmethod
    def tp_rules():
        from deepspeed_tpu.models.gpt2 import gpt2_tp_rules
        return gpt2_tp_rules()


CHECKPOINT_POLICIES = [HFGPT2LayerPolicy, GPTNEOLayerPolicy,
                       GPTJLayerPolicy, MegatronLayerPolicy]


def detect_checkpoint_policy(sd):
    """Auto-detect which architecture a state dict belongs to (the
    replace_method='auto' analogue, reference replace_module.py)."""
    for pol in CHECKPOINT_POLICIES:
        try:
            if pol.matches(sd):
                return pol
        except Exception:
            continue
    return None


def convert_hf_checkpoint(sd, config, **ctx):
    """Detect + convert in one call; returns (params, policy) or raises.
    ``ctx`` (e.g. checkpoint_version=...) is forwarded to the policy."""
    pol = detect_checkpoint_policy(sd)
    if pol is None:
        raise ValueError(
            "unrecognised checkpoint format: no injection policy matched "
            f"(known: {[p.name for p in CHECKPOINT_POLICIES]})")
    return pol.convert(sd, config, **ctx), pol
