"""Module injection — swap model layers for TPU-optimised equivalents.

Rebuild of deepspeed/module_inject/replace_module.py
(``replace_transformer_layer`` :123, generic walker ``replace_module``
:651, ``ReplaceWithTensorSlicing`` :41) and replace_policy.py. The
reference mutates an eager torch module tree, swapping HF layer instances
for fused-CUDA modules or tensor-sliced linears. Flax modules are
immutable dataclasses, so injection is a CONFIG transform: policies map a
module class to (replacement class, kwargs transform), and
``replace_module`` rebuilds the module tree with replacements applied.
Tensor slicing is not a module swap at all on TPU — it is the
ModelParallelRules PartitionSpec table (zero/partition.py), which the
policies provide via ``tp_rules()``.
"""

import dataclasses
from typing import Callable, Dict, Optional, Type

import flax.linen as nn

from deepspeed_tpu.utils.logging import logger


class ReplacePolicy:
    """Base policy (reference replace_policy.py DSPolicy)."""
    source_class: Optional[Type] = None

    def match(self, module) -> bool:
        return self.source_class is not None and \
            isinstance(module, self.source_class)

    def replacement(self, module):
        raise NotImplementedError

    def tp_rules(self):
        """PartitionSpec rules implementing the reference's tensor-slicing
        injection (ReplaceWithTensorSlicing / LinearAllreduce)."""
        return []


class GPT2BlockPolicy(ReplacePolicy):
    """Policy for this package's GPT-2 blocks: already Pallas-backed, so
    replacement is identity; provides the megatron TP rules
    (reference HFGPT2LayerPolicy)."""

    def __init__(self):
        from deepspeed_tpu.models import gpt2
        self.source_class = gpt2.Block

    def replacement(self, module):
        return module

    def tp_rules(self):
        from deepspeed_tpu.models.gpt2 import gpt2_tp_rules
        return gpt2_tp_rules()


class BertLayerPolicy(ReplacePolicy):
    """Reference HFBertLayerPolicy: swap an encoder layer for the fused
    DeepSpeedTransformerLayer (ops/transformer/transformer.py)."""

    def __init__(self):
        try:
            from deepspeed_tpu.models import bert
            self.source_class = bert.BertLayer
        except Exception:  # model family not present
            self.source_class = None

    def replacement(self, module):
        from deepspeed_tpu.ops.transformer.transformer import (
            DeepSpeedTransformerConfig, DeepSpeedTransformerLayer)
        cfg = DeepSpeedTransformerConfig(
            hidden_size=module.hidden_size,
            heads=module.num_heads,
            intermediate_size=getattr(module, "intermediate_size",
                                      4 * module.hidden_size),
            pre_layer_norm=getattr(module, "pre_layer_norm", False))
        return DeepSpeedTransformerLayer(cfg)

    def tp_rules(self):
        from deepspeed_tpu.models.bert import bert_tp_rules
        return bert_tp_rules()


GENERIC_POLICIES = [GPT2BlockPolicy, BertLayerPolicy]


def replace_module(model: nn.Module, policies=None) -> nn.Module:
    """Rebuild *model* with policy replacements applied (reference :651).

    Flax modules are frozen dataclasses; submodules declared as fields are
    replaced via dataclasses.replace. Compact-style models (submodules
    created inside __call__) can't be walked — they're already built on
    this package's ops, which is what injection would install anyway."""
    policies = [p() if isinstance(p, type) else p
                for p in (policies or GENERIC_POLICIES)]

    def transform(mod):
        for pol in policies:
            if pol.match(mod):
                return pol.replacement(mod)
        if dataclasses.is_dataclass(mod):
            updates = {}
            for f in dataclasses.fields(mod):
                try:
                    v = getattr(mod, f.name)
                except AttributeError:
                    continue
                if isinstance(v, nn.Module):
                    new_v = transform(v)
                    if new_v is not v:
                        updates[f.name] = new_v
            if updates:
                return dataclasses.replace(mod, **updates)
        return mod

    return transform(model)


def replace_transformer_layer(orig_layer_impl, model, policy=None,
                              micro_batch_size=-1, config=None, seed=-1,
                              max_seq_length=512, **kwargs):
    """API-parity wrapper (reference :123)."""
    return replace_module(model, policies=[policy] if policy else None)


def tensor_slicing_rules(policies=None):
    """Collect the TP PartitionSpec rules from all policies — the
    declarative form of ReplaceWithTensorSlicing (reference :41)."""
    rules = []
    for p in (policies or GENERIC_POLICIES):
        pol = p() if isinstance(p, type) else p
        try:
            rules.extend(pol.tp_rules())
        except Exception as e:
            logger.warning(f"policy {p}: tp_rules unavailable ({e})")
    return rules
