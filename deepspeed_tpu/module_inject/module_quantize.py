"""Module-level weight quantization for inference (MoQ int8).

Rebuild of deepspeed/module_inject/module_quantize.py:6
(``quantize_transformer_layer``), which walks a model and casts each
transformer layer's four matmul weights (qkv, attn-out, mlp-in, mlp-out)
to int8 in place. Flax separates params from modules, so the TPU form
walks the PARAMS pytree: matched kernels are replaced by true int8
arrays (4x smaller in HBM than fp32) plus a parallel ``quant_scales``
collection holding one fp32 scale per output column. The model consumes
them through ``QuantDense`` (ops/quantizer/int8_linear.py), which folds
the dequant into the matmul — the analogue of the reference's
dequantize-inside-GEMM inference kernels
(csrc/transformer/inference/csrc/dequantize.cu).
"""

import re

import jax

from deepspeed_tpu.ops.quantizer.int8_linear import (
    dequantize_weight_int8, quantize_weight_int8)
from deepspeed_tpu.runtime.eigenvalue import path_str

# the four transformer matmuls, GPT-2 naming + DeepSpeedTransformerLayer
# naming (reference megatron_layer_quantize / bert_layer_quantize)
DEFAULT_PATTERNS = (
    r"(^|/)h_\d+/(attn/(qkv|proj)|mlp/(fc|proj))/kernel$",
    r"(^|/)(attn_qkv|attn_out)/kernel$",
    r"(^|/)(inter_w|output_w/kernel)$",
)


def _set_by_path(tree, segs, leaf):
    node = tree
    for s in segs[:-1]:
        node = node.setdefault(s, {})
    node[segs[-1]] = leaf


def quantize_transformer_layer(params, patterns=DEFAULT_PATTERNS, bits=8):
    """Quantize matched transformer weights to TRUE int8 storage.

    Returns ``(new_params, quant_scales)``: ``new_params`` is ``params``
    with matched 2D kernels replaced by int8 arrays; ``quant_scales``
    mirrors the module hierarchy with a ``kernel_scale`` leaf per
    quantized kernel — pass it as the ``quant_scales`` collection to
    ``module.apply`` (the InferenceEngine does this automatically).
    """
    if bits != 8:
        raise ValueError(
            f"module-level weight quantization stores int8 (got bits="
            f"{bits}); sub-8-bit TRAINING schedules are runtime/quantize.py")
    regexes = [re.compile(p) for p in patterns]
    scales = {}
    n = 0

    def q(path, x):
        nonlocal n
        joined = path_str(path)
        if (getattr(x, "ndim", 0) == 2
                and any(r.search(joined) for r in regexes)):
            wq, scale = quantize_weight_int8(x)
            segs = joined.split("/")
            _set_by_path(scales, segs[:-1] + ["kernel_scale"], scale)
            n += 1
            return wq
        return x

    new_params = jax.tree_util.tree_map_with_path(q, params)
    if n == 0:
        raise ValueError(
            "quantize_transformer_layer matched no kernels; pass patterns "
            "for this model's layer naming (default matches GPT-2 blocks "
            "and DeepSpeedTransformerLayer)")
    return new_params, scales


def dequantize_transformer_layer(params, quant_scales, dtype=None):
    """Revert: int8 kernels back to float using the stored scales
    (reference revert path; exact inverse of the stored representation)."""
    import jax.numpy as jnp
    dtype = dtype or jnp.float32

    def flatten(tree, prefix=()):
        out = {}
        for k, v in tree.items():
            if isinstance(v, dict):
                out.update(flatten(v, prefix + (k,)))
            else:
                out[prefix + (k,)] = v
        return out

    scale_by_dir = {segs[:-1]: s for segs, s in flatten(quant_scales).items()}

    def dq(path, x):
        if getattr(x, "dtype", None) == jnp.int8:
            segs = tuple(path_str(path).split("/"))
            scale = scale_by_dir.get(segs[:-1])
            if scale is not None:
                return dequantize_weight_int8(x, scale, dtype)
        return x

    return jax.tree_util.tree_map_with_path(dq, params)
