from deepspeed_tpu.module_inject.replace_module import (  # noqa: F401
    CHECKPOINT_POLICIES,
    CheckpointPolicy,
    GENERIC_POLICIES,
    GPTJLayerPolicy,
    GPTNEOLayerPolicy,
    HFGPT2LayerPolicy,
    MegatronLayerPolicy,
    ReplacePolicy,
    convert_hf_checkpoint,
    detect_checkpoint_policy,
    replace_module,
    replace_transformer_layer,
    revert_transformer_layer,
    tensor_slicing_rules,
)
from deepspeed_tpu.module_inject.module_quantize import (  # noqa: F401
    dequantize_transformer_layer,
    quantize_transformer_layer,
)
