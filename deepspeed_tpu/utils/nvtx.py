"""Profiler range annotation (reference deepspeed/utils/nvtx.py).

The reference wraps functions in NVTX ranges for nsys timelines; the TPU
analogue is a ``jax.profiler.TraceAnnotation`` (shows up as a named range
in the XLA/TensorBoard profiler) combined with ``jax.named_scope`` so the
annotation also lands in HLO op metadata of anything traced inside.
"""

import functools

import jax


def instrument_w_nvtx(func):
    """Decorator: record ``func``'s span in the JAX profiler timeline."""

    @functools.wraps(func)
    def wrapped(*args, **kwargs):
        with jax.profiler.TraceAnnotation(func.__qualname__), \
                jax.named_scope(func.__qualname__):
            return func(*args, **kwargs)

    return wrapped
