"""Wall-clock and throughput timers.

Parity with the reference ``deepspeed/utils/timer.py``
(``SynchronizedWallClockTimer`` timer.py:23, ``ThroughputTimer`` :122).
The CUDA synchronisation maps to a dispatch-ordered trivial program +
device_get (see ``_device_synchronize``) for the breakdown timers, and a
cheap effects barrier for the per-step throughput timer.
"""

import time

from deepspeed_tpu.utils.logging import log_dist

try:
    import psutil
    PSUTIL_AVAILABLE = True
except ImportError:
    PSUTIL_AVAILABLE = False


_SYNC_FN = None


def _device_synchronize():
    """TRUE device barrier: programs execute in dispatch order, so fetching
    the result of a freshly dispatched trivial program proves everything
    dispatched before it has finished. ``jax.effects_barrier`` /
    ``block_until_ready`` are NOT sufficient — they don't drain pure
    computations (through the remote tunnel they return immediately, and
    the round-3 wall-clock numbers measured dispatch, not device time).
    Costs one host<->device round trip — which is why only the
    wall_clock_breakdown timers use it, per phase boundary, and only when
    the flag is on (the reference's timers pay cuda.synchronize the same
    way)."""
    global _SYNC_FN
    try:
        import jax
        import jax.numpy as jnp
        if _SYNC_FN is None:
            _SYNC_FN = jax.jit(lambda: jnp.zeros(()))
        jax.device_get(_SYNC_FN())
    except Exception:
        pass


def _dispatch_barrier():
    """Cheap ordering barrier for the throughput timer: waits only for
    effectful ops. Per-step true syncs would add a tunnel round trip to
    EVERY step; across the tput timer's 50-step windows the bounded
    dispatch queue makes host-side timestamps asymptotically correct."""
    try:
        import jax
        jax.effects_barrier()
    except Exception:
        pass


class SynchronizedWallClockTimer:
    """Group of named timers, each synchronising the device on start/stop."""

    class Timer:
        def __init__(self, name):
            self.name_ = name
            self.elapsed_ = 0.0
            self.started_ = False
            self.start_time = time.time()

        def start(self):
            assert not self.started_, f"{self.name_} timer has already been started"
            _device_synchronize()
            self.start_time = time.time()
            self.started_ = True

        def stop(self, reset=False, record=False):
            """``record=True`` additionally observes this start->stop
            interval into the telemetry metrics registry (histogram
            ``timer_<name>_ms``) — the reference's dead parameter, given
            the recording semantics its name promises."""
            assert self.started_, "timer is not started"
            _device_synchronize()
            interval = time.time() - self.start_time
            if reset:
                self.elapsed_ = interval
            else:
                self.elapsed_ += interval
            self.started_ = False
            if record:
                from deepspeed_tpu.telemetry.metrics import get_registry
                get_registry().histogram(
                    f"timer_{self.name_}_ms",
                    "SynchronizedWallClockTimer recorded intervals"
                ).observe(interval * 1000.0)

        def reset(self):
            self.elapsed_ = 0.0
            self.started_ = False

        def elapsed(self, reset=True):
            started_ = self.started_
            if self.started_:
                self.stop()
            elapsed_ = self.elapsed_
            if reset:
                self.reset()
            if started_:
                self.start()
            return elapsed_

        def mean(self):
            return self.elapsed(reset=False)

    def __init__(self):
        self.timers = {}

    def __call__(self, name):
        if name not in self.timers:
            self.timers[name] = self.Timer(name)
        return self.timers[name]

    def has_timer(self, name):
        return name in self.timers

    @staticmethod
    def memory_usage():
        if not PSUTIL_AVAILABLE:
            return "mem stats unavailable"
        vm = psutil.virtual_memory()
        return f"host mem used: {vm.used / (1024**3):.2f} GB ({vm.percent}%)"

    def log(self, names, normalizer=1.0, reset=True, memory_breakdown=False, ranks=None):
        assert normalizer > 0.0
        string = "time (ms)"
        for name in names:
            if name in self.timers:
                elapsed_time = self.timers[name].elapsed(reset=reset) * 1000.0 / normalizer
                string += " | {}: {:.2f}".format(name, elapsed_time)
        if memory_breakdown:
            string += " | " + self.memory_usage()
        log_dist(string, ranks=ranks or [0])

    def get_mean(self, names, normalizer=1.0, reset=True):
        assert normalizer > 0.0
        means = {}
        for name in names:
            if name in self.timers:
                elapsed_time = self.timers[name].mean() * 1000.0 / normalizer
                means[name] = elapsed_time
        return means


class ThroughputTimer:
    """Samples/sec timer mirroring the reference's ThroughputTimer."""

    def __init__(self, batch_size, start_step=2, steps_per_output=50, monitor_memory=False, logging_fn=None):
        self.start_time = 0
        self.end_time = 0
        self.started = False
        self.batch_size = max(1, batch_size)
        self.start_step = start_step
        self.epoch_count = 0
        self.micro_step_count = 0
        self.global_step_count = 0
        self.total_elapsed_time = 0
        self.step_elapsed_time = 0
        self.steps_per_output = steps_per_output
        self.monitor_memory = monitor_memory
        self.logging = logging_fn
        if self.logging is None:
            from deepspeed_tpu.utils.logging import logger
            self.logging = logger.info
        self.initialized = False

    def update_epoch_count(self):
        self.epoch_count += 1
        self.micro_step_count = 0

    def _init_timer(self):
        self.initialized = True

    def start(self):
        self._init_timer()
        self.started = True
        if self.global_step_count >= self.start_step:
            _dispatch_barrier()
            self.start_time = time.time()

    def stop(self, global_step=False, report_speed=True):
        if not self.started:
            return
        self.started = False
        self.micro_step_count += 1
        if global_step:
            self.global_step_count += 1
        if self.start_time > 0:
            _dispatch_barrier()
            self.end_time = time.time()
            duration = self.end_time - self.start_time
            self.total_elapsed_time += duration
            self.step_elapsed_time += duration

            if global_step:
                if report_speed and self.global_step_count % self.steps_per_output == 0:
                    # clock-resolution zero (or an all-warmup window) must
                    # not crash the log line
                    curr = (self.batch_size / self.step_elapsed_time
                            if self.step_elapsed_time > 0 else 0.0)
                    self.logging(
                        "epoch={}/micro_step={}/global_step={}, RunningAvgSamplesPerSec={}, "
                        "CurrSamplesPerSec={}".format(
                            self.epoch_count, self.micro_step_count, self.global_step_count,
                            self.avg_samples_per_sec(), curr))
                self.step_elapsed_time = 0

    def avg_samples_per_sec(self):
        """0.0 before any timed step (warmup: the first ``start_step``
        steps are untimed) — not the reference's ``-inf``, which poisoned
        every consumer that averaged or formatted it."""
        if self.total_elapsed_time > 0:
            total_step_offset = self.global_step_count - self.start_step
            avg_time_per_step = self.total_elapsed_time / max(1, total_step_offset)
            return self.batch_size / avg_time_per_step
        return 0.0
