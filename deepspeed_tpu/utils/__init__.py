"""Public ``deepspeed_tpu.utils`` surface (reference deepspeed/utils/
__init__.py): logging, the distributed bootstrap, group queries, the
profiler annotation decorator, and the RepeatingLoader convenience."""

from deepspeed_tpu.utils.logging import log_dist, logger  # noqa: F401
from deepspeed_tpu.utils.nvtx import instrument_w_nvtx  # noqa: F401


def init_distributed(*args, **kwargs):
    """Reference utils/__init__.py re-export of the comm bootstrap."""
    from deepspeed_tpu import comm
    return comm.init_distributed(*args, **kwargs)


def __getattr__(name):
    # lazy: RepeatingLoader pulls in the runtime package, and groups is
    # itself a submodule callers import as `from ...utils import groups`
    if name == "RepeatingLoader":
        from deepspeed_tpu.runtime.dataloader import RepeatingLoader
        return RepeatingLoader
    raise AttributeError(name)
