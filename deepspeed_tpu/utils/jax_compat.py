"""Version-compat shims for jax APIs that moved/renamed across releases.

One home for the dance (previously copy-pasted at every call site), so
the next jax rename is fixed once."""


def get_shard_map():
    """(shard_map, kwargs): the callable plus the replication-check-off
    keyword spelled the way THIS jax spells it (``check_vma=False`` on
    jax >= 0.8's ``jax.shard_map``, ``check_rep=False`` on the older
    ``jax.experimental.shard_map``)."""
    try:
        from jax import shard_map
        return shard_map, {"check_vma": False}
    except ImportError:  # pragma: no cover — pre-0.8 jax
        from jax.experimental.shard_map import shard_map
        return shard_map, {"check_rep": False}


def under_manual_sharding():
    """True when tracing INSIDE a shard_map body (the abstract mesh has
    Manual axes) — a nested shard_map over the same axes would crash at
    trace time, so mesh-aware wrappers must no-op there."""
    try:
        from jax.sharding import AxisType, get_abstract_mesh
        return AxisType.Manual in tuple(
            getattr(get_abstract_mesh(), "axis_types", ()) or ())
    except Exception:  # pragma: no cover — very old jax
        return False
