"""Offline ZeRO-checkpoint → consolidated fp32 state-dict converter.

Rebuild of deepspeed/utils/zero_to_fp32.py (entry points :126/:156/:258/
:331/:380/:396): reconstruct a full fp32 param dict from the per-process
``zero_pp_rank_*_optim_states.pt`` shard files, without an engine or
devices. Usable as a library or CLI:

    python -m deepspeed_tpu.utils.zero_to_fp32 <checkpoint_dir> <output>

The shard files carry (index → ndarray) fragments of the fp32 master
params (runtime/checkpoint_io.py), so reconstruction is index-based and
dp-world-agnostic — the elastic-resume property of the reference's
_restore_from_elastic_fp32_weights (stage_1_and_2.py:2023).
"""

import argparse
import glob
import os
import pickle

from deepspeed_tpu.runtime.checkpoint_io import assemble


def get_latest_tag(checkpoint_dir):
    latest = os.path.join(checkpoint_dir, "latest")
    if os.path.isfile(latest):
        with open(latest) as f:
            return f.read().strip()
    import re as _re

    def natural(t):  # global_step10 > global_step9
        return [int(x) if x.isdigit() else x
                for x in _re.split(r"(\d+)", t)]

    tags = sorted((d for d in os.listdir(checkpoint_dir)
                   if os.path.isdir(os.path.join(checkpoint_dir, d))),
                  key=natural)
    assert tags, f"no checkpoint tags under {checkpoint_dir}"
    return tags[-1]


def get_fp32_state_dict_from_zero_checkpoint(checkpoint_dir, tag=None):
    """Reference :396 — returns {param_path: np.ndarray fp32}."""
    if tag is None:
        tag = get_latest_tag(checkpoint_dir)
    ckpt_dir = os.path.join(checkpoint_dir, str(tag))
    zero_files = sorted(glob.glob(
        os.path.join(ckpt_dir, "zero_pp_rank_*_optim_states.pt")))
    assert zero_files, f"no zero_pp_rank files in {ckpt_dir}"
    payloads = []
    for path in zero_files:
        with open(path, "rb") as f:
            payloads.append(pickle.load(f)["param_shards"])
    return assemble(payloads)


def convert_zero_checkpoint_to_fp32_state_dict(checkpoint_dir, output_file,
                                               tag=None):
    """Reference :380 — write the consolidated dict to *output_file*."""
    state_dict = get_fp32_state_dict_from_zero_checkpoint(checkpoint_dir, tag)
    with open(output_file, "wb") as f:
        pickle.dump(state_dict, f)
    print(f"saved {len(state_dict)} tensors to {output_file}")
    return state_dict


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("checkpoint_dir")
    parser.add_argument("output_file")
    parser.add_argument("-t", "--tag", default=None)
    args = parser.parse_args()
    convert_zero_checkpoint_to_fp32_state_dict(
        args.checkpoint_dir, args.output_file, tag=args.tag)


if __name__ == "__main__":
    main()
